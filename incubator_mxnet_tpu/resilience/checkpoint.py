"""Crash-consistent checkpoint IO: tmp → fsync → atomic rename + manifest.

The write protocol (cf. CheckFreq, Mohan et al., FAST'21):

1. the writer produces the payload in `path + ".tmp.<pid>"` (same
   directory, so the rename is atomic on POSIX);
2. the tmp file is fsync'd, then `os.replace`'d over the canonical path;
3. the containing directory is fsync'd so the rename itself survives a
   power cut;
4. a sidecar manifest (`path + ".sha256"`) with the payload's sha256 and
   size is written through the same tmp/fsync/rename dance.

A crash at any step leaves either the previous canonical file (+ its
manifest) or the complete new pair — never a torn canonical file that
loads garbage. `verify` checks a file against its manifest at load; a
file without a manifest (pre-resilience checkpoints) verifies as legacy.

This module hosts the `ckpt.write` fault-injection site: mode `fail`
raises mid-write (tmp file only — canonical untouched), mode `torn`
deliberately bypasses the protocol and leaves a truncated canonical file
with a full-payload manifest, which is exactly the corruption `verify`
must catch.
"""
from __future__ import annotations

import hashlib
import json
import logging
import os

from . import fault as _fault

logger = logging.getLogger(__name__)

__all__ = ["atomic_save", "atomic_write_bytes", "manifest_path",
           "read_manifest", "verify"]

MANIFEST_SUFFIX = ".sha256"

_WRITE_METRIC = "mxtpu_ckpt_writes_total"
_WRITE_HELP = ("Checkpoint file writes through resilience.checkpoint, by "
               "outcome (ok, injected-fail, injected-torn).")
_VERIFY_METRIC = "mxtpu_ckpt_verify_failures_total"
_VERIFY_HELP = ("Checkpoint files that failed manifest verification at "
                "load, by reason (missing-file, size, checksum, "
                "bad-manifest).")

_CHUNK = 1 << 20


def manifest_path(path):
    """Sidecar manifest path for a checkpoint file."""
    return str(path) + MANIFEST_SUFFIX


def _sha256_file(path):
    h = hashlib.sha256()
    with open(path, "rb") as f:
        while True:
            chunk = f.read(_CHUNK)
            if not chunk:
                break
            h.update(chunk)
    return h.hexdigest()


def _fsync_dir(path):
    d = os.path.dirname(os.path.abspath(path)) or "."
    try:
        fd = os.open(d, os.O_RDONLY)
    except OSError:  # platforms/filesystems that can't open a directory
        return
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _replace_atomic(tmp, path):
    with open(tmp, "rb") as f:
        os.fsync(f.fileno())
    os.replace(tmp, path)
    _fsync_dir(path)


def _write_manifest(path, digest, size):
    m = manifest_path(path)
    tmp = m + f".tmp.{os.getpid()}"
    payload = json.dumps(
        {"file": os.path.basename(str(path)), "sha256": digest,
         "size": size, "version": 1},
        sort_keys=True).encode("utf-8")
    with open(tmp, "wb") as f:
        f.write(payload)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, m)
    _fsync_dir(m)


def read_manifest(path):
    """The parsed sidecar manifest for `path`, or None if absent or
    unparseable."""
    try:
        with open(manifest_path(path), "rb") as f:
            m = json.loads(f.read().decode("utf-8"))
    except (OSError, ValueError):
        return None
    return m if isinstance(m, dict) and "sha256" in m else None


def _inc(name, help_, path=None, **labels):
    from .. import telemetry as _telemetry

    _telemetry.inc(name, 1, help=help_, **labels)
    # every write outcome / verify failure also lands in the flight
    # recorder, so a post-mortem dump shows the checkpoint history
    _telemetry.log_event(
        "ckpt_write" if name == _WRITE_METRIC else "ckpt_verify_failure",
        **(dict(labels, path=path) if path else labels))


def atomic_save(path, writer, site="ckpt.write", instance=""):
    """Crash-consistently materialize `path` via `writer(tmp_path)`.

    `writer` produces the full payload at the tmp path it is given (e.g.
    `lambda p: nd.save(p, save_dict)`); this function then runs the
    fsync/rename/manifest protocol. Returns the payload's sha256 hex.
    """
    path = str(path)
    act = _fault.injector().action(site, instance)
    tmp = path + f".tmp.{os.getpid()}"
    if act == "fail":
        # mid-write crash: partial tmp file, canonical + manifest untouched
        with open(tmp, "wb") as f:
            f.write(b"\0" * 64)
        _inc(_WRITE_METRIC, _WRITE_HELP, path=path,
             outcome="injected-fail")
        raise _fault.InjectedIOError(
            f"fault injection: checkpoint write failed at {site!r} "
            f"({path})")
    try:
        writer(tmp)
        digest = _sha256_file(tmp)
        size = os.path.getsize(tmp)
        if act == "torn":
            # deliberately corrupt: truncated canonical + full-size
            # manifest — the torn state verify() exists to catch
            with open(tmp, "rb") as f:
                data = f.read(max(1, size // 2))
            with open(path, "wb") as f:
                f.write(data)
            os.remove(tmp)
            _write_manifest(path, digest, size)
            _inc(_WRITE_METRIC, _WRITE_HELP, path=path,
                 outcome="injected-torn")
            logger.warning("fault injection: torn checkpoint left at %s",
                           path)
            return digest
        _replace_atomic(tmp, path)
    except BaseException:
        try:
            if os.path.exists(tmp):
                os.remove(tmp)
        except OSError:
            pass
        raise
    _write_manifest(path, digest, size)
    _inc(_WRITE_METRIC, _WRITE_HELP, path=path, outcome="ok")
    return digest


def atomic_write_bytes(path, data, site="ckpt.write", instance=""):
    """`atomic_save` for an in-memory payload."""
    def _writer(tmp):
        with open(tmp, "wb") as f:
            f.write(data)

    return atomic_save(path, _writer, site=site, instance=instance)


def verify(path):
    """True iff `path` exists and matches its sidecar manifest.

    A file with no (or unparseable) manifest verifies as legacy-valid —
    pre-resilience checkpoints stay loadable. Missing file, size
    mismatch, or checksum mismatch is a verification failure (counted).
    """
    path = str(path)
    if not os.path.isfile(path):
        _inc(_VERIFY_METRIC, _VERIFY_HELP, path=path, reason="missing-file")
        return False
    m = read_manifest(path)
    if m is None:
        if os.path.exists(manifest_path(path)):
            _inc(_VERIFY_METRIC, _VERIFY_HELP, path=path, reason="bad-manifest")
            return False
        return True  # legacy checkpoint: no manifest was ever written
    size = m.get("size")
    if size is not None and os.path.getsize(path) != size:
        _inc(_VERIFY_METRIC, _VERIFY_HELP, path=path, reason="size")
        logger.warning("checkpoint %s failed verification: size %d != "
                       "manifest %d", path, os.path.getsize(path), size)
        return False
    if _sha256_file(path) != m["sha256"]:
        _inc(_VERIFY_METRIC, _VERIFY_HELP, path=path, reason="checksum")
        logger.warning("checkpoint %s failed verification: checksum "
                       "mismatch", path)
        return False
    return True
