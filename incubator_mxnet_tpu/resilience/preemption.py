"""Preemption-safe graceful shutdown: drain the step, bundle the state,
leave the quorum, exit with a distinct code.

Cloud TPU/GPU capacity is routinely reclaimed with a short notice window:
the kernel delivers SIGTERM and the job has seconds to get its state to
durable storage. The naive reaction — die mid-step — costs the epoch
(checkpoints are per-epoch) and, in a PS job, stalls every survivor until
the heartbeat timeout evicts the corpse. This module implements the
drain protocol instead (cf. Varuna, Athlur et al., EuroSys'22 on
low-priority/spot training):

1. `install()` chains SIGTERM/SIGINT handlers (same discipline as the
   flight recorder's excepthooks: previous handlers still run). The
   handler ONLY sets a flag — no IO, no locks; a Python signal handler
   interrupts the main thread between bytecodes, so touching the
   telemetry ring or the checkpoint path from it could deadlock against
   the very code it interrupted. A SECOND signal escalates: the handler
   raises `Preempted` immediately for jobs stuck in a long step.
2. The training loop polls `requested()` (or calls
   `maybe_checkpoint_and_exit`) at step/epoch boundaries — the in-flight
   step always completes, so the bundle is taken at a consistent point.
3. `write_bundle()` captures the FULL resume state crash-consistently:
   parameters, optimizer states, the data pipeline's mid-epoch cursor
   (`DataLoader.state_dict()`), and the global PRNG position
   (`random.get_state()`), each through the tmp/fsync/rename + manifest
   protocol.
4. `checkpoint_and_exit()` additionally retires this rank from the PS
   sync group via the graceful-leave RPC (survivors' quorum shrinks NOW,
   no heartbeat-timeout stall), dumps the flight recorder, and exits
   with `PREEMPTED_EXIT_CODE` (83) so supervisors can distinguish "was
   preempted, resume me" from a crash.

`Trainer.auto_resume` consumes the bundle: a resumed job continues from
the exact batch after the drain point with a bit-identical data order
and RNG stream (docs/FAULT_TOLERANCE.md — Preemption and exact resume).
"""
from __future__ import annotations

import logging
import os
import pickle
import signal
import threading

from . import checkpoint as _checkpoint

logger = logging.getLogger(__name__)

__all__ = ["PREEMPTED_EXIT_CODE", "Preempted", "install", "uninstall",
           "requested", "reset", "bundle_paths", "write_bundle",
           "read_bundle", "clear_bundle", "checkpoint_and_exit",
           "maybe_checkpoint_and_exit"]

# distinct from any Python default so a supervisor can branch on it:
# "exit 83 == drained cleanly, resubmit with auto_resume"
PREEMPTED_EXIT_CODE = 83

_PREEMPT_METRIC = "mxtpu_preemptions_total"
_PREEMPT_HELP = ("Preemption drains completed: a termination signal "
                 "arrived, the in-flight step finished, and a resume "
                 "bundle was written, by signal.")

BUNDLE_SUFFIX = "-preempt.bundle"
_PARAMS_SUFFIX = "-preempt.params"
_STATES_SUFFIX = "-preempt.states"


class Preempted(SystemExit):
    """Raised (or escalated to) when a preemption drain ends the process;
    carries `PREEMPTED_EXIT_CODE` so `sys.exit` semantics apply."""

    def __init__(self, signum=None):
        super().__init__(PREEMPTED_EXIT_CODE)
        self.signum = signum


# handler state: flag + signum, written ONLY from the signal handler
_lock = threading.Lock()
_requested_event = threading.Event()
_signum = None
_prev_handlers = None   # {signum: previous handler} while installed


def install(signals=(signal.SIGTERM, signal.SIGINT)):
    """Chain drain handlers onto `signals` (idempotent). The first
    delivery marks the request and lets the previous handler run; a
    second delivery of any installed signal escalates to an immediate
    `Preempted` raise (the operator pressed Ctrl-C twice, or the
    platform re-signaled a job that is stuck mid-step)."""
    global _prev_handlers
    with _lock:
        if _prev_handlers is not None:
            return
        _prev_handlers = {}
        for sig in signals:
            prev = signal.getsignal(sig)
            _prev_handlers[sig] = prev

            def _handler(signum, frame, _prev=prev):
                global _signum
                if _requested_event.is_set():
                    raise Preempted(signum)
                _signum = signum
                _requested_event.set()
                if callable(_prev):
                    _prev(signum, frame)

            signal.signal(sig, _handler)
    logger.info("preemption: drain handlers installed for %s",
                [signal.Signals(s).name for s in signals])


def uninstall():
    """Restore the pre-install handlers (tests; idempotent)."""
    global _prev_handlers
    with _lock:
        if _prev_handlers is None:
            return
        for sig, prev in _prev_handlers.items():
            signal.signal(sig, prev)
        _prev_handlers = None


def requested():
    """True once a termination signal arrived; poll this at step/epoch
    boundaries to drain instead of dying mid-step."""
    return _requested_event.is_set()


def reset():
    """Clear the request flag (tests / a job that decided not to die)."""
    global _signum
    _requested_event.clear()
    _signum = None


def bundle_paths(prefix):
    """(bundle, params, states) paths for `prefix` — the resume bundle's
    fixed on-disk shape."""
    prefix = str(prefix)
    return (prefix + BUNDLE_SUFFIX, prefix + _PARAMS_SUFFIX,
            prefix + _STATES_SUFFIX)


def write_bundle(prefix, trainer=None, net=None, loader=None, epoch=0):
    """Crash-consistently capture the full resume state under `prefix`.

    Writes `-preempt.params` (when `net` is given), `-preempt.states`
    (when `trainer` is given), then the `-preempt.bundle` descriptor —
    LAST, so a crash mid-bundle leaves no descriptor pointing at absent
    payloads. The descriptor records the epoch being interrupted, the
    global PRNG position, and the data pipeline's mid-epoch cursor.
    """
    from .. import random as _random

    bundle, params, states = bundle_paths(prefix)
    if net is not None:
        _checkpoint.atomic_save(params, net.save_parameters)
    if trainer is not None:
        trainer.save_states(states)
    payload = {
        "version": 1,
        "epoch": int(epoch),
        "rng": _random.get_state(),
        "loader": None if loader is None else loader.state_dict(),
        "has_params": net is not None,
        "has_states": trainer is not None,
    }
    _checkpoint.atomic_write_bytes(bundle, pickle.dumps(payload))
    logger.info("preemption: resume bundle written at %s (epoch %d, "
                "loader %s)", bundle, int(epoch),
                "mid-epoch" if loader is not None else "absent")
    return bundle


def read_bundle(prefix):
    """The verified bundle descriptor for `prefix`, or None.

    Stricter than `verify()` alone: a bundle file MUST carry a manifest
    (they are always written with one), so the legacy no-manifest
    loophole cannot admit a torn bundle whose sidecar was lost. Payload
    files the descriptor declares are verified too."""
    bundle, params, states = bundle_paths(prefix)
    if not os.path.isfile(bundle):
        return None
    if _checkpoint.read_manifest(bundle) is None \
            or not _checkpoint.verify(bundle):
        logger.warning("preemption: bundle %s failed verification; "
                       "ignoring it", bundle)
        return None
    try:
        with open(bundle, "rb") as f:
            payload = pickle.loads(f.read())
    except (OSError, ValueError, pickle.UnpicklingError, EOFError) as e:
        logger.warning("preemption: bundle %s unreadable (%s: %s); "
                       "ignoring it", bundle, type(e).__name__, e)
        return None
    if not isinstance(payload, dict) or payload.get("version") != 1:
        logger.warning("preemption: bundle %s has unknown layout; "
                       "ignoring it", bundle)
        return None
    if payload.get("has_params") and not _checkpoint.verify(params):
        logger.warning("preemption: bundle %s names a params payload "
                       "that fails verification; ignoring it", bundle)
        return None
    if payload.get("has_states") and not _checkpoint.verify(states):
        logger.warning("preemption: bundle %s names a states payload "
                       "that fails verification; ignoring it", bundle)
        return None
    return payload


def clear_bundle(prefix):
    """Remove the bundle files (+ manifests) — called once a resume has
    consumed them, so a later crash cannot resurrect a stale position."""
    for path in bundle_paths(prefix):
        for p in (path, _checkpoint.manifest_path(path)):
            try:
                os.remove(p)
            except FileNotFoundError:
                pass


def checkpoint_and_exit(prefix, trainer=None, net=None, loader=None,
                        epoch=0, kv=None):
    """The drain endgame: bundle the state, retire from the sync group,
    dump the black box, raise `Preempted` (exit code 83).

    `kv` (or `trainer`'s kvstore) is asked to `leave()` when it knows
    how — the PS quorum shrinks immediately instead of stalling
    survivors until the heartbeat timeout. Telemetry happens HERE, on
    the main thread, never in the signal handler."""
    from .. import telemetry as _telemetry
    from ..telemetry import recorder as _recorder

    signum = _signum
    signame = (signal.Signals(signum).name
               if signum is not None else "request")
    _telemetry.log_event("preemption_drain", prefix=str(prefix),
                         epoch=int(epoch), signal=signame)
    write_bundle(prefix, trainer=trainer, net=net, loader=loader,
                 epoch=epoch)
    if kv is None and trainer is not None:
        kv = getattr(trainer, "_kvstore", None)
    leave = getattr(kv, "leave", None)
    if callable(leave):
        try:
            leave()
        except Exception as e:
            # dying anyway; the bundle is safe on disk and survivors
            # will evict this rank by heartbeat instead
            logger.warning("preemption: graceful leave failed (%s: %s); "
                           "exiting regardless", type(e).__name__, e)
    _telemetry.inc(_PREEMPT_METRIC, 1, help=_PREEMPT_HELP, signal=signame)
    # preserve the timeline of the drained run before the process goes
    _recorder.dump("preemption")
    logger.info("preemption: drain complete; exiting %d",
                PREEMPTED_EXIT_CODE)
    raise Preempted(signum)


def maybe_checkpoint_and_exit(prefix, trainer=None, net=None, loader=None,
                              epoch=0, kv=None):
    """Poll-and-drain helper for training loops: no-op until a signal
    arrived, then runs the full drain. Call at step/epoch boundaries."""
    if requested():
        checkpoint_and_exit(prefix, trainer=trainer, net=net,
                            loader=loader, epoch=epoch, kv=kv)
