"""KVStore: the parameter synchronization API.

TPU-native re-design of the reference kvstore family (ref:
include/mxnet/kvstore.h; src/kvstore/ — local/device comm.h, nccl
kvstore_nccl.h:62, dist kvstore_dist.h:44). API surface (init/push/pull/
row_sparse_pull/set_updater/rank/num_workers/barrier) is kept so
Module/Trainer code ports unchanged; the transport is different by design:

- 'local'/'device'/'nccl'/'tree': single-process multi-device. There are no
  explicit reduce kernels or P2P rings — values live as (possibly sharded)
  jax.Arrays; multi-device gradient summation happens inside the XLA program
  via GSPMD-inserted ICI all-reduce, so push() just aggregates lists.
- 'dist_sync'/'dist_device_sync'/'dist_async': multi-process. ps-lite's
  server/worker protocol is replaced by DCN+ICI collectives over all hosts
  (jax.distributed), i.e. the serverless all-reduce the reference only had
  via Horovod.
"""
from __future__ import annotations

import functools
import logging
import os
import pickle
import tempfile
import threading
import time

import numpy as np
import jax
import jax.numpy as jnp

from . import telemetry as _telemetry
from .analysis.sanitizers import san_lock
from .ndarray.ndarray import NDArray
from .ndarray.sparse import RowSparseNDArray

logger = logging.getLogger(__name__)

__all__ = ["KVStore", "TwoBitCompressor", "create", "create_kvstore_for_module"]


def _to_data(v):
    return v._data if isinstance(v, NDArray) else jnp.asarray(v)


class TwoBitCompressor:
    """2-bit gradient compression with error-feedback residual
    (ref: src/kvstore/gradient_compression.h:37, gradient_compression-inl.h:68).

    encode() maps each gradient element to one of three levels
    {-threshold, 0, +threshold}, packs 4 elements per byte (a genuinely
    2-bit wire representation), and keeps the quantization error in a
    per-key residual that is added to the next step's gradient — so
    sub-threshold gradients accumulate and are eventually transmitted,
    exactly the reference's semantics.
    """

    def __init__(self, threshold=0.5):
        self.threshold = float(threshold)
        self._residual = {}

    def encode(self, key, grad):
        """grad -> (packed uint8 wire payload, element count). Updates the
        key's residual with the quantization error."""
        acc = grad + self._residual.get(key, 0.0)
        codes = jnp.where(acc >= self.threshold, 1,
                          jnp.where(acc <= -self.threshold, 2, 0)).astype(jnp.uint8)
        decoded = self._decode_codes(codes)
        self._residual[key] = acc - decoded
        flat = codes.ravel()
        pad = (-flat.size) % 4
        flat = jnp.pad(flat, (0, pad))
        quads = flat.reshape(-1, 4)
        packed = (quads[:, 0] | (quads[:, 1] << 2) | (quads[:, 2] << 4)
                  | (quads[:, 3] << 6))
        return packed, grad.size

    def decode(self, packed, shape):
        """Inverse of encode's packing: wire payload -> dense gradient."""
        n = int(np.prod(shape)) if shape else 1
        quads = jnp.stack([(packed >> s) & 0x3 for s in (0, 2, 4, 6)], axis=1)
        codes = quads.ravel()[:n].reshape(shape)
        return self._decode_codes(codes)

    def _decode_codes(self, codes):
        return jnp.where(codes == 1, self.threshold,
                         jnp.where(codes == 2, -self.threshold, 0.0))

    def roundtrip(self, key, grad):
        """Local-store path: same signal degradation + error feedback as a
        compressed push, with no wire to cross."""
        acc = grad + self._residual.get(key, 0.0)
        q = jnp.where(acc >= self.threshold, self.threshold,
                      jnp.where(acc <= -self.threshold, -self.threshold, 0.0))
        self._residual[key] = acc - q
        return q


def _make_compressor(params):
    if params is None:
        return None
    if params.get("type") == "2bit":
        return TwoBitCompressor(float(params.get("threshold", 0.5)))
    raise ValueError(f"unsupported gradient compression {params!r}")


class KVStore:
    """Single-process store (ref: kvstore_local.h / comm.h)."""

    # Trainer.allreduce_grads may flatten many dense grads into one
    # contiguous array and pushpull it as a single key (bucketed
    # allreduce, MXTPU_ALLREDUCE_BUCKET_KB). Safe wherever pushpull is a
    # stateless per-key merge-and-reset; subclasses with per-key state on
    # the push path (elastic-averaging mix counters, server-owned
    # weights) flip this off and keep one pushpull per tensor.
    supports_bucketed_allreduce = True

    def __init__(self, kv_type="local"):
        self._type = kv_type
        self._store = {}
        self._updater = None
        self._optimizer = None
        self._compression = None

    # -- identity ----------------------------------------------------------
    @property
    def type(self):
        return self._type

    @property
    def rank(self):
        return 0

    @property
    def num_workers(self):
        return 1

    def barrier(self):
        pass

    @property
    def num_dead_node(self):
        return 0

    def set_gradient_compression(self, compression_params):
        self._compression = _make_compressor(dict(compression_params))

    # -- data --------------------------------------------------------------
    def init(self, key, value):
        """(ref: KVStore::Init)"""
        if isinstance(key, (list, tuple)):
            for k, v in zip(key, value):
                self.init(k, v)
            return
        v = value[0] if isinstance(value, (list, tuple)) else value
        self._store[key] = v if isinstance(v, NDArray) else NDArray(v)
        _telemetry.ledger.track(self._store[key], "kv_buffers")

    def _reduce(self, value):
        """Sum a list of per-device values (CommCPU/CommDevice analog).
        Row-sparse values reduce sparsely (ref: comm.h row-sparse reduce
        paths at comm.h:226) and stay sparse for the updater. Returns either
        a BaseSparseNDArray or a raw jnp array — never an NDArray."""
        from .ndarray.sparse import BaseSparseNDArray, add_n

        if not isinstance(value, (list, tuple)):
            return value if isinstance(value, BaseSparseNDArray) else _to_data(value)
        if any(isinstance(v, BaseSparseNDArray) for v in value):
            r = add_n(*value)  # dense NDArray when any operand densifies
            return r if isinstance(r, BaseSparseNDArray) else r._data
        acc = _to_data(value[0])
        for v in value[1:]:
            acc = acc + _to_data(v)
        return acc

    def _apply_sparse_push(self, key, grad):
        """Shared sparse-gradient apply: updater sees the sparse grad so the
        lazy/sparse optimizer paths engage; compression doesn't apply
        (reference falls back to uncompressed for sparse too)."""
        if self._updater is not None:
            self._updater(_key_int(key), grad, self._store[key])
        else:
            dense = grad.todense()
            if key in self._store:
                self._store[key]._data = self._store[key]._data + dense._data
            else:
                self._store[key] = dense

    def push(self, key, value, priority=0):
        """(ref: KVStore::Push) — aggregate + optionally run updater."""
        from .ndarray.sparse import BaseSparseNDArray

        if isinstance(key, (list, tuple)):
            for k, v in zip(key, value):
                self.push(k, v, priority)
            return
        grad = self._reduce(value)
        if isinstance(grad, BaseSparseNDArray):
            self._apply_sparse_push(key, grad)
            return
        if self._compression is not None:
            grad = self._compression.roundtrip(key, grad)
        if self._updater is not None:
            weight = self._store[key]
            self._updater(_key_int(key), NDArray._from_data(grad), weight)
        else:
            if key in self._store:
                self._store[key]._data = self._store[key]._data + grad
            else:
                self._store[key] = NDArray._from_data(grad)

    def pull(self, key, out=None, priority=0, ignore_sparse=True):
        """(ref: KVStore::Pull) — broadcast to out array(s)."""
        if isinstance(key, (list, tuple)):
            for k, o in zip(key, out):
                self.pull(k, o, priority)
            return
        src = self._store[key]
        outs = out if isinstance(out, (list, tuple)) else [out]
        for o in outs:
            if o is not None:
                o._data = src._data
        return src

    def pushpull(self, key, value, out=None, priority=0):
        if isinstance(key, (list, tuple)):
            # recurse per element so the per-key accumulator reset below
            # always runs (a single push(list)+pull(list) would leave
            # allreduce-mode gradients in the store, corrupting step N+1)
            outs = out if isinstance(out, (list, tuple)) else [out] * len(key)
            for k, v, o in zip(key, value, outs):
                self.pushpull(k, v, o, priority)
            return
        self.push(key, value, priority)
        if out is not None:
            self.pull(key, out, priority)
            if self._updater is None:
                # pure allreduce semantics: reset the accumulator after pull
                del self._store[key]

    def row_sparse_pull(self, key, out=None, priority=0, row_ids=None):
        """(ref: KVStore::PullRowSparse) — gather only requested rows."""
        src = self._store[key]
        rid = row_ids[0] if isinstance(row_ids, (list, tuple)) else row_ids
        idx = _to_data(rid).astype(jnp.int32)
        rows = jnp.take(src._data, idx, axis=0)
        outs = out if isinstance(out, (list, tuple)) else [out]
        for o in outs:
            if isinstance(o, RowSparseNDArray):
                o.data._data = rows
                o.indices._data = idx.astype(jnp.int64)
            else:
                o._data = jnp.zeros_like(src._data).at[idx].set(rows)
        return out

    # -- updater/optimizer -------------------------------------------------
    def set_updater(self, updater):
        self._updater = updater

    def set_optimizer(self, optimizer):
        """(ref: kvstore.py set_optimizer — pickles optimizer to servers; here
        it directly becomes the local updater)"""
        from . import optimizer as opt

        self._optimizer = optimizer
        self._updater = opt.get_updater(optimizer)

    def save_optimizer_states(self, fname, dump_optimizer=False):
        assert self._updater is not None
        with open(fname, "wb") as f:
            f.write(self._updater.get_states(dump_optimizer))

    def load_optimizer_states(self, fname):
        assert self._updater is not None
        with open(fname, "rb") as f:
            self._updater.set_states(f.read())


class KVStoreDist(KVStore):
    """Multi-host store over DCN+ICI collectives (replaces ps-lite; ref:
    src/kvstore/kvstore_dist.h:44). Requires jax.distributed to be
    initialized by the launcher (tools/launch.py); degrades to local when
    single-process."""

    def __init__(self, kv_type="dist_sync"):
        super().__init__(kv_type)
        self._heartbeat = _Heartbeat.maybe_start(self.rank, self.num_workers)

    @property
    def rank(self):
        return jax.process_index()

    @property
    def num_workers(self):
        return jax.process_count()

    def barrier(self):
        if jax.process_count() > 1:
            from jax.experimental import multihost_utils

            multihost_utils.sync_global_devices("kvstore_barrier")

    @property
    def num_dead_node(self):
        """Heartbeat-based dead-peer count (ref: ps-lite Postoffice
        GetDeadNodes via kvstore_dist.h:121). Workers touch a per-rank
        heartbeat file; a rank is dead once its heartbeat goes stale."""
        if self._heartbeat is None:
            return 0
        return self._heartbeat.num_dead()

    def _allreduce_row_sparse(self, grad):
        """Cross-worker row_sparse sum: only (row_id, row) pairs cross DCN
        (ref: DataHandleRowSparse kvstore_dist_server.h:499). Ragged nnz is
        padded to the cross-worker max so the allgather has a fixed shape;
        pad rows carry index -1 and are dropped on receive."""
        import numpy as _np
        from jax.experimental import multihost_utils
        from .ndarray.sparse import RowSparseNDArray, add_n
        from .ndarray.ndarray import NDArray as _ND

        idx = _np.asarray(grad.indices.asnumpy(), _np.int64)
        dat = _np.asarray(grad.data.asnumpy())
        nnz = _np.asarray([idx.shape[0]], _np.int64)
        max_nnz = int(multihost_utils.process_allgather(nnz).max())
        width = dat.shape[1:]
        pidx = _np.full((max_nnz,), -1, _np.int64)
        pdat = _np.zeros((max_nnz,) + width, dat.dtype)
        pidx[: idx.shape[0]] = idx
        pdat[: idx.shape[0]] = dat
        all_idx = multihost_utils.process_allgather(pidx)   # (W, max_nnz)
        all_dat = multihost_utils.process_allgather(pdat)   # (W, max_nnz, ...)
        parts = []
        for w in range(all_idx.shape[0]):
            keep = _np.asarray(all_idx[w]) >= 0
            parts.append(RowSparseNDArray(
                _ND(_np.asarray(all_dat[w])[keep]),
                _ND(_np.asarray(all_idx[w])[keep]), grad.shape))
        return add_n(*parts)

    def push(self, key, value, priority=0):
        from .ndarray.sparse import BaseSparseNDArray, RowSparseNDArray

        if isinstance(key, (list, tuple)):
            for k, v in zip(key, value):
                self.push(k, v, priority)
            return
        grad = self._reduce(value)
        if isinstance(grad, BaseSparseNDArray):
            if self.num_workers > 1:
                if not isinstance(grad, RowSparseNDArray):
                    grad = grad.tostype("row_sparse")
                grad = self._allreduce_row_sparse(grad)
            self._apply_sparse_push(key, grad)
            return
        if self.num_workers > 1:
            import numpy as _np
            from jax.experimental import multihost_utils

            if self._compression is not None:
                # compress-on-the-wire (ref: DataHandleCompressed): only the
                # packed 2-bit payload crosses DCN; each worker keeps its own
                # error-feedback residual and decodes the peers' payloads.
                payload, _n = self._compression.encode(key, grad)
                gathered = multihost_utils.process_allgather(
                    _np.asarray(payload))
                grad = sum(
                    self._compression.decode(jnp.asarray(gathered[i]),
                                             grad.shape)
                    for i in range(gathered.shape[0]))
            else:
                # host-side hop: the local grad may be committed to one local
                # device; allgather wants process-replicated input
                gathered = multihost_utils.process_allgather(_np.asarray(grad))
                grad = jnp.sum(jnp.asarray(gathered), axis=0)
        elif self._compression is not None:
            grad = self._compression.roundtrip(key, grad)
        if self._updater is not None:
            self._updater(_key_int(key), NDArray._from_data(grad), self._store[key])
        else:
            if key in self._store:
                self._store[key]._data = self._store[key]._data + grad
            else:
                self._store[key] = NDArray._from_data(grad)




class KVStoreDistAsync(KVStoreDist):
    """Asynchronous distributed store (ref: `dist_async` —
    kvstore_dist_server.h:348 applies updates instantly, workers never
    barrier per step).

    TPU-native design: there is no server, so "async" = **bounded-staleness
    elastic averaging**. Every push applies the optimizer LOCALLY with zero
    cross-worker blocking; every `period`-th push of a key mixes that key's
    weights toward the cross-worker mean (collectives match by call order,
    so stragglers only rendezvous at mix points — staleness is bounded by
    `period`, the role MXNET_KVSTORE's async staleness played). Tune with
    MXTPU_ASYNC_PERIOD / MXTPU_ASYNC_ALPHA.
    """

    # push is stateful per key (mix-point counters keyed by parameter);
    # a flattened bucket key would dodge the elastic-averaging schedule
    supports_bucketed_allreduce = False

    def __init__(self, kv_type="dist_async"):
        super().__init__(kv_type)
        from . import config as _config

        self._period = max(1, _config.get("MXTPU_ASYNC_PERIOD"))
        self._alpha = _config.get("MXTPU_ASYNC_ALPHA")
        self._push_counts = {}

    def push(self, key, value, priority=0):
        from .ndarray.sparse import BaseSparseNDArray

        if isinstance(key, (list, tuple)):
            for k, v in zip(key, value):
                self.push(k, v, priority)
            return
        grad = self._reduce(value)
        if isinstance(grad, BaseSparseNDArray):
            # async sparse: apply locally; consensus happens at mix points
            self._apply_sparse_push(key, grad)
            c = self._push_counts.get(key, 0) + 1
            self._push_counts[key] = c
            if self.num_workers > 1 and c % self._period == 0:
                self._mix(key)
            return
        if self._compression is not None:
            grad = self._compression.roundtrip(key, grad)
        # local apply — no cross-worker communication on the hot path
        if self._updater is not None:
            self._updater(_key_int(key), NDArray._from_data(grad),
                          self._store[key])
        else:
            if key in self._store:
                self._store[key]._data = self._store[key]._data + grad
            else:
                self._store[key] = NDArray._from_data(grad)
        c = self._push_counts.get(key, 0) + 1
        self._push_counts[key] = c
        if self.num_workers > 1 and c % self._period == 0:
            self._mix(key)

    def _mix(self, key, alpha=None):
        """Elastic-average this key toward the cross-worker mean."""
        import numpy as _np
        from jax.experimental import multihost_utils

        alpha = self._alpha if alpha is None else alpha
        w = self._store[key]
        gathered = multihost_utils.process_allgather(_np.asarray(w._data))
        mean = jnp.mean(jnp.asarray(gathered), axis=0)
        w._data = (1.0 - alpha) * w._data + alpha * mean

    def sync_all(self, alpha=1.0):
        """Force full weight consensus (e.g. before eval/checkpoint)."""
        for key in list(self._store):
            self._mix(key, alpha=alpha)


class KVStoreDistAsyncServer(KVStoreDist):
    """`dist_async` with the reference's TRUE parameter-server semantics:
    a server (rank 0 host thread) owns the authoritative weights and applies
    each worker's update the instant its push arrives — no cross-worker
    averaging, no per-step blocking (ref: kvstore_dist_server.h:348-358).

    Select with kvstore type 'dist_async_server'. The default
    'dist_async' remains collective-based elastic averaging (see
    KVStoreDistAsync) because collectives are the TPU-native transport; this
    class exists for workloads that depend on server-applied async-SGD
    semantics (staleness realized per-push, shared optimizer state).
    """

    # the server owns per-key weights; a synthetic bucket key has no
    # server-side weight to update (and this store never takes the
    # allreduce_grads path anyway — update_on_kvstore is forced on)
    supports_bucketed_allreduce = False
    # list-key pushpull runs hierarchically instead: intra-host GSPMD
    # reduction first, then ONE push_many/pull_many RPC pair per
    # byte-capped bucket — the Trainer keys off this flag
    supports_hierarchical_pushpull = True

    def __init__(self, kv_type="dist_async_server"):
        super().__init__(kv_type)
        from . import config as _config
        from . import ps as _ps

        host, port = _ps.default_server_addr()
        self._server = None
        if self.rank == 0:
            self._server = _ps.ParameterServer(self.num_workers, host=host,
                                               port=port)
            port = self._server.port
            host = self._server.host
        self._client = _ps.PSClient(host, port)
        self._shapes = {}
        # versioned membership: every worker (re)joins its rank up front —
        # a replacement process re-admits into the quorum, learns the
        # epoch + key directory, and its sync pushes are epoch-fenced
        self._member = self._client.join(self.rank)
        self._hb_stop = threading.Event()
        self._hb_client = None
        self._left = False
        if self.num_workers > 1:
            # data-plane liveness on a DEDICATED client: the main client
            # serializes request/response under one lock, so a beat
            # riding it would stall behind a blocked sync rendezvous —
            # exactly when the server most needs to see this worker alive
            self._hb_client = _ps.PSClient(host, port,
                                           instance=f"hb{self.rank}")
            interval = _config.get("MXTPU_HEARTBEAT_INTERVAL")
            self._hb_client.heartbeat(self.rank)

            def _beat_loop():
                while not self._hb_stop.wait(interval):
                    try:
                        self._hb_client.heartbeat(self.rank)
                    except (ConnectionError, OSError, RuntimeError):
                        # the redial already ran under the client's
                        # per-instance-seeded (jittered) RetryPolicy, so
                        # a fleet-wide blip rejoins staggered; a server
                        # that stays gone surfaces via num_dead instead
                        pass

            threading.Thread(target=_beat_loop, daemon=True,
                             name=f"mxtpu-ps-beat-r{self.rank}").start()

    def barrier(self):
        # the server's counting barrier: matches PS semantics and works
        # even before jax.distributed collectives are usable
        from . import ps as _ps

        try:
            self._client.barrier()
        except _ps.StaleEpochError:
            # membership changed under us (a peer rejoined or was
            # replaced): adopt the new epoch and rendezvous again
            self.refresh_membership()
            self._client.barrier()

    def refresh_membership(self):
        """Re-read {epoch, num_workers, quorum} after a membership change
        (the recovery step a StaleEpochError asks for)."""
        info = self._client.membership()
        logger.info("dist_async_server r%d: membership epoch %s, world %s",
                    self.rank, info["epoch"], info["num_workers"])
        return info

    def init(self, key, value):
        if isinstance(key, (list, tuple)):
            for k, v in zip(key, value):
                self.init(k, v)
            return
        v = value[0] if isinstance(value, (list, tuple)) else value
        v = v if isinstance(v, NDArray) else NDArray(v)
        self._shapes[key] = v.shape
        if self.rank == 0:
            self._client.init(key, v.asnumpy())
        self._client.barrier()

    def set_optimizer(self, optimizer):
        """Ship the optimizer to the server (ref: CommandType::kController)."""
        self._optimizer = optimizer
        if self.rank == 0:
            self._client.set_optimizer(optimizer)
        self._client.barrier()

    def set_updater(self, updater):
        raise NotImplementedError(
            "dist_async_server applies updates server-side; use "
            "set_optimizer (the reference's dist kvstore has the same "
            "constraint for custom python updaters)")

    def set_gradient_compression(self, compression_params):
        """Compression crosses the REAL wire here: the worker ships the
        packed 2-bit payload and the server decodes (ref:
        gradient_compression.h:37 + DataHandleCompressed)."""
        super().set_gradient_compression(compression_params)
        if self.rank == 0:
            self._client.set_compression(dict(compression_params))
        self._client.barrier()

    def set_optimizer_attrs(self, attrs):
        """Propagate live attribute changes (lr, rescale_grad, ...) to the
        server's optimizer without rebuilding it (state survives)."""
        if self.rank == 0:
            self._client.set_optimizer_attrs(dict(attrs))
        self._client.barrier()

    def push(self, key, value, priority=0):
        from .ndarray.sparse import BaseSparseNDArray, RowSparseNDArray

        if isinstance(key, (list, tuple)):
            for k, v in zip(key, value):
                self.push(k, v, priority)
            return
        import numpy as _np

        grad = self._reduce(value)
        if isinstance(grad, BaseSparseNDArray):
            if not isinstance(grad, RowSparseNDArray):
                grad = grad.tostype("row_sparse")
            # only the occupied rows cross the wire, applied sparsely
            # server-side (ref: DataHandleRowSparse kvstore_dist_server.h:499)
            self._client.push_rows(key,
                                   _np.asarray(grad.indices.asnumpy()),
                                   _np.asarray(grad.data.asnumpy()))
            return

        if self._compression is not None:
            # worker keeps the error-feedback residual; only the packed
            # payload (4 grads/byte) crosses TCP
            payload, _n = self._compression.encode(key, grad)
            self._client.push_compressed(key, _np.asarray(payload),
                                         tuple(grad.shape))
            return
        self._client.push(key, _np.asarray(grad), sync=False)

    def pull(self, key, out=None, priority=0, ignore_sparse=True):
        if isinstance(key, (list, tuple)):
            for k, o in zip(key, out):
                self.pull(k, o, priority)
            return
        val = jnp.asarray(self._client.pull(key))
        outs = out if isinstance(out, (list, tuple)) else [out]
        for o in outs:
            if o is not None:
                o._data = val
        return NDArray._from_data(val)

    def pushpull(self, key, value, out=None, priority=0):
        if isinstance(key, (list, tuple)):
            outs = out if isinstance(out, (list, tuple)) else [out] * len(key)
            if self._hierarchical_ok(value):
                self._pushpull_hierarchical(list(key), list(value),
                                            list(outs))
                return
            for k, v, o in zip(key, value, outs):
                self.pushpull(k, v, o, priority)
            return
        self.push(key, value, priority)
        if out is not None:
            self.pull(key, out, priority)

    def _hierarchical_ok(self, values):
        """Dense, uncompressed list pushes batch hierarchically; sparse
        and 2-bit-compressed gradients keep their dedicated wire formats
        on the per-key path."""
        from . import config as _config
        from .ndarray.sparse import BaseSparseNDArray

        if self._compression is not None:
            return False
        if _config.get("MXTPU_PS_BUCKET_KB") <= 0:
            return False
        for v in values:
            vs = v if isinstance(v, (list, tuple)) else [v]
            if any(isinstance(x, BaseSparseNDArray) for x in vs):
                return False
        return True

    def _pushpull_hierarchical(self, keys, values, outs):
        """Hierarchical allreduce: stage 1 reduces each gradient
        intra-host over the GSPMD mesh (`_reduce` — per-device shards
        never cross the wire individually); stage 2 ships ONE
        push_many/pull_many RPC pair per byte-capped bucket to the
        server instead of one pair per key (~num_keys x fewer RPCs, and
        a single choke point per bucket for membership changes).
        Server-side application is per-key through the same optimizer
        path, so weights stay bit-identical to the flat path."""
        import numpy as _np

        from . import config as _config

        cap = _config.get("MXTPU_PS_BUCKET_KB") * 1024
        grads = [_np.asarray(_to_data(self._reduce(v))) for v in values]
        buckets = []
        cur, cur_bytes = [], 0
        for i, g in enumerate(grads):
            if cur and cur_bytes + g.nbytes > cap:
                buckets.append(cur)
                cur, cur_bytes = [], 0
            cur.append(i)
            cur_bytes += g.nbytes
        if cur:
            buckets.append(cur)
        for bucket in buckets:
            bkeys = [keys[i] for i in bucket]
            self._client.push_many(bkeys, [grads[i] for i in bucket],
                                   sync=False)
            vals = self._client.pull_many(bkeys)
            for i, val in zip(bucket, vals):
                o = outs[i]
                jval = jnp.asarray(val)
                for oo in (o if isinstance(o, (list, tuple)) else [o]):
                    if oo is not None:
                        oo._data = jval
        _telemetry.inc(_KV_BYTES, int(sum(g.nbytes for g in grads)),
                       help="Payload bytes through kvstore push/pull.",
                       op="pushpull_hierarchical", store=self.type)

    def row_sparse_pull(self, key, out=None, priority=0, row_ids=None):
        """Only the requested rows cross the wire — after a host-side
        dedup (repeated ids in a batch are the common case on zipfian
        data), bucket-padded to the MXTPU_SPARSE_NNZ_BUCKETING grid so
        steady-state pulls keep a stable wire shape
        (ref: DataHandleRowSparse kvstore_dist_server.h:499)."""
        import numpy as _np

        from .ndarray.sparse import pad_row_ids as _pad_row_ids

        rid = row_ids[0] if isinstance(row_ids, (list, tuple)) else row_ids
        idx = _np.asarray(_to_data(rid)).astype(_np.int64)
        uniq, inv = _np.unique(idx, return_inverse=True)
        wire, _n = _pad_row_ids(uniq)
        block = jnp.asarray(self._client.pull_rows(key, wire))
        # scatter back to the caller's per-position view via the inverse
        rows = jnp.take(block, jnp.asarray(inv), axis=0)
        outs = out if isinstance(out, (list, tuple)) else [out]
        for o in outs:
            if isinstance(o, RowSparseNDArray):
                o.data._data = rows
                o.indices._data = jnp.asarray(idx)
            else:
                full = jnp.zeros(self._shapes[key], rows.dtype)
                o._data = full.at[jnp.asarray(idx)].set(rows)
        return out

    def sync_all(self, alpha=1.0):
        """Server weights are already authoritative — nothing to reconcile."""

    def save_optimizer_states(self, fname, dump_optimizer=False):
        """Optimizer state lives ON the server — fetch it over the wire
        (ref: the reference cannot do this; server state was unrecoverable
        there)."""
        from . import resilience as _resilience

        blob = self._client.get_optimizer_states(dump_optimizer)
        _resilience.atomic_write_bytes(fname, blob, site="ckpt.states")

    def load_optimizer_states(self, fname):
        with open(fname, "rb") as f:
            blob = f.read()
        if self.rank == 0:
            self._client.set_optimizer_states(blob)
        self._client.barrier()

    def leave(self):
        """Graceful departure (resilience.preemption drain): stop the
        heartbeat thread, then tell the server this rank is leaving so
        the survivors' quorum shrinks immediately — no heartbeat-timeout
        wait, no spurious eviction alarm. After leave() this rank is out
        of the quorum, so close() skips the farewell rendezvous (a
        departed rank counting toward a barrier would over-fill it).
        Idempotent; a replacement process rejoins via the normal join
        path."""
        if self._left:
            return
        self._left = True
        self._hb_stop.set()
        try:
            self._client.leave(self.rank)
            logger.info("dist_async_server r%d: left the sync group "
                        "gracefully", self.rank)
        except (ConnectionError, OSError, RuntimeError) as e:
            # the server may already be gone mid-preemption; the quorum
            # then shrinks via the heartbeat timeout instead
            logger.warning("dist_async_server r%d: graceful leave failed "
                           "(%s: %s); survivors will evict by heartbeat",
                           self.rank, type(e).__name__, e)

    def close(self):
        self._hb_stop.set()
        if not self._left:
            try:
                # best-effort farewell rendezvous: with a peer dead the
                # quorum shrinks (or the barrier errors), and shutdown must
                # proceed either way — a dead worker cannot hold the job's
                # teardown hostage
                self.barrier()
            except (ConnectionError, OSError, RuntimeError) as e:
                logger.warning("dist_async_server close: farewell barrier "
                               "failed (%s: %s); shutting down anyway",
                               type(e).__name__, e)
        if self._server is not None:
            self._server.shutdown()
        if self._hb_client is not None:
            self._hb_client.close()
        self._client.close()
        if self._left:
            return
        # collective rendezvous AFTER the listener is closed: a successor
        # store on the same port must never find the old server accepting
        super().barrier()


def _key_int(key):
    if isinstance(key, int):
        return key
    return key


# ---------------------------------------------------------------------------
# telemetry instrumentation: bytes + latency per scalar-key push/pull.
# Wrapping happens per class __dict__ so an inherited (already-wrapped)
# method is never wrapped twice, and list-key calls pass through untimed —
# they recurse into scalar calls which ARE timed, so nothing double-counts.
# ---------------------------------------------------------------------------

_KV_SECONDS = "mxtpu_kvstore_seconds"
_KV_BYTES = "mxtpu_kvstore_bytes_total"


def _payload_nbytes(value):
    """Bytes of an NDArray / sparse NDArray / raw array payload (or a list
    of them) without materializing anything on host."""
    total = 0
    for v in value if isinstance(value, (list, tuple)) else [value]:
        if v is None:
            continue
        if hasattr(v, "data") and hasattr(v, "indices"):  # sparse: the wire
            total += _payload_nbytes([v.data, v.indices])  # payload is rows
            total += _payload_nbytes(getattr(v, "indptr", None))  # + indices
            continue
        data = getattr(v, "_data", v)
        nbytes = getattr(data, "nbytes", None)
        if nbytes is None:
            shape = getattr(data, "shape", None)
            if shape is None:
                continue
            itemsize = getattr(getattr(data, "dtype", None), "itemsize", 4)
            nbytes = int(np.prod(shape)) * itemsize if shape else itemsize
        total += int(nbytes)
    return total


def _instrument_kv(op, method):
    @functools.wraps(method)
    def wrapped(self, key, *args, **kwargs):
        if not _telemetry.enabled() or isinstance(key, (list, tuple)):
            return method(self, key, *args, **kwargs)
        t0 = time.perf_counter()
        try:
            return method(self, key, *args, **kwargs)
        finally:
            _telemetry.observe(
                _KV_SECONDS, time.perf_counter() - t0,
                help="Latency of scalar-key kvstore operations.",
                op=op, store=self.type)
            payload = kwargs.get("value" if op == "push" else "out",
                                 args[0] if args else None)
            nbytes = _payload_nbytes(payload)
            if nbytes:
                _telemetry.inc(
                    _KV_BYTES, nbytes,
                    help="Payload bytes through kvstore push/pull.",
                    op=op, store=self.type)
    return wrapped


for _cls in (KVStore, KVStoreDist, KVStoreDistAsync, KVStoreDistAsyncServer):
    for _op in ("push", "pull"):
        if _op in _cls.__dict__:
            setattr(_cls, _op, _instrument_kv(_op, _cls.__dict__[_op]))
del _cls, _op


class _TcpHeartbeat:
    """TCP worker heartbeats for dead-node detection (ref: ps-lite
    Heartbeat/GetDeadNodes over zmq), riding the PS control plane: rank 0
    hosts a heartbeat service (a ParameterServer instance on coordinator
    port + 29), every worker beats its rank over a socket from a daemon
    thread, and `num_dead` is answered server-side from beat staleness.
    Works cross-host with no shared-filesystem assumption."""

    _singleton = None
    _singleton_lock = san_lock("kvstore.hb_singleton")

    def __init__(self, rank, num_workers, host, port, interval, timeout):
        from . import ps as _ps

        self.rank = rank
        self.timeout = timeout
        self._created = time.time()
        self._server = None
        if rank == 0:
            self._server = _ps.ParameterServer(num_workers, host=host,
                                               port=port)
            port = self._server.port
            host = self._server.host
        # per-rank instance tag: the client's redial RetryPolicy seeds
        # its backoff jitter from it, so after a fleet-wide network blip
        # every rank's heartbeat sender reconnects on a DIFFERENT
        # schedule instead of thundering-herding the coordinator
        self._client = _ps.PSClient(host, port, instance=f"hb{rank}")
        self._client.heartbeat(rank)
        self._stop = threading.Event()
        self._interval = interval
        t = threading.Thread(target=self._loop, daemon=True,
                             name="mxtpu-heartbeat")
        t.start()

    @classmethod
    def get(cls, rank, num_workers, host, port, interval, timeout):
        """One heartbeat service per process, shared by every kvstore
        instance (a second bind on the port would otherwise fail)."""
        with cls._singleton_lock:
            if cls._singleton is None:
                cls._singleton = cls(rank, num_workers, host, port,
                                     interval, timeout)
            return cls._singleton

    def _loop(self):
        while not self._stop.wait(self._interval):
            try:
                self._client.heartbeat(self.rank)
            except (ConnectionError, OSError, RuntimeError):
                # the redial already ran (and backed off, jittered per
                # rank) inside the client's RetryPolicy; a server that
                # stays gone surfaces via num_dead, and a beat that does
                # land after an eviction re-admits this rank
                pass

    def num_dead(self):
        # never-seen peers count as dead only once THIS observer's own
        # startup grace has passed (parity with the file transport)
        grace = time.time() - self._created > self.timeout
        try:
            return int(self._client.num_dead(self.rank, self.timeout,
                                             grace))
        except (ConnectionError, OSError, RuntimeError):
            return 1  # the coordinator itself is unreachable

    def stop(self):
        self._stop.set()


class _Heartbeat:
    """File-based worker heartbeats for dead-node detection (ref: ps-lite
    heartbeat/GetDeadNodes, surfaced as KVStore::get_num_dead_node
    include/mxnet/kvstore.h:353).

    Each worker touches `<dir>/rank_<i>` every MXTPU_HEARTBEAT_INTERVAL
    seconds from a daemon thread; a peer is dead when its file has not been
    touched for MXTPU_HEARTBEAT_TIMEOUT seconds (or never appeared within
    the timeout of store creation). The default transport is the TCP
    control plane (_TcpHeartbeat) whenever a coordinator is configured;
    this file transport remains for coordinator-less local jobs and as an
    explicit opt-in (MXTPU_HEARTBEAT_TRANSPORT=file).
    """

    def __init__(self, rank, num_workers, hb_dir, interval, timeout):
        self.rank = rank
        self.num_workers = num_workers
        self.dir = hb_dir
        self.interval = interval
        self.timeout = timeout
        self.start_time = time.time()
        os.makedirs(hb_dir, exist_ok=True)
        self._stop = threading.Event()
        self._beat()
        t = threading.Thread(target=self._loop, daemon=True,
                             name="mxtpu-heartbeat-file")
        t.start()

    @classmethod
    def maybe_start(cls, rank, num_workers):
        if num_workers <= 1:
            return None
        from . import config as _config

        interval = _config.get("MXTPU_HEARTBEAT_INTERVAL")
        timeout = _config.get("MXTPU_HEARTBEAT_TIMEOUT")
        transport = _config.get("MXTPU_HEARTBEAT_TRANSPORT")
        coord = _config.get("MXTPU_COORDINATOR")
        if coord and ":" in coord and transport in ("tcp", "auto"):
            host, port = coord.rsplit(":", 1)
            try:
                return _TcpHeartbeat.get(rank, num_workers, host,
                                         int(port) + 29, interval, timeout)
            except (OSError, ConnectionError) as e:
                if transport == "tcp":
                    # explicit request: never silently downgrade (a split
                    # transport makes survivors report false dead nodes)
                    raise RuntimeError(
                        f"MXTPU_HEARTBEAT_TRANSPORT=tcp but the heartbeat "
                        f"service at {host}:{int(port) + 29} is "
                        f"unreachable: {e}") from e
                import warnings

                warnings.warn(f"TCP heartbeat service unreachable ({e}); "
                              "falling back to file heartbeats — dead-node "
                              "detection requires a shared filesystem")
        hb_dir = _config.get("MXTPU_HEARTBEAT_DIR")
        if not hb_dir:
            tag = (coord or "local").replace(":", "_").replace("/", "_")
            hb_dir = os.path.join(tempfile.gettempdir(), f"mxtpu_hb_{tag}")
        return cls(rank, num_workers, hb_dir, interval, timeout)

    def _path(self, rank):
        return os.path.join(self.dir, f"rank_{rank}")

    def _beat(self):
        try:
            with open(self._path(self.rank), "w") as f:
                f.write(str(time.time()))
        except OSError:
            pass

    def _loop(self):
        while not self._stop.wait(self.interval):
            self._beat()

    def stop(self):
        self._stop.set()

    def num_dead(self):
        now = time.time()
        dead = 0
        for r in range(self.num_workers):
            if r == self.rank:
                continue
            try:
                mtime = os.path.getmtime(self._path(r))
            except OSError:
                # never seen: dead only once the startup grace has passed
                if now - self.start_time > self.timeout:
                    dead += 1
                continue
            if now - mtime > self.timeout:
                dead += 1
        return dead


def create(name="local"):
    """(ref: KVStore::Create src/kvstore/kvstore.cc:40) — string dispatch."""
    if not isinstance(name, str):
        raise TypeError("name must be a string")
    if "dist" in name:
        from . import distributed

        distributed.init_from_env()  # launcher env -> jax.distributed
        if "async" in name:
            if name == "dist_async_server":
                return KVStoreDistAsyncServer(name)
            return KVStoreDistAsync(name)
        return KVStoreDist(name)
    return KVStore(name)


def create_kvstore_for_module(kvstore, num_device, arg_params):
    """(ref: model.py:82 _create_kvstore)"""
    update_on_kvstore = True
    if kvstore is None:
        kv = None
    elif isinstance(kvstore, KVStore):
        kv = kvstore
    elif isinstance(kvstore, str):
        if num_device == 1 and "dist" not in kvstore:
            kv = None
        else:
            kv = create(kvstore)
    else:
        raise TypeError(f"bad kvstore type {type(kvstore)}")
    if kv is None:
        update_on_kvstore = False
    elif isinstance(kv, KVStoreDistAsyncServer):
        # true parameter server: optimizer runs ON the server
        update_on_kvstore = True
    elif "dist" in kv.type:
        # dist on TPU = serverless allreduce; optimizer runs locally
        update_on_kvstore = False
    return kv, update_on_kvstore
