"""KVStore: the parameter synchronization API.

TPU-native re-design of the reference kvstore family (ref:
include/mxnet/kvstore.h; src/kvstore/ — local/device comm.h, nccl
kvstore_nccl.h:62, dist kvstore_dist.h:44). API surface (init/push/pull/
row_sparse_pull/set_updater/rank/num_workers/barrier) is kept so
Module/Trainer code ports unchanged; the transport is different by design:

- 'local'/'device'/'nccl'/'tree': single-process multi-device. There are no
  explicit reduce kernels or P2P rings — values live as (possibly sharded)
  jax.Arrays; multi-device gradient summation happens inside the XLA program
  via GSPMD-inserted ICI all-reduce, so push() just aggregates lists.
- 'dist_sync'/'dist_device_sync'/'dist_async': multi-process. ps-lite's
  server/worker protocol is replaced by DCN+ICI collectives over all hosts
  (jax.distributed), i.e. the serverless all-reduce the reference only had
  via Horovod.
"""
from __future__ import annotations

import pickle

import numpy as np
import jax
import jax.numpy as jnp

from .ndarray.ndarray import NDArray
from .ndarray.sparse import RowSparseNDArray

__all__ = ["KVStore", "create", "create_kvstore_for_module"]


def _to_data(v):
    return v._data if isinstance(v, NDArray) else jnp.asarray(v)


class KVStore:
    """Single-process store (ref: kvstore_local.h / comm.h)."""

    def __init__(self, kv_type="local"):
        self._type = kv_type
        self._store = {}
        self._updater = None
        self._optimizer = None
        self._compression = None

    # -- identity ----------------------------------------------------------
    @property
    def type(self):
        return self._type

    @property
    def rank(self):
        return 0

    @property
    def num_workers(self):
        return 1

    def barrier(self):
        pass

    @property
    def num_dead_node(self):
        return 0

    def set_gradient_compression(self, compression_params):
        self._compression = dict(compression_params)

    # -- data --------------------------------------------------------------
    def init(self, key, value):
        """(ref: KVStore::Init)"""
        if isinstance(key, (list, tuple)):
            for k, v in zip(key, value):
                self.init(k, v)
            return
        v = value[0] if isinstance(value, (list, tuple)) else value
        self._store[key] = v if isinstance(v, NDArray) else NDArray(v)

    def _reduce(self, value):
        """Sum a list of per-device values (CommCPU/CommDevice analog)."""
        if not isinstance(value, (list, tuple)):
            return _to_data(value)
        acc = _to_data(value[0])
        for v in value[1:]:
            acc = acc + _to_data(v)
        return acc

    def push(self, key, value, priority=0):
        """(ref: KVStore::Push) — aggregate + optionally run updater."""
        if isinstance(key, (list, tuple)):
            for k, v in zip(key, value):
                self.push(k, v, priority)
            return
        grad = self._reduce(value)
        if self._compression is not None and self._compression.get("type") == "2bit":
            grad = _two_bit_roundtrip(grad, float(self._compression.get("threshold", 0.5)))
        if self._updater is not None:
            weight = self._store[key]
            self._updater(_key_int(key), NDArray._from_data(grad), weight)
        else:
            if key in self._store:
                self._store[key]._data = self._store[key]._data + grad
            else:
                self._store[key] = NDArray._from_data(grad)

    def pull(self, key, out=None, priority=0, ignore_sparse=True):
        """(ref: KVStore::Pull) — broadcast to out array(s)."""
        if isinstance(key, (list, tuple)):
            for k, o in zip(key, out):
                self.pull(k, o, priority)
            return
        src = self._store[key]
        outs = out if isinstance(out, (list, tuple)) else [out]
        for o in outs:
            if o is not None:
                o._data = src._data
        return src

    def pushpull(self, key, value, out=None, priority=0):
        self.push(key, value, priority)
        if out is not None:
            if self._updater is None:
                # pure allreduce semantics: pull then reset accumulator
                self.pull(key, out, priority)
                if not isinstance(key, (list, tuple)):
                    del self._store[key]
            else:
                self.pull(key, out, priority)

    def row_sparse_pull(self, key, out=None, priority=0, row_ids=None):
        """(ref: KVStore::PullRowSparse) — gather only requested rows."""
        src = self._store[key]
        rid = row_ids[0] if isinstance(row_ids, (list, tuple)) else row_ids
        idx = _to_data(rid).astype(jnp.int32)
        rows = jnp.take(src._data, idx, axis=0)
        outs = out if isinstance(out, (list, tuple)) else [out]
        for o in outs:
            if isinstance(o, RowSparseNDArray):
                o.data._data = rows
                o.indices._data = idx.astype(jnp.int64)
            else:
                o._data = jnp.zeros_like(src._data).at[idx].set(rows)
        return out

    # -- updater/optimizer -------------------------------------------------
    def set_updater(self, updater):
        self._updater = updater

    def set_optimizer(self, optimizer):
        """(ref: kvstore.py set_optimizer — pickles optimizer to servers; here
        it directly becomes the local updater)"""
        from . import optimizer as opt

        self._optimizer = optimizer
        self._updater = opt.get_updater(optimizer)

    def save_optimizer_states(self, fname, dump_optimizer=False):
        assert self._updater is not None
        with open(fname, "wb") as f:
            f.write(self._updater.get_states(dump_optimizer))

    def load_optimizer_states(self, fname):
        assert self._updater is not None
        with open(fname, "rb") as f:
            self._updater.set_states(f.read())


class KVStoreDist(KVStore):
    """Multi-host store over DCN+ICI collectives (replaces ps-lite; ref:
    src/kvstore/kvstore_dist.h:44). Requires jax.distributed to be
    initialized by the launcher (tools/launch.py); degrades to local when
    single-process."""

    def __init__(self, kv_type="dist_sync"):
        super().__init__(kv_type)

    @property
    def rank(self):
        return jax.process_index()

    @property
    def num_workers(self):
        return jax.process_count()

    def barrier(self):
        if jax.process_count() > 1:
            from jax.experimental import multihost_utils

            multihost_utils.sync_global_devices("kvstore_barrier")

    def push(self, key, value, priority=0):
        if isinstance(key, (list, tuple)):
            for k, v in zip(key, value):
                self.push(k, v, priority)
            return
        grad = self._reduce(value)
        if self._compression is not None and self._compression.get("type") == "2bit":
            # compress-on-the-wire semantics: quantize the local contribution
            # before it crosses DCN (ref: DataHandleCompressed)
            grad = _two_bit_roundtrip(
                grad, float(self._compression.get("threshold", 0.5)))
        if self.num_workers > 1:
            import numpy as _np
            from jax.experimental import multihost_utils

            # host-side hop: the local grad may be committed to one local
            # device; allgather wants process-replicated input
            grad = multihost_utils.process_allgather(_np.asarray(grad))
            grad = jnp.sum(jnp.asarray(grad), axis=0)
        if self._updater is not None:
            self._updater(_key_int(key), NDArray._from_data(grad), self._store[key])
        else:
            if key in self._store:
                self._store[key]._data = self._store[key]._data + grad
            else:
                self._store[key] = NDArray._from_data(grad)




class KVStoreDistAsync(KVStoreDist):
    """Asynchronous distributed store (ref: `dist_async` —
    kvstore_dist_server.h:348 applies updates instantly, workers never
    barrier per step).

    TPU-native design: there is no server, so "async" = **bounded-staleness
    elastic averaging**. Every push applies the optimizer LOCALLY with zero
    cross-worker blocking; every `period`-th push of a key mixes that key's
    weights toward the cross-worker mean (collectives match by call order,
    so stragglers only rendezvous at mix points — staleness is bounded by
    `period`, the role MXNET_KVSTORE's async staleness played). Tune with
    MXTPU_ASYNC_PERIOD / MXTPU_ASYNC_ALPHA.
    """

    def __init__(self, kv_type="dist_async"):
        super().__init__(kv_type)
        import os as _os

        self._period = max(1, int(_os.environ.get("MXTPU_ASYNC_PERIOD", "16")))
        self._alpha = float(_os.environ.get("MXTPU_ASYNC_ALPHA", "0.5"))
        self._push_counts = {}

    def push(self, key, value, priority=0):
        if isinstance(key, (list, tuple)):
            for k, v in zip(key, value):
                self.push(k, v, priority)
            return
        grad = self._reduce(value)
        if self._compression is not None and self._compression.get("type") == "2bit":
            grad = _two_bit_roundtrip(
                grad, float(self._compression.get("threshold", 0.5)))
        # local apply — no cross-worker communication on the hot path
        if self._updater is not None:
            self._updater(_key_int(key), NDArray._from_data(grad),
                          self._store[key])
        else:
            if key in self._store:
                self._store[key]._data = self._store[key]._data + grad
            else:
                self._store[key] = NDArray._from_data(grad)
        c = self._push_counts.get(key, 0) + 1
        self._push_counts[key] = c
        if self.num_workers > 1 and c % self._period == 0:
            self._mix(key)

    def _mix(self, key, alpha=None):
        """Elastic-average this key toward the cross-worker mean."""
        import numpy as _np
        from jax.experimental import multihost_utils

        alpha = self._alpha if alpha is None else alpha
        w = self._store[key]
        gathered = multihost_utils.process_allgather(_np.asarray(w._data))
        mean = jnp.mean(jnp.asarray(gathered), axis=0)
        w._data = (1.0 - alpha) * w._data + alpha * mean

    def sync_all(self, alpha=1.0):
        """Force full weight consensus (e.g. before eval/checkpoint)."""
        for key in list(self._store):
            self._mix(key, alpha=alpha)


def _key_int(key):
    if isinstance(key, int):
        return key
    return key


def _two_bit_roundtrip(grad, threshold):
    """2-bit gradient quantization semantics (ref: gradient_compression.h:37).

    Single-process stores apply the quantize->dequantize roundtrip so
    training sees the same signal degradation + error-feedback as the
    reference's compressed push.
    """
    q = jnp.where(grad >= threshold, threshold, jnp.where(grad <= -threshold, -threshold, 0.0))
    return q


def create(name="local"):
    """(ref: KVStore::Create src/kvstore/kvstore.cc:40) — string dispatch."""
    if not isinstance(name, str):
        raise TypeError("name must be a string")
    if "dist" in name:
        from . import distributed

        distributed.init_from_env()  # launcher env -> jax.distributed
        if "async" in name:
            return KVStoreDistAsync(name)
        return KVStoreDist(name)
    return KVStore(name)


def create_kvstore_for_module(kvstore, num_device, arg_params):
    """(ref: model.py:82 _create_kvstore)"""
    update_on_kvstore = True
    if kvstore is None:
        kv = None
    elif isinstance(kvstore, KVStore):
        kv = kvstore
    elif isinstance(kvstore, str):
        if num_device == 1 and "dist" not in kvstore:
            kv = None
        else:
            kv = create(kvstore)
    else:
        raise TypeError(f"bad kvstore type {type(kvstore)}")
    if kv is None:
        update_on_kvstore = False
    elif "dist" in kv.type:
        # dist on TPU = serverless allreduce; optimizer runs locally
        update_on_kvstore = False
    return kv, update_on_kvstore
