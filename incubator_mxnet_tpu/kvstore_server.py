"""Server-role bootstrap (ref: python/mxnet/kvstore_server.py —
KVStoreServer:28 wraps the server loop; _init_kvstore_server_module:75
turns a process whose DMLC_ROLE is `server` into a blocking server).

TPU-native mapping: the server loop is `ps.ParameterServer` (the
authoritative-weight store behind kvstore type 'dist_async_server');
workers ship the optimizer over the authenticated control channel exactly
like the reference's CommandType::kController pickle. A process launched
with MXTPU_ROLE=server (e.g. by tools/launch.py) calls `run()` and never
returns until the job's workers disconnect.
"""
from __future__ import annotations

import logging
import os
import sys

from .ps import ParameterServer, default_server_addr

__all__ = ["KVStoreServer", "_init_kvstore_server_module"]


def _server_metrics_port(num_workers):
    """/metrics port for a PS server process: the configured base port
    offset by the worker count, so on a shared host the workers (who sit
    at the base port) and the server never collide. None when no port is
    configured."""
    from . import config as _config

    base = _config.get("MXNET_TELEMETRY_PORT")
    if base <= 0:
        return None
    return base + int(num_workers)


class KVStoreServer:
    """Blocking wrapper running the parameter-server loop in this
    process."""

    def __init__(self, kvstore=None, num_workers=None, host=None, port=None):
        self.kvstore = kvstore  # accepted for API parity; the server loop
        # here is self-contained and does not need a worker-side store
        if num_workers is None:
            # launcher wire protocol (reference DMLC_* pairing) -- raw env
            # read by design, like MXTPU_ROLE below
            num_workers = int(os.environ.get(  # mxlint: disable=MXL007
                "MXTPU_NUM_WORKERS",
                os.environ.get("DMLC_NUM_WORKER", "1")))
        self.metrics_server = None
        from . import config as _config

        if _config.get("MXNET_TELEMETRY"):
            # server-side counters (dedup hits, evictions) are useless if
            # nobody can scrape them: bind this role's offset port BEFORE
            # telemetry auto-resolution can grab the base one
            metrics_port = _server_metrics_port(num_workers)
            if metrics_port is not None:
                from . import telemetry as _telemetry

                self.metrics_server = _telemetry.enable(port=metrics_port)
        addr_host, addr_port = default_server_addr()
        self._server = ParameterServer(
            num_workers=num_workers,
            host=host if host is not None else addr_host,
            port=port if port is not None else addr_port)
        # elastic membership state, visible from process start: the
        # epoch gauge must exist (at 0) before the first join bumps it,
        # so dashboards can tell "no membership change yet" from "no
        # server"
        cap = _config.get("MXTPU_MAX_WORKERS")
        from . import telemetry as _telemetry

        _telemetry.set_gauge(
            "mxtpu_ps_membership_epoch", 0,
            help="Current membership epoch of the ParameterServer; bumps "
                 "on every membership change (readmission, rank "
                 "takeover, world growth).")
        logging.getLogger(__name__).info(
            "parameter server on %s:%d — world %d, elastic cap %s",
            self._server.host, self._server.port, num_workers,
            cap if cap > 0 else "off (fixed world)")

    def run(self):
        """Serve until every worker has disconnected (the reference's
        MXKVStoreRunServer blocking contract)."""
        logging.basicConfig(
            level=logging.INFO,
            format="%(asctime)-15s Server %(message)s")
        self._server.serve_forever()


def _init_kvstore_server_module():
    """If this process was launched in the server role, run the server
    loop and exit — mirrors the reference's import-time role check."""
    # launcher wire protocol, read before any framework import may
    # finish (paired with the reference DMLC_* names) -- stays a raw
    # env read by design
    role = os.environ.get("MXTPU_ROLE",  # mxlint: disable=MXL007
                          os.environ.get("DMLC_ROLE", ""))
    if role == "server":
        server = KVStoreServer()
        server.run()
        sys.exit(0)


if __name__ == "__main__":
    # dedicated server process: `python -m incubator_mxnet_tpu.kvstore_server`
    # is an explicit request to serve — override any inherited role env
    os.environ["MXTPU_ROLE"] = "server"
    _init_kvstore_server_module()
