"""Operator registry.

TPU-native analog of the reference's NNVM op registry (ref:
src/operator/*/*.cc `NNVM_REGISTER_OP`, include/mxnet/op_attr_types.h). Each
op is a *pure function* over jax arrays plus static attributes. From this one
registry we generate both the eager `nd.*` functions and the symbolic `sym.*`
builders, the same way the reference generates Python frontends from
`MXSymbolGetAtomicSymbolInfo` (ref: python/mxnet/ndarray/register.py:157).

Key differences from the reference, by design:
- No FCompute/FInferShape/FInferType triples: shape/type inference is
  `jax.eval_shape` over the same pure function; gradients come from `jax.vjp`
  (ref's FGradient pass: src/nnvm/gradient.cc) — one definition, no drift.
- Mutable aux state (e.g. BatchNorm running stats) is modeled functionally:
  the op returns updated aux values as extra outputs and the caller writes
  them back (ref models this as in-place aux_states on the executor).
"""
from __future__ import annotations

import inspect
from dataclasses import dataclass, field
from typing import Callable, Optional, Sequence

__all__ = ["OpDef", "register", "get_op", "list_ops", "OP_REGISTRY", "alias"]

OP_REGISTRY: dict[str, "OpDef"] = {}


@dataclass
class OpDef:
    """One registered operator.

    fn signature convention: positional params are tensor inputs; keyword-only
    params are static attrs. A ``*args`` param means variadic tensor inputs
    (Concat/add_n style). If ``needs_rng``/``needs_training`` the evaluator
    passes ``_rng`` (a jax PRNG key) / ``_training`` (bool) keyword args.
    """

    name: str
    fn: Callable
    inputs: Sequence[str] = ()
    variadic: bool = False
    num_outputs: int = 1
    # names of inputs that are mutable aux state; fn returns
    # (out_0..out_{n-1}, new_aux_0, ...) when training
    aux: Sequence[str] = ()
    needs_rng: bool = False
    needs_training: bool = False
    # inputs that are optional (may be None), e.g. bias under no_bias
    optional: Sequence[str] = ()
    attrs: dict = field(default_factory=dict)  # attr name -> default
    aliases: Sequence[str] = ()
    no_grad_inputs: Sequence[str] = ()  # integer-like inputs w/o gradients
    # inputs whose buffers the op semantically CONSUMES (in-place update
    # contract: the caller rebinds them to the op's outputs — optimizer
    # weight/state updates). The jitted eager dispatch donates these to
    # XLA off-CPU so the update writes in place instead of allocating a
    # second copy of every parameter (ref: MXNET_EXEC_ENABLE_INPLACE).
    donate: Sequence[str] = ()

    @property
    def attr_names(self):
        return tuple(self.attrs.keys())


def register(
    name,
    *,
    num_outputs=1,
    aux=(),
    needs_rng=False,
    needs_training=False,
    optional=(),
    aliases=(),
    no_grad_inputs=(),
    donate=(),
):
    """Decorator registering a pure function as an operator."""

    def deco(fn):
        sig = inspect.signature(fn)
        inputs, attrs, variadic = [], {}, False
        for pname, p in sig.parameters.items():
            if pname in ("_rng", "_training"):
                continue
            if p.kind == inspect.Parameter.VAR_POSITIONAL:
                variadic = True
            elif p.kind == inspect.Parameter.KEYWORD_ONLY:
                attrs[pname] = None if p.default is inspect.Parameter.empty else p.default
            elif p.kind in (
                inspect.Parameter.POSITIONAL_ONLY,
                inspect.Parameter.POSITIONAL_OR_KEYWORD,
            ):
                inputs.append(pname)
        opdef = OpDef(
            name=name,
            fn=fn,
            inputs=tuple(inputs),
            variadic=variadic,
            num_outputs=num_outputs,
            aux=tuple(aux),
            needs_rng=needs_rng,
            needs_training=needs_training,
            optional=tuple(optional),
            attrs=attrs,
            aliases=tuple(aliases),
            no_grad_inputs=tuple(no_grad_inputs),
            donate=tuple(donate),
        )
        OP_REGISTRY[name] = opdef
        for a in aliases:
            OP_REGISTRY[a] = opdef
        fn.__opdef__ = opdef
        return fn

    return deco


def alias(existing, *names):
    op = OP_REGISTRY[existing]
    for n in names:
        OP_REGISTRY[n] = op


def get_op(name) -> Optional[OpDef]:
    return OP_REGISTRY.get(name)


def list_ops():
    return sorted(set(o.name for o in OP_REGISTRY.values()))
