"""INT8 quantized operators (ref: src/operator/quantization/ —
quantized_conv.cu, quantized_fully_connected.cc, quantized_pooling.cc).

TPU-native: int8 x int8 -> int32 via `preferred_element_type` maps straight
onto the MXU's integer path (v5e: 394 int8 TOPS, 2x bf16). No zero-points —
symmetric per-tensor scales, matching the reference's int8 scheme. The ops
are inference-only (no_grad), like the reference's quantized kernels.
"""
from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from .registry import register
from .nn import _conv_dn, _tup


@register("_contrib_quantized_conv", optional=("bias",),
          no_grad_inputs=("data", "weight", "bias"))
def quantized_conv(data, weight, bias=None, *, kernel=None, stride=None,
                   dilate=None, pad=None, num_filter=None, num_group=1,
                   no_bias=True, layout=None):
    """int8 NCHW convolution with int32 accumulation
    (ref: quantized_conv.cu). `bias`, when given, must already be int32 in
    the product scale (s_data * s_weight)."""
    nd = data.ndim - 2
    strides = _tup(stride, nd)
    dil = _tup(dilate, nd)
    p = _tup(pad, nd) if pad is not None else (0,) * nd
    out = lax.conv_general_dilated(
        data.astype(jnp.int8),
        weight.astype(jnp.int8),
        window_strides=strides,
        padding=[(pi, pi) for pi in p],
        rhs_dilation=dil,
        dimension_numbers=_conv_dn(nd),
        feature_group_count=num_group,
        preferred_element_type=jnp.int32,
    )
    if bias is not None and not no_bias:
        out = out + bias.astype(jnp.int32).reshape((1, -1) + (1,) * nd)
    return out


@register("_contrib_quantized_fully_connected", optional=("bias",),
          no_grad_inputs=("data", "weight", "bias"))
def quantized_fully_connected(data, weight, bias=None, *, num_hidden=None,
                              no_bias=True, flatten=True):
    """int8 y = x W^T (+ b) with int32 accumulation
    (ref: quantized_fully_connected.cc)."""
    x = data.reshape((data.shape[0], -1)) if flatten and data.ndim > 2 else data
    y = lax.dot_general(
        x.astype(jnp.int8), weight.astype(jnp.int8),
        (((x.ndim - 1,), (1,)), ((), ())),
        preferred_element_type=jnp.int32,
    )
    if bias is not None and not no_bias:
        y = y + bias.astype(jnp.int32)
    return y


@register("_contrib_quantized_pooling", no_grad_inputs=("data",))
def quantized_pooling(data, *, kernel=None, stride=None, pad=None,
                      pool_type="max", global_pool=False,
                      pooling_convention="valid"):
    """Pooling on int8 activations (ref: quantized_pooling.cc). Max pools
    stay int8 (including ceil-mode/'full' convention: the identity pad is
    int8-min, so the max is exact); avg pools accumulate in int32 and
    round back."""
    nd = data.ndim - 2
    if global_pool:
        k = data.shape[2:]
        strides = (1,) * nd
        p = (0,) * nd
    else:
        k = _tup(kernel, nd)
        strides = _tup(stride, nd) if stride is not None else k
        p = _tup(pad, nd) if pad is not None else (0,) * nd
    dims = (1, 1) + tuple(k)
    strd = (1, 1) + tuple(strides)
    pads = [(pi, pi) for pi in p]
    if pooling_convention == "full" and not global_pool:
        # ceil-mode (same high-side padding rule as ops.nn.pooling)
        for i in range(nd):
            dim = data.shape[2 + i]
            in_sz = dim + 2 * p[i]
            rem = (in_sz - k[i]) % strides[i]
            extra = (strides[i] - rem) % strides[i] if rem != 0 else 0
            pads[i] = (p[i], p[i] + extra)
    padding = ((0, 0), (0, 0)) + tuple(pads)
    if pool_type == "max":
        return lax.reduce_window(data,
                                 jnp.asarray(jnp.iinfo(jnp.int8).min,
                                             dtype=data.dtype),
                                 lax.max, dims, strd, padding)
    acc = lax.reduce_window(data.astype(jnp.int32), 0, lax.add,
                            dims, strd, padding)
    count = 1
    for ki in k:
        count *= ki
    return jnp.clip(jnp.round(acc / count), -128, 127).astype(jnp.int8)


# --- quantize/dequantize wire ops (ref: quantization/quantize.cc,
# quantize_v2.cc, dequantize.cc, requantize.cc, quantized_concat.cc,
# quantized_flatten.cc) -----------------------------------------------------


def _q_range(min_r, max_r):
    """Symmetric scale for int8 from a calibration range."""
    amax = jnp.maximum(jnp.abs(min_r), jnp.abs(max_r))
    return jnp.where(amax > 0, 127.0 / amax, 1.0)


@register("_contrib_quantize", num_outputs=3,
          no_grad_inputs=("data", "min_range", "max_range"))
def _contrib_quantize(data, min_range, max_range, *, out_type="int8"):
    """fp32 -> int8 with explicit calibration range tensors; returns
    (q, min, max) like the reference."""
    if out_type not in ("int8", "auto"):
        raise NotImplementedError(
            f"quantize out_type='{out_type}': the MXU int8 path is the "
            f"implemented target (uint8 is not)")
    scale = _q_range(min_range, max_range)
    q = jnp.clip(jnp.rint(data * scale), -127, 127).astype(jnp.int8)
    amax = jnp.maximum(jnp.abs(min_range), jnp.abs(max_range))
    return q, -amax, amax


@register("_contrib_quantize_v2", num_outputs=3, no_grad_inputs=("data",))
def _contrib_quantize_v2(data, *, out_type="int8", min_calib_range=None,
                         max_calib_range=None):
    """Range from attrs when calibrated, else from the data
    (ref: quantize_v2.cc)."""
    if out_type not in ("int8", "auto"):
        raise NotImplementedError(
            f"quantize_v2 out_type='{out_type}': the MXU int8 path is the "
            f"implemented target (uint8 is not)")
    if min_calib_range is not None and max_calib_range is not None:
        lo = jnp.asarray(min_calib_range, jnp.float32)
        hi = jnp.asarray(max_calib_range, jnp.float32)
    else:
        lo, hi = data.min(), data.max()
    scale = _q_range(lo, hi)
    q = jnp.clip(jnp.rint(data * scale), -127, 127).astype(jnp.int8)
    amax = jnp.maximum(jnp.abs(lo), jnp.abs(hi))
    return q, -amax, amax


@register("_contrib_dequantize",
          no_grad_inputs=("data", "min_range", "max_range"))
def _contrib_dequantize(data, min_range, max_range, *, out_type="float32"):
    """Map int8/uint8 values back to float32 using the recorded (min, max)
    range."""
    if out_type != "float32":
        raise NotImplementedError(
            f"dequantize out_type='{out_type}': only float32 reconstruction "
            f"is implemented")
    scale = _q_range(min_range, max_range)
    return data.astype(jnp.float32) / scale


@register("_contrib_requantize", num_outputs=3,
          no_grad_inputs=("data", "min_range", "max_range"))
def _contrib_requantize(data, min_range, max_range, *, min_calib_range=None,
                        max_calib_range=None, out_type="int8"):
    """int32 accumulator -> int8 (ref: requantize.cc). The int32 range
    tensors describe the REAL values of the accumulator's int32 extremes,
    so the reconstruction scale is amax/(2^31-1), not the int8 127."""
    if out_type not in ("int8", "auto"):
        raise NotImplementedError(
            f"requantize out_type='{out_type}': the MXU int8 path is the "
            f"implemented target (uint8 is not)")
    amax32 = jnp.maximum(jnp.abs(min_range), jnp.abs(max_range))
    real = data.astype(jnp.float32) * (amax32 / 2147483647.0)
    if min_calib_range is not None and max_calib_range is not None:
        lo = jnp.asarray(min_calib_range, jnp.float32)
        hi = jnp.asarray(max_calib_range, jnp.float32)
    else:
        lo, hi = real.min(), real.max()
    scale = _q_range(lo, hi)
    q = jnp.clip(jnp.rint(real * scale), -127, 127).astype(jnp.int8)
    amax = jnp.maximum(jnp.abs(lo), jnp.abs(hi))
    return q, -amax, amax


@register("_contrib_quantized_flatten", num_outputs=3,
          no_grad_inputs=("data", "min_range", "max_range"))
def _contrib_quantized_flatten(data, min_range, max_range):
    """Flatten quantized data, passing its (min, max) range through unchanged."""
    return data.reshape(data.shape[0], -1), min_range, max_range


@register("_contrib_quantized_concat", num_outputs=3)
def _contrib_quantized_concat(*args, num_args=None, dim=1):
    """Concat n int8 tensors whose ranges may differ: requantize each onto
    the merged range, then concat (ref: quantized_concat.cc). Inputs are
    (data_0..n-1, min_0..n-1, max_0..n-1)."""
    n = int(num_args) if num_args else len(args) // 3
    datas, mins, maxs = args[:n], args[n:2 * n], args[2 * n:3 * n]
    amaxs = [jnp.maximum(jnp.abs(lo), jnp.abs(hi))
             for lo, hi in zip(mins, maxs)]
    merged = amaxs[0]
    for a in amaxs[1:]:
        merged = jnp.maximum(merged, a)
    scaled = [
        jnp.clip(jnp.rint(d.astype(jnp.float32) * (a / merged)), -127, 127
                 ).astype(jnp.int8)
        for d, a in zip(datas, amaxs)
    ]
    return jnp.concatenate(scaled, axis=int(dim)), -merged, merged
