"""INT8 quantized operators (ref: src/operator/quantization/ —
quantized_conv.cu, quantized_fully_connected.cc, quantized_pooling.cc).

TPU-native: int8 x int8 -> int32 via `preferred_element_type` maps straight
onto the MXU's integer path (v5e: 394 int8 TOPS, 2x bf16). No zero-points —
symmetric per-tensor scales, matching the reference's int8 scheme. The ops
are inference-only (no_grad), like the reference's quantized kernels.
"""
from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from .registry import register
from .nn import _conv_dn, _tup


@register("_contrib_quantized_conv", optional=("bias",),
          no_grad_inputs=("data", "weight", "bias"))
def quantized_conv(data, weight, bias=None, *, kernel=None, stride=None,
                   dilate=None, pad=None, num_filter=None, num_group=1,
                   no_bias=True, layout=None):
    """int8 NCHW convolution with int32 accumulation
    (ref: quantized_conv.cu). `bias`, when given, must already be int32 in
    the product scale (s_data * s_weight)."""
    nd = data.ndim - 2
    strides = _tup(stride, nd)
    dil = _tup(dilate, nd)
    p = _tup(pad, nd) if pad is not None else (0,) * nd
    out = lax.conv_general_dilated(
        data.astype(jnp.int8),
        weight.astype(jnp.int8),
        window_strides=strides,
        padding=[(pi, pi) for pi in p],
        rhs_dilation=dil,
        dimension_numbers=_conv_dn(nd),
        feature_group_count=num_group,
        preferred_element_type=jnp.int32,
    )
    if bias is not None and not no_bias:
        out = out + bias.astype(jnp.int32).reshape((1, -1) + (1,) * nd)
    return out


@register("_contrib_quantized_fully_connected", optional=("bias",),
          no_grad_inputs=("data", "weight", "bias"))
def quantized_fully_connected(data, weight, bias=None, *, num_hidden=None,
                              no_bias=True, flatten=True):
    """int8 y = x W^T (+ b) with int32 accumulation
    (ref: quantized_fully_connected.cc)."""
    x = data.reshape((data.shape[0], -1)) if flatten and data.ndim > 2 else data
    y = lax.dot_general(
        x.astype(jnp.int8), weight.astype(jnp.int8),
        (((x.ndim - 1,), (1,)), ((), ())),
        preferred_element_type=jnp.int32,
    )
    if bias is not None and not no_bias:
        y = y + bias.astype(jnp.int32)
    return y


@register("_contrib_quantized_pooling", no_grad_inputs=("data",))
def quantized_pooling(data, *, kernel=None, stride=None, pad=None,
                      pool_type="max", global_pool=False):
    """Pooling on int8 activations (ref: quantized_pooling.cc). Max pools
    stay int8; avg pools accumulate in int32 and round back."""
    nd = data.ndim - 2
    if global_pool:
        k = data.shape[2:]
        strides = (1,) * nd
        p = (0,) * nd
    else:
        k = _tup(kernel, nd)
        strides = _tup(stride, nd) if stride is not None else k
        p = _tup(pad, nd) if pad is not None else (0,) * nd
    dims = (1, 1) + tuple(k)
    strd = (1, 1) + tuple(strides)
    padding = ((0, 0), (0, 0)) + tuple((pi, pi) for pi in p)
    if pool_type == "max":
        return lax.reduce_window(data,
                                 jnp.asarray(jnp.iinfo(jnp.int8).min,
                                             dtype=data.dtype),
                                 lax.max, dims, strd, padding)
    acc = lax.reduce_window(data.astype(jnp.int32), 0, lax.add,
                            dims, strd, padding)
    count = 1
    for ki in k:
        count *= ki
    return jnp.clip(jnp.round(acc / count), -128, 127).astype(jnp.int8)
