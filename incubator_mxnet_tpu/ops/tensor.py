"""Tensor math operators.

TPU-native coverage of the reference's `src/operator/tensor/` family
(elemwise_unary/binary, broadcast, reductions, dot, indexing, matrix ops,
ordering, init ops — ref: SURVEY §2 N5). Every op is a pure jnp/lax function;
XLA fuses elementwise chains into surrounding matmuls so there is no need for
the reference's mshadow expression templates or Kernel<OP,xpu>::Launch
machinery (ref: src/operator/mxnet_op.h:538).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register, alias

# ---------------------------------------------------------------------------
# elementwise binary (broadcasting, like the reference's broadcast_* family;
# the strict elemwise_* variants share the same impl since XLA handles both)
# ---------------------------------------------------------------------------


def _binary(name, fn, aliases=()):
    @register(name, aliases=aliases)
    def op(lhs, rhs):
        """Elementwise binary operator with numpy broadcasting; the registered
        name (e.g. broadcast_add) selects the function."""
        return fn(lhs, rhs)

    op.__name__ = name
    return op


_binary("broadcast_add", jnp.add, aliases=("elemwise_add", "_plus", "_add"))
_binary("broadcast_sub", jnp.subtract, aliases=("elemwise_sub", "_minus", "_sub"))
_binary("broadcast_mul", jnp.multiply, aliases=("elemwise_mul", "_mul"))
_binary("broadcast_div", jnp.divide, aliases=("elemwise_div", "_div"))
_binary("broadcast_mod", jnp.mod, aliases=("_mod",))
_binary("broadcast_power", jnp.power, aliases=("_power", "pow"))
_binary("broadcast_maximum", jnp.maximum, aliases=("_maximum", "maximum"))
_binary("broadcast_minimum", jnp.minimum, aliases=("_minimum", "minimum"))
_binary("broadcast_hypot", jnp.hypot, aliases=("_hypot",))
_binary("broadcast_equal", lambda a, b: jnp.equal(a, b).astype(a.dtype), aliases=("_equal",))
_binary(
    "broadcast_not_equal", lambda a, b: jnp.not_equal(a, b).astype(a.dtype), aliases=("_not_equal",)
)
_binary("broadcast_greater", lambda a, b: jnp.greater(a, b).astype(a.dtype), aliases=("_greater",))
_binary(
    "broadcast_greater_equal",
    lambda a, b: jnp.greater_equal(a, b).astype(a.dtype),
    aliases=("_greater_equal",),
)
_binary("broadcast_lesser", lambda a, b: jnp.less(a, b).astype(a.dtype), aliases=("_lesser",))
_binary(
    "broadcast_lesser_equal",
    lambda a, b: jnp.less_equal(a, b).astype(a.dtype),
    aliases=("_lesser_equal",),
)
_binary(
    "broadcast_logical_and",
    lambda a, b: jnp.logical_and(a, b).astype(a.dtype),
    aliases=("_logical_and",),
)
_binary(
    "broadcast_logical_or",
    lambda a, b: jnp.logical_or(a, b).astype(a.dtype),
    aliases=("_logical_or",),
)
_binary(
    "broadcast_logical_xor",
    lambda a, b: jnp.logical_xor(a, b).astype(a.dtype),
    aliases=("_logical_xor",),
)


# scalar ops (ref: elemwise_binary_scalar_op*.cc) — scalar is a static attr
def _scalar_op(name, fn, aliases=()):
    @register(name, aliases=aliases)
    def op(data, *, scalar=1.0):
        """Elementwise op against a static `scalar` attr (ref:
        elemwise_binary_scalar_op)."""
        return fn(data, scalar)

    op.__name__ = name
    return op


_scalar_op("_plus_scalar", lambda x, s: x + s)
_scalar_op("_minus_scalar", lambda x, s: x - s)
_scalar_op("_rminus_scalar", lambda x, s: s - x)
_scalar_op("_mul_scalar", lambda x, s: x * s)
_scalar_op("_div_scalar", lambda x, s: x / s)
_scalar_op("_rdiv_scalar", lambda x, s: s / x)
_scalar_op("_mod_scalar", lambda x, s: jnp.mod(x, s))
_scalar_op("_rmod_scalar", lambda x, s: jnp.mod(s, x))
_scalar_op("_power_scalar", lambda x, s: jnp.power(x, s))
_scalar_op("_rpower_scalar", lambda x, s: jnp.power(s, x))
_scalar_op("_maximum_scalar", lambda x, s: jnp.maximum(x, s))
_scalar_op("_minimum_scalar", lambda x, s: jnp.minimum(x, s))
_scalar_op("_equal_scalar", lambda x, s: (x == s).astype(x.dtype))
_scalar_op("_not_equal_scalar", lambda x, s: (x != s).astype(x.dtype))
_scalar_op("_greater_scalar", lambda x, s: (x > s).astype(x.dtype))
_scalar_op("_greater_equal_scalar", lambda x, s: (x >= s).astype(x.dtype))
_scalar_op("_lesser_scalar", lambda x, s: (x < s).astype(x.dtype))
_scalar_op("_lesser_equal_scalar", lambda x, s: (x <= s).astype(x.dtype))


# ---------------------------------------------------------------------------
# elementwise unary (ref: elemwise_unary_op_basic.cc + mshadow_op.h functors)
# ---------------------------------------------------------------------------


def _unary(name, fn, aliases=()):
    @register(name, aliases=aliases)
    def op(data):
        """Elementwise unary function applied to the whole array."""
        return fn(data)

    op.__name__ = name
    return op


_unary("abs", jnp.abs)
_unary("sign", jnp.sign)
_unary("rint", jnp.rint)
_unary("round", jnp.round)
_unary("ceil", jnp.ceil)
_unary("floor", jnp.floor)
_unary("trunc", jnp.trunc)
_unary("fix", jnp.fix)
_unary("square", jnp.square)
_unary("sqrt", jnp.sqrt)
_unary("rsqrt", lambda x: lax.rsqrt(x))
_unary("cbrt", jnp.cbrt)
_unary("rcbrt", lambda x: 1.0 / jnp.cbrt(x))
_unary("exp", jnp.exp)
_unary("log", jnp.log)
_unary("log10", jnp.log10)
_unary("log2", jnp.log2)
_unary("log1p", jnp.log1p)
_unary("expm1", jnp.expm1)
_unary("sin", jnp.sin)
_unary("cos", jnp.cos)
_unary("tan", jnp.tan)
_unary("arcsin", jnp.arcsin)
_unary("arccos", jnp.arccos)
_unary("arctan", jnp.arctan)
_unary("sinh", jnp.sinh)
_unary("cosh", jnp.cosh)
_unary("tanh", jnp.tanh)
_unary("arcsinh", jnp.arcsinh)
_unary("arccosh", jnp.arccosh)
_unary("arctanh", jnp.arctanh)
_unary("degrees", jnp.degrees)
_unary("radians", jnp.radians)
_unary("sigmoid", jax.nn.sigmoid)
_unary("softsign", jax.nn.soft_sign)
_unary("relu", jax.nn.relu)
_unary("erf", jax.scipy.special.erf)
_unary("erfinv", jax.scipy.special.erfinv)
_unary("gamma", lambda x: jnp.exp(jax.scipy.special.gammaln(x)))
_unary("gammaln", jax.scipy.special.gammaln)
_unary("reciprocal", jnp.reciprocal)
_unary("negative", jnp.negative, aliases=("_neg",))
_unary("logical_not", lambda x: jnp.logical_not(x).astype(x.dtype))
_unary("identity", lambda x: x, aliases=("_copy",))
_unary("BlockGrad", lax.stop_gradient, aliases=("stop_gradient",))
_unary("make_loss", lambda x: x, aliases=("MakeLoss",))


@register("smooth_l1")
def smooth_l1(data, *, scalar=1.0):
    """Elementwise smooth-L1: quadratic inside |x| < 1/sigma^2, linear outside."""
    s2 = scalar * scalar
    absd = jnp.abs(data)
    return jnp.where(absd < 1.0 / s2, 0.5 * s2 * data * data, absd - 0.5 / s2)


@register("clip")
def clip(data, *, a_min=0.0, a_max=1.0):
    """Clamp values to [a_min, a_max]."""
    return jnp.clip(data, a_min, a_max)


@register("Cast", aliases=("cast",))
def cast(data, *, dtype="float32"):
    """Cast to `dtype`."""
    from ..base import dtype_np

    return data.astype(dtype_np(dtype))


@register("amp_cast")
def amp_cast(data, *, dtype="float32"):
    """AMP dtype cast -- same as `cast`, kept distinct so AMP graph passes can
    target it."""
    from ..base import dtype_np

    return data.astype(dtype_np(dtype))


# ---------------------------------------------------------------------------
# reductions (ref: broadcast_reduce_op_value.cc) — MXNet axis semantics:
# axis may be int/tuple/None; `exclude` inverts the axis set.
# ---------------------------------------------------------------------------


def _norm_axis(axis, ndim, exclude=False):
    if axis is None:
        ax = tuple(range(ndim))
    elif isinstance(axis, int):
        ax = (axis % ndim,)
    else:
        ax = tuple(a % ndim for a in axis)
    if exclude:
        ax = tuple(i for i in range(ndim) if i not in ax)
    return ax


def _reduce(name, fn, aliases=()):
    @register(name, aliases=aliases)
    def op(data, *, axis=None, keepdims=False, exclude=False):
        """Reduction over `axis` (None = all axes) with keepdims/exclude
        semantics."""
        ax = _norm_axis(axis, data.ndim, exclude)
        return fn(data, axis=ax, keepdims=keepdims)

    op.__name__ = name
    return op


_reduce("sum", jnp.sum, aliases=("sum_axis",))
_reduce("mean", jnp.mean)
_reduce("prod", jnp.prod)
_reduce("nansum", jnp.nansum)
_reduce("nanprod", jnp.nanprod)
_reduce("max", jnp.max, aliases=("max_axis",))
_reduce("min", jnp.min, aliases=("min_axis",))


@register("norm")
def norm(data, *, ord=2, axis=None, keepdims=False):
    """Vector norm of order `ord` over `axis`."""
    ax = None if axis is None else (axis if isinstance(axis, tuple) else (axis,))
    if ord == 1:
        r = jnp.sum(jnp.abs(data), axis=ax, keepdims=keepdims)
    else:
        r = jnp.sqrt(jnp.sum(jnp.square(data), axis=ax, keepdims=keepdims))
    return r


@register("argmax", no_grad_inputs=("data",))
def argmax(data, *, axis=None, keepdims=False):
    """Index of the maximum along `axis`, as float (reference convention)."""
    out = jnp.argmax(data, axis=axis, keepdims=keepdims)
    return out.astype(jnp.float32)


@register("argmin", no_grad_inputs=("data",))
def argmin(data, *, axis=None, keepdims=False):
    """Index of the minimum along `axis`, as float (reference convention)."""
    out = jnp.argmin(data, axis=axis, keepdims=keepdims)
    return out.astype(jnp.float32)


@register("argmax_channel", no_grad_inputs=("data",))
def argmax_channel(data):
    """Argmax over axis 1 (the channel dim) for each instance."""
    return jnp.argmax(data, axis=1).astype(jnp.float32)


# ---------------------------------------------------------------------------
# dot / linalg (ref: tensor/dot-inl.h, tensor/la_op.h) — straight onto the MXU
# ---------------------------------------------------------------------------


@register("dot")
def dot(lhs, rhs, *, transpose_a=False, transpose_b=False):
    """Dot / matrix product of the two inputs, with optional transposes."""
    a = lhs.T if transpose_a else lhs
    b = rhs.T if transpose_b else rhs
    if a.ndim == 1 and b.ndim == 1:
        return jnp.dot(a, b)
    # MXNet dot contracts last axis of a with first axis of b
    return jnp.tensordot(a, b, axes=([a.ndim - 1], [0]))


@register("batch_dot")
def batch_dot(lhs, rhs, *, transpose_a=False, transpose_b=False):
    """Batched matrix product over the leading batch dimension."""
    a = jnp.swapaxes(lhs, -1, -2) if transpose_a else lhs
    b = jnp.swapaxes(rhs, -1, -2) if transpose_b else rhs
    return jnp.matmul(a, b)


@register("_linalg_gemm2", aliases=("linalg_gemm2",))
def linalg_gemm2(A, B, *, transpose_a=False, transpose_b=False, alpha=1.0):
    """GEMM: alpha * op(A) @ op(B) with optional transposes."""
    a = jnp.swapaxes(A, -1, -2) if transpose_a else A
    b = jnp.swapaxes(B, -1, -2) if transpose_b else B
    return alpha * jnp.matmul(a, b)


@register("_linalg_gemm", aliases=("linalg_gemm",))
def linalg_gemm(A, B, C, *, transpose_a=False, transpose_b=False, alpha=1.0, beta=1.0):
    """GEMM with accumulate: alpha * op(A) @ op(B) + beta * C."""
    a = jnp.swapaxes(A, -1, -2) if transpose_a else A
    b = jnp.swapaxes(B, -1, -2) if transpose_b else B
    return alpha * jnp.matmul(a, b) + beta * C


@register("_linalg_potrf", aliases=("linalg_potrf",))
def linalg_potrf(A):
    """Cholesky factor of a symmetric positive-definite matrix."""
    return jnp.linalg.cholesky(A)


@register("_linalg_potri", aliases=("linalg_potri",))
def linalg_potri(A):
    """Matrix inverse from a Cholesky factor (potrf output)."""
    L = A
    ident = jnp.broadcast_to(jnp.eye(L.shape[-1], dtype=L.dtype), L.shape)
    Linv = jax.scipy.linalg.solve_triangular(L, ident, lower=True)
    return jnp.matmul(jnp.swapaxes(Linv, -1, -2), Linv)


@register("_linalg_trsm", aliases=("linalg_trsm",))
def linalg_trsm(A, B, *, transpose=False, rightside=False, lower=True, alpha=1.0):
    """Triangular solve against B (left or right sided), scaled by alpha."""
    a = jnp.swapaxes(A, -1, -2) if transpose else A
    low = bool(lower) != bool(transpose)
    if rightside:
        x = jax.scipy.linalg.solve_triangular(
            jnp.swapaxes(a, -1, -2), jnp.swapaxes(B, -1, -2), lower=not low
        )
        return alpha * jnp.swapaxes(x, -1, -2)
    return alpha * jax.scipy.linalg.solve_triangular(a, B, lower=low)


@register("_linalg_trmm", aliases=("linalg_trmm",))
def linalg_trmm(A, B, *, transpose=False, rightside=False, lower=True, alpha=1.0):
    """Triangular matrix product: alpha * op(A) @ B (left or right sided)."""
    a = jnp.swapaxes(A, -1, -2) if transpose else A
    a = jnp.tril(a) if (bool(lower) != bool(transpose)) else jnp.triu(a)
    return alpha * (jnp.matmul(B, a) if rightside else jnp.matmul(a, B))


@register("_linalg_syrk", aliases=("linalg_syrk",))
def linalg_syrk(A, *, transpose=False, alpha=1.0):
    """Symmetric rank-k update: alpha * A @ A^T (or A^T @ A)."""
    at = jnp.swapaxes(A, -1, -2)
    return alpha * (jnp.matmul(at, A) if transpose else jnp.matmul(A, at))


@register("_linalg_sumlogdiag", aliases=("linalg_sumlogdiag",))
def linalg_sumlogdiag(A):
    """Sum of the log of the diagonal entries (log-det from a Cholesky factor)."""
    return jnp.sum(jnp.log(jnp.diagonal(A, axis1=-2, axis2=-1)), axis=-1)


@register("_linalg_extractdiag", aliases=("linalg_extractdiag",))
def linalg_extractdiag(A, *, offset=0):
    """Extract the k-th diagonal as a vector."""
    return jnp.diagonal(A, offset=offset, axis1=-2, axis2=-1)


@register("_linalg_makediag", aliases=("linalg_makediag",))
def linalg_makediag(A, *, offset=0):
    """Embed a vector as the k-th diagonal of a square matrix."""
    return jax.vmap(lambda v: jnp.diag(v, k=offset))(A.reshape((-1, A.shape[-1]))).reshape(
        A.shape[:-1] + (A.shape[-1] + abs(offset),) * 2
    ) if A.ndim > 1 else jnp.diag(A, k=offset)


# ---------------------------------------------------------------------------
# matrix / shape manipulation (ref: tensor/matrix_op-inl.h)
# ---------------------------------------------------------------------------


@register("Reshape", aliases=("reshape",))
def reshape(data, *, shape=None, reverse=False):
    # supports MXNet magic numbers 0 (copy dim) and -1 (infer); -2/-3/-4 subset
    """Reshape with the reference's special codes: 0 copy dim, -1 infer, -2
    copy rest, -3 merge two, -4 split."""
    if shape is None:
        return data
    src = list(data.shape)
    out = []
    i = 0  # cursor into src dims
    shape = list(shape)
    if reverse:
        src = src[::-1]
        shape = shape[::-1]
    k = 0
    while k < len(shape):
        s = shape[k]
        if s == 0:
            out.append(src[i]); i += 1
        elif s == -1:
            out.append(-1); i += 1
        elif s == -2:
            out.extend(src[i:]); i = len(src)
        elif s == -3:
            out.append(src[i] * src[i + 1]); i += 2
        elif s == -4:
            a, b = shape[k + 1], shape[k + 2]
            k += 2
            a = src[i] // b if a == -1 else a
            b = src[i] // a if b == -1 else b
            out.extend([a, b]); i += 1
        else:
            out.append(s)
            if i < len(src):
                i += 1
        k += 1
    if reverse:
        out = out[::-1]
    return jnp.reshape(data, tuple(out))


@register("reshape_like")
def reshape_like(lhs, rhs):
    """Reshape data to the shape of a second input (optionally a slice of its
    dims)."""
    return jnp.reshape(lhs, rhs.shape)


@register("Flatten", aliases=("flatten",))
def flatten(data):
    """Collapse all dims after the first into one."""
    return jnp.reshape(data, (data.shape[0], -1))


@register("transpose")
def transpose(data, *, axes=None):
    """Permute axes (reversed order when `axes` is empty)."""
    return jnp.transpose(data, axes=axes if axes else None)


@register("SwapAxis", aliases=("swapaxes",))
def swapaxes(data, *, dim1=0, dim2=0):
    """Exchange two axes."""
    return jnp.swapaxes(data, dim1, dim2)


@register("expand_dims")
def expand_dims(data, *, axis=0):
    """Insert a size-1 axis at `axis`."""
    return jnp.expand_dims(data, axis)


@register("squeeze")
def squeeze(data, *, axis=None):
    """Remove size-1 axes (all of them, or just `axis`)."""
    return jnp.squeeze(data, axis=axis)


@register("broadcast_to")
def broadcast_to(data, *, shape=None):
    """Broadcast to `shape` (0 keeps the input's dim)."""
    tgt = tuple(d if s == 0 else s for s, d in zip(shape, data.shape))
    return jnp.broadcast_to(data, tgt)


@register("broadcast_like")
def broadcast_like(lhs, rhs):
    """Broadcast data to the shape of a second input."""
    return jnp.broadcast_to(lhs, rhs.shape)


@register("broadcast_axis", aliases=("broadcast_axes",))
def broadcast_axis(data, *, axis=(), size=()):
    """Broadcast the given size-1 axes to the given sizes."""
    axes = (axis,) if isinstance(axis, int) else tuple(axis)
    sizes = (size,) if isinstance(size, int) else tuple(size)
    tgt = list(data.shape)
    for a, s in zip(axes, sizes):
        tgt[a] = s
    return jnp.broadcast_to(data, tuple(tgt))


@register("zeros_like")
def zeros_like(data):
    """Zeros with the input's shape and dtype."""
    return jnp.zeros_like(data)


@register("ones_like")
def ones_like(data):
    """Ones with the input's shape and dtype."""
    return jnp.ones_like(data)


@register("shape_array", no_grad_inputs=("data",))
def shape_array(data):
    """The input's shape as a 1-D int64 array."""
    return jnp.array(data.shape, dtype=jnp.int64)


@register("size_array", no_grad_inputs=("data",))
def size_array(data):
    """The input's element count as a 1-element int64 array."""
    return jnp.array([data.size], dtype=jnp.int64)


@register("slice")
def slice_op(data, *, begin=(), end=(), step=()):
    """Slice with per-axis begin/end/step (the reference's `slice`)."""
    idx = []
    for i in range(data.ndim):
        b = begin[i] if i < len(begin) else None
        e = end[i] if i < len(end) else None
        s = step[i] if i < len(step) and step[i] is not None and step[i] != 0 else None
        idx.append(slice(b, e, s))
    return data[tuple(idx)]


@register("slice_axis")
def slice_axis(data, *, axis=0, begin=0, end=None):
    """Slice [begin, end) along one axis."""
    idx = [slice(None)] * data.ndim
    idx[axis] = slice(begin, end)
    return data[tuple(idx)]


@register("slice_like")
def slice_like(data, shape_like, *, axes=()):
    """Slice data down to the shape of a second input on the given axes."""
    axs = tuple(axes) if axes else tuple(range(min(data.ndim, shape_like.ndim)))
    idx = [slice(None)] * data.ndim
    for a in axs:
        idx[a] = slice(0, shape_like.shape[a])
    return data[tuple(idx)]


@register("Concat", aliases=("concat",))
def concat(*args, dim=1):
    """Concatenate inputs along `dim`."""
    return jnp.concatenate(args, axis=dim)


@register("stack")
def stack(*args, axis=0):
    """Stack inputs along a new `axis`."""
    return jnp.stack(args, axis=axis)


@register("add_n", aliases=("ElementWiseSum", "_sum"))
def add_n(*args):
    """Elementwise sum of all inputs."""
    out = args[0]
    for a in args[1:]:
        out = out + a
    return out


def _split_outputs(attrs):
    return int(attrs.get("num_outputs", 1))


@register("SliceChannel", aliases=("split",), num_outputs=_split_outputs)
def split(data, *, num_outputs=1, axis=1, squeeze_axis=False):
    """Split into `num_outputs` parts along `axis` (multi-output)."""
    parts = jnp.split(data, num_outputs, axis=axis)
    if squeeze_axis:
        parts = [jnp.squeeze(p, axis=axis) for p in parts]
    return tuple(parts) if num_outputs > 1 else parts[0]


@register("tile")
def tile(data, *, reps=()):
    """Repeat the whole array `reps` times per axis."""
    return jnp.tile(data, reps)


@register("repeat")
def repeat(data, *, repeats=1, axis=None):
    """Repeat each element `repeats` times along `axis`."""
    return jnp.repeat(data, repeats, axis=axis)


@register("reverse", aliases=("flip",))
def reverse(data, *, axis=()):
    """Reverse along the given axes."""
    axs = (axis,) if isinstance(axis, int) else tuple(axis)
    return jnp.flip(data, axis=axs)


@register("Pad", aliases=("pad",))
def pad(data, *, mode="constant", pad_width=(), constant_value=0.0):
    """Pad spatial dims of 4-D/5-D input with constant/edge/reflect padding."""
    pw = [(pad_width[2 * i], pad_width[2 * i + 1]) for i in range(len(pad_width) // 2)]
    if mode == "constant":
        return jnp.pad(data, pw, mode="constant", constant_values=constant_value)
    if mode == "edge":
        return jnp.pad(data, pw, mode="edge")
    if mode == "reflect":
        return jnp.pad(data, pw, mode="reflect")
    raise ValueError(f"unknown pad mode {mode}")


@register("diag")
def diag(data, *, k=0):
    """Extract the k-th diagonal (>=2-D input) or build a diagonal matrix
    (1-D)."""
    if data.ndim == 1:
        return jnp.diag(data, k=k)
    return jnp.diagonal(data, offset=k, axis1=-2, axis2=-1)


@register("depth_to_space")
def depth_to_space(data, *, block_size=2):
    """Rearrange channel blocks into spatial blocks (NCHW, block_size)."""
    n, c, h, w = data.shape
    b = block_size
    x = data.reshape(n, b, b, c // (b * b), h, w)
    x = x.transpose(0, 3, 4, 1, 5, 2)
    return x.reshape(n, c // (b * b), h * b, w * b)


@register("space_to_depth")
def space_to_depth(data, *, block_size=2):
    """Rearrange spatial blocks into channels (NCHW, block_size)."""
    n, c, h, w = data.shape
    b = block_size
    x = data.reshape(n, c, h // b, b, w // b, b)
    x = x.transpose(0, 3, 5, 1, 2, 4)
    return x.reshape(n, c * b * b, h // b, w // b)


# ---------------------------------------------------------------------------
# indexing (ref: tensor/indexing_op.h)
# ---------------------------------------------------------------------------


@register("take", no_grad_inputs=("indices",))
def take(a, indices, *, axis=0, mode="clip"):
    """Gather slices along `axis` by integer indices, with clip/wrap modes."""
    idx = indices.astype(jnp.int32)
    return jnp.take(a, idx, axis=axis, mode=mode if mode != "raise" else "clip")


@register("batch_take", no_grad_inputs=("indices",))
def batch_take(a, indices):
    """Per-row gather: out[i] = data[i, indices[i]]."""
    idx = indices.astype(jnp.int32)
    return a[jnp.arange(a.shape[0]), idx]


@register("pick", no_grad_inputs=("index",))
def pick(data, index, *, axis=-1, keepdims=False, mode="clip"):
    """Pick one element per row along `axis` by index."""
    idx = index.astype(jnp.int32)
    if mode == "wrap":  # ref: pick mode=wrap wraps indices modulo the dim
        idx = jnp.mod(idx, data.shape[axis])
    else:
        idx = jnp.clip(idx, 0, data.shape[axis] - 1)
    out = jnp.take_along_axis(data, jnp.expand_dims(idx, axis=axis), axis=axis)
    if not keepdims:
        out = jnp.squeeze(out, axis=axis)
    return out


@register("Embedding", no_grad_inputs=("data",))
def embedding(data, weight, *, input_dim=None, output_dim=None, dtype="float32", sparse_grad=False):
    """Look up integer indices in a (input_dim, output_dim) weight table."""
    idx = data.astype(jnp.int32)
    return jnp.take(weight, idx, axis=0, mode="clip")


@register("gather_nd", no_grad_inputs=("indices",))
def gather_nd(data, indices):
    """Gather elements addressed by the leading index dimension."""
    idx = indices.astype(jnp.int32)
    m = idx.shape[0]
    return data[tuple(idx[i] for i in range(m))]


@register("scatter_nd", no_grad_inputs=("indices",))
def scatter_nd(data, indices, *, shape=None):
    """Scatter data into zeros of `shape` at the given indices."""
    idx = indices.astype(jnp.int32)
    m = idx.shape[0]
    out = jnp.zeros(shape, dtype=data.dtype)
    return out.at[tuple(idx[i] for i in range(m))].add(data)


@register("one_hot", no_grad_inputs=("indices",))
def one_hot(indices, *, depth=None, on_value=1.0, off_value=0.0, dtype="float32"):
    """One-hot encode integer indices to `depth` classes with on/off values."""
    from ..base import dtype_np

    oh = jax.nn.one_hot(indices.astype(jnp.int32), depth)
    return (oh * (on_value - off_value) + off_value).astype(dtype_np(dtype))


@register("where", no_grad_inputs=("condition",))
def where(condition, x, y):
    """Select elementwise from x or y by condition."""
    return jnp.where(condition.astype(bool), x, y)


@register("boolean_mask", no_grad_inputs=("index",))
def boolean_mask(data, index, *, axis=0):
    # dynamic-shape op: evaluated eagerly (not jit-safe); reference is
    # contrib.boolean_mask
    """Keep rows where the boolean mask is set. Output shape is data-dependent
    (jnp.compress), so this host-syncs under jit -- the validator flags it
    as MXA030."""
    mask = index.astype(bool)
    return jnp.compress(mask, data, axis=axis)


# ---------------------------------------------------------------------------
# ordering (ref: tensor/ordering_op-inl.h)
# ---------------------------------------------------------------------------


@register("sort")
def sort(data, *, axis=-1, is_ascend=True):
    """Sort along `axis`, optionally descending."""
    out = jnp.sort(data, axis=axis)
    if not is_ascend:
        out = jnp.flip(out, axis=axis)
    return out


@register("argsort", no_grad_inputs=("data",))
def argsort(data, *, axis=-1, is_ascend=True, dtype="float32"):
    """Indices that would sort along `axis`, cast to the requested dtype."""
    from ..base import dtype_np

    out = jnp.argsort(data, axis=axis)
    if not is_ascend:
        out = jnp.flip(out, axis=axis)
    return out.astype(dtype_np(dtype))


def _topk_outputs(attrs):
    rt = attrs.get("ret_typ", "indices")
    return 2 if rt == "both" else 1


@register("topk", no_grad_inputs=("data",), num_outputs=_topk_outputs)
def topk(data, *, axis=-1, k=1, ret_typ="indices", is_ascend=False, dtype="float32"):
    """Top-k values/indices along `axis` with the reference's ret_typ modes."""
    from ..base import dtype_np

    ax = axis % data.ndim
    src = -data if is_ascend else data
    moved = jnp.moveaxis(src, ax, -1)
    vals, idxs = lax.top_k(moved, k)
    if is_ascend:
        vals = -vals
    vals = jnp.moveaxis(vals, -1, ax)
    idxs = jnp.moveaxis(idxs, -1, ax).astype(dtype_np(dtype))
    if ret_typ == "value":
        return vals
    if ret_typ == "both":
        return vals, idxs
    return idxs


# ---------------------------------------------------------------------------
# sequence ops (ref: src/operator/sequence_*.cc)
# ---------------------------------------------------------------------------


@register("SequenceMask", optional=("sequence_length",), no_grad_inputs=("sequence_length",))
def sequence_mask(data, sequence_length=None, *, use_sequence_length=False, value=0.0, axis=0):
    """Mask time steps beyond each sequence's length with `value` (TNC layout)."""
    if not use_sequence_length or sequence_length is None:
        return data
    maxlen = data.shape[axis]
    steps = jnp.arange(maxlen)
    mask = steps[:, None] < sequence_length[None, :].astype(jnp.int32)  # (T, B)
    if axis == 1:
        mask = mask.T
    shape = [1] * data.ndim
    shape[axis] = data.shape[axis]
    shape[1 - axis] = data.shape[1 - axis]
    mask = mask.reshape(shape)
    return jnp.where(mask, data, jnp.asarray(value, dtype=data.dtype))


@register("SequenceLast", optional=("sequence_length",), no_grad_inputs=("sequence_length",))
def sequence_last(data, sequence_length=None, *, use_sequence_length=False, axis=0):
    """Last valid time step of each sequence (TNC layout)."""
    if not use_sequence_length or sequence_length is None:
        idx = [slice(None)] * data.ndim
        idx[axis] = -1
        return data[tuple(idx)]
    last = (sequence_length.astype(jnp.int32) - 1)
    moved = jnp.moveaxis(data, axis, 0)  # (T, B, ...)
    return moved[last, jnp.arange(moved.shape[1])]


@register("SequenceReverse", optional=("sequence_length",), no_grad_inputs=("sequence_length",))
def sequence_reverse(data, sequence_length=None, *, use_sequence_length=False, axis=0):
    """Reverse each sequence up to its length (TNC layout)."""
    if not use_sequence_length or sequence_length is None:
        return jnp.flip(data, axis=axis)
    moved = jnp.moveaxis(data, axis, 0)  # (T, B, ...)
    T = moved.shape[0]
    lens = sequence_length.astype(jnp.int32)
    t = jnp.arange(T)[:, None]
    src = jnp.where(t < lens[None, :], lens[None, :] - 1 - t, t)  # (T, B)
    out = jnp.take_along_axis(
        moved, src.reshape(src.shape + (1,) * (moved.ndim - 2)).astype(jnp.int32), axis=0
    )
    return jnp.moveaxis(out, 0, axis)


# ---------------------------------------------------------------------------
# init-like ops used inside graphs
# ---------------------------------------------------------------------------


@register("_arange_like", no_grad_inputs=("data",))
def arange_like(data, *, start=0.0, step=1.0, axis=None):
    """arange shaped like the input along `axis` (or its flattened size)."""
    n = data.size if axis is None else data.shape[axis]
    return start + step * jnp.arange(n, dtype=jnp.float32)


@register("histogram", no_grad_inputs=("data",))
def histogram(data, *, bin_cnt=10, range=None):
    """Histogram counts (and bin edges) of the input."""
    lo, hi = range if range is not None else (float(data.min()), float(data.max()))
    hist, edges = jnp.histogram(data, bins=bin_cnt, range=(lo, hi))
    return hist.astype(jnp.float32)


# --- round-2 op-gap batch (reference ops previously uncovered) ------------


@register("hard_sigmoid")
def hard_sigmoid(data, *, alpha=0.2, beta=0.5):
    """(ref: src/operator/tensor/elemwise_unary_op_basic.cc hard_sigmoid)."""
    return jnp.clip(alpha * data + beta, 0.0, 1.0)


@register("khatri_rao")
def khatri_rao(*args):
    """Column-wise Kronecker product: (n_i, k) inputs -> (prod n_i, k)
    (ref: src/operator/contrib/krprod.cc khatri_rao)."""
    out = args[0]
    for m in args[1:]:
        out = (out[:, None, :] * m[None, :, :]).reshape(-1, out.shape[-1])
    return out


@register("_ravel_multi_index", aliases=("ravel_multi_index",),
          no_grad_inputs=("data",))
def ravel_multi_index(data, *, shape):
    """(ndim, N) coordinates -> (N,) flat indices for `shape`
    (ref: src/operator/tensor/ravel.cc). Index math is int32 (jax default):
    index spaces beyond 2^31-1 elements are rejected rather than silently
    wrapped."""
    strides = []
    acc = 1
    for dim in tuple(shape)[::-1]:
        strides.append(acc)
        acc *= int(dim)
    if acc >= 2 ** 31:
        raise ValueError(
            f"shape {tuple(shape)} has {acc} elements; int32 flat indexing "
            "overflows beyond 2**31-1")
    strides = jnp.asarray(strides[::-1], jnp.int32)
    return jnp.sum(data.astype(jnp.int32) * strides[:, None], axis=0)


@register("_unravel_index", aliases=("unravel_index",),
          no_grad_inputs=("data",))
def unravel_index(data, *, shape):
    """(N,) flat indices -> (ndim, N) coordinates (ref: ravel.cc)."""
    import math

    if math.prod(int(d) for d in shape) >= 2 ** 31:
        raise ValueError(
            f"shape {tuple(shape)} exceeds int32 flat-index range")
    coords = jnp.unravel_index(data.astype(jnp.int32), tuple(shape))
    return jnp.stack(coords, axis=0).astype(jnp.int32)


@register("_square_sum", aliases=("square_sum",))
def square_sum(data, *, axis=None, keepdims=False):
    """sum(x^2) in one pass (ref: src/operator/tensor/square_sum-inl.h —
    the sparse-aware fused square+sum; sparse inputs densify here and the
    row_sparse fast path lives with the sparse kernels)."""
    ax = tuple(axis) if isinstance(axis, (list, tuple)) else axis
    return jnp.sum(jnp.square(data), axis=ax, keepdims=keepdims)


def _split_v2_outputs(attrs):
    ios = attrs.get("indices_or_sections", 1)
    if isinstance(ios, (list, tuple)):
        return len(ios) + 1
    return int(ios)


@register("_split_v2", aliases=("split_v2",), num_outputs=_split_v2_outputs)
def split_v2(data, *, indices_or_sections=1, axis=0, squeeze_axis=False):
    """Split by section count OR explicit indices
    (ref: src/operator/tensor/matrix_op.cc _split_v2)."""
    ios = indices_or_sections
    parts = jnp.split(data, list(ios) if isinstance(ios, (list, tuple))
                      else int(ios), axis=axis)
    if squeeze_axis:
        parts = [jnp.squeeze(p, axis=axis) for p in parts]
    return tuple(parts) if len(parts) > 1 else parts[0]


@register("_linalg_gelqf", aliases=("linalg_gelqf",), num_outputs=2)
def linalg_gelqf(A):
    """LQ factorization A = L @ Q with Q orthonormal rows
    (ref: src/operator/tensor/la_op.cc _linalg_gelqf). Computed as the
    transpose of the QR factorization of A^T — one MXU-friendly qr call."""
    q, r = jnp.linalg.qr(jnp.swapaxes(A, -1, -2), mode="reduced")
    return jnp.swapaxes(r, -1, -2), jnp.swapaxes(q, -1, -2)


@register("_linalg_syevd", aliases=("linalg_syevd",), num_outputs=2)
def linalg_syevd(A):
    """Symmetric eigendecomposition: returns (U, L) with A = U^T diag(L) U
    (ref: la_op.cc _linalg_syevd — note the reference's U holds eigenvectors
    as ROWS)."""
    w, v = jnp.linalg.eigh(A)
    return jnp.swapaxes(v, -1, -2), w


# special-function tail (ref: src/operator/mshadow_op.h digamma family)
_unary("digamma", jax.scipy.special.digamma)


@register("polygamma")
def polygamma(data, *, n=0):
    """n-th derivative of digamma (ref role: mshadow_op.h special-function
    tail; n=0 reduces to digamma)."""
    return jax.scipy.special.polygamma(int(n), data)


# --- round-4 op-gap batch: name-parity tail vs the reference registry -----
# (ref: grep NNVM_REGISTER_OP over src/operator/ diffed against OP_REGISTRY;
# backward/vendor-internal names are intentionally absent — vjp and XLA
# subsume them)

_scalar_op("_hypot_scalar", lambda x, s: jnp.hypot(x, s))
_scalar_op("_logical_and_scalar",
           lambda x, s: ((x != 0) & (s != 0)).astype(x.dtype))
_scalar_op("_logical_or_scalar",
           lambda x, s: ((x != 0) | (s != 0)).astype(x.dtype))
_scalar_op("_logical_xor_scalar",
           lambda x, s: ((x != 0) ^ (s != 0)).astype(x.dtype))

# the reference's in-place/scatter spellings of existing math (storage
# fallback behavior is an engine concern the functional protocol subsumes)
alias("_plus_scalar", "_scatter_plus_scalar")
alias("_minus_scalar", "_scatter_minus_scalar")
alias("broadcast_div", "_scatter_elemwise_div")
alias("broadcast_add", "_grad_add")
alias("histogram", "_histogram")
alias("boolean_mask", "_contrib_boolean_mask")
# deprecated 1.x public spellings (ref:
# elemwise_binary_broadcast_op_basic.cc:34,82 `broadcast_plus/minus`;
# broadcast_reduce_op_index.cc:112 `choose_element_0index` -> pick;
# matrix_op.cc:451 `crop` -> slice)
alias("broadcast_add", "broadcast_plus")
alias("broadcast_sub", "broadcast_minus")
alias("pick", "choose_element_0index")
alias("slice", "crop")


@register("_arange", aliases=("arange",))
def _arange(*, start=0.0, stop=None, step=1.0, repeat=1, infer_range=False,
            dtype="float32"):
    """(ref: src/operator/tensor/init_op.cc _arange)"""
    from ..base import dtype_np

    vals = jnp.arange(start, stop, step, dtype=dtype_np(dtype))
    if repeat > 1:
        vals = jnp.repeat(vals, repeat)
    return vals


@register("_eye", aliases=("eye",))
def _eye(*, N, M=0, k=0, dtype="float32"):
    """(ref: init_op.cc _eye)"""
    from ..base import dtype_np

    return jnp.eye(int(N), int(M) or None, int(k), dtype=dtype_np(dtype))


@register("_full", aliases=("full",))
def _full(*, shape, value, dtype="float32"):
    """(ref: init_op.cc _full)"""
    from ..base import dtype_np

    return jnp.full(tuple(shape), value, dtype=dtype_np(dtype))


@register("_zeros", aliases=("_zeros_without_dtype",))
def _zeros(*, shape, dtype="float32"):
    """(ref: init_op.cc _zeros / _zeros_without_dtype)"""
    from ..base import dtype_np

    return jnp.zeros(tuple(shape), dtype=dtype_np(dtype))


@register("_ones")
def _ones(*, shape, dtype="float32"):
    """(ref: init_op.cc _ones)"""
    from ..base import dtype_np

    return jnp.ones(tuple(shape), dtype=dtype_np(dtype))


def _slice_index(begin, end, step):
    """begin/end/step attr triples -> a tuple of Python slices (step may be
    shorter than begin/end or empty; missing entries mean stride 1)."""
    out = []
    for i, (b, e) in enumerate(zip(begin, end)):
        s = step[i] if i < len(step) else None
        out.append(slice(None if b is None else int(b),
                         None if e is None else int(e),
                         None if not s else int(s)))
    return tuple(out)


@register("_slice_assign")
def _slice_assign(lhs, rhs, *, begin, end, step=()):
    """Functional write: lhs with lhs[begin:end:step] replaced by rhs
    (ref: src/operator/tensor/matrix_op.cc _slice_assign — the autograd
    spelling of sliced writes)."""
    return lhs.at[_slice_index(begin, end, step)].set(rhs)


@register("_slice_assign_scalar")
def _slice_assign_scalar(data, *, scalar, begin, end, step=()):
    """(ref: matrix_op.cc _slice_assign_scalar)"""
    return data.at[_slice_index(begin, end, step)].set(scalar)


@register("_scatter_set_nd", no_grad_inputs=("indices",))
def _scatter_set_nd(lhs, rhs, indices, *, shape=None):
    """lhs with rhs written at gather_nd-style indices
    (ref: indexing_op.cc _scatter_set_nd)."""
    idx = tuple(indices.astype(jnp.int32))
    return lhs.at[idx].set(rhs)


@register("_identity_with_attr_like_rhs", no_grad_inputs=("rhs",))
def _identity_with_attr_like_rhs(lhs, rhs):
    """Identity of lhs; rhs only contributes storage/shape attrs in the
    reference's graph passes (ref: elemwise_unary_op_basic.cc)."""
    return lhs


@register("_contrib_bipartite_matching", num_outputs=2,
          no_grad_inputs=("data",))
def _contrib_bipartite_matching(data, *, threshold=None, is_ascend=False,
                                topk=-1):
    """Greedy bipartite matching over a (rows, cols) score matrix, or a
    batch of them (leading dims vmapped)
    (ref: src/operator/contrib/bounding_box.cc bipartite_matching):
    repeatedly take the globally best remaining pair that passes
    `threshold` (score > thr descending, score < thr ascending); returns
    (row->col assignment, col->row assignment), -1 = unmatched.
    Sequential by nature — a lax.fori_loop, so it stays jittable (sizes
    are anchor-count scale)."""
    if data.ndim > 2:
        import functools as _ft

        fn = _ft.partial(_contrib_bipartite_matching.__opdef__.fn,
                         threshold=threshold, is_ascend=is_ascend, topk=topk)
        for _ in range(data.ndim - 2):
            fn = jax.vmap(fn)
        return fn(data)
    n, m = data.shape
    steps = min(n, m) if topk < 0 else min(topk, min(n, m))
    # work on sign-flipped scores so "best" is always the max; the
    # threshold flips with the sign (ascending: match while value < thr)
    if threshold is None:
        thr = -jnp.inf
    else:
        thr = -float(threshold) if is_ascend else float(threshold)
    sign = -1.0 if is_ascend else 1.0

    def body(_, state):
        scores, row_match, col_match = state
        flat = jnp.argmax(scores)
        r, c = flat // m, flat % m
        take = scores[r, c] > thr
        row_match = jnp.where(take, row_match.at[r].set(c), row_match)
        col_match = jnp.where(take, col_match.at[c].set(r), col_match)
        # knock out the chosen row and column
        scores = jnp.where(take,
                           scores.at[r, :].set(-jnp.inf)
                           .at[:, c].set(-jnp.inf),
                           scores.at[r, c].set(-jnp.inf))
        return scores, row_match, col_match

    scores0 = sign * data.astype(jnp.float32)
    init = (scores0,
            jnp.full((n,), -1, jnp.float32), jnp.full((m,), -1, jnp.float32))
    _, row_match, col_match = lax.fori_loop(0, steps, body, init)
    return row_match, col_match
