"""Fused-epilogue rewrite: conv→BN→ReLU(→residual-add) chains to one
Pallas pass (MXTPU_FUSED_EPILOGUE).

The gluon frontend executes ops eagerly-within-trace (no whole-graph HLO
pass to hook), so the chain is matched at op-dispatch time instead:
BatchNorm dispatches record lightweight provenance on their output
NDArray, residual adds propagate it, and a ReLU Activation dispatch whose
input carries BN provenance re-emits the chain as ONE
`pallas_kernels.bn_act_epilogue` call — the BN affine folded to
per-channel scale/shift applied to the conv accumulator, the activation,
and the residual add in a single HBM read+write.

The ALREADY-dispatched unfused BN/add outputs are left in place: inside a
jit trace they become dead code the moment the relu consumes the fused
value instead, so XLA's DCE removes them and the rewrite costs nothing
extra in the compiled program (the batch-moment reductions the scale/shift
need unify with the BN's own via CSE). Provenance is only recorded while
tracing (the output wraps a jax Tracer) AND the knob is on, so with
`MXTPU_FUSED_EPILOGUE=0` — the default — every dispatch takes the
identical code path and the compiled program is bit-for-bit today's.

Only channels-last (axis == ndim-1) BatchNorm in f32-or-narrower dtypes
is rewritten: the kernel tiles (rows, C) with C on lanes, and its math is
f32 (a float64 net keeps f64 stats and must stay on the XLA path).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .. import config

__all__ = ["enabled", "note_batch_norm", "note_add", "maybe_rewrite_relu",
           "rewrites_applied"]

# trace-time count of chains actually re-emitted through the kernel;
# tests and the perf-structure CI tier assert on it (reset per check)
rewrites_applied = 0


def enabled():
    return config.get("MXTPU_FUSED_EPILOGUE")


def _tracing(nd):
    return isinstance(nd._data, jax.core.Tracer)


def note_batch_norm(out_nd, slots, call_attrs):
    """Record BN provenance on the primary output (called from the eager
    dispatcher after a BatchNorm op ran, knob already checked)."""
    if not _tracing(out_nd):
        return
    data, gamma, beta, mmean, mvar = (s._data if s is not None else None
                                      for s in slots[:5])
    if data is None or gamma is None or beta is None:
        return
    out_nd._epi_prov = ("bn", (data, gamma, beta, mmean, mvar),
                        dict(call_attrs))


def note_add(out_nd, a_nd, b_nd):
    """Propagate provenance through a residual add: if either operand is a
    BN output of the same shape, the add is a candidate residual join."""
    if not _tracing(out_nd) or not enabled():
        return
    for bn, other in ((a_nd, b_nd), (b_nd, a_nd)):
        prov = getattr(bn, "_epi_prov", None)
        if (prov is not None and prov[0] == "bn"
                and bn._data.shape == other._data.shape):
            out_nd._epi_prov = ("add", prov, other._data)
            return


def maybe_rewrite_relu(data_nd):
    """Attempt the fused re-emit for relu(data). Returns the fused jnp
    value, or None when the chain does not match."""
    prov = getattr(data_nd, "_epi_prov", None)
    if prov is None:
        return None
    if prov[0] == "bn":
        return _emit(prov[1], prov[2], None)
    if prov[0] == "add":
        return _emit(prov[1][1], prov[1][2], prov[2])
    return None


def _emit(bn_inputs, attrs, residual):
    data, gamma, beta, mmean, mvar = bn_inputs
    axis = attrs.get("axis", 1) % data.ndim
    if axis != data.ndim - 1:
        return None  # kernel is channels-last only
    stat_dt = jnp.promote_types(data.dtype, jnp.float32)
    if stat_dt != jnp.float32:
        return None  # f64 nets keep f64 stats on the XLA path
    eps = attrs.get("eps", 1e-3)
    g = jnp.ones_like(gamma) if attrs.get("fix_gamma", True) else gamma
    if attrs.get("_training", False) and not attrs.get("use_global_stats",
                                                       False):
        # same batch moments the BN computed — CSE unifies the reductions
        reduce_axes = tuple(range(data.ndim - 1))
        xf = data.astype(stat_dt)
        mean = jnp.mean(xf, axis=reduce_axes)
        var = jnp.var(xf, axis=reduce_axes)
    else:
        mean = mmean.astype(stat_dt)
        var = mvar.astype(stat_dt)
    scale = g.astype(stat_dt) * lax.rsqrt(var + eps)
    shift = beta.astype(stat_dt) - mean * scale
    from . import pallas_kernels

    out = pallas_kernels.bn_act_epilogue(data, scale, shift,
                                         residual=residual)
    global rewrites_applied
    rewrites_applied += 1
    return out
