"""Pallas TPU kernels for hot ops.

Where the reference reaches for hand-written CUDA (ref: SURVEY §2 N6/N8),
the TPU build authors Pallas kernels. Flash attention here is TRAINABLE:
the forward is the blocked online-softmax kernel (never materializing the
(T, T) score matrix in HBM), and the backward is the standard
FlashAttention-2 recomputation pair — a dQ kernel gridded over query blocks
and a dK/dV kernel gridded over key blocks — wired up with jax.custom_vjp.
Falls back to `interpret=True` off-TPU so the same kernels run in CPU tests.
"""
from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

__all__ = ["flash_attention", "softmax_xent", "flash_decode",
           "dense_decode_attention", "paged_decode_attention",
           "paged_decode_attention_wide", "bn_act_epilogue",
           "DECODE_BLOCK", "DENSE_FALLBACKS_TOTAL"]

_NEG_INF = -1e30

# Per-row statistics (lse, delta) ride with a trailing lane dimension:
# Mosaic requires the last two dims of every block to be (8, 128)-tileable
# or equal to the array dims, so a rank-1 (block_q,) stats block — whose
# sublane dim is a squeezed batch axis — does not lower. The official TPU
# flash kernels (jax.experimental.pallas.ops.tpu.flash_attention
# MIN_BLOCK_SIZE) replicate the scalar across a full 128-wide lane dim;
# 8 lanes satisfies the same rule via the equal-to-array-dim clause at
# 1/16th the HBM footprint.
_STAT_LANES = 8


def _causal_mask(s, q_start, k_start):
    q_pos = q_start + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
    k_pos = k_start + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    return jnp.where(q_pos >= k_pos, s, _NEG_INF)


def _blocks_until(q_end, block):
    """Number of `block`-sized chunks covering positions [0, q_end)."""
    return (q_end + block - 1) // block


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, block_k, seq_len,
                causal, scale):
    # one grid step handles one (batch*head, q_block); loops over k blocks
    q = q_ref[...]  # (block_q, d)
    block_q, d = q.shape
    q_idx = pl.program_id(1)

    def body(start, carry):
        o, m, l = carry
        k = k_ref[pl.ds(start * block_k, block_k), :]
        v = v_ref[pl.ds(start * block_k, block_k), :]
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale
        if causal:
            s = _causal_mask(s, q_idx * block_q, start * block_k)
        m_new = jnp.maximum(m, jnp.max(s, axis=1))
        p = jnp.exp(s - m_new[:, None])
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=1)
        o_new = o * alpha[:, None] + jnp.dot(
            p.astype(v.dtype), v, preferred_element_type=jnp.float32
        )
        return o_new, m_new, l_new

    o0 = jnp.zeros((block_q, d), jnp.float32)
    m0 = jnp.full((block_q,), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q,), jnp.float32)
    num_k = seq_len // block_k
    if causal:  # skip fully-masked key blocks above the diagonal
        num_k = _blocks_until((q_idx + 1) * block_q, block_k)
    o, m, l = jax.lax.fori_loop(0, num_k, body, (o0, m0, l0))
    l = jnp.maximum(l, 1e-30)
    o_ref[...] = (o / l[:, None]).astype(o_ref.dtype)
    lse_ref[...] = jnp.broadcast_to((m + jnp.log(l))[:, None],
                                    (block_q, _STAT_LANES))


def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, *,
               block_k, seq_len, causal, scale):
    """dQ for one query block: dq = sum_k (P*(dP - D)) * scale @ K."""
    q = q_ref[...]
    do = do_ref[...]
    lse = lse_ref[...][:, :1]    # (block_q, 1) from the lane-replicated tile
    delta = delta_ref[...][:, :1]  # rowsum(dO * O)
    block_q, d = q.shape
    q_idx = pl.program_id(1)

    def body(start, dq):
        k = k_ref[pl.ds(start * block_k, block_k), :]
        v = v_ref[pl.ds(start * block_k, block_k), :]
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale
        if causal:
            s = _causal_mask(s, q_idx * block_q, start * block_k)
        p = jnp.exp(s - lse)
        dp = jnp.dot(do, v.T, preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        return dq + jnp.dot(ds.astype(k.dtype), k,
                            preferred_element_type=jnp.float32)

    dq0 = jnp.zeros((block_q, d), jnp.float32)
    num_k = seq_len // block_k
    if causal:
        num_k = _blocks_until((q_idx + 1) * block_q, block_k)
    dq = jax.lax.fori_loop(0, num_k, body, dq0)
    dq_ref[...] = dq.astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref,
                dv_ref, *, block_q, seq_len, causal, scale):
    """dK, dV for one key block: loops over query blocks recomputing P."""
    k = k_ref[...]
    v = v_ref[...]
    block_k, d = k.shape
    k_idx = pl.program_id(1)

    def body(start, carry):
        dk, dv = carry
        q = q_ref[pl.ds(start * block_q, block_q), :]
        do = do_ref[pl.ds(start * block_q, block_q), :]
        lse = lse_ref[pl.ds(start * block_q, block_q), :1]    # (bq, 1)
        delta = delta_ref[pl.ds(start * block_q, block_q), :1]
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale
        if causal:
            s = _causal_mask(s, start * block_q, k_idx * block_k)
        p = jnp.exp(s - lse)                                # (bq, bk)
        dv_new = dv + jnp.dot(p.T.astype(do.dtype), do,
                              preferred_element_type=jnp.float32)
        dp = jnp.dot(do, v.T, preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale                       # (bq, bk)
        dk_new = dk + jnp.dot(ds.T.astype(q.dtype), q,
                              preferred_element_type=jnp.float32)
        return dk_new, dv_new

    zeros = jnp.zeros((block_k, d), jnp.float32)
    num_q = seq_len // block_q
    # skip query blocks strictly above the diagonal (they see no key here)
    start_q = (k_idx * block_k) // block_q if causal else 0
    dk, dv = jax.lax.fori_loop(start_q, num_q, body, (zeros, zeros))
    dk_ref[...] = dk.astype(dk_ref.dtype)
    dv_ref[...] = dv.astype(dv_ref.dtype)


def _flash_fwd(q, k, v, causal, block_q, block_k, interpret):
    B, H, T, D = q.shape
    scale = 1.0 / np.sqrt(D)
    qr = q.reshape(B * H, T, D)
    kr = k.reshape(B * H, T, D)
    vr = v.reshape(B * H, T, D)
    kernel = functools.partial(
        _fwd_kernel, block_k=block_k, seq_len=T, causal=causal, scale=scale)
    o, lse = pl.pallas_call(
        kernel,
        grid=(B * H, T // block_q),
        in_specs=[
            pl.BlockSpec((None, block_q, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, T, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((None, T, D), lambda b, i: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, block_q, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, block_q, _STAT_LANES),
                         lambda b, i: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B * H, T, D), q.dtype),
            jax.ShapeDtypeStruct((B * H, T, _STAT_LANES), jnp.float32),
        ],
        interpret=interpret,
    )(qr, kr, vr)
    return o.reshape(B, H, T, D), lse[..., 0].reshape(B, H, T)


def _flash_bwd(q, k, v, o, lse, do, causal, block_q, block_k, interpret):
    B, H, T, D = q.shape
    scale = 1.0 / np.sqrt(D)
    qr = q.reshape(B * H, T, D)
    kr = k.reshape(B * H, T, D)
    vr = v.reshape(B * H, T, D)
    dor = do.reshape(B * H, T, D)
    # lane-replicate the per-row stats (see _STAT_LANES)
    lser = jnp.broadcast_to(lse.reshape(B * H, T)[..., None],
                            (B * H, T, _STAT_LANES))
    # D_i = rowsum(dO * O): cheap dense elementwise, no kernel needed
    delta = jnp.sum(dor.astype(jnp.float32)
                    * o.reshape(B * H, T, D).astype(jnp.float32), axis=-1)
    delta = jnp.broadcast_to(delta[..., None], (B * H, T, _STAT_LANES))

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, block_k=block_k, seq_len=T,
                          causal=causal, scale=scale),
        grid=(B * H, T // block_q),
        in_specs=[
            pl.BlockSpec((None, block_q, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, T, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((None, T, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((None, block_q, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, block_q, _STAT_LANES),
                         lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, block_q, _STAT_LANES),
                         lambda b, i: (b, i, 0)),
        ],
        out_specs=pl.BlockSpec((None, block_q, D), lambda b, i: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((B * H, T, D), q.dtype),
        interpret=interpret,
    )(qr, kr, vr, dor, lser, delta)

    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, block_q=block_q, seq_len=T,
                          causal=causal, scale=scale),
        grid=(B * H, T // block_k),
        in_specs=[
            pl.BlockSpec((None, T, D), lambda b, j: (b, 0, 0)),
            pl.BlockSpec((None, block_k, D), lambda b, j: (b, j, 0)),
            pl.BlockSpec((None, block_k, D), lambda b, j: (b, j, 0)),
            pl.BlockSpec((None, T, D), lambda b, j: (b, 0, 0)),
            pl.BlockSpec((None, T, _STAT_LANES), lambda b, j: (b, 0, 0)),
            pl.BlockSpec((None, T, _STAT_LANES), lambda b, j: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, block_k, D), lambda b, j: (b, j, 0)),
            pl.BlockSpec((None, block_k, D), lambda b, j: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B * H, T, D), k.dtype),
            jax.ShapeDtypeStruct((B * H, T, D), v.dtype),
        ],
        interpret=interpret,
    )(qr, kr, vr, dor, lser, delta)

    return (dq.reshape(B, H, T, D), dk.reshape(B, H, T, D),
            dv.reshape(B, H, T, D))


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash(q, k, v, causal, block_q, block_k, interpret):
    o, _ = _flash_fwd(q, k, v, causal, block_q, block_k, interpret)
    return o


def _flash_vjp_fwd(q, k, v, causal, block_q, block_k, interpret):
    o, lse = _flash_fwd(q, k, v, causal, block_q, block_k, interpret)
    return o, (q, k, v, o, lse)


def _flash_vjp_bwd(causal, block_q, block_k, interpret, res, do):
    q, k, v, o, lse = res
    return _flash_bwd(q, k, v, o, lse, do, causal, block_q, block_k,
                      interpret)


_flash.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


def flash_attention(q, k, v, causal=False, block_q=128, block_k=128,
                    interpret=None):
    """Fused attention: q,k,v (B, H, T, D) -> (B, H, T, D).

    Blocked flash-attention Pallas kernels, forward AND backward
    (FlashAttention-2 recomputation scheme): O(T) HBM, scores live in VMEM,
    trainable under jax.grad.
    """
    if interpret is None:
        interpret = jax.default_backend() == "cpu"
    B, H, T, D = q.shape
    block_q = min(block_q, T)
    block_k = min(block_k, T)
    assert T % block_q == 0 and T % block_k == 0, "seq len must divide blocks"
    return _flash(q, k, v, causal, block_q, block_k, interpret)


# ---------------------------------------------------------------------------
# Fused softmax cross-entropy (the transformer loss hot path)
# ---------------------------------------------------------------------------
#
# For large vocabularies the naive loss materializes softmax(logits) in HBM
# (B*V floats) twice — once forward, once backward. These kernels keep each
# (block_b, V) tile in VMEM: the forward computes max/logsumexp/label-logit
# in one pass and emits only per-row scalars; the backward regenerates
# softmax from the saved logsumexp and fuses the one-hot subtraction
# (ref role: softmax_output-inl.h fused SoftmaxOutput grad kernel).


def _xent_fwd_kernel(logits_ref, labels_ref, loss_ref, lse_ref):
    # per-row tensors ride as (block_b, 1): Mosaic rejects rank-1 blocks
    # unless they span the array or tile by 128 (the trailing unit lane
    # dim passes via the equal-to-array-dim clause)
    logits = logits_ref[...].astype(jnp.float32)      # (block_b, V)
    labels = labels_ref[...][:, 0]                    # (block_b,)
    m = jnp.max(logits, axis=-1)
    lse = m + jnp.log(jnp.sum(jnp.exp(logits - m[:, None]), axis=-1))
    onehot = (jax.lax.broadcasted_iota(jnp.int32, logits.shape, 1)
              == labels[:, None])
    picked = jnp.sum(jnp.where(onehot, logits, 0.0), axis=-1)
    loss_ref[...] = (lse - picked)[:, None]
    lse_ref[...] = lse[:, None]


def _xent_bwd_kernel(logits_ref, labels_ref, lse_ref, dloss_ref, dlogits_ref):
    logits = logits_ref[...].astype(jnp.float32)
    labels = labels_ref[...][:, 0]
    lse = lse_ref[...][:, 0]
    dloss = dloss_ref[...][:, 0]
    p = jnp.exp(logits - lse[:, None])                # softmax, recomputed
    onehot = (jax.lax.broadcasted_iota(jnp.int32, logits.shape, 1)
              == labels[:, None])
    dlogits_ref[...] = ((p - onehot.astype(jnp.float32))
                        * dloss[:, None]).astype(dlogits_ref.dtype)


def _sds(shape, dtype, vma):
    """ShapeDtypeStruct carrying varying-mesh-axes metadata when the kernel
    runs inside a shard_map body (jax requires it with check_vma)."""
    if vma:
        return jax.ShapeDtypeStruct(shape, dtype, vma=frozenset(vma))
    return jax.ShapeDtypeStruct(shape, dtype)


def _xent_fwd(logits, labels, block_b, interpret, vma):
    b, v = logits.shape
    grid = (pl.cdiv(b, block_b),)
    loss, lse = pl.pallas_call(
        _xent_fwd_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_b, v), lambda i: (i, 0)),
            pl.BlockSpec((block_b, 1), lambda i: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((block_b, 1), lambda i: (i, 0)),
            pl.BlockSpec((block_b, 1), lambda i: (i, 0)),
        ],
        out_shape=[
            _sds((b, 1), jnp.float32, vma),
            _sds((b, 1), jnp.float32, vma),
        ],
        interpret=interpret,
    )(logits, labels[:, None])
    return loss[:, 0], lse[:, 0]


def _xent_bwd_call(logits, labels, lse, dloss, block_b, interpret, vma):
    b, v = logits.shape
    grid = (pl.cdiv(b, block_b),)
    return pl.pallas_call(
        _xent_bwd_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_b, v), lambda i: (i, 0)),
            pl.BlockSpec((block_b, 1), lambda i: (i, 0)),
            pl.BlockSpec((block_b, 1), lambda i: (i, 0)),
            pl.BlockSpec((block_b, 1), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((block_b, v), lambda i: (i, 0)),
        out_shape=_sds((b, v), logits.dtype, vma),
        interpret=interpret,
    )(logits, labels[:, None], lse[:, None], dloss[:, None])


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4))
def _xent(logits, labels, block_b, interpret, vma):
    loss, _ = _xent_fwd(logits, labels, block_b, interpret, vma)
    return loss


def _xent_vjp_fwd(logits, labels, block_b, interpret, vma):
    loss, lse = _xent_fwd(logits, labels, block_b, interpret, vma)
    return loss, (logits, labels, lse)


def _xent_vjp_bwd(block_b, interpret, vma, res, dloss):
    logits, labels, lse = res
    dlogits = _xent_bwd_call(logits, labels, lse, dloss, block_b, interpret,
                             vma)
    return dlogits, None


_xent.defvjp(_xent_vjp_fwd, _xent_vjp_bwd)


def softmax_xent(logits, labels, block_b=8, interpret=None, vma=None):
    """Fused per-row softmax cross-entropy: logits (..., V) x int labels
    (...,) -> loss (...,). Differentiable (custom VJP regenerates softmax
    from the saved logsumexp — no (B, V) softmax tensor ever hits HBM).
    Inside a shard_map body pass `vma` = the mesh axes the data varies
    over (jax requires the metadata on pallas outputs there)."""
    if interpret is None:
        interpret = jax.default_backend() == "cpu"
    shape = logits.shape[:-1]
    v = logits.shape[-1]
    flat = logits.reshape(-1, v)
    lab = labels.reshape(-1).astype(jnp.int32)
    block_b = min(block_b, flat.shape[0])
    if vma is None:
        # inside a shard_map body the outputs must carry the same
        # varying-mesh-axes metadata as the traced inputs
        vma = tuple(getattr(jax.typeof(flat), "vma", ()) or ())
    if interpret and vma:
        # interpret-mode Pallas inside shard_map trips jax's vma accounting
        # in the emulation machinery itself (a CPU-test-only configuration);
        # use the numerically-identical dense form there. Compiled kernels
        # (real TPU) take the pallas_call path with vma-tagged outputs.
        logp = jax.nn.log_softmax(flat.astype(jnp.float32), axis=-1)
        loss = -jnp.take_along_axis(logp, lab[:, None], axis=-1)[:, 0]
        return loss.reshape(shape)
    loss = _xent(flat, lab, block_b, interpret,
                 tuple(vma) if vma else None)
    return loss.reshape(shape)


# ---------------------------------------------------------------------------
# Flash decode: single-query attention over a KV cache (the serving-side
# memory-bound op — one (1, D) query streams the cache once, online softmax,
# no (T,) probability vector in HBM). Valid lengths arrive as data so every
# decode step is the same compiled kernel; `n_valid` may be a scalar (whole
# batch at one depth — the lockstep decode_step path) or a (B,) vector
# (per-sequence depths — the continuous-batching serving path).
# ---------------------------------------------------------------------------

# flash_decode tiles the cache time axis in blocks of this size; caches are
# padded up to a multiple at init (models.transformer.init_kv_cache) so the
# Pallas path always engages instead of silently falling back to dense.
DECODE_BLOCK = 128

DENSE_FALLBACKS_TOTAL = "mxtpu_decode_dense_fallbacks_total"
_FALLBACKS_HELP = ("flash_decode calls that fell back to the dense "
                   "(non-Pallas) cache attention because the cache length "
                   "does not tile into decode blocks, by reason.")


def _count_dense_fallback(reason):
    # trace-time event (shapes are static), so the counter costs nothing
    # on the per-step hot path; lazy import keeps this module jax-only
    # when telemetry is off
    from .. import telemetry

    telemetry.inc(DENSE_FALLBACKS_TOTAL, help=_FALLBACKS_HELP,
                  reason=reason)


def _per_seq_n_valid(n_valid, batch):
    """Canonicalize `n_valid` (python/traced scalar or (B,) vector) to a
    (B,) int32 vector."""
    nv = jnp.asarray(n_valid, jnp.int32)
    return jnp.broadcast_to(nv, (batch,))


def _decode_kernel(q_ref, k_ref, v_ref, nv_ref, o_ref, *, block_k, scale):
    q = q_ref[...]  # (1, d)
    nv = nv_ref[0]

    def body(j, carry):
        o, m, l = carry
        k = k_ref[pl.ds(j * block_k, block_k), :]
        v = v_ref[pl.ds(j * block_k, block_k), :]
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale
        idx = j * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        s = jnp.where(idx < nv, s, _NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=1))
        p = jnp.exp(s - m_new[:, None])
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=1)
        o_new = o * alpha[:, None] + jnp.dot(
            p.astype(v.dtype), v, preferred_element_type=jnp.float32)
        return o_new, m_new, l_new

    d = q.shape[1]
    o0 = jnp.zeros((1, d), jnp.float32)
    m0 = jnp.full((1,), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((1,), jnp.float32)
    num_k = (nv + block_k - 1) // block_k  # dynamic: stream only live blocks
    o, m, l = jax.lax.fori_loop(0, num_k, body, (o0, m0, l0))
    l = jnp.maximum(l, 1e-30)
    o_ref[...] = (o / l[:, None]).astype(o_ref.dtype)


def dense_decode_attention(q, k_cache, v_cache, n_valid):
    """Reference single-query cache attention (also the non-tiling
    fallback and decode_step's dense path): q (B, H, D), caches
    (B, T, H, D), attend to the first n_valid positions. `n_valid` is a
    scalar (one depth for the whole batch) or a (B,) vector (ragged
    per-sequence depths)."""
    B, T = k_cache.shape[0], k_cache.shape[1]
    D = q.shape[-1]
    nv = _per_seq_n_valid(n_valid, B)
    s = jnp.einsum("bhd,bthd->bht", q, k_cache) / np.sqrt(D)
    s = jnp.where(jnp.arange(T)[None, None] < nv[:, None, None], s,
                  _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bht,bthd->bhd", p, v_cache)


def _epilogue_fwd_kernel(x_ref, scale_ref, shift_ref, o_ref):
    """y = relu(x*scale + shift) for one (block_r, C) tile, f32 math."""
    x = x_ref[...].astype(jnp.float32)
    y = x * scale_ref[...] + shift_ref[...]
    o_ref[...] = jnp.maximum(y, 0.0).astype(o_ref.dtype)


def _epilogue_res_fwd_kernel(x_ref, scale_ref, shift_ref, r_ref, o_ref):
    """y = relu(x*scale + shift + residual) in one tile pass."""
    x = x_ref[...].astype(jnp.float32)
    y = x * scale_ref[...] + shift_ref[...] + r_ref[...].astype(jnp.float32)
    o_ref[...] = jnp.maximum(y, 0.0).astype(o_ref.dtype)


def _epilogue_bwd_kernel(x_ref, scale_ref, y_ref, dy_ref,
                         dx_ref, dscale_ref, dshift_ref, *, block_r, rows,
                         dres_ref=None):
    """Backward tile: mask from y>0 (no pre-activation tensor saved),
    dx = dy*mask*scale, channel sums dscale/dshift ACCUMULATE across the
    sequential TPU grid into one revisited (1, C) block (zeroed at i==0).
    The final row block may be ragged (cdiv grid): rows beyond `rows` are
    masked out of both dx and the channel sums."""
    i = pl.program_id(0)
    x = x_ref[...].astype(jnp.float32)
    dy = dy_ref[...].astype(jnp.float32)
    row = i * block_r + jax.lax.broadcasted_iota(jnp.int32, x.shape, 0)
    live = (row < rows) & (y_ref[...].astype(jnp.float32) > 0.0)
    g = jnp.where(live, dy, 0.0)
    # x must be masked too: the padded tail of a ragged block reads as
    # NaN in interpret mode, and 0 * NaN poisons the channel sums
    x = jnp.where(live, x, 0.0)
    dx_ref[...] = (g * scale_ref[...]).astype(dx_ref.dtype)
    if dres_ref is not None:
        dres_ref[...] = g.astype(dres_ref.dtype)

    @pl.when(i == 0)
    def _zero():
        dscale_ref[...] = jnp.zeros_like(dscale_ref)
        dshift_ref[...] = jnp.zeros_like(dshift_ref)

    dscale_ref[...] += jnp.sum(g * x, axis=0, keepdims=True)
    dshift_ref[...] += jnp.sum(g, axis=0, keepdims=True)


def _epilogue_fwd_call(x, scale, shift, residual, block_r, interpret):
    r, c = x.shape
    grid = (pl.cdiv(r, block_r),)
    row_spec = pl.BlockSpec((block_r, c), lambda i: (i, 0))
    chan_spec = pl.BlockSpec((1, c), lambda i: (0, 0))
    if residual is None:
        return pl.pallas_call(
            _epilogue_fwd_kernel,
            grid=grid,
            in_specs=[row_spec, chan_spec, chan_spec],
            out_specs=row_spec,
            out_shape=jax.ShapeDtypeStruct((r, c), x.dtype),
            interpret=interpret,
        )(x, scale, shift)
    return pl.pallas_call(
        _epilogue_res_fwd_kernel,
        grid=grid,
        in_specs=[row_spec, chan_spec, chan_spec, row_spec],
        out_specs=row_spec,
        out_shape=jax.ShapeDtypeStruct((r, c), x.dtype),
        interpret=interpret,
    )(x, scale, shift, residual)


def _epilogue_bwd_call(x, scale, y, dy, with_res, block_r, interpret):
    r, c = x.shape
    grid = (pl.cdiv(r, block_r),)
    row_spec = pl.BlockSpec((block_r, c), lambda i: (i, 0))
    chan_spec = pl.BlockSpec((1, c), lambda i: (0, 0))
    kernel = functools.partial(_epilogue_bwd_kernel, block_r=block_r, rows=r)
    if with_res:
        # dres rides as a 4th output; wrap so it lands after dshift in the
        # positional out_refs yet reaches the kernel as a keyword
        def kernel(x_ref, scale_ref, y_ref, dy_ref, dx_ref, dscale_ref,
                   dshift_ref, dres_ref):
            _epilogue_bwd_kernel(x_ref, scale_ref, y_ref, dy_ref, dx_ref,
                                 dscale_ref, dshift_ref, block_r=block_r,
                                 rows=r, dres_ref=dres_ref)
    out_specs = [row_spec, chan_spec, chan_spec]
    out_shape = [
        jax.ShapeDtypeStruct((r, c), x.dtype),
        jax.ShapeDtypeStruct((1, c), jnp.float32),
        jax.ShapeDtypeStruct((1, c), jnp.float32),
    ]
    if with_res:
        out_specs.append(row_spec)
        out_shape.append(jax.ShapeDtypeStruct((r, c), dy.dtype))
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[row_spec, chan_spec, row_spec, row_spec],
        out_specs=out_specs,
        out_shape=out_shape,
        interpret=interpret,
    )(x, scale, y, dy)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _epi_plain(x, scale, shift, block_r, interpret):
    return _epilogue_fwd_call(x, scale, shift, None, block_r, interpret)


def _epi_plain_fwd(x, scale, shift, block_r, interpret):
    y = _epilogue_fwd_call(x, scale, shift, None, block_r, interpret)
    return y, (x, scale, y)


def _epi_plain_bwd(block_r, interpret, res, dy):
    x, scale, y = res
    dx, dscale, dshift = _epilogue_bwd_call(x, scale, y, dy, False, block_r,
                                            interpret)
    return dx, dscale, dshift  # scale/shift primals are (1, C)


_epi_plain.defvjp(_epi_plain_fwd, _epi_plain_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5))
def _epi_res(x, scale, shift, residual, block_r, interpret):
    return _epilogue_fwd_call(x, scale, shift, residual, block_r, interpret)


def _epi_res_fwd(x, scale, shift, residual, block_r, interpret):
    y = _epilogue_fwd_call(x, scale, shift, residual, block_r, interpret)
    # the residual itself is NOT saved: its gradient is dy*mask, and the
    # mask regenerates from y
    return y, (x, scale, y)


def _epi_res_bwd(block_r, interpret, res, dy):
    x, scale, y = res
    dx, dscale, dshift, dres = _epilogue_bwd_call(x, scale, y, dy, True,
                                                  block_r, interpret)
    return dx, dscale, dshift, dres


_epi_res.defvjp(_epi_res_fwd, _epi_res_bwd)


def bn_act_epilogue(x, scale, shift, residual=None, block_rows=256,
                    interpret=None):
    """Fused conv/matmul epilogue: relu(x*scale + shift [+ residual]) on a
    channels-last accumulator in ONE HBM pass, with a custom-VJP backward.

    x: (..., C) — typically an NHWC conv output; scale/shift: (C,) — the
    BN affine folded to per-channel scale = gamma*rsqrt(var+eps) and
    shift = beta - mean*scale; residual: same shape as x or None. Math in
    f32, output in x.dtype. The backward recomputes the ReLU mask from
    the saved OUTPUT (y > 0), so no pre-activation tensor is kept:
    dx = dy*mask*scale, dresidual = dy*mask, dscale = Σ dy*mask*x,
    dshift = Σ dy*mask (channel sums accumulated across the sequential
    grid). This is the HBM-traffic lever MXTPU_FUSED_EPILOGUE arms: the
    BN-normalize + ReLU + residual-add chain reads and writes the
    activation tensor once instead of once per op."""
    if interpret is None:
        interpret = jax.default_backend() == "cpu"
    c = x.shape[-1]
    flat = x.reshape(-1, c)
    r = flat.shape[0]
    block_r = min(block_rows, r)
    scale2 = jnp.asarray(scale, jnp.float32).reshape(1, c)
    shift2 = jnp.asarray(shift, jnp.float32).reshape(1, c)
    if residual is None:
        y = _epi_plain(flat, scale2, shift2, block_r, interpret)
    else:
        y = _epi_res(flat, scale2, shift2, residual.reshape(-1, c), block_r,
                     interpret)
    return y.reshape(x.shape)


def flash_decode(q, k_cache, v_cache, n_valid, block_k=DECODE_BLOCK,
                 interpret=None):
    """Single-step attention: q (B, H, D) against caches (B, T, H, D),
    attending to the first `n_valid` positions (traced scalar, or a (B,)
    vector of per-sequence depths). Returns (B, H, D)."""
    if interpret is None:
        interpret = jax.default_backend() == "cpu"
    B, T, H, D = k_cache.shape
    blk = min(block_k, T)
    if T % blk != 0:  # cache length must tile; fall back to dense
        _count_dense_fallback("untiled_cache")
        return dense_decode_attention(q, k_cache, v_cache, n_valid)
    qr = q.reshape(B, H, 1, D)
    kr = k_cache.transpose(0, 2, 1, 3)  # (B, H, T, D)
    vr = v_cache.transpose(0, 2, 1, 3)
    nv = _per_seq_n_valid(n_valid, B)
    kernel = functools.partial(_decode_kernel, block_k=blk,
                               scale=1.0 / np.sqrt(D))
    o = pl.pallas_call(
        kernel,
        grid=(B, H),
        in_specs=[
            pl.BlockSpec((None, None, 1, D), lambda b, h: (b, h, 0, 0)),
            pl.BlockSpec((None, None, T, D), lambda b, h: (b, h, 0, 0)),
            pl.BlockSpec((None, None, T, D), lambda b, h: (b, h, 0, 0)),
            pl.BlockSpec((1,), lambda b, h: (b,)),
        ],
        out_specs=pl.BlockSpec((None, None, 1, D),
                               lambda b, h: (b, h, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((B, H, 1, D), q.dtype),
        interpret=interpret,
    )(qr, kr, vr, nv)
    return o.reshape(B, H, D)


# ---------------------------------------------------------------------------
# Paged decode: single-query attention where K/V live in a global page pool
# shared by every sequence (the vLLM/PagedAttention data structure). Each
# sequence owns a page-table row; the kernel walks it with pl.ds gathers and
# runs the same online-softmax accumulation as _decode_kernel. Per-sequence
# valid lengths make it the continuous-batching serving kernel: slots at
# different depths decode in ONE launch of one compiled program.
# ---------------------------------------------------------------------------


def _paged_decode_kernel(pt_ref, nv_ref, q_ref, k_ref, v_ref, o_ref, *,
                         page_size, scale):
    """One (b, h) grid step. pt_ref (B, P_max) and nv_ref (B,) are
    scalar-prefetch refs (SMEM — readable for control flow and pl.ds
    gather indices); k_ref/v_ref see the whole pool for head h."""
    b = pl.program_id(0)
    q = q_ref[...]  # (1, d)
    nv = nv_ref[b]

    def body(j, carry):
        o, m, l = carry
        page = pt_ref[b, j]
        k = k_ref[pl.ds(page, 1)].reshape(page_size, -1)
        v = v_ref[pl.ds(page, 1)].reshape(page_size, -1)
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale
        idx = (j * page_size
               + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1))
        s = jnp.where(idx < nv, s, _NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=1))
        p = jnp.exp(s - m_new[:, None])
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=1)
        o_new = o * alpha[:, None] + jnp.dot(
            p.astype(v.dtype), v, preferred_element_type=jnp.float32)
        return o_new, m_new, l_new

    d = q.shape[1]
    o0 = jnp.zeros((1, d), jnp.float32)
    m0 = jnp.full((1,), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((1,), jnp.float32)
    # walk only the live pages of THIS sequence (dynamic bound, like the
    # dynamic num_k of _decode_kernel); dead slots (nv == 0) do no work
    num_pages = (nv + page_size - 1) // page_size
    o, m, l = jax.lax.fori_loop(0, num_pages, body, (o0, m0, l0))
    l = jnp.maximum(l, 1e-30)
    o_ref[...] = (o / l[:, None]).astype(o_ref.dtype)


def paged_decode_attention(q, k_pages, v_pages, page_table, n_valid,
                           interpret=None):
    """Single-query attention over a paged KV cache.

    q: (B, H, D) — one query per decode slot;
    k_pages/v_pages: (num_pages, page_size, H, D) — the global page pool;
    page_table: (B, P_max) int32 — page ids owned by each slot, in
    sequence order (entries past the live length are ignored);
    n_valid: (B,) int32 (or scalar) — tokens live per slot; 0 marks a
    dead slot (its output is the zero-length softmax of the null page —
    finite garbage the caller discards).

    Returns (B, H, D). The pool stays in its natural layout; the grid is
    (B, H) and each step streams only ceil(n_valid/page_size) pages of
    its own sequence via pl.ds gathers driven by the scalar-prefetched
    page table (so HBM traffic per decoded token is the live cache, not
    B x T_max)."""
    if interpret is None:
        interpret = jax.default_backend() == "cpu"
    from jax.experimental.pallas import tpu as pltpu

    B, H, D = q.shape
    num_pages, page_size = k_pages.shape[0], k_pages.shape[1]
    nv = _per_seq_n_valid(n_valid, B)
    pt = jnp.asarray(page_table, jnp.int32)
    qr = q.reshape(B, H, 1, D)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, H),
        in_specs=[
            pl.BlockSpec((None, None, 1, D),
                         lambda b, h, *refs: (b, h, 0, 0)),
            pl.BlockSpec((num_pages, page_size, None, D),
                         lambda b, h, *refs: (0, 0, h, 0)),
            pl.BlockSpec((num_pages, page_size, None, D),
                         lambda b, h, *refs: (0, 0, h, 0)),
        ],
        out_specs=pl.BlockSpec((None, None, 1, D),
                               lambda b, h, *refs: (b, h, 0, 0)),
    )
    kernel = functools.partial(_paged_decode_kernel, page_size=page_size,
                               scale=1.0 / np.sqrt(D))
    o = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, H, 1, D), q.dtype),
        interpret=interpret,
    )(pt, nv, qr, k_pages, v_pages)
    return o.reshape(B, H, D)


def _paged_decode_wide_kernel(pt_ref, nb_ref, q_ref, k_ref, v_ref, o_ref, *,
                              page_size, scale):
    """One (b, h) grid step with Q query rows at consecutive positions:
    row i sits at position nb + i and attends idx < nb + i + 1 — the
    paged prefix plus causal masking WITHIN the call. Same page walk and
    online-softmax accumulation as _paged_decode_kernel, with per-row
    (Q,) carries instead of (1,)."""
    b = pl.program_id(0)
    q = q_ref[...]  # (Q, d)
    nb = nb_ref[b]
    n_q = q.shape[0]

    def body(j, carry):
        o, m, l = carry
        page = pt_ref[b, j]
        k = k_ref[pl.ds(page, 1)].reshape(page_size, -1)
        v = v_ref[pl.ds(page, 1)].reshape(page_size, -1)
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale
        idx = (j * page_size
               + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1))
        row = jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
        s = jnp.where(idx < nb + row + 1, s, _NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=1))
        p = jnp.exp(s - m_new[:, None])
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=1)
        o_new = o * alpha[:, None] + jnp.dot(
            p.astype(v.dtype), v, preferred_element_type=jnp.float32)
        return o_new, m_new, l_new

    d = q.shape[1]
    o0 = jnp.zeros((n_q, d), jnp.float32)
    m0 = jnp.full((n_q,), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((n_q,), jnp.float32)
    # the deepest row attends nb + Q tokens; clamp the walk to the table
    # width so speculative rows past a sequence's last owned page never
    # index the table out of bounds (their outputs are discarded)
    num_pages = jnp.minimum((nb + n_q + page_size - 1) // page_size,
                            pt_ref.shape[1])
    o, m, l = jax.lax.fori_loop(0, num_pages, body, (o0, m0, l0))
    l = jnp.maximum(l, 1e-30)
    o_ref[...] = (o / l[:, None]).astype(o_ref.dtype)


def paged_decode_attention_wide(q, k_pages, v_pages, page_table, n_base,
                                interpret=None):
    """Wider-query attention over a paged KV cache: Q consecutive query
    tokens per sequence in ONE launch.

    q: (B, Q, H, D) — query i of sequence b sits at position
    n_base[b] + i; k_pages/v_pages: (num_pages, page_size, H, D) pool
    (the caller has already scattered the Q new tokens' K/V into it);
    page_table: (B, P_max) int32; n_base: (B,) int32 — tokens cached
    per sequence BEFORE this call's first query. Query i attends
    positions < n_base + i + 1 (paged prefix + intra-call causal), so a
    single launch serves chunked prefill (Q = chunk), cached-prefix
    tail prefill (n_base = cached tokens) and speculative verification
    (Q = lookahead + 1) — the vLLM/Sarathi "one kernel, many query
    widths" trick on the repo's own page walk.

    Returns (B, Q, H, D)."""
    if interpret is None:
        interpret = jax.default_backend() == "cpu"
    from jax.experimental.pallas import tpu as pltpu

    B, Q, H, D = q.shape
    num_pages, page_size = k_pages.shape[0], k_pages.shape[1]
    nb = _per_seq_n_valid(n_base, B)
    pt = jnp.asarray(page_table, jnp.int32)
    qr = q.transpose(0, 2, 1, 3)  # (B, H, Q, D)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, H),
        in_specs=[
            pl.BlockSpec((None, None, Q, D),
                         lambda b, h, *refs: (b, h, 0, 0)),
            pl.BlockSpec((num_pages, page_size, None, D),
                         lambda b, h, *refs: (0, 0, h, 0)),
            pl.BlockSpec((num_pages, page_size, None, D),
                         lambda b, h, *refs: (0, 0, h, 0)),
        ],
        out_specs=pl.BlockSpec((None, None, Q, D),
                               lambda b, h, *refs: (b, h, 0, 0)),
    )
    kernel = functools.partial(_paged_decode_wide_kernel,
                               page_size=page_size,
                               scale=1.0 / np.sqrt(D))
    o = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, H, Q, D), q.dtype),
        interpret=interpret,
    )(pt, nb, qr, k_pages, v_pages)
    return o.transpose(0, 2, 1, 3)
