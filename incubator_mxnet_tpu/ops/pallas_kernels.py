"""Pallas TPU kernels for hot ops.

Where the reference reaches for hand-written CUDA (ref: SURVEY §2 N6/N8),
the TPU build authors Pallas kernels. First kernel: fused flash attention —
blocked over VMEM with online softmax, never materializing the (T, T) score
matrix in HBM. Falls back to `interpret=True` off-TPU so the same code runs
in CPU tests.
"""
from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

__all__ = ["flash_attention"]

_NEG_INF = -1e30


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, *, block_k, seq_len, causal, scale):
    # one grid step handles one (batch*head, q_block); loops over k blocks
    q = q_ref[...]  # (block_q, d)
    block_q, d = q.shape
    q_idx = pl.program_id(1)

    def body(start, carry):
        o, m, l = carry
        k = k_ref[pl.ds(start * block_k, block_k), :]
        v = v_ref[pl.ds(start * block_k, block_k), :]
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale
        if causal:
            q_pos = q_idx * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            k_pos = start * block_k + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=1))
        p = jnp.exp(s - m_new[:, None])
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=1)
        o_new = o * alpha[:, None] + jnp.dot(
            p.astype(v.dtype), v, preferred_element_type=jnp.float32
        )
        return o_new, m_new, l_new

    o0 = jnp.zeros((block_q, d), jnp.float32)
    m0 = jnp.full((block_q,), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q,), jnp.float32)
    num_k = seq_len // block_k
    o, m, l = jax.lax.fori_loop(0, num_k, body, (o0, m0, l0))
    o_ref[...] = (o / jnp.maximum(l, 1e-30)[:, None]).astype(o_ref.dtype)


def flash_attention(q, k, v, causal=False, block_q=128, block_k=128, interpret=None):
    """Fused attention: q,k,v (B, H, T, D) -> (B, H, T, D).

    Blocked flash-attention Pallas kernel; O(T) HBM, scores live in VMEM.
    """
    if interpret is None:
        interpret = jax.default_backend() == "cpu"
    B, H, T, D = q.shape
    block_q = min(block_q, T)
    block_k = min(block_k, T)
    assert T % block_q == 0 and T % block_k == 0, "seq len must divide blocks"
    scale = 1.0 / np.sqrt(D)

    qr = q.reshape(B * H, T, D)
    kr = k.reshape(B * H, T, D)
    vr = v.reshape(B * H, T, D)

    kernel = functools.partial(
        _flash_kernel, block_k=block_k, seq_len=T, causal=causal, scale=scale
    )
    out = pl.pallas_call(
        kernel,
        grid=(B * H, T // block_q),
        in_specs=[
            pl.BlockSpec((None, block_q, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, T, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((None, T, D), lambda b, i: (b, 0, 0)),
        ],
        out_specs=pl.BlockSpec((None, block_q, D), lambda b, i: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((B * H, T, D), q.dtype),
        interpret=interpret,
    )(qr, kr, vr)
    return out.reshape(B, H, T, D)
