"""Random sampling operators.

TPU-native equivalent of the reference's `src/operator/random/` samplers
(ref: SURVEY §2 N31). The reference keeps per-device PRNG resource states
(resource.h kRandom); here every sampler is a pure function of an explicit
jax PRNG key threaded in by the evaluator (`_rng`), with the global seed
state living in `random.py` — deterministic per replica by construction.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..base import dtype_np
from .registry import register


def _dt(dtype):
    return dtype_np(dtype if dtype not in (None, "None") else "float32")


@register("_random_uniform", aliases=("uniform",), needs_rng=True)
def random_uniform(*, low=0.0, high=1.0, shape=(1,), dtype="float32", _rng=None):
    """Draw Uniform(low, high) samples with the given shape."""
    return jax.random.uniform(_rng, shape, minval=low, maxval=high, dtype=_dt(dtype))


@register("_random_normal", aliases=("normal",), needs_rng=True)
def random_normal(*, loc=0.0, scale=1.0, shape=(1,), dtype="float32", _rng=None):
    """Draw Normal(loc, scale) samples with the given shape."""
    return loc + scale * jax.random.normal(_rng, shape, dtype=_dt(dtype))


@register("_random_gamma", aliases=("gamma_sample",), needs_rng=True)
def random_gamma(*, alpha=1.0, beta=1.0, shape=(1,), dtype="float32", _rng=None):
    """Draw Gamma(alpha, beta) samples with the given shape."""
    return beta * jax.random.gamma(_rng, alpha, shape, dtype=_dt(dtype))


@register("_random_exponential", needs_rng=True)
def random_exponential(*, lam=1.0, shape=(1,), dtype="float32", _rng=None):
    """Draw Exponential(lam) samples with the given shape."""
    return jax.random.exponential(_rng, shape, dtype=_dt(dtype)) / lam


@register("_random_poisson", needs_rng=True)
def random_poisson(*, lam=1.0, shape=(1,), dtype="float32", _rng=None):
    """Draw Poisson(lam) samples with the given shape."""
    return jax.random.poisson(_rng, lam, shape).astype(_dt(dtype))


@register("_random_negative_binomial", needs_rng=True)
def random_negative_binomial(*, k=1, p=0.5, shape=(1,), dtype="float32", _rng=None):
    """Draw NegativeBinomial(k, p) samples with the given shape."""
    k1, k2 = jax.random.split(_rng)
    lam = jax.random.gamma(k1, k, shape) * (1 - p) / p
    return jax.random.poisson(k2, lam, shape).astype(_dt(dtype))


@register("_random_generalized_negative_binomial", needs_rng=True)
def random_gen_neg_binomial(*, mu=1.0, alpha=1.0, shape=(1,), dtype="float32", _rng=None):
    """Draw generalized-negative-binomial (mu, alpha) samples with the given
    shape."""
    k1, k2 = jax.random.split(_rng)
    r = 1.0 / alpha
    p = r / (r + mu)
    lam = jax.random.gamma(k1, r, shape) * (1 - p) / p
    return jax.random.poisson(k2, lam, shape).astype(_dt(dtype))


@register("_random_randint", aliases=("randint",), needs_rng=True)
def random_randint(*, low=0, high=1, shape=(1,), dtype="int32", _rng=None):
    """Draw integers uniformly from [low, high) with the given shape."""
    return jax.random.randint(_rng, shape, low, high, dtype=_dt(dtype))


@register("_sample_unique_zipfian", needs_rng=True)
def sample_unique_zipfian(*, range_max=1, shape=(1,), _rng=None):
    """Draw unique samples from an approximate Zipfian over [0, range_max);
    rejection sampling makes the work data-dependent (host-syncs under jit)."""
    u = jax.random.uniform(_rng, shape)
    cls = (jnp.exp(u * jnp.log(range_max + 1.0)) - 1.0).astype(jnp.int64)
    return jnp.clip(cls, 0, range_max - 1)


@register("_sample_multinomial", aliases=("sample_multinomial",), needs_rng=True,
          no_grad_inputs=("data",),
          num_outputs=lambda attrs: 2 if attrs.get("get_prob") else 1)
def sample_multinomial(data, *, shape=(), get_prob=False, dtype="int32", _rng=None):
    """Draw category indices from each row's probability distribution."""
    n = int(jnp.prod(jnp.array(shape))) if shape else 1
    logits = jnp.log(jnp.maximum(data, 1e-30))
    if data.ndim == 1:
        out = jax.random.categorical(_rng, logits, shape=(n,))
        out = out.reshape(shape) if shape else out.reshape(())
    else:
        out = jax.random.categorical(_rng, logits[:, None, :].repeat(max(n, 1), axis=1), axis=-1)
        out = out.reshape((data.shape[0],) + tuple(shape)) if shape else out.reshape((data.shape[0],))
    sample = out.astype(_dt(dtype))
    if not get_prob:
        return sample
    # ref: sample_multinomial get_prob=True also returns the sampled
    # class's log-likelihood (used by REINFORCE-style estimators)
    if data.ndim == 1:
        logp = logits[out.reshape(-1)].reshape(sample.shape)
    else:
        flat = out.reshape(data.shape[0], -1).astype(jnp.int32)
        logp = jnp.take_along_axis(logits, flat, axis=-1).reshape(sample.shape)
    return sample, logp.astype(data.dtype)


@register("_shuffle", aliases=("shuffle",), needs_rng=True)
def shuffle(data, *, _rng=None):
    """Randomly permute the input along its first axis."""
    return jax.random.permutation(_rng, data, axis=0)


@register("_random_bernoulli", aliases=("bernoulli",), needs_rng=True)
def bernoulli(*, p=0.5, shape=(1,), dtype="float32", _rng=None):
    """Draw Bernoulli samples from per-element probabilities (or logits)."""
    return jax.random.bernoulli(_rng, p, shape).astype(_dt(dtype))


# --- sample_* family: TENSOR distribution parameters, one draw-set per
#     parameter row (ref: src/operator/random/multisample_op.cc) -----------


def _shape_tuple(shape):
    if shape in (None, "None", ()):
        return ()
    return (int(shape),) if isinstance(shape, (int, float)) else tuple(
        int(s) for s in shape)


def _expand(p, shape):
    """Append singleton dims so per-row params broadcast over the draws."""
    return p.reshape(tuple(p.shape) + (1,) * len(shape))


@register("_sample_uniform", aliases=("sample_uniform",), needs_rng=True,
          no_grad_inputs=("low", "high"))
def sample_uniform(low, high, *, shape=(), dtype="float32", _rng=None):
    """Per-row Uniform draws: row i samples from (low[i], high[i])."""
    shape = _shape_tuple(shape)
    u = jax.random.uniform(_rng, tuple(low.shape) + shape, dtype=_dt(dtype))
    return _expand(low, shape) + u * (_expand(high, shape) - _expand(low, shape))


@register("_sample_normal", aliases=("sample_normal",), needs_rng=True,
          no_grad_inputs=("mu", "sigma"))
def sample_normal(mu, sigma, *, shape=(), dtype="float32", _rng=None):
    """Per-row Normal draws: row i samples from (mu[i], sigma[i])."""
    shape = _shape_tuple(shape)
    z = jax.random.normal(_rng, tuple(mu.shape) + shape, dtype=_dt(dtype))
    return _expand(mu, shape) + _expand(sigma, shape) * z


@register("_sample_gamma", aliases=("sample_gamma",), needs_rng=True,
          no_grad_inputs=("alpha", "beta"))
def sample_gamma(alpha, beta, *, shape=(), dtype="float32", _rng=None):
    """Per-row Gamma draws from (alpha[i], beta[i])."""
    shape = _shape_tuple(shape)
    g = jax.random.gamma(_rng, _expand(alpha, shape),
                         tuple(alpha.shape) + shape, dtype=_dt(dtype))
    return _expand(beta, shape) * g


@register("_sample_exponential", aliases=("sample_exponential",),
          needs_rng=True, no_grad_inputs=("lam",))
def sample_exponential(lam, *, shape=(), dtype="float32", _rng=None):
    """Per-row Exponential draws from lam[i]."""
    shape = _shape_tuple(shape)
    e = jax.random.exponential(_rng, tuple(lam.shape) + shape, dtype=_dt(dtype))
    return e / _expand(lam, shape)


@register("_sample_poisson", aliases=("sample_poisson",), needs_rng=True,
          no_grad_inputs=("lam",))
def sample_poisson(lam, *, shape=(), dtype="float32", _rng=None):
    """Per-row Poisson draws from lam[i]."""
    shape = _shape_tuple(shape)
    return jax.random.poisson(_rng, _expand(lam, shape),
                              tuple(lam.shape) + shape).astype(_dt(dtype))


@register("_sample_negative_binomial", aliases=("sample_negative_binomial",),
          needs_rng=True, no_grad_inputs=("k", "p"))
def sample_negative_binomial(k, p, *, shape=(), dtype="float32", _rng=None):
    """Per-row NegativeBinomial draws from (k[i], p[i])."""
    shape = _shape_tuple(shape)
    k1, k2 = jax.random.split(_rng)
    full = tuple(k.shape) + shape
    lam = (jax.random.gamma(k1, _expand(k, shape), full)
           * (1 - _expand(p, shape)) / _expand(p, shape))
    return jax.random.poisson(k2, lam, full).astype(_dt(dtype))


@register("_sample_generalized_negative_binomial",
          aliases=("sample_generalized_negative_binomial",), needs_rng=True,
          no_grad_inputs=("mu", "alpha"))
def sample_gen_neg_binomial(mu, alpha, *, shape=(), dtype="float32", _rng=None):
    """Per-row generalized-negative-binomial draws from (mu[i], alpha[i])."""
    shape = _shape_tuple(shape)
    k1, k2 = jax.random.split(_rng)
    full = tuple(mu.shape) + shape
    r = 1.0 / _expand(alpha, shape)
    p = r / (r + _expand(mu, shape))
    lam = jax.random.gamma(k1, r, full) * (1 - p) / p
    return jax.random.poisson(k2, lam, full).astype(_dt(dtype))


# Deprecated 1.x-era public spellings, kept so ported scripts resolve
# (ref: src/operator/random/sample_op.cc:83,101,116,128,140,153,167,182
# `.add_alias("random_*")`).
from .registry import alias as _alias  # noqa: E402

for _dist in ("uniform", "normal", "gamma", "exponential", "poisson",
              "negative_binomial", "generalized_negative_binomial",
              "randint"):
    _alias(f"_random_{_dist}", f"random_{_dist}")
del _alias, _dist
