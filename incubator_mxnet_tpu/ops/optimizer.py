"""Fused optimizer update operators.

TPU-native equivalent of the reference's fused update ops
(ref: src/operator/optimizer_op-inl.h — sgd_update, sgd_mom_update,
adam_update, etc., SURVEY §2 N5). Each returns the updated weight (and
updated states); the Optimizer/Updater layer writes results back into the
parameter arrays. All are jit-compiled once per shape/dtype and fuse into a
handful of elementwise XLA kernels.

Functional protocol: update ops return tuples (new_weight, new_state...);
MXNet mutates in place. Multi-output counts are static per op.
"""
from __future__ import annotations

import jax.numpy as jnp

from .registry import register


def _apply_wd(grad, weight, wd):
    return grad + wd * weight


def _rescale_clip(grad, rescale_grad, clip_gradient):
    g = grad * rescale_grad
    if clip_gradient is not None and clip_gradient > 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    return g


def _one_minus_pow(beta, t):
    """1 - beta**t, cancellation-free for traced fp32 t (beta2=0.999 at
    t=1 loses ~4 digits in the naive form). Python t keeps exact double
    math so eager callers are unchanged."""
    if isinstance(t, (int, float)):
        return 1.0 - beta ** t
    if beta <= 0.0:
        return jnp.ones_like(jnp.asarray(t, jnp.float32))
    import math

    return -jnp.expm1(jnp.asarray(t, jnp.float32) * math.log(beta))


@register("sgd_update", no_grad_inputs=("weight", "grad"),
          donate=('weight',))
def sgd_update(weight, grad, *, lr, wd=0.0, rescale_grad=1.0, clip_gradient=-1.0, lazy_update=True):
    """SGD step: weight -= lr * (rescaled, clipped grad + wd * weight)."""
    g = _rescale_clip(grad, rescale_grad, clip_gradient)
    return weight - lr * (g + wd * weight)


@register("sgd_mom_update", num_outputs=2, no_grad_inputs=("weight", "grad", "mom"),
          donate=('weight', 'mom'))
def sgd_mom_update(
    weight, grad, mom, *, lr, momentum=0.0, wd=0.0, rescale_grad=1.0, clip_gradient=-1.0, lazy_update=True
):
    """Momentum SGD step: mom = momentum * mom - lr * grad; weight += mom."""
    g = _rescale_clip(grad, rescale_grad, clip_gradient)
    new_mom = momentum * mom - lr * (g + wd * weight)
    return weight + new_mom, new_mom


@register("nag_mom_update", num_outputs=2, no_grad_inputs=("weight", "grad", "mom"),
          donate=('weight', 'mom'))
def nag_mom_update(weight, grad, mom, *, lr, momentum=0.0, wd=0.0, rescale_grad=1.0, clip_gradient=-1.0):
    """Nesterov accelerated SGD step (gradient looked ahead through momentum)."""
    g = _rescale_clip(grad, rescale_grad, clip_gradient) + wd * weight
    new_mom = momentum * mom + g
    return weight - lr * (g + momentum * new_mom), new_mom


@register("adam_update", num_outputs=3, no_grad_inputs=("weight", "grad", "mean", "var"),
          donate=('weight', 'mean', 'var'))
def adam_update(
    weight, grad, mean, var, *, lr, beta1=0.9, beta2=0.999, epsilon=1e-8, wd=0.0,
    rescale_grad=1.0, clip_gradient=-1.0, lazy_update=True,
):
    """Adam step: first/second-moment EMAs with epsilon-stabilized update."""
    g = _rescale_clip(grad, rescale_grad, clip_gradient) + wd * weight
    new_mean = beta1 * mean + (1 - beta1) * g
    new_var = beta2 * var + (1 - beta2) * jnp.square(g)
    return weight - lr * new_mean / (jnp.sqrt(new_var) + epsilon), new_mean, new_var


@register("rmsprop_update", num_outputs=2, no_grad_inputs=("weight", "grad", "n"),
          donate=('weight', 'n'))
def rmsprop_update(
    weight, grad, n, *, lr, gamma1=0.95, epsilon=1e-8, wd=0.0, rescale_grad=1.0,
    clip_gradient=-1.0, clip_weights=-1.0,
):
    """RMSProp step: scale the gradient by the sqrt of a squared-grad EMA."""
    g = _rescale_clip(grad, rescale_grad, clip_gradient) + wd * weight
    new_n = (1 - gamma1) * jnp.square(g) + gamma1 * n
    new_w = weight - lr * g / jnp.sqrt(new_n + epsilon)
    if clip_weights is not None and clip_weights > 0:
        new_w = jnp.clip(new_w, -clip_weights, clip_weights)
    return new_w, new_n


@register("rmspropalex_update", num_outputs=4, no_grad_inputs=("weight", "grad", "n", "g", "delta"),
          donate=('weight', 'n', 'g', 'delta'))
def rmspropalex_update(
    weight, grad, n, g, delta, *, lr, gamma1=0.95, gamma2=0.9, epsilon=1e-8, wd=0.0,
    rescale_grad=1.0, clip_gradient=-1.0, clip_weights=-1.0,
):
    """Centered RMSProp (Alex Graves' variant): additionally tracks the grad
    mean."""
    gr = _rescale_clip(grad, rescale_grad, clip_gradient) + wd * weight
    new_n = (1 - gamma1) * jnp.square(gr) + gamma1 * n
    new_g = (1 - gamma1) * gr + gamma1 * g
    new_delta = gamma2 * delta - lr * gr / jnp.sqrt(new_n - jnp.square(new_g) + epsilon)
    new_w = weight + new_delta
    if clip_weights is not None and clip_weights > 0:
        new_w = jnp.clip(new_w, -clip_weights, clip_weights)
    return new_w, new_n, new_g, new_delta


@register("ftrl_update", num_outputs=3, no_grad_inputs=("weight", "grad", "z", "n"),
          donate=('weight', 'z', 'n'))
def ftrl_update(
    weight, grad, z, n, *, lr, lamda1=0.01, beta=1.0, wd=0.0, rescale_grad=1.0, clip_gradient=-1.0
):
    """FTRL-proximal step with L1/L2 regularization."""
    g = _rescale_clip(grad, rescale_grad, clip_gradient)
    new_n = n + jnp.square(g)
    sigma = (jnp.sqrt(new_n) - jnp.sqrt(n)) / lr
    new_z = z + g - sigma * weight
    new_w = jnp.where(
        jnp.abs(new_z) > lamda1,
        -(new_z - jnp.sign(new_z) * lamda1) / ((beta + jnp.sqrt(new_n)) / lr + wd),
        jnp.zeros_like(weight),
    )
    return new_w, new_z, new_n


@register("signsgd_update", no_grad_inputs=("weight", "grad"),
          donate=('weight',))
def signsgd_update(weight, grad, *, lr, wd=0.0, rescale_grad=1.0, clip_gradient=-1.0):
    """SignSGD step: weight -= lr * sign(grad)."""
    g = _rescale_clip(grad, rescale_grad, clip_gradient)
    return weight - lr * (jnp.sign(g) + wd * weight)


@register("signum_update", num_outputs=2, no_grad_inputs=("weight", "grad", "mom"),
          donate=('weight', 'mom'))
def signum_update(
    weight, grad, mom, *, lr, momentum=0.0, wd=0.0, rescale_grad=1.0, clip_gradient=-1.0, wd_lh=0.0
):
    """Signum step: SignSGD applied to a momentum buffer."""
    g = _rescale_clip(grad, rescale_grad, clip_gradient)
    new_mom = momentum * mom - (1 - momentum) * (g + wd * weight)
    new_w = weight * (1 - lr * wd_lh) + lr * jnp.sign(new_mom)
    return new_w, new_mom


@register("ftml_update", num_outputs=4, no_grad_inputs=("weight", "grad", "d", "v", "z"),
          donate=('weight', 'd', 'v', 'z'))
def ftml_update(
    weight, grad, d, v, z, *, lr, beta1=0.6, beta2=0.999, epsilon=1e-8, wd=0.0,
    rescale_grad=1.0, clip_grad=-1.0, t=1,
):
    """FTML (Follow The Moving Leader) step."""
    g = _rescale_clip(grad, rescale_grad, clip_grad) + wd * weight
    new_v = beta2 * v + (1 - beta2) * jnp.square(g)
    d_t = (_one_minus_pow(beta1, t) / lr
           * (jnp.sqrt(new_v / _one_minus_pow(beta2, t)) + epsilon))
    sigma = d_t - beta1 * d
    new_z = beta1 * z + (1 - beta1) * g - sigma * weight
    new_w = -new_z / d_t
    return new_w, d_t, new_v, new_z


@register("adamw_update", num_outputs=3, no_grad_inputs=("weight", "grad", "mean", "var"),
          donate=('weight', 'mean', 'var'))
def adamw_update(
    weight, grad, mean, var, *, lr, beta1=0.9, beta2=0.999, epsilon=1e-8, wd=0.0, eta=1.0,
    rescale_grad=1.0, clip_gradient=-1.0,
):
    """AdamW step: Adam with decoupled weight decay (eta * lr * wd * weight)."""
    g = _rescale_clip(grad, rescale_grad, clip_gradient)
    new_mean = beta1 * mean + (1 - beta1) * g
    new_var = beta2 * var + (1 - beta2) * jnp.square(g)
    new_w = weight - eta * (lr * new_mean / (jnp.sqrt(new_var) + epsilon) + wd * weight)
    return new_w, new_mean, new_var


# ---------------------------------------------------------------------------
# Aggregated (multi-tensor) SGD updates (ref: src/operator/optimizer_op.cc:318
# multi_sgd_update / multi_sgd_mom_update / multi_mp_sgd_update /
# multi_mp_sgd_mom_update — one launch updating num_weights tensors).
#
# Functional protocol deviation from the reference: the reference mutates
# momentum / weight32 inputs in place and returns only the weights; a pure
# op cannot, so ALL updated tensors are returned — weights first, then
# momenta (mom variants), then fp32 master weights (mp variants), each
# group in input order. The fused training path (fused.GluonTrainStep)
# remains the idiomatic route; these exist for ported-script name parity
# and are XLA-fused into one program anyway when jitted together.
# ---------------------------------------------------------------------------


def _nw(attrs):
    """num_weights from an attr dict, with a real error when omitted (the
    eager frontend fills required attrs with None, and the num_outputs
    lambdas run before the op body's own guard could)."""
    nw = attrs.get("num_weights")
    if nw is None:
        raise TypeError("multi update requires num_weights")
    return int(nw)


def _multi_groups(args, group, num_weights):
    if num_weights is None:
        raise TypeError("multi update requires num_weights")
    expected = group * int(num_weights)
    if len(args) != expected:
        # the declared output count comes from num_weights alone; a
        # mismatched tensor count would silently drop updates otherwise
        raise ValueError(
            f"multi update with num_weights={num_weights} expects "
            f"{expected} tensors ({group} per weight), got {len(args)}")
    return [args[i:i + group] for i in range(0, len(args), group)]


def _per_weight(attr, i, what):
    if attr is None:
        # required attr: the eager frontend fills omitted required attrs
        # with None — raise rather than train at a silent default
        raise TypeError(f"multi update requires {what} (per-weight tuple)")
    if isinstance(attr, (tuple, list)):
        attr = attr[i]
    if hasattr(attr, "dtype"):
        # traced/array scalar (the aggregated Trainer path passes lr as a
        # jit argument so lr changes don't retrace) — float() would be a
        # ConcretizationError inside the trace
        return attr
    return float(attr)


@register("multi_sgd_update", num_outputs=lambda attrs: _nw(attrs))
def multi_sgd_update(*args, lrs, wds, num_weights, rescale_grad=1.0,
                     clip_gradient=-1.0):
    """Aggregated SGD step over many (weight, grad) pairs in one fused program."""
    outs = []
    for i, (w, g) in enumerate(_multi_groups(args, 2, num_weights)):
        outs.append(sgd_update(
            w, g, lr=_per_weight(lrs, i, "lrs"), wd=_per_weight(wds, i, "wds"),
            rescale_grad=rescale_grad, clip_gradient=clip_gradient))
    return tuple(outs)


@register("multi_sgd_mom_update",
          num_outputs=lambda attrs: 2 * _nw(attrs))
def multi_sgd_mom_update(*args, lrs, wds, num_weights, momentum=0.0,
                         rescale_grad=1.0, clip_gradient=-1.0):
    """Aggregated momentum-SGD step over many (weight, grad, mom) triples."""
    ws, ms = [], []
    for i, (w, g, m) in enumerate(_multi_groups(args, 3, num_weights)):
        new_w, new_m = sgd_mom_update(
            w, g, m, lr=_per_weight(lrs, i, "lrs"), momentum=momentum,
            wd=_per_weight(wds, i, "wds"), rescale_grad=rescale_grad,
            clip_gradient=clip_gradient)
        ws.append(new_w)
        ms.append(new_m)
    return tuple(ws) + tuple(ms)


@register("multi_mp_sgd_update",
          num_outputs=lambda attrs: 2 * _nw(attrs))
def multi_mp_sgd_update(*args, lrs, wds, num_weights, rescale_grad=1.0,
                        clip_gradient=-1.0):
    """Mixed precision: per weight (weight, grad, weight32); math in fp32
    master weights, low-precision weight refreshed by cast."""
    ws, w32s = [], []
    for i, (w, g, w32) in enumerate(_multi_groups(args, 3, num_weights)):
        new_w32 = sgd_update(
            w32, g.astype(w32.dtype), lr=_per_weight(lrs, i, "lrs"),
            wd=_per_weight(wds, i, "wds"), rescale_grad=rescale_grad,
            clip_gradient=clip_gradient)
        ws.append(new_w32.astype(w.dtype))
        w32s.append(new_w32)
    return tuple(ws) + tuple(w32s)


@register("multi_mp_sgd_mom_update",
          num_outputs=lambda attrs: 3 * _nw(attrs))
def multi_mp_sgd_mom_update(*args, lrs, wds, num_weights, momentum=0.0,
                            rescale_grad=1.0, clip_gradient=-1.0):
    """Aggregated mixed-precision momentum SGD: low-precision weights with fp32
    master copies and momenta."""
    ws, ms, w32s = [], [], []
    for i, (w, g, m, w32) in enumerate(_multi_groups(args, 4, num_weights)):
        new_w32, new_m = sgd_mom_update(
            w32, g.astype(w32.dtype), m, lr=_per_weight(lrs, i, "lrs"),
            momentum=momentum, wd=_per_weight(wds, i, "wds"),
            rescale_grad=rescale_grad, clip_gradient=clip_gradient)
        ws.append(new_w32.astype(w.dtype))
        ms.append(new_m)
        w32s.append(new_w32)
    return tuple(ws) + tuple(ms) + tuple(w32s)


@register("mp_sgd_update", num_outputs=2,
          no_grad_inputs=("weight", "grad", "weight32"),
          donate=('weight', 'weight32'))
def mp_sgd_update(weight, grad, weight32, *, lr, wd=0.0, rescale_grad=1.0,
                  clip_gradient=-1.0, lazy_update=True):
    """Mixed-precision SGD: math on the fp32 master copy, low-precision
    weight refreshed by cast (ref: optimizer_op.cc mp_sgd_update)."""
    new_w32 = sgd_update(weight32, grad.astype(weight32.dtype), lr=lr, wd=wd,
                         rescale_grad=rescale_grad,
                         clip_gradient=clip_gradient)
    return new_w32.astype(weight.dtype), new_w32


@register("mp_sgd_mom_update", num_outputs=3,
          no_grad_inputs=("weight", "grad", "mom", "weight32"),
          donate=('weight', 'mom', 'weight32'))
def mp_sgd_mom_update(weight, grad, mom, weight32, *, lr, momentum=0.0,
                      wd=0.0, rescale_grad=1.0, clip_gradient=-1.0,
                      lazy_update=True):
    """(ref: optimizer_op.cc mp_sgd_mom_update)"""
    new_w32, new_mom = sgd_mom_update(
        weight32, grad.astype(weight32.dtype), mom, lr=lr, momentum=momentum,
        wd=wd, rescale_grad=rescale_grad, clip_gradient=clip_gradient)
    return new_w32.astype(weight.dtype), new_mom, new_w32


@register("_adamw_update", num_outputs=3,
          no_grad_inputs=("weight", "grad", "mean", "var", "rescale_grad"),
          donate=('weight', 'mean', 'var'))
def _adamw_update_dyn(weight, grad, mean, var, rescale_grad, *, lr,
                      beta1=0.9, beta2=0.999, epsilon=1e-8, wd=0.0, eta=1.0,
                      clip_gradient=-1.0):
    """AdamW whose rescale factor is a TENSOR input: with dynamic loss
    scaling the scale (1/loss_scale) arrives per step, and a non-finite
    or zero scale SKIPS the update entirely
    (ref: src/operator/contrib/adamw.cc _adamw_update)."""
    scale = jnp.reshape(rescale_grad.astype(jnp.float32), ())
    ok = jnp.isfinite(scale) & (scale != 0)
    safe = jnp.where(ok, scale, 1.0)
    new_w, new_mean, new_var = adamw_update(
        weight, grad, mean, var, lr=lr, beta1=beta1, beta2=beta2,
        epsilon=epsilon, wd=wd, eta=eta, rescale_grad=safe,
        clip_gradient=clip_gradient)
    keep = lambda new, old: jnp.where(ok, new, old)  # noqa: E731
    return keep(new_w, weight), keep(new_mean, mean), keep(new_var, var)


@register("_mp_adamw_update", num_outputs=4,
          no_grad_inputs=("weight", "grad", "mean", "var", "weight32",
                          "rescale_grad"),
          donate=('weight', 'mean', 'var', 'weight32'))
def _mp_adamw_update(weight, grad, mean, var, weight32, rescale_grad, *, lr,
                     beta1=0.9, beta2=0.999, epsilon=1e-8, wd=0.0, eta=1.0,
                     clip_gradient=-1.0):
    """(ref: contrib/adamw.cc _mp_adamw_update)"""
    new_w32, new_mean, new_var = _adamw_update_dyn(
        weight32, grad.astype(weight32.dtype), mean, var, rescale_grad,
        lr=lr, beta1=beta1, beta2=beta2, epsilon=epsilon, wd=wd, eta=eta,
        clip_gradient=clip_gradient)
    return new_w32.astype(weight.dtype), new_mean, new_var, new_w32


@register("_contrib_group_adagrad_update", num_outputs=2,
          no_grad_inputs=("weight", "grad", "history"),
          donate=('weight', 'history'))
def _contrib_group_adagrad_update(weight, grad, history, *, lr,
                                  rescale_grad=1.0, clip_gradient=-1.0,
                                  epsilon=1e-5):
    """Row-wise (grouped) AdaGrad: one accumulator per row
    (ref: src/operator/contrib/optimizer_op.cc group_adagrad)."""
    g = _rescale_clip(grad, rescale_grad, clip_gradient)
    axes = tuple(range(1, g.ndim))
    new_h = history + jnp.mean(jnp.square(g), axis=axes, keepdims=True) \
        if g.ndim > 1 else history + jnp.square(g)
    denom = jnp.sqrt(new_h) + epsilon
    return weight - lr * g / denom, new_h
