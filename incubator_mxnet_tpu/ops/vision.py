"""Vision / detection operators.

TPU-native coverage of the reference's detection + sampling op families
(ref: SURVEY §2 N29/N30 — src/operator/contrib/{bounding_box,multibox_*,
roi_align}*, src/operator/{roi_pooling,bilinear_sampler,spatial_transformer,
grid_generator,correlation}*). Design notes vs the CUDA reference:

- Everything is fixed-shape and mask-based: suppressed/invalid detections are
  encoded as ``-1`` rows in a dense output (the reference does the same), so
  the whole family is jit/pjit friendly — no dynamic shapes reach XLA.
- NMS is a sequential suppression over score-sorted candidates expressed as a
  ``lax.fori_loop`` updating a keep-mask against a precomputed IoU matrix;
  the reference's per-thread CUDA loops become O(N) vector ops per step.
- ROI pooling/align and the samplers are gather/bilinear-weight formulations
  (MXU/VPU friendly) instead of scatter-style CUDA kernels.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .registry import register

# ---------------------------------------------------------------------------
# box utilities
# ---------------------------------------------------------------------------


def _to_corner(b, fmt):
    """(..., 4) boxes to corner (x1, y1, x2, y2) format."""
    if fmt == "corner":
        return b
    cx, cy, w, h = b[..., 0], b[..., 1], b[..., 2], b[..., 3]
    return jnp.stack([cx - w / 2, cy - h / 2, cx + w / 2, cy + h / 2], axis=-1)


def _to_format(b, fmt):
    if fmt == "corner":
        return b
    x1, y1, x2, y2 = b[..., 0], b[..., 1], b[..., 2], b[..., 3]
    return jnp.stack([(x1 + x2) / 2, (y1 + y2) / 2, x2 - x1, y2 - y1], axis=-1)


def _pair_iou(a, b):
    """IoU matrix between corner boxes a (N,4) and b (M,4) -> (N, M)."""
    ax1, ay1, ax2, ay2 = a[:, 0:1], a[:, 1:2], a[:, 2:3], a[:, 3:4]
    bx1, by1, bx2, by2 = b[None, :, 0], b[None, :, 1], b[None, :, 2], b[None, :, 3]
    iw = jnp.maximum(jnp.minimum(ax2, bx2) - jnp.maximum(ax1, bx1), 0.0)
    ih = jnp.maximum(jnp.minimum(ay2, by2) - jnp.maximum(ay1, by1), 0.0)
    inter = iw * ih
    area_a = jnp.maximum(ax2 - ax1, 0.0) * jnp.maximum(ay2 - ay1, 0.0)
    area_b = jnp.maximum(bx2 - bx1, 0.0) * jnp.maximum(by2 - by1, 0.0)
    union = area_a + area_b - inter
    return jnp.where(union > 0, inter / union, 0.0)


@register("_contrib_box_iou", aliases=("box_iou",))
def box_iou(lhs, rhs, *, format="corner"):
    """IoU of every lhs box against every rhs box
    (ref: src/operator/contrib/bounding_box.cc `_contrib_box_iou`).

    Output shape lhs.shape[:-1] + rhs.shape[:-1].
    """
    lshape, rshape = lhs.shape[:-1], rhs.shape[:-1]
    a = _to_corner(lhs.reshape(-1, 4), format)
    b = _to_corner(rhs.reshape(-1, 4), format)
    return _pair_iou(a, b).reshape(lshape + rshape)


def _nms_one(data, overlap_thresh, valid_thresh, topk, coord_start, score_index,
             id_index, force_suppress, in_format, out_format):
    """NMS over one (N, K) batch element; returns (N, K) with -1 rows."""
    n, k = data.shape
    scores = data[:, score_index]
    valid = scores > valid_thresh
    neg_inf = jnp.asarray(-jnp.inf, scores.dtype)
    order = jnp.argsort(jnp.where(valid, scores, neg_inf))[::-1]
    data = data[order]
    valid = valid[order]
    if topk > 0:
        valid = valid & (jnp.arange(n) < topk)

    boxes = _to_corner(data[:, coord_start:coord_start + 4], in_format)
    iou = _pair_iou(boxes, boxes)
    if id_index >= 0 and not force_suppress:
        same = data[:, id_index][:, None] == data[:, id_index][None, :]
    else:
        same = jnp.ones((n, n), bool)
    idx = jnp.arange(n)

    def body(i, keep):
        sup = keep[i] & keep & same[i] & (iou[i] > overlap_thresh) & (idx > i)
        return keep & ~sup

    keep = lax.fori_loop(0, n, body, valid)

    out = data
    if out_format != in_format:
        conv = _to_format(boxes, out_format) if out_format == "center" else boxes
        out = out.at[:, coord_start:coord_start + 4].set(conv)
    return jnp.where(keep[:, None], out, -jnp.ones_like(out))


@register("_contrib_box_nms", aliases=("box_nms",))
def box_nms(data, *, overlap_thresh=0.5, valid_thresh=0.0, topk=-1,
            coord_start=2, score_index=1, id_index=-1, background_id=-1,
            force_suppress=False, in_format="corner", out_format="corner"):
    """Non-maximum suppression (ref: src/operator/contrib/bounding_box.cc).

    Input (..., N, K): each row [.., id?, score, x1, y1, x2, y2, ..]; output
    has the same shape, score-sorted, suppressed rows set to -1.
    """
    shape = data.shape
    flat = data.reshape((-1,) + shape[-2:])
    if background_id >= 0 and id_index >= 0:
        bg = flat[..., id_index] == background_id
        flat = jnp.where(bg[..., None], -jnp.ones_like(flat), flat)
    out = jax.vmap(
        lambda d: _nms_one(d, overlap_thresh, valid_thresh, topk, coord_start,
                           score_index, id_index, force_suppress, in_format,
                           out_format)
    )(flat)
    return out.reshape(shape)


def _encode_offsets(anchors_center, gt_center, variances):
    """Shared SSD box-regression encoding: (d_cx/w, d_cy/h, log dw, log dh)/var."""
    var = jnp.asarray(variances, jnp.float32)
    a, g = anchors_center, gt_center
    return jnp.stack([
        (g[..., 0] - a[..., 0]) / a[..., 2] / var[0],
        (g[..., 1] - a[..., 1]) / a[..., 3] / var[1],
        jnp.log(jnp.maximum(g[..., 2] / jnp.maximum(a[..., 2], 1e-12), 1e-12)) / var[2],
        jnp.log(jnp.maximum(g[..., 3] / jnp.maximum(a[..., 3], 1e-12), 1e-12)) / var[3],
    ], axis=-1)


def _decode_offsets(offsets, anchors_center, variances):
    """Inverse of _encode_offsets -> center-format boxes."""
    var = jnp.asarray(variances, jnp.float32)
    d = offsets * var
    a = anchors_center
    cx = d[..., 0] * a[..., 2] + a[..., 0]
    cy = d[..., 1] * a[..., 3] + a[..., 1]
    w = jnp.exp(d[..., 2]) * a[..., 2]
    h = jnp.exp(d[..., 3]) * a[..., 3]
    return jnp.stack([cx, cy, w, h], axis=-1)


@register("_contrib_box_encode", aliases=("box_encode",))
def box_encode(samples, matches, anchors, refs, *, means=(0., 0., 0., 0.),
               stds=(0.1, 0.1, 0.2, 0.2)):
    """Encode matched gt boxes as regression targets vs anchors
    (ref: src/operator/contrib/bounding_box.cc `_contrib_box_encode`).

    samples (B,N) in {+1 pos, -1 neg/ignore}, matches (B,N) gt indices,
    anchors (B,N,4), refs (B,M,4) corner boxes. Returns (targets, masks).
    """
    m = matches.astype(jnp.int32)
    gt = jnp.take_along_axis(refs, m[..., None].repeat(4, -1), axis=1)
    a_c, g_c = _to_format(anchors, "center"), _to_format(gt, "center")
    t = _encode_offsets(a_c, g_c, stds)
    t = t - jnp.asarray(means, t.dtype) / jnp.asarray(stds, t.dtype)
    mask = (samples > 0.5)[..., None].astype(t.dtype)
    return t * mask, mask


@register("_contrib_box_decode", aliases=("box_decode",))
def box_decode(data, anchors, *, std0=0.1, std1=0.1, std2=0.2, std3=0.2,
               clip=-1.0, format="corner"):
    """Decode regression targets back to boxes (inverse of box_encode)."""
    a_c = _to_format(anchors, "center") if format == "corner" else anchors
    out = _to_corner(_decode_offsets(data, a_c, (std0, std1, std2, std3)),
                     "center")
    if clip > 0:
        out = jnp.clip(out, 0.0, clip)
    return out


# ---------------------------------------------------------------------------
# MultiBox (SSD) family
# ---------------------------------------------------------------------------


@register("_contrib_MultiBoxPrior", aliases=("MultiBoxPrior",))
def multibox_prior(data, *, sizes=(1.0,), ratios=(1.0,), clip=False,
                   steps=(-1.0, -1.0), offsets=(0.5, 0.5)):
    """Anchor-box generator (ref: src/operator/contrib/multibox_prior.cc).

    data (B, C, H, W) -> (1, H*W*(len(sizes)+len(ratios)-1), 4) normalized
    corner boxes. Anchor set per pixel: (sizes[i], ratios[0]) for all i plus
    (sizes[0], ratios[j]) for j >= 1, matching the reference ordering.
    """
    h, w = data.shape[2], data.shape[3]
    sizes = tuple(float(s) for s in (sizes if not np.isscalar(sizes) else (sizes,)))
    ratios = tuple(float(r) for r in (ratios if not np.isscalar(ratios) else (ratios,)))
    step_y = steps[0] if steps[0] > 0 else 1.0 / h
    step_x = steps[1] if steps[1] > 0 else 1.0 / w
    cy = (jnp.arange(h, dtype=jnp.float32) + offsets[0]) * step_y
    cx = (jnp.arange(w, dtype=jnp.float32) + offsets[1]) * step_x
    cyg, cxg = jnp.meshgrid(cy, cx, indexing="ij")  # (H, W)

    wh = [(s * math.sqrt(ratios[0]) / 2, s / math.sqrt(ratios[0]) / 2) for s in sizes]
    wh += [(sizes[0] * math.sqrt(r) / 2, sizes[0] / math.sqrt(r) / 2)
           for r in ratios[1:]]
    half = jnp.asarray(wh, jnp.float32)  # (A, 2) half-w, half-h
    c = jnp.stack([cxg, cyg], -1)[:, :, None, :]  # (H, W, 1, 2)
    lo = c - half[None, None, :, :]
    hi = c + half[None, None, :, :]
    anchors = jnp.concatenate([lo, hi], -1).reshape(1, -1, 4)
    if clip:
        anchors = jnp.clip(anchors, 0.0, 1.0)
    return anchors


def _match_one(iou, valid_gt, overlap_threshold):
    """Greedy bipartite + threshold matching for one image.

    iou (N anchors, M gt), valid_gt (M,) bool. Returns matches (N,) int32
    gt index or -1. Mirrors MultiBoxTargetForward's two-phase matching
    (ref: src/operator/contrib/multibox_target.cc).
    """
    n, m = iou.shape
    iou = jnp.where(valid_gt[None, :], iou, -1.0)

    def body(_, state):
        matches, col_used, work = state
        flat = jnp.argmax(work)
        i, j = flat // m, flat % m
        best = work[i, j]
        do = best > 1e-12
        matches = jnp.where(do, matches.at[i].set(j), matches)
        col_used = jnp.where(do, col_used.at[j].set(True), col_used)
        work = jnp.where(do, work.at[i, :].set(-1.0).at[:, j].set(-1.0), work)
        return matches, col_used, work

    matches0 = jnp.full((n,), -1, jnp.int32)
    matches, col_used, _ = lax.fori_loop(
        0, m, body, (matches0, jnp.zeros((m,), bool), iou))

    # phase 2: unmatched anchors take their argmax gt if IoU > threshold
    best_j = jnp.argmax(iou, axis=1).astype(jnp.int32)
    best_v = jnp.max(iou, axis=1)
    thr = (matches < 0) & (best_v > overlap_threshold)
    return jnp.where(thr, best_j, matches)


@register("_contrib_MultiBoxTarget", aliases=("MultiBoxTarget",),
          num_outputs=3, no_grad_inputs=("anchor", "label", "cls_pred"))
def multibox_target(anchor, label, cls_pred, *, overlap_threshold=0.5,
                    ignore_label=-1.0, negative_mining_ratio=-1.0,
                    negative_mining_thresh=0.5, minimum_negative_samples=0,
                    variances=(0.1, 0.1, 0.2, 0.2)):
    """SSD training-target assigner (ref: src/operator/contrib/multibox_target.cc).

    anchor (1, N, 4) corner; label (B, M, 5) rows [cls, x1, y1, x2, y2] with
    cls = -1 padding; cls_pred (B, num_cls+1, N). Returns
    (loc_target (B, 4N), loc_mask (B, 4N), cls_target (B, N)) where
    cls_target is gt class + 1 (0 = background, ignore_label = ignored).
    """
    a = anchor[0]  # (N, 4)
    n = a.shape[0]

    def per_image(lab, pred):
        valid = lab[:, 0] >= 0
        iou = _pair_iou(a, lab[:, 1:5])
        matches = _match_one(iou, valid, overlap_threshold)
        pos = matches >= 0
        m = jnp.maximum(matches, 0)
        gt = lab[m]  # (N, 5)
        a_c = _to_format(a, "center")
        g_c = _to_format(gt[:, 1:5], "center")
        t = _encode_offsets(a_c, g_c, variances)
        loc_t = jnp.where(pos[:, None], t, 0.0).reshape(-1)
        loc_m = jnp.where(pos[:, None], jnp.ones((n, 4)), jnp.zeros((n, 4))).reshape(-1)
        cls_t = jnp.where(pos, gt[:, 0] + 1.0, 0.0)
        if negative_mining_ratio > 0:
            # hard-negative mining: keep top (ratio * num_pos) negatives by
            # background-class "difficulty" (max non-bg prob), ignore the rest.
            # Anchors overlapping any ground truth above negative_mining_thresh
            # are never negative candidates (ref: multibox_target.cc) — they
            # are ignored instead of trained as background.
            num_pos = jnp.sum(pos)
            max_neg = jnp.maximum(num_pos * negative_mining_ratio,
                                  float(minimum_negative_samples))
            conf = jnp.max(pred[1:, :], axis=0)  # (N,) hardest-negative score
            max_iou = jnp.max(jnp.where(valid[None, :], iou, 0.0), axis=1)
            neg = ~pos & (max_iou < negative_mining_thresh)
            neg_score = jnp.where(neg, conf, -jnp.inf)
            rank = jnp.argsort(jnp.argsort(-neg_score))  # rank 0 = hardest
            keep_neg = neg & (rank < max_neg)
            cls_t = jnp.where(~pos & ~keep_neg, float(ignore_label), cls_t)
        return loc_t, loc_m, cls_t

    loc_t, loc_m, cls_t = jax.vmap(per_image)(label, cls_pred)
    return loc_t, loc_m, cls_t


@register("_contrib_MultiBoxDetection", aliases=("MultiBoxDetection",),
          no_grad_inputs=("cls_prob", "loc_pred", "anchor"))
def multibox_detection(cls_prob, loc_pred, anchor, *, clip=True, threshold=0.01,
                       background_id=0, nms_threshold=0.5, force_suppress=False,
                       variances=(0.1, 0.1, 0.2, 0.2), nms_topk=-1):
    """SSD decode + per-class NMS (ref: src/operator/contrib/multibox_detection.cc).

    cls_prob (B, num_cls+1, N), loc_pred (B, 4N), anchor (1, N, 4). Output
    (B, N, 6) rows [class_id, score, x1, y1, x2, y2], invalid rows -1.
    """
    a_c = _to_format(anchor[0], "center")  # (N, 4)
    n = a_c.shape[0]

    def per_image(prob, loc):
        # drop background row, pick best foreground class per anchor
        fg = jnp.concatenate([prob[:background_id], prob[background_id + 1:]], 0)
        cls = jnp.argmax(fg, axis=0).astype(jnp.float32)
        score = jnp.max(fg, axis=0)
        boxes = _to_corner(_decode_offsets(loc.reshape(n, 4), a_c, variances),
                           "center")
        if clip:
            boxes = jnp.clip(boxes, 0.0, 1.0)
        keep = score > threshold
        rows = jnp.concatenate([cls[:, None], score[:, None], boxes], -1)
        rows = jnp.where(keep[:, None], rows, -1.0)
        return _nms_one(rows, nms_threshold, 0.0, nms_topk, 2, 1, 0,
                        force_suppress, "corner", "corner")

    return jax.vmap(per_image)(cls_prob, loc_pred)


# ---------------------------------------------------------------------------
# ROI pooling / align
# ---------------------------------------------------------------------------


@register("ROIPooling", no_grad_inputs=("rois",))
def roi_pooling(data, rois, *, pooled_size, spatial_scale):
    """Max pooling over regions (ref: src/operator/roi_pooling.cc).

    data (B, C, H, W); rois (R, 5) rows [batch_idx, x1, y1, x2, y2] in image
    coordinates. Output (R, C, ph, pw). Mask-and-reduce formulation: bin
    membership masks over H and W replace the reference's scatter kernel.
    """
    ph, pw = (pooled_size if not np.isscalar(pooled_size)
              else (pooled_size, pooled_size))
    b, c, h, w = data.shape

    def one_roi(roi):
        bidx = roi[0].astype(jnp.int32)
        # std::round = half away from zero (ref roi_pooling.cc:69-72)
        x1 = _round_half_away(roi[1] * spatial_scale)
        y1 = _round_half_away(roi[2] * spatial_scale)
        x2 = _round_half_away(roi[3] * spatial_scale)
        y2 = _round_half_away(roi[4] * spatial_scale)
        rh = jnp.maximum(y2 - y1 + 1.0, 1.0)
        rw = jnp.maximum(x2 - x1 + 1.0, 1.0)
        bin_h, bin_w = rh / ph, rw / pw
        ys = jnp.arange(h, dtype=jnp.float32)
        xs = jnp.arange(w, dtype=jnp.float32)
        i = jnp.arange(ph, dtype=jnp.float32)
        j = jnp.arange(pw, dtype=jnp.float32)
        # bin i covers [floor(y1 + i*bin_h), ceil(y1 + (i+1)*bin_h))
        y_lo = jnp.floor(y1 + i[:, None] * bin_h)
        y_hi = jnp.ceil(y1 + (i[:, None] + 1) * bin_h)
        x_lo = jnp.floor(x1 + j[:, None] * bin_w)
        x_hi = jnp.ceil(x1 + (j[:, None] + 1) * bin_w)
        row_m = (ys[None, :] >= y_lo) & (ys[None, :] < y_hi)  # (ph, H)
        col_m = (xs[None, :] >= x_lo) & (xs[None, :] < x_hi)  # (pw, W)
        img = data[bidx]  # (C, H, W)
        neg = jnp.asarray(-jnp.inf, data.dtype)
        # reduce H per output row: (C, ph, H, W) -> (C, ph, W)
        rowred = jnp.where(row_m[None, :, :, None], img[:, None, :, :], neg)
        rowred = jnp.max(rowred, axis=2)
        # reduce W per output col: (C, ph, 1, W) vs (pw, W) -> (C, ph, pw)
        out = jnp.max(jnp.where(col_m[None, None, :, :], rowred[:, :, None, :],
                                neg), axis=3)
        return jnp.where(jnp.isfinite(out), out, 0.0)

    return jax.vmap(one_roi)(rois)


@register("Crop", optional=("crop_like",), no_grad_inputs=("crop_like",))
def crop_op(data, crop_like=None, *, offset=(0, 0), h_w=(0, 0),
            center_crop=False, num_args=1):
    """Legacy spatial crop of (N, C, H, W) to h_w or to crop_like's H/W,
    at (y, x) offset or centered (ref: src/operator/crop.cc)."""
    if crop_like is not None and num_args == 2:
        th, tw = int(crop_like.shape[2]), int(crop_like.shape[3])
    else:
        th, tw = int(h_w[0]), int(h_w[1])
    if th <= 0 or tw <= 0:
        raise ValueError("Crop: target size must be positive (set h_w or "
                         "pass crop_like with num_args=2)")
    h, w = data.shape[2], data.shape[3]
    if center_crop:
        oy, ox = (h - th) // 2, (w - tw) // 2
    else:
        oy, ox = int(offset[0]), int(offset[1])
    if not (0 <= oy and oy + th <= h and 0 <= ox and ox + tw <= w):
        raise ValueError(f"Crop: window {th}x{tw}@({oy},{ox}) outside "
                         f"{h}x{w}")
    return data[:, :, oy:oy + th, ox:ox + tw]


def _round_half_away(x):
    """C round(): halves go away from zero (jnp.round is banker's)."""
    return jnp.sign(x) * jnp.floor(jnp.abs(x) + 0.5)


@register("_contrib_PSROIPooling", aliases=("PSROIPooling",),
          no_grad_inputs=("rois",))
def psroi_pooling(data, rois, *, spatial_scale, output_dim, pooled_size,
                  group_size=0):
    """Position-sensitive ROI pooling, the R-FCN head
    (ref: src/operator/contrib/psroi_pooling.cc).

    data (B, output_dim*group^2, H, W); rois (R, 5). Output bin (i, j) of
    channel o AVERAGES the (o, gi, gj) channel page over the bin's
    region — mask-and-reduce like ROIPooling above (no scatter kernel)."""
    group = int(group_size) or int(pooled_size)
    p = int(pooled_size)
    b, c, h, w = data.shape
    o_dim = int(output_dim)

    def one_roi(roi):
        bidx = roi[0].astype(jnp.int32)
        # round(roi) + 1, half away from zero (ref psroi_pooling.cu:72-75)
        x1 = _round_half_away(roi[1]) * spatial_scale
        y1 = _round_half_away(roi[2]) * spatial_scale
        x2 = (_round_half_away(roi[3]) + 1.0) * spatial_scale
        y2 = (_round_half_away(roi[4]) + 1.0) * spatial_scale
        rh = jnp.maximum(y2 - y1, 0.1)
        rw = jnp.maximum(x2 - x1, 0.1)
        bin_h, bin_w = rh / p, rw / p
        ys = jnp.arange(h, dtype=jnp.float32)
        xs = jnp.arange(w, dtype=jnp.float32)
        i = jnp.arange(p, dtype=jnp.float32)
        y_lo = jnp.floor(y1 + i[:, None] * bin_h)
        y_hi = jnp.ceil(y1 + (i[:, None] + 1.0) * bin_h)
        x_lo = jnp.floor(x1 + i[:, None] * bin_w)
        x_hi = jnp.ceil(x1 + (i[:, None] + 1.0) * bin_w)
        row_m = (ys[None, :] >= y_lo) & (ys[None, :] < y_hi)  # (p, H)
        col_m = (xs[None, :] >= x_lo) & (xs[None, :] < x_hi)  # (p, W)
        img = data[bidx].reshape(o_dim, group, group, h, w)
        gi = jnp.clip((i.astype(jnp.int32) * group) // p, 0, group - 1)
        pages = img[:, gi][:, :, gi]  # (O, p, p, H, W): bin -> its page
        m2 = (row_m[:, None, :, None] & col_m[None, :, None, :])  # (p,p,H,W)
        num = jnp.sum(jnp.where(m2[None], pages, 0.0), axis=(-1, -2))
        cnt = jnp.maximum(jnp.sum(m2, axis=(-1, -2)), 1).astype(data.dtype)
        return num / cnt[None]  # (O, p, p); empty bins -> 0

    return jax.vmap(one_roi)(rois)


@register("_contrib_DeformablePSROIPooling",
          aliases=("DeformablePSROIPooling",), optional=("trans",),
          no_grad_inputs=("rois",), num_outputs=1)
def deformable_psroi_pooling(data, rois, trans=None, *, spatial_scale,
                             output_dim, pooled_size, group_size=0,
                             part_size=0, sample_per_part=4,
                             trans_std=0.0, no_trans=False):
    """Deformable position-sensitive ROI pooling (Deformable R-FCN head,
    ref: src/operator/contrib/deformable_psroi_pooling.cc).

    Each bin averages sample_per_part^2 bilinear taps; `trans` holds
    per-(class, bin) offsets in roi-size units, scaled by trans_std.
    With no_trans/absent trans this is the sampled form of PSROIPooling."""
    group = int(group_size) or int(pooled_size)
    p = int(pooled_size)
    part = int(part_size) or p
    s = int(sample_per_part)
    b, c, h, w = data.shape
    o_dim = int(output_dim)
    use_trans = (trans is not None) and not no_trans
    n_cls = (trans.shape[1] // 2) if use_trans else 1
    per_cls = max(o_dim // n_cls, 1)

    def one_roi(roi, tr):
        bidx = roi[0].astype(jnp.int32)
        x1 = _round_half_away(roi[1]) * spatial_scale - 0.5
        y1 = _round_half_away(roi[2]) * spatial_scale - 0.5
        x2 = (_round_half_away(roi[3]) + 1.0) * spatial_scale - 0.5
        y2 = (_round_half_away(roi[4]) + 1.0) * spatial_scale - 0.5
        rh = jnp.maximum(y2 - y1, 0.1)
        rw = jnp.maximum(x2 - x1, 0.1)
        bin_h, bin_w = rh / p, rw / p
        sub_h, sub_w = bin_h / s, bin_w / s
        i = jnp.arange(p, dtype=jnp.float32)
        # taps at iw * sub_bin from the bin start — no half-sub-bin center
        # offset (ref deformable_psroi_pooling.cu:144-145)
        u = jnp.arange(s, dtype=jnp.float32)
        # base tap grid per bin: (p, s) each axis
        ys0 = y1 + i[:, None] * bin_h + u[None, :] * sub_h
        xs0 = x1 + i[:, None] * bin_w + u[None, :] * sub_w
        # per-(class, bin) offsets from the part grid
        if use_trans:
            pi = jnp.clip((i.astype(jnp.int32) * part) // p, 0, part - 1)
            t = tr.reshape(n_cls, 2, part, part)
            off_y = t[:, 1][:, pi][:, :, pi] * trans_std  # (cls, p, p)
            off_x = t[:, 0][:, pi][:, :, pi] * trans_std
        else:
            off_y = jnp.zeros((1, p, p), jnp.float32)
            off_x = jnp.zeros((1, p, p), jnp.float32)
        # tap coords: (cls, p_i, p_j, s_i, s_j)
        ty = (ys0[None, :, None, :, None] + (off_y * rh)[:, :, :, None, None])
        tx = (xs0[None, None, :, None, :] + (off_x * rw)[:, :, :, None, None])
        ty = jnp.broadcast_to(ty, (n_cls, p, p, s, s))
        tx = jnp.broadcast_to(tx, (n_cls, p, p, s, s))
        img = data[bidx].reshape(o_dim, group, group, h, w)
        gi = jnp.clip((i.astype(jnp.int32) * group) // p, 0, group - 1)
        pages = img[:, gi][:, :, gi]  # (O, p, p, H, W)

        def sample_o(page, cls_id):
            # page (p, p, H, W); taps (p, p, s, s). A tap outside
            # [-0.5, dim-0.5] is skipped from BOTH the sum and the count;
            # in-range taps are clamped to [0, dim-1] before bilinear
            # sampling (ref deformable_psroi_pooling.cu:147-158).
            yy, xx = ty[cls_id], tx[cls_id]
            valid = ((yy >= -0.5) & (yy <= h - 0.5)
                     & (xx >= -0.5) & (xx <= w - 0.5))
            yy = jnp.clip(yy, 0.0, h - 1.0)
            xx = jnp.clip(xx, 0.0, w - 1.0)
            y0 = jnp.floor(yy)
            x0 = jnp.floor(xx)
            wy1, wx1 = yy - y0, xx - x0

            def tap(yi, xi, wgt):
                yc = jnp.clip(yi, 0, h - 1).astype(jnp.int32)
                xc = jnp.clip(xi, 0, w - 1).astype(jnp.int32)
                v = jnp.take_along_axis(
                    jnp.take_along_axis(
                        page[:, :, :, None, None, :],
                        yc[:, :, None, :, :, None].astype(jnp.int32), axis=2),
                    xc[:, :, None, :, :, None].astype(jnp.int32), axis=5)
                return v[:, :, 0, :, :, 0] * wgt

            out = (tap(y0, x0, (1 - wy1) * (1 - wx1))
                   + tap(y0, x0 + 1, (1 - wy1) * wx1)
                   + tap(y0 + 1, x0, wy1 * (1 - wx1))
                   + tap(y0 + 1, x0 + 1, wy1 * wx1))
            cnt = jnp.sum(valid, axis=(-1, -2)).astype(page.dtype)  # (p, p)
            tot = jnp.sum(out * valid.astype(page.dtype), axis=(-1, -2))
            return jnp.where(cnt > 0, tot / jnp.maximum(cnt, 1.0), 0.0)

        cls_ids = jnp.arange(o_dim, dtype=jnp.int32) // per_cls
        cls_ids = jnp.clip(cls_ids, 0, n_cls - 1)
        return jax.vmap(sample_o)(pages, cls_ids)  # (O, p, p)

    if use_trans:
        return jax.vmap(one_roi)(rois, trans)
    dummy = jnp.zeros((rois.shape[0], 2, part, part), jnp.float32)
    return jax.vmap(one_roi)(rois, dummy)


def _bilinear_gather(img, ys, xs):
    """Bilinear sample img (C, H, W) at float coords ys/xs (...,) with zero pad."""
    c, h, w = img.shape
    y0 = jnp.floor(ys)
    x0 = jnp.floor(xs)
    wy1, wx1 = ys - y0, xs - x0
    wy0, wx0 = 1.0 - wy1, 1.0 - wx1

    def tap(yi, xi, wgt):
        inb = (yi >= 0) & (yi < h) & (xi >= 0) & (xi < w)
        yc = jnp.clip(yi, 0, h - 1).astype(jnp.int32)
        xc = jnp.clip(xi, 0, w - 1).astype(jnp.int32)
        v = img[:, yc, xc]  # (C, ...)
        return v * (wgt * inb.astype(img.dtype))

    return (tap(y0, x0, wy0 * wx0) + tap(y0, x0 + 1, wy0 * wx1)
            + tap(y0 + 1, x0, wy1 * wx0) + tap(y0 + 1, x0 + 1, wy1 * wx1))


@register("_contrib_ROIAlign", aliases=("ROIAlign",), no_grad_inputs=("rois",))
def roi_align(data, rois, *, pooled_size, spatial_scale, sample_ratio=-1,
              position_sensitive=False, aligned=False):
    """ROIAlign (ref: src/operator/contrib/roi_align.cc). Average of bilinear
    samples on a fixed sub-grid per bin. The reference's adaptive sample
    count (ceil(roi/bin)) is data-dependent; on TPU we fix it to 2 when
    sample_ratio <= 0 so shapes stay static. position_sensitive=True gives
    the R-FCN PS-ROIAlign layout: input channels C = C_out*ph*pw, bin (i, j)
    reads channel group c_out*ph*pw + i*pw + j.
    """
    ph, pw = (pooled_size if not np.isscalar(pooled_size)
              else (pooled_size, pooled_size))
    s = int(sample_ratio) if sample_ratio > 0 else 2
    offset = 0.5 if aligned else 0.0

    def one_roi(roi):
        bidx = roi[0].astype(jnp.int32)
        x1 = roi[1] * spatial_scale - offset
        y1 = roi[2] * spatial_scale - offset
        x2 = roi[3] * spatial_scale - offset
        y2 = roi[4] * spatial_scale - offset
        rw = x2 - x1 if aligned else jnp.maximum(x2 - x1, 1.0)
        rh = y2 - y1 if aligned else jnp.maximum(y2 - y1, 1.0)
        bin_h, bin_w = rh / ph, rw / pw
        i = jnp.arange(ph, dtype=jnp.float32)[:, None]
        k = (jnp.arange(s, dtype=jnp.float32) + 0.5) / s
        ys = (y1 + (i + k[None, :]) * bin_h).reshape(-1)  # (ph*s,)
        j = jnp.arange(pw, dtype=jnp.float32)[:, None]
        xs = (x1 + (j + k[None, :]) * bin_w).reshape(-1)  # (pw*s,)
        yg = jnp.repeat(ys, pw * s)
        xg = jnp.tile(xs, ph * s)
        # reference boundary semantics (roi_align.cc bilinear_interpolate):
        # a sample beyond [-1, dim] is zero; within that margin it CLAMPS
        # to the edge (continuous at the border), unlike plain zero-pad
        h, w = data.shape[2], data.shape[3]
        valid = ((yg >= -1.0) & (yg <= h) & (xg >= -1.0) & (xg <= w))
        yg = jnp.clip(yg, 0.0, h - 1.0)
        xg = jnp.clip(xg, 0.0, w - 1.0)
        v = _bilinear_gather(data[bidx], yg, xg)  # (C, ph*s*pw*s)
        v = v * valid.astype(v.dtype)[None, :]
        v = v.reshape(v.shape[0], ph, s, pw, s)
        full = jnp.mean(v, axis=(2, 4))  # (C, ph, pw)
        if not position_sensitive:
            return full
        c_out = full.shape[0] // (ph * pw)
        g = full.reshape(c_out, ph, pw, ph, pw)
        i = jnp.arange(ph)[:, None]
        j = jnp.arange(pw)[None, :]
        return g[:, i, j, i, j]  # (C_out, ph, pw): bin (i,j) from its own group

    return jax.vmap(one_roi)(rois)


# ---------------------------------------------------------------------------
# samplers / transformers
# ---------------------------------------------------------------------------


@register("BilinearSampler")
def bilinear_sampler(data, grid, *, cudnn_off=False):
    """Sample data with a normalized flow grid
    (ref: src/operator/bilinear_sampler.cc). data (B, C, H, W),
    grid (B, 2, H', W') with grid[:,0]=x, grid[:,1]=y in [-1, 1];
    out-of-bounds reads are zero (matches the reference's zero padding).
    """
    b, c, h, w = data.shape
    xs = (grid[:, 0] + 1.0) * (w - 1) / 2.0
    ys = (grid[:, 1] + 1.0) * (h - 1) / 2.0
    return jax.vmap(_bilinear_gather)(data, ys, xs)


@register("GridGenerator")
def grid_generator(data, *, transform_type="affine", target_shape=(0, 0)):
    """Generate sampling grids (ref: src/operator/grid_generator.cc).

    affine: data (B, 6) -> grid (B, 2, H, W) from target_shape.
    warp:   data (B, 2, H, W) optical flow added to the identity grid.
    """
    if transform_type == "affine":
        h, w = int(target_shape[0]), int(target_shape[1])
        theta = data.reshape(-1, 2, 3)
        yt, xt = jnp.meshgrid(jnp.linspace(-1.0, 1.0, h),
                              jnp.linspace(-1.0, 1.0, w), indexing="ij")
        ones = jnp.ones_like(xt)
        src = jnp.stack([xt, yt, ones], 0).reshape(3, -1)  # (3, H*W)
        out = jnp.einsum("bij,jk->bik", theta, src)  # (B, 2, H*W)
        return out.reshape(-1, 2, h, w)
    if transform_type == "warp":
        b, _, h, w = data.shape
        yg, xg = jnp.meshgrid(jnp.arange(h, dtype=data.dtype),
                              jnp.arange(w, dtype=data.dtype), indexing="ij")
        x = (xg[None] + data[:, 0]) * 2.0 / jnp.maximum(w - 1, 1) - 1.0
        y = (yg[None] + data[:, 1]) * 2.0 / jnp.maximum(h - 1, 1) - 1.0
        return jnp.stack([x, y], 1)
    raise ValueError(f"unknown transform_type {transform_type}")


@register("SpatialTransformer")
def spatial_transformer(data, loc, *, target_shape=(0, 0),
                        transform_type="affine", sampler_type="bilinear",
                        cudnn_off=False):
    """Affine spatial transformer network op
    (ref: src/operator/spatial_transformer.cc): loc (B, 6) affine params ->
    grid -> bilinear sample of data.
    """
    if sampler_type != "bilinear":  # the reference supports only bilinear too
        raise ValueError(f"sampler_type must be 'bilinear', got {sampler_type}")
    grid = grid_generator(loc, transform_type=transform_type,
                          target_shape=target_shape)
    return bilinear_sampler(data, grid)


@register("Correlation", num_outputs=1)
def correlation(data1, data2, *, kernel_size=1, max_displacement=1, stride1=1,
                stride2=1, pad_size=0, is_multiply=True):
    """FlowNet correlation layer (ref: src/operator/correlation.cc).

    Static-displacement formulation: one fused elementwise-mean per
    displacement (Python loop unrolls into the XLA graph; the displacement
    set is a compile-time constant).
    """
    b, c, h, w = data1.shape
    pad = int(pad_size)
    d1 = jnp.pad(data1, ((0, 0), (0, 0), (pad, pad), (pad, pad)))
    d2 = jnp.pad(data2, ((0, 0), (0, 0), (pad, pad), (pad, pad)))
    hp, wp = h + 2 * pad, w + 2 * pad
    k = int(kernel_size)
    rad = k // 2
    nbh = int(max_displacement) // int(stride2)
    border = rad + int(max_displacement)
    out_h = int(math.ceil((hp - border * 2) / float(stride1)))
    out_w = int(math.ceil((wp - border * 2) / float(stride1)))
    ys = border + jnp.arange(out_h) * stride1
    xs = border + jnp.arange(out_w) * stride1

    maps = []
    for dy in range(-nbh, nbh + 1):
        for dx in range(-nbh, nbh + 1):
            oy, ox = dy * stride2, dx * stride2
            if is_multiply:
                prod = d1 * jnp.roll(d2, shift=(-oy, -ox), axis=(2, 3))
            else:
                prod = jnp.abs(d1 - jnp.roll(d2, shift=(-oy, -ox), axis=(2, 3)))
            if k > 1:
                prod = lax.reduce_window(
                    prod, 0.0, lax.add, (1, 1, k, k), (1, 1, 1, 1), "SAME"
                ) / (k * k)
            m = jnp.mean(prod, axis=1)  # (B, Hp, Wp) — the k*k*C normalizer
            maps.append(m[:, ys][:, :, xs])
    return jnp.stack(maps, axis=1)


# ---------------------------------------------------------------------------
# resize / adaptive pooling (gluon-cv support ops)
# ---------------------------------------------------------------------------


@register("_contrib_BilinearResize2D", aliases=("BilinearResize2D",))
def bilinear_resize_2d(data, *, height=0, width=0, scale_height=None,
                       scale_width=None, mode="size"):
    """Bilinear resize (ref: src/operator/contrib/bilinear_resize.cc).

    Uses the reference's align_corners=True convention: source coordinate
    i * (H-1)/(oH-1) (jax.image.resize's half-pixel convention differs).
    """
    if mode != "size":
        raise NotImplementedError(
            f"BilinearResize2D mode='{mode}': only explicit size/scale "
            f"resizing is implemented (the like/odd_scale/to_even_* size "
            f"derivations are not)")
    b, c, h, w = data.shape
    oh = int(height) if height else int(round(h * (scale_height or 1.0)))
    ow = int(width) if width else int(round(w * (scale_width or 1.0)))
    ys = jnp.linspace(0.0, h - 1.0, oh)
    xs = jnp.linspace(0.0, w - 1.0, ow)
    yg = jnp.repeat(ys, ow)
    xg = jnp.tile(xs, oh)
    out = jax.vmap(lambda img: _bilinear_gather(img, yg, xg))(data)
    return out.reshape(b, c, oh, ow)


@register("_contrib_AdaptiveAvgPooling2D", aliases=("AdaptiveAvgPooling2D",))
def adaptive_avg_pooling_2d(data, *, output_size=(1, 1)):
    """Adaptive average pooling (ref: src/operator/contrib/adaptive_avg_pooling.cc)."""
    if np.isscalar(output_size):
        output_size = (int(output_size), int(output_size))
    oh, ow = int(output_size[0]), int(output_size[1])
    b, c, h, w = data.shape
    # integer bin boundaries identical to the reference's start/end formula
    ys = [(int(math.floor(i * h / oh)), int(math.ceil((i + 1) * h / oh)))
          for i in range(oh)]
    xs = [(int(math.floor(j * w / ow)), int(math.ceil((j + 1) * w / ow)))
          for j in range(ow)]
    rows = [jnp.mean(data[:, :, y0:y1, :], axis=2, keepdims=True)
            for (y0, y1) in ys]
    col_pooled = jnp.concatenate(rows, axis=2)  # (B, C, oh, W)
    cols = [jnp.mean(col_pooled[:, :, :, x0:x1], axis=3, keepdims=True)
            for (x0, x1) in xs]
    return jnp.concatenate(cols, axis=3)


# ---------------------------------------------------------------------------
# Deformable convolution (ref: src/operator/contrib/deformable_convolution.cc,
# Dai et al. 2017). TPU formulation: the deformable im2col becomes a batched
# bilinear gather building (B, C*kh*kw, H', W'), and the convolution itself
# collapses to one big matmul on the MXU — no scatter/atomics.
# ---------------------------------------------------------------------------


@register("_contrib_DeformableConvolution", aliases=("DeformableConvolution",),
          optional=("bias",))
def deformable_convolution(data, offset, weight, bias=None, *, kernel,
                           num_filter, stride=(1, 1), pad=(0, 0),
                           dilate=(1, 1), num_deformable_group=1,
                           num_group=1, no_bias=False, workspace=1024,
                           layout="NCHW"):
    """data (B, C, H, W); offset (B, 2*kh*kw*num_deformable_group, H', W');
    weight (num_filter, C/num_group, kh, kw). Output (B, num_filter, H', W').
    Offsets are (dy, dx) per kernel tap, per deformable group.
    """
    if layout != "NCHW":
        raise ValueError("DeformableConvolution supports layout='NCHW' only "
                         "(matches the reference op)")
    kh, kw = int(kernel[0]), int(kernel[1])
    sh, sw = int(stride[0]), int(stride[1])
    ph, pw = int(pad[0]), int(pad[1])
    dh, dw = int(dilate[0]), int(dilate[1])
    b, c, h, w = data.shape
    oh = (h + 2 * ph - dh * (kh - 1) - 1) // sh + 1
    ow = (w + 2 * pw - dw * (kw - 1) - 1) // sw + 1
    ndg = int(num_deformable_group)
    cg = c // ndg

    # base sampling locations per output position and tap (in padded coords,
    # shifted back by pad to input coords)
    oy = jnp.arange(oh, dtype=jnp.float32) * sh - ph
    ox = jnp.arange(ow, dtype=jnp.float32) * sw - pw
    ky = jnp.arange(kh, dtype=jnp.float32) * dh
    kx = jnp.arange(kw, dtype=jnp.float32) * dw
    base_y = oy[:, None, None, None] + ky[None, None, :, None]  # (oh,1,kh,1)
    base_x = ox[None, :, None, None] + kx[None, None, None, :]  # (1,ow,1,kw)
    base_y = jnp.broadcast_to(base_y, (oh, ow, kh, kw))
    base_x = jnp.broadcast_to(base_x, (oh, ow, kh, kw))

    off = offset.reshape(b, ndg, kh, kw, 2, oh, ow)

    def one_image(img, off_i):
        cols = []
        for g in range(ndg):  # static loop over deformable groups
            dy = jnp.transpose(off_i[g, :, :, 0], (2, 3, 0, 1))  # (oh,ow,kh,kw)
            dx = jnp.transpose(off_i[g, :, :, 1], (2, 3, 0, 1))
            ys = (base_y + dy).reshape(-1)
            xs = (base_x + dx).reshape(-1)
            v = _bilinear_gather(img[g * cg:(g + 1) * cg], ys, xs)
            cols.append(v.reshape(cg, oh, ow, kh, kw))
        col = jnp.concatenate(cols, axis=0)  # (C, oh, ow, kh, kw)
        return jnp.transpose(col, (0, 3, 4, 1, 2))  # (C, kh, kw, oh, ow)

    col = jax.vmap(one_image)(data, off)  # (B, C, kh, kw, oh, ow)

    cpg = c // num_group
    fpg = num_filter // num_group
    col = col.reshape(b, num_group, cpg * kh * kw, oh * ow)
    wmat = weight.reshape(num_group, fpg, cpg * kh * kw)
    out = jnp.einsum("bgkp,gfk->bgfp", col, wmat)
    out = out.reshape(b, num_filter, oh, ow)
    if bias is not None and not no_bias:
        out = out + bias.reshape(1, -1, 1, 1)
    return out


# ---------------------------------------------------------------------------
# RPN proposals (Faster-RCNN)
# ---------------------------------------------------------------------------


def _make_anchors(h, w, stride, scales, ratios):
    """Anchor grid (A*h*w, 4) corners, matching the reference's generation
    (ref: src/operator/contrib/proposal.cc GenerateAnchors): base box of
    `stride` size at each cell, per ratio then per scale."""
    base = stride - 1.0
    ctr = base / 2.0
    size = stride * stride
    anchors = []
    for r in ratios:
        size_r = size / r
        ws = np.round(np.sqrt(size_r))
        hs = np.round(ws * r)
        for s in scales:
            wss, hss = ws * s, hs * s
            anchors.append([ctr - 0.5 * (wss - 1), ctr - 0.5 * (hss - 1),
                            ctr + 0.5 * (wss - 1), ctr + 0.5 * (hss - 1)])
    A = len(anchors)
    anchors = jnp.asarray(anchors, jnp.float32)  # (A, 4)
    sx = (jnp.arange(w, dtype=jnp.float32) * stride)
    sy = (jnp.arange(h, dtype=jnp.float32) * stride)
    shift = jnp.stack(jnp.meshgrid(sx, sy), axis=-1)        # (h, w, 2)
    shift = jnp.concatenate([shift, shift], axis=-1)        # (h, w, 4)
    all_a = anchors[None, None] + shift[:, :, None]         # (h, w, A, 4)
    return all_a.reshape(-1, 4), A


def _proposal_one(score, bbox, im_info, anchors, *, pre_top, post_top,
                  nms_thresh, min_size, stride):
    """One image's RPN proposals (ref: proposal.cc ProposalOp::Forward).
    score (A*h*w,), bbox deltas (A*h*w, 4), anchors (A*h*w, 4)."""
    height, width, im_scale = im_info[0], im_info[1], im_info[2]
    # decode: deltas are (dx, dy, dw, dh) on center format
    aw = anchors[:, 2] - anchors[:, 0] + 1.0
    ah = anchors[:, 3] - anchors[:, 1] + 1.0
    ax = anchors[:, 0] + 0.5 * (aw - 1.0)
    ay = anchors[:, 1] + 0.5 * (ah - 1.0)
    cx = bbox[:, 0] * aw + ax
    cy = bbox[:, 1] * ah + ay
    pw = jnp.exp(jnp.clip(bbox[:, 2], -10, 10)) * aw
    ph = jnp.exp(jnp.clip(bbox[:, 3], -10, 10)) * ah
    x1 = jnp.clip(cx - 0.5 * (pw - 1.0), 0, width - 1.0)
    y1 = jnp.clip(cy - 0.5 * (ph - 1.0), 0, height - 1.0)
    x2 = jnp.clip(cx + 0.5 * (pw - 1.0), 0, width - 1.0)
    y2 = jnp.clip(cy + 0.5 * (ph - 1.0), 0, height - 1.0)
    # min-size filter in input-image scale
    ms = min_size * im_scale
    keep = ((x2 - x1 + 1.0) >= ms) & ((y2 - y1 + 1.0) >= ms)
    score = jnp.where(keep, score, -1.0)
    # pre-NMS topk
    k = min(pre_top, score.shape[0]) if pre_top > 0 else score.shape[0]
    top_scores, top_idx = lax.top_k(score, k)
    rows = jnp.stack([jnp.zeros_like(top_scores), top_scores,
                      x1[top_idx], y1[top_idx], x2[top_idx], y2[top_idx]],
                     axis=1)
    rows = jnp.where(top_scores[:, None] > -1.0, rows, -1.0)
    kept = _nms_one(rows, nms_thresh, -1.0, -1, 2, 1, -1, True,
                    "corner", "corner")
    # compact survivors (suppressed rows are -1 holes), then take the
    # post-NMS top; short batches pad with duplicates of the best proposal
    # (the reference pads the same way)
    order = jnp.argsort(-kept[:, 1])
    kept = kept[order]
    if kept.shape[0] < post_top:  # fewer candidates than the quota
        pad_n = post_top - kept.shape[0]
        kept = jnp.concatenate(
            [kept, jnp.tile(kept[0][None], (pad_n, 1))], axis=0)
    post = kept[:post_top]
    invalid = post[:, 1] < 0
    post = jnp.where(invalid[:, None], kept[0][None, :], post)
    return post[:, 2:6], post[:, 1]


@register("_contrib_Proposal", aliases=("_contrib_MultiProposal", "Proposal"),
          num_outputs=lambda attrs: 2 if attrs.get("output_score") else 1,
          no_grad_inputs=("cls_prob", "bbox_pred", "im_info"))
def proposal(cls_prob, bbox_pred, im_info, *, rpn_pre_nms_top_n=6000,
             rpn_post_nms_top_n=300, threshold=0.7, rpn_min_size=16,
             scales=(4, 8, 16, 32), ratios=(0.5, 1, 2),
             feature_stride=16, output_score=False, iou_loss=False):
    """RPN proposal generation (ref: src/operator/contrib/proposal.cc:1 and
    multi_proposal.cc — both served here since the computation vmaps over
    the batch).

    cls_prob (B, 2A, H, W) — second half holds foreground scores;
    bbox_pred (B, 4A, H, W); im_info (B, 3) rows (height, width, scale).
    Returns rois (B*rpn_post_nms_top_n, 5) [batch_idx, x1, y1, x2, y2]
    (+ scores (B*rpn_post_nms_top_n, 1) when output_score).
    """
    if iou_loss:
        raise NotImplementedError(
            "iou_loss=True decoding (x1,y1,x2,y2 deltas) is not implemented; "
            "retrain the RPN with the standard transform or decode manually")
    b, a2, h, w = cls_prob.shape
    A = a2 // 2
    anchors, A2 = _make_anchors(h, w, feature_stride, scales, ratios)
    assert A2 == A, f"anchor count {A2} != cls_prob channels//2 {A}"
    # (B, A, h, w) fg scores -> (B, h*w*A) matching anchor enumeration
    fg = cls_prob[:, A:].transpose(0, 2, 3, 1).reshape(b, -1)
    deltas = (bbox_pred.reshape(b, A, 4, h, w)
              .transpose(0, 3, 4, 1, 2).reshape(b, -1, 4))

    def one(score_i, delta_i, info_i):
        return _proposal_one(
            score_i, delta_i, info_i, anchors,
            pre_top=int(rpn_pre_nms_top_n), post_top=int(rpn_post_nms_top_n),
            nms_thresh=float(threshold), min_size=float(rpn_min_size),
            stride=feature_stride)

    boxes, scores = jax.vmap(one)(fg, deltas, im_info)
    batch_idx = jnp.repeat(jnp.arange(b, dtype=jnp.float32),
                           int(rpn_post_nms_top_n))
    rois = jnp.concatenate([batch_idx[:, None], boxes.reshape(-1, 4)], axis=1)
    if output_score:
        return rois, scores.reshape(-1, 1)
    return rois


# --- nd.image.* op names (ref: src/operator/image/image_random.cc +
# image_resize.cc — the _image_* registry spellings) ------------------------


@register("_image_to_tensor")
def _image_to_tensor(data):
    """HWC (or NHWC) [0,255] -> CHW (NCHW) float32 [0,1]
    (ref: image_random.cc ToTensor)."""
    x = data.astype(jnp.float32) / 255.0
    if x.ndim == 3:
        return jnp.transpose(x, (2, 0, 1))
    return jnp.transpose(x, (0, 3, 1, 2))


@register("_image_normalize")
def _image_normalize(data, *, mean=(0.0,), std=(1.0,)):
    """Channel-wise (x - mean) / std on CHW/NCHW floats
    (ref: image_random.cc Normalize)."""
    c_axis = 0 if data.ndim == 3 else 1
    shape = [1] * data.ndim
    shape[c_axis] = -1
    m = jnp.asarray(mean, data.dtype).reshape(shape)
    s = jnp.asarray(std, data.dtype).reshape(shape)
    return (data - m) / s


@register("_image_resize")
def _image_resize(data, *, size, keep_ratio=False, interp=1):
    """Bilinear/nearest resize of HWC or NHWC images
    (ref: image_resize.cc Resize)."""
    method = "nearest" if interp == 0 else "bilinear"
    ih, iw = (data.shape[0], data.shape[1]) if data.ndim == 3 \
        else (data.shape[1], data.shape[2])
    if isinstance(size, int):
        if keep_ratio:
            # MXNet semantics (resize-inl.h): int size + keep_ratio fits
            # the SHORT edge to `size`, long edge keeps the aspect ratio
            if ih > iw:
                w, h = size, int(ih * size / iw)
            else:
                w, h = int(iw * size / ih), size
        else:
            w = h = size
    else:
        # tuple size is exact; MXNet ignores keep_ratio here
        w, h = int(size[0]), int(size[1])
    if data.ndim == 3:
        return jax.image.resize(data, (h, w, data.shape[2]), method)
    return jax.image.resize(data, (data.shape[0], h, w, data.shape[3]),
                            method)
