"""Contrib operators: FFT, count_sketch, quadratic, hawkes, group norm.

TPU-native coverage of the reference's misc contrib ops
(ref: SURVEY §2 N29 — src/operator/contrib/{fft,count_sketch,quadratic}*).
The reference's cuFFT / custom-CUDA kernels become jnp.fft / one-hot matmul
formulations that XLA lowers for the MXU/VPU.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from .registry import register


@register("_contrib_fft")
def fft(data, *, compute_size=128):
    """Forward FFT (ref: src/operator/contrib/fft.cc `_contrib_fft`).

    Input (..., d) real; output (..., 2d) with interleaved real/imag parts,
    matching the reference's cuFFT output layout. compute_size (the
    reference's batching knob) is accepted but irrelevant under XLA.
    """
    out = jnp.fft.fft(data.astype(jnp.complex64), axis=-1)
    inter = jnp.stack([jnp.real(out), jnp.imag(out)], axis=-1)
    return inter.reshape(data.shape[:-1] + (2 * data.shape[-1],)).astype(jnp.float32)


@register("_contrib_ifft")
def ifft(data, *, compute_size=128):
    """Inverse FFT (ref: src/operator/contrib/ifft.cc). Input (..., 2d)
    interleaved real/imag; output (..., d) real. Like the reference (cuFFT
    unnormalized), the output is NOT divided by d."""
    d = data.shape[-1] // 2
    pairs = data.reshape(data.shape[:-1] + (d, 2))
    comp = pairs[..., 0] + 1j * pairs[..., 1]
    return (jnp.real(jnp.fft.ifft(comp, axis=-1)) * d).astype(jnp.float32)


@register("_contrib_count_sketch", no_grad_inputs=("h", "s"))
def count_sketch(data, h, s, *, out_dim, processing_batch_size=32):
    """Count-sketch projection (ref: src/operator/contrib/count_sketch.cc).

    data (N, d); h (d,) hash bucket per input dim in [0, out_dim);
    s (d,) signs in {+1, -1}. out[n, h[i]] += s[i] * data[n, i].
    Scatter-add becomes a one-hot matmul so it rides the MXU instead of the
    reference's atomic-add CUDA kernel.
    """
    hh = h.reshape(-1).astype(jnp.int32)
    ss = s.reshape(-1).astype(data.dtype)
    onehot = jax.nn.one_hot(hh, int(out_dim), dtype=data.dtype)  # (d, out)
    return jnp.matmul(data * ss[None, :], onehot)


@register("_contrib_quadratic")
def quadratic(data, *, a=0.0, b=0.0, c=0.0):
    """Elementwise a*x^2 + b*x + c — the reference's tutorial custom op
    (ref: src/operator/contrib/quadratic_op.cc)."""
    return a * data * data + b * data + c


@register("GroupNorm")
def group_norm(data, gamma, beta, *, num_groups=1, eps=1e-5):
    """Group normalization (ref: src/operator/nn/group_norm.cc, v1.6).

    data (N, C, ...); normalizes over each of num_groups channel groups.
    """
    n = data.shape[0]
    c = data.shape[1]
    g = int(num_groups)
    x = data.reshape((n, g, c // g) + data.shape[2:])
    axes = tuple(range(2, x.ndim))
    mean = jnp.mean(x, axis=axes, keepdims=True)
    var = jnp.var(x, axis=axes, keepdims=True)
    x = (x - mean) * jax.lax.rsqrt(var + eps)
    x = x.reshape(data.shape)
    bshape = (1, c) + (1,) * (data.ndim - 2)
    return x * gamma.reshape(bshape) + beta.reshape(bshape)


@register("_contrib_hawkesll", num_outputs=2, no_grad_inputs=("state",))
def hawkesll(lda, alpha, beta, state, lags, marks, valid_length, *, ignore=None):
    """Hawkes-process log-likelihood is niche (ref:
    src/operator/contrib/hawkes_ll.cc); provided as a jnp composition.

    Simplified parity: returns (loglik (N,), new_state). lda (N,K) background
    intensity, alpha/beta (K,), lags/marks (N,T), valid_length (N,).
    """
    n, t = lags.shape
    k = lda.shape[1]
    marks_i = marks.astype(jnp.int32)

    def one_seq(lda_i, st_i, lag_i, mk_i, vl_i):
        def step(carry, inp):
            st, ll = carry
            lag, mk, idx = inp
            valid = idx < vl_i
            decayed = st * jnp.exp(-beta * lag)
            lam = lda_i[mk] + alpha[mk] * decayed[mk]
            ll = ll + jnp.where(valid, jnp.log(jnp.maximum(lam, 1e-20)), 0.0)
            # padding steps must leave the state untouched (decay included)
            st = jnp.where(valid,
                           decayed.at[mk].add(beta[mk]).astype(st.dtype), st)
            return (st, ll), None

        (st, ll), _ = jax.lax.scan(
            step, (st_i, 0.0),
            (lag_i, mk_i, jnp.arange(t)))
        # compensator over the observation window (sum of lags as horizon)
        horizon = jnp.sum(jnp.where(jnp.arange(t) < vl_i, lag_i, 0.0))
        ll = ll - jnp.sum(lda_i) * horizon
        return ll, st

    ll, new_state = jax.vmap(one_seq)(lda, state, lags, marks_i, valid_length)
    return ll, new_state


@register("_contrib_SyncBatchNorm", aux=("moving_mean", "moving_var"),
          needs_training=True)
def sync_batch_norm(data, gamma, beta, moving_mean, moving_var, *,
                    eps=1e-3, momentum=0.9, fix_gamma=True,
                    use_global_stats=False, output_mean_var=False,
                    ndev=1, axis_name=None, key="", _training=False):
    """Cross-replica batch norm (ref: src/operator/contrib/sync_batch_norm.cc).

    TPU-native stance: under pjit with a globally-sharded batch, plain
    BatchNorm already reduces over the GLOBAL batch (XLA inserts the
    collectives) — sync-by-construction. This op exists for shard_map-style
    per-replica code: pass `axis_name` of the mapped mesh axis and the batch
    statistics are averaged with lax.pmean across it (the reference's
    `ndev`-wide key-grouped allreduce). With axis_name=None it degrades to
    ordinary BatchNorm semantics.
    """
    from jax import lax as _lax

    g = jnp.ones_like(gamma) if fix_gamma else gamma
    axis = 1
    reduce_axes = tuple(i for i in range(data.ndim) if i != axis)
    bshape = [1] * data.ndim
    bshape[axis] = data.shape[axis]

    if _training and not use_global_stats:
        mean = jnp.mean(data, axis=reduce_axes)
        meansq = jnp.mean(data * data, axis=reduce_axes)
        if axis_name is not None:
            mean = _lax.pmean(mean, axis_name)
            meansq = _lax.pmean(meansq, axis_name)
        var = meansq - mean * mean
        out = (data - mean.reshape(bshape)) * jax.lax.rsqrt(
            var.reshape(bshape) + eps)
        out = out * g.reshape(bshape) + beta.reshape(bshape)
        new_mean = momentum * moving_mean + (1 - momentum) * mean
        new_var = momentum * moving_var + (1 - momentum) * var
        return out, new_mean, new_var
    out = (data - moving_mean.reshape(bshape)) * jax.lax.rsqrt(
        moving_var.reshape(bshape) + eps)
    return out * g.reshape(bshape) + beta.reshape(bshape)


# --- round-4 op-gap batch (name-parity tail) -------------------------------


@register("_contrib_quadratic", aliases=("_contrib_backward_quadratic",))
def _contrib_quadratic(data, *, a=0.0, b=0.0, c=0.0):
    """a*x^2 + b*x + c (ref: src/operator/contrib/quadratic_op.cc — the
    tutorial op; kept for script parity)."""
    return a * jnp.square(data) + b * data + c


@register("_contrib_div_sqrt_dim")
def _contrib_div_sqrt_dim(data):
    """data / sqrt(last_dim) (ref: contrib/transformer.cc
    _contrib_div_sqrt_dim — the attention-score scaling helper)."""
    return data / jnp.sqrt(jnp.asarray(data.shape[-1], data.dtype))


def _grad_mult_fwd(data, scalar):
    return data, None


def _grad_mult_bwd(scalar, _res, g):
    return (g * scalar,)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def _grad_mult(data, scalar):
    return data


_grad_mult.defvjp(_grad_mult_fwd, _grad_mult_bwd)


@register("_contrib_gradientmultiplier",
          aliases=("_contrib_backward_gradientmultiplier",))
def _contrib_gradientmultiplier(data, *, scalar=1.0):
    """Identity forward, gradient scaled by `scalar` (ref:
    contrib/gradient_multiplier_op.cc — e.g. gradient reversal with
    scalar=-1 for domain adaptation)."""
    return _grad_mult(data, float(scalar))


@register("_contrib_index_copy", aliases=("_contrib_backward_index_copy",),
          no_grad_inputs=("index",))
def _contrib_index_copy(old, index, new):
    """old with new's rows written at `index` along axis 0
    (ref: contrib/index_copy.cc)."""
    return old.at[index.astype(jnp.int32)].set(new)


@register("_contrib_getnnz")
def _contrib_getnnz(data, *, axis=None):
    """Count of non-zero entries (ref: contrib/nnz.cc — CSR nnz; the
    functional dense form counts exactly)."""
    return jnp.count_nonzero(data, axis=axis).astype(jnp.int32)


def _kl_sparse_fwd(data, sparseness_target, penalty):
    return data, jnp.mean(jax.nn.sigmoid(data), axis=0)


def _kl_sparse_bwd(sparseness_target, penalty, rho_hat, g):
    # d/da KL(rho || rho_hat(a)) added to the incoming gradient
    # (ref: identity_attach_KL_sparse_reg-inl.h Backward). The chain
    # (-rho/rho_hat + (1-rho)/(1-rho_hat)) * rho_hat*(1-rho_hat) simplifies
    # to rho_hat - rho, which is finite even when the mean activation
    # saturates to exactly 0 or 1 (the quotient form emits NaN there).
    return (g + penalty * (rho_hat - sparseness_target),)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2))
def _kl_sparse(data, sparseness_target, penalty):
    return data


_kl_sparse.defvjp(_kl_sparse_fwd, _kl_sparse_bwd)


@register("IdentityAttachKLSparseReg")
def identity_attach_kl_sparse_reg(data, *, sparseness_target=0.1,
                                  penalty=0.001, momentum=0.9):
    """Identity that attaches a KL sparsity penalty gradient on the mean
    sigmoid activation (ref: src/operator/identity_attach_KL_sparse_reg.cc;
    the running-average momentum is subsumed by the per-batch mean in this
    functional form)."""
    return _kl_sparse(data, float(sparseness_target), float(penalty))
