"""Neural-network operators.

TPU-native coverage of the reference's `src/operator/nn/` + root NN ops
(ref: SURVEY §2 N5/N8). Where the reference dispatches to cuDNN kernels
(nn/cudnn/*-inl.h), these lower to XLA HLO (conv_general_dilated,
reduce_window) or composed jnp — XLA autotuning replaces cuDNN algo
selection. The fused multi-layer RNN op (ref: rnn-inl.h:49) is a `lax.scan`
over time so the compiled program does not grow with sequence length.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .registry import alias, register

# ---------------------------------------------------------------------------
# FullyConnected
# ---------------------------------------------------------------------------


@register("FullyConnected", optional=("bias",))
def fully_connected(data, weight, bias=None, *, num_hidden=None, no_bias=False, flatten=True):
    """y = x W^T + b (ref: src/operator/nn/fully_connected.cc)."""
    x = data.reshape((data.shape[0], -1)) if flatten and data.ndim > 2 else data
    if x.dtype != weight.dtype:  # mixed precision: MXU wants matching operand dtypes
        x = x.astype(weight.dtype)
    y = jnp.matmul(x, weight.T)
    if bias is not None and not no_bias:
        y = y + bias
    return y


# ---------------------------------------------------------------------------
# Convolution / Deconvolution
# ---------------------------------------------------------------------------


def _conv_dn(ndim):
    sp = "DHW"[3 - ndim:]
    return (f"NC{sp}", f"OI{sp}", f"NC{sp}")


def _tup(v, n):
    if v is None or v == ():
        return (1,) * n
    if isinstance(v, int):
        return (v,) * n
    t = tuple(int(x) for x in v)
    return t if len(t) == n else t + (t[-1],) * (n - len(t))


@register("Convolution", optional=("bias",))
def convolution(
    data,
    weight,
    bias=None,
    *,
    kernel=None,
    stride=None,
    dilate=None,
    pad=None,
    num_filter=None,
    num_group=1,
    no_bias=False,
    workspace=1024,
    cudnn_tune=None,
    cudnn_off=False,
    layout=None,
):
    """N-d convolution, NC(D)HW layout (ref: src/operator/nn/convolution.cc:388).

    Lowers to a single XLA convolution HLO — the direct MXU path; the
    reference's im2col/cuDNN algo machinery has no analog here.
    """
    nd = data.ndim - 2
    strides = _tup(stride, nd)
    dil = _tup(dilate, nd)
    p = _tup(pad, nd) if pad is not None else (0,) * nd
    padding = [(pi, pi) for pi in p]
    if data.dtype != weight.dtype:  # mixed precision: MXU wants matching operand dtypes
        data = data.astype(weight.dtype)
    sp = "DHW"[3 - nd:]
    channels_last = layout is not None and layout == f"N{sp}C"
    if channels_last:
        # TPU-native layout: convolution consumes/produces channels-last and
        # HWIO weights directly — no transposes reach XLA. The weight is
        # still stored OI{sp} (the reference's layout) and re-laid out here;
        # XLA folds the transpose into the weight's layout assignment.
        dn = (f"N{sp}C", f"OI{sp}", f"N{sp}C")
    else:
        dn = _conv_dn(nd)
    out = lax.conv_general_dilated(
        data,
        weight,
        window_strides=strides,
        padding=padding,
        rhs_dilation=dil,
        dimension_numbers=dn,
        feature_group_count=num_group,
    )
    if bias is not None and not no_bias:
        bshape = (1,) + (1,) * nd + (-1,) if channels_last \
            else (1, -1) + (1,) * nd
        out = out + bias.reshape(bshape)
    return out


@register("Deconvolution", optional=("bias",))
def deconvolution(
    data,
    weight,
    bias=None,
    *,
    kernel=None,
    stride=None,
    dilate=None,
    pad=None,
    adj=None,
    target_shape=None,
    num_filter=None,
    num_group=1,
    no_bias=True,
    workspace=512,
    cudnn_tune=None,
    cudnn_off=False,
    layout=None,
):
    """Transposed convolution (ref: src/operator/nn/deconvolution.cc).

    Only NC{sp} layouts are supported (channels-last deconvolution raises —
    better loud than silently convolving the wrong axes).

    Weight layout (in_c, out_c/group, *k) as in the reference; implemented as
    the gradient of convolution via input dilation.
    """
    nd = data.ndim - 2
    strides = _tup(stride, nd)
    p = _tup(pad, nd) if pad is not None else (0,) * nd
    a = _tup(adj, nd) if adj is not None else (0,) * nd
    k = weight.shape[2:]
    if num_group != 1:
        xs = jnp.split(data, num_group, axis=1)
        ws = jnp.split(weight, num_group, axis=0)
        outs = [
            _deconv1(x, w, strides, p, a, k, nd) for x, w in zip(xs, ws)
        ]
        out = jnp.concatenate(outs, axis=1)
    else:
        out = _deconv1(data, weight, strides, p, a, k, nd)
    if bias is not None and not no_bias:
        out = out + bias.reshape((1, -1) + (1,) * nd)
    return out


def _deconv1(x, w, strides, p, a, k, nd):
    # gradient-of-conv: dilate input by stride, correlate with flipped kernel
    w_t = jnp.flip(w, axis=tuple(range(2, 2 + nd)))  # (I, O, *k) spatial-flipped
    padding = [(k[i] - 1 - p[i], k[i] - 1 - p[i] + a[i]) for i in range(nd)]
    sp = "DHW"[3 - nd:]
    return lax.conv_general_dilated(
        x,
        w_t,
        window_strides=(1,) * nd,
        padding=padding,
        lhs_dilation=strides,
        dimension_numbers=(f"NC{sp}", f"IO{sp}", f"NC{sp}"),
    )


# ---------------------------------------------------------------------------
# Pooling
# ---------------------------------------------------------------------------


@register("Pooling")
def pooling(
    data,
    *,
    kernel=None,
    pool_type="max",
    global_pool=False,
    stride=None,
    pad=None,
    pooling_convention="valid",
    count_include_pad=True,
    cudnn_off=False,
    layout=None,
    p_value=2,
):
    """Max/avg/sum/lp pooling via XLA reduce_window (ref: nn/pooling.cc, nn/pool.h).

    `layout='N{sp}C'` pools channels-last without transposes (TPU-native)."""
    nd = data.ndim - 2
    sp = "DHW"[3 - nd:]
    channels_last = layout is not None and layout == f"N{sp}C"
    spatial = tuple(range(1, 1 + nd)) if channels_last \
        else tuple(range(2, 2 + nd))
    if global_pool:
        if pool_type == "max":
            out = jnp.max(data, axis=spatial, keepdims=True)
        elif pool_type == "sum":
            out = jnp.sum(data, axis=spatial, keepdims=True)
        elif pool_type == "lp":
            pv = float(p_value)
            out = jnp.power(jnp.sum(jnp.power(jnp.abs(data), pv),
                                    axis=spatial, keepdims=True), 1.0 / pv)
        else:
            out = jnp.mean(data, axis=spatial, keepdims=True)
        return out
    k = _tup(kernel, nd)
    # unset stride defaults to 1 per dim (ref: pooling.cc:46-57); the
    # gluon layers default strides=pool_size themselves before calling
    s = _tup(stride, nd) if stride is not None else _tup(1, nd)

    def _dims(vals, one=1):
        t = tuple(vals)
        return (one,) + t + (one,) if channels_last else (one, one) + t

    p = _tup(pad, nd) if pad is not None else (0,) * nd
    window = _dims(k)
    strides = _dims(s)
    pads = [(pi, pi) for pi in p]
    has_empty_window = False
    if pooling_convention == "full":
        # ceil-mode: pad high side enough that ceil-division windows fit
        for i in range(nd):
            dim = data.shape[spatial[i]]
            in_sz = dim + 2 * p[i]
            rem = (in_sz - k[i]) % s[i]
            extra = (s[i] - rem) % s[i] if rem != 0 else 0
            pads[i] = (p[i], p[i] + extra)
            # the last ceil window is EMPTY when its start (in padded
            # coords) lies at/after the end of left-pad + input
            n_out = 1 + (in_sz - k[i] + extra) // s[i]
            if (n_out - 1) * s[i] >= p[i] + dim:
                has_empty_window = True
    padding = ((0, 0),) + tuple(pads) + ((0, 0),) if channels_last \
        else ((0, 0), (0, 0)) + tuple(pads)
    if pool_type == "max":
        init = -jnp.inf if jnp.issubdtype(data.dtype, jnp.floating) else jnp.iinfo(data.dtype).min
        out = lax.reduce_window(data, init, lax.max, window, strides, padding)
        if has_empty_window and jnp.issubdtype(data.dtype, jnp.floating):
            # a ceil window fell entirely past the input; the reference
            # leaves MinValue<DType> (the lowest FINITE value,
            # pool.h:103) there, not -inf. Statically gated: the common
            # evenly-dividing case pays nothing.
            ones = jnp.ones_like(data)
            cnt = lax.reduce_window(ones, 0.0, lax.add, window, strides,
                                    padding)
            out = jnp.where(cnt > 0, out,
                            jnp.asarray(jnp.finfo(data.dtype).min,
                                        data.dtype))
        return out
    summed = lax.reduce_window(data, 0.0, lax.add, window, strides, padding)
    if pool_type == "sum":
        return summed
    if pool_type == "avg":
        if count_include_pad:
            denom = float(np.prod(k))
            return summed / denom
        ones = jnp.ones_like(data)
        counts = lax.reduce_window(ones, 0.0, lax.add, window, strides, padding)
        return summed / counts
    if pool_type == "lp":
        # ref: nn/pool.h lp_pooling — p_value in {1, 2, 3}
        pv = float(p_value)
        return jnp.power(
            lax.reduce_window(jnp.power(jnp.abs(data), pv), 0.0, lax.add,
                              window, strides, padding),
            1.0 / pv,
        )
    raise ValueError(f"unknown pool_type {pool_type}")


# ---------------------------------------------------------------------------
# Normalization
# ---------------------------------------------------------------------------


@register(
    "BatchNorm",
    aux=("moving_mean", "moving_var"),
    needs_training=True,
)
def batch_norm(
    data,
    gamma,
    beta,
    moving_mean,
    moving_var,
    *,
    eps=1e-3,
    momentum=0.9,
    fix_gamma=True,
    use_global_stats=False,
    output_mean_var=False,
    axis=1,
    cudnn_off=False,
    _training=False,
):
    """Batch normalization (ref: src/operator/nn/batch_norm.cc).

    Functional aux-state protocol: in training mode returns
    (out, new_moving_mean, new_moving_var); the evaluator writes the new
    values back into the aux arrays (the reference mutates aux in place).
    """
    if output_mean_var:
        raise NotImplementedError(
            "BatchNorm output_mean_var=True: the batch moments are carried "
            "through the functional aux-state protocol here; read the "
            "updated moving stats instead, or use LayerNorm's moment "
            "outputs")
    g = jnp.ones_like(gamma) if fix_gamma else gamma
    reduce_axes = tuple(i for i in range(data.ndim) if i != axis % data.ndim)
    bshape = [1] * data.ndim
    bshape[axis % data.ndim] = data.shape[axis % data.ndim]

    # Statistics in at least fp32 (bf16 mean/var loses too much precision);
    # promote rather than pin so float64 activations keep f64 stats. Output
    # cast back so bf16 stays bf16 end-to-end.
    out_dtype = data.dtype
    stat_dt = jnp.promote_types(data.dtype, jnp.float32)
    xf = data.astype(stat_dt)
    if _training and not use_global_stats:
        mean = jnp.mean(xf, axis=reduce_axes)
        var = jnp.var(xf, axis=reduce_axes)
        new_mean = moving_mean * momentum + mean.astype(moving_mean.dtype) * (1 - momentum)
        new_var = moving_var * momentum + var.astype(moving_var.dtype) * (1 - momentum)
    else:
        mean, var = moving_mean.astype(stat_dt), moving_var.astype(stat_dt)
        new_mean, new_var = moving_mean, moving_var
    x_hat = (xf - mean.reshape(bshape)) * lax.rsqrt(var.reshape(bshape) + eps)
    out = (x_hat * g.reshape(bshape).astype(stat_dt)
           + beta.reshape(bshape).astype(stat_dt)).astype(out_dtype)
    if _training:
        return out, lax.stop_gradient(new_mean), lax.stop_gradient(new_var)
    return out


@register("LayerNorm",
          num_outputs=lambda attrs: 3 if attrs.get("output_mean_var") else 1)
def layer_norm(data, gamma, beta, *, axis=-1, eps=1e-5, output_mean_var=False):
    """Layer normalization (ref: src/operator/nn/layer_norm.cc).

    With output_mean_var, also returns the per-group mean and std
    (gradient-stopped, matching the reference's FNumVisibleOutputs). The
    normalized axis is kept as size 1 in mean/std (ref LayerNormShape sets
    moments_shape[axis]=1) so (data - mean) / std broadcasts directly."""
    mean = jnp.mean(data, axis=axis, keepdims=True)
    var = jnp.var(data, axis=axis, keepdims=True)
    x_hat = (data - mean) * lax.rsqrt(var + eps)
    bshape = [1] * data.ndim
    bshape[axis % data.ndim] = data.shape[axis % data.ndim]
    out = x_hat * gamma.reshape(bshape) + beta.reshape(bshape)
    if output_mean_var:
        return (out,
                lax.stop_gradient(mean),
                lax.stop_gradient(jnp.sqrt(var + eps)))
    return out


@register("InstanceNorm")
def instance_norm(data, gamma, beta, *, eps=1e-3):
    """Instance norm over spatial dims (ref: src/operator/instance_norm.cc)."""
    axes = tuple(range(2, data.ndim))
    mean = jnp.mean(data, axis=axes, keepdims=True)
    var = jnp.var(data, axis=axes, keepdims=True)
    x_hat = (data - mean) * lax.rsqrt(var + eps)
    bshape = (1, -1) + (1,) * (data.ndim - 2)
    return x_hat * gamma.reshape(bshape) + beta.reshape(bshape)


@register("L2Normalization")
def l2_normalization(data, *, eps=1e-10, mode="instance"):
    """(ref: src/operator/l2_normalization.cc)"""
    if mode == "instance":
        axes = tuple(range(1, data.ndim))
    elif mode == "channel":
        axes = (1,)
    elif mode == "spatial":
        axes = tuple(range(2, data.ndim))
    else:
        raise ValueError(mode)
    nrm = jnp.sqrt(jnp.sum(jnp.square(data), axis=axes, keepdims=True) + eps)
    return data / nrm


@register("LRN")
def lrn(data, *, alpha=1e-4, beta=0.75, knorm=2.0, nsize=5):
    """Local response norm across channels (ref: src/operator/nn/lrn.cc)."""
    sq = jnp.square(data)
    half = nsize // 2
    # sum over channel window via padded cumulative trick
    padded = jnp.pad(sq, ((0, 0), (half, half)) + ((0, 0),) * (data.ndim - 2))
    window = sum(
        lax.slice_in_dim(padded, i, i + data.shape[1], axis=1) for i in range(nsize)
    )
    return data / jnp.power(knorm + (alpha / nsize) * window, beta)


# ---------------------------------------------------------------------------
# Activations
# ---------------------------------------------------------------------------


@register("Activation")
def activation(data, *, act_type="relu"):
    """(ref: src/operator/nn/activation.cc)"""
    if act_type == "relu":
        return jax.nn.relu(data)
    if act_type == "sigmoid":
        return jax.nn.sigmoid(data)
    if act_type == "tanh":
        return jnp.tanh(data)
    if act_type == "softrelu":
        return jax.nn.softplus(data)
    if act_type == "softsign":
        return jax.nn.soft_sign(data)
    raise ValueError(f"unknown act_type {act_type}")


@register("LeakyReLU", optional=("gamma",), needs_rng=True, needs_training=True)
def leaky_relu(
    data,
    gamma=None,
    *,
    act_type="leaky",
    slope=0.25,
    lower_bound=0.125,
    upper_bound=0.334,
    _rng=None,
    _training=False,
):
    """(ref: src/operator/leaky_relu.cc). prelu takes a learned `gamma` input."""
    if act_type == "leaky":
        return jnp.where(data > 0, data, slope * data)
    if act_type == "prelu":
        g = gamma.reshape((1, -1) + (1,) * (data.ndim - 2)) if gamma.ndim == 1 else gamma
        return jnp.where(data > 0, data, g * data)
    if act_type == "elu":
        return jnp.where(data > 0, data, slope * jnp.expm1(data))
    if act_type == "selu":
        a, scale = 1.6732632423543772, 1.0507009873554805
        return scale * jnp.where(data > 0, data, a * jnp.expm1(data))
    if act_type == "gelu":
        return jax.nn.gelu(data, approximate=False)
    if act_type == "rrelu":
        if _training and _rng is not None:
            s = jax.random.uniform(
                _rng, data.shape, minval=lower_bound, maxval=upper_bound, dtype=data.dtype
            )
        else:
            s = (lower_bound + upper_bound) / 2.0
        return jnp.where(data > 0, data, s * data)
    raise ValueError(f"unknown act_type {act_type}")


def _f32_reduce(fn, data, *args, **kwargs):
    """Run a softmax-family reduction with float32 accumulation for sub-f32
    inputs (TPU discipline: bf16 matmuls, f32 softmax/logsumexp — a bf16
    logsumexp over a 1000-class axis loses ~2 decimal digits), returning
    the input dtype."""
    if data.dtype in (jnp.bfloat16, jnp.float16):
        return fn(data.astype(jnp.float32), *args, **kwargs).astype(data.dtype)
    return fn(data, *args, **kwargs)


@register("softmax", optional=("length",), no_grad_inputs=("length",))
def softmax(data, length=None, *, axis=-1, temperature=None,
            use_length=None):
    """Softmax over `axis`, with optional `temperature` scaling."""
    x = data / temperature if temperature else data
    if use_length is False:  # reference scripts pass use_length explicitly
        length = None
    if length is None:
        return _f32_reduce(jax.nn.softmax, x, axis=axis)
    # masked softmax (ref: softmax use_length=True): positions at or past
    # each row's length get probability 0; fully-masked rows return 0s
    ax = axis % x.ndim
    shape = [1] * x.ndim
    shape[ax] = x.shape[ax]
    mask = jnp.arange(x.shape[ax]).reshape(shape) < jnp.expand_dims(
        length.astype(jnp.int32), ax)
    x = jnp.where(mask, x, -jnp.inf)
    out = _f32_reduce(jax.nn.softmax, x, axis=axis)
    return jnp.where(mask, out, jnp.zeros((), out.dtype))


@register("log_softmax")
def log_softmax(data, *, axis=-1, temperature=None):
    """Numerically stable log of softmax over `axis`."""
    x = data / temperature if temperature else data
    return _f32_reduce(jax.nn.log_softmax, x, axis=axis)


@register("softmin")
def softmin(data, *, axis=-1):
    """Softmax of the negated input: smallest values get the largest weights."""
    return _f32_reduce(jax.nn.softmax, -data, axis=axis)


@register("SoftmaxActivation")
def softmax_activation(data, *, mode="instance"):
    """Reference-compat softmax over channels (or the whole instance)."""
    if mode == "channel":
        return _f32_reduce(jax.nn.softmax, data, axis=1)
    return _f32_reduce(jax.nn.softmax, data.reshape(data.shape[0], -1),
                       axis=-1).reshape(data.shape)


@register("softmax_cross_entropy", no_grad_inputs=("label",))
def softmax_cross_entropy(data, label):
    """Cross-entropy between softmax(data) and integer labels, summed over the
    batch."""
    logp = _f32_reduce(jax.nn.log_softmax, data, axis=-1)
    lbl = label.astype(jnp.int32)
    return -jnp.sum(jnp.take_along_axis(logp, lbl[:, None], axis=-1))


# ---------------------------------------------------------------------------
# Output ops with MXNet training-loss semantics.
#
# The reference's SoftmaxOutput/`*RegressionOutput` define their OWN backward
# (gradient of implied loss, ignoring head gradients — ref:
# src/operator/softmax_output-inl.h). Reproduced here with jax.custom_vjp so
# `Module.fit`-style training matches numerically.
# ---------------------------------------------------------------------------


def _softmax_output_impl(
    data, label, grad_scale, ignore_label, use_ignore, multi_output,
    normalization, smooth_alpha, preserve_shape, out_grad
):
    if multi_output:
        return _f32_reduce(jax.nn.softmax, data, axis=1)
    if preserve_shape or data.ndim <= 2:
        return _f32_reduce(jax.nn.softmax, data, axis=-1)
    # reference default for ND input: flatten the non-batch dims and
    # softmax over the flattened classes (ref: softmax_output-inl.h;
    # preserve_shape=True instead softmaxes each last-axis slice)
    flat = data.reshape(data.shape[0], -1)
    return _f32_reduce(jax.nn.softmax, flat, axis=-1).reshape(data.shape)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4, 5, 6, 7, 8, 9))
def _softmax_output(
    data, label, grad_scale, ignore_label, use_ignore, multi_output,
    normalization, smooth_alpha, preserve_shape, out_grad
):
    return _softmax_output_impl(
        data, label, grad_scale, ignore_label, use_ignore, multi_output,
        normalization, smooth_alpha, preserve_shape, out_grad
    )


def _softmax_output_fwd(
    data, label, grad_scale, ignore_label, use_ignore, multi_output,
    normalization, smooth_alpha, preserve_shape, out_grad
):
    out = _softmax_output_impl(
        data, label, grad_scale, ignore_label, use_ignore, multi_output,
        normalization, smooth_alpha, preserve_shape, out_grad
    )
    return out, (out, label)


def _softmax_output_bwd(
    grad_scale, ignore_label, use_ignore, multi_output, normalization,
    smooth_alpha, preserve_shape, out_grad, res, g
):
    out, label = res
    shape = out.shape
    flattened = not multi_output and not preserve_shape and out.ndim > 2
    if flattened:
        out = out.reshape(shape[0], -1)
        g = g.reshape(shape[0], -1)
    axis = 1 if multi_output else -1
    n_class = out.shape[axis]
    lbl = label.astype(jnp.int32)
    onehot = jax.nn.one_hot(lbl, n_class, dtype=out.dtype)
    if multi_output:
        # label (N, d1, ...) -> onehot (N, d1, ..., C) -> move C to axis 1
        onehot = jnp.moveaxis(onehot, -1, 1)
    if smooth_alpha:
        onehot = onehot * (1 - smooth_alpha) + smooth_alpha / n_class
    grad = out - onehot
    if use_ignore:
        mask = (lbl != int(ignore_label)).astype(out.dtype)
        mask = jnp.expand_dims(mask, axis=axis)
        grad = grad * mask
    scale = grad_scale
    if normalization == "batch":
        scale = scale / out.shape[0]
    elif normalization == "valid" and use_ignore:
        valid = jnp.maximum(jnp.sum(lbl != int(ignore_label)).astype(out.dtype), 1.0)
        grad = grad / valid
    grad = grad * scale
    if out_grad:
        # ref: softmax_output-inl.h out_grad=True — scale the implied-loss
        # gradient by the incoming head gradient (make_loss chaining)
        grad = grad * g
    if flattened:
        grad = grad.reshape(shape)
    return (grad, jnp.zeros_like(label))


_softmax_output.defvjp(_softmax_output_fwd, _softmax_output_bwd)


@register("SoftmaxOutput", aliases=("Softmax",), no_grad_inputs=("label",))
def softmax_output(
    data,
    label,
    *,
    grad_scale=1.0,
    ignore_label=-1.0,
    use_ignore=False,
    multi_output=False,
    normalization="null",
    preserve_shape=False,
    out_grad=False,
    smooth_alpha=0.0,
):
    """Softmax forward whose backward is (softmax - one_hot(label)) *
    grad_scale -- the reference's fused softmax loss layer."""
    return _softmax_output(
        data, label, float(grad_scale), float(ignore_label), bool(use_ignore),
        bool(multi_output), normalization, float(smooth_alpha),
        bool(preserve_shape), bool(out_grad),
    )


def _make_regression_output(name, fwd_fn, grad_fn):
    @functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
    def _impl(data, label, grad_scale):
        return fwd_fn(data)

    def _fwd(data, label, grad_scale):
        out = fwd_fn(data)
        return out, (out, label)

    def _bwd(grad_scale, res, g):
        out, label = res
        return (grad_fn(out, label) * grad_scale, jnp.zeros_like(label))

    _impl.defvjp(_fwd, _bwd)

    @register(name, no_grad_inputs=("label",))
    def op(data, label, *, grad_scale=1.0):
        """Regression output: forward activation with the reference's fixed
        loss gradient (out - label), attached via custom vjp."""
        return _impl(data, label, float(grad_scale))

    op.__name__ = name
    return op


_make_regression_output(
    "LinearRegressionOutput", lambda x: x, lambda o, l: (o - l.reshape(o.shape))
)
_make_regression_output(
    "LogisticRegressionOutput", jax.nn.sigmoid, lambda o, l: (o - l.reshape(o.shape))
)
_make_regression_output(
    "MAERegressionOutput", lambda x: x, lambda o, l: jnp.sign(o - l.reshape(o.shape))
)


# ---------------------------------------------------------------------------
# Dropout
# ---------------------------------------------------------------------------


@register("Dropout", needs_rng=True, needs_training=True)
def dropout(data, *, p=0.5, mode="training", axes=(), cudnn_off=False, _rng=None, _training=False):
    """(ref: src/operator/nn/dropout.cc) — inverted dropout."""
    if not _training and mode != "always":
        return data
    if p <= 0 or _rng is None:
        return data
    shape = list(data.shape)
    for a in axes:
        shape[a] = 1
    keep = jax.random.bernoulli(_rng, 1.0 - p, tuple(shape))
    return jnp.where(keep, data / (1.0 - p), jnp.zeros_like(data))


# ---------------------------------------------------------------------------
# UpSampling
# ---------------------------------------------------------------------------


@register("UpSampling")
def upsampling(*args, scale=2, sample_type="nearest", num_args=1, num_filter=0, multi_input_mode="concat", workspace=512):
    """(ref: src/operator/upsampling.cc) nearest/bilinear upsampling."""
    data = args[0]
    n, c, h, w = data.shape
    if sample_type == "nearest":
        out = jnp.repeat(jnp.repeat(data, scale, axis=2), scale, axis=3)
    else:
        out = jax.image.resize(data, (n, c, h * scale, w * scale), method="bilinear")
    return out


# ---------------------------------------------------------------------------
# Fused RNN (ref: src/operator/rnn-inl.h:49 — LSTM/GRU/vanilla, multi-layer,
# bidirectional, packed parameter vector). Implemented as lax.scan over time:
# compile once, run any T of the same padded length.
#
# Packed layout (documented, self-consistent): for each layer, for each
# direction: W_i2h (G*H, in), W_h2h (G*H, H); then for each layer/direction:
# b_i2h (G*H), b_h2h (G*H). Gate order: LSTM [i, f, g, o], GRU [r, z, n].
# ---------------------------------------------------------------------------

_GATES = {"rnn_relu": 1, "rnn_tanh": 1, "lstm": 4, "gru": 3}


def rnn_param_size(num_layers, input_size, state_size, bidirectional=False, mode="lstm"):
    """Total packed parameter count for the fused RNN op."""
    G, H, D = _GATES[mode], state_size, 2 if bidirectional else 1
    size = 0
    for layer in range(num_layers):
        inp = input_size if layer == 0 else H * D
        size += D * (G * H * inp + G * H * H)  # weights
    size += num_layers * D * 2 * G * H  # biases
    return size


def _rnn_slice_params(params, num_layers, input_size, H, D, G):
    """Slice the packed vector into per-(layer, direction) weight/bias sets."""
    offset = 0
    Wx, Wh = [], []
    for layer in range(num_layers):
        inp = input_size if layer == 0 else H * D
        wx_l, wh_l = [], []
        for _ in range(D):
            wx_l.append(params[offset : offset + G * H * inp].reshape(G * H, inp))
            offset += G * H * inp
            wh_l.append(params[offset : offset + G * H * H].reshape(G * H, H))
            offset += G * H * H
        Wx.append(wx_l)
        Wh.append(wh_l)
    bx, bh = [], []
    for layer in range(num_layers):
        bx_l, bh_l = [], []
        for _ in range(D):
            bx_l.append(params[offset : offset + G * H]); offset += G * H
            bh_l.append(params[offset : offset + G * H]); offset += G * H
        bx.append(bx_l)
        bh.append(bh_l)
    return Wx, Wh, bx, bh


def _lstm_step(carry, x_t, wx, wh, bx, bh, H, clip_min=None, clip_max=None):
    h, c = carry
    gates = x_t @ wx.T + bx + h @ wh.T + bh
    i, f, g, o = jnp.split(gates, 4, axis=-1)
    c_new = jax.nn.sigmoid(f) * c + jax.nn.sigmoid(i) * jnp.tanh(g)
    if clip_min is not None or clip_max is not None:
        # ref: rnn-inl.h / cuDNN cell clipping — the cell state is bounded
        # BEFORE the output gate reads it
        c_new = jnp.clip(c_new, clip_min, clip_max)
    h_new = jax.nn.sigmoid(o) * jnp.tanh(c_new)
    return (h_new, c_new), h_new


def _gru_step(carry, x_t, wx, wh, bx, bh, H):
    (h,) = carry
    gx = x_t @ wx.T + bx
    gh = h @ wh.T + bh
    rx, zx, nx = jnp.split(gx, 3, axis=-1)
    rh, zh, nh = jnp.split(gh, 3, axis=-1)
    r = jax.nn.sigmoid(rx + rh)
    z = jax.nn.sigmoid(zx + zh)
    n = jnp.tanh(nx + r * nh)
    h_new = (1 - z) * n + z * h
    return (h_new,), h_new


def _rnn_tanh_step(carry, x_t, wx, wh, bx, bh, H):
    (h,) = carry
    h_new = jnp.tanh(x_t @ wx.T + bx + h @ wh.T + bh)
    return (h_new,), h_new


def _rnn_relu_step(carry, x_t, wx, wh, bx, bh, H):
    (h,) = carry
    h_new = jax.nn.relu(x_t @ wx.T + bx + h @ wh.T + bh)
    return (h_new,), h_new


_STEPS = {"lstm": _lstm_step, "gru": _gru_step, "rnn_tanh": _rnn_tanh_step, "rnn_relu": _rnn_relu_step}


def _rnn_outputs(attrs):
    if not attrs.get("state_outputs", False):
        return 1
    return 3 if attrs.get("mode", "lstm") == "lstm" else 2


@register("RNN", optional=("state_cell",), needs_rng=True, needs_training=True, num_outputs=_rnn_outputs)
def rnn(
    data,
    parameters,
    state,
    state_cell=None,
    *,
    state_size=None,
    num_layers=1,
    mode="lstm",
    bidirectional=False,
    p=0.0,
    state_outputs=False,
    projection_size=None,
    lstm_state_clip_min=None,
    lstm_state_clip_max=None,
    _rng=None,
    _training=False,
):
    """Fused multi-layer (bi)RNN over packed params (ref: rnn-inl.h:49).

    data: (T, B, I); state: (L*D, B, H); state_cell (lstm): (L*D, B, H).
    Returns output (T, B, H*D) [+ final h [+ final c for lstm] when
    state_outputs].
    """
    T, B, I = data.shape
    H, D, G = state_size, 2 if bidirectional else 1, _GATES[mode]
    step = _STEPS[mode]
    if projection_size:
        raise NotImplementedError(
            "RNN projection_size: use gluon.contrib.rnn.LSTMPCell (the "
            "projected-LSTM path); the fused RNN op runs full-rank cells")
    if mode == "lstm" and (lstm_state_clip_min is not None
                           or lstm_state_clip_max is not None):
        step = functools.partial(_lstm_step, clip_min=lstm_state_clip_min,
                                 clip_max=lstm_state_clip_max)
    Wx, Wh, bx, bh = _rnn_slice_params(parameters, num_layers, I, H, D, G)

    x = data
    hs_out, cs_out = [], []
    for layer in range(num_layers):
        if p > 0 and _training and layer > 0 and _rng is not None:
            _rng, sub = jax.random.split(_rng)
            keep = jax.random.bernoulli(sub, 1.0 - p, x.shape)
            x = jnp.where(keep, x / (1.0 - p), 0.0)
        dir_outs = []
        for d in range(D):
            idx = layer * D + d
            # a (L*D, 1, H) initial state broadcasts over the batch (the
            # symbolic rnn cells' begin_state default); scan carries need
            # the full (B, H) shape up front
            h0 = jnp.broadcast_to(state[idx], (B, H))
            carry = ((h0, jnp.broadcast_to(state_cell[idx], (B, H)))
                     if mode == "lstm" else (h0,))
            wx, wh, bxx, bhh = Wx[layer][d], Wh[layer][d], bx[layer][d], bh[layer][d]
            xs = jnp.flip(x, axis=0) if d == 1 else x

            def scan_fn(c, xt, wx=wx, wh=wh, bxx=bxx, bhh=bhh):
                return step(c, xt, wx, wh, bxx, bhh, H)

            final, ys = lax.scan(scan_fn, carry, xs)
            if d == 1:
                ys = jnp.flip(ys, axis=0)
            dir_outs.append(ys)
            hs_out.append(final[0])
            if mode == "lstm":
                cs_out.append(final[1])
        x = jnp.concatenate(dir_outs, axis=-1) if D > 1 else dir_outs[0]

    if not state_outputs:
        return x
    hN = jnp.stack(hs_out, axis=0)
    if mode == "lstm":
        return x, hN, jnp.stack(cs_out, axis=0)
    return x, hN


# ---------------------------------------------------------------------------
# CTC loss (ref: src/operator/nn/ctc_loss.cc over 3rdparty warpctc headers) —
# here via optax's native XLA implementation.
# ---------------------------------------------------------------------------


@register("CTCLoss", aliases=("ctc_loss",), optional=("data_lengths", "label_lengths"),
          no_grad_inputs=("label", "data_lengths", "label_lengths"))
def ctc_loss(
    data,
    label,
    data_lengths=None,
    label_lengths=None,
    *,
    use_data_lengths=False,
    use_label_lengths=False,
    blank_label="first",
):
    """CTC loss. data: (T, B, C); label: (B, L) with -1/0 padding."""
    import optax

    T, B, C = data.shape
    logits = jnp.moveaxis(data, 0, 1)  # (B, T, C)
    if use_data_lengths and data_lengths is not None:
        t = jnp.arange(T)[None, :]
        logit_paddings = (t >= data_lengths[:, None].astype(jnp.int32)).astype(jnp.float32)
    else:
        logit_paddings = jnp.zeros((B, T), dtype=jnp.float32)
    lbl = label.astype(jnp.int32)
    if use_label_lengths and label_lengths is not None:
        L = label.shape[1]
        pos = jnp.arange(L)[None, :]
        label_paddings = (pos >= label_lengths[:, None].astype(jnp.int32)).astype(jnp.float32)
    else:
        label_paddings = (lbl <= 0).astype(jnp.float32) if blank_label == "first" else (lbl < 0).astype(jnp.float32)
    if blank_label == "first":
        # optax uses blank_id; MXNet 'first' means class 0 is blank and labels are 1-based
        return optax.ctc_loss(logits, logit_paddings, lbl, label_paddings, blank_id=0)
    return optax.ctc_loss(logits, logit_paddings, lbl, label_paddings, blank_id=C - 1)


# ---------------------------------------------------------------------------
# im2col / col2im (ref: src/operator/nn/im2col.h + the im2col/col2im ops) —
# sliding-block extraction and its scatter-add inverse. On TPU these lower
# to XLA's patch-extraction (reduce_window family); col2im is expressed as
# the exact linear transpose of im2col via jax.vjp, so the pair is
# adjoint by construction.
# ---------------------------------------------------------------------------


def _im2col_patches(data, kernel, stride, dilate, pad):
    n_sp = len(kernel)
    stride = _tup(stride, n_sp)
    dilate = _tup(dilate, n_sp)
    padv = _tup(pad, n_sp) if pad else (0,) * n_sp
    padding = [(p, p) for p in padv]
    # feature dim comes back channel-major (c, k0, k1): exactly the
    # reference's (c * K_h + kh) * K_w + kw layout
    patches = lax.conv_general_dilated_patches(
        data, tuple(kernel), stride, padding, rhs_dilation=dilate,
        dimension_numbers=("NCHW", "OIHW", "NCHW") if n_sp == 2 else None,
    )
    return patches


@register("im2col")
def im2col(data, *, kernel, stride=(), dilate=(), pad=()):
    """(ref: src/operator/nn/im2col.h im2col CPU/GPU kernels; op
    registration src/operator/nn/im2col.cc). data (N, C, spatial...) ->
    (N, C*prod(kernel), prod(out_spatial))."""
    patches = _im2col_patches(data, tuple(kernel), stride, dilate, pad)
    n, f = patches.shape[0], patches.shape[1]
    return patches.reshape(n, f, -1)


@register("col2im")
def col2im(data, *, output_size, kernel, stride=(), dilate=(), pad=()):
    """(ref: src/operator/nn/im2col.h col2im — scatter-add of column blocks
    back into an image). data (N, C*prod(kernel), L) -> (N, C,
    *output_size). Exact adjoint of im2col (same kernel/stride/dilate/pad),
    expressed as its vjp."""
    kernel = tuple(int(k) for k in kernel)
    out_sp = tuple(int(s) for s in output_size)
    n = data.shape[0]
    c = data.shape[1] // int(np.prod(kernel))
    x_shape = (n, c) + out_sp

    def fwd(img):
        return im2col(img, kernel=kernel, stride=stride, dilate=dilate,
                      pad=pad)

    _, vjp = jax.vjp(fwd, jnp.zeros(x_shape, data.dtype))
    (img,) = vjp(data)
    return img


# ---------------------------------------------------------------------------
# SVMOutput (ref: src/operator/svm_output.cc) — identity forward whose
# backward is the multiclass hinge-loss gradient wrt the scores, ignoring
# head gradients (the SoftmaxOutput-style "output op" contract).
# Both branches match the reference sign-for-sign: L1_SVM stores dL/ds
# directly; L2_SVM stores the bracketed magnitude then multiplies by
# -reg_coef (svm_output.cc:60-63), landing on the same descent gradient
# with the coefficient applied.
# ---------------------------------------------------------------------------


def _svm_grad(out, label, margin, reg, use_linear):
    lbl = label.astype(jnp.int32)
    onehot = jax.nn.one_hot(lbl, out.shape[-1], dtype=out.dtype)
    if use_linear:
        g_true = -(margin - out > 0).astype(out.dtype)
        g_other = (margin + out > 0).astype(out.dtype)
    else:
        g_true = -2.0 * jnp.maximum(margin - out, 0.0)
        g_other = 2.0 * jnp.maximum(margin + out, 0.0)
    return reg * (onehot * g_true + (1 - onehot) * g_other)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4))
def _svm_output(data, label, margin, reg, use_linear):
    return data


def _svm_output_fwd(data, label, margin, reg, use_linear):
    return data, (data, label)


def _svm_output_bwd(margin, reg, use_linear, res, g):
    out, label = res
    return (_svm_grad(out, label, margin, reg, use_linear),
            jnp.zeros_like(label))


_svm_output.defvjp(_svm_output_fwd, _svm_output_bwd)


@register("SVMOutput")
def svm_output(data, label, *, margin=1.0, regularization_coefficient=1.0,
               use_linear=False):
    """(ref: src/operator/svm_output.cc:89 SVMOutput registration)."""
    return _svm_output(data, label, float(margin),
                       float(regularization_coefficient), bool(use_linear))


# round-4 name-parity aliases
alias("BatchNorm", "BatchNorm_v1")
alias("Embedding", "_contrib_SparseEmbedding")
# legacy v1 forms kept for ported-script compat (ref: convolution_v1.cc,
# pooling_v1.cc — same math, pre-NNVM parameter structs)
alias("Convolution", "Convolution_v1")
alias("Pooling", "Pooling_v1")
# vendor-specific legacy name: same math, the cudnn dispatch is a backend
# concern XLA subsumes (ref: cudnn_batch_norm.cc NNVM_REGISTER_OP)
alias("BatchNorm", "CuDNNBatchNorm")
