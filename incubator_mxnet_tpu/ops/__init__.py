"""Operator library: registry + tensor/nn/random/optimizer op families.

This package is the analog of the reference's `src/operator/` tree (SURVEY §2
N5-N8, N31): pure jax functions registered once and surfaced through both the
eager (`nd`) and symbolic (`sym`) frontends.
"""
from .registry import OP_REGISTRY, OpDef, get_op, list_ops, register, alias  # noqa: F401
from . import tensor  # noqa: F401
from . import nn  # noqa: F401
from . import random  # noqa: F401
from . import optimizer  # noqa: F401
from . import vision  # noqa: F401
from . import contrib_ops  # noqa: F401
from . import quantized  # noqa: F401
