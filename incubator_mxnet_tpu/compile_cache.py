"""Persistent, content-addressed executable cache + AOT compile path.

ROADMAP item 4: a single resnet50 train step costs 81 s (fp32) / 111 s
(bf16) of XLA compile time (MEASURED_r05, docs/PERF_ANALYSIS.md §1),
paid again on *every* process start — a fatal tax on preemption resume
(PR 8), elastic re-admits (PR 6), and serving restarts. This module
makes the second process skip XLA entirely:

- `wrap(name, jax.jit(fn), ...)` returns a `CachedJit` that, on the
  first call per shape signature, lowers the function to StableHLO,
  hashes the text (content-addressed: the *program* is the key, not the
  call site), and looks the executable up on disk before compiling.
  A hit deserializes via `jax.experimental.serialize_executable` —
  trace time is still paid, XLA compile time is not.
- `CachedJit.warm(*abstract)` is the AOT path: resolve (and populate)
  the executable from `jax.ShapeDtypeStruct`s without executing —
  `tools/warmup.py` uses it to precompile every (shape bucket x dtype)
  before the first request arrives.

Cache entries are keyed on (canonical graph hash, arg avals, backend +
device kind + device/process count, donation mask, framework+jax+jaxlib
version salt, `MXTPU_COMPILE_CACHE_SALT`), stored one file per entry
under `MXTPU_COMPILE_CACHE_DIR` with the crash-consistent write protocol
from `resilience/checkpoint.py` (tmp -> fsync -> replace + sha256
sidecar manifest) and an LRU size cap (`MXTPU_COMPILE_CACHE_MAX_MB`).
Corrupt, torn, or version-mismatched entries are evicted and the caller
silently falls back to a fresh compile — the cache can never change
numerics, only skip work.

Every site reports `mxtpu_compile_cache_{hits,misses,evictions}_total`
and attributes skipped wall-clock to `mxtpu_compile_cache_saved_seconds`
(the stored entry's measured compile time minus the deserialize cost).
Cache hits register their signature with `telemetry/compilereg.py` via
the cached path, so a fully-warm process shows **zero** compile events
and zero `mxtpu_compile_seconds` observations — the property the CI
cold-start tier gates on.

The cache trusts its directory (entries are pickles, same trust domain
as checkpoints); point `MXTPU_COMPILE_CACHE_DIR` only at storage you
control.
"""
from __future__ import annotations

import hashlib
import logging
import os
import pickle
import threading
import time

import jax
import jax.numpy as jnp

from . import config
from . import telemetry
from .resilience import checkpoint as _ckpt
from .telemetry import compilereg as _compilereg

__all__ = ["CachedJit", "wrap", "enabled", "cache_dir", "entry_key",
           "abstract_signature", "abstractify", "stats", "reset_stats",
           "clear",
           "HITS_TOTAL", "MISSES_TOTAL", "EVICTIONS_TOTAL", "SAVED_SECONDS"]

logger = logging.getLogger(__name__)

HITS_TOTAL = "mxtpu_compile_cache_hits_total"
_HITS_HELP = ("Executables served from the persistent compile cache "
              "instead of XLA, by fn.")
MISSES_TOTAL = "mxtpu_compile_cache_misses_total"
_MISSES_HELP = ("Cache lookups that fell through to a fresh XLA compile "
                "(the entry is then written back), by fn.")
EVICTIONS_TOTAL = "mxtpu_compile_cache_evictions_total"
_EVICT_HELP = ("Cache entries deleted, by reason (corrupt / version / "
               "lru / clear).")
SAVED_SECONDS = "mxtpu_compile_cache_saved_seconds"
_SAVED_HELP = ("Compile wall-clock skipped by cache hits: the stored "
               "entry's measured compile time minus the deserialize "
               "cost, by fn.")

# bump to invalidate every existing cache entry on a format change
_SCHEMA = 1
_SUFFIX = ".exe"

_stats_lock = threading.Lock()
_stats = {"hits": 0, "misses": 0, "evictions": 0, "saved_seconds": 0.0}


def stats():
    """Process-local cache counters (independent of telemetry state)."""
    with _stats_lock:
        return dict(_stats)


def reset_stats():
    with _stats_lock:
        for k in _stats:
            _stats[k] = 0.0 if k == "saved_seconds" else 0


def _bump(key, amount=1):
    with _stats_lock:
        _stats[key] += amount


# ---------------------------------------------------------------------------
# key derivation
# ---------------------------------------------------------------------------

def _dtype_name(dt):
    """Canonical dtype spelling ('float32', 'bfloat16', ...) — the same
    normalization compilereg uses, so one program yields one key."""
    try:
        return jnp.dtype(dt).name
    except TypeError:
        return str(dt)


def abstract_signature(args):
    """Canonical aval signature of a pytree of (concrete or abstract)
    args: per-leaf (shape, dtype-name, weak_type) plus the treedef
    string. jax flattens dict keys in sorted order, so the treedef
    string is cross-process stable."""
    leaves, treedef = jax.tree_util.tree_flatten(args)
    parts = []
    for leaf in leaves:
        if hasattr(leaf, "shape") and hasattr(leaf, "dtype"):
            # committed arrays fold their sharding into the signature:
            # under MXTPU_SHARD_POLICY the same train step is compiled
            # once with replicated params (first call) and once with the
            # settled sharded layout — two distinct executables that must
            # not collide on one key. Mirrors abstractify(): uncommitted
            # arrays (and plain ShapeDtypeStructs without a sharding)
            # contribute None, so AOT warm() and runtime still agree.
            if isinstance(leaf, jax.ShapeDtypeStruct):
                sharding = leaf.sharding
            elif getattr(leaf, "_committed", False):
                sharding = getattr(leaf, "sharding", None)
            else:
                sharding = None
            parts.append((tuple(leaf.shape), _dtype_name(leaf.dtype),
                          bool(getattr(leaf, "weak_type", False)),
                          str(sharding) if sharding is not None else None))
        else:
            parts.append(("py", type(leaf).__name__, repr(leaf)))
    return (tuple(parts), str(treedef))


def abstractify(tree):
    """Pytree of (possibly concrete) arrays -> `jax.ShapeDtypeStruct`s
    that lower to byte-identical StableHLO as the live values: committed
    arrays keep their sharding annotation (lowering embeds it in the
    module text), uncommitted ones drop it — so an AOT warm() and the
    later runtime call derive the SAME cache key."""
    def one(d):
        if isinstance(d, jax.ShapeDtypeStruct):
            return d
        if hasattr(d, "shape") and hasattr(d, "dtype"):
            committed = getattr(d, "_committed", False)
            sharding = getattr(d, "sharding", None) if committed else None
            return jax.ShapeDtypeStruct(d.shape, d.dtype,
                                        sharding=sharding)
        return d
    return jax.tree_util.tree_map(one, tree)


def _framework_version():
    try:
        from . import __version__
        return __version__
    except ImportError:
        return "0"


def _salts():
    """Version material folded into every key: any component bump
    invalidates the whole cache (serialized executables are not
    portable across jax/jaxlib versions)."""
    try:
        import jaxlib
        jaxlib_v = getattr(jaxlib, "__version__", "?")
    except ImportError:
        jaxlib_v = "?"
    return (_framework_version(), jax.__version__, jaxlib_v,
            str(config.get("MXTPU_COMPILE_CACHE_SALT")))


def _platform_fingerprint():
    """Backend + device kind + topology: an executable compiled for one
    mesh shape or chip generation must never be served to another."""
    try:
        dev = jax.devices()[0]
        kind = getattr(dev, "device_kind", "?")
    except RuntimeError:
        kind = "?"
    return (jax.default_backend(), kind, jax.device_count(),
            jax.process_count())


def entry_key(fn_name, graph_hash, signature, donated=(), static_key=None):
    """Content-addressed cache key. `graph_hash` (sha256 of the
    StableHLO text) already pins program + shapes + dtypes; signature,
    donation mask, platform, and version salts are folded in explicitly
    so key semantics don't depend on what XLA happens to embed."""
    material = repr((
        "mxtpu-compile-cache", _SCHEMA, fn_name, graph_hash, signature,
        tuple(donated), static_key, _platform_fingerprint(), _salts()))
    return hashlib.sha256(material.encode()).hexdigest()


def graph_hash_of(lowered):
    """sha256 of the lowered StableHLO text — deterministic across
    processes (verified: no location info, stable symbol numbering)."""
    return hashlib.sha256(lowered.as_text().encode()).hexdigest()


# ---------------------------------------------------------------------------
# disk store
# ---------------------------------------------------------------------------

def cache_dir():
    return str(config.get("MXTPU_COMPILE_CACHE_DIR") or "")


def enabled():
    """True when MXTPU_COMPILE_CACHE_DIR names a cache directory."""
    return bool(cache_dir())


class _Store:
    """One directory of <key>.exe entries + sha256 sidecar manifests."""

    def __init__(self, root):
        self.root = root
        self._lock = threading.Lock()

    def path(self, key):
        return os.path.join(self.root, key + _SUFFIX)

    def get(self, key, fn_name=""):
        """-> entry dict, or None (miss / corrupt-evicted / stale)."""
        path = self.path(key)
        if not os.path.isfile(path):
            return None
        if not _ckpt.verify(path) or _ckpt.read_manifest(path) is None:
            # torn write, checksum mismatch, or a bare file someone
            # dropped in (cache entries always carry a manifest)
            self.evict(path, "corrupt", fn_name=fn_name)
            return None
        try:
            with open(path, "rb") as f:
                rec = pickle.loads(f.read())
        except Exception:
            # any unpickle failure is "corrupt"; the entry is replaced
            # by the fresh compile that follows
            self.evict(path, "corrupt", fn_name=fn_name)
            return None
        if (not isinstance(rec, dict) or rec.get("schema") != _SCHEMA
                or rec.get("salts") != _salts()):
            self.evict(path, "version", fn_name=fn_name)
            return None
        try:
            os.utime(path)  # LRU recency touch
        except OSError:
            pass
        return rec

    def put(self, key, rec, fn_name=""):
        data = pickle.dumps(rec, protocol=pickle.HIGHEST_PROTOCOL)
        with self._lock:
            os.makedirs(self.root, exist_ok=True)
            _ckpt.atomic_write_bytes(self.path(key), data,
                                     site="compile_cache.write",
                                     instance=fn_name)
            self._enforce_cap()

    def evict(self, path, reason, fn_name=""):
        for p in (path, _ckpt.manifest_path(path)):
            try:
                if os.path.exists(p):
                    os.remove(p)
            except OSError:
                pass
        _bump("evictions")
        telemetry.inc(EVICTIONS_TOTAL, help=_EVICT_HELP, reason=reason,
                      fn=fn_name)

    def entries(self):
        """[(mtime, bytes incl. manifest, path)] for every entry."""
        out = []
        try:
            names = os.listdir(self.root)
        except OSError:
            return out
        for name in names:
            if not name.endswith(_SUFFIX):
                continue
            path = os.path.join(self.root, name)
            try:
                st = os.stat(path)
            except OSError:
                continue
            size = st.st_size
            try:
                size += os.path.getsize(_ckpt.manifest_path(path))
            except OSError:
                pass
            out.append((st.st_mtime, size, path))
        return out

    def _enforce_cap(self):
        cap_mb = float(config.get("MXTPU_COMPILE_CACHE_MAX_MB"))
        if cap_mb <= 0:
            return
        cap = cap_mb * 1024 * 1024
        entries = sorted(self.entries())
        total = sum(size for _, size, _ in entries)
        # oldest-recency first; the newest entry is never evicted (a cap
        # smaller than one executable degrades to cache-of-one, not
        # cache-of-none)
        while total > cap and len(entries) > 1:
            _, size, path = entries.pop(0)
            self.evict(path, "lru")
            total -= size


_stores = {}
_stores_lock = threading.Lock()


def _store():
    root = cache_dir()
    if not root:
        return None
    root = os.path.abspath(root)
    with _stores_lock:
        st = _stores.get(root)
        if st is None:
            st = _stores[root] = _Store(root)
        return st


def clear():
    """Delete every entry in the active cache directory (tests/tools)."""
    st = _store()
    if st is None:
        return 0
    n = 0
    for _, _, path in st.entries():
        st.evict(path, "clear")
        n += 1
    return n


# ---------------------------------------------------------------------------
# the cached jit wrapper
# ---------------------------------------------------------------------------

def _has_tracer(args):
    return any(isinstance(leaf, jax.core.Tracer)
               for leaf in jax.tree_util.tree_leaves(args))


class CachedJit:
    """Wraps a `jax.jit(...)` callable with a persistent executable
    cache. Call it exactly like the jit; use `.warm(*abstract)` for AOT
    precompilation. Attribute access falls through to the wrapped jit,
    so `.lower(...)` etc. keep working."""

    is_cached = True

    def __init__(self, fn_name, wrapped, donated=(), static_key=None):
        self._name = fn_name
        self._wrapped = wrapped
        self._donated = tuple(donated)
        self._static_key = static_key
        self._compiled = {}   # canonical signature -> jax.stages.Compiled
        self._lock = threading.Lock()

    def __call__(self, *args):
        if _has_tracer(args):
            # traced through another jit / vjp: defer to the wrapped fn,
            # the outer program owns compilation
            return self._wrapped(*args)
        compiled = self._resolve(args)
        if compiled is None:
            return self._wrapped(*args)
        return compiled(*args)

    def warm(self, *abstract_args):
        """AOT path: resolve (and, on miss, compile + persist) the
        executable for `jax.ShapeDtypeStruct` args without executing.
        Returns "hit", "miss", "memo" (already resolved in-process), or
        "disabled"."""
        if not enabled():
            return "disabled"
        before = stats()
        sig = abstract_signature(abstract_args)
        with self._lock:
            memo = sig in self._compiled
        if memo:
            return "memo"
        if self._resolve(abstract_args) is None:
            return "disabled"
        after = stats()
        return "hit" if after["hits"] > before["hits"] else "miss"

    def aot_compile(self, *abstract_args):
        """Resolve the `jax.stages.Compiled` for abstract args via the
        cache (compiling and persisting on miss) — the AOT sibling of
        `__call__` for callers that want the executable itself
        (cost_analysis, warmup)."""
        compiled = self._resolve(abstract_args)
        if compiled is None:
            compiled = self._wrapped.lower(*abstract_args).compile()
        return compiled

    def _resolve(self, args):
        sig = abstract_signature(args)
        with self._lock:
            compiled = self._compiled.get(sig)
        if compiled is not None:
            return compiled
        st = _store()
        if st is None:
            return None
        t0 = time.perf_counter()
        try:
            lowered = self._wrapped.lower(*args)
            ghash = graph_hash_of(lowered)
        except Exception:
            logger.debug("compile cache: lowering failed for %s; "
                         "falling back to plain jit", self._name,
                         exc_info=True)
            return None
        key = entry_key(self._name, ghash, sig, donated=self._donated,
                        static_key=self._static_key)
        compiled = self._load(st, key, ghash, sig, t0)
        if compiled is None:
            compiled = self._compile_and_put(st, key, lowered, ghash,
                                             sig, t0)
        with self._lock:
            self._compiled[sig] = compiled
        return compiled

    def _load(self, st, key, ghash, sig, t0):
        rec = st.get(key, fn_name=self._name)
        if rec is None:
            return None
        try:
            from jax.experimental.serialize_executable import (
                deserialize_and_load)
            compiled = deserialize_and_load(
                rec["payload"], rec["in_tree"], rec["out_tree"])
        except Exception:
            # stale flatbuffer, partial entry the manifest missed, ...
            st.evict(st.path(key), "corrupt", fn_name=self._name)
            return None
        elapsed = time.perf_counter() - t0
        saved = max(0.0, float(rec.get("compile_s") or 0.0) - elapsed)
        _bump("hits")
        _bump("saved_seconds", saved)
        telemetry.inc(HITS_TOTAL, help=_HITS_HELP, fn=self._name)
        telemetry.inc(SAVED_SECONDS, amount=saved, help=_SAVED_HELP,
                      fn=self._name)
        # record the signature as known WITHOUT counting a compile:
        # the warm process must show zero compile events
        _compilereg.register_cached(self._name, sig, graph_hash=ghash[:16])
        return compiled

    def _compile_and_put(self, st, key, lowered, ghash, sig, t0):
        compiled = lowered.compile()
        compile_s = time.perf_counter() - t0
        _bump("misses")
        telemetry.inc(MISSES_TOTAL, help=_MISSES_HELP, fn=self._name)
        _compilereg.register(self._name, sig, compile_s=compile_s,
                             graph_hash=ghash[:16])
        try:
            from jax.experimental.serialize_executable import serialize
            payload, in_tree, out_tree = serialize(compiled)
            st.put(key, {
                "schema": _SCHEMA, "salts": _salts(),
                "payload": payload, "in_tree": in_tree,
                "out_tree": out_tree, "fn": self._name,
                "graph_hash": ghash, "compile_s": compile_s,
                "created": time.time(),
            }, fn_name=self._name)
        except Exception:
            # unserializable executable (callbacks, host buffers):
            # still usable in-process, just not persisted
            logger.debug("compile cache: could not persist %s",
                         self._name, exc_info=True)
        return compiled

    def __getattr__(self, name):
        return getattr(self._wrapped, name)


def wrap(fn_name, jitted, donated=(), static_key=None):
    """Wrap a fresh `jax.jit(...)` in a CachedJit when the cache is
    enabled; return it unchanged otherwise (zero overhead when off).
    The decision is taken at wrap time — build models after setting
    `MXTPU_COMPILE_CACHE_DIR`."""
    if not enabled():
        return jitted
    return CachedJit(fn_name, jitted, donated=donated,
                     static_key=static_key)
