"""Executor: a bound symbolic graph.

TPU-native equivalent of the reference's GraphExecutor
(ref: src/executor/graph_executor.cc — Init:298, Forward:65, Backward:77,
RunOps:1291). Instead of per-node cached engine ops + a memory planner, the
whole graph is ONE pure function: inference forward is `jax.jit` of it
(XLA does fusion/liveness/in-place planning), training forward uses
`jax.vjp` to hold the backward closure, mirroring the fwd/bwd split of the
reference API while keeping everything async on device.
"""
from __future__ import annotations

import logging

import jax
import jax.numpy as jnp

from . import compile_cache as _compile_cache
from . import config as _config
from . import random as _global_random
from . import telemetry as _telemetry
from .ndarray.ndarray import NDArray

__all__ = ["Executor"]

_log = logging.getLogger(__name__)

_VALIDATE_FINDINGS = "mxtpu_graph_validate_findings_total"


def _maybe_validate(symbol, args, aux):
    """Opt-in bind-time graph validation (MXNET_GRAPH_VALIDATE=warn|raise).

    The nnvm analog: the reference runs shape/type passes inside
    GraphExecutor::Init before any kernel exists; here the validator runs
    the same checks over the symbol being bound, using the bound arrays'
    shapes as ground truth, so a bad graph fails with per-node MXA
    diagnostics instead of a node-anonymous XLA trace error."""
    mode = str(_config.get("MXNET_GRAPH_VALIDATE")).lower()
    if mode in ("", "off", "0", "false"):
        return
    from .analysis import validate as _validate

    shapes = {n: tuple(a.shape) for n, a in {**args, **aux}.items()
              if a is not None}
    report = _validate(symbol, shapes=shapes)
    for d in report:
        _telemetry.inc(
            _VALIDATE_FINDINGS, 1,
            help="Findings emitted by bind-time graph validation "
                 "(MXNET_GRAPH_VALIDATE), by code and severity.",
            code=d.code, severity=str(d.severity))
        _log.warning("graph validation: %s", d)
    if mode == "raise":
        report.raise_if_errors()


class Executor:
    def __init__(self, symbol, ctx, args, args_grad, grad_req, aux_states):
        self._symbol = symbol
        self._ctx = ctx
        self.arg_dict = dict(args)  # name -> NDArray
        self.grad_dict = dict(args_grad or {})
        self.grad_req = dict(grad_req)
        self.aux_dict = dict(aux_states or {})
        _maybe_validate(symbol, self.arg_dict, self.aux_dict)
        self._eval_fn = symbol.make_eval_fn()
        self._needs_rng = any(
            (not n.is_var) and n.op.needs_rng for n in symbol._topo_nodes()
        )
        sym_name = getattr(symbol, "name", None) or "sym"
        self._jit_infer = _compile_cache.wrap(
            f"executor.infer[{sym_name}]",
            jax.jit(lambda a, x, k: self._eval_fn(a, x, k, False)),
            static_key=sym_name)
        self._vjp = None
        self._grad_names = None
        self.outputs: list[NDArray] = []
        self._monitor_callback = None
        # binds are rare and expensive (each implies an XLA compile), so a
        # post-mortem dump showing one near the failure is signal
        _telemetry.log_event("executor_bind", args=len(self.arg_dict),
                             outputs=len(symbol.list_outputs()))
        if not _compile_cache.enabled():
            # compile registry: two binds of the same symbol with
            # different arg shapes are a retrace of that graph. With the
            # persistent cache on, the wrapped infer jit registers its
            # real outcome (cached hit vs compile) on first dispatch
            # instead of this bind-implies-compile approximation.
            _telemetry.compilereg.register(
                f"executor.bind[{sym_name}]",
                tuple(sorted(
                    (n, tuple(a.shape), str(a.dtype))
                    for n, a in {**self.arg_dict, **self.aux_dict}.items()
                    if a is not None)))

    def warmup(self):
        """AOT-precompile the inference program into the persistent
        compile cache without executing a forward (serving warm-start;
        tools/warmup.py --infer). Abstract args mirror the bound slots,
        so the entry written here is the one forward(is_train=False)
        will look up. Returns the cache resolution status ("hit" /
        "miss" / "memo" / "disabled")."""
        if not getattr(self._jit_infer, "is_cached", False):
            return "disabled"
        args = {k: v._data for k, v in self.arg_dict.items()}
        aux = {k: v._data for k, v in self.aux_dict.items()}
        key = _global_random.next_key() if self._needs_rng else None
        abstract = _compile_cache.abstractify((args, aux, key))
        return self._jit_infer.warm(*abstract)

    # -- properties mirroring the reference Executor ----------------------
    @property
    def output_dict(self):
        return dict(zip(self._symbol.list_outputs(), self.outputs))

    def set_monitor_callback(self, callback, monitor_all=False):
        self._monitor_callback = callback

    # -- forward/backward --------------------------------------------------
    def forward(self, is_train=False, **kwargs):
        """(ref: GraphExecutor::Forward) — returns list of output NDArrays."""
        with _telemetry.span("executor.forward", train=is_train):
            return self._forward_impl(is_train, **kwargs)

    def _forward_impl(self, is_train=False, **kwargs):
        for k, v in kwargs.items():
            if k not in self.arg_dict:
                raise ValueError(f"unknown argument {k}")
            data = v._data if isinstance(v, NDArray) else jnp.asarray(v)
            slot = self.arg_dict[k]._data
            if data.dtype != slot.dtype:
                # slots keep their bound dtype; feeds cast into them
                # (ref: Executor.forward copies into the existing buffer,
                # executor.py arg_dict[name][:] = value) — this is what
                # makes a bf16-bound executor compute in bf16 from fp32
                # feeds instead of silently promoting back to fp32
                data = data.astype(slot.dtype)
            self.arg_dict[k]._data = data

        args = {k: v._data for k, v in self.arg_dict.items()}
        aux = {k: v._data for k, v in self.aux_dict.items()}
        key = _global_random.next_key() if self._needs_rng else None

        if not is_train:
            outs, _ = self._jit_infer(args, aux, key)
            self.outputs = [NDArray._from_data(o) for o in outs]
            _telemetry.ledger.track(self.outputs, "activations")
            self._vjp = None
            return self.outputs

        grad_names = [n for n, r in self.grad_req.items() if r != "null" and n in self.arg_dict]
        self._grad_names = grad_names
        grad_args = {n: args[n] for n in grad_names}
        other_args = {n: a for n, a in args.items() if n not in grad_args}

        def f(ga):
            full = {**ga, **other_args}
            outs, new_aux = self._eval_fn(full, aux, key, True)
            return tuple(outs), new_aux

        (outs, new_aux), vjp = jax.vjp(f, grad_args)
        # new_aux rides along as a primal output; zero cotangents at backward
        self._vjp = vjp
        self._n_outs = len(outs)
        self._new_aux_avals = {k: (v.shape, v.dtype) for k, v in new_aux.items()}
        for k, v in new_aux.items():
            if k in self.aux_dict:
                self.aux_dict[k]._data = v
        self.outputs = [NDArray._from_data(o) for o in outs]
        _telemetry.ledger.track(self.outputs, "activations")
        return self.outputs

    def backward(self, out_grads=None, is_train=True):
        """(ref: GraphExecutor::Backward) — accumulate into grad arrays."""
        with _telemetry.span("executor.backward"):
            return self._backward_impl(out_grads, is_train)

    def _backward_impl(self, out_grads=None, is_train=True):
        if self._vjp is None:
            raise RuntimeError("call forward(is_train=True) before backward()")
        if out_grads is None:
            cts = tuple(jnp.ones(o.shape, o._data.dtype) for o in self.outputs)
        else:
            if isinstance(out_grads, NDArray):
                out_grads = [out_grads]
            cts = tuple(
                g._data if isinstance(g, NDArray) else jnp.asarray(g) for g in out_grads
            )
        aux_cts = {
            k: jnp.zeros(shape, dtype) for k, (shape, dtype) in self._new_aux_avals.items()
        }
        (grad_dict,) = self._vjp((cts, aux_cts))
        for name, g in grad_dict.items():
            if name not in self.grad_dict:
                continue
            req = self.grad_req.get(name, "write")
            if req == "add":
                self.grad_dict[name]._data = self.grad_dict[name]._data + g
            else:
                self.grad_dict[name]._data = g
        self._vjp = None

    # -- param IO ----------------------------------------------------------
    def copy_params_from(self, arg_params, aux_params=None, allow_extra_params=False):
        """(ref: Executor::CopyParamsFrom)"""
        for name, arr in (arg_params or {}).items():
            if name in self.arg_dict:
                self.arg_dict[name]._data = jnp.asarray(
                    arr._data if isinstance(arr, NDArray) else arr,
                    dtype=self.arg_dict[name]._data.dtype,
                )
            elif not allow_extra_params:
                raise ValueError(f"unknown arg {name}")
        for name, arr in (aux_params or {}).items():
            if name in self.aux_dict:
                self.aux_dict[name]._data = jnp.asarray(
                    arr._data if isinstance(arr, NDArray) else arr,
                    dtype=self.aux_dict[name]._data.dtype,
                )
            elif not allow_extra_params:
                raise ValueError(f"unknown aux {name}")

    def reshape(self, partial_shaping=False, allow_up_sizing=False, **kwargs):
        """Rebind with new input shapes (cheap: XLA re-specializes on shape)."""
        data_shapes = {k: v for k, v in kwargs.items()}
        return self._symbol.simple_bind(
            ctx=self._ctx,
            grad_req={n: self.grad_req.get(n, "write") for n in self.arg_dict},
            **data_shapes,
        )
