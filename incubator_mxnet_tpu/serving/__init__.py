"""Online serving: continuous batching over a paged KV cache.

The reference framework ships no request-facing path (its serving story
was the C predict API over static graphs); this package is the TPU-native
one. `pages.PageAllocator` owns the global KV page pool; `engine
.ServingEngine` runs vLLM/Orca-style iteration-level scheduling: a fixed
set of decode slots advance one token per step in ONE compiled program
(`models.transformer.decode_step_paged` over
`ops.pallas_kernels.paged_decode_attention`), requests admit into free
slots with bucketed prefill and evict on EOS/length with immediate page
recycling. Every shape is static, so the steady state performs zero
retraces — gated by telemetry.compilereg and warmed by compile_cache.

Three optional levers stack on that base (each knob-off
byte-identical): `pages.PrefixCache` prefix-shares page-aligned prompt
KV copy-on-write (`MXTPU_PREFIX_CACHE`), chunked prefill interleaves
prompt chunks with decode steps (`MXTPU_PREFILL_CHUNK`), and n-gram
prompt-lookup speculation verifies drafts through one wide-query
program (`MXTPU_SPEC_NGRAM`/`MXTPU_SPEC_LOOKAHEAD`).

Above the single engine sits the fault-tolerant fleet layer:
`fleet.FleetRouter` health-checks replicas by heartbeat, fails
in-flight requests over mid-stream through the `fleet.RequestJournal`
(greedy decode makes the replayed continuation token-identical), and
runs zero-drop draining rolling restarts; `gateway.ServingGateway` is
the streaming HTTP front door with tenant-fair admission control
backpressured by KV page-pool occupancy.
"""
from .pages import PageAllocator, PrefixCache  # noqa: F401
from .engine import Request, RequestResult, ServingEngine  # noqa: F401
from .fleet import (  # noqa: F401
    FleetRouter, JournalEntry, Replica, RequestJournal)
from .gateway import ServingGateway  # noqa: F401

__all__ = ["PageAllocator", "PrefixCache", "Request", "RequestResult",
           "ServingEngine", "FleetRouter", "JournalEntry", "Replica",
           "RequestJournal", "ServingGateway"]
