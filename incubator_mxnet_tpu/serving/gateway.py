"""HTTP serving gateway: streaming generation in front of the fleet.

A small stdlib HTTP front end (the telemetry /metrics server is the
pattern — no web framework, `ThreadingHTTPServer` + one daemon thread)
that turns the FleetRouter into a service:

- `POST /v1/generate` — body `{"prompt": [ids...], "max_new_tokens": N,
  "eos_id": id?, "tenant": "name"?, "stream": true?}`. With
  `stream: true` (the default) the response is close-delimited NDJSON
  (HTTP/1.0, no Content-Length): one `{"event": "token", ...}` line per
  generated token as it is produced, then a terminal `done`/`failed`
  line. Tokens stream straight off the request journal, so a mid-stream
  replica failover is invisible here beyond a pause: the journal's
  epoch fence guarantees every index appears exactly once, in order.
- `GET /healthz` — fleet liveness for load balancers: 200 while at
  least one replica is healthy, 503 when draining or empty.

Admission control is backpressure, not buffering: a request is REJECTED
with `429 Retry-After` when its tenant's queue already holds
`MXTPU_GATEWAY_QUEUE_LIMIT` waiting requests or when every healthy
replica's KV page pool is above `MXTPU_GATEWAY_MAX_OCCUPANCY` — the
caller retries against a fleet that said so honestly instead of timing
out against one that lied. A draining fleet (rolling restart's final
step, SIGTERM) answers `503 Retry-After`: new work belongs on the
replacement fleet, in-flight work finishes here.

The `gateway.accept` fault site is consulted once per HTTP request
before admission (`drop`/`fail` → a clean 503 with outcome "injected",
`delay` → a slow accept path), which is how the chaos legs separate
"the gateway shed load" from "the fleet lost a request" — the former is
allowed, the latter never is.

Observability (the fleet observatory's front end):

- With tracing on (`MXTPU_TRACE_DIR`), every request gets a root
  `gateway.request` span on lane "gateway". An inbound W3C
  `traceparent` header is adopted (external client correlation); the
  response carries a `Traceparent` header naming the gateway span, the
  NDJSON stream opens with a `{"event": "trace", ...}` line, and the
  context rides `router.submit(trace_ctx=...)` down through
  `fleet.dispatch` into each replica's `serving.request` span — one
  trace per request, fleet-wide.
- `GET /metrics` is the fleet metrics federation point: the router's
  rollup + per-replica gauges are refreshed, then the whole process
  registry is rendered as Prometheus text.
- `MXTPU_GATEWAY_ACCESS_LOG` (a path, or `-` for stderr) turns on an
  NDJSON access log: one line per generate request with tenant,
  status, token counts, queue-wait/TTFT/latency, trace id, serving
  replica, and failover count — replacing the silently-discarded
  `log_message` default.
"""
from __future__ import annotations

import json
import queue
import sys
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from .. import config, telemetry
from ..analysis import sanitizers as _sanitizers
from ..resilience import fault as _fault
from ..telemetry import distributed as _dtrace

__all__ = ["ServingGateway"]

GW_REQUESTS_TOTAL = "mxtpu_gateway_requests_total"
GW_INFLIGHT = "mxtpu_gateway_inflight"
GW_ACCESS_LINES_TOTAL = "mxtpu_gateway_access_log_lines_total"

GATEWAY_SPAN = "gateway.request"
GATEWAY_LANE = "gateway"


class _Reject(Exception):
    """Admission refused: (status, outcome label, body dict)."""

    def __init__(self, status, outcome, body, retry_after=None):
        super().__init__(body.get("error", outcome))
        self.status = status
        self.outcome = outcome
        self.body = body
        self.retry_after = retry_after


class _GatewayServer(ThreadingHTTPServer):
    daemon_threads = True
    gateway = None  # set by ServingGateway before serving


class _AccessLog:
    """NDJSON access log behind `MXTPU_GATEWAY_ACCESS_LOG`: one JSON
    line per request, appended to the configured path (`-` = stderr,
    empty = off). Thread-safe; the file opens lazily on first write so
    an idle gateway never touches disk."""

    def __init__(self, target):
        self._target = str(target or "")
        self._lock = _sanitizers.san_lock("serving.gateway_access_log")
        self._file = None

    @property
    def enabled(self):
        return bool(self._target)

    def write(self, record):
        if not self._target:
            return
        line = json.dumps(record, sort_keys=True) + "\n"
        with self._lock:
            if self._target == "-":
                sys.stderr.write(line)
            else:
                if self._file is None:
                    self._file = open(self._target, "a", encoding="utf-8")
                self._file.write(line)
                self._file.flush()
        telemetry.inc(GW_ACCESS_LINES_TOTAL)

    def close(self):
        with self._lock:
            if self._file is not None:
                self._file.close()
                self._file = None


class _Handler(BaseHTTPRequestHandler):
    # HTTP/1.0: responses are close-delimited, which is what lets the
    # token stream flush line-by-line without chunked-encoding framing
    protocol_version = "HTTP/1.0"
    _trace = None  # gateway.request trace context for this request

    def log_message(self, fmt, *args):
        # BaseHTTPRequestHandler's default stderr chatter stays off;
        # non-generate traffic (healthz, metrics, 404s) gets a basic
        # access-log line, while /v1/generate writes its rich line from
        # handle_generate (with journal-derived latency/replica fields)
        gw = self.server.gateway
        if gw is None or self.path == "/v1/generate":
            return
        gw.access_log.write({"ts": time.time(), "method": self.command,
                             "path": self.path, "line": fmt % args})

    def _reply(self, status, body, retry_after=None):
        data = (json.dumps(body) + "\n").encode()
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(data)))
        if retry_after is not None:
            self.send_header("Retry-After", f"{retry_after:g}")
        if self._trace is not None:
            self.send_header("Traceparent", _dtrace.format_traceparent(
                self._trace["tid"], self._trace["sid"]))
        self.end_headers()
        self.wfile.write(data)

    def do_GET(self):
        gw = self.server.gateway
        if self.path == "/healthz":
            status, body = gw.health()
            self._reply(status, body)
        elif self.path == "/metrics":
            data = gw.metrics_text().encode()
            self.send_response(200)
            self.send_header("Content-Type",
                             "text/plain; version=0.0.4")
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)
        else:
            self._reply(404, {"error": f"unknown path {self.path!r}"})

    def do_POST(self):
        gw = self.server.gateway
        if self.path != "/v1/generate":
            self._reply(404, {"error": f"unknown path {self.path!r}"})
            return
        gw.handle_generate(self)


class ServingGateway:
    """The fleet's HTTP front door. Binds 127.0.0.1:`port` (the
    `MXTPU_GATEWAY_PORT` knob; 0 = ephemeral, read the bound port back
    from `.port`) and serves until `close()`."""

    def __init__(self, router, *, port=None, queue_limit=None,
                 max_occupancy=None, retry_after=None,
                 request_timeout=600.0):
        self.router = router
        self.queue_limit = int(
            queue_limit if queue_limit is not None
            else config.get("MXTPU_GATEWAY_QUEUE_LIMIT"))
        self.max_occupancy = float(
            max_occupancy if max_occupancy is not None
            else config.get("MXTPU_GATEWAY_MAX_OCCUPANCY"))
        self.retry_after = float(
            retry_after if retry_after is not None
            else config.get("MXTPU_GATEWAY_RETRY_AFTER"))
        self.request_timeout = float(request_timeout)
        self.access_log = _AccessLog(config.get("MXTPU_GATEWAY_ACCESS_LOG"))
        self._inflight = 0
        self._inflight_lock = _sanitizers.san_lock("serving.gateway")
        bind_port = int(port if port is not None
                        else config.get("MXTPU_GATEWAY_PORT"))
        self._server = _GatewayServer(("127.0.0.1", bind_port), _Handler)
        self._server.gateway = self
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True,
            name="mxtpu-gateway-http")
        self._thread.start()
        telemetry.log_event("gateway_started", port=self.port)

    @property
    def port(self):
        return self._server.server_address[1]

    def close(self):
        self._server.shutdown()
        self._server.server_close()
        self._thread.join(timeout=10.0)
        self.access_log.close()

    def metrics_text(self):
        """Fleet metrics federation: refresh the router's rollup and
        per-replica gauges, then render the whole process registry —
        in-process replicas share it, so one scrape sees the gateway,
        the router, and every replica's engine under one namespace."""
        self.router.export_fleet_gauges()
        return telemetry.prometheus_text()

    # -- request path ------------------------------------------------------

    def health(self):
        healthy = self.router.healthy_count()
        if self.router.draining:
            return 503, {"status": "draining", "healthy_replicas": healthy,
                         "retry_after_s": self.retry_after}
        if not healthy:
            return 503, {"status": "unhealthy", "healthy_replicas": 0}
        return 200, {"status": "ok", "healthy_replicas": healthy}

    def _admit(self, raw, tr=None):
        """Fault site, drain check, parse, backpressure, journal submit.
        Returns (entry_id, tenant, event queue); raises _Reject."""
        try:
            _fault.injector().raise_for("gateway.accept")
        except (ConnectionError, OSError) as e:
            raise _Reject(503, "injected",
                          {"error": f"fault injection: {e}"},
                          retry_after=self.retry_after) from None
        if self.router.draining:
            raise _Reject(503, "draining",
                          {"error": "fleet is draining (rolling restart); "
                                    "retry against the replacement fleet"},
                          retry_after=self.retry_after)
        try:
            payload = json.loads(raw or b"{}")
            prompt = payload["prompt"]
            max_new = int(payload["max_new_tokens"])
        except (ValueError, TypeError, KeyError) as e:
            raise _Reject(400, "error",
                          {"error": f"bad request body: {e!r}"}) from None
        tenant = str(payload.get("tenant", "default"))
        if self.router.tenant_depth(tenant) >= self.queue_limit:
            raise _Reject(429, "rejected",
                          {"error": f"tenant {tenant!r} queue is full "
                                    f"({self.queue_limit})"},
                          retry_after=self.retry_after)
        if self.router.min_occupancy() >= self.max_occupancy:
            raise _Reject(429, "rejected",
                          {"error": "KV page pools above "
                                    f"{self.max_occupancy:.0%} on every "
                                    "healthy replica"},
                          retry_after=self.retry_after)
        events = queue.Queue()
        try:
            entry_id = self.router.submit(
                prompt, max_new, eos_id=payload.get("eos_id"),
                tenant=tenant, sink=events.put,
                trace_ctx=(tr["tid"], tr["sid"]) if tr else None)
        except ValueError as e:
            raise _Reject(400, "error", {"error": str(e)}) from None
        except RuntimeError as e:
            raise _Reject(503, "draining" if "draining" in str(e)
                          else "error", {"error": str(e)},
                          retry_after=self.retry_after) from None
        return entry_id, tenant, payload.get("stream", True), events

    def handle_generate(self, handler):
        raw = handler.rfile.read(
            int(handler.headers.get("Content-Length") or 0))
        t0 = time.monotonic()
        tr = None
        if _dtrace.trace_active():
            # root span context: adopt the client's traceparent when it
            # sent one (external correlation), else start a fresh trace
            inbound = _dtrace.parse_traceparent(
                handler.headers.get("traceparent"))
            tr = {"tid": inbound[0] if inbound else _dtrace.new_id(),
                  "pid": inbound[1] if inbound else None,
                  "sid": _dtrace.new_id(), "ns": time.time_ns()}
            handler._trace = tr
        entry_id = tenant = None
        try:
            entry_id, tenant, stream, events = self._admit(raw, tr)
        except _Reject as rej:
            telemetry.inc(GW_REQUESTS_TOTAL, outcome=rej.outcome)
            handler._reply(rej.status, rej.body,
                           retry_after=rej.retry_after)
            self._finish_http(tr, t0, status=rej.status,
                              outcome=rej.outcome, tenant=tenant,
                              entry_id=None)
            return
        with self._inflight_lock:
            self._inflight += 1
            telemetry.set_gauge(GW_INFLIGHT, self._inflight)
        try:
            outcome = (self._stream_response(handler, entry_id, events)
                       if stream else
                       self._unary_response(handler, entry_id, events))
        except (BrokenPipeError, ConnectionResetError):
            outcome = "error"  # client went away mid-stream
        finally:
            with self._inflight_lock:
                self._inflight -= 1
                telemetry.set_gauge(GW_INFLIGHT, self._inflight)
        telemetry.inc(GW_REQUESTS_TOTAL, outcome=outcome)
        self._finish_http(tr, t0,
                          status=200 if outcome == "ok" else 500,
                          outcome=outcome, tenant=tenant,
                          entry_id=entry_id)

    def _finish_http(self, tr, t0, *, status, outcome, tenant, entry_id):
        """End-of-request bookkeeping: close the gateway.request root
        span and write the access-log line (rich journal-derived fields
        when the request got far enough to have an entry)."""
        dur_s = time.monotonic() - t0
        if tr is not None:
            rec = {"name": GATEWAY_SPAN, "tid": tr["tid"],
                   "sid": tr["sid"], "ts": tr["ns"],
                   "dur_ns": max(0, int(dur_s * 1e9)),
                   "lane": GATEWAY_LANE,
                   "extra": {"status": status, "outcome": outcome,
                             "tenant": tenant, "entry": entry_id}}
            if tr.get("pid"):
                rec["pid"] = tr["pid"]
            _dtrace.record_span(rec)
        if not self.access_log.enabled:
            return
        line = {"ts": time.time(), "method": "POST",
                "path": "/v1/generate", "status": status,
                "outcome": outcome, "tenant": tenant, "entry": entry_id,
                "latency_s": round(dur_s, 6),
                "trace_id": tr["tid"] if tr else None}
        if entry_id is not None:
            e = self.router.journal.get(entry_id)
            line.update({
                "tenant": e.tenant,
                "prompt_tokens": int(e.prompt.size),
                "output_tokens": len(e.tokens),
                "replica": e.replica_id,
                "failovers": e.resubmits,
                "finish_reason": e.finish_reason,
                "queue_wait_s": (round(e.assigned_at - e.submitted_at, 6)
                                 if e.assigned_at else None),
                "ttft_s": (round(e.first_token_at - e.submitted_at, 6)
                           if e.first_token_at else None)})
            if e.finished_at:
                line["latency_s"] = round(
                    e.finished_at - e.submitted_at, 6)
        self.access_log.write(line)

    def _next_event(self, events):
        try:
            return events.get(timeout=self.request_timeout)
        except queue.Empty:
            return {"event": "failed",
                    "error": f"gateway timeout after "
                             f"{self.request_timeout:g}s"}

    def _stream_response(self, handler, entry_id, events):
        handler.send_response(200)
        handler.send_header("Content-Type", "application/x-ndjson")
        handler.send_header("X-Entry-Id", str(entry_id))
        tr = handler._trace
        if tr is not None:
            handler.send_header("Traceparent", _dtrace.format_traceparent(
                tr["tid"], tr["sid"]))
        # no Content-Length: HTTP/1.0 + Connection: close delimit the
        # stream, each event line flushes as the fleet produces it
        handler.send_header("Connection", "close")
        handler.end_headers()
        if tr is not None:
            # trace echo: streaming clients learn the trace id first,
            # before any token, so a hung stream is already correlatable
            handler.wfile.write((json.dumps(
                {"event": "trace", "trace_id": tr["tid"],
                 "entry_id": entry_id}) + "\n").encode())
            handler.wfile.flush()
        while True:
            ev = self._next_event(events)
            handler.wfile.write((json.dumps(ev) + "\n").encode())
            handler.wfile.flush()
            if ev.get("event") == "done":
                return "ok"
            if ev.get("event") == "failed":
                return "error"

    def _unary_response(self, handler, entry_id, events):
        while True:
            ev = self._next_event(events)
            if ev.get("event") == "done":
                body = {"entry_id": entry_id,
                        "tokens": ev["tokens"],
                        "finish_reason": ev["finish_reason"],
                        "resubmits": ev.get("resubmits", 0)}
                if handler._trace is not None:
                    body["trace_id"] = handler._trace["tid"]
                handler._reply(200, body)
                return "ok"
            if ev.get("event") == "failed":
                handler._reply(500, {"entry_id": entry_id,
                                     "error": ev.get("error", "failed")})
                return "error"
