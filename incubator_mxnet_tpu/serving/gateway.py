"""HTTP serving gateway: streaming generation in front of the fleet.

A small stdlib HTTP front end (the telemetry /metrics server is the
pattern — no web framework, `ThreadingHTTPServer` + one daemon thread)
that turns the FleetRouter into a service:

- `POST /v1/generate` — body `{"prompt": [ids...], "max_new_tokens": N,
  "eos_id": id?, "tenant": "name"?, "stream": true?}`. With
  `stream: true` (the default) the response is close-delimited NDJSON
  (HTTP/1.0, no Content-Length): one `{"event": "token", ...}` line per
  generated token as it is produced, then a terminal `done`/`failed`
  line. Tokens stream straight off the request journal, so a mid-stream
  replica failover is invisible here beyond a pause: the journal's
  epoch fence guarantees every index appears exactly once, in order.
- `GET /healthz` — fleet liveness for load balancers: 200 while at
  least one replica is healthy, 503 when draining or empty.

Admission control is backpressure, not buffering: a request is REJECTED
with `429 Retry-After` when its tenant's queue already holds
`MXTPU_GATEWAY_QUEUE_LIMIT` waiting requests or when every healthy
replica's KV page pool is above `MXTPU_GATEWAY_MAX_OCCUPANCY` — the
caller retries against a fleet that said so honestly instead of timing
out against one that lied. A draining fleet (rolling restart's final
step, SIGTERM) answers `503 Retry-After`: new work belongs on the
replacement fleet, in-flight work finishes here.

The `gateway.accept` fault site is consulted once per HTTP request
before admission (`drop`/`fail` → a clean 503 with outcome "injected",
`delay` → a slow accept path), which is how the chaos legs separate
"the gateway shed load" from "the fleet lost a request" — the former is
allowed, the latter never is.
"""
from __future__ import annotations

import json
import queue
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from .. import config, telemetry
from ..analysis import sanitizers as _sanitizers
from ..resilience import fault as _fault

__all__ = ["ServingGateway"]

GW_REQUESTS_TOTAL = "mxtpu_gateway_requests_total"
GW_INFLIGHT = "mxtpu_gateway_inflight"


class _Reject(Exception):
    """Admission refused: (status, outcome label, body dict)."""

    def __init__(self, status, outcome, body, retry_after=None):
        super().__init__(body.get("error", outcome))
        self.status = status
        self.outcome = outcome
        self.body = body
        self.retry_after = retry_after


class _GatewayServer(ThreadingHTTPServer):
    daemon_threads = True
    gateway = None  # set by ServingGateway before serving


class _Handler(BaseHTTPRequestHandler):
    # HTTP/1.0: responses are close-delimited, which is what lets the
    # token stream flush line-by-line without chunked-encoding framing
    protocol_version = "HTTP/1.0"

    def log_message(self, fmt, *args):  # quiet; telemetry has the counts
        pass

    def _reply(self, status, body, retry_after=None):
        data = (json.dumps(body) + "\n").encode()
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(data)))
        if retry_after is not None:
            self.send_header("Retry-After", f"{retry_after:g}")
        self.end_headers()
        self.wfile.write(data)

    def do_GET(self):
        gw = self.server.gateway
        if self.path == "/healthz":
            status, body = gw.health()
            self._reply(status, body)
        else:
            self._reply(404, {"error": f"unknown path {self.path!r}"})

    def do_POST(self):
        gw = self.server.gateway
        if self.path != "/v1/generate":
            self._reply(404, {"error": f"unknown path {self.path!r}"})
            return
        gw.handle_generate(self)


class ServingGateway:
    """The fleet's HTTP front door. Binds 127.0.0.1:`port` (the
    `MXTPU_GATEWAY_PORT` knob; 0 = ephemeral, read the bound port back
    from `.port`) and serves until `close()`."""

    def __init__(self, router, *, port=None, queue_limit=None,
                 max_occupancy=None, retry_after=None,
                 request_timeout=600.0):
        self.router = router
        self.queue_limit = int(
            queue_limit if queue_limit is not None
            else config.get("MXTPU_GATEWAY_QUEUE_LIMIT"))
        self.max_occupancy = float(
            max_occupancy if max_occupancy is not None
            else config.get("MXTPU_GATEWAY_MAX_OCCUPANCY"))
        self.retry_after = float(
            retry_after if retry_after is not None
            else config.get("MXTPU_GATEWAY_RETRY_AFTER"))
        self.request_timeout = float(request_timeout)
        self._inflight = 0
        self._inflight_lock = _sanitizers.san_lock("serving.gateway")
        bind_port = int(port if port is not None
                        else config.get("MXTPU_GATEWAY_PORT"))
        self._server = _GatewayServer(("127.0.0.1", bind_port), _Handler)
        self._server.gateway = self
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True,
            name="mxtpu-gateway-http")
        self._thread.start()
        telemetry.log_event("gateway_started", port=self.port)

    @property
    def port(self):
        return self._server.server_address[1]

    def close(self):
        self._server.shutdown()
        self._server.server_close()
        self._thread.join(timeout=10.0)

    # -- request path ------------------------------------------------------

    def health(self):
        healthy = self.router.healthy_count()
        if self.router.draining:
            return 503, {"status": "draining", "healthy_replicas": healthy,
                         "retry_after_s": self.retry_after}
        if not healthy:
            return 503, {"status": "unhealthy", "healthy_replicas": 0}
        return 200, {"status": "ok", "healthy_replicas": healthy}

    def _admit(self, raw):
        """Fault site, drain check, parse, backpressure, journal submit.
        Returns (entry_id, tenant, event queue); raises _Reject."""
        try:
            _fault.injector().raise_for("gateway.accept")
        except (ConnectionError, OSError) as e:
            raise _Reject(503, "injected",
                          {"error": f"fault injection: {e}"},
                          retry_after=self.retry_after) from None
        if self.router.draining:
            raise _Reject(503, "draining",
                          {"error": "fleet is draining (rolling restart); "
                                    "retry against the replacement fleet"},
                          retry_after=self.retry_after)
        try:
            payload = json.loads(raw or b"{}")
            prompt = payload["prompt"]
            max_new = int(payload["max_new_tokens"])
        except (ValueError, TypeError, KeyError) as e:
            raise _Reject(400, "error",
                          {"error": f"bad request body: {e!r}"}) from None
        tenant = str(payload.get("tenant", "default"))
        if self.router.tenant_depth(tenant) >= self.queue_limit:
            raise _Reject(429, "rejected",
                          {"error": f"tenant {tenant!r} queue is full "
                                    f"({self.queue_limit})"},
                          retry_after=self.retry_after)
        if self.router.min_occupancy() >= self.max_occupancy:
            raise _Reject(429, "rejected",
                          {"error": "KV page pools above "
                                    f"{self.max_occupancy:.0%} on every "
                                    "healthy replica"},
                          retry_after=self.retry_after)
        events = queue.Queue()
        try:
            entry_id = self.router.submit(
                prompt, max_new, eos_id=payload.get("eos_id"),
                tenant=tenant, sink=events.put)
        except ValueError as e:
            raise _Reject(400, "error", {"error": str(e)}) from None
        except RuntimeError as e:
            raise _Reject(503, "draining" if "draining" in str(e)
                          else "error", {"error": str(e)},
                          retry_after=self.retry_after) from None
        return entry_id, tenant, payload.get("stream", True), events

    def handle_generate(self, handler):
        raw = handler.rfile.read(
            int(handler.headers.get("Content-Length") or 0))
        try:
            entry_id, tenant, stream, events = self._admit(raw)
        except _Reject as rej:
            telemetry.inc(GW_REQUESTS_TOTAL, outcome=rej.outcome)
            handler._reply(rej.status, rej.body,
                           retry_after=rej.retry_after)
            return
        with self._inflight_lock:
            self._inflight += 1
            telemetry.set_gauge(GW_INFLIGHT, self._inflight)
        try:
            outcome = (self._stream_response(handler, entry_id, events)
                       if stream else
                       self._unary_response(handler, entry_id, events))
        except (BrokenPipeError, ConnectionResetError):
            outcome = "error"  # client went away mid-stream
        finally:
            with self._inflight_lock:
                self._inflight -= 1
                telemetry.set_gauge(GW_INFLIGHT, self._inflight)
        telemetry.inc(GW_REQUESTS_TOTAL, outcome=outcome)

    def _next_event(self, events):
        try:
            return events.get(timeout=self.request_timeout)
        except queue.Empty:
            return {"event": "failed",
                    "error": f"gateway timeout after "
                             f"{self.request_timeout:g}s"}

    def _stream_response(self, handler, entry_id, events):
        handler.send_response(200)
        handler.send_header("Content-Type", "application/x-ndjson")
        handler.send_header("X-Entry-Id", str(entry_id))
        # no Content-Length: HTTP/1.0 + Connection: close delimit the
        # stream, each event line flushes as the fleet produces it
        handler.send_header("Connection", "close")
        handler.end_headers()
        while True:
            ev = self._next_event(events)
            handler.wfile.write((json.dumps(ev) + "\n").encode())
            handler.wfile.flush()
            if ev.get("event") == "done":
                return "ok"
            if ev.get("event") == "failed":
                return "error"

    def _unary_response(self, handler, entry_id, events):
        while True:
            ev = self._next_event(events)
            if ev.get("event") == "done":
                handler._reply(200, {"entry_id": entry_id,
                                     "tokens": ev["tokens"],
                                     "finish_reason": ev["finish_reason"],
                                     "resubmits": ev.get("resubmits", 0)})
                return "ok"
            if ev.get("event") == "failed":
                handler._reply(500, {"entry_id": entry_id,
                                     "error": ev.get("error", "failed")})
                return "error"
