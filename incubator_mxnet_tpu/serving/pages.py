"""Host-side page allocator + prefix cache for the paged KV cache.

The device pool (`models.transformer.init_paged_kv_cache`) is
`(L, num_pages, page_size, H, Dh)`; this allocator owns the free list
over `num_pages` and hands out page ids. Page 0 is the RESERVED NULL
PAGE: it is never allocated, and dead decode slots / padded prefill rows
scatter their writes there, so an all-zero page-table row is always a
safe "empty" row. Allocation is all-or-nothing (a request either gets
every page it needs or stays in the queue — no mid-decode exhaustion),
and `free()` returns pages for immediate reuse without touching device
memory: stale K/V in a recycled page is dead data beyond every live
sequence's `n_valid` until overwritten.

Pages are REFCOUNTED (the vLLM/PagedAttention block-sharing design):
`alloc()` hands out pages at refcount 1, `share()` adds references so
several page tables can point at the same physical page read-only, and
`free()` decrements — a page only returns to the free list when its
last reference drops. `cow()` is the copy-on-write primitive: it turns
a shared reference into an exclusively-owned page id (the caller copies
the device bytes and rewrites its table row).

`PrefixCache` is the hash-trie prefix index over page-aligned token-id
chunks that makes sharing automatic: after a prompt prefills, its full
pages are inserted keyed by their token content (plus one "partial
leaf" for a non-page-aligned prompt tail); later prompts look up their
longest cached page-aligned prefix and map those pages instead of
recomputing them. The cache holds one reference per cached page, so
entries survive the inserting request's eviction; LRU eviction only
touches pages no live request references (refcount == the cache's own
single reference).

Pure host bookkeeping — no jax imports, safe to use from schedulers and
tests without a device.
"""
from __future__ import annotations

from collections import deque

import numpy as np

__all__ = ["PageAllocator", "PrefixCache", "NULL_PAGE"]

NULL_PAGE = 0

_recorder = None


def _log_page_event(op, pages, owner, free):
    """`page_lifecycle` flight-recorder events (alloc/share/cow/free
    with owner provenance) so a post-mortem dump can reconstruct who
    leaked a page. Emitted only while a page sanitizer is attached
    (MXTPU_SANITIZERS=pages) — the default path does not spend ring
    capacity or event-encoding time on per-page bookkeeping. Lazily
    bound so this module stays importable without the telemetry package
    (and keeps its no-jax-imports contract)."""
    global _recorder
    if _recorder is None:
        try:
            from ..telemetry import recorder as _rec
        except Exception:
            _recorder = False
            return
        _recorder = _rec
    if _recorder is False:
        return
    _recorder.log_event("page_lifecycle", op=op, pages=list(pages),
                        owner=owner, free=free)


class PageAllocator:
    """Refcounting free-list allocator over a pool of `num_pages` KV
    pages of `page_size` tokens each (page 0 reserved)."""

    def __init__(self, num_pages: int, page_size: int):
        if num_pages < 2:
            raise ValueError("need at least 2 pages (page 0 is the "
                             f"reserved null page), got {num_pages}")
        if page_size < 1:
            raise ValueError(f"page_size must be >= 1, got {page_size}")
        self.num_pages = int(num_pages)
        self.page_size = int(page_size)
        # FIFO recycling keeps page ids roughly round-robin, which makes
        # reuse-after-free bugs show up deterministically in tests
        self._free = deque(range(1, self.num_pages))
        self._refs: dict[int, int] = {}
        # armed by analysis.sanitizers.attach_page_sanitizer when the
        # pages sanitizer is on; every transition below feeds it
        self.sanitizer = None

    @property
    def num_free(self) -> int:
        return len(self._free)

    @property
    def num_in_use(self) -> int:
        """Physical pages with at least one reference — a page shared by
        N tables still counts ONCE (it occupies one pool slot)."""
        return len(self._refs)

    @property
    def capacity(self) -> int:
        """Allocatable pages (the null page doesn't count)."""
        return self.num_pages - 1

    def occupancy(self) -> float:
        """Fraction of allocatable pages currently owned, in [0, 1]."""
        return self.num_in_use / self.capacity

    def fragmentation(self) -> float:
        """Free-list fragmentation in [0, 1]: 1 minus the largest run of
        CONSECUTIVE page ids in the free list over the free count. 0 when
        the free pages form one contiguous id range (or none are free);
        approaches 1 as recycling interleaves the pool. Paged attention
        doesn't need contiguity — this is a health signal for the
        /debug/engine view, not an allocator constraint."""
        if not self._free:
            return 0.0
        ids = sorted(self._free)
        longest = run = 1
        for prev, cur in zip(ids, ids[1:]):
            run = run + 1 if cur == prev + 1 else 1
            longest = max(longest, run)
        return 1.0 - longest / len(ids)

    def pages_needed(self, n_tokens: int) -> int:
        """Pages required to hold `n_tokens` cache entries."""
        if n_tokens <= 0:
            return 0
        return -(-int(n_tokens) // self.page_size)

    def alloc(self, n_pages: int, owner=None):
        """Allocate `n_pages` pages at refcount 1; returns the page-id
        list, or None when the pool can't cover it (all-or-nothing —
        the caller keeps the request queued instead of half-admitting
        it). `owner` is provenance (request id, "prefix_cache") for the
        page_lifecycle event stream and the page sanitizer's mapping
        registry."""
        n_pages = int(n_pages)
        if n_pages < 0:
            raise ValueError(f"cannot alloc {n_pages} pages")
        if n_pages > len(self._free):
            return None
        pages = [self._free.popleft() for _ in range(n_pages)]
        for p in pages:
            self._refs[p] = 1
        if self.sanitizer is not None:
            self.sanitizer.on_alloc(pages, owner=owner)
            if pages:
                _log_page_event("alloc", pages, owner, len(self._free))
        return pages

    def extend(self, pages, old_tokens: int, new_tokens: int, owner=None):
        """Grow an allocation that covers `old_tokens` so it covers
        `new_tokens`: allocates only the delta pages and returns the new
        combined list (the input list is not mutated), or None when the
        pool can't cover the growth (nothing is allocated)."""
        need = self.pages_needed(new_tokens) - self.pages_needed(old_tokens)
        if need <= 0:
            return list(pages)
        extra = self.alloc(need, owner=owner)
        if extra is None:
            return None
        return list(pages) + extra

    def share(self, pages, owner=None):
        """Add one reference to each page — a second page table now maps
        it read-only. Sharing a page that isn't live raises (that table
        would read recycled garbage)."""
        pages = list(pages)
        bad = [p for p in pages if p not in self._refs]
        if bad:
            if self.sanitizer is not None:
                self.sanitizer.on_share(bad, owner=owner)
            raise ValueError(f"sharing pages not currently allocated: {bad}")
        if self.sanitizer is not None:
            self.sanitizer.on_share(pages, owner=owner)
        for p in pages:
            self._refs[p] += 1
        if self.sanitizer is not None and pages:
            _log_page_event("share", pages, owner, len(self._free))

    def refcount(self, page: int) -> int:
        """References currently held on `page` (0 = free/null)."""
        return self._refs.get(int(page), 0)

    def refcount_histogram(self) -> dict:
        """{refcount: number of pages} over live pages — the sharing
        shape of the pool for /debug/engine."""
        hist: dict[int, int] = {}
        for c in self._refs.values():
            hist[c] = hist.get(c, 0) + 1
        return hist

    def cow(self, page: int, owner=None):
        """Copy-on-write: turn one reference on a SHARED `page` into an
        exclusively-owned page id. Returns `page` unchanged when the
        caller already holds the only reference (no copy needed); else
        allocates a fresh page, moves the caller's reference onto it and
        returns the new id — the caller must then copy the device bytes
        and repoint its table row. Returns None when the pool has no
        free page for the copy (nothing changes; the caller can evict
        prefix-cache entries and retry)."""
        page = int(page)
        count = self._refs.get(page)
        if not count:
            if self.sanitizer is not None:
                self.sanitizer.on_cow(page, None, owner=owner)
            raise ValueError(f"cow on page {page} which is not allocated")
        if count == 1:
            return page
        fresh = self.alloc(1, owner=owner)
        if fresh is None:
            return None
        self._refs[page] = count - 1
        if self.sanitizer is not None:
            self.sanitizer.on_cow(page, fresh[0], owner=owner)
            _log_page_event("cow", [page, fresh[0]], owner, len(self._free))
        return fresh[0]

    def free(self, pages, owner=None):
        """Drop one reference per page; a page returns to the pool for
        immediate reuse when its LAST reference drops. Freeing a page
        that isn't currently allocated (double free, or the null page)
        raises — that's a scheduler bug corrupting another request's
        cache, not a condition to paper over."""
        pages = list(pages)
        bad = [p for p in pages if p not in self._refs]
        if bad:
            if self.sanitizer is not None:
                self.sanitizer.on_free(bad, owner=owner)
            raise ValueError(f"freeing pages not currently allocated: {bad}")
        if self.sanitizer is not None:
            self.sanitizer.on_free(pages, owner=owner)
        for p in pages:
            if self._refs[p] > 1:
                self._refs[p] -= 1
            else:
                del self._refs[p]
                self._free.append(p)
        if self.sanitizer is not None and pages:
            _log_page_event("free", pages, owner, len(self._free))

    def table_row(self, pages, width: int):
        """Pad a page list to a fixed-width page-table row (null-page
        padded) — the static shape decode_step_paged needs."""
        if len(pages) > width:
            raise ValueError(f"{len(pages)} pages exceed table width "
                             f"{width}")
        return list(pages) + [NULL_PAGE] * (width - len(pages))


class _Node:
    """One full-page trie node: `page` holds exactly the `page_size`
    tokens of its chunk key; `children` continue the prefix; `partials`
    map a shorter-than-a-page token tail (bytes key) to (page, tokens)
    leaves."""

    __slots__ = ("page", "children", "partials", "tick")

    def __init__(self, page, tick):
        self.page = page
        self.children: dict = {}
        self.partials: dict = {}
        self.tick = tick


class PrefixCache:
    """Hash-trie prefix index over page-aligned token-id chunks.

    Keys are the token ids of each `page_size` chunk of a prompt (as
    bytes), so two prompts share cached pages exactly as far as their
    page-aligned token prefixes agree. The cache holds ONE allocator
    reference per cached page; `evict()` walks leaves in LRU order and
    only drops pages whose refcount equals that single cache reference
    (no live request is mapped onto them) — the "LRU at refcount 0"
    rule counted in live-request references.

    `max_pages` caps the cached-page count (0 = unbounded, bounded only
    by pool pressure via the engine's on-demand eviction).
    """

    def __init__(self, allocator: PageAllocator, max_pages: int = 0):
        self.allocator = allocator
        self.max_pages = int(max_pages)
        self._children: dict = {}   # root level full-page nodes
        self._partials: dict = {}   # root level partial leaves
        self._pages: dict = {}      # page -> (container_dict, key)
        self._tick = 0
        self.evictions = 0

    # -- introspection ----------------------------------------------------

    @property
    def cached_pages(self) -> int:
        return len(self._pages)

    def stats(self) -> dict:
        return {"cached_pages": self.cached_pages,
                "capacity": self.max_pages,
                "evictions": self.evictions}

    # -- core -------------------------------------------------------------

    def _touch(self):
        self._tick += 1
        return self._tick

    @staticmethod
    def _key(tokens) -> bytes:
        return tokens.tobytes()

    def lookup(self, prompt):
        """Longest cached page-aligned prefix of `prompt` (np.int32).

        Returns (pages, partial): `pages` is the list of full cached
        pages covering prompt[:len(pages)*page_size]; `partial` is
        (page, chunk_tokens) for a cached partial leaf stored directly
        under the last matched node whose tokens extend the match, or
        None. The caller decides how much of the partial chunk its
        prompt tail actually shares (and takes its own references via
        `allocator.share`)."""
        ps = self.allocator.page_size
        pages = []
        children, partials = self._children, self._partials
        i = 0
        tick = self._touch()
        while (i + 1) * ps <= prompt.size:
            node = children.get(self._key(prompt[i * ps:(i + 1) * ps]))
            if node is None:
                break
            node.tick = tick
            pages.append(node.page)
            children, partials = node.children, node.partials
            i += 1
        partial = None
        tail = prompt[i * ps:]
        if tail.size and partials:
            # a partial leaf matches when one is a prefix of the other:
            # walk the (few) leaves at this node and take the longest
            # shared length
            best = 0
            for ptoks, (page, _) in partials.items():
                n = min(len(ptoks) // 4, tail.size)  # int32 = 4 bytes
                chunk = np.frombuffer(ptoks, dtype=np.int32)
                if n and np.array_equal(chunk[:n], tail[:n]):
                    if n > best:
                        best = n
                        partial = (page, chunk)
        return pages, partial

    def insert(self, prompt, pages):
        """Register a freshly-prefilled prompt's pages: full chunks go
        into the trie, a non-aligned tail becomes a partial leaf. Only
        NEW entries take a cache reference (chunks already cached keep
        the original page — by construction the caller mapped that same
        page). Returns the set of `pages` indices the cache now also
        references (the engine marks the partial one copy-on-write)."""
        prompt = np.asarray(prompt, np.int32)
        ps = self.allocator.page_size
        tick = self._touch()
        children, partials = self._children, self._partials
        newly_cached = set()
        i = 0
        while (i + 1) * ps <= prompt.size:
            key = self._key(prompt[i * ps:(i + 1) * ps])
            node = children.get(key)
            if node is None:
                page = pages[i]
                self.allocator.share([page], owner="prefix_cache")
                node = _Node(page, tick)
                children[key] = node
                self._pages[page] = (children, key)
                newly_cached.add(i)
            else:
                node.tick = tick
            children, partials = node.children, node.partials
            i += 1
        tail = prompt[i * ps:]
        if tail.size:
            key = self._key(tail)
            if key not in partials and i < len(pages):
                page = pages[i]
                if page not in self._pages:
                    self.allocator.share([page], owner="prefix_cache")
                    partials[key] = (page, tick)
                    self._pages[page] = (partials, key)
                    newly_cached.add(i)
        if self.max_pages:
            self.evict(self.cached_pages - self.max_pages)
        return newly_cached

    def release(self, page):
        """Targeted drop of the cache's reference on `page` (only held
        for leaf entries — partial leaves and childless full nodes).
        Returns True when released. The engine's COW fallback: when the
        pool has no page for the copy, stealing the cache's reference
        back makes the writer exclusive again."""
        entry = self._pages.get(page)
        if entry is None:
            return False
        container, key = entry
        node = container.get(key)
        if isinstance(node, _Node) and (node.children or node.partials):
            return False  # mid-trie: children key off this page's chunk
        del container[key]
        del self._pages[page]
        self.allocator.free([page], owner="prefix_cache")
        self.evictions += 1
        return True

    def evict(self, n_pages: int) -> int:
        """Evict up to `n_pages` cached pages in LRU order, touching
        only pages no live request references (refcount == the cache's
        single reference). Interior trie nodes become evictable once
        their subtree goes — the scan loops until it frees enough or a
        full pass makes no progress. Returns pages actually freed."""
        if n_pages <= 0:
            return 0
        freed = 0
        while freed < n_pages:
            candidates = []  # (tick, page, container, key)
            stack = [(self._children, self._partials)]
            while stack:
                children, partials = stack.pop()
                for key, (page, tick) in list(partials.items()):
                    if self.allocator.refcount(page) == 1:
                        candidates.append((tick, page, partials, key))
                for key, node in list(children.items()):
                    if not node.children and not node.partials:
                        if self.allocator.refcount(node.page) == 1:
                            candidates.append(
                                (node.tick, node.page, children, key))
                    else:
                        stack.append((node.children, node.partials))
            if not candidates:
                break
            candidates.sort(key=lambda c: c[0])
            progressed = False
            for _, page, container, key in candidates:
                if freed >= n_pages:
                    break
                if key in container and page in self._pages:
                    del container[key]
                    del self._pages[page]
                    self.allocator.free([page], owner="prefix_cache")
                    self.evictions += 1
                    freed += 1
                    progressed = True
            if not progressed:
                break
        return freed
