"""Host-side page allocator for the paged KV cache.

The device pool (`models.transformer.init_paged_kv_cache`) is
`(L, num_pages, page_size, H, Dh)`; this allocator owns the free list
over `num_pages` and hands out page ids. Page 0 is the RESERVED NULL
PAGE: it is never allocated, and dead decode slots / padded prefill rows
scatter their writes there, so an all-zero page-table row is always a
safe "empty" row. Allocation is all-or-nothing (a request either gets
every page it needs or stays in the queue — no mid-decode exhaustion),
and `free()` returns pages for immediate reuse without touching device
memory: stale K/V in a recycled page is dead data beyond every live
sequence's `n_valid` until overwritten.

Pure host bookkeeping — no jax imports, safe to use from schedulers and
tests without a device.
"""
from __future__ import annotations

from collections import deque

__all__ = ["PageAllocator", "NULL_PAGE"]

NULL_PAGE = 0


class PageAllocator:
    """Free-list allocator over a pool of `num_pages` KV pages of
    `page_size` tokens each (page 0 reserved)."""

    def __init__(self, num_pages: int, page_size: int):
        if num_pages < 2:
            raise ValueError("need at least 2 pages (page 0 is the "
                             f"reserved null page), got {num_pages}")
        if page_size < 1:
            raise ValueError(f"page_size must be >= 1, got {page_size}")
        self.num_pages = int(num_pages)
        self.page_size = int(page_size)
        # FIFO recycling keeps page ids roughly round-robin, which makes
        # reuse-after-free bugs show up deterministically in tests
        self._free = deque(range(1, self.num_pages))
        self._owned: set[int] = set()

    @property
    def num_free(self) -> int:
        return len(self._free)

    @property
    def num_in_use(self) -> int:
        return len(self._owned)

    @property
    def capacity(self) -> int:
        """Allocatable pages (the null page doesn't count)."""
        return self.num_pages - 1

    def occupancy(self) -> float:
        """Fraction of allocatable pages currently owned, in [0, 1]."""
        return self.num_in_use / self.capacity

    def fragmentation(self) -> float:
        """Free-list fragmentation in [0, 1]: 1 minus the largest run of
        CONSECUTIVE page ids in the free list over the free count. 0 when
        the free pages form one contiguous id range (or none are free);
        approaches 1 as recycling interleaves the pool. Paged attention
        doesn't need contiguity — this is a health signal for the
        /debug/engine view, not an allocator constraint."""
        if not self._free:
            return 0.0
        ids = sorted(self._free)
        longest = run = 1
        for prev, cur in zip(ids, ids[1:]):
            run = run + 1 if cur == prev + 1 else 1
            longest = max(longest, run)
        return 1.0 - longest / len(ids)

    def pages_needed(self, n_tokens: int) -> int:
        """Pages required to hold `n_tokens` cache entries."""
        if n_tokens <= 0:
            return 0
        return -(-int(n_tokens) // self.page_size)

    def alloc(self, n_pages: int):
        """Allocate `n_pages` pages; returns the page-id list, or None
        when the pool can't cover it (all-or-nothing — the caller keeps
        the request queued instead of half-admitting it)."""
        n_pages = int(n_pages)
        if n_pages < 0:
            raise ValueError(f"cannot alloc {n_pages} pages")
        if n_pages > len(self._free):
            return None
        pages = [self._free.popleft() for _ in range(n_pages)]
        self._owned.update(pages)
        return pages

    def extend(self, pages, old_tokens: int, new_tokens: int):
        """Grow an allocation that covers `old_tokens` so it covers
        `new_tokens`: allocates only the delta pages and returns the new
        combined list (the input list is not mutated), or None when the
        pool can't cover the growth (nothing is allocated)."""
        need = self.pages_needed(new_tokens) - self.pages_needed(old_tokens)
        if need <= 0:
            return list(pages)
        extra = self.alloc(need)
        if extra is None:
            return None
        return list(pages) + extra

    def free(self, pages):
        """Return pages to the pool for immediate reuse. Freeing a page
        that isn't currently allocated (double free, or the null page)
        raises — that's a scheduler bug corrupting another request's
        cache, not a condition to paper over."""
        pages = list(pages)
        bad = [p for p in pages if p not in self._owned]
        if bad:
            raise ValueError(f"freeing pages not currently allocated: {bad}")
        for p in pages:
            self._owned.discard(p)
            self._free.append(p)

    def table_row(self, pages, width: int):
        """Pad a page list to a fixed-width page-table row (null-page
        padded) — the static shape decode_step_paged needs."""
        if len(pages) > width:
            raise ValueError(f"{len(pages)} pages exceed table width "
                             f"{width}")
        return list(pages) + [NULL_PAGE] * (width - len(pages))
