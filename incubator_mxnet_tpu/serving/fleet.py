"""Fault-tolerant serving fleet: journal, replicas, health-checked router.

One ServingEngine is a single point of failure: the process dies and
every in-flight request dies with it. This module is the fleet layer
that composes the repo's robustness primitives into a serving stack
that survives replica death mid-stream:

- `RequestJournal` — the durable record of every accepted request:
  (prompt, sampling params, tokens streamed so far). Token deliveries
  are tagged with the entry's ASSIGNMENT EPOCH and absolute position;
  only current-epoch tokens extending the accepted stream are taken, so
  a failed-over request resumes exactly after its last delivered token
  and a zombie replica (slow, declared dead, still streaming) can never
  duplicate one. Greedy decode makes the replayed continuation
  token-identical to the undisturbed run — the chaos gate asserts it.
- `Replica` — one ServingEngine behind the router's RPC seam. `pump()`
  runs one scheduler step, updates the replica's heartbeat, and
  forwards newly streamed tokens to the journal. The fault sites live
  here: `replica.kill` (abrupt death — no drain, no more heartbeats)
  and `replica.rpc` (drop/fail = a lost exchange, delay = a SLOW
  replica whose heartbeats go stale while it keeps producing).
- `FleetRouter` — membership, health, scheduling. Replicas are marked
  dead after `MXTPU_FLEET_HEARTBEAT_TIMEOUT` seconds without a pump
  heartbeat (failure detection is ONLY heartbeats — a dead replica
  answers nothing, so nothing else is trustworthy); their journaled
  in-flight requests are deterministically resubmitted to survivors,
  resuming from the last streamed token. Admission is per-tenant fair
  round-robin to the least-loaded healthy replica. `drain()` is the
  rolling-restart handshake (PR 8's SIGTERM discipline extended to
  serving): stop admitting, hand queued work back to the router,
  finish in-slot requests, leave; process SIGTERM drains the whole
  fleet. A full rolling restart drops zero requests.

Two execution modes share all of that logic: `tick()` runs one router
iteration inline (the deterministic manual-pump mode every test and
chaos scenario drives), while `start()` runs one pump thread per
replica plus a router thread (the live mode behind serving/gateway.py —
per-replica threads so one slow replica cannot stall the others'
heartbeats).

Lock order (lockdep-checked under MXTPU_SANITIZERS=locks):
serving.fleet -> serving.replica -> serving.engine -> serving.journal.
"""
from __future__ import annotations

import dataclasses
import itertools
import threading
import time
from collections import deque

import numpy as np

from .. import config, telemetry
from ..analysis import sanitizers as _sanitizers
from ..resilience import fault as _fault
from ..resilience import preemption as _preemption
from ..telemetry import distributed as _dtrace
from ..telemetry import exporters as _exporters
from ..telemetry import recorder as _recorder

__all__ = ["JournalEntry", "RequestJournal", "Replica", "FleetRouter"]

FLEET_REPLICAS = "mxtpu_fleet_replicas"
FAILOVERS_TOTAL = "mxtpu_fleet_failovers_total"
RESUBMITS_TOTAL = "mxtpu_fleet_resubmits_total"
DRAINS_TOTAL = "mxtpu_fleet_drains_total"
DUP_DROPPED_TOTAL = "mxtpu_fleet_dup_tokens_dropped_total"
LOST_TOTAL = "mxtpu_fleet_lost_requests_total"
# fleet-level rollups + per-replica federation gauges (the gateway's
# /metrics aggregation; ROADMAP item 1's autoscaler input)
FLEET_QUEUE_DEPTH = "mxtpu_fleet_queue_depth"
FLEET_OLDEST_QUEUED = "mxtpu_fleet_oldest_queued_seconds"
FLEET_REPLICA_HEALTH = "mxtpu_fleet_replica_health"
FLEET_TOTAL_QUEUE_DEPTH = "mxtpu_fleet_total_queue_depth"
FLEET_PAGE_OCCUPANCY = "mxtpu_fleet_page_occupancy"
FLEET_REPLICA_QUEUE_DEPTH = "mxtpu_fleet_replica_queue_depth"
FLEET_REPLICA_SLOTS = "mxtpu_fleet_replica_slots_in_use"
FLEET_REPLICA_OCCUPANCY = "mxtpu_fleet_replica_page_occupancy"

REPLICA_STATES = ("healthy", "draining", "dead", "left")

# router-side trace records (registered in telemetry/names.py): the
# causal chain gateway.request -> fleet.dispatch -> serving.request,
# with fleet.failover spanning the outage window between losing a
# replica and re-dispatching on the survivor. Emitted straight through
# distributed.record_span — zero-cost when tracing is off.
DISPATCH_SPAN = "fleet.dispatch"
FAILOVER_SPAN = "fleet.failover"
RESUBMIT_SPAN = "fleet.resubmit"
# journal token-delivery record (not a span): absolute positions each
# accepted delivery covered — trace_merge --fleet --check proves no
# position was ever delivered twice from these
DELIVERY_KIND = "fleet_delivery"
ROUTER_LANE = "router"


def _trace_ts(tr, clk):
    """Wall-clock ns for a router-clock instant: deltas come from the
    injectable fleet clock (fake clocks in tests/chaos), anchored to the
    wall time captured when the entry was journaled."""
    return tr["ns_submit"] + int((clk - tr["clk_submit"]) * 1e9)


@dataclasses.dataclass
class JournalEntry:
    """One accepted request's full recovery record. `tokens` is the
    client-visible stream: every token in it has been delivered exactly
    once, and a resubmission's engine prompt is `prompt + tokens` so the
    continuation picks up right after the last delivered token."""
    entry_id: int
    tenant: str
    prompt: np.ndarray  # (T_p,) int32
    max_new_tokens: int
    eos_id: int | None
    submitted_at: float
    sink: object = None          # callable(event dict) or None
    tokens: list = dataclasses.field(default_factory=list)
    epoch: int = 0               # bumped on every (re)assignment release
    state: str = "queued"        # queued | assigned | done | failed
    replica_id: str | None = None
    engine_rid: int | None = None
    resubmits: int = 0           # failover resubmissions consumed
    assigned_at: float = 0.0     # first assignment (queue-wait anchor)
    first_token_at: float = 0.0
    finished_at: float = 0.0
    finish_reason: str | None = None
    error: str | None = None
    # distributed-trace context (None with tracing off): tid shared by
    # every span the request produces anywhere in the fleet, psid the
    # gateway's root span id, ns_submit/clk_submit the wall/router-clock
    # anchor pair, plus transient dispatch/failover bookkeeping
    trace: dict | None = None


class RequestJournal:
    """Requests the gateway accepted and what each has streamed so far.

    Deliveries carry (epoch, absolute position): stale epochs are the
    zombie-replica path, positions below the accepted length are
    duplicates — both are counted and dropped, never re-emitted, so the
    client-facing sink sees every position exactly once, in order."""

    def __init__(self, clock=time.monotonic, slo=None):
        self._lock = _sanitizers.san_lock("serving.journal")
        self._clock = clock
        self._entries: dict[int, JournalEntry] = {}
        self._ids = itertools.count()
        self.slo = slo or None
        self.dup_dropped = 0
        self.lost = 0

    def record(self, prompt, max_new_tokens, eos_id, tenant, sink):
        with self._lock:
            entry = JournalEntry(
                entry_id=next(self._ids), tenant=str(tenant),
                prompt=np.asarray(prompt, np.int32).reshape(-1),
                max_new_tokens=int(max_new_tokens),
                eos_id=eos_id, submitted_at=self._clock(), sink=sink)
            self._entries[entry.entry_id] = entry
            return entry

    def get(self, entry_id):
        with self._lock:
            return self._entries[entry_id]

    def bind(self, entry, replica_id, engine_rid):
        with self._lock:
            entry.state = "assigned"
            entry.replica_id = str(replica_id)
            entry.engine_rid = engine_rid
            if not entry.assigned_at:
                entry.assigned_at = self._clock()

    def release(self, entry):
        """Unbind for resubmission: the epoch bump is the dedup fence —
        anything the old assignment still delivers is stale."""
        with self._lock:
            entry.epoch += 1
            entry.state = "queued"
            entry.replica_id = None
            entry.engine_rid = None

    def on_tokens(self, entry_id, epoch, start, tokens):
        """Accept a delivery of continuation tokens at absolute
        positions [start, start+len). Returns how many were accepted."""
        with self._lock:
            entry = self._entries[entry_id]
            taken = 0
            if entry.state in ("done", "failed") or epoch != entry.epoch:
                dropped = len(tokens)
            else:
                dropped = 0
                now = self._clock()
                for j, tok in enumerate(tokens):
                    pos = start + j
                    if pos < len(entry.tokens):
                        dropped += 1  # duplicate of a delivered position
                        continue
                    if pos > len(entry.tokens):
                        raise RuntimeError(
                            f"journal gap: entry {entry_id} delivered "
                            f"position {pos} with only "
                            f"{len(entry.tokens)} tokens accepted")
                    entry.tokens.append(int(tok))
                    taken += 1
                    if not entry.first_token_at:
                        entry.first_token_at = now
                    self._emit_locked(entry, {
                        "event": "token", "index": pos, "token": int(tok)})
                if taken and entry.trace is not None:
                    # accepted-delivery record: the absolute position
                    # range this delivery appended. trace_merge --fleet
                    # --check proves per-entry contiguity (monotone
                    # journal positions, no position delivered twice).
                    tr = entry.trace
                    _dtrace.record_span({
                        "kind": DELIVERY_KIND, "ts": _trace_ts(tr, now),
                        "tid": tr["tid"], "entry": entry.entry_id,
                        "epoch": epoch,
                        "start": len(entry.tokens) - taken, "n": taken,
                        "replica": entry.replica_id, "lane": ROUTER_LANE})
            if dropped:
                self.dup_dropped += dropped
                telemetry.inc(DUP_DROPPED_TOTAL, amount=float(dropped))
            return taken

    def on_finish(self, entry_id, epoch, reason):
        """A replica reports the entry finished ('eos' | 'length').
        Stale epochs (the zombie finishing after failover already
        re-ran the request) are ignored."""
        with self._lock:
            entry = self._entries[entry_id]
            if entry.state in ("done", "failed") or epoch != entry.epoch:
                return False
            self._finish_locked(entry, reason)
            return True

    def finish_direct(self, entry, reason):
        """Router-side completion without a replica: a resubmission
        whose streamed tokens already satisfy EOS/length."""
        with self._lock:
            if entry.state in ("done", "failed"):
                return
            self._finish_locked(entry, reason)

    def fail(self, entry, error):
        with self._lock:
            if entry.state in ("done", "failed"):
                return
            entry.state = "failed"
            entry.error = str(error)
            entry.finished_at = self._clock()
            self.lost += 1
            telemetry.inc(LOST_TOTAL)
            telemetry.log_event("fleet_request_lost",
                                entry=entry.entry_id, error=str(error))
            self._emit_locked(entry, {
                "event": "failed", "entry_id": entry.entry_id,
                "error": str(error)})

    def _finish_locked(self, entry, reason):
        entry.state = "done"
        entry.finish_reason = reason
        entry.finished_at = self._clock()
        self._emit_locked(entry, {
            "event": "done", "entry_id": entry.entry_id,
            "finish_reason": reason, "tokens": list(entry.tokens),
            "resubmits": entry.resubmits})
        if self.slo is not None:
            self.slo.observe_request(
                ttft=(entry.first_token_at - entry.submitted_at
                      if entry.first_token_at else None),
                queue_wait=(entry.assigned_at - entry.submitted_at
                            if entry.assigned_at else None),
                request_latency=entry.finished_at - entry.submitted_at)

    @staticmethod
    def _emit_locked(entry, event):
        if entry.sink is not None:
            entry.sink(event)

    def assigned_to(self, replica_id):
        with self._lock:
            return sorted(
                (e for e in self._entries.values()
                 if e.state == "assigned" and e.replica_id == replica_id),
                key=lambda e: e.entry_id)

    def unfinished(self):
        with self._lock:
            return sorted(
                (e for e in self._entries.values()
                 if e.state not in ("done", "failed")),
                key=lambda e: e.entry_id)

    def snapshot(self):
        with self._lock:
            states = {}
            for e in self._entries.values():
                states[e.state] = states.get(e.state, 0) + 1
            return {"entries": len(self._entries), "states": states,
                    "dup_tokens_dropped": self.dup_dropped,
                    "lost": self.lost}

    def dump_entries(self):
        """Per-entry forensics rows (the failover post-mortem dump's
        journal snapshot): enough to replay the resume decision for
        every request that was in flight when a replica died."""
        with self._lock:
            return [{
                "entry": e.entry_id, "tenant": e.tenant,
                "state": e.state, "epoch": e.epoch,
                "replica": e.replica_id, "engine_rid": e.engine_rid,
                "tokens_delivered": len(e.tokens),
                "max_new_tokens": e.max_new_tokens,
                "resubmits": e.resubmits,
                "finish_reason": e.finish_reason, "error": e.error,
                "trace_id": (e.trace or {}).get("tid"),
            } for e in sorted(self._entries.values(),
                              key=lambda e: e.entry_id)]


class Replica:
    """One ServingEngine behind the router's RPC seam.

    The replica's heartbeat IS its scheduler pump: every successful
    `pump()` stamps `last_beat`. A replica that stops pumping — killed
    by the `replica.kill` fault site, `kill()` from a chaos driver, or
    a real crash in the live mode — simply goes silent, and the router
    learns the only way a router can: the heartbeat timeout."""

    def __init__(self, replica_id, engine, journal, clock=time.monotonic):
        self.replica_id = str(replica_id)
        self.engine = engine
        self.journal = journal
        self._clock = clock
        # the replica id is the engine's timeline lane: every
        # serving.request span this engine emits lands on a per-replica
        # lane in the merged fleet trace
        if getattr(engine, "trace_lane", None) is None:
            engine.trace_lane = self.replica_id
        self._lock = _sanitizers.san_lock("serving.replica")
        self.state = "healthy"
        self.last_beat = clock()
        self.pumps = 0
        # silent death (replica.kill fault, chaos silent_kill, a real
        # crash): the replica stops pumping and beating but the
        # ROUTER-visible state stays as-is — the router must discover
        # the corpse the honest way, through the heartbeat timeout
        self._failed = False
        # engine rid -> [entry_id, epoch, base, delivered]: the delivery
        # cursor. `base` = journal tokens at (re)submission, so engine
        # continuation position i is absolute position base + i.
        self._bindings: dict = {}
        self._orphans: list = []

    # -- router-facing RPC surface ----------------------------------------

    def dispatch(self, entry, allow_draining=False):
        """Submit a journal entry (or its resumption) into the engine.
        The resume prompt is `prompt + tokens streamed so far`, with the
        token budget reduced by what was already delivered — greedy
        decode then continues token-identically. `replica.rpc` faults
        apply (drop/fail raise to the router; delay slows the call).
        `allow_draining` is the fleet-wide-shutdown exception: with no
        healthy survivors left, draining replicas finish the stragglers."""
        _fault.injector().raise_for("replica.rpc", self.replica_id)
        with self._lock:
            ok = ("healthy", "draining") if allow_draining else ("healthy",)
            if self._failed or self.state not in ok:
                raise ConnectionError(
                    f"replica {self.replica_id} is {self.state}, "
                    f"not accepting dispatches")
            base = len(entry.tokens)
            prompt = entry.prompt if not base else np.concatenate(
                [entry.prompt, np.asarray(entry.tokens, np.int32)])
            # the engine's serving.request span adopts the fleet trace
            # id and parents under this dispatch's fleet.dispatch span
            tr = entry.trace
            ctx = ((tr["tid"], tr.get("dispatch_sid"))
                   if tr is not None else None)
            rid = self.engine.submit(prompt, entry.max_new_tokens - base,
                                     entry.eos_id, trace_ctx=ctx)
            self._bindings[rid] = [entry.entry_id, entry.epoch, base, 0]
            return rid

    def pump(self):
        """One scheduler heartbeat: consult the fault sites, run one
        engine step when there is work, stamp the heartbeat, forward
        new tokens/finishes to the journal. Returns False once dead."""
        with self._lock:
            if self._failed or self.state in ("dead", "left"):
                return False
            if _fault.injector().action("replica.kill", self.replica_id):
                # abrupt death: engine state (KV pages, queue,
                # half-streamed outputs) is gone; no drain, no further
                # heartbeats — and no state change the router could
                # cheat off. Recovery is journal failover only, after
                # the heartbeat timeout exposes the corpse.
                self._failed = True
                self._bindings.clear()
                telemetry.log_event("fleet_replica_killed",
                                    replica=self.replica_id)
                return False
        # the router<->replica exchange: a delay here is a SLOW replica
        # (heartbeat stamped late), drop/fail a lost exchange (no step,
        # no heartbeat) — both without killing anything
        act = _fault.injector().sleep_for("replica.rpc", self.replica_id)
        if act in ("drop", "fail"):
            return True
        with self._lock:
            if self._failed or self.state in ("dead", "left"):
                return False
            if self.engine.queue_depth or self.engine.slots_in_use:
                self.engine.step()
            self.last_beat = self._clock()
            self.pumps += 1
            if self._bindings:
                self._deliver_locked()
            return True

    def _deliver_locked(self):
        results = self.engine.results()
        live = self.engine.live_tokens()
        for rid in list(self._bindings):
            entry_id, epoch, base, delivered = b = self._bindings[rid]
            res = results.get(rid)
            toks = res.tokens if res is not None else live.get(rid)
            if toks is not None and len(toks) > delivered:
                self.journal.on_tokens(entry_id, epoch,
                                       base + delivered, toks[delivered:])
                b[3] = len(toks)
            if res is None:
                continue
            del self._bindings[rid]
            if res.finish_reason in ("eos", "length"):
                self.journal.on_finish(entry_id, epoch, res.finish_reason)
            else:
                # evicted/cancelled without the router unbinding first:
                # a replica-local loss the router must requeue
                self._orphans.append(entry_id)

    # -- drain handshake (rolling restarts) --------------------------------

    def begin_drain(self, handoff=True):
        """Stop admitting; hand engine-QUEUED requests back to the
        router for immediate placement elsewhere (they hold no pages —
        nothing is lost by moving them); in-slot requests decode to
        completion here. Returns the handed-off journal entry ids.
        `handoff=False` is the fleet-wide-shutdown variant: with every
        replica draining there is nowhere to hand work to, so queued
        requests are finished locally instead."""
        with self._lock:
            if self.state != "healthy":
                return []
            self.state = "draining"
            handed = []
            for rid in self.engine.queued_request_ids() if handoff else ():
                b = self._bindings.pop(rid, None)
                # unbound BEFORE the cancel: the engine's "cancelled"
                # result then has no binding, so no client-facing event
                self.engine.cancel(rid)
                if b is not None:
                    handed.append(b[0])
            telemetry.log_event("fleet_replica_draining",
                                replica=self.replica_id,
                                handed_off=len(handed))
            return handed

    def drained(self):
        with self._lock:
            return (self.state == "draining"
                    and not self.engine.queue_depth
                    and not self.engine.slots_in_use
                    and not self._bindings)

    def leave(self):
        """Drain complete: leave the router. With the page sanitizer
        armed this is also a quiescence proof — a drained replica that
        still holds page references leaked them (MXS013)."""
        with self._lock:
            if self.state != "draining":
                return False
            self.state = "left"
        san = getattr(self.engine, "_page_san", None)
        if san is not None:
            san.assert_quiescent()
        telemetry.log_event("fleet_replica_left", replica=self.replica_id)
        return True

    def silent_kill(self):
        """Chaos helper: abrupt, silent death. Heartbeats stop NOW,
        nothing is handed off, and the router-visible state does NOT
        change — detection must come from the heartbeat timeout."""
        with self._lock:
            if self._failed or self.state in ("dead", "left"):
                return
            self._failed = True
            self._bindings.clear()

    def mark_dead(self):
        """Router-side transition once the heartbeat timeout expired:
        the replica is now officially a corpse."""
        with self._lock:
            if self.state in ("dead", "left"):
                return
            self.state = "dead"
            self._failed = True
            self._bindings.clear()

    # -- introspection -----------------------------------------------------

    def heartbeat_age(self, now):
        with self._lock:
            return now - self.last_beat

    def inflight(self):
        with self._lock:
            return len(self._bindings)

    def take_orphans(self):
        with self._lock:
            orphans, self._orphans = self._orphans, []
            return orphans


class FleetRouter:
    """Health-checked router over a fleet of serving replicas.

    `tick()` is one router iteration (manual-pump mode): SIGTERM check,
    heartbeat health check + failover, drain progression, per-tenant
    fair dispatch, one pump per live replica. `start()` runs the same
    phases on background threads for the live HTTP gateway."""

    def __init__(self, *, clock=time.monotonic, heartbeat_timeout=None,
                 max_resubmits=None, slo=None):
        self._clock = clock
        self.heartbeat_timeout = float(
            heartbeat_timeout if heartbeat_timeout is not None
            else config.get("MXTPU_FLEET_HEARTBEAT_TIMEOUT"))
        self.max_resubmits = int(
            max_resubmits if max_resubmits is not None
            else config.get("MXTPU_FLEET_MAX_RESUBMITS"))
        self._lock = _sanitizers.san_lock("serving.fleet")
        self.journal = RequestJournal(clock=clock, slo=slo)
        self._replicas: dict[str, Replica] = {}
        self._tenants: dict[str, deque] = {}
        self._tenant_order: list = []
        self._rr = 0
        self._rid_ids = itertools.count(1)
        self.failovers = 0
        self.resubmits = 0
        self.drains = 0
        self.draining = False  # fleet-wide (SIGTERM): stop admitting
        self.ticks = 0
        # chaos_serving --inject lost-request: silently skip ONE failover
        # resubmission — the zero-lost-requests gate MUST catch this
        self._chaos_lose_one = False
        # chaos_serving --inject broken-chain: drop ONE resubmitted
        # entry's trace context before redispatch, orphaning the
        # survivor's serving.request span — trace_merge --fleet --check
        # MUST catch the broken causal chain
        self._chaos_break_trace = False
        self._stop = threading.Event()
        self._threads: dict = {}
        self._started = False
        self._interval = 0.002
        _exporters.register_debug_handler("/debug/fleet",
                                          self.debug_snapshot)

    # -- membership --------------------------------------------------------

    def add_replica(self, engine, replica_id=None):
        """Join a replica (a fresh ServingEngine) to the fleet; returns
        the Replica handle. In threaded mode its pump thread starts
        immediately — this is the rolling-restart replacement path."""
        with self._lock:
            rid = str(replica_id if replica_id is not None
                      else f"r{next(self._rid_ids)}")
            live = self._replicas.get(rid)
            if live is not None and live.state in ("healthy", "draining"):
                raise ValueError(f"replica id {rid!r} is already active")
            rep = Replica(rid, engine, self.journal, clock=self._clock)
            self._replicas[rid] = rep
            started = self._started
        telemetry.log_event("fleet_replica_joined", replica=rep.replica_id)
        if started:
            self._spawn_replica_thread(rep)
        return rep

    def replica(self, replica_id):
        with self._lock:
            return self._replicas[str(replica_id)]

    def _active_locked(self):
        return [self._replicas[rid] for rid in sorted(self._replicas)
                if self._replicas[rid].state in ("healthy", "draining")]

    # -- admission ---------------------------------------------------------

    def submit(self, prompt, max_new_tokens, eos_id=None,
               tenant="default", sink=None, trace_ctx=None):
        """Journal one request and queue it for dispatch; returns the
        journal entry id. Validation mirrors ServingEngine.submit so an
        unservable request fails HERE (the gateway's 400), never on a
        replica. `trace_ctx` is the gateway root span's
        (trace_id, span_id) — every fleet/replica span of this request
        shares the trace id and chains up to that root."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size == 0:
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {max_new_tokens}")
        with self._lock:
            if self.draining:
                raise RuntimeError("fleet is draining; not admitting")
            healthy = [r for r in self._replicas.values()
                       if r.state == "healthy"]
            if not healthy:
                raise RuntimeError("no healthy replicas")
            total = prompt.size + int(max_new_tokens)
            max_len = min(r.engine.max_len for r in healthy)
            if total > max_len:
                raise ValueError(
                    f"prompt ({prompt.size}) + max_new_tokens "
                    f"({max_new_tokens}) exceeds the fleet's max_len "
                    f"({max_len})")
            if all(r.engine.allocator.pages_needed(total)
                   > r.engine.allocator.capacity for r in healthy):
                raise ValueError(
                    f"request needs more KV pages than any healthy "
                    f"replica's pool holds")
            entry = self.journal.record(prompt, max_new_tokens, eos_id,
                                        tenant, sink)
            if _dtrace.trace_active():
                tid, psid = trace_ctx if trace_ctx else (None, None)
                entry.trace = {
                    "tid": tid or _dtrace.new_id(), "psid": psid,
                    "ns_submit": time.time_ns(),
                    "clk_submit": entry.submitted_at}
            self._enqueue_locked(entry, front=False)
            return entry.entry_id

    def _enqueue_locked(self, entry, front):
        dq = self._tenants.get(entry.tenant)
        if dq is None:
            dq = self._tenants[entry.tenant] = deque()
            self._tenant_order.append(entry.tenant)
        if front:
            dq.appendleft(entry)
        else:
            dq.append(entry)

    # -- the router iteration ----------------------------------------------

    def tick(self):
        """One router iteration (manual-pump mode). Deterministic given
        a deterministic clock: replicas pump in sorted-id order."""
        if _preemption.requested() and not self.draining:
            self.drain_all()
        self._health_check()
        self._progress_drains()
        self._dispatch()
        with self._lock:
            reps = self._active_locked()
        for rep in reps:
            rep.pump()
        self._collect_orphans()
        # again after the pumps: a replica whose LAST in-slot request
        # just finished leaves this tick, not next
        self._progress_drains()
        self.ticks += 1
        self._export_gauges()

    def _health_check(self):
        now = self._clock()
        with self._lock:
            stale = [r for r in self._replicas.values()
                     if r.state in ("healthy", "draining")
                     and r.heartbeat_age(now) > self.heartbeat_timeout]
            for rep in stale:
                self._declare_dead_locked(rep)

    def _declare_dead_locked(self, rep):
        rep.mark_dead()
        self.failovers += 1
        telemetry.inc(FAILOVERS_TOTAL)
        telemetry.log_event("fleet_replica_dead", replica=rep.replica_id,
                            timeout_s=self.heartbeat_timeout)
        entries = self.journal.assigned_to(rep.replica_id)
        if self._chaos_lose_one and entries:
            # seeded negative: drop one in-flight request on the floor.
            # It stays "assigned" to a corpse forever — exactly the bug
            # the zero-lost-requests chaos gate exists to catch.
            entries.pop(0)
            self._chaos_lose_one = False
        self._dump_failover_locked(rep, entries)
        for entry in reversed(entries):  # appendleft keeps id order
            self._requeue_locked(entry, reason="failover",
                                 cause="heartbeat_timeout")

    def _dump_failover_locked(self, rep, entries):
        """Flight-recorder post-mortem on every replica death: the full
        journal snapshot plus every replica's recent request timelines
        (victim AND survivors — the forensics view of what each side
        was doing when the heartbeat timeout fired)."""
        timelines = {}
        for rid in sorted(self._replicas):
            other = self._replicas[rid]
            try:
                timelines[rid] = other.engine.recent_timelines()
            except Exception:  # a corpse's engine may be torn down
                timelines[rid] = []
        _recorder.dump("fleet-failover", extra={
            "fleet": {
                "victim": rep.replica_id,
                "cause": "heartbeat_timeout",
                "heartbeat_timeout_s": self.heartbeat_timeout,
                "failovers": self.failovers,
                "requeued_entries": [e.entry_id for e in entries],
                "journal": self.journal.snapshot(),
                "journal_entries": self.journal.dump_entries(),
                "replica_timelines": timelines,
            }})

    def _emit_fleet_record(self, name, tr, *, ts, dur_s=0.0, extra=None,
                           sid=None, pid=None):
        rec = {"name": name, "tid": tr["tid"],
               "sid": sid if sid is not None else _dtrace.new_id(),
               "ts": int(ts), "dur_ns": max(0, int(dur_s * 1e9)),
               "lane": ROUTER_LANE}
        if pid is not None:
            rec["pid"] = pid
        if extra:
            rec["extra"] = extra
        _dtrace.record_span(rec)

    def _requeue_locked(self, entry, reason, cause=None):
        """Resubmission path: bump the epoch (the dedup fence), then
        either finish directly (the streamed tokens already satisfy
        EOS/length), fail (failover budget exhausted), or requeue at
        the FRONT of the tenant queue so recovered requests do not wait
        behind fresh arrivals. `cause` (failovers only) names what
        killed the old assignment: heartbeat_timeout | rpc_fault."""
        victim = entry.replica_id
        self.journal.release(entry)
        self.resubmits += 1
        telemetry.inc(RESUBMITS_TOTAL, reason=reason)
        tr = entry.trace
        now = self._clock()
        if tr is not None:
            self._emit_fleet_record(
                RESUBMIT_SPAN, tr, ts=_trace_ts(tr, now),
                pid=tr.get("psid"), extra={
                    "entry": entry.entry_id, "reason": reason,
                    "epoch": entry.epoch,
                    "resume_pos": len(entry.tokens),
                    "resubmits_remaining":
                        self.max_resubmits - entry.resubmits
                        - (1 if reason == "failover" else 0)})
            if reason == "failover":
                # the failover span covers the outage window: it opens
                # here (the requeue) and closes at the next successful
                # dispatch, which fills in the survivor replica id
                tr["failover"] = {"cause": cause, "victim": victim,
                                  "clk": now}
        if reason == "failover":
            # only unplanned resubmits consume budget: a rolling restart
            # may hand the same request off any number of times
            entry.resubmits += 1
            if entry.resubmits > self.max_resubmits:
                self.journal.fail(
                    entry, f"failover budget exhausted after "
                           f"{entry.resubmits - 1} resubmissions")
                self._resolve_failover_locked(entry, None)
                return
        if (entry.eos_id is not None and entry.tokens
                and entry.tokens[-1] == entry.eos_id):
            self.journal.finish_direct(entry, "eos")
            self._resolve_failover_locked(entry, None)
            return
        if len(entry.tokens) >= entry.max_new_tokens:
            self.journal.finish_direct(entry, "length")
            self._resolve_failover_locked(entry, None)
            return
        self._enqueue_locked(entry, front=True)

    def _resolve_failover_locked(self, entry, survivor):
        """Close a pending fleet.failover span: the outage window ran
        from the requeue to this moment — the survivor's dispatch, or a
        terminal router-side decision (budget exhausted / finished
        directly), in which case `survivor` is None."""
        tr = entry.trace
        if tr is None:
            return
        stash = tr.pop("failover", None)
        if stash is None:
            return
        now = self._clock()
        self._emit_fleet_record(
            FAILOVER_SPAN, tr, ts=_trace_ts(tr, stash["clk"]),
            dur_s=now - stash["clk"], pid=tr.get("psid"), extra={
                "entry": entry.entry_id, "cause": stash["cause"],
                "victim": stash["victim"], "survivor": survivor,
                "epoch": entry.epoch,
                "resume_pos": len(entry.tokens),
                "resubmits_remaining":
                    self.max_resubmits - entry.resubmits})

    def _progress_drains(self):
        with self._lock:
            # fleet-wide drain: nobody leaves while undispatched work
            # remains — a momentarily-empty replica must stay to take
            # the stragglers (there are no healthy survivors to)
            if self.draining and any(self._tenants.values()):
                return
            draining = [r for r in self._replicas.values()
                        if r.state == "draining"]
        for rep in draining:
            if rep.drained() and rep.leave():
                with self._lock:
                    self.drains += 1
                telemetry.inc(DRAINS_TOTAL)

    def _dispatch(self):
        with self._lock:
            n = len(self._tenant_order)
            if not n:
                return
            dispatched = True
            while dispatched:
                dispatched = False
                for k in range(n):
                    tenant = self._tenant_order[(self._rr + k) % n]
                    dq = self._tenants.get(tenant)
                    if not dq:
                        continue
                    best = self._pick_replica_locked()
                    if best is None:
                        return  # no capacity anywhere: stop the sweep
                    entry = dq.popleft()
                    if self._chaos_break_trace and entry.resubmits:
                        # seeded negative: lose the resubmission's trace
                        # context, so the survivor's serving.request
                        # span starts a fresh, orphaned trace — exactly
                        # the broken chain --fleet --check must flag
                        entry.trace = None
                        self._chaos_break_trace = False
                    tr = entry.trace
                    dispatch_clk = self._clock()
                    if tr is not None:
                        # pre-mint the dispatch span id so the engine's
                        # serving.request span can parent under it
                        tr["dispatch_sid"] = _dtrace.new_id()
                    try:
                        erid = best.dispatch(
                            entry, allow_draining=self.draining)
                    except (ConnectionError, OSError):
                        # dispatch RPC lost: back to the front, retry
                        # next tick (the health check owns giving up)
                        dq.appendleft(entry)
                        self.resubmits += 1
                        telemetry.inc(RESUBMITS_TOTAL, reason="rpc")
                        return
                    self.journal.bind(entry, best.replica_id, erid)
                    if tr is not None:
                        self._emit_fleet_record(
                            DISPATCH_SPAN, tr,
                            ts=_trace_ts(tr, dispatch_clk),
                            dur_s=self._clock() - dispatch_clk,
                            sid=tr.pop("dispatch_sid"),
                            pid=tr.get("psid"), extra={
                                "entry": entry.entry_id,
                                "replica": best.replica_id,
                                "request": erid, "epoch": entry.epoch,
                                "resume_pos": len(entry.tokens),
                                "resubmits": entry.resubmits})
                        self._resolve_failover_locked(
                            entry, best.replica_id)
                    dispatched = True
                self._rr = (self._rr + 1) % n

    def _pick_replica_locked(self):
        """Least-loaded healthy replica with an uncommitted slot. The
        router never queues more onto a replica than its slots — queued
        work holds no pages and is trivially movable, so keeping the
        per-replica queue shallow keeps drains and failovers cheap."""
        # fleet-wide drain: no healthy survivors will ever appear, so
        # draining replicas take the stragglers (zero-drop shutdown)
        ok = ("healthy", "draining") if self.draining else ("healthy",)
        best, best_load = None, None
        for rid in sorted(self._replicas):
            rep = self._replicas[rid]
            if rep.state not in ok or rep._failed:
                continue
            eng = rep.engine
            load = eng.slots_in_use + eng.queue_depth
            if load >= eng.slots:
                continue
            if best is None or load < best_load:
                best, best_load = rep, load
        return best

    def _collect_orphans(self):
        with self._lock:
            reps = list(self._replicas.values())
            for rep in reps:
                for entry_id in rep.take_orphans():
                    self._requeue_locked(self.journal.get(entry_id),
                                         reason="failover",
                                         cause="rpc_fault")

    # -- drains / rolling restarts -----------------------------------------

    def drain(self, replica_id, handoff=True):
        """Begin the drain handshake on one replica: stop admitting to
        it, requeue its engine-queued requests NOW, let in-slot
        requests finish. The replica leaves the router once empty."""
        with self._lock:
            rep = self._replicas[str(replica_id)]
            for entry_id in rep.begin_drain(handoff=handoff):
                self._requeue_locked(self.journal.get(entry_id),
                                     reason="drain")

    def drain_all(self):
        """Fleet-wide drain — the process-SIGTERM path (PR 8's drain
        protocol extended to serving): stop admitting at the gateway,
        finish or hand off everything in flight, every replica leaves."""
        with self._lock:
            self.draining = True
            reps = [r.replica_id for r in self._replicas.values()
                    if r.state == "healthy"]
        telemetry.log_event("fleet_drain_all", replicas=len(reps))
        for rid in reps:
            # no handoff: every replica is draining, so queued work has
            # nowhere to go — each replica finishes its own backlog
            self.drain(rid, handoff=False)

    def kill(self, replica_id):
        """Chaos helper: abrupt silent death of one replica. Detection
        still happens the honest way — heartbeat timeout."""
        self.replica(replica_id).silent_kill()

    # -- threaded mode -----------------------------------------------------

    def start(self, interval=0.002):
        """Run the fleet on background threads: one pump loop per
        replica (a slow replica cannot stall the others' heartbeats)
        plus one router loop for health, dispatch, and drains."""
        with self._lock:
            if self._started:
                return
            self._started = True
            self._interval = float(interval)
            reps = self._active_locked()
        self._stop.clear()
        t = threading.Thread(target=self._router_loop, daemon=True,
                             name="mxtpu-fleet-router")
        self._threads["__router__"] = t
        t.start()
        for rep in reps:
            self._spawn_replica_thread(rep)

    def _spawn_replica_thread(self, rep):
        t = threading.Thread(target=self._replica_loop, args=(rep,),
                             daemon=True,
                             name=f"mxtpu-replica-{rep.replica_id}")
        self._threads[rep.replica_id] = t
        t.start()

    def _replica_loop(self, rep):
        while not self._stop.is_set():
            if not rep.pump():
                return  # dead or left: the corpse stops consuming CPU
            self._stop.wait(self._interval)

    def _router_loop(self):
        while not self._stop.is_set():
            if _preemption.requested() and not self.draining:
                self.drain_all()
            self._health_check()
            self._progress_drains()
            self._dispatch()
            self._collect_orphans()
            self._progress_drains()
            self._export_gauges()
            self._stop.wait(self._interval)

    def stop(self):
        self._stop.set()
        for t in list(self._threads.values()):
            t.join(timeout=10.0)
        with self._lock:
            self._started = False
            self._threads.clear()

    # -- convenience / introspection ---------------------------------------

    def run_until_idle(self, max_ticks=10_000):
        """Manual-pump drive: tick until every journal entry reached a
        terminal state and the tenant queues are empty. Returns True on
        idle, False when max_ticks ran out first (a LOST request)."""
        for _ in range(max_ticks):
            if self.idle():
                return True
            self.tick()
        return self.idle()

    def idle(self):
        with self._lock:
            if any(self._tenants.values()):
                return False
        return not self.journal.unfinished()

    def result(self, entry_id):
        """Terminal view of one request: (tokens, finish_reason) — or
        state/error while unfinished/failed."""
        e = self.journal.get(entry_id)
        return {"entry_id": e.entry_id, "state": e.state,
                "tokens": list(e.tokens),
                "finish_reason": e.finish_reason,
                "resubmits": e.resubmits, "error": e.error}

    def min_occupancy(self):
        """KV page-pool occupancy of the LEAST loaded healthy replica —
        the gateway's admission-control signal (1.0 with no healthy
        replica: shed everything)."""
        with self._lock:
            occ = [r.engine.allocator.occupancy()
                   for r in self._replicas.values()
                   if r.state == "healthy"]
        return min(occ) if occ else 1.0

    def tenant_depth(self, tenant):
        with self._lock:
            dq = self._tenants.get(str(tenant))
            return len(dq) if dq else 0

    def healthy_count(self):
        with self._lock:
            return sum(r.state == "healthy"
                       for r in self._replicas.values())

    def export_fleet_gauges(self):
        """Refresh the fleet's rollup + per-replica federation gauges
        in the process registry — called each router iteration and by
        the gateway right before serving /metrics, so a scrape always
        sees current values."""
        self._export_gauges()

    def _export_gauges(self):
        now = self._clock()
        with self._lock:
            counts = {}
            per_replica = []
            for rid in sorted(self._replicas):
                r = self._replicas[rid]
                counts[r.state] = counts.get(r.state, 0) + 1
                eng = r.engine
                per_replica.append(
                    (rid, r.state, eng.queue_depth, eng.slots_in_use,
                     eng.allocator.occupancy()))
            front_depth = sum(len(dq) for dq in self._tenants.values())
            oldest = min(
                (e.submitted_at for dq in self._tenants.values()
                 for e in dq), default=None)
        for state in REPLICA_STATES:
            telemetry.set_gauge(FLEET_REPLICAS, counts.get(state, 0),
                                state=state)
        # router front queue (requests journaled but not yet on any
        # replica) — the autoscaler's backlog signal
        telemetry.set_gauge(FLEET_QUEUE_DEPTH, front_depth)
        telemetry.set_gauge(FLEET_OLDEST_QUEUED,
                            (now - oldest) if oldest is not None else 0.0)
        # fleet rollups across live replicas
        live = [p for p in per_replica if p[1] in ("healthy", "draining")]
        telemetry.set_gauge(
            FLEET_TOTAL_QUEUE_DEPTH,
            front_depth + sum(p[2] for p in live))
        telemetry.set_gauge(
            FLEET_PAGE_OCCUPANCY,
            sum(p[4] for p in live) / len(live) if live else 0.0)
        # per-replica federation: one labelled series per replica, and
        # a one-hot health-state matrix (value 1 on the current state)
        for rid, state, qd, slots, occ in per_replica:
            for s in REPLICA_STATES:
                telemetry.set_gauge(FLEET_REPLICA_HEALTH,
                                    1.0 if s == state else 0.0,
                                    replica=rid, state=s)
            telemetry.set_gauge(FLEET_REPLICA_QUEUE_DEPTH, qd,
                                replica=rid)
            telemetry.set_gauge(FLEET_REPLICA_SLOTS, slots, replica=rid)
            telemetry.set_gauge(FLEET_REPLICA_OCCUPANCY, occ,
                                replica=rid)

    def debug_snapshot(self):
        """Live-fleet JSON snapshot, served at /debug/fleet by the
        telemetry HTTP server (MXTPU_DEBUG_ENDPOINTS=1) and rendered as
        per-replica rows by tools/serving_top.py — the operator's view
        of a rolling restart."""
        now = self._clock()
        with self._lock:
            reps = [{
                "replica": rep.replica_id,
                "state": rep.state,
                "slots_in_use": rep.engine.slots_in_use,
                "slots": rep.engine.slots,
                "queue_depth": rep.engine.queue_depth,
                "inflight": rep.inflight(),
                "occupancy": rep.engine.allocator.occupancy(),
                "heartbeat_age_s": (rep.heartbeat_age(now)
                                    if rep.state in ("healthy", "draining")
                                    else None),
                "pumps": rep.pumps,
            } for _, rep in sorted(self._replicas.items())]
            tenants = {t: len(dq) for t, dq in sorted(self._tenants.items())}
            counters = {"failovers": self.failovers,
                        "resubmits": self.resubmits,
                        "drains": self.drains,
                        "ticks": self.ticks}
            draining = self.draining
            oldest = min(
                (e.submitted_at for dq in self._tenants.values()
                 for e in dq), default=None)
            front_queue = {
                "depth": sum(len(dq) for dq in self._tenants.values()),
                "oldest_s": (now - oldest) if oldest is not None else 0.0,
            }
        return {
            "schema": "mxtpu-serving-fleet-debug-v1",
            "draining": draining,
            "heartbeat_timeout_s": self.heartbeat_timeout,
            "replicas": reps,
            "tenants": tenants,
            "front_queue": front_queue,
            "counters": counters,
            "journal": self.journal.snapshot(),
        }
