"""Continuous-batching decode engine over the paged KV cache.

Iteration-level scheduling (Orca) over PagedAttention-style storage
(vLLM), on the repo's own primitives:

- a FIFO request queue feeding a FIXED set of `MXTPU_DECODE_SLOTS`
  decode slots — the static batch dimension of every decode step;
- admission = all-or-nothing page allocation (serving/pages.py) for the
  request's worst case, then a BUCKETED prefill (prompt padded up to one
  of a few static lengths — the MXTPU_SPARSE_NNZ_BUCKETING idea applied
  to sequence length) writing prompt K/V straight into the pages;
- one `decode_step_paged` per engine step advances EVERY live slot one
  token, each at its own depth (per-slot positions + page-table rows);
- eviction on EOS or max-tokens recycles pages immediately — the next
  admission can reuse them without touching device memory.

Every device call has a static shape: one decode program, one prefill
program per bucket. The steady state therefore performs ZERO retraces
(compilereg-gated in CI) and a warm replica performs zero compiles
(`warm()` AOT-populates the PR 10 compile cache; tools/warmup.py
--decode drives it).

Greedy decoding (temperature 0) — token-for-token identical to
sequential `models.transformer.generate()` per request, which is the
equivalence CI asserts.

Three OPTIONAL throughput levers stack on this substrate, each
knob-off byte-identical to the base engine (no extra compiled programs,
same outputs):

- `MXTPU_PREFIX_CACHE` — prefix-cached copy-on-write pages (vLLM
  block sharing): admission looks up the longest cached page-aligned
  prefix of the prompt, maps those pages READ-ONLY into the new
  request's table (a host table write instead of device prefill) and
  prefills only the tail. A cached partial page is copied before the
  tail writes into it; a freshly-cached partial page is copied on the
  first decode write (`serving_page_copy`).
- `MXTPU_PREFILL_CHUNK` — chunked prefill (Sarathi-Serve): prompts
  stream through one wide-query program (`serving_wide_q{C}`) a chunk
  per step, interleaved with the batched decode, so short requests
  stop waiting behind long prompts.
- `MXTPU_SPEC_NGRAM` / `MXTPU_SPEC_LOOKAHEAD` — draft-free prompt
  lookup speculation: the trailing n-gram of each slot's own history
  proposes up to `lookahead` tokens; ONE wide-query call verifies all
  slots' proposals and accepted prefixes advance positions in bulk.
  Rejected tails need no rollback — their K/V lands beyond every
  live `n_valid` (dead data, overwritten by the next step's writes).
"""
from __future__ import annotations

import dataclasses
import itertools
import time
from collections import deque

import numpy as np
import jax
import jax.numpy as jnp

from .. import compile_cache, config, telemetry
from ..analysis import sanitizers as _sanitizers
from ..models import transformer as _tfm
from ..telemetry import compilereg
from ..telemetry import distributed as _dtrace
from ..telemetry import exporters as _exporters
from ..telemetry import recorder as _recorder
from ..telemetry import slo as _slo
from .pages import PageAllocator, PrefixCache

__all__ = ["Request", "RequestResult", "ServingEngine"]

QUEUE_DEPTH = "mxtpu_serving_queue_depth"
SLOTS_IN_USE = "mxtpu_serving_slots_in_use"
PAGES_IN_USE = "mxtpu_serving_pages_in_use"
PAGE_UTILIZATION = "mxtpu_serving_page_utilization"
REQUESTS_TOTAL = "mxtpu_serving_requests_total"
TOKENS_TOTAL = "mxtpu_serving_tokens_total"
REQUEST_SECONDS = "mxtpu_serving_request_seconds"
QUEUE_WAIT_SECONDS = "mxtpu_serving_queue_wait_seconds"
TTFT_SECONDS = "mxtpu_serving_ttft_seconds"
OLDEST_QUEUED = "mxtpu_serving_oldest_queued_seconds"
ADMISSION_BLOCKED = "mxtpu_serving_admission_blocked_total"
WASTED_TOKENS = "mxtpu_serving_wasted_tokens_total"
GOODPUT = "mxtpu_serving_goodput"
PREFIX_LOOKUPS = "mxtpu_serving_prefix_lookups_total"
PREFIX_TOKENS_SAVED = "mxtpu_serving_prefix_tokens_saved_total"
PREFIX_CACHED_PAGES = "mxtpu_serving_prefix_cached_pages"
COW_COPIES = "mxtpu_serving_cow_copies_total"
PREFILL_CHUNKS = "mxtpu_serving_prefill_chunks_total"
SPEC_PROPOSED = "mxtpu_spec_proposed_tokens_total"
SPEC_ACCEPTED = "mxtpu_spec_accepted_tokens_total"

# tail-prefill chunk width when the prefix cache is on but chunked
# prefill is off: the tail still streams through the wide program (the
# bucketed prefill can only start at position 0), in fixed-width chunks
# so ONE wide signature covers every tail length
_SYNC_TAIL_CHUNK = 32

_EMPTY_PROP = np.zeros((0,), np.int32)

# per-request lifecycle record names (registered in telemetry/names.py);
# emitted straight through distributed.record_span — zero-cost when
# tracing is off, and rendered as one lane per request by
# tools/trace_merge.py --requests
REQ_SPAN = "serving.request"
REQ_QUEUED_SPAN = "serving.request.queued"
REQ_PREFILL_SPAN = "serving.request.prefill"
REQ_DECODE_SPAN = "serving.request.decode"
REQ_STEP_KIND = "req_step"  # batched decode-progress record, one per STEP

# sub-ms to minutes: decode steps are ms-scale, queued requests can wait
_LATENCY_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                    0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0)


@dataclasses.dataclass
class Request:
    """One generation request: greedy-decode up to `max_new_tokens`
    continuation tokens, stopping early when `eos_id` is produced
    (the EOS token is included in the output)."""
    request_id: int
    prompt: np.ndarray  # (T_p,) int32
    max_new_tokens: int
    eos_id: int | None = None
    submitted_at: float = 0.0
    admitted_at: float = 0.0
    ttft_s: float = 0.0       # set at prefill; 0 until admitted
    trace: dict | None = None  # per-request trace context (tracing on)


@dataclasses.dataclass
class RequestResult:
    request_id: int
    tokens: list  # generated continuation (includes EOS when hit)
    finish_reason: str  # "eos" | "length" | "evicted" | "cancelled"
    prompt_len: int
    queue_wait_s: float
    latency_s: float
    ttft_s: float = 0.0  # 0.0 for cancelled-in-queue requests


def _default_buckets(max_len):
    """Powers of two from 16 up to (and always including) max_len."""
    raw = str(config.get("MXTPU_PREFILL_BUCKETS") or "")
    if raw.strip():
        buckets = sorted({int(b) for b in raw.split(",") if b.strip()})
    else:
        buckets, b = [], 16
        while b < max_len:
            buckets.append(b)
            b *= 2
        buckets.append(max_len)
    return [b for b in buckets if b <= max_len] or [max_len]


class ServingEngine:
    """Continuous-batching greedy-decode engine for one transformer.

    >>> eng = ServingEngine(params, cfg)
    >>> rid = eng.submit([1, 2, 3], max_new_tokens=16, eos_id=0)
    >>> results = eng.run()          # drain queue + slots
    >>> results[rid].tokens

    `step()` runs ONE scheduler iteration (admissions + one decode
    step) for callers that interleave serving with other work.
    """

    def __init__(self, params, cfg, *, slots=None, page_size=None,
                 num_pages=None, max_len=None, clock=time.monotonic,
                 slo=None, prefix_cache=None, prefill_chunk=None,
                 spec_ngram=None, spec_lookahead=None):
        self.params = params
        self.cfg = cfg
        self.page_size = int(page_size or config.get("MXTPU_PAGE_SIZE"))
        self.slots = int(slots or config.get("MXTPU_DECODE_SLOTS"))
        self.max_len = int(max_len or cfg.max_len)
        if self.max_len > cfg.max_len:
            raise ValueError(f"max_len {self.max_len} exceeds the "
                             f"model's positional table ({cfg.max_len})")
        self.table_width = -(-self.max_len // self.page_size)
        if num_pages is None:
            num_pages = int(config.get("MXTPU_SERVING_PAGES"))
        if not num_pages:  # auto: every slot can hold a full sequence
            num_pages = self.slots * self.table_width + 1
        self.allocator = PageAllocator(num_pages, self.page_size)
        # shadow-state refcount checker (None unless MXTPU_SANITIZERS
        # lists "pages"); run() proves quiescence at drain through it
        self._page_san = _sanitizers.attach_page_sanitizer(self.allocator)
        self.paged = _tfm.init_paged_kv_cache(cfg, num_pages,
                                              self.page_size)
        self.prefill_buckets = _default_buckets(self.max_len)
        self._clock = clock
        # explicit timeline lane for this engine's trace records (fleet
        # replicas set it to their replica id so a multi-replica process
        # still renders one lane per replica); None = the process lane
        self.trace_lane = None
        # the engine lock: submit/cancel arrive from gateway and fleet
        # threads while the pump thread sits inside step(). Reentrant
        # because step() finishing a request may call back through the
        # public surface; a san_rlock so lockdep sees the ordering
        # against the fleet/journal locks.
        self._lock = _sanitizers.san_rlock("serving.engine")

        # perf levers (each defaults from its knob; constructor args
        # override for tests/benches) — all off reproduces the base
        # engine byte-for-byte: no extra jits are even constructed
        if prefix_cache is None:
            prefix_cache = int(config.get("MXTPU_PREFIX_CACHE"))
        if prefill_chunk is None:
            prefill_chunk = int(config.get("MXTPU_PREFILL_CHUNK"))
        if spec_ngram is None:
            spec_ngram = int(config.get("MXTPU_SPEC_NGRAM"))
        if spec_lookahead is None:
            spec_lookahead = int(config.get("MXTPU_SPEC_LOOKAHEAD"))
        self.prefill_chunk = max(0, min(int(prefill_chunk), self.max_len))
        self.spec_ngram = max(0, int(spec_ngram))
        self.spec_lookahead = max(1, int(spec_lookahead))
        self.prefix_cache = (
            PrefixCache(self.allocator,
                        max_pages=prefix_cache if prefix_cache > 1 else 0)
            if prefix_cache else None)

        S, W = self.slots, self.table_width
        self._tables = np.zeros((S, W), np.int32)
        self._positions = np.zeros((S,), np.int32)
        self._next_tok = np.zeros((S,), np.int32)
        self._slot_req: list[Request | None] = [None] * S
        self._slot_pages: list[list] = [[] for _ in range(S)]
        self._slot_out: list[list] = [[] for _ in range(S)]
        # lever slot state: pending chunked-prefill descriptor, and the
        # table index whose page must copy-on-write before the slot's
        # next decode write (-1 = none)
        self._slot_prefill: list[dict | None] = [None] * S
        self._slot_cow_idx = [-1] * S
        self._queue: deque[Request] = deque()
        self._results: dict[int, RequestResult] = {}
        self._ids = itertools.count()
        self.steps = 0

        # host-side goodput accounting (source of truth independent of
        # whether the metrics registry is enabled): device token-position
        # kinds, plus tokens spent on requests later evicted mid-stream
        self._tokens = {"prefill": 0, "decode": 0, "pad": 0,
                        "spec_rejected": 0}
        self._wasted_evicted = 0
        # lever counters (host source of truth; mirrored to telemetry)
        self._prefix_lookups = 0
        self._prefix_hits = 0
        self._prefix_tokens_saved = 0
        self._cow_copies = 0
        self._prefill_chunks = 0
        self._spec_proposed = 0
        self._spec_accepted = 0
        # last-N finished-request timelines, embedded in SLO breach dumps
        # and the /debug/engine snapshot
        self._timelines: deque = deque(
            maxlen=max(1, int(config.get("MXTPU_SLO_DUMP_TIMELINES"))))
        if slo is None:
            slo = _slo.from_env(timelines=self.recent_timelines)
        self.slo = slo or None
        _exporters.register_debug_handler("/debug/engine",
                                          self.debug_snapshot)

        # donation frees the old pool the moment the step runs; CPU
        # buffers aren't donatable (jax warns and copies anyway)
        donate = (1,) if jax.default_backend() != "cpu" else ()
        self._decode = compile_cache.wrap(
            "serving_decode_step",
            jax.jit(self._decode_fn, donate_argnums=donate),
            donated=donate)
        # one jit per bucket: the bucket length is baked into the prompt
        # shape, so each T_b is its own named executable for compilereg,
        # the compile cache, and warmup
        self._prefills = {
            T_b: compile_cache.wrap(
                f"serving_prefill_b{T_b}",
                jax.jit(self._prefill_fn, donate_argnums=donate),
                donated=donate, static_key=T_b)
            for T_b in self.prefill_buckets}
        # lever programs are built LAZILY (and the page-copy jit only
        # when the prefix cache is on) so an all-knobs-off engine
        # registers exactly the legacy compile sites
        self._donate = donate
        self._wides: dict = {}
        if self.prefix_cache is not None:
            copy_donate = (0,) if donate else ()
            self._page_copy = compile_cache.wrap(
                "serving_page_copy",
                jax.jit(self._copy_fn, donate_argnums=copy_donate),
                donated=copy_donate)

    # -- jitted programs ---------------------------------------------------

    def _decode_fn(self, params, paged, tokens, positions, table):
        logits, paged = _tfm.decode_step_paged(
            params, paged, tokens, positions, table, self.cfg)
        return jnp.argmax(logits, axis=-1).astype(jnp.int32), paged

    def _prefill_fn(self, params, paged, prompt, true_len, table):
        paged, logits = _tfm.prefill_paged(
            params, paged, prompt, true_len, table, self.cfg)
        return jnp.argmax(logits, axis=-1).astype(jnp.int32), paged

    def _wide_fn(self, params, paged, tokens, start, n_real, table):
        logits, paged = _tfm.decode_step_paged_wide(
            params, paged, tokens, start, n_real, table, self.cfg)
        return jnp.argmax(logits, axis=-1).astype(jnp.int32), paged

    def _copy_fn(self, paged, src, dst):
        k, v = paged["k"], paged["v"]
        return {"k": k.at[:, dst].set(k[:, src]),
                "v": v.at[:, dst].set(v[:, src])}

    def _wide(self, n_q):
        """Wide-query program for `n_q` rows per slot — one named site
        (`serving_wide_q{n_q}`) per width, so chunked prefill, prefix
        tail prefill, and speculative verification each trace exactly
        once and the steady state stays retrace-free."""
        fn = self._wides.get(n_q)
        if fn is None:
            fn = compile_cache.wrap(
                f"serving_wide_q{n_q}",
                jax.jit(self._wide_fn, donate_argnums=self._donate),
                donated=self._donate, static_key=n_q)
            self._wides[n_q] = fn
        return fn

    # -- public API --------------------------------------------------------

    def submit(self, prompt, max_new_tokens, eos_id=None, trace_ctx=None):
        """Queue one request; returns its request id. Validation is
        eager: an unservable request fails here, not mid-decode.

        `trace_ctx` is an optional inbound (trace_id, parent_span_id)
        pair — the fleet router passes its `fleet.dispatch` span so a
        failed-over request's engine spans on BOTH replicas share ONE
        trace, parented under the dispatch that placed them."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size == 0:
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            raise ValueError(f"max_new_tokens must be >= 1, "
                             f"got {max_new_tokens}")
        total = prompt.size + int(max_new_tokens)
        if total > self.max_len:
            raise ValueError(
                f"prompt ({prompt.size}) + max_new_tokens "
                f"({max_new_tokens}) exceeds max_len ({self.max_len})")
        need = self.allocator.pages_needed(total)
        if need > self.allocator.capacity:
            raise ValueError(
                f"request needs {need} pages but the pool only has "
                f"{self.allocator.capacity}")
        with self._lock:
            rid = next(self._ids)
            req = Request(rid, prompt, int(max_new_tokens), eos_id,
                          submitted_at=self._clock())
            if _dtrace.trace_active():
                # trace context is born HERE (or adopted from trace_ctx):
                # tid groups the whole lifecycle, sid is the root
                # "serving.request" span every stage parents under,
                # ns_submit anchors engine-clock deltas to wall time
                tid, psid = trace_ctx if trace_ctx else (None, None)
                req.trace = {"tid": tid or _dtrace.new_id(),
                             "sid": _dtrace.new_id(),
                             "ns_submit": time.time_ns(),
                             "clk_submit": req.submitted_at}
                if psid is not None:
                    req.trace["pid"] = psid
            self._queue.append(req)
            telemetry.set_gauge(QUEUE_DEPTH, len(self._queue))
            telemetry.set_gauge(
                OLDEST_QUEUED,
                self._clock() - self._queue[0].submitted_at)
            return rid

    def step(self):
        """One scheduler iteration: admit queued requests into free
        slots (FIFO, backpressured by page availability), then advance
        every live slot one token in a single decode program. Returns
        the number of live slots after the iteration."""
        with self._lock:
            with telemetry.span("serving.step", step=self.steps):
                self._admit()
                if self.prefill_chunk:
                    self._prefill_chunks_once()
                if self.spec_ngram:
                    live = self._decode_spec_once()
                else:
                    live = self._decode_once()
            self.steps += 1
            self._export_gauges()
            return live

    def run(self, max_steps=100_000):
        """Drive step() until the queue and every slot drain; returns
        {request_id: RequestResult} for everything finished so far.
        `max_steps` bounds a scheduler bug (a request that can never
        finish) — hitting it raises instead of spinning forever."""
        for _ in range(max_steps):
            with self._lock:
                if not self._queue and not any(self._slot_req):
                    if self._page_san is not None:
                        # every live reference must now be owned by the
                        # prefix cache; anything else leaked (MXS013)
                        self._page_san.assert_quiescent()
                    return dict(self._results)
            self.step()
        raise RuntimeError(f"serving engine did not drain within "
                           f"{max_steps} steps")

    def results(self):
        with self._lock:
            return dict(self._results)

    def live_tokens(self):
        """{request_id: continuation tokens streamed so far} for every
        request holding a slot (mid-prefill slots report []). Queued
        requests have produced nothing and do not appear. This is the
        fleet journal's streaming tap: it is read after every pump and
        the per-request deltas forwarded to the client."""
        with self._lock:
            return {r.request_id: list(self._slot_out[s])
                    for s, r in enumerate(self._slot_req) if r is not None}

    def queued_request_ids(self):
        """Request ids still waiting in the admission queue (FIFO
        order) — the set a draining replica hands straight back to the
        router instead of finishing locally."""
        with self._lock:
            return [r.request_id for r in self._queue]

    @property
    def queue_depth(self):
        return len(self._queue)

    @property
    def slots_in_use(self):
        return sum(r is not None for r in self._slot_req)

    def warm(self):
        """AOT-precompile the decode step and every prefill bucket into
        the persistent compile cache (no execution, no buffer writes).
        Returns {site: status} with compile_cache.warm statuses."""
        S, W = self.slots, self.table_width
        a = compile_cache.abstractify
        i32 = jnp.int32
        out = {}
        if getattr(self._decode, "is_cached", False):
            out["serving_decode_step"] = self._decode.warm(
                a(self.params), a(self.paged),
                jax.ShapeDtypeStruct((S,), i32),
                jax.ShapeDtypeStruct((S,), i32),
                jax.ShapeDtypeStruct((S, W), i32))
        for T_b, fn in self._prefills.items():
            if getattr(fn, "is_cached", False):
                out[f"serving_prefill_b{T_b}"] = fn.warm(
                    a(self.params), a(self.paged),
                    jax.ShapeDtypeStruct((1, T_b), i32),
                    jax.ShapeDtypeStruct((1,), i32),
                    jax.ShapeDtypeStruct((1, W), i32))
        # lever programs: exactly the wide widths the enabled knobs
        # will call, plus the page-copy program when caching is on
        wide_qs = set()
        if self.prefill_chunk:
            wide_qs.add(self.prefill_chunk)
        elif self.prefix_cache is not None:
            wide_qs.add(min(_SYNC_TAIL_CHUNK, self.max_len))
        if self.spec_ngram:
            wide_qs.add(self.spec_lookahead + 1)
        for q in sorted(wide_qs):
            fn = self._wide(q)
            if getattr(fn, "is_cached", False):
                out[f"serving_wide_q{q}"] = fn.warm(
                    a(self.params), a(self.paged),
                    jax.ShapeDtypeStruct((S, q), i32),
                    jax.ShapeDtypeStruct((S,), i32),
                    jax.ShapeDtypeStruct((S,), i32),
                    jax.ShapeDtypeStruct((S, W), i32))
        if (self.prefix_cache is not None
                and getattr(self._page_copy, "is_cached", False)):
            out["serving_page_copy"] = self._page_copy.warm(
                a(self.paged), jax.ShapeDtypeStruct((), i32),
                jax.ShapeDtypeStruct((), i32))
        return out

    # -- scheduling internals ----------------------------------------------

    def _free_slot(self):
        for s, r in enumerate(self._slot_req):
            if r is None:
                return s
        return None

    def _bucket_for(self, n):
        for b in self.prefill_buckets:
            if b >= n:
                return b
        raise ValueError(f"prompt length {n} exceeds the largest "
                         f"prefill bucket {self.prefill_buckets[-1]}")

    def _admit(self):
        """FIFO admission: stop at the first request that can't get a
        slot or its pages (head-of-line order keeps scheduling
        deterministic — no small request overtakes a starved big one)."""
        levered = self.prefix_cache is not None or self.prefill_chunk
        while self._queue:
            slot = self._free_slot()
            if slot is None:
                telemetry.inc(ADMISSION_BLOCKED, reason="slots")
                return
            req = self._queue[0]
            if levered:
                if not self._admit_levered(slot, req):
                    return  # backpressure: wait for an eviction
                continue
            total = req.prompt.size + req.max_new_tokens
            pages = self.allocator.alloc(self.allocator.pages_needed(total),
                                         owner=req.request_id)
            if pages is None:
                telemetry.inc(ADMISSION_BLOCKED, reason="pages")
                return  # backpressure: wait for an eviction
            self._queue.popleft()
            req.admitted_at = self._clock()
            telemetry.observe(QUEUE_WAIT_SECONDS,
                              req.admitted_at - req.submitted_at,
                              buckets=_LATENCY_BUCKETS)
            telemetry.set_gauge(QUEUE_DEPTH, len(self._queue))
            if req.trace is not None:
                self._emit_request_record(
                    REQ_QUEUED_SPAN, req.trace, ts=req.trace["ns_submit"],
                    dur_s=req.admitted_at - req.submitted_at,
                    pid=req.trace["sid"],
                    extra={"request": req.request_id})
            self._prefill_into(slot, req, pages)

    def _prefill_into(self, slot, req, pages):
        T_p = req.prompt.size
        T_b = self._bucket_for(T_p)
        row = np.asarray(
            self.allocator.table_row(pages, self.table_width), np.int32)
        prompt = np.zeros((1, T_b), np.int32)
        prompt[0, :T_p] = req.prompt
        clk_prefill = self._clock()
        with telemetry.span("serving.prefill", request=req.request_id,
                            bucket=T_b):
            tok, self.paged = self._prefills[T_b](
                self.params, self.paged, jnp.asarray(prompt),
                jnp.asarray([T_p], np.int32), jnp.asarray(row[None]))
        first = int(np.asarray(tok)[0])
        clk_first = self._clock()
        pad = T_b - T_p
        self._tokens["prefill"] += T_p
        telemetry.inc(TOKENS_TOTAL, amount=float(T_p), kind="prefill")
        if pad:
            # padded rows run through the MXU like real tokens — they are
            # processed-but-wasted, the prefill half of the goodput split
            self._tokens["pad"] += pad
            telemetry.inc(TOKENS_TOTAL, amount=float(pad), kind="pad")
            telemetry.inc(WASTED_TOKENS, amount=float(pad),
                          reason="prefill_pad")
        req.ttft_s = clk_first - req.submitted_at
        telemetry.observe(TTFT_SECONDS, req.ttft_s,
                          buckets=_LATENCY_BUCKETS)
        if req.trace is not None:
            req.trace["clk_first"] = clk_first
            self._emit_request_record(
                REQ_PREFILL_SPAN, req.trace,
                ts=self._trace_ts(req.trace, clk_prefill),
                dur_s=clk_first - clk_prefill, pid=req.trace["sid"],
                extra={"request": req.request_id, "bucket": T_b,
                       "prompt_len": T_p, "pad": pad})
        self._slot_req[slot] = req
        self._slot_pages[slot] = pages
        self._slot_out[slot] = [first]
        self._tables[slot] = row
        self._positions[slot] = T_p
        self._next_tok[slot] = first
        if self._is_done(req, [first]):
            self._finish(slot)

    # -- lever path: prefix-cached COW pages + chunked prefill -------------

    def _admit_levered(self, slot, req):
        """Admission with the prefix-cache / chunked-prefill levers on:
        map the longest cached page-aligned prefix read-only into the
        slot's table (a host write instead of device prefill), allocate
        fresh pages for the rest, then stream only the uncached tail
        through the wide program — synchronously here, or one chunk per
        step when chunked prefill is on. Returns False on page
        backpressure (the request stays queued)."""
        ps = self.page_size
        T_p = req.prompt.size
        w_req = self.allocator.pages_needed(T_p + req.max_new_tokens)
        used_full, part_page, n_part = [], None, 0
        if self.prefix_cache is not None:
            full_pages, partial = self.prefix_cache.lookup(req.prompt)
            # the LAST prompt token is always recomputed — its logits
            # are the first output token, which a table write can't give
            limit = T_p - 1
            n_full = min(len(full_pages), limit // ps)
            used_full = full_pages[:n_full]
            if partial is not None and n_full == len(full_pages):
                page, chunk = partial
                n_part = min(int(chunk.size), limit - n_full * ps)
                part_page = page if n_part > 0 else None
                n_part = max(0, n_part) if part_page is not None else 0
        n_cached = len(used_full) * ps + n_part
        # references: mapped full pages are shared for the slot's whole
        # lifetime; the cached partial page is pinned only until its
        # bytes are copied into a fresh page below
        protect = used_full + ([part_page] if part_page is not None
                               else [])
        self.allocator.share(protect, owner=req.request_id)
        fresh = self.allocator.alloc(w_req - len(used_full),
                                     owner=req.request_id)
        if fresh is None and self.prefix_cache is not None:
            # pool pressure: LRU-evict cache pages no live request maps
            deficit = (w_req - len(used_full)) - self.allocator.num_free
            self.prefix_cache.evict(deficit)
            fresh = self.allocator.alloc(w_req - len(used_full),
                                         owner=req.request_id)
        if fresh is None:
            self.allocator.free(protect, owner=req.request_id)
            telemetry.inc(ADMISSION_BLOCKED, reason="pages")
            return False
        if self.prefix_cache is not None:
            self._prefix_lookups += 1
            hit = n_cached > 0
            self._prefix_hits += int(hit)
            self._prefix_tokens_saved += n_cached
            telemetry.inc(PREFIX_LOOKUPS,
                          outcome="hit" if hit else "miss")
            if n_cached:
                telemetry.inc(PREFIX_TOKENS_SAVED,
                              amount=float(n_cached))
        self._queue.popleft()
        req.admitted_at = self._clock()
        telemetry.observe(QUEUE_WAIT_SECONDS,
                          req.admitted_at - req.submitted_at,
                          buckets=_LATENCY_BUCKETS)
        telemetry.set_gauge(QUEUE_DEPTH, len(self._queue))
        if req.trace is not None:
            self._emit_request_record(
                REQ_QUEUED_SPAN, req.trace, ts=req.trace["ns_submit"],
                dur_s=req.admitted_at - req.submitted_at,
                pid=req.trace["sid"],
                extra={"request": req.request_id})
        pages = used_full + fresh
        row = np.asarray(
            self.allocator.table_row(pages, self.table_width), np.int32)
        if part_page is not None:
            # eager copy-on-write: the tail prefill writes into this
            # page's token range, so the slot gets a private copy of
            # the cached bytes first
            self.paged = self._page_copy(
                self.paged, jnp.asarray(part_page, jnp.int32),
                jnp.asarray(fresh[0], jnp.int32))
            self.allocator.free([part_page],  # drop the pin only
                                owner=req.request_id)
            self._cow_copies += 1
            telemetry.inc(COW_COPIES, site="admit")
        self._slot_req[slot] = req
        self._slot_pages[slot] = pages
        self._slot_out[slot] = []
        self._slot_prefill[slot] = {
            "prompt": req.prompt, "row": row, "pos": n_cached,
            "n_cached": n_cached, "chunks": 0,
            "clk_start": self._clock()}
        if not self.prefill_chunk:
            # synchronous tail prefill: run every chunk before the next
            # admission (chunked mode instead leaves the descriptor for
            # step() to advance one chunk per iteration)
            while self._slot_prefill[slot] is not None:
                self._prefill_chunks_once(only_slot=slot)
        return True

    def _prefill_chunks_once(self, only_slot=None):
        """Advance pending prefills one chunk in ONE wide-program call
        covering every mid-prefill slot; decoding/idle slots ride along
        masked out (n_real=0, zero table rows — writes land in the null
        page), so the call shape is static."""
        pend = [s for s in range(self.slots)
                if self._slot_prefill[s] is not None
                and (only_slot is None or s == only_slot)]
        if not pend:
            return
        C = self.prefill_chunk or min(_SYNC_TAIL_CHUNK, self.max_len)
        S, W = self.slots, self.table_width
        toks = np.zeros((S, C), np.int32)
        start = np.zeros((S,), np.int32)
        n_real = np.zeros((S,), np.int32)
        tables = np.zeros((S, W), np.int32)
        for s in pend:
            st = self._slot_prefill[s]
            pos, prompt = st["pos"], st["prompt"]
            n = min(C, prompt.size - pos)
            toks[s, :n] = prompt[pos:pos + n]
            start[s] = pos
            n_real[s] = n
            tables[s] = st["row"]
        if self._page_san is not None:
            for s in pend:
                lo = int(start[s]) // self.page_size
                hi = (int(start[s]) + int(n_real[s]) - 1) // self.page_size
                self._page_san.note_write(
                    self._slot_req[s].request_id,
                    self._slot_pages[s][lo:hi + 1])
        with telemetry.span("serving.prefill_chunk", slots=len(pend)):
            out, self.paged = self._wide(C)(
                self.params, self.paged, jnp.asarray(toks),
                jnp.asarray(start), jnp.asarray(n_real),
                jnp.asarray(tables))
        out = np.asarray(out)
        for s in pend:
            st = self._slot_prefill[s]
            n = int(n_real[s])
            st["pos"] += n
            st["chunks"] += 1
            self._prefill_chunks += 1
            self._tokens["prefill"] += n
            telemetry.inc(TOKENS_TOTAL, amount=float(n), kind="prefill")
            telemetry.inc(PREFILL_CHUNKS)
            pad = C - n
            if pad:
                self._tokens["pad"] += pad
                telemetry.inc(TOKENS_TOTAL, amount=float(pad),
                              kind="pad")
                telemetry.inc(WASTED_TOKENS, amount=float(pad),
                              reason="prefill_pad")
            if st["pos"] >= st["prompt"].size:
                self._finish_prefill(s, int(out[s, n - 1]))

    def _finish_prefill(self, slot, first):
        """Last tail chunk done: record TTFT, install the slot's decode
        state, register the prompt's pages in the prefix cache, and arm
        the lazy copy-on-write if caching shared the page the first
        decode token will write into."""
        st = self._slot_prefill[slot]
        self._slot_prefill[slot] = None
        req = self._slot_req[slot]
        prompt = st["prompt"]
        T_p = prompt.size
        clk_first = self._clock()
        req.ttft_s = clk_first - req.submitted_at
        telemetry.observe(TTFT_SECONDS, req.ttft_s,
                          buckets=_LATENCY_BUCKETS)
        if req.trace is not None:
            req.trace["clk_first"] = clk_first
            self._emit_request_record(
                REQ_PREFILL_SPAN, req.trace,
                ts=self._trace_ts(req.trace, st["clk_start"]),
                dur_s=clk_first - st["clk_start"], pid=req.trace["sid"],
                extra={"request": req.request_id,
                       "prompt_len": int(T_p),
                       "cached": int(st["n_cached"]),
                       "chunks": int(st["chunks"])})
        self._slot_out[slot] = [first]
        self._tables[slot] = st["row"]
        self._positions[slot] = T_p
        self._next_tok[slot] = first
        if self.prefix_cache is not None:
            n_prompt_pages = self.allocator.pages_needed(T_p)
            self.prefix_cache.insert(
                prompt, self._slot_pages[slot][:n_prompt_pages])
            telemetry.set_gauge(PREFIX_CACHED_PAGES,
                                self.prefix_cache.cached_pages)
            # the page the first decode token (position T_p) writes
            # into: if insert() just shared the slot's own partial tail
            # page, it must copy-on-write before that write lands
            wi = T_p // self.page_size
            if (T_p % self.page_size
                    and wi < len(self._slot_pages[slot])
                    and self.allocator.refcount(
                        self._slot_pages[slot][wi]) > 1):
                self._slot_cow_idx[slot] = wi
        if self._is_done(req, [first]):
            self._finish(slot)

    def _resolve_cow(self, slot):
        """The slot's next decode write lands in a shared
        partially-filled page: give it a private page first. Fallbacks
        when the pool has no page for the copy: steal the cache's own
        reference back (the writer becomes exclusive — no copy
        needed), else LRU-evict one cached page and retry."""
        idx = self._slot_cow_idx[slot]
        self._slot_cow_idx[slot] = -1
        page = self._slot_pages[slot][idx]
        rid = self._slot_req[slot].request_id
        new = self.allocator.cow(page, owner=rid)
        if new is None:
            if self.prefix_cache.release(page):
                return  # cache ref dropped; the slot now owns the page
            if self.prefix_cache.evict(1):
                new = self.allocator.cow(page, owner=rid)
        if new is None:
            raise RuntimeError(
                f"copy-on-write of page {page} failed: KV pool "
                f"exhausted and the prefix cache holds no evictable "
                f"page")
        if new != page:
            self.paged = self._page_copy(
                self.paged, jnp.asarray(page, jnp.int32),
                jnp.asarray(new, jnp.int32))
            self._slot_pages[slot][idx] = new
            self._tables[slot, idx] = new
            self._cow_copies += 1
            telemetry.inc(COW_COPIES, site="decode")

    # -- lever path: n-gram prompt-lookup speculation ----------------------

    def _propose(self, prompt, out, k):
        """Prompt-lookup proposal: match the trailing `spec_ngram`
        tokens of the slot's history (prompt + generated) against
        earlier history and propose up to `k` continuation tokens of
        the most recent prior match."""
        n = self.spec_ngram
        hist = np.concatenate([prompt, np.asarray(out, np.int32)])
        if hist.size < n + 1:
            return _EMPTY_PROP
        gram = hist[-n:]
        for i in range(hist.size - n - 1, -1, -1):
            if np.array_equal(hist[i:i + n], gram):
                return hist[i + n:i + n + k].astype(np.int32)
        return _EMPTY_PROP

    def _decode_spec_once(self):
        """Speculative decode step: every live slot processes
        `lookahead+1` query rows in one wide program — its guaranteed
        next token plus its proposal. The longest proposal prefix
        matching the model's own greedy outputs is accepted in bulk;
        rejected rows need no rollback (their K/V sits beyond the
        slot's advanced position — dead data the next step
        overwrites)."""
        live_slots = [s for s, r in enumerate(self._slot_req)
                      if r is not None and self._slot_prefill[s] is None]
        if not live_slots:
            return self.slots_in_use
        if self.prefix_cache is not None:
            for s in live_slots:
                if self._slot_cow_idx[s] >= 0:
                    self._resolve_cow(s)
        S = self.slots
        Q = self.spec_lookahead + 1
        toks = np.zeros((S, Q), np.int32)
        start = np.zeros((S,), np.int32)
        n_real = np.zeros((S,), np.int32)
        props = {}
        for s in live_slots:
            req = self._slot_req[s]
            room = req.max_new_tokens - len(self._slot_out[s]) - 1
            k_s = min(self.spec_lookahead, room)
            prop = (self._propose(req.prompt, self._slot_out[s], k_s)
                    if k_s > 0 else _EMPTY_PROP)
            props[s] = prop
            toks[s, 0] = self._next_tok[s]
            if prop.size:
                toks[s, 1:1 + prop.size] = prop
            start[s] = self._positions[s]
            n_real[s] = 1 + prop.size
        if self._page_san is not None:
            # rows [start, start+n_real) of each slot land in its table
            for s in live_slots:
                lo = int(start[s]) // self.page_size
                hi = (int(start[s]) + int(n_real[s]) - 1) // self.page_size
                self._page_san.note_write(
                    self._slot_req[s].request_id,
                    self._slot_pages[s][lo:hi + 1])
        tok, self.paged = self._wide(Q)(
            self.params, self.paged, jnp.asarray(toks),
            jnp.asarray(start), jnp.asarray(n_real),
            jnp.asarray(self._tables))
        tok = np.asarray(tok)
        for s in live_slots:
            req = self._slot_req[s]
            prop = props[s]
            # row i's argmax is the model's true greedy token i+1; the
            # proposal is accepted exactly as far as it matches them
            emitted = [int(tok[s, 0])]
            for i in range(prop.size):
                if int(prop[i]) != emitted[i]:
                    break
                emitted.append(int(tok[s, i + 1]))
            accepted = len(emitted) - 1
            self._spec_proposed += int(prop.size)
            self._spec_accepted += accepted
            if prop.size:
                telemetry.inc(SPEC_PROPOSED, amount=float(prop.size))
            if accepted:
                telemetry.inc(SPEC_ACCEPTED, amount=float(accepted))
            applied = 0
            for t in emitted:
                applied += 1
                self._slot_out[s].append(t)
                self._positions[s] += 1
                self._next_tok[s] = t
                if self._is_done(req, self._slot_out[s]):
                    self._finish(s)
                    break
            # Q device rows split: delivered tokens, rejected/unused
            # speculation rows, and padding rows past the proposal
            rejected = (1 + int(prop.size)) - applied
            pad = Q - 1 - int(prop.size)
            self._tokens["decode"] += applied
            telemetry.inc(TOKENS_TOTAL, amount=float(applied),
                          kind="decode")
            if rejected:
                self._tokens["spec_rejected"] += rejected
                telemetry.inc(TOKENS_TOTAL, amount=float(rejected),
                              kind="spec_rejected")
                telemetry.inc(WASTED_TOKENS, amount=float(rejected),
                              reason="spec_rejected")
            if pad:
                self._tokens["pad"] += pad
                telemetry.inc(TOKENS_TOTAL, amount=float(pad),
                              kind="pad")
                telemetry.inc(WASTED_TOKENS, amount=float(pad),
                              reason="spec_pad")
        if _dtrace.trace_active():
            rec = {
                "kind": REQ_STEP_KIND, "ts": time.time_ns(),
                "step": self.steps,
                "slots": [[self._slot_req[s].request_id,
                           len(self._slot_out[s]) + 1]
                          for s in live_slots
                          if self._slot_req[s] is not None]}
            if self.trace_lane is not None:
                rec["lane"] = self.trace_lane
            _dtrace.record_span(rec)
        return self.slots_in_use

    def _decode_once(self):
        live_slots = [s for s, r in enumerate(self._slot_req)
                      if r is not None and self._slot_prefill[s] is None]
        if not live_slots:
            return self.slots_in_use
        if self.prefix_cache is not None:
            for s in live_slots:
                if self._slot_cow_idx[s] >= 0:
                    self._resolve_cow(s)
        if self._page_san is not None:
            # the step writes one K/V entry per live slot at _positions[s]
            for s in live_slots:
                self._page_san.note_write(
                    self._slot_req[s].request_id,
                    [self._slot_pages[s][int(self._positions[s])
                                         // self.page_size]])
        tok, self.paged = self._decode(
            self.params, self.paged, jnp.asarray(self._next_tok),
            jnp.asarray(self._positions), jnp.asarray(self._tables))
        tok = np.asarray(tok)
        n_live = len(live_slots)
        self._tokens["decode"] += n_live
        telemetry.inc(TOKENS_TOTAL, amount=float(n_live), kind="decode")
        if _dtrace.trace_active():
            # ONE batched progress record per decode STEP (not per token):
            # [request_id, tokens emitted so far] per live slot. Not a
            # span — trace_merge partitions kind=req_step out of the span
            # pipeline and uses it for per-request step counting.
            rec = {
                "kind": REQ_STEP_KIND, "ts": time.time_ns(),
                "step": self.steps,
                "slots": [[self._slot_req[s].request_id,
                           len(self._slot_out[s]) + 1]
                          for s in live_slots]}
            if self.trace_lane is not None:
                rec["lane"] = self.trace_lane
            _dtrace.record_span(rec)
        for s in live_slots:
            req = self._slot_req[s]
            self._slot_out[s].append(int(tok[s]))
            self._positions[s] += 1
            self._next_tok[s] = tok[s]
            if self._is_done(req, self._slot_out[s]):
                self._finish(s)
        return self.slots_in_use

    def _is_done(self, req, out):
        if req.eos_id is not None and out and out[-1] == req.eos_id:
            return True
        return len(out) >= req.max_new_tokens

    def _finish(self, slot, reason=None):
        """Evict: record the result and recycle the pages IMMEDIATELY —
        the very next _admit() can hand them to a queued request.
        `reason` overrides the eos/length inference (mid-stream
        eviction passes "evicted").

        Idempotent per occupancy: a slot that already finished (EOS in
        the same step a cancel() raced in, say) returns without
        touching the allocator — the double-free guard the MXS010
        regression test pins."""
        req = self._slot_req[slot]
        if req is None:
            return
        out = self._slot_out[slot]
        if reason is None:
            reason = ("eos" if req.eos_id is not None and out
                      and out[-1] == req.eos_id else "length")
        now = self._clock()
        queue_wait = req.admitted_at - req.submitted_at
        latency = now - req.submitted_at
        self._results[req.request_id] = RequestResult(
            request_id=req.request_id, tokens=list(out),
            finish_reason=reason, prompt_len=int(req.prompt.size),
            queue_wait_s=queue_wait, latency_s=latency,
            ttft_s=req.ttft_s)
        telemetry.inc(REQUESTS_TOTAL, outcome=reason)
        telemetry.observe(REQUEST_SECONDS, latency,
                          buckets=_LATENCY_BUCKETS)
        if reason == "evicted":
            # everything this request pushed through the device is now
            # undelivered output (its pad rows are already in the pad kind)
            wasted = int(req.prompt.size) + len(out)
            self._wasted_evicted += wasted
            telemetry.inc(WASTED_TOKENS, amount=float(wasted),
                          reason="evicted")
        self._record_timeline(req, len(out), reason, queue_wait, latency)
        _recorder.log_event("serving_request_finish",
                            request=req.request_id, outcome=reason,
                            tokens=len(out))
        if self.slo is not None:
            self.slo.observe_request(
                ttft=req.ttft_s, queue_wait=queue_wait,
                request_latency=latency,
                goodput=self._goodput_fraction())
        tr = req.trace
        if tr is not None:
            clk_first = tr.get("clk_first")
            if clk_first is not None and len(out) > 1:
                self._emit_request_record(
                    REQ_DECODE_SPAN, tr,
                    ts=self._trace_ts(tr, clk_first),
                    dur_s=now - clk_first, pid=tr["sid"],
                    extra={"request": req.request_id,
                           "steps": len(out) - 1})
            self._emit_request_record(
                REQ_SPAN, tr, ts=tr["ns_submit"], dur_s=latency,
                sid=tr["sid"], pid=tr.get("pid"),
                extra={"request": req.request_id,
                       "prompt_len": int(req.prompt.size),
                       "tokens": len(out), "finish": reason,
                       "queue_wait_s": queue_wait,
                       "ttft_s": req.ttft_s, "latency_s": latency,
                       "decode_steps": max(0, len(out) - 1)})
        self.allocator.free(self._slot_pages[slot], owner=req.request_id)
        self._slot_req[slot] = None
        self._slot_pages[slot] = []
        self._slot_out[slot] = []
        self._slot_prefill[slot] = None
        self._slot_cow_idx[slot] = -1
        self._tables[slot] = 0
        self._positions[slot] = 0
        self._next_tok[slot] = 0

    # -- per-request trace plumbing ----------------------------------------

    @staticmethod
    def _trace_ts(tr, clk):
        """Wall-clock ns for an engine-clock instant: deltas come from
        the injectable engine clock (so trace durations agree with the
        latency histograms even under a synthetic clock), anchored to
        the wall time captured at submit."""
        return tr["ns_submit"] + int((clk - tr["clk_submit"]) * 1e9)

    def _emit_request_record(self, name, tr, *, ts, dur_s, extra,
                             sid=None, pid=None):
        record = {"name": name, "tid": tr["tid"],
                  "sid": sid if sid is not None else _dtrace.new_id(),
                  "ts": int(ts), "dur_ns": max(0, int(dur_s * 1e9)),
                  "extra": extra}
        if pid is not None:
            record["pid"] = pid
        if self.trace_lane is not None:
            record["lane"] = self.trace_lane
        _dtrace.record_span(record)

    def _record_timeline(self, req, n_tokens, reason, queue_wait, latency):
        self._timelines.append({
            "request_id": req.request_id,
            "prompt_len": int(req.prompt.size),
            "tokens": n_tokens,
            "finish": reason,
            "queue_wait_s": queue_wait,
            "ttft_s": req.ttft_s if req.admitted_at else None,
            "latency_s": latency,
        })

    # -- introspection ------------------------------------------------------

    def recent_timelines(self):
        """Last-N finished-request timeline dicts (newest last) — the
        payload the SLO breach dump carries."""
        return list(self._timelines)

    def goodput(self):
        """Token accounting split: device token-positions by kind, the
        wasted share (prefill padding + rejected speculation + evicted
        requests' tokens), and the useful fraction."""
        processed = sum(self._tokens.values())
        useful = (self._tokens["prefill"] + self._tokens["decode"]
                  - self._wasted_evicted)
        return {
            "prefill": self._tokens["prefill"],
            "decode": self._tokens["decode"],
            "pad": self._tokens["pad"],
            "spec_rejected": self._tokens["spec_rejected"],
            "wasted_evicted": self._wasted_evicted,
            "processed": processed,
            "useful": useful,
            "fraction": useful / processed if processed else 1.0,
        }

    @property
    def prefix_hit_rate(self):
        """Fraction of admissions that mapped at least one cached page
        (0.0 when the prefix cache is off or nothing was admitted)."""
        return (self._prefix_hits / self._prefix_lookups
                if self._prefix_lookups else 0.0)

    @property
    def prefix_tokens_saved(self):
        """Prompt tokens never prefilled because their pages came from
        the prefix cache."""
        return self._prefix_tokens_saved

    @property
    def cow_copies(self):
        """Copy-on-write page copies performed (admission + decode)."""
        return self._cow_copies

    @property
    def spec_acceptance(self):
        """Accepted / proposed draft tokens (0.0 before any
        proposal)."""
        return (self._spec_accepted / self._spec_proposed
                if self._spec_proposed else 0.0)

    def _goodput_fraction(self):
        processed = sum(self._tokens.values())
        if not processed:
            return 1.0
        return (self._tokens["prefill"] + self._tokens["decode"]
                - self._wasted_evicted) / processed

    def debug_snapshot(self):
        """Live-engine JSON snapshot, served at /debug/engine by the
        telemetry HTTP server (MXTPU_DEBUG_ENDPOINTS=1) and rendered by
        tools/serving_top.py."""
        with self._lock:
            return self._debug_snapshot_locked()

    def _debug_snapshot_locked(self):
        now = self._clock()
        slot_rows = []
        for s, req in enumerate(self._slot_req):
            if req is None:
                slot_rows.append({"slot": s, "state": "idle"})
            else:
                pending = self._slot_prefill[s]
                slot_rows.append({
                    "slot": s,
                    "state": "prefilling" if pending else "decoding",
                    "request_id": req.request_id,
                    "age_s": now - req.submitted_at,
                    "prompt_len": int(req.prompt.size),
                    "tokens_out": len(self._slot_out[s]),
                    "position": (int(pending["pos"]) if pending
                                 else int(self._positions[s])),
                    "pages_held": len(self._slot_pages[s]),
                })
        queued = [{"request_id": r.request_id,
                   "age_s": now - r.submitted_at,
                   "prompt_len": int(r.prompt.size),
                   "max_new_tokens": r.max_new_tokens}
                  for r in self._queue]
        compile_rows = {
            fn: {"signatures": v["signatures"], "retraces": v["retraces"]}
            for fn, v in compilereg.snapshot().items()
            if fn.startswith("serving_")}
        cache = self.prefix_cache
        prefix_rows = None
        if cache is not None:
            prefix_rows = {
                "cached_pages": cache.cached_pages,
                "capacity": cache.max_pages,
                "lookups": self._prefix_lookups,
                "hits": self._prefix_hits,
                "hit_rate": self.prefix_hit_rate,
                "tokens_saved": self._prefix_tokens_saved,
                "evictions": cache.evictions,
                "cow_copies": self._cow_copies,
                "refcount_histogram": {
                    str(k): v for k, v in sorted(
                        self.allocator.refcount_histogram().items())},
            }
        spec_rows = None
        if self.spec_ngram:
            spec_rows = {
                "ngram": self.spec_ngram,
                "lookahead": self.spec_lookahead,
                "proposed": self._spec_proposed,
                "accepted": self._spec_accepted,
                "acceptance": self.spec_acceptance,
            }
        chunk_rows = None
        if self.prefill_chunk:
            chunk_rows = {
                "chunk": self.prefill_chunk,
                "in_flight": sum(p is not None
                                 for p in self._slot_prefill),
                "chunks_total": self._prefill_chunks,
            }
        return {
            "schema": "mxtpu-serving-engine-debug-v2",
            "steps": self.steps,
            "slots": slot_rows,
            "slots_in_use": self.slots_in_use,
            "queue": queued,
            "queue_depth": len(self._queue),
            "pages": {
                "capacity": self.allocator.capacity,
                "in_use": self.allocator.num_in_use,
                "free": self.allocator.num_free,
                "page_size": self.allocator.page_size,
                "occupancy": self.allocator.occupancy(),
                "fragmentation": self.allocator.fragmentation(),
            },
            "prefix_cache": prefix_rows,
            "speculation": spec_rows,
            "chunked_prefill": chunk_rows,
            "tokens": self.goodput(),
            "compile": compile_rows,
            "slo": self.slo.snapshot() if self.slo is not None else None,
            "requests_finished": len(self._results),
        }

    def cancel(self, request_id):
        """Cancel a request: still-queued requests finish as
        "cancelled" (nothing was processed); live ones are EVICTED
        mid-stream — pages recycle immediately and every token they
        pushed through the device counts as wasted. Returns True when
        the request was cancelled, False when the id is unknown or
        already finished."""
        with self._lock:
            return self._cancel_locked(request_id)

    def _cancel_locked(self, request_id):
        for i, req in enumerate(self._queue):
            if req.request_id == request_id:
                del self._queue[i]
                now = self._clock()
                waited = now - req.submitted_at
                self._results[request_id] = RequestResult(
                    request_id=request_id, tokens=[],
                    finish_reason="cancelled",
                    prompt_len=int(req.prompt.size),
                    queue_wait_s=waited, latency_s=waited)
                telemetry.inc(REQUESTS_TOTAL, outcome="cancelled")
                telemetry.set_gauge(QUEUE_DEPTH, len(self._queue))
                self._record_timeline(req, 0, "cancelled", waited, waited)
                _recorder.log_event("serving_request_finish",
                                    request=request_id,
                                    outcome="cancelled", tokens=0)
                if req.trace is not None:
                    self._emit_request_record(
                        REQ_SPAN, req.trace, ts=req.trace["ns_submit"],
                        dur_s=waited, sid=req.trace["sid"],
                        pid=req.trace.get("pid"),
                        extra={"request": request_id,
                               "prompt_len": int(req.prompt.size),
                               "tokens": 0, "finish": "cancelled",
                               "latency_s": waited, "decode_steps": 0})
                return True
        for s, req in enumerate(self._slot_req):
            if req is not None and req.request_id == request_id:
                self._finish(s, reason="evicted")
                self._export_gauges()
                return True
        return False

    def _export_gauges(self):
        telemetry.set_gauge(QUEUE_DEPTH, len(self._queue))
        telemetry.set_gauge(SLOTS_IN_USE, self.slots_in_use)
        telemetry.set_gauge(PAGES_IN_USE, self.allocator.num_in_use)
        telemetry.set_gauge(
            PAGE_UTILIZATION,
            self.allocator.num_in_use / max(1, self.allocator.capacity))
        telemetry.set_gauge(
            OLDEST_QUEUED,
            self._clock() - self._queue[0].submitted_at
            if self._queue else 0.0)
        telemetry.set_gauge(GOODPUT, self._goodput_fraction())
        if self.prefix_cache is not None:
            telemetry.set_gauge(PREFIX_CACHED_PAGES,
                                self.prefix_cache.cached_pages)
