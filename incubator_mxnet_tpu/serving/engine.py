"""Continuous-batching decode engine over the paged KV cache.

Iteration-level scheduling (Orca) over PagedAttention-style storage
(vLLM), on the repo's own primitives:

- a FIFO request queue feeding a FIXED set of `MXTPU_DECODE_SLOTS`
  decode slots — the static batch dimension of every decode step;
- admission = all-or-nothing page allocation (serving/pages.py) for the
  request's worst case, then a BUCKETED prefill (prompt padded up to one
  of a few static lengths — the MXTPU_SPARSE_NNZ_BUCKETING idea applied
  to sequence length) writing prompt K/V straight into the pages;
- one `decode_step_paged` per engine step advances EVERY live slot one
  token, each at its own depth (per-slot positions + page-table rows);
- eviction on EOS or max-tokens recycles pages immediately — the next
  admission can reuse them without touching device memory.

Every device call has a static shape: one decode program, one prefill
program per bucket. The steady state therefore performs ZERO retraces
(compilereg-gated in CI) and a warm replica performs zero compiles
(`warm()` AOT-populates the PR 10 compile cache; tools/warmup.py
--decode drives it).

Greedy decoding (temperature 0) — token-for-token identical to
sequential `models.transformer.generate()` per request, which is the
equivalence CI asserts.
"""
from __future__ import annotations

import dataclasses
import itertools
import time
from collections import deque

import numpy as np
import jax
import jax.numpy as jnp

from .. import compile_cache, config, telemetry
from ..models import transformer as _tfm
from ..telemetry import compilereg
from ..telemetry import distributed as _dtrace
from ..telemetry import exporters as _exporters
from ..telemetry import recorder as _recorder
from ..telemetry import slo as _slo
from .pages import PageAllocator

__all__ = ["Request", "RequestResult", "ServingEngine"]

QUEUE_DEPTH = "mxtpu_serving_queue_depth"
SLOTS_IN_USE = "mxtpu_serving_slots_in_use"
PAGES_IN_USE = "mxtpu_serving_pages_in_use"
PAGE_UTILIZATION = "mxtpu_serving_page_utilization"
REQUESTS_TOTAL = "mxtpu_serving_requests_total"
TOKENS_TOTAL = "mxtpu_serving_tokens_total"
REQUEST_SECONDS = "mxtpu_serving_request_seconds"
QUEUE_WAIT_SECONDS = "mxtpu_serving_queue_wait_seconds"
TTFT_SECONDS = "mxtpu_serving_ttft_seconds"
OLDEST_QUEUED = "mxtpu_serving_oldest_queued_seconds"
ADMISSION_BLOCKED = "mxtpu_serving_admission_blocked_total"
WASTED_TOKENS = "mxtpu_serving_wasted_tokens_total"
GOODPUT = "mxtpu_serving_goodput"

# per-request lifecycle record names (registered in telemetry/names.py);
# emitted straight through distributed.record_span — zero-cost when
# tracing is off, and rendered as one lane per request by
# tools/trace_merge.py --requests
REQ_SPAN = "serving.request"
REQ_QUEUED_SPAN = "serving.request.queued"
REQ_PREFILL_SPAN = "serving.request.prefill"
REQ_DECODE_SPAN = "serving.request.decode"
REQ_STEP_KIND = "req_step"  # batched decode-progress record, one per STEP

# sub-ms to minutes: decode steps are ms-scale, queued requests can wait
_LATENCY_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                    0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0)


@dataclasses.dataclass
class Request:
    """One generation request: greedy-decode up to `max_new_tokens`
    continuation tokens, stopping early when `eos_id` is produced
    (the EOS token is included in the output)."""
    request_id: int
    prompt: np.ndarray  # (T_p,) int32
    max_new_tokens: int
    eos_id: int | None = None
    submitted_at: float = 0.0
    admitted_at: float = 0.0
    ttft_s: float = 0.0       # set at prefill; 0 until admitted
    trace: dict | None = None  # per-request trace context (tracing on)


@dataclasses.dataclass
class RequestResult:
    request_id: int
    tokens: list  # generated continuation (includes EOS when hit)
    finish_reason: str  # "eos" | "length" | "evicted" | "cancelled"
    prompt_len: int
    queue_wait_s: float
    latency_s: float


def _default_buckets(max_len):
    """Powers of two from 16 up to (and always including) max_len."""
    raw = str(config.get("MXTPU_PREFILL_BUCKETS") or "")
    if raw.strip():
        buckets = sorted({int(b) for b in raw.split(",") if b.strip()})
    else:
        buckets, b = [], 16
        while b < max_len:
            buckets.append(b)
            b *= 2
        buckets.append(max_len)
    return [b for b in buckets if b <= max_len] or [max_len]


class ServingEngine:
    """Continuous-batching greedy-decode engine for one transformer.

    >>> eng = ServingEngine(params, cfg)
    >>> rid = eng.submit([1, 2, 3], max_new_tokens=16, eos_id=0)
    >>> results = eng.run()          # drain queue + slots
    >>> results[rid].tokens

    `step()` runs ONE scheduler iteration (admissions + one decode
    step) for callers that interleave serving with other work.
    """

    def __init__(self, params, cfg, *, slots=None, page_size=None,
                 num_pages=None, max_len=None, clock=time.monotonic,
                 slo=None):
        self.params = params
        self.cfg = cfg
        self.page_size = int(page_size or config.get("MXTPU_PAGE_SIZE"))
        self.slots = int(slots or config.get("MXTPU_DECODE_SLOTS"))
        self.max_len = int(max_len or cfg.max_len)
        if self.max_len > cfg.max_len:
            raise ValueError(f"max_len {self.max_len} exceeds the "
                             f"model's positional table ({cfg.max_len})")
        self.table_width = -(-self.max_len // self.page_size)
        if num_pages is None:
            num_pages = int(config.get("MXTPU_SERVING_PAGES"))
        if not num_pages:  # auto: every slot can hold a full sequence
            num_pages = self.slots * self.table_width + 1
        self.allocator = PageAllocator(num_pages, self.page_size)
        self.paged = _tfm.init_paged_kv_cache(cfg, num_pages,
                                              self.page_size)
        self.prefill_buckets = _default_buckets(self.max_len)
        self._clock = clock

        S, W = self.slots, self.table_width
        self._tables = np.zeros((S, W), np.int32)
        self._positions = np.zeros((S,), np.int32)
        self._next_tok = np.zeros((S,), np.int32)
        self._slot_req: list[Request | None] = [None] * S
        self._slot_pages: list[list] = [[] for _ in range(S)]
        self._slot_out: list[list] = [[] for _ in range(S)]
        self._queue: deque[Request] = deque()
        self._results: dict[int, RequestResult] = {}
        self._ids = itertools.count()
        self.steps = 0

        # host-side goodput accounting (source of truth independent of
        # whether the metrics registry is enabled): device token-position
        # kinds, plus tokens spent on requests later evicted mid-stream
        self._tokens = {"prefill": 0, "decode": 0, "pad": 0}
        self._wasted_evicted = 0
        # last-N finished-request timelines, embedded in SLO breach dumps
        # and the /debug/engine snapshot
        self._timelines: deque = deque(
            maxlen=max(1, int(config.get("MXTPU_SLO_DUMP_TIMELINES"))))
        if slo is None:
            slo = _slo.from_env(timelines=self.recent_timelines)
        self.slo = slo or None
        _exporters.register_debug_handler("/debug/engine",
                                          self.debug_snapshot)

        # donation frees the old pool the moment the step runs; CPU
        # buffers aren't donatable (jax warns and copies anyway)
        donate = (1,) if jax.default_backend() != "cpu" else ()
        self._decode = compile_cache.wrap(
            "serving_decode_step",
            jax.jit(self._decode_fn, donate_argnums=donate),
            donated=donate)
        # one jit per bucket: the bucket length is baked into the prompt
        # shape, so each T_b is its own named executable for compilereg,
        # the compile cache, and warmup
        self._prefills = {
            T_b: compile_cache.wrap(
                f"serving_prefill_b{T_b}",
                jax.jit(self._prefill_fn, donate_argnums=donate),
                donated=donate, static_key=T_b)
            for T_b in self.prefill_buckets}

    # -- jitted programs ---------------------------------------------------

    def _decode_fn(self, params, paged, tokens, positions, table):
        logits, paged = _tfm.decode_step_paged(
            params, paged, tokens, positions, table, self.cfg)
        return jnp.argmax(logits, axis=-1).astype(jnp.int32), paged

    def _prefill_fn(self, params, paged, prompt, true_len, table):
        paged, logits = _tfm.prefill_paged(
            params, paged, prompt, true_len, table, self.cfg)
        return jnp.argmax(logits, axis=-1).astype(jnp.int32), paged

    # -- public API --------------------------------------------------------

    def submit(self, prompt, max_new_tokens, eos_id=None):
        """Queue one request; returns its request id. Validation is
        eager: an unservable request fails here, not mid-decode."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size == 0:
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            raise ValueError(f"max_new_tokens must be >= 1, "
                             f"got {max_new_tokens}")
        total = prompt.size + int(max_new_tokens)
        if total > self.max_len:
            raise ValueError(
                f"prompt ({prompt.size}) + max_new_tokens "
                f"({max_new_tokens}) exceeds max_len ({self.max_len})")
        need = self.allocator.pages_needed(total)
        if need > self.allocator.capacity:
            raise ValueError(
                f"request needs {need} pages but the pool only has "
                f"{self.allocator.capacity}")
        rid = next(self._ids)
        req = Request(rid, prompt, int(max_new_tokens), eos_id,
                      submitted_at=self._clock())
        if _dtrace.trace_active():
            # trace context is born HERE: tid groups the whole lifecycle,
            # sid is the root "serving.request" span every stage parents
            # under, ns_submit anchors engine-clock deltas to wall time
            req.trace = {"tid": _dtrace.new_id(), "sid": _dtrace.new_id(),
                         "ns_submit": time.time_ns(),
                         "clk_submit": req.submitted_at}
        self._queue.append(req)
        telemetry.set_gauge(QUEUE_DEPTH, len(self._queue))
        telemetry.set_gauge(
            OLDEST_QUEUED,
            self._clock() - self._queue[0].submitted_at)
        return rid

    def step(self):
        """One scheduler iteration: admit queued requests into free
        slots (FIFO, backpressured by page availability), then advance
        every live slot one token in a single decode program. Returns
        the number of live slots after the iteration."""
        with telemetry.span("serving.step", step=self.steps):
            self._admit()
            live = self._decode_once()
        self.steps += 1
        self._export_gauges()
        return live

    def run(self, max_steps=100_000):
        """Drive step() until the queue and every slot drain; returns
        {request_id: RequestResult} for everything finished so far.
        `max_steps` bounds a scheduler bug (a request that can never
        finish) — hitting it raises instead of spinning forever."""
        for _ in range(max_steps):
            if not self._queue and not any(self._slot_req):
                return dict(self._results)
            self.step()
        raise RuntimeError(f"serving engine did not drain within "
                           f"{max_steps} steps")

    def results(self):
        return dict(self._results)

    @property
    def queue_depth(self):
        return len(self._queue)

    @property
    def slots_in_use(self):
        return sum(r is not None for r in self._slot_req)

    def warm(self):
        """AOT-precompile the decode step and every prefill bucket into
        the persistent compile cache (no execution, no buffer writes).
        Returns {site: status} with compile_cache.warm statuses."""
        S, W = self.slots, self.table_width
        a = compile_cache.abstractify
        i32 = jnp.int32
        out = {}
        if getattr(self._decode, "is_cached", False):
            out["serving_decode_step"] = self._decode.warm(
                a(self.params), a(self.paged),
                jax.ShapeDtypeStruct((S,), i32),
                jax.ShapeDtypeStruct((S,), i32),
                jax.ShapeDtypeStruct((S, W), i32))
        for T_b, fn in self._prefills.items():
            if getattr(fn, "is_cached", False):
                out[f"serving_prefill_b{T_b}"] = fn.warm(
                    a(self.params), a(self.paged),
                    jax.ShapeDtypeStruct((1, T_b), i32),
                    jax.ShapeDtypeStruct((1,), i32),
                    jax.ShapeDtypeStruct((1, W), i32))
        return out

    # -- scheduling internals ----------------------------------------------

    def _free_slot(self):
        for s, r in enumerate(self._slot_req):
            if r is None:
                return s
        return None

    def _bucket_for(self, n):
        for b in self.prefill_buckets:
            if b >= n:
                return b
        raise ValueError(f"prompt length {n} exceeds the largest "
                         f"prefill bucket {self.prefill_buckets[-1]}")

    def _admit(self):
        """FIFO admission: stop at the first request that can't get a
        slot or its pages (head-of-line order keeps scheduling
        deterministic — no small request overtakes a starved big one)."""
        while self._queue:
            slot = self._free_slot()
            if slot is None:
                telemetry.inc(ADMISSION_BLOCKED, reason="slots")
                return
            req = self._queue[0]
            total = req.prompt.size + req.max_new_tokens
            pages = self.allocator.alloc(self.allocator.pages_needed(total))
            if pages is None:
                telemetry.inc(ADMISSION_BLOCKED, reason="pages")
                return  # backpressure: wait for an eviction
            self._queue.popleft()
            req.admitted_at = self._clock()
            telemetry.observe(QUEUE_WAIT_SECONDS,
                              req.admitted_at - req.submitted_at,
                              buckets=_LATENCY_BUCKETS)
            telemetry.set_gauge(QUEUE_DEPTH, len(self._queue))
            if req.trace is not None:
                self._emit_request_record(
                    REQ_QUEUED_SPAN, req.trace, ts=req.trace["ns_submit"],
                    dur_s=req.admitted_at - req.submitted_at,
                    pid=req.trace["sid"],
                    extra={"request": req.request_id})
            self._prefill_into(slot, req, pages)

    def _prefill_into(self, slot, req, pages):
        T_p = req.prompt.size
        T_b = self._bucket_for(T_p)
        row = np.asarray(
            self.allocator.table_row(pages, self.table_width), np.int32)
        prompt = np.zeros((1, T_b), np.int32)
        prompt[0, :T_p] = req.prompt
        clk_prefill = self._clock()
        with telemetry.span("serving.prefill", request=req.request_id,
                            bucket=T_b):
            tok, self.paged = self._prefills[T_b](
                self.params, self.paged, jnp.asarray(prompt),
                jnp.asarray([T_p], np.int32), jnp.asarray(row[None]))
        first = int(np.asarray(tok)[0])
        clk_first = self._clock()
        pad = T_b - T_p
        self._tokens["prefill"] += T_p
        telemetry.inc(TOKENS_TOTAL, amount=float(T_p), kind="prefill")
        if pad:
            # padded rows run through the MXU like real tokens — they are
            # processed-but-wasted, the prefill half of the goodput split
            self._tokens["pad"] += pad
            telemetry.inc(TOKENS_TOTAL, amount=float(pad), kind="pad")
            telemetry.inc(WASTED_TOKENS, amount=float(pad),
                          reason="prefill_pad")
        req.ttft_s = clk_first - req.submitted_at
        telemetry.observe(TTFT_SECONDS, req.ttft_s,
                          buckets=_LATENCY_BUCKETS)
        if req.trace is not None:
            req.trace["clk_first"] = clk_first
            self._emit_request_record(
                REQ_PREFILL_SPAN, req.trace,
                ts=self._trace_ts(req.trace, clk_prefill),
                dur_s=clk_first - clk_prefill, pid=req.trace["sid"],
                extra={"request": req.request_id, "bucket": T_b,
                       "prompt_len": T_p, "pad": pad})
        self._slot_req[slot] = req
        self._slot_pages[slot] = pages
        self._slot_out[slot] = [first]
        self._tables[slot] = row
        self._positions[slot] = T_p
        self._next_tok[slot] = first
        if self._is_done(req, [first]):
            self._finish(slot)

    def _decode_once(self):
        live_slots = [s for s, r in enumerate(self._slot_req)
                      if r is not None]
        if not live_slots:
            return 0
        tok, self.paged = self._decode(
            self.params, self.paged, jnp.asarray(self._next_tok),
            jnp.asarray(self._positions), jnp.asarray(self._tables))
        tok = np.asarray(tok)
        n_live = len(live_slots)
        self._tokens["decode"] += n_live
        telemetry.inc(TOKENS_TOTAL, amount=float(n_live), kind="decode")
        if _dtrace.trace_active():
            # ONE batched progress record per decode STEP (not per token):
            # [request_id, tokens emitted so far] per live slot. Not a
            # span — trace_merge partitions kind=req_step out of the span
            # pipeline and uses it for per-request step counting.
            _dtrace.record_span({
                "kind": REQ_STEP_KIND, "ts": time.time_ns(),
                "step": self.steps,
                "slots": [[self._slot_req[s].request_id,
                           len(self._slot_out[s]) + 1]
                          for s in live_slots]})
        for s in live_slots:
            req = self._slot_req[s]
            self._slot_out[s].append(int(tok[s]))
            self._positions[s] += 1
            self._next_tok[s] = tok[s]
            if self._is_done(req, self._slot_out[s]):
                self._finish(s)
        return self.slots_in_use

    def _is_done(self, req, out):
        if req.eos_id is not None and out and out[-1] == req.eos_id:
            return True
        return len(out) >= req.max_new_tokens

    def _finish(self, slot, reason=None):
        """Evict: record the result and recycle the pages IMMEDIATELY —
        the very next _admit() can hand them to a queued request.
        `reason` overrides the eos/length inference (mid-stream
        eviction passes "evicted")."""
        req = self._slot_req[slot]
        out = self._slot_out[slot]
        if reason is None:
            reason = ("eos" if req.eos_id is not None and out
                      and out[-1] == req.eos_id else "length")
        now = self._clock()
        queue_wait = req.admitted_at - req.submitted_at
        latency = now - req.submitted_at
        self._results[req.request_id] = RequestResult(
            request_id=req.request_id, tokens=list(out),
            finish_reason=reason, prompt_len=int(req.prompt.size),
            queue_wait_s=queue_wait, latency_s=latency)
        telemetry.inc(REQUESTS_TOTAL, outcome=reason)
        telemetry.observe(REQUEST_SECONDS, latency,
                          buckets=_LATENCY_BUCKETS)
        if reason == "evicted":
            # everything this request pushed through the device is now
            # undelivered output (its pad rows are already in the pad kind)
            wasted = int(req.prompt.size) + len(out)
            self._wasted_evicted += wasted
            telemetry.inc(WASTED_TOKENS, amount=float(wasted),
                          reason="evicted")
        self._record_timeline(req, len(out), reason, queue_wait, latency)
        _recorder.log_event("serving_request_finish",
                            request=req.request_id, outcome=reason,
                            tokens=len(out))
        if self.slo is not None:
            self.slo.observe_request(
                ttft=req.ttft_s, queue_wait=queue_wait,
                request_latency=latency,
                goodput=self._goodput_fraction())
        tr = req.trace
        if tr is not None:
            clk_first = tr.get("clk_first")
            if clk_first is not None and len(out) > 1:
                self._emit_request_record(
                    REQ_DECODE_SPAN, tr,
                    ts=self._trace_ts(tr, clk_first),
                    dur_s=now - clk_first, pid=tr["sid"],
                    extra={"request": req.request_id,
                           "steps": len(out) - 1})
            self._emit_request_record(
                REQ_SPAN, tr, ts=tr["ns_submit"], dur_s=latency,
                sid=tr["sid"],
                extra={"request": req.request_id,
                       "prompt_len": int(req.prompt.size),
                       "tokens": len(out), "finish": reason,
                       "queue_wait_s": queue_wait,
                       "ttft_s": req.ttft_s, "latency_s": latency,
                       "decode_steps": max(0, len(out) - 1)})
        self.allocator.free(self._slot_pages[slot])
        self._slot_req[slot] = None
        self._slot_pages[slot] = []
        self._slot_out[slot] = []
        self._tables[slot] = 0
        self._positions[slot] = 0
        self._next_tok[slot] = 0

    # -- per-request trace plumbing ----------------------------------------

    @staticmethod
    def _trace_ts(tr, clk):
        """Wall-clock ns for an engine-clock instant: deltas come from
        the injectable engine clock (so trace durations agree with the
        latency histograms even under a synthetic clock), anchored to
        the wall time captured at submit."""
        return tr["ns_submit"] + int((clk - tr["clk_submit"]) * 1e9)

    @staticmethod
    def _emit_request_record(name, tr, *, ts, dur_s, extra,
                             sid=None, pid=None):
        record = {"name": name, "tid": tr["tid"],
                  "sid": sid if sid is not None else _dtrace.new_id(),
                  "ts": int(ts), "dur_ns": max(0, int(dur_s * 1e9)),
                  "extra": extra}
        if pid is not None:
            record["pid"] = pid
        _dtrace.record_span(record)

    def _record_timeline(self, req, n_tokens, reason, queue_wait, latency):
        self._timelines.append({
            "request_id": req.request_id,
            "prompt_len": int(req.prompt.size),
            "tokens": n_tokens,
            "finish": reason,
            "queue_wait_s": queue_wait,
            "ttft_s": req.ttft_s if req.admitted_at else None,
            "latency_s": latency,
        })

    # -- introspection ------------------------------------------------------

    def recent_timelines(self):
        """Last-N finished-request timeline dicts (newest last) — the
        payload the SLO breach dump carries."""
        return list(self._timelines)

    def goodput(self):
        """Token accounting split: device token-positions by kind, the
        wasted share (prefill padding + evicted requests' tokens), and
        the useful fraction."""
        processed = sum(self._tokens.values())
        useful = (self._tokens["prefill"] + self._tokens["decode"]
                  - self._wasted_evicted)
        return {
            "prefill": self._tokens["prefill"],
            "decode": self._tokens["decode"],
            "pad": self._tokens["pad"],
            "wasted_evicted": self._wasted_evicted,
            "processed": processed,
            "useful": useful,
            "fraction": useful / processed if processed else 1.0,
        }

    def _goodput_fraction(self):
        processed = sum(self._tokens.values())
        if not processed:
            return 1.0
        return (self._tokens["prefill"] + self._tokens["decode"]
                - self._wasted_evicted) / processed

    def debug_snapshot(self):
        """Live-engine JSON snapshot, served at /debug/engine by the
        telemetry HTTP server (MXTPU_DEBUG_ENDPOINTS=1) and rendered by
        tools/serving_top.py."""
        now = self._clock()
        slot_rows = []
        for s, req in enumerate(self._slot_req):
            if req is None:
                slot_rows.append({"slot": s, "state": "idle"})
            else:
                slot_rows.append({
                    "slot": s, "state": "decoding",
                    "request_id": req.request_id,
                    "age_s": now - req.submitted_at,
                    "prompt_len": int(req.prompt.size),
                    "tokens_out": len(self._slot_out[s]),
                    "position": int(self._positions[s]),
                    "pages_held": len(self._slot_pages[s]),
                })
        queued = [{"request_id": r.request_id,
                   "age_s": now - r.submitted_at,
                   "prompt_len": int(r.prompt.size),
                   "max_new_tokens": r.max_new_tokens}
                  for r in self._queue]
        compile_rows = {
            fn: {"signatures": v["signatures"], "retraces": v["retraces"]}
            for fn, v in compilereg.snapshot().items()
            if fn.startswith("serving_")}
        return {
            "schema": "mxtpu-serving-engine-debug-v1",
            "steps": self.steps,
            "slots": slot_rows,
            "slots_in_use": self.slots_in_use,
            "queue": queued,
            "queue_depth": len(self._queue),
            "pages": {
                "capacity": self.allocator.capacity,
                "in_use": self.allocator.num_in_use,
                "free": self.allocator.num_free,
                "page_size": self.allocator.page_size,
                "occupancy": self.allocator.occupancy(),
                "fragmentation": self.allocator.fragmentation(),
            },
            "tokens": self.goodput(),
            "compile": compile_rows,
            "slo": self.slo.snapshot() if self.slo is not None else None,
            "requests_finished": len(self._results),
        }

    def cancel(self, request_id):
        """Cancel a request: still-queued requests finish as
        "cancelled" (nothing was processed); live ones are EVICTED
        mid-stream — pages recycle immediately and every token they
        pushed through the device counts as wasted. Returns True when
        the request was cancelled, False when the id is unknown or
        already finished."""
        for i, req in enumerate(self._queue):
            if req.request_id == request_id:
                del self._queue[i]
                now = self._clock()
                waited = now - req.submitted_at
                self._results[request_id] = RequestResult(
                    request_id=request_id, tokens=[],
                    finish_reason="cancelled",
                    prompt_len=int(req.prompt.size),
                    queue_wait_s=waited, latency_s=waited)
                telemetry.inc(REQUESTS_TOTAL, outcome="cancelled")
                telemetry.set_gauge(QUEUE_DEPTH, len(self._queue))
                self._record_timeline(req, 0, "cancelled", waited, waited)
                _recorder.log_event("serving_request_finish",
                                    request=request_id,
                                    outcome="cancelled", tokens=0)
                if req.trace is not None:
                    self._emit_request_record(
                        REQ_SPAN, req.trace, ts=req.trace["ns_submit"],
                        dur_s=waited, sid=req.trace["sid"],
                        extra={"request": request_id,
                               "prompt_len": int(req.prompt.size),
                               "tokens": 0, "finish": "cancelled",
                               "latency_s": waited, "decode_steps": 0})
                return True
        for s, req in enumerate(self._slot_req):
            if req is not None and req.request_id == request_id:
                self._finish(s, reason="evicted")
                self._export_gauges()
                return True
        return False

    def _export_gauges(self):
        telemetry.set_gauge(QUEUE_DEPTH, len(self._queue))
        telemetry.set_gauge(SLOTS_IN_USE, self.slots_in_use)
        telemetry.set_gauge(PAGES_IN_USE, self.allocator.num_in_use)
        telemetry.set_gauge(
            PAGE_UTILIZATION,
            self.allocator.num_in_use / max(1, self.allocator.capacity))
        telemetry.set_gauge(
            OLDEST_QUEUED,
            self._clock() - self._queue[0].submitted_at
            if self._queue else 0.0)
        telemetry.set_gauge(GOODPUT, self._goodput_fraction())
