"""Terascale sparse embedding tier: PS-row-sharded tables with deduped,
bucketed, prefetch-overlapped row pulls.

The reference framework's signature production workload is row-sparse
embedding training through ps-lite (ref: kvstore_dist row-sparse paths,
src/kvstore/kvstore_dist_server.h DataHandleRowSparse): tables too large
for one host live on the server fleet and workers move only the rows a
batch touches. This module is that tier on the TPU-native stack:

- **Row sharding.** Global row ``r`` of every table lives ONLY on shard
  server ``r % num_shards`` (as local row ``r // num_shards``). A table's
  HBM footprint divides across the fleet; a worker's footprint stays
  O(batch) — ledger-tracked under role ``embedding``. Tables initialize
  SERVER-SIDE from a deterministic per-global-row spec (ps.init_rows), so
  not even one shard's rows ever materialize on a worker.
- **Deduped, bucketed pulls.** Per step the batch's ids are uniqued on
  host (the zipfian dedup win), padded to the MXTPU_SPARSE_NNZ_BUCKETING
  power-of-two grid (stable shapes -> zero steady-state retraces; every
  pull registers its shape signature with telemetry.compilereg under
  ``embedding.pull``), and fetched with ONE ``pull_rows_multi`` RPC per
  shard server carrying every table's rows — mirroring the hierarchical
  push_many bucketing. The naive per-key path (one blocking RPC per table
  per server, no bucketing) is kept as ``path="per_key"`` for the
  recommender bench's A/B.
- **Pull/forward overlap.** With MXTPU_SPARSE_PREFETCH an ordered
  background worker owns ALL shard RPCs: grad pushes enqueue asynchronously
  behind the dense allreduce, and the NEXT batch's pull enqueues behind
  them — the queue preserves exactly the blocking path's push(N) < pull(N+1)
  order, so overlap changes wall time, never math. The step blocks only on
  the unfinished remainder of its prefetch, surfaced as the ``sparse_pull``
  stepstats phase.

Gradient flow: ``gluon.contrib.SparseEmbedding`` marks the pulled row
block as an autograd variable; backward deposits the block's dense
gradient (O(batch) rows), and ``push_grads`` ships the ``[:n_uniq]`` slice
to the owning shards, where the server applies it through the optimizer's
lazy row-sparse path (only touched rows update; membership-epoch fenced,
dedup-enveloped — exactly-once across retries).

Chaos/elasticity: a shard is a plain dense tensor under its key on a
ParameterServer, so the PR-6 state-transfer contract applies unchanged —
``snapshot()`` bootstraps each shard through its manifest-verified pull
path into a sharded_checkpoint directory, and ``restore_shard()`` seeds a
replacement server from those verified bytes.
"""
from __future__ import annotations

import queue
import threading

import numpy as np

from . import config as _config
from .analysis.sanitizers import san_lock
from .ndarray.ndarray import NDArray
from .ndarray.sparse import bucket_nnz, pad_row_ids  # noqa: F401 (re-export)

__all__ = ["ShardedEmbeddingService", "RemoteEmbeddingTable",
           "launch_local_fleet"]

PULL_RPCS_TOTAL = "mxtpu_embedding_pull_rpcs_total"
_PULL_RPCS_HELP = ("Row-pull RPCs issued by the sharded embedding service, "
                   "by path (batched = one multi-table RPC per server, "
                   "per_key = naive one RPC per table per server).")
PUSH_RPCS_TOTAL = "mxtpu_embedding_push_rpcs_total"
_PUSH_RPCS_HELP = ("Row-sparse grad-push RPCs issued by the sharded "
                   "embedding service, by path (batched / per_key).")
ROWS_PULLED_TOTAL = "mxtpu_embedding_rows_pulled_total"
_ROWS_HELP = ("Embedding rows fetched over the wire by the sharded "
              "embedding service (after dedup, including bucket padding).")
DEDUP_SAVED_TOTAL = "mxtpu_embedding_dedup_saved_rows_total"
_DEDUP_HELP = ("Embedding row fetches avoided by per-step id dedup: "
               "requested ids minus unique ids, summed over pulls (the "
               "zipfian dedup win in rows).")
PREFETCH_HITS_TOTAL = "mxtpu_embedding_prefetch_hits_total"
_PREFETCH_HELP = ("Embedding pulls served from a completed or in-flight "
                  "background prefetch, by outcome (ready = zero blocking, "
                  "wait = blocked on the remainder).")

# ledger role for worker-side pulled row blocks: the acceptance contract is
# live bytes O(batch uniques), never O(vocab)
LEDGER_ROLE = "embedding"


def _shard_of(ids, num_shards):
    """Route global row ids -> (shard index vector, local id vector)."""
    ids = np.asarray(ids, np.int64)
    return ids % num_shards, ids // num_shards


class _Pull:
    """A pull in flight on the worker thread (or completed inline)."""

    __slots__ = ("event", "blocks", "error", "started")

    def __init__(self):
        self.event = threading.Event()
        self.blocks = None
        self.error = None
        self.started = False


class RemoteEmbeddingTable:
    """Handle to one PS-sharded table: shape metadata + the id plan. All
    wire traffic goes through the owning service (so multi-table steps
    share one RPC per server)."""

    def __init__(self, service, name, vocab, dim, dtype):
        self.service = service
        self.name = name
        self.vocab = int(vocab)
        self.dim = int(dim)
        self.dtype = str(dtype)

    def pull(self, raw_ids):
        """Fetch the unique rows for `raw_ids` (deduped, bucket-padded).
        Returns (rows_block np.ndarray, inv, n_uniq): block[inv[:len]]
        reconstructs the per-position rows; rows [n_uniq:] are bucket
        padding (repeats) and must never see gradient math."""
        (block,), plan = self.service.pull([(self.name, raw_ids)])
        return block, plan[0][1], plan[0][2]

    def full_table(self):
        """Gather the whole table onto THIS host (verification only —
        workers never do this on the training path; O(vocab) here by
        construction)."""
        return self.service.full_table(self.name)


class ShardedEmbeddingService:
    """Client of an embedding-shard PS fleet. Not thread-safe for
    concurrent steps; ONE training loop drives it (the background worker
    is an internal pipeline stage, not a concurrency API)."""

    def __init__(self, addrs=None, clients=None, prefetch=None):
        from .ps import PSClient

        if clients is None:
            if addrs is None:
                raw = _config.get("MXTPU_EMBEDDING_SHARDS")
                addrs = [a for a in str(raw).split(",") if a.strip()]
            if not addrs:
                raise ValueError(
                    "no embedding shards: pass addrs/clients or set "
                    "MXTPU_EMBEDDING_SHARDS=host:port,host:port,...")
            clients = []
            for addr in addrs:
                host, _, port = str(addr).strip().rpartition(":")
                clients.append(PSClient(host, int(port)))
        self._clients = list(clients)
        self._tables = {}
        self._bucket_floor = {}  # table -> sticky high-water pull bucket
        self._optimizer = None
        self._pending_grads = []   # [(name, uniq_ids, rows_nd, n_uniq)]
        self._prefetched = {}      # plan key -> _Pull
        self._prefetch_on = (_config.get("MXTPU_SPARSE_PREFETCH")
                             if prefetch is None else bool(prefetch))
        self._jobs = None
        self._worker = None
        # cross-thread error handoff: the worker WRITES under this lock,
        # the training thread's _check_worker does an atomic
        # read-and-clear under it (an unlocked swap here was the classic
        # lost-error race the lock sanitizer exists to flag)
        self._worker_error_lock = san_lock("embedding.worker_error")
        self._worker_error = None
        if self._prefetch_on:
            self._jobs = queue.Queue()
            self._worker = threading.Thread(
                target=self._worker_loop, name="mxtpu-embedding-prefetch",
                daemon=True)
            self._worker.start()

    # -- fleet ---------------------------------------------------------------
    @property
    def num_shards(self):
        return len(self._clients)

    @property
    def clients(self):
        return list(self._clients)

    def set_optimizer(self, optimizer):
        """Ship the optimizer to every shard server (server-side lazy
        sparse apply — the worker never runs the embedding update)."""
        self._optimizer = optimizer
        for c in self._clients:
            c.set_optimizer(optimizer)

    def table(self, name, vocab, dim, dtype="float32", init="uniform",
              scale=0.05, seed=0):
        """Create (or re-open) a sharded table: shard s materializes its
        local rows server-side from the deterministic spec. Idempotent —
        init_rows is first-writer-wins per server."""
        handle = self._tables.get(name)
        if handle is not None:
            return handle
        vocab, dim = int(vocab), int(dim)
        S = self.num_shards
        for s, c in enumerate(self._clients):
            local_rows = (vocab - s + S - 1) // S
            spec = (("zeros",) if init == "zeros"
                    else ("uniform", float(scale), int(seed), s, S))
            c.init_rows(name, local_rows, dim, dtype, spec)
        handle = RemoteEmbeddingTable(self, name, vocab, dim, dtype)
        self._tables[name] = handle
        return handle

    # -- pull plane ----------------------------------------------------------
    def _plan(self, requests):
        """Host-side id plan for one step's pulls: dedup each table's ids,
        pad the unique set to its nnz bucket (knob-gated), register the
        resulting shape signature with the compile registry. Returns
        [(name, inv, n_uniq, padded_uniq_ids)].

        The bucket is a STICKY per-table high-water mark: once a table
        has pulled a 32-row bucket it keeps pulling 32 even when a later
        batch's uniques fit 16. A uniq count hovering at a bucket
        boundary would otherwise flip the wire/gather shape every few
        steps — and every flip back is a retrace; padding rows are far
        cheaper than recompiles."""
        from . import telemetry as _telemetry

        plan = []
        for name, raw in requests:
            raw = np.asarray(raw, np.int64).reshape(-1)
            uniq, inv = np.unique(raw, return_inverse=True)
            padded, n_uniq = pad_row_ids(uniq)
            if _config.get("MXTPU_SPARSE_NNZ_BUCKETING"):
                floor = self._bucket_floor.get(name, 0)
                if padded.size < floor:
                    padded = np.concatenate(
                        [padded,
                         np.full(floor - padded.size, padded[-1], np.int64)])
                else:
                    self._bucket_floor[name] = padded.size
            _telemetry.inc(DEDUP_SAVED_TOTAL, raw.size - n_uniq,
                           help=_DEDUP_HELP)
            dim = self._tables[name].dim
            _telemetry.compilereg.register(
                "embedding.pull",
                (("table", name), ("block", (int(padded.size), dim)),
                 ("inv", int(raw.size))))
            plan.append((name, inv.astype(np.int64), n_uniq, padded))
        return plan

    def _rpc_pull(self, plan):
        """The wire half: ONE pull_rows_multi RPC per shard server that
        owns any requested row, covering every table in the plan."""
        from . import telemetry as _telemetry

        S = self.num_shards
        blocks = [np.empty((p[3].size, self._tables[p[0]].dim),
                           _np_dtype(self._tables[p[0]].dtype))
                  for p in plan]
        per_server = [([], []) for _ in range(S)]  # (names, local ids)
        slots = [[] for _ in range(S)]             # (plan idx, positions)
        for i, (name, _inv, _n, ids) in enumerate(plan):
            shard, local = _shard_of(ids, S)
            for s in range(S):
                pos = np.nonzero(shard == s)[0]
                if pos.size == 0:
                    continue
                per_server[s][0].append(name)
                per_server[s][1].append(local[pos])
                slots[s].append((i, pos))
        with _telemetry.span("embedding.pull"):
            for s in range(S):
                names, locals_ = per_server[s]
                if not names:
                    continue
                out = self._clients[s].pull_rows_multi(names, locals_)
                _telemetry.inc(PULL_RPCS_TOTAL, 1, help=_PULL_RPCS_HELP,
                               path="batched")
                for (i, pos), rows in zip(slots[s], out):
                    blocks[i][pos] = rows
        _telemetry.inc(ROWS_PULLED_TOTAL,
                       sum(p[3].size for p in plan), help=_ROWS_HELP)
        return blocks

    def _plan_key(self, plan):
        return tuple((name, ids.tobytes()) for name, _i, _n, ids in plan)

    def prefetch(self, requests):
        """Enqueue the NEXT batch's pulls on the background worker: they
        run after every already-enqueued grad push (so the math matches
        the blocking path bit for bit) while the caller's dense compute
        proceeds. No-op when prefetch is off."""
        if not self._prefetch_on:
            return None
        plan = self._plan(requests)
        fut = _Pull()
        self._prefetched[self._plan_key(plan)] = fut
        self._jobs.put(("pull", plan, fut))
        return fut

    def pull(self, requests):
        """Fetch row blocks for `requests` = [(table_name, raw_ids)].
        Served from a matching prefetch when one is outstanding;
        otherwise the pull runs now — still ORDERED behind any pending
        async pushes. Blocking time lands in the sparse_pull stepstats
        phase. Returns (blocks, plan)."""
        from . import telemetry as _telemetry
        from .telemetry import stepstats as _stepstats

        self._check_worker()
        plan = self._plan(requests)
        fut = self._prefetched.pop(self._plan_key(plan), None)
        if fut is not None:
            _telemetry.inc(
                PREFETCH_HITS_TOTAL, 1, help=_PREFETCH_HELP,
                outcome="ready" if fut.event.is_set() else "wait")
            with _stepstats.phase("sparse_pull"):
                fut.event.wait()
            if fut.error is not None:
                raise fut.error
            return fut.blocks, plan
        with _stepstats.phase("sparse_pull"):
            if self._prefetch_on:
                # an unprefetched pull still queues, so it cannot overtake
                # an in-flight grad push of rows it is about to read
                fut = _Pull()
                self._jobs.put(("pull", plan, fut))
                fut.event.wait()
                if fut.error is not None:
                    raise fut.error
                return fut.blocks, plan
            return self._rpc_pull(plan), plan

    def pull_per_key(self, name, raw_ids):
        """The naive baseline the recommender bench A/Bs against: one
        BLOCKING pull_rows RPC per table per shard, no bucketing, no
        overlap (the id dedup itself is framework behavior — both paths
        share it, so weights stay comparable). Returns
        (rows_block, inv, n_uniq)."""
        from . import telemetry as _telemetry
        from .telemetry import stepstats as _stepstats

        raw = np.asarray(raw_ids, np.int64).reshape(-1)
        uniq, inv = np.unique(raw, return_inverse=True)
        _telemetry.inc(DEDUP_SAVED_TOTAL, raw.size - uniq.size,
                       help=_DEDUP_HELP)
        table = self._tables[name]
        _telemetry.compilereg.register(
            "embedding.pull",
            (("table", name), ("block", (int(uniq.size), table.dim)),
             ("inv", int(raw.size))))
        block = np.empty((uniq.size, table.dim), _np_dtype(table.dtype))
        shard, local = _shard_of(uniq, self.num_shards)
        with _stepstats.phase("sparse_pull"), \
                _telemetry.span("embedding.pull"):
            for s in range(self.num_shards):
                pos = np.nonzero(shard == s)[0]
                if pos.size == 0:
                    continue
                block[pos] = self._clients[s].pull_rows(name, local[pos])
                _telemetry.inc(PULL_RPCS_TOTAL, 1, help=_PULL_RPCS_HELP,
                               path="per_key")
        _telemetry.inc(ROWS_PULLED_TOTAL, uniq.size, help=_ROWS_HELP)
        return block, inv.astype(np.int64), int(uniq.size)

    # -- push plane ----------------------------------------------------------
    def stash_grad(self, name, uniq_ids, rows_nd, n_uniq):
        """Called by SparseEmbedding's forward: remember where backward
        will deposit this block's gradient."""
        self._pending_grads.append((name, uniq_ids, rows_nd, n_uniq))

    def push_grads(self, grads=None, per_key=False):
        """Push row-sparse grads to their owning shards. Default source is
        the stashed pending set (after loss.backward()). With the worker
        on, the push enqueues and returns immediately — asynchronously
        behind the dense allreduce — and the NEXT pull queues behind it.
        `per_key` forces the naive one-RPC-per-table blocking wire."""
        if grads is None:
            grads = [(name, ids[:n], _grad_of(rows_nd, n))
                     for name, ids, rows_nd, n in self._pending_grads]
            self._pending_grads.clear()
        if not grads:
            return
        self._check_worker()
        if self._prefetch_on and not per_key:
            self._jobs.put(("push", list(grads)))
            return
        self._rpc_push(grads, per_key=per_key)

    def _rpc_push(self, grads, per_key=False):
        """One push_rows_multi RPC per shard server (or per-key blocking
        RPCs for the baseline). Rows ride the dedup envelope and epoch
        fence; the server applies them through the lazy sparse path."""
        from . import telemetry as _telemetry

        path = "per_key" if per_key else "batched"
        S = self.num_shards
        per_server = [([], [], []) for _ in range(S)]
        for name, ids, rows in grads:
            ids = np.asarray(ids, np.int64)
            rows = np.asarray(rows)
            shard, local = _shard_of(ids, S)
            for s in range(S):
                pos = np.nonzero(shard == s)[0]
                if pos.size == 0:
                    continue
                per_server[s][0].append(name)
                per_server[s][1].append(local[pos])
                per_server[s][2].append(rows[pos])
        with _telemetry.span("embedding.push"):
            for s in range(S):
                names, ids_l, rows_l = per_server[s]
                if not names:
                    continue
                if per_key:
                    for name, ids, rows in zip(names, ids_l, rows_l):
                        self._clients[s].push_rows(name, ids, rows)
                        _telemetry.inc(PUSH_RPCS_TOTAL, 1,
                                       help=_PUSH_RPCS_HELP, path=path)
                else:
                    self._clients[s].push_rows_multi(names, ids_l, rows_l)
                    _telemetry.inc(PUSH_RPCS_TOTAL, 1,
                                   help=_PUSH_RPCS_HELP, path=path)

    # -- background worker ---------------------------------------------------
    def _worker_loop(self):
        while True:
            job = self._jobs.get()
            kind = job[0]
            if kind == "stop":
                return
            try:
                if kind == "push":
                    self._rpc_push(job[1])
                else:  # pull
                    _k, plan, fut = job
                    fut.started = True
                    fut.blocks = self._rpc_pull(plan)
                    fut.event.set()
            except Exception as e:  # surfaced on the next wait/flush
                if kind == "pull":
                    job[2].error = e
                    job[2].event.set()
                else:
                    with self._worker_error_lock:
                        self._worker_error = e

    def _check_worker(self):
        with self._worker_error_lock:
            err, self._worker_error = self._worker_error, None
        if err is not None:
            raise err

    def flush(self):
        """Drain the background queue (epoch boundary / before reading
        weights): every enqueued push and prefetch has reached the
        servers when this returns."""
        if not self._prefetch_on:
            return
        done = threading.Event()
        fut = _Pull()
        fut.event = done
        self._jobs.put(("pull", [], fut))  # empty plan = queue barrier
        done.wait()
        self._check_worker()

    # -- verification / chaos ------------------------------------------------
    def full_table(self, name):
        """Reassemble a table from its shards (tests/bench only)."""
        table = self._tables[name]
        S = self.num_shards
        out = np.empty((table.vocab, table.dim), _np_dtype(table.dtype))
        for s, c in enumerate(self._clients):
            out[s::S] = np.asarray(c.pull(name))
        return out

    def snapshot(self, directory):
        """Write every shard's rows to `directory`/shard-<s> through the
        manifest-verified bootstrap pull (PR-6 state-transfer contract) +
        the sharded_checkpoint writer — the recovery source a replacement
        shard server restores from."""
        import os

        from .contrib import sharded_checkpoint as _sc

        self.flush()
        paths = []
        for s, c in enumerate(self._clients):
            state = c.bootstrap()  # manifest-verified {key: rows}
            path = os.path.join(directory, f"shard-{s}")
            _sc.save(path, state)
            if not _sc.verify(path):
                raise RuntimeError(
                    f"embedding snapshot shard {s} failed manifest "
                    "verification")
            paths.append(path)
        return paths

    def restore_shard(self, shard, directory, client):
        """Seed a REPLACEMENT server for `shard` from a snapshot: verify
        the manifest, init every key's local rows from the restored
        bytes, re-ship the optimizer, and swap the client into the
        fleet."""
        import os

        from .contrib import sharded_checkpoint as _sc

        path = os.path.join(directory, f"shard-{shard}")
        if not _sc.verify(path):
            raise RuntimeError(
                f"embedding snapshot shard {shard} failed manifest "
                "verification at restore")
        state = _sc.restore(path)
        for key, arr in state.items():
            client.init(key, np.asarray(arr))
        if self._optimizer is not None:
            client.set_optimizer(self._optimizer)
        old, self._clients[shard] = self._clients[shard], client
        try:
            old.close()
        except Exception:
            pass
        return state

    def close(self):
        if self._prefetch_on and self._worker is not None:
            self._jobs.put(("stop",))
            self._worker.join(timeout=10)
            self._worker = None
        for c in self._clients:
            try:
                c.close()
            except Exception:
                pass


def _grad_of(rows_nd, n_uniq):
    """The [:n_uniq] slice of a pulled block's deposited gradient (bucket
    padding rows never reach the wire or the optimizer)."""
    g = getattr(rows_nd, "_grad", None)
    if g is None:
        raise RuntimeError(
            "SparseEmbedding forward ran under record() but no gradient "
            "was deposited — did loss.backward() run?")
    return np.asarray(g.asnumpy())[:n_uniq]


def _np_dtype(name):
    from .ps import _dtype_by_name

    return _dtype_by_name(name)


def launch_local_fleet(num_shards, host="127.0.0.1"):
    """In-process shard fleet for tests/bench: returns (servers, service).
    Each shard is a real ParameterServer on a real socket (num_workers=1
    — embedding pushes are async applies, never sync rendezvous)."""
    from .ps import ParameterServer, PSClient

    servers = [ParameterServer(num_workers=1, host=host, port=0)
               for _ in range(int(num_shards))]
    clients = [PSClient(host, s.port) for s in servers]
    return servers, ShardedEmbeddingService(clients=clients)
