"""Eager NDArray API (ref: python/mxnet/ndarray/).

Creation ops, generated operator functions, serialization, and the
random/linalg/sparse/contrib sub-namespaces.
"""
from __future__ import annotations

import struct

import numpy as np

import jax
import jax.numpy as jnp

from ..base import dtype_np
from ..context import Context, current_context
from .ndarray import NDArray, array, concatenate, from_jax, waitall
from . import register as _register

# generated op functions (nd.relu, nd.FullyConnected, nd.dot, ...)
_register.install_ops(globals())

from . import random  # noqa: E402,F401
from . import image  # noqa: E402,F401
from . import linalg  # noqa: E402,F401
from . import sparse  # noqa: E402,F401
from . import contrib  # noqa: E402,F401


def _place(data, ctx):
    if ctx is None:
        return NDArray._from_data(data)
    return NDArray(data, ctx=ctx)


def _default_dtype():
    from .. import config as _config

    return _config.get("MXTPU_DEFAULT_DTYPE")


def zeros(shape, ctx=None, dtype=None, **kwargs):
    dtype = dtype or _default_dtype()
    return _place(jnp.zeros(shape, dtype_np(dtype)), ctx)


def ones(shape, ctx=None, dtype=None, **kwargs):
    dtype = dtype or _default_dtype()
    return _place(jnp.ones(shape, dtype_np(dtype)), ctx)


def full(shape, val, ctx=None, dtype=None, **kwargs):
    dtype = dtype or _default_dtype()
    return _place(jnp.full(shape, val, dtype_np(dtype)), ctx)


def empty(shape, ctx=None, dtype=None):
    dtype = dtype or _default_dtype()
    return zeros(shape, ctx=ctx, dtype=dtype)


def arange(start, stop=None, step=1.0, repeat=1, ctx=None, dtype=None):
    dtype = dtype or _default_dtype()
    out = jnp.arange(start, stop, step, dtype=dtype_np(dtype))
    if repeat > 1:
        out = jnp.repeat(out, repeat)
    return _place(out, ctx)


def eye(N, M=0, k=0, ctx=None, dtype="float32"):
    return _place(jnp.eye(N, M if M else N, k=k, dtype=dtype_np(dtype)), ctx)


def moveaxis(data, source, destination):
    return data._apply(lambda d: jnp.moveaxis(d, source, destination))


def stack_arrays(*arrays, axis=0):
    from .. import autograd

    return autograd.invoke_recorded(lambda *xs: jnp.stack(xs, axis=axis), list(arrays))[0]


# ---------------------------------------------------------------------------
# DLPack interop (ref: python/mxnet/ndarray/ndarray.py
# to_dlpack_for_read / to_dlpack_for_write / from_dlpack; the DLTensor
# role of include/mxnet/tensor_blob.h:111)


class _CapsuleHolder:
    """Adapter for legacy 'dltensor' PyCapsules (the reference
    from_dlpack's primary input): jax consumes only protocol objects, so
    wrap the capsule in one. A bare capsule carries no introspectable
    device, so this reports kDLCPU — true for every capsule this module
    itself produces (to_dlpack_for_read exports host buffers) and for
    CPU-framework producers. A capsule wrapping ACCELERATOR memory from
    a third party cannot be imported this way; hand over the producer's
    tensor object instead (the protocol carries the true device)."""

    def __init__(self, capsule):
        self._capsule = capsule

    def __dlpack__(self, **kwargs):
        return self._capsule

    def __dlpack_device__(self):
        return (1, 0)  # (kDLCPU, 0)


def from_dlpack(obj):
    """Wrap any DLPack-exporting object (torch tensor, numpy array,
    another framework's tensor) as an NDArray, zero-copy where the
    producer's device/layout allows. Legacy 'dltensor' capsules are
    accepted too and are assumed host-resident (see _CapsuleHolder)."""
    if type(obj).__name__ == "PyCapsule":
        obj = _CapsuleHolder(obj)
    return NDArray._from_data(jnp.from_dlpack(obj))


def to_dlpack_for_read(arr):
    """Export `arr` as a legacy DLPack capsule for read-only use
    (e.g. `torch.utils.dlpack.from_dlpack`). XLA buffers are immutable,
    so reads always see a consistent value.

    CPU-resident arrays export zero-copy. Accelerator-resident arrays
    are copied to host first and export the HOST buffer — no external
    framework can address a TPU buffer through a raw capsule, and this
    keeps every capsule this module produces host-resident (the
    assumption _CapsuleHolder relies on for re-import). For same-device
    exchange, pass the NDArray itself: the `__dlpack__` protocol carries
    the true device."""
    arr.wait_to_read()
    d = arr._data
    if any(dev.platform != "cpu" for dev in d.devices()):
        return np.asarray(jax.device_get(d)).__dlpack__()
    return d.__dlpack__()


def to_dlpack_for_write(arr):
    """The reference's for-write variant aliases the buffer for in-place
    mutation by the consumer. XLA device buffers are immutable — aliased
    writes cannot be supported. Consumers should write into their own
    tensor and wrap it back with `from_dlpack` (zero-copy on CPU)."""
    raise NotImplementedError(
        "to_dlpack_for_write: XLA buffers are immutable; write into a "
        "consumer-owned tensor and re-import it with nd.from_dlpack "
        "instead (zero-copy on CPU)")


# ---------------------------------------------------------------------------
# serialization (ref: src/ndarray/ndarray.cc Save/Load,
# python/mxnet/ndarray/utils.py:149 save / :222 load). Our container format:
# magic + count + per-entry (name, dtype, shape, raw little-endian bytes).

_MAGIC = b"MXTPU001"


def save(fname, data):
    """Save NDArray / list / dict of NDArrays to a binary container file."""
    if isinstance(data, NDArray):
        entries = [("", data)]
    elif isinstance(data, (list, tuple)):
        entries = [("", d) for d in data]
    elif isinstance(data, dict):
        entries = sorted(data.items())
    else:
        raise TypeError("save expects NDArray, list, or dict")
    with open(fname, "wb") as f:
        f.write(_MAGIC)
        f.write(struct.pack("<q", len(entries)))
        for name, arr in entries:
            a = arr.asnumpy() if isinstance(arr, NDArray) else np.asarray(arr)
            nb = name.encode("utf-8")
            dt = a.dtype.name.encode("utf-8")
            f.write(struct.pack("<i", len(nb))); f.write(nb)
            f.write(struct.pack("<i", len(dt))); f.write(dt)
            f.write(struct.pack("<i", a.ndim))
            f.write(struct.pack(f"<{a.ndim}q", *a.shape))
            raw = np.ascontiguousarray(a).tobytes()
            f.write(struct.pack("<q", len(raw))); f.write(raw)


def load(fname):
    """Load a container saved by `save` -> list or dict of NDArrays.
    Reference-format .params files (kMXAPINDArrayListMagic) are detected
    and read transparently, so checkpoints trained with the reference load
    with the same call (ref: python/mxnet/ndarray/utils.py:222)."""
    from .legacy_io import is_mxnet_params, load_mxnet_params

    if is_mxnet_params(fname):
        return load_mxnet_params(fname)
    with open(fname, "rb") as f:
        magic = f.read(8)
        if magic != _MAGIC:
            raise ValueError(f"{fname}: not a valid NDArray container")
        (count,) = struct.unpack("<q", f.read(8))
        named, anon = {}, []
        for _ in range(count):
            (ln,) = struct.unpack("<i", f.read(4)); name = f.read(ln).decode()
            (ld,) = struct.unpack("<i", f.read(4)); dt = f.read(ld).decode()
            (nd_,) = struct.unpack("<i", f.read(4))
            shape = struct.unpack(f"<{nd_}q", f.read(8 * nd_)) if nd_ else ()
            (nb,) = struct.unpack("<q", f.read(8))
            a = np.frombuffer(f.read(nb), dtype=dtype_np(dt)).reshape(shape)
            arr = NDArray(jnp.asarray(a))
            if name:
                named[name] = arr
            else:
                anon.append(arr)
    return named if named else anon


def imdecode(buf, flag=1, to_rgb=True, **kwargs):  # pragma: no cover - thin wrapper
    from ..image import imdecode as _imdecode

    return _imdecode(buf, flag=flag, to_rgb=to_rgb)


# name-parity re-exports from the sparse module (ref: nd.cast_storage /
# nd.sparse.retain — sparse-typed ops live outside the dense-array registry)
from .sparse import cast_storage  # noqa: E402,F401


def Custom(*inputs, op_type=None, **kwargs):
    """nd.Custom(data, ..., op_type='my_op') — always present, like the
    reference's Custom op; dispatches to registered CustomOpProps
    (ref: src/operator/custom/custom.cc)."""
    from ..operator import Custom as _dispatch

    return _dispatch(*inputs, op_type=op_type, **kwargs)
