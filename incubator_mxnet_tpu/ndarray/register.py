"""Generated eager op functions.

TPU-native analog of the reference's import-time frontend codegen
(ref: python/mxnet/ndarray/register.py:157 — builds nd.* functions from the
op registry via MXSymbolGetAtomicSymbolInfo). Here the registry is the
in-process `ops.OP_REGISTRY`; each generated function routes through the
autograd dispatcher (`autograd.invoke_recorded`), mirroring
`_imperative_invoke` -> `MXImperativeInvokeEx`
(ref: python/mxnet/_ctypes/ndarray.py:65).
"""
from __future__ import annotations

import os
import sys
from collections import OrderedDict

import jax
import jax.numpy as jnp
from jax import lax

from .. import autograd
from .. import profiler as _profiler
from .. import random as _global_random
from ..ops.registry import OP_REGISTRY, OpDef
from .ndarray import NDArray

__all__ = ["invoke_by_name", "install_ops"]


def _as_data_or_none(x):
    if x is None:
        return None
    if isinstance(x, NDArray):
        return x
    return NDArray(jnp.asarray(x))


# LRU: shape-diverse eager workloads would otherwise grow this without
# bound (every distinct (op, attrs) keeps its jitted callable plus XLA's
# per-shape executables alive). Cap via MXTPU_EAGER_JIT_CACHE_SIZE.
_EAGER_JIT_CACHE: OrderedDict = OrderedDict()
_EAGER_JIT_CACHE_DEFAULT_CAP = 512


def _eager_jit_cache_cap():
    """Env read at insert time (misses only — the hit path stays a dict
    lookup), same runtime-retunable contract as MXTPU_EAGER_JIT; the knob
    is documented in config.py. 0 = unbounded."""
    raw = os.environ.get("MXTPU_EAGER_JIT_CACHE_SIZE")  # mxlint: disable=MXL007
    if raw is None:
        return _EAGER_JIT_CACHE_DEFAULT_CAP
    try:
        return int(raw)
    except ValueError:
        return _EAGER_JIT_CACHE_DEFAULT_CAP


def _freeze(v):
    if isinstance(v, (list, tuple)):
        return tuple(_freeze(x) for x in v)
    if isinstance(v, dict):
        # no sort: call_attrs insertion order is opdef.attrs order (update
        # of existing keys preserves position), identical across calls
        return tuple((k, _freeze(x)) for k, x in v.items())
    # tag leaves with their type: hash(2) == hash(2.0) == hash(True), and a
    # closure traced with int 2 must not serve a call made with float 2.0
    return (type(v).__name__, v)


def _eager_jit_enabled():
    """Per-call read of MXTPU_EAGER_JIT (tests toggle it at runtime), kept
    off the config registry's knob machinery — this is the hottest line of
    eager dispatch. The knob stays documented in config.py."""
    raw = os.environ.get("MXTPU_EAGER_JIT")  # mxlint: disable=MXL007
    if raw is None:
        return False
    return raw.lower() not in ("0", "false", "off", "")


def _donation_argnums(opdef, live_idx):
    """Positions (into the jitted fn's live-array arglist) of inputs the op
    declares it consumes (`OpDef.donate`) — optimizer weight/state updates.

    Donating lets XLA write the update in place instead of allocating a
    second copy of every parameter buffer, which is where the eager
    optimizer path's peak-memory headroom comes from. CPU donation is a
    no-op in XLA (it warns and copies anyway), so the gate keeps the
    default test backend quiet; the positions themselves are backend-free
    and unit-testable."""
    if not opdef.donate:
        return ()
    consumed = {opdef.inputs.index(n) for n in opdef.donate
                if n in opdef.inputs}
    return tuple(k for k, i in enumerate(live_idx) if i in consumed)


def _maybe_jit(opdef, fn, call_attrs, live_idx, n_slots):
    """Per-(op, attrs) jit cache for eager dispatch (MXTPU_EAGER_JIT).

    Off by default: XLA compiles per input-shape signature, which hurts
    shape-diverse eager workloads; on TPU, steady-shape eager loops gain
    the fused-kernel dispatch the reference gets from its engine bulking
    (ref: MXNET_EXEC_BULK_EXEC_* — same latency-for-compilation trade)."""
    if opdef.needs_rng or not _eager_jit_enabled():
        return fn
    key = (opdef.name, _freeze(call_attrs), tuple(live_idx), n_slots)
    try:
        hash(key)
    except TypeError:
        return fn
    cached = _EAGER_JIT_CACHE.get(key)
    if cached is None:
        # the jitted callable closes over THIS call's attrs; the cache key
        # guarantees any hit was built from equal attrs
        donate = (_donation_argnums(opdef, live_idx)
                  if jax.default_backend() != "cpu" else ())
        from .. import compile_cache as _compile_cache

        # persistent-cache wrapper (no-op when MXTPU_COMPILE_CACHE_DIR
        # is unset): a restarted eager workload reloads each op's
        # executable instead of re-tracing it. Calls traced through
        # autograd's vjp see Tracers and bypass straight to the jit.
        cached = _compile_cache.wrap(
            f"eager.{opdef.name}", jax.jit(fn, donate_argnums=donate),
            donated=donate, static_key=key[1:])
        _EAGER_JIT_CACHE[key] = cached
        cap = _eager_jit_cache_cap()
        if cap > 0:
            while len(_EAGER_JIT_CACHE) > cap:
                _EAGER_JIT_CACHE.popitem(last=False)
        from .. import telemetry as _telemetry

        _telemetry.set_gauge(
            "mxtpu_eager_jit_cache_size", len(_EAGER_JIT_CACHE),
            help="Entries in the eager-dispatch jit cache "
                 "(LRU, capped by MXTPU_EAGER_JIT_CACHE_SIZE).")
        if not _compile_cache.enabled():
            # compile registry: a second attrs/arity key for the same op
            # is a retrace of that op's eager program. With the
            # persistent cache on, the wrapper registers (hit or
            # compile) itself on first dispatch.
            _telemetry.compilereg.register(f"eager.{opdef.name}", key[1:])
    else:
        _EAGER_JIT_CACHE.move_to_end(key)
    return cached


def invoke(opdef: OpDef, args, kwargs):
    """Generic eager invocation of a registered op."""
    kwargs = dict(kwargs)
    out = kwargs.pop("out", None)
    kwargs.pop("name", None)
    kwargs.pop("ctx", None) if "ctx" not in opdef.attrs else None

    if opdef.variadic:
        slots = [_as_data_or_none(a) for a in args]
        attrs = {k: v for k, v in kwargs.items() if v is not None or k in opdef.attrs}
    else:
        slots = [None] * len(opdef.inputs)
        attrs = {}
        positional_attrs = set()
        attr_names = list(opdef.attrs)
        for i, a in enumerate(args):
            if i < len(slots):
                slots[i] = _as_data_or_none(a)
            else:
                # positional overflow maps onto attrs in signature order,
                # like the reference's generated signatures (e.g.
                # nd.one_hot(indices, depth))
                j = i - len(slots)
                if j >= len(attr_names):
                    raise TypeError(
                        f"op {opdef.name}: too many positional arguments")
                attrs[attr_names[j]] = a
                positional_attrs.add(attr_names[j])
        for k, v in kwargs.items():
            if k in opdef.inputs:
                slots[opdef.inputs.index(k)] = _as_data_or_none(v)
            else:
                if k in positional_attrs:
                    raise TypeError(
                        f"op {opdef.name}: got multiple values for "
                        f"argument {k!r}")
                attrs[k] = v

    # resolve static attrs with defaults
    call_attrs = dict(opdef.attrs)
    call_attrs.update({k: v for k, v in attrs.items() if k in opdef.attrs})
    # tolerate unknown attrs silently only if the fn takes them; else error
    unknown = {k for k in attrs if k not in opdef.attrs}
    if unknown:
        raise TypeError(f"op {opdef.name}: unknown arguments {sorted(unknown)}")

    if (opdef.name == "Activation" and call_attrs.get("act_type") == "relu"
            and out is None and slots and slots[0] is not None
            and getattr(slots[0], "_epi_prov", None) is not None):
        # MXTPU_FUSED_EPILOGUE: the input carries BatchNorm provenance
        # (recorded below, traced dispatches only) — re-emit the
        # BN→ReLU(→add) chain as one Pallas epilogue pass; the unfused
        # chain already dispatched becomes dead code under XLA DCE
        from ..ops import epilogue as _epilogue

        fused_val = _epilogue.maybe_rewrite_relu(slots[0])
        if fused_val is not None:
            return NDArray._from_data(fused_val)

    training = autograd.is_training()
    if opdef.needs_rng:
        call_attrs["_rng"] = _global_random.next_key()
    if opdef.needs_training:
        call_attrs["_training"] = training

    has_aux = bool(opdef.aux) and training
    n_primary = opdef.num_outputs(call_attrs) if callable(opdef.num_outputs) else opdef.num_outputs

    live_idx = [i for i, v in enumerate(slots) if v is not None]
    live_arrays = [slots[i] for i in live_idx]
    aux_pos = [opdef.inputs.index(a) for a in opdef.aux] if (opdef.aux and not opdef.variadic) else []
    n_slots = len(slots)

    # fn must not close over `slots` (its NDArrays would be pinned for the
    # process lifetime by the eager-jit cache) — only plain ints/attrs
    def fn(*live_datas):
        full = [None] * n_slots
        for i, d in zip(live_idx, live_datas):
            full[i] = d
        for ap in aux_pos:
            if full[ap] is not None:
                full[ap] = lax.stop_gradient(full[ap])
        return opdef.fn(*full, **call_attrs)

    fn = _maybe_jit(opdef, fn, call_attrs, live_idx, n_slots)
    if _profiler.aggregate_enabled():
        results = _profiler.timed_invoke(
            opdef.name, autograd.invoke_recorded, fn, live_arrays,
            name=opdef.name)
    else:
        results = autograd.invoke_recorded(fn, live_arrays, name=opdef.name)

    if has_aux:
        primary = results[:n_primary]
        aux_new = results[n_primary:]
        for ap, new in zip(aux_pos, aux_new):
            holder = slots[ap]
            if holder is not None:
                holder._data = new._data
        results = primary

    if opdef.name == "BatchNorm" and out is None:
        from ..ops import epilogue as _epilogue

        if _epilogue.enabled():
            _epilogue.note_batch_norm(results[0], slots, call_attrs)

    if out is not None:
        if len(results) != 1:
            raise ValueError("out= supported only for single-output ops")
        out._data = results[0]._data
        return out
    return results if len(results) > 1 else results[0]


def invoke_by_name(name, args, kwargs):
    return invoke(OP_REGISTRY[name], args, kwargs)


def _make_fn(opdef: OpDef, public_name: str):
    def generated(*args, **kwargs):
        return invoke(opdef, args, kwargs)

    generated.__name__ = public_name
    generated.__qualname__ = public_name
    generated.__doc__ = opdef.fn.__doc__ or f"Eager op `{opdef.name}`."
    return generated


def install_ops(module_dict):
    """Install one function per registry entry into a module namespace."""
    for name, opdef in OP_REGISTRY.items():
        if name not in module_dict:
            module_dict[name] = _make_fn(opdef, name)
