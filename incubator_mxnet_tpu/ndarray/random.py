"""nd.random namespace (ref: python/mxnet/ndarray/random.py)."""
from __future__ import annotations

from ..ops.registry import OP_REGISTRY
from . import register as _register


def _call(name, kwargs):
    return _register.invoke(OP_REGISTRY[name], (), kwargs)


def uniform(low=0.0, high=1.0, shape=(1,), dtype="float32", ctx=None, out=None, **kwargs):
    return _call("_random_uniform", dict(low=low, high=high, shape=_t(shape), dtype=dtype, out=out))


def normal(loc=0.0, scale=1.0, shape=(1,), dtype="float32", ctx=None, out=None, **kwargs):
    return _call("_random_normal", dict(loc=loc, scale=scale, shape=_t(shape), dtype=dtype, out=out))


def randn(*shape, loc=0.0, scale=1.0, dtype="float32", ctx=None, **kwargs):
    return normal(loc=loc, scale=scale, shape=shape or (1,), dtype=dtype)


def gamma(alpha=1.0, beta=1.0, shape=(1,), dtype="float32", ctx=None, out=None, **kwargs):
    return _call("_random_gamma", dict(alpha=alpha, beta=beta, shape=_t(shape), dtype=dtype, out=out))


def exponential(scale=1.0, shape=(1,), dtype="float32", ctx=None, out=None, **kwargs):
    return _call("_random_exponential", dict(lam=1.0 / scale, shape=_t(shape), dtype=dtype, out=out))


def poisson(lam=1.0, shape=(1,), dtype="float32", ctx=None, out=None, **kwargs):
    return _call("_random_poisson", dict(lam=lam, shape=_t(shape), dtype=dtype, out=out))


def negative_binomial(k=1, p=1.0, shape=(1,), dtype="float32", ctx=None, out=None, **kwargs):
    return _call("_random_negative_binomial", dict(k=k, p=p, shape=_t(shape), dtype=dtype, out=out))


def generalized_negative_binomial(mu=1.0, alpha=1.0, shape=(1,), dtype="float32", ctx=None, out=None, **kwargs):
    return _call(
        "_random_generalized_negative_binomial",
        dict(mu=mu, alpha=alpha, shape=_t(shape), dtype=dtype, out=out),
    )


def randint(low, high, shape=(1,), dtype="int32", ctx=None, out=None, **kwargs):
    return _call("_random_randint", dict(low=low, high=high, shape=_t(shape), dtype=dtype, out=out))


def multinomial(data, shape=(), get_prob=False, dtype="int32", **kwargs):
    return _register.invoke(
        OP_REGISTRY["_sample_multinomial"],
        (data,),
        dict(shape=_t(shape), get_prob=get_prob, dtype=dtype),
    )


def shuffle(data, **kwargs):
    return _register.invoke(OP_REGISTRY["_shuffle"], (data,), {})


def bernoulli(p=0.5, shape=(1,), dtype="float32", ctx=None, out=None, **kwargs):
    return _call("_random_bernoulli", dict(p=p, shape=_t(shape), dtype=dtype, out=out))


def _t(shape):
    return (shape,) if isinstance(shape, int) else tuple(shape)


# seeding lives on the package-level random module
from ..random import seed  # noqa: E402,F401
