"""NDArray: the user-facing tensor.

TPU-native equivalent of the reference NDArray (ref: include/mxnet/ndarray.h:82,
src/ndarray/ndarray.cc, python/mxnet/ndarray/ndarray.py). Design mapping:
- the reference's Chunk (storage handle + engine var) -> a `jax.Array`, whose
  buffer and async token ARE the storage handle and dependency var: XLA's
  runtime orders reads/writes, so WaitToRead == block_until_ready.
- engine-scheduled CopyFromTo -> `jax.device_put` (async D2D/H2D).
- autograd entry_ -> (_node, _node_index) pointing into the vjp tape.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ..base import dtype_np
from ..context import Context, current_context
from .. import autograd

__all__ = ["NDArray", "array", "waitall", "from_jax", "concatenate"]


def _ctx_of_jax(data) -> Context:
    try:
        dev = next(iter(data.devices()))
    except Exception:
        return current_context()
    if dev.platform == "cpu":
        return Context("cpu", dev.id)
    return Context("tpu", dev.id)


class NDArray:
    """Dense tensor handle over a jax.Array."""

    __slots__ = ("_data", "_grad", "_grad_req", "_node", "_node_index",
                 "_dense_grad_buf", "_grad_gen", "_epi_prov", "__weakref__")

    # make NDArray win against numpy in mixed dunder dispatch
    __array_priority__ = 1000.0

    def __init__(self, data, ctx=None):
        if isinstance(data, NDArray):
            data = data._data
        if not isinstance(data, jax.Array):
            data = jnp.asarray(data)
        if ctx is not None:
            data = jax.device_put(data, Context(ctx).jax_device())
        self._data = data
        self._grad = None
        self._grad_req = "write"
        self._node = None
        self._node_index = 0

    @classmethod
    def _from_data(cls, data):
        out = cls.__new__(cls)
        out._data = data
        out._grad = None
        out._grad_req = "write"
        out._node = None
        out._node_index = 0
        return out

    # -- basic properties --------------------------------------------------
    @property
    def shape(self):
        return tuple(self._data.shape)

    @property
    def dtype(self):
        return np.dtype(self._data.dtype)

    @property
    def size(self):
        return int(self._data.size)

    @property
    def ndim(self):
        return self._data.ndim

    @property
    def context(self) -> Context:
        return _ctx_of_jax(self._data)

    ctx = context

    @property
    def stype(self):
        return "default"

    @property
    def T(self):
        return self.transpose()

    @property
    def grad(self):
        return self._grad

    # -- materialization / sync -------------------------------------------
    def asnumpy(self):
        """Blocking copy to host (ref: NDArray::SyncCopyToCPU)."""
        return np.asarray(jax.device_get(self._data))

    def asscalar(self):
        if self.size != 1:
            raise ValueError("The current array is not a scalar")
        return self.asnumpy().reshape(()).item()

    def item(self):
        return self.asscalar()

    def wait_to_read(self):
        """ref: NDArray::WaitToRead — resolves when pending writes complete."""
        self._data.block_until_ready()

    def wait_to_write(self):
        self._data.block_until_ready()

    # -- DLPack interop (ref: ndarray.py to_dlpack_for_read/from_dlpack;
    # include/mxnet/tensor_blob.h:111 DLTensor) -----------------------------
    def __dlpack__(self, **kwargs):
        """Standard DLPack protocol: `torch.from_dlpack(nd_array)` and
        `np.from_dlpack(nd_array)` view the buffer zero-copy."""
        return self._data.__dlpack__(**kwargs)

    def __dlpack_device__(self):
        return self._data.__dlpack_device__()

    # -- autograd ----------------------------------------------------------
    def attach_grad(self, grad_req="write", stype=None):
        """Allocate a gradient buffer (ref: autograd.mark_variables). Detaches."""
        self._node = None
        self._grad = NDArray._from_data(jnp.zeros(self.shape, self._data.dtype))
        self._grad_req = grad_req

    def detach(self):
        out = NDArray._from_data(self._data)
        return out

    def backward(self, out_grad=None, retain_graph=False, train_mode=True):
        autograd.backward([self], [out_grad], retain_graph=retain_graph, train_mode=train_mode)

    # -- conversion / movement --------------------------------------------
    def astype(self, dtype, copy=True):
        return self._apply(lambda d: d.astype(dtype_np(dtype)))

    def as_in_context(self, ctx):
        if ctx == self.context:
            return self
        return NDArray._from_data(jax.device_put(self._data, Context(ctx).jax_device()))

    as_in_ctx = as_in_context

    def copyto(self, other):
        """ref: NDArray::CopyFromTo — async copy to a context or array."""
        if isinstance(other, Context):
            return NDArray._from_data(jax.device_put(self._data, other.jax_device()))
        if isinstance(other, NDArray):
            other._data = jax.device_put(self._data, next(iter(other._data.devices())))
            return other
        raise TypeError(f"copyto does not support type {type(other)}")

    def copy(self):
        return NDArray._from_data(jnp.array(self._data))

    def tostype(self, stype):
        if stype == "default":
            return self
        from . import sparse

        return sparse.cast_storage(self, stype)

    def as_nd_ndarray(self):
        return self

    def asnumpy_or_none(self):
        return self.asnumpy()

    # -- helpers -----------------------------------------------------------
    def _apply(self, fn, *others):
        """Route a jnp-level fn through the autograd dispatcher."""
        return autograd.invoke_recorded(fn, [self, *others])[0]

    # -- shape ops (methods mirror reference NDArray methods) -------------
    def reshape(self, *shape, **kwargs):
        if len(shape) == 1 and isinstance(shape[0], (tuple, list)):
            shape = tuple(shape[0])
        _r = _reg()
        return _r.invoke_by_name("Reshape", [self], {"shape": shape, **kwargs})

    def reshape_like(self, other):
        return self._apply(lambda a, b: jnp.reshape(a, b.shape), other)

    def transpose(self, axes=None):
        return self._apply(lambda d: jnp.transpose(d, axes=axes))

    def swapaxes(self, dim1, dim2):
        return self._apply(lambda d: jnp.swapaxes(d, dim1, dim2))

    def flatten(self):
        return self._apply(lambda d: jnp.reshape(d, (d.shape[0], -1)))

    def expand_dims(self, axis):
        return self._apply(lambda d: jnp.expand_dims(d, axis))

    def squeeze(self, axis=None):
        return self._apply(lambda d: jnp.squeeze(d, axis=axis))

    def broadcast_to(self, shape):
        return self._apply(lambda d: jnp.broadcast_to(d, shape))

    def broadcast_like(self, other):
        return self._apply(lambda a, b: jnp.broadcast_to(a, b.shape), other)

    def split(self, num_outputs, axis=1, squeeze_axis=False):
        _r = _reg()
        return _r.invoke_by_name(
            "split", [self],
            {"num_outputs": num_outputs, "axis": axis, "squeeze_axis": squeeze_axis},
        )

    def slice(self, begin, end, step=None):
        _r = _reg()
        return _r.invoke_by_name("slice", [self], {"begin": begin, "end": end, "step": step or ()})

    def slice_axis(self, axis, begin, end):
        _r = _reg()
        return _r.invoke_by_name("slice_axis", [self], {"axis": axis, "begin": begin, "end": end})

    def take(self, indices, axis=0, mode="clip"):
        _r = _reg()
        return _r.invoke_by_name("take", [self, indices], {"axis": axis, "mode": mode})

    def one_hot(self, depth, **kwargs):
        _r = _reg()
        return _r.invoke_by_name("one_hot", [self], {"depth": depth, **kwargs})

    def pad(self, mode, pad_width, constant_value=0.0):
        _r = _reg()
        return _r.invoke_by_name(
            "pad", [self],
            {"mode": mode, "pad_width": pad_width, "constant_value": constant_value},
        )

    def tile(self, reps):
        return self._apply(lambda d: jnp.tile(d, reps))

    def repeat(self, repeats, axis=None):
        return self._apply(lambda d: jnp.repeat(d, repeats, axis=axis))

    def flip(self, axis):
        return self._apply(lambda d: jnp.flip(d, axis=axis))

    def diag(self, k=0):
        _r = _reg()
        return _r.invoke_by_name("diag", [self], {"k": k})

    # -- reductions --------------------------------------------------------
    def _reduce(self, name, axis=None, keepdims=False, **kw):
        _r = _reg()
        return _r.invoke_by_name(name, [self], {"axis": axis, "keepdims": keepdims, **kw})

    def sum(self, axis=None, keepdims=False, **kw):
        return self._reduce("sum", axis, keepdims)

    def mean(self, axis=None, keepdims=False, **kw):
        return self._reduce("mean", axis, keepdims)

    def prod(self, axis=None, keepdims=False, **kw):
        return self._reduce("prod", axis, keepdims)

    def max(self, axis=None, keepdims=False, **kw):
        return self._reduce("max", axis, keepdims)

    def min(self, axis=None, keepdims=False, **kw):
        return self._reduce("min", axis, keepdims)

    def norm(self, ord=2, axis=None, keepdims=False):
        _r = _reg()
        return _r.invoke_by_name("norm", [self], {"ord": ord, "axis": axis, "keepdims": keepdims})

    def argmax(self, axis=None, keepdims=False):
        _r = _reg()
        return _r.invoke_by_name("argmax", [self], {"axis": axis, "keepdims": keepdims})

    def argmin(self, axis=None, keepdims=False):
        _r = _reg()
        return _r.invoke_by_name("argmin", [self], {"axis": axis, "keepdims": keepdims})

    def argsort(self, axis=-1, is_ascend=True):
        _r = _reg()
        return _r.invoke_by_name("argsort", [self], {"axis": axis, "is_ascend": is_ascend})

    def sort(self, axis=-1, is_ascend=True):
        _r = _reg()
        return _r.invoke_by_name("sort", [self], {"axis": axis, "is_ascend": is_ascend})

    def topk(self, axis=-1, k=1, ret_typ="indices", is_ascend=False):
        _r = _reg()
        return _r.invoke_by_name(
            "topk", [self], {"axis": axis, "k": k, "ret_typ": ret_typ, "is_ascend": is_ascend}
        )

    def clip(self, a_min, a_max):
        return self._apply(lambda d: jnp.clip(d, a_min, a_max))

    def abs(self):
        return self._apply(jnp.abs)

    def sign(self):
        return self._apply(jnp.sign)

    def sqrt(self):
        return self._apply(jnp.sqrt)

    def square(self):
        return self._apply(jnp.square)

    def exp(self):
        return self._apply(jnp.exp)

    def log(self):
        return self._apply(jnp.log)

    def tanh(self):
        return self._apply(jnp.tanh)

    def sigmoid(self):
        return self._apply(jax.nn.sigmoid)

    def relu(self):
        return self._apply(jax.nn.relu)

    def softmax(self, axis=-1):
        return self._apply(lambda d: jax.nn.softmax(d, axis=axis))

    def log_softmax(self, axis=-1):
        return self._apply(lambda d: jax.nn.log_softmax(d, axis=axis))

    def round(self):
        return self._apply(jnp.round)

    def floor(self):
        return self._apply(jnp.floor)

    def ceil(self):
        return self._apply(jnp.ceil)

    def dot(self, other, transpose_a=False, transpose_b=False):
        _r = _reg()
        return _r.invoke_by_name(
            "dot", [self, other], {"transpose_a": transpose_a, "transpose_b": transpose_b}
        )

    # -- dunder arithmetic -------------------------------------------------
    def _binop(self, other, fn, scalar_fn=None):
        if isinstance(other, NDArray):
            return autograd.invoke_recorded(fn, [self, other])[0]
        return autograd.invoke_recorded(lambda a: scalar_fn(a, other) if scalar_fn else fn(a, other), [self])[0]

    def __add__(self, other):
        out = self._binop(other, jnp.add, lambda a, s: a + s)
        if isinstance(other, NDArray) and (
                getattr(self, "_epi_prov", None) is not None
                or getattr(other, "_epi_prov", None) is not None):
            # a BN output flowing into an add is a candidate residual
            # join for the fused-epilogue rewrite (ops/epilogue.py)
            from ..ops import epilogue as _epilogue

            _epilogue.note_add(out, self, other)
        return out

    __radd__ = __add__

    def __sub__(self, other):
        return self._binop(other, jnp.subtract, lambda a, s: a - s)

    def __rsub__(self, other):
        return autograd.invoke_recorded(lambda a: other - a, [self])[0]

    def __mul__(self, other):
        return self._binop(other, jnp.multiply, lambda a, s: a * s)

    __rmul__ = __mul__

    def __truediv__(self, other):
        return self._binop(other, jnp.divide, lambda a, s: a / s)

    def __rtruediv__(self, other):
        return autograd.invoke_recorded(lambda a: other / a, [self])[0]

    def __mod__(self, other):
        return self._binop(other, jnp.mod, lambda a, s: jnp.mod(a, s))

    def __rmod__(self, other):
        return autograd.invoke_recorded(lambda a: jnp.mod(other, a), [self])[0]

    def __pow__(self, other):
        return self._binop(other, jnp.power, lambda a, s: jnp.power(a, s))

    def __rpow__(self, other):
        return autograd.invoke_recorded(lambda a: jnp.power(other, a), [self])[0]

    def __matmul__(self, other):
        return self._binop(other, jnp.matmul)

    def __neg__(self):
        return self._apply(jnp.negative)

    def __abs__(self):
        return self._apply(jnp.abs)

    def __iadd__(self, other):
        o = other._data if isinstance(other, NDArray) else other
        self._data = self._data + o
        return self

    def __isub__(self, other):
        o = other._data if isinstance(other, NDArray) else other
        self._data = self._data - o
        return self

    def __imul__(self, other):
        o = other._data if isinstance(other, NDArray) else other
        self._data = self._data * o
        return self

    def __itruediv__(self, other):
        o = other._data if isinstance(other, NDArray) else other
        self._data = self._data / o
        return self

    def _cmp(self, other, fn):
        o = other._data if isinstance(other, NDArray) else other
        return NDArray._from_data(fn(self._data, o).astype(self._data.dtype))

    def __eq__(self, other):
        if other is None:
            return False
        return self._cmp(other, jnp.equal)

    def __ne__(self, other):
        if other is None:
            return True
        return self._cmp(other, jnp.not_equal)

    def __lt__(self, other):
        return self._cmp(other, jnp.less)

    def __le__(self, other):
        return self._cmp(other, jnp.less_equal)

    def __gt__(self, other):
        return self._cmp(other, jnp.greater)

    def __ge__(self, other):
        return self._cmp(other, jnp.greater_equal)

    def __hash__(self):
        return id(self)

    def __bool__(self):
        if self.size == 1:
            return bool(self.asnumpy())
        raise ValueError("The truth value of an NDArray with multiple elements is ambiguous.")

    def __len__(self):
        return self.shape[0] if self.ndim else 0

    def __float__(self):
        return float(self.asscalar())

    def __int__(self):
        return int(self.asscalar())

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    # -- indexing ----------------------------------------------------------
    def _jax_key(self, key):
        if isinstance(key, NDArray):
            return key._data.astype(jnp.int32) if jnp.issubdtype(key._data.dtype, jnp.floating) else key._data
        if isinstance(key, tuple):
            return tuple(self._jax_key(k) if isinstance(k, NDArray) else k for k in key)
        return key

    def __getitem__(self, key):
        if self._data.size >= 2 ** 31:
            # int64-element-count tensors: jnp indexing routes offsets
            # through int32 gather args and overflows; static lax.slice
            # carries its bounds as attributes instead
            params = _static_slice_params(self._data.shape, key)
            if params is not None:
                return autograd.invoke_recorded(
                    lambda d: _apply_static_slice(d, params), [self])[0]
        k = self._jax_key(key)
        return autograd.invoke_recorded(lambda d: d[k], [self])[0]

    def __setitem__(self, key, value):
        v = value._data if isinstance(value, NDArray) else value
        if self._data.size >= 2 ** 31:
            # writes share the int32 scatter-offset overflow: rebuild along
            # axis 0 from static slices instead
            updated = _static_set(self._data, key, v)
            if updated is None:
                raise IndexError(
                    "unsupported index pattern for a tensor with >= 2**31 "
                    "elements; use int/slice indexing on axis 0")
            self._data = updated
            return
        k = self._jax_key(key)
        self._data = self._data.at[k].set(v)

    def __repr__(self):
        return f"\n{self.asnumpy()}\n<NDArray {'x'.join(map(str, self.shape))} @{self.context}>"

    # numpy interop
    def __array__(self, dtype=None):
        a = self.asnumpy()
        return a.astype(dtype) if dtype is not None else a


def _static_slice_params(shape, key):
    """(starts, stops, steps, squeeze_axes) for a static int/slice key, or
    None when the key is not statically sliceable. Validation only — no
    device work (the caller executes once on the tape)."""
    idx = key if isinstance(key, tuple) else (key,)
    if len(idx) > len(shape):
        return None
    starts, stops, steps, squeeze = [], [], [], []
    for ax, k in enumerate(idx):
        size = shape[ax]
        if isinstance(k, bool):  # bool is an int subtype but means masking
            return None
        if isinstance(k, (int, np.integer)):
            kk = int(k) + size if k < 0 else int(k)
            if not 0 <= kk < size:
                raise IndexError(f"index {k} out of bounds for axis {ax}")
            starts.append(kk)
            stops.append(kk + 1)
            steps.append(1)
            squeeze.append(ax)
        elif isinstance(k, slice):
            st, sp, stp = k.indices(size)
            if stp <= 0:
                return None
            starts.append(st)
            stops.append(max(sp, st))
            steps.append(stp)
        else:
            return None
    for ax in range(len(idx), len(shape)):
        starts.append(0)
        stops.append(shape[ax])
        steps.append(1)
    return starts, stops, steps, tuple(squeeze)


def _apply_static_slice(d, params):
    """Execute lax.slice with STATIC (attribute) bounds — no int32 index
    arguments, so offsets beyond 2^31 work on int64-sized tensors."""
    starts, stops, steps, squeeze = params
    out = jax.lax.slice(d, starts, stops, steps)
    if squeeze:
        out = jnp.squeeze(out, axis=squeeze)
    return out


def _static_set(d, key, v):
    """Functional write for int64-sized tensors: rebuild along axis 0 from
    static slices (concat), avoiding int32 scatter offsets. Supports an
    int or contiguous slice on axis 0 (rest of the axes full). Returns
    None for unsupported patterns."""
    k = key[0] if isinstance(key, tuple) and len(key) == 1 else key
    n = d.shape[0]
    if isinstance(k, bool):
        return None
    if isinstance(k, (int, np.integer)):
        kk = int(k) + n if k < 0 else int(k)
        if not 0 <= kk < n:
            raise IndexError(f"index {k} out of bounds")
        start, stop = kk, kk + 1
        vshape = (1,) + tuple(d.shape[1:])
    elif isinstance(k, slice):
        start, stop, step = k.indices(n)
        if step != 1:
            return None
        stop = max(stop, start)
        vshape = (stop - start,) + tuple(d.shape[1:])
    else:
        return None
    val = jnp.broadcast_to(jnp.asarray(v, d.dtype), vshape)
    ones = [1] * d.ndim
    # explicit strides: jax's strided slice impl keeps the bounds static,
    # while the unstrided form re-dispatches through dynamic_slice whose
    # int32 start args overflow at 2^31
    head = jax.lax.slice(d, [0] * d.ndim, [start] + list(d.shape[1:]), ones)
    tail = jax.lax.slice(d, [stop] + [0] * (d.ndim - 1), list(d.shape), ones)
    return jnp.concatenate([head, val, tail], axis=0)



_REGISTER = None


def _reg():
    """The register module, cached after first use (register imports this
    module, so a top-level import would be circular; the per-call
    `from . import` form costs importlib-lock time in hot methods)."""
    global _REGISTER
    if _REGISTER is None:
        from . import register
        _REGISTER = register
    return _REGISTER


def array(source_array, ctx=None, dtype=None):
    """Create an NDArray from any array-like (ref: mx.nd.array)."""
    if isinstance(source_array, NDArray):
        data = source_array._data
    elif isinstance(source_array, (np.ndarray, jax.Array)):
        # jax arrays are the native device type: wrap without a host
        # round-trip (which would also silently cast bf16 to float32)
        data = source_array
    else:
        # python lists/scalars default to float32, as the reference does
        data = np.asarray(source_array, dtype=dtype_np(dtype) if dtype else np.float32)
    out = NDArray(jnp.asarray(data), ctx=ctx)
    if dtype is not None:
        out = out.astype(dtype)
    return out


def from_jax(data):
    return NDArray._from_data(data)


def concatenate(arrays, axis=0):
    return autograd.invoke_recorded(lambda *xs: jnp.concatenate(xs, axis=axis), list(arrays))[0]


def waitall():
    """Block until all async computation completes (ref: Engine::WaitForAll)."""
    try:
        jax.effects_barrier()
    except Exception:
        pass
