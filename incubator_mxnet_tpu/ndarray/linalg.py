"""nd.linalg namespace (ref: python/mxnet/ndarray/linalg.py over la_op.h)."""
from __future__ import annotations

from ..ops.registry import OP_REGISTRY
from . import register as _register


def _fn(name):
    def f(*args, **kwargs):
        return _register.invoke(OP_REGISTRY[name], args, kwargs)

    f.__name__ = name.replace("_linalg_", "")
    return f


gemm = _fn("_linalg_gemm")
gemm2 = _fn("_linalg_gemm2")
potrf = _fn("_linalg_potrf")
potri = _fn("_linalg_potri")
trsm = _fn("_linalg_trsm")
trmm = _fn("_linalg_trmm")
syrk = _fn("_linalg_syrk")
sumlogdiag = _fn("_linalg_sumlogdiag")
extractdiag = _fn("_linalg_extractdiag")
makediag = _fn("_linalg_makediag")
