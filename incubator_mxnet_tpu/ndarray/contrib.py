"""nd.contrib namespace (ref: python/mxnet/ndarray/contrib.py).

Round-1 subset; detection/vision contrib ops land with the vision models.
"""
from __future__ import annotations

import jax.numpy as jnp

from .. import autograd
from .ndarray import NDArray
from ..ops.registry import OP_REGISTRY
from . import register as _register


def boolean_mask(data, index, axis=0):
    return _register.invoke(OP_REGISTRY["boolean_mask"], (data, index), dict(axis=axis))


def index_copy(old_tensor, index_vector, new_tensor):
    idx = index_vector._data.astype(jnp.int32)
    return autograd.invoke_recorded(
        lambda old, new: old.at[idx].set(new), [old_tensor, new_tensor]
    )[0]


def index_array(data, axes=None):
    shape = data.shape
    axes_ = tuple(axes) if axes is not None else tuple(range(len(shape)))
    grids = jnp.meshgrid(*[jnp.arange(shape[a]) for a in axes_], indexing="ij")
    out = jnp.stack([g.astype(jnp.int64) for g in grids], axis=-1)
    return NDArray._from_data(out)


def arange_like(data, start=0.0, step=1.0, axis=None):
    return _register.invoke(
        OP_REGISTRY["_arange_like"], (data,), dict(start=start, step=step, axis=axis)
    )
