"""nd.contrib namespace (ref: python/mxnet/ndarray/contrib.py).

Round-1 subset; detection/vision contrib ops land with the vision models.
"""
from __future__ import annotations

import jax.numpy as jnp

from .. import autograd
from .ndarray import NDArray
from ..ops.registry import OP_REGISTRY
from . import register as _register


def boolean_mask(data, index, axis=0):
    return _register.invoke(OP_REGISTRY["boolean_mask"], (data, index), dict(axis=axis))


def index_copy(old_tensor, index_vector, new_tensor):
    idx = index_vector._data.astype(jnp.int32)
    return autograd.invoke_recorded(
        lambda old, new: old.at[idx].set(new), [old_tensor, new_tensor]
    )[0]


def index_array(data, axes=None):
    shape = data.shape
    axes_ = tuple(axes) if axes is not None else tuple(range(len(shape)))
    grids = jnp.meshgrid(*[jnp.arange(shape[a]) for a in axes_], indexing="ij")
    out = jnp.stack([g.astype(jnp.int64) for g in grids], axis=-1)
    return NDArray._from_data(out)


def arange_like(data, start=0.0, step=1.0, axis=None):
    return _register.invoke(
        OP_REGISTRY["_arange_like"], (data,), dict(start=start, step=step, axis=axis)
    )


# --- DGL graph-sampling ops (host-side CSR kernels; see contrib/graph.py,
#     ref: src/operator/contrib/dgl_graph.cc) ------------------------------

def dgl_csr_neighbor_uniform_sample(csr, *seeds, **kwargs):
    from ..contrib import graph as _graph
    kwargs.pop("num_args", None)
    return _graph.csr_neighbor_uniform_sample(csr, *seeds, **kwargs)


def dgl_csr_neighbor_non_uniform_sample(csr, probability, *seeds, **kwargs):
    from ..contrib import graph as _graph
    kwargs.pop("num_args", None)
    return _graph.csr_neighbor_non_uniform_sample(csr, probability, *seeds, **kwargs)


def dgl_subgraph(graph, *vertex_arrays, **kwargs):
    from ..contrib import graph as _graph
    kwargs.pop("num_args", None)
    return _graph.dgl_subgraph(graph, *vertex_arrays, **kwargs)


def edge_id(csr, u, v):
    from ..contrib import graph as _graph
    return _graph.edge_id(csr, u, v)


def dgl_adjacency(csr):
    from ..contrib import graph as _graph
    return _graph.dgl_adjacency(csr)


def dgl_graph_compact(*args, **kwargs):
    from ..contrib import graph as _graph
    kwargs.pop("num_args", None)
    return _graph.dgl_graph_compact(*args, **kwargs)


def _install_contrib_ops():
    """Surface every `_contrib_*` registry op here under its short name
    (mirrors the reference's `nd.contrib` codegen,
    ref: python/mxnet/ndarray/register.py:157)."""
    for _name, _op in list(OP_REGISTRY.items()):
        if not _name.startswith("_contrib_"):
            continue
        short = _name[len("_contrib_"):]
        if short in globals():
            continue

        def _make(opdef):
            def f(*args, **kwargs):
                return _register.invoke(opdef, args, kwargs)
            return f

        fn = _make(_op)
        fn.__name__ = short
        fn.__doc__ = _op.fn.__doc__
        globals()[short] = fn


_install_contrib_ops()
