"""Reference-format NDArray container IO (the `.params` files every MXNet
release wrote).

Byte-exact implementation of the reference's serialization
(ref: src/ndarray/ndarray.cc:1776 NDArray::Save(fo, data, names) —
kMXAPINDArrayListMagic header + dmlc vector<NDArray> + vector<string>;
:1576 per-array v2 layout — NDARRAY_V2_MAGIC, storage type, TShape as
uint32 ndim + int64 dims, Context, mshadow type flag, raw buffer; :1662
LegacyLoad for v1/ndim-magic files), so checkpoints trained with the
reference load here offline — the no-egress answer to the reference's
model-zoo downloads (ref: python/mxnet/ndarray/utils.py:222 load).
"""
from __future__ import annotations

import struct

import numpy as np

__all__ = ["load_mxnet_params", "save_mxnet_params", "is_mxnet_params"]

_LIST_MAGIC = 0x112            # kMXAPINDArrayListMagic
_V2_MAGIC = 0xF993FAC9         # NDARRAY_V2_MAGIC (storage types)
_V1_MAGIC = 0xF993FAC8         # NDARRAY_V1_MAGIC (int64 shapes)

# mshadow type flags (ref: 3rdparty/mshadow/mshadow/base.h kFloat32...)
_TYPE_FLAGS = {0: "float32", 1: "float64", 2: "float16", 3: "uint8",
               4: "int32", 5: "int8", 6: "int64", 7: "bool",
               12: "bfloat16"}
_FLAG_OF = {v: k for k, v in _TYPE_FLAGS.items()}

# NDArrayStorageType (ref: include/mxnet/ndarray.h) and its aux counts
_STYPE_DEFAULT, _STYPE_ROW_SPARSE, _STYPE_CSR = 0, 1, 2
_NUM_AUX = {_STYPE_DEFAULT: 0, _STYPE_ROW_SPARSE: 1, _STYPE_CSR: 2}


class _Reader:
    def __init__(self, buf):
        self.buf = buf
        self.pos = 0

    def read(self, fmt):
        out = struct.unpack_from("<" + fmt, self.buf, self.pos)
        self.pos += struct.calcsize("<" + fmt)
        return out if len(out) > 1 else out[0]

    def read_bytes(self, n):
        out = self.buf[self.pos:self.pos + n]
        if len(out) != n:
            raise ValueError("truncated NDArray container")
        self.pos += n
        return out


def _read_tshape(r, dim_fmt="q"):
    ndim = r.read("I")
    if ndim == 0:
        return ()
    return tuple(r.read(f"{ndim}{dim_fmt}") if ndim > 1
                 else (r.read(dim_fmt),))


def _np_of(r, shape, type_flag):
    dt = _TYPE_FLAGS.get(type_flag)
    if dt is None:
        raise ValueError(f"unknown mshadow type flag {type_flag}")
    if dt == "float16":
        npdt = np.float16
    elif dt == "bfloat16":
        import ml_dtypes

        npdt = ml_dtypes.bfloat16
    else:
        npdt = np.dtype(dt)
    n = int(np.prod(shape)) if shape else 1
    raw = r.read_bytes(n * np.dtype(npdt).itemsize)
    return np.frombuffer(raw, dtype=npdt).reshape(shape).copy()


def _read_one(r):
    """One NDArray (ref: NDArray::Load ndarray.cc:1693 + LegacyLoad:1662).
    Returns a numpy array, a sparse triple, or None for the empty array."""
    magic = r.read("I")
    if magic == _V2_MAGIC:
        stype = r.read("i")
        nad = _NUM_AUX.get(stype)
        if nad is None:
            raise ValueError(f"unknown storage type {stype}")
        sshape = _read_tshape(r) if nad > 0 else None
        shape = _read_tshape(r)
        if not shape:
            return None
        r.read("ii")  # Context (dev_type, dev_id) — irrelevant on load
        type_flag = r.read("i")
        aux = []
        if nad > 0:
            aux_meta = [(r.read("i"), _read_tshape(r)) for _ in range(nad)]
            data = _np_of(r, sshape, type_flag)
            for aflag, ashape in aux_meta:
                aux.append(_np_of(r, ashape, aflag))
            return ("sparse", stype, shape, data, aux)
        return _np_of(r, shape, type_flag)
    # legacy: V1 (int64 dims) or the magic IS the ndim (uint32 dims)
    if magic == _V1_MAGIC:
        shape = _read_tshape(r, "q")
    else:
        ndim = magic
        if ndim > 32:
            raise ValueError(f"bad NDArray magic 0x{magic:x}")
        shape = tuple(r.read(f"{ndim}I")) if ndim > 1 else \
            ((r.read("I"),) if ndim else ())
    if not shape:
        return None
    r.read("ii")  # Context
    type_flag = r.read("i")
    return _np_of(r, shape, type_flag)


def is_mxnet_params(path_or_bytes):
    """True when the file/bytes carry the reference container magic."""
    if isinstance(path_or_bytes, bytes):
        head = path_or_bytes[:8]
    else:
        with open(path_or_bytes, "rb") as f:
            head = f.read(8)
    return len(head) == 8 and struct.unpack("<Q", head)[0] == _LIST_MAGIC


def load_mxnet_params(path_or_bytes):
    """Read a reference-format .params file -> dict name -> NDArray (or a
    list when the file carries no names), exactly like the reference's
    `mx.nd.load` (ref: ndarray.cc:1788 NDArray::Load)."""
    from .ndarray import NDArray
    from .sparse import CSRNDArray, RowSparseNDArray

    if isinstance(path_or_bytes, bytes):
        buf = path_or_bytes
    else:
        with open(path_or_bytes, "rb") as f:
            buf = f.read()
    r = _Reader(buf)
    header, _reserved = r.read("Q"), r.read("Q")
    if header != _LIST_MAGIC:
        raise ValueError("not a reference-format NDArray container "
                         f"(magic 0x{header:x})")
    count = r.read("Q")
    arrays = []
    for _ in range(count):
        item = _read_one(r)
        if item is None:
            arrays.append(None)
        elif isinstance(item, tuple) and item[0] == "sparse":
            _, stype, shape, data, aux = item
            if stype == _STYPE_ROW_SPARSE:
                arrays.append(RowSparseNDArray(
                    NDArray(data), NDArray(aux[0].astype(np.int64)), shape))
            else:
                # CSR aux order on disk: kIndPtr=0, kIdx=1
                # (ref: include/mxnet/ndarray.h csr::CSRAuxType)
                arrays.append(CSRNDArray(
                    NDArray(data), NDArray(aux[0].astype(np.int64)),
                    NDArray(aux[1].astype(np.int64)), shape))
        else:
            arrays.append(NDArray(item))
    n_names = r.read("Q")
    names = []
    for _ in range(n_names):
        ln = r.read("Q")
        names.append(r.read_bytes(ln).decode("utf-8"))
    if not names:
        return arrays
    if len(names) != len(arrays):
        raise ValueError("corrupt container: name/array count mismatch")
    return dict(zip(names, arrays))


def save_mxnet_params(path, data):
    """Write a reference-format .params file the reference itself can load
    (dense arrays; v2 layout). `data`: dict name -> array, or list."""
    if isinstance(data, dict):
        names = list(data.keys())
        arrays = [data[n] for n in names]
    else:
        names, arrays = [], list(data)
    out = [struct.pack("<QQQ", _LIST_MAGIC, 0, len(arrays))]
    for arr in arrays:
        a = np.asarray(arr.asnumpy() if hasattr(arr, "asnumpy") else arr)
        dt = a.dtype.name
        if dt not in _FLAG_OF:
            raise TypeError(f"dtype {dt} has no mshadow type flag")
        out.append(struct.pack("<Ii", _V2_MAGIC, _STYPE_DEFAULT))
        out.append(struct.pack("<I", a.ndim))
        out.append(struct.pack(f"<{a.ndim}q", *a.shape))
        out.append(struct.pack("<ii", 1, 0))  # Context: cpu(0)
        out.append(struct.pack("<i", _FLAG_OF[dt]))
        out.append(np.ascontiguousarray(a).tobytes())
    out.append(struct.pack("<Q", len(names)))
    for n in names:
        nb = n.encode("utf-8")
        out.append(struct.pack("<Q", len(nb)) + nb)
    blob = b"".join(out)
    if path is None:
        return blob
    with open(path, "wb") as f:
        f.write(blob)
    return path
