"""nd.image namespace (ref: python/mxnet/ndarray/image.py — the generated
`_image_*` op wrappers exposed under friendly names)."""
from __future__ import annotations

from ..ops.registry import OP_REGISTRY
from . import register as _register

__all__ = ["to_tensor", "normalize", "resize"]


def to_tensor(data):
    """HWC/NHWC [0,255] -> CHW/NCHW float32 [0,1]."""
    return _register.invoke(OP_REGISTRY["_image_to_tensor"], (data,), {})


def normalize(data, mean=(0.0,), std=(1.0,)):
    # scalar or per-channel sequence, like the reference API
    mean = tuple(mean) if hasattr(mean, "__len__") else (float(mean),)
    std = tuple(std) if hasattr(std, "__len__") else (float(std),)
    return _register.invoke(OP_REGISTRY["_image_normalize"], (data,),
                            dict(mean=mean, std=std))


def resize(data, size, keep_ratio=False, interp=1):
    return _register.invoke(
        OP_REGISTRY["_image_resize"], (data,),
        dict(size=size, keep_ratio=keep_ratio, interp=interp))
