"""nd.image namespace (ref: python/mxnet/ndarray/image.py — the generated
`_image_*` op wrappers exposed under friendly names)."""
from __future__ import annotations

from ..ops.registry import OP_REGISTRY
from . import register as _register

__all__ = ["to_tensor", "normalize", "resize"]


def to_tensor(data):
    """HWC/NHWC [0,255] -> CHW/NCHW float32 [0,1]."""
    return _register.invoke(OP_REGISTRY["_image_to_tensor"], (data,), {})


def normalize(data, mean=(0.0,), std=(1.0,)):
    return _register.invoke(OP_REGISTRY["_image_normalize"], (data,),
                            dict(mean=tuple(mean), std=tuple(std)))


def resize(data, size, keep_ratio=False, interp=1):
    return _register.invoke(
        OP_REGISTRY["_image_resize"], (data,),
        dict(size=size, keep_ratio=keep_ratio, interp=interp))
