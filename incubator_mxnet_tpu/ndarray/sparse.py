"""Sparse NDArray storage types (ref: python/mxnet/ndarray/sparse.py,
src/ndarray/ndarray.cc kRowSparseStorage/kCSRStorage).

TPU-native stance: XLA has no first-class sparse tensors, so sparse storage
is a *host-side format* (index + value arrays) used for communication and
embedding-style workloads; compute materializes via gather/scatter, which XLA
lowers efficiently. Round 1 covers construction, conversion, elementwise and
dot paths used by the kvstore row_sparse protocol.
"""
from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from .ndarray import NDArray

__all__ = [
    "RowSparseNDArray",
    "CSRNDArray",
    "row_sparse_array",
    "csr_matrix",
    "cast_storage",
    "zeros",
]


class BaseSparseNDArray:
    @property
    def context(self):
        return self.data.context

    @property
    def dtype(self):
        return self.data.dtype

    def wait_to_read(self):
        self.data.wait_to_read()


class RowSparseNDArray(BaseSparseNDArray):
    """Rows at `indices` hold `data`; other rows are zero
    (ref: ndarray.h kRowSparseStorage)."""

    stype = "row_sparse"

    def __init__(self, data, indices, shape):
        self.data = data if isinstance(data, NDArray) else NDArray(data)
        self.indices = indices if isinstance(indices, NDArray) else NDArray(indices, dtype="int64")
        self.shape = tuple(shape)

    def tostype(self, stype):
        if stype == "row_sparse":
            return self
        if stype == "default":
            return self.todense()
        raise ValueError(stype)

    def todense(self) -> NDArray:
        out = jnp.zeros(self.shape, dtype=self.data._data.dtype)
        idx = self.indices._data.astype(jnp.int32)
        return NDArray._from_data(out.at[idx].set(self.data._data))

    def asnumpy(self):
        return self.todense().asnumpy()

    def copyto(self, other):
        return self.todense().copyto(other)

    def __repr__(self):
        return f"<RowSparseNDArray {'x'.join(map(str, self.shape))} nnz_rows={self.indices.shape[0]}>"


class CSRNDArray(BaseSparseNDArray):
    """Compressed sparse row matrix (ref: ndarray.h kCSRStorage)."""

    stype = "csr"

    def __init__(self, data, indptr, indices, shape):
        self.data = data if isinstance(data, NDArray) else NDArray(data)
        self.indptr = indptr if isinstance(indptr, NDArray) else NDArray(indptr, dtype="int64")
        self.indices = indices if isinstance(indices, NDArray) else NDArray(indices, dtype="int64")
        self.shape = tuple(shape)

    def tostype(self, stype):
        if stype == "csr":
            return self
        if stype == "default":
            return self.todense()
        raise ValueError(stype)

    def todense(self) -> NDArray:
        import scipy.sparse as sp  # host-side conversion

        m = sp.csr_matrix(
            (self.data.asnumpy(), self.indices.asnumpy(), self.indptr.asnumpy()), shape=self.shape
        )
        return NDArray(jnp.asarray(m.toarray()))

    def asnumpy(self):
        return self.todense().asnumpy()

    def __repr__(self):
        return f"<CSRNDArray {'x'.join(map(str, self.shape))} nnz={self.data.shape[0]}>"


def row_sparse_array(arg, shape=None, ctx=None, dtype=None):
    if isinstance(arg, tuple) and len(arg) == 2:
        data, indices = arg
        return RowSparseNDArray(NDArray(np.asarray(data, dtype=np.float32 if dtype is None else dtype)),
                                NDArray(np.asarray(indices, dtype=np.int64)), shape)
    dense = np.asarray(arg.asnumpy() if isinstance(arg, NDArray) else arg)
    nz_rows = np.where(np.any(dense != 0, axis=tuple(range(1, dense.ndim))))[0]
    return RowSparseNDArray(NDArray(dense[nz_rows]), NDArray(nz_rows.astype(np.int64)), dense.shape)


def csr_matrix(arg, shape=None, ctx=None, dtype=None):
    import scipy.sparse as sp

    if isinstance(arg, tuple) and len(arg) == 3:
        data, indices, indptr = arg
        return CSRNDArray(NDArray(np.asarray(data)), NDArray(np.asarray(indptr, dtype=np.int64)),
                          NDArray(np.asarray(indices, dtype=np.int64)), shape)
    dense = np.asarray(arg.asnumpy() if isinstance(arg, NDArray) else arg)
    m = sp.csr_matrix(dense)
    return CSRNDArray(NDArray(m.data), NDArray(m.indptr.astype(np.int64)),
                      NDArray(m.indices.astype(np.int64)), dense.shape)


def cast_storage(arr, stype):
    if stype == "default":
        return arr.todense() if isinstance(arr, BaseSparseNDArray) else arr
    if stype == "row_sparse":
        return row_sparse_array(arr)
    if stype == "csr":
        return csr_matrix(arr)
    raise ValueError(stype)


def zeros(stype, shape, ctx=None, dtype="float32"):
    if stype == "row_sparse":
        return RowSparseNDArray(
            NDArray(np.zeros((0,) + tuple(shape[1:]), dtype=np.float32)),
            NDArray(np.zeros((0,), dtype=np.int64)),
            shape,
        )
    if stype == "csr":
        return CSRNDArray(
            NDArray(np.zeros((0,), dtype=np.float32)),
            NDArray(np.zeros((shape[0] + 1,), dtype=np.int64)),
            NDArray(np.zeros((0,), dtype=np.int64)),
            shape,
        )
    raise ValueError(stype)


def retain(rsp: RowSparseNDArray, indices) -> RowSparseNDArray:
    """Keep only the given rows of a row_sparse array
    (ref: sparse_retain op)."""
    want = np.asarray(indices.asnumpy() if isinstance(indices, NDArray) else indices).astype(np.int64)
    have = rsp.indices.asnumpy()
    mask = np.isin(have, want)
    return RowSparseNDArray(
        NDArray(rsp.data.asnumpy()[mask]), NDArray(have[mask]), rsp.shape
    )
