"""Sparse NDArray storage types + sparse operators
(ref: python/mxnet/ndarray/sparse.py CSRNDArray:287/RowSparseNDArray:561;
src/ndarray/ndarray.cc kRowSparseStorage/kCSRStorage;
src/operator/tensor/dot-inl.h sparse dot kernels;
src/operator/tensor/sparse_retain-inl.h).

TPU-native stance: XLA has no first-class sparse tensors, so sparse storage
is an (index, value) host-visible format whose COMPUTE lowers to the three
primitives XLA/TPU handles well — gather, dense matmul on the gathered
block, and `segment_sum` scatter-reduction. A CSR x dense matmul is:

    rows  = searchsorted(indptr, arange(nnz))          # nnz -> row ids
    prod  = data[:, None] * dense[col_indices]         # gather + multiply
    out   = segment_sum(prod, rows, num_segments=m)    # fused scatter-add

All kernels have static shapes (nnz is a compile-time constant per batch
signature), so they jit cleanly. Index-set algebra (unions, uniqueness) is
data-dependent and stays on host — sparse arrays are an eager/communication
format here; the hot training path remains dense XLA programs.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from .ndarray import NDArray

__all__ = [
    "BaseSparseNDArray",
    "RowSparseNDArray",
    "CSRNDArray",
    "row_sparse_array",
    "csr_matrix",
    "cast_storage",
    "zeros",
    "retain",
    "dot",
    "add",
    "subtract",
    "multiply",
    "add_n",
    "bucket_nnz",
    "pad_row_ids",
]


class BaseSparseNDArray:
    @property
    def context(self):
        return self.data.context

    @property
    def dtype(self):
        return self.data.dtype

    def wait_to_read(self):
        self.data.wait_to_read()

    # sparse arrays share the dense save/load container via densification
    # markers; see ndarray/utils.py for the container format.
    def __add__(self, other):
        return add(self, other)

    def __sub__(self, other):
        return subtract(self, other)

    def __mul__(self, other):
        return multiply(self, other)

    __radd__ = __add__
    __rmul__ = __mul__


class RowSparseNDArray(BaseSparseNDArray):
    """Rows at `indices` hold `data`; other rows are zero
    (ref: ndarray.h kRowSparseStorage, python RowSparseNDArray:561)."""

    stype = "row_sparse"

    def __init__(self, data, indices, shape):
        self.data = data if isinstance(data, NDArray) else NDArray(data)
        self.indices = indices if isinstance(indices, NDArray) else NDArray(indices, dtype="int64")
        self.shape = tuple(shape)

    @property
    def nnz(self):
        return int(self.indices.shape[0])

    def tostype(self, stype):
        if stype == "row_sparse":
            return self
        if stype == "default":
            return self.todense()
        raise ValueError(stype)

    def todense(self) -> NDArray:
        out = jnp.zeros(self.shape, dtype=self.data._data.dtype)
        idx = self.indices._data.astype(jnp.int32)
        return NDArray._from_data(out.at[idx].set(self.data._data))

    def asnumpy(self):
        return self.todense().asnumpy()

    def copyto(self, other):
        return self.todense().copyto(other)

    def copy(self):
        return RowSparseNDArray(
            NDArray(self.data._data), NDArray(self.indices._data), self.shape)

    def check_format(self, full_check=True):
        """(ref: CheckFormat for kRowSparseStorage) — indices strictly
        ascending, in range, matching data rows."""
        idx = self.indices.asnumpy()
        if idx.shape[0] != self.data.shape[0]:
            raise ValueError("indices/data length mismatch")
        if idx.size and (np.any(np.diff(idx) <= 0)):
            raise ValueError("row_sparse indices must be strictly ascending")
        if idx.size and (idx[0] < 0 or idx[-1] >= self.shape[0]):
            raise ValueError("row index out of range")

    def retain(self, indices):
        return retain(self, indices)

    def __repr__(self):
        return f"<RowSparseNDArray {'x'.join(map(str, self.shape))} nnz_rows={self.indices.shape[0]}>"


class CSRNDArray(BaseSparseNDArray):
    """Compressed sparse row matrix (ref: ndarray.h kCSRStorage,
    python CSRNDArray:287)."""

    stype = "csr"

    def __init__(self, data, indptr, indices, shape):
        self.data = data if isinstance(data, NDArray) else NDArray(data)
        self.indptr = indptr if isinstance(indptr, NDArray) else NDArray(indptr, dtype="int64")
        self.indices = indices if isinstance(indices, NDArray) else NDArray(indices, dtype="int64")
        self.shape = tuple(shape)

    @property
    def nnz(self):
        return int(self.data.shape[0])

    def tostype(self, stype):
        if stype == "csr":
            return self
        if stype == "default":
            return self.todense()
        if stype == "row_sparse":
            return row_sparse_array(self.todense())
        raise ValueError(stype)

    def todense(self) -> NDArray:
        dense = _csr_to_dense(self.data._data, self.indices._data,
                              self.indptr._data, self.shape)
        return NDArray._from_data(dense)

    def asnumpy(self):
        return self.todense().asnumpy()

    def copy(self):
        return CSRNDArray(NDArray(self.data._data), NDArray(self.indptr._data),
                          NDArray(self.indices._data), self.shape)

    def check_format(self, full_check=True):
        """(ref: CheckFormat for kCSRStorage)."""
        indptr = self.indptr.asnumpy()
        idx = self.indices.asnumpy()
        if indptr.shape[0] != self.shape[0] + 1:
            raise ValueError("indptr length must be rows+1")
        if indptr[0] != 0 or indptr[-1] != idx.shape[0]:
            raise ValueError("indptr endpoints invalid")
        if np.any(np.diff(indptr) < 0):
            raise ValueError("indptr must be non-decreasing")
        if idx.size and (idx.min() < 0 or idx.max() >= self.shape[1]):
            raise ValueError("column index out of range")

    def __getitem__(self, key):
        """Row slicing (ref: CSRNDArray.__getitem__) — returns CSR."""
        if isinstance(key, int):
            if key < 0:
                key += self.shape[0]
            if not 0 <= key < self.shape[0]:
                raise IndexError(f"row {key} out of range")
            key = slice(key, key + 1)
        if not isinstance(key, slice):
            raise TypeError("CSR supports int/slice row indexing")
        start, stop, stride = key.indices(self.shape[0])
        if stride != 1:
            raise ValueError("CSR slicing requires step 1")
        stop = max(stop, start)  # empty (not negative-shaped) for stop<start
        indptr = self.indptr.asnumpy()
        lo, hi = int(indptr[start]), int(indptr[stop])
        return CSRNDArray(
            NDArray(self.data.asnumpy()[lo:hi]),
            NDArray((indptr[start:stop + 1] - lo).astype(np.int64)),
            NDArray(self.indices.asnumpy()[lo:hi]),
            (stop - start, self.shape[1]),
        )

    def __repr__(self):
        return f"<CSRNDArray {'x'.join(map(str, self.shape))} nnz={self.data.shape[0]}>"


# ---------------------------------------------------------------------------
# jittable sparse kernels (gather + segment_sum formulation)
# ---------------------------------------------------------------------------


def _row_ids_from_indptr(indptr, nnz):
    """Per-nonzero row ids from a CSR indptr: rows[j] = the row containing
    nonzero j. searchsorted keeps this jittable with static nnz."""
    return (jnp.searchsorted(indptr, jnp.arange(nnz, dtype=indptr.dtype),
                             side="right") - 1).astype(jnp.int32)


from functools import partial


@partial(jax.jit, static_argnums=3)
def _csr_to_dense(data, indices, indptr, shape):
    rows = _row_ids_from_indptr(indptr, data.shape[0])
    out = jnp.zeros(shape, dtype=data.dtype)
    return out.at[rows, indices.astype(jnp.int32)].add(data)


@partial(jax.jit, static_argnums=4)
def _csr_dot_dense(data, indices, indptr, rhs, m):
    """CSR(m,k) x dense(k,n) -> dense(m,n). MXU-adjacent formulation:
    gather rhs rows by column index, scale, segment-sum by row.
    Tolerates nnz PADDING: padded entries carry data 0 (their row id
    resolves to m, which segment_sum drops; their clamped gathers multiply
    against zero)."""
    rows = _row_ids_from_indptr(indptr, data.shape[0])
    gathered = rhs[indices.astype(jnp.int32)]          # (nnz, n)
    prod = data.reshape(-1, *([1] * (rhs.ndim - 1))) * gathered
    return jax.ops.segment_sum(prod, rows, num_segments=m)


@partial(jax.jit, static_argnums=4)
def _csr_T_dot_dense(data, indices, indptr, rhs, k):
    """CSR(m,k)^T x dense(m,n) -> dense(k,n): scatter-add into columns.
    nnz-padding-tolerant like _csr_dot_dense."""
    rows = _row_ids_from_indptr(indptr, data.shape[0])
    gathered = rhs[rows]                                # (nnz, n)
    prod = data.reshape(-1, *([1] * (rhs.ndim - 1))) * gathered
    return jax.ops.segment_sum(prod, indices.astype(jnp.int32), num_segments=k)


# ---------------------------------------------------------------------------
# nnz bucketing: "nnz is a compile-time constant" means every distinct nnz
# is a distinct XLA program; real sparse streams (minibatches of LibSVM
# rows, sampled subgraphs) vary nnz per batch and would recompile forever.
# Padding nnz up to a power-of-2 bucket bounds the number of programs at
# log2(max_nnz) while adding only zero-contribution entries (ref role:
# src/operator/tensor/dot-inl.h handles dynamic nnz at runtime; XLA's
# static shapes make bucketing the equivalent policy).
# ---------------------------------------------------------------------------


def _bucket_nnz(n):
    """Smallest power-of-2 >= n (floor 16 keeps tiny batches in one
    bucket)."""
    n = int(n)
    if n <= 16:
        return 16
    return 1 << (n - 1).bit_length()


def _pad_nnz(data, indices):
    """Pad (data, indices) along nnz to the bucket size with zeros; returns
    them unchanged when bucketing is disabled (MXTPU_SPARSE_NNZ_BUCKETING)
    or already at a bucket boundary."""
    from .. import config as _config

    if not _config.get("MXTPU_SPARSE_NNZ_BUCKETING"):
        return data, indices
    n = int(data.shape[0])
    b = _bucket_nnz(n)
    if b == n:
        return data, indices
    pad = b - n
    data = jnp.concatenate(
        [data, jnp.zeros((pad,) + tuple(data.shape[1:]), data.dtype)])
    indices = jnp.concatenate(
        [indices, jnp.zeros((pad,), indices.dtype)])
    return data, indices


def bucket_nnz(n):
    """Public bucket grid: the nnz a sparse buffer is padded to when
    MXTPU_SPARSE_NNZ_BUCKETING is on — smallest power-of-2 >= n, floor 16.
    Every consumer of the grid (sparse kernels, the sharded embedding
    service's pull blocks, kvstore row pulls) MUST share this function so
    one batch's nnz maps to one shape everywhere."""
    return _bucket_nnz(n)


def pad_row_ids(ids, force=False):
    """Pad a host-side row-id vector up to its nnz bucket by repeating the
    last id. Returns (padded_ids, n_valid). Repeats — not zeros — so a
    padded PULL fetches a row that is being fetched anyway (no phantom row
    0 traffic) and the consumer slices [:n_valid] before any gradient
    math, keeping padding invisible to the optimizer. No-op (aside from
    the int64 cast) while MXTPU_SPARSE_NNZ_BUCKETING is off and `force`
    is not set."""
    from .. import config as _config

    ids = np.asarray(ids, np.int64).reshape(-1)
    n = int(ids.shape[0])
    if not (force or _config.get("MXTPU_SPARSE_NNZ_BUCKETING")):
        return ids, n
    b = _bucket_nnz(n)
    if b == n or n == 0:
        return ids, n
    return np.concatenate([ids, np.full(b - n, ids[-1], np.int64)]), n


def dot(lhs, rhs, transpose_a=False):
    """Sparse dot (ref: src/operator/tensor/dot-inl.h; python sparse.dot).

    Supported, mirroring the reference's storage-inference table:
      dot(csr, dense)            -> dense     (SpMM)
      dot(csr, dense, T_a=True)  -> row_sparse (rows = touched columns)
      dot(dense, row_sparse)     -> dense     (gathered column block)
      dot(rsp/csr-as-dense, ...) -> dense fallbacks via todense()
    """
    if isinstance(lhs, CSRNDArray):
        r = rhs._data if isinstance(rhs, NDArray) else rhs.todense()._data
        if not transpose_a:
            out = _csr_dot_dense(lhs.data._data, lhs.indices._data,
                                 lhs.indptr._data, r, lhs.shape[0])
            return NDArray._from_data(out)
        dense_out = _csr_T_dot_dense(lhs.data._data, lhs.indices._data,
                                     lhs.indptr._data, r, lhs.shape[1])
        # output rows = columns touched by any nonzero — data-dependent,
        # resolved on host (eager), as the reference's FInferStorageType
        # does when it emits kRowSparseStorage for csr^T . dense
        cols = np.unique(np.asarray(lhs.indices.asnumpy(), dtype=np.int64))
        return RowSparseNDArray(
            NDArray(jnp.take(dense_out, jnp.asarray(cols), axis=0)),
            NDArray(cols), (lhs.shape[1],) + tuple(dense_out.shape[1:]))
    if isinstance(lhs, NDArray) and isinstance(rhs, RowSparseNDArray):
        if transpose_a:
            raise NotImplementedError("dot(dense^T, rsp) unsupported")
        # (m,k) x rsp(k,n): only stored rows of rhs contribute
        idx = rhs.indices._data.astype(jnp.int32)
        cols = jnp.take(lhs._data, idx, axis=1)          # (m, nnz_rows)
        return NDArray._from_data(cols @ rhs.data._data)
    if isinstance(lhs, RowSparseNDArray):
        return NDArray._from_data(
            (lhs.todense()._data.T if transpose_a else lhs.todense()._data)
            @ (rhs._data if isinstance(rhs, NDArray) else rhs.todense()._data))
    raise TypeError(f"unsupported sparse dot: {type(lhs)} x {type(rhs)}")


# ---------------------------------------------------------------------------
# elementwise (index-set algebra on host, value compute in jnp)
# ---------------------------------------------------------------------------


def _rsp_binary(lhs: RowSparseNDArray, rhs: RowSparseNDArray, fn):
    assert lhs.shape == rhs.shape, (lhs.shape, rhs.shape)
    li, ri = lhs.indices.asnumpy(), rhs.indices.asnumpy()
    union = np.union1d(li, ri).astype(np.int64)
    # union1d output is sorted, so positions are a vectorized searchsorted
    width = lhs.data._data.shape[1:]
    lfull = jnp.zeros((len(union),) + width, lhs.data._data.dtype)
    rfull = jnp.zeros((len(union),) + width, rhs.data._data.dtype)
    if li.size:
        lfull = lfull.at[jnp.asarray(np.searchsorted(union, li))].set(lhs.data._data)
    if ri.size:
        rfull = rfull.at[jnp.asarray(np.searchsorted(union, ri))].set(rhs.data._data)
    return RowSparseNDArray(NDArray(fn(lfull, rfull)), NDArray(union), lhs.shape)


def add(lhs, rhs):
    """elemwise_add with sparse storage (ref: elemwise_binary_op_basic.cc)."""
    if isinstance(lhs, RowSparseNDArray) and isinstance(rhs, RowSparseNDArray):
        return _rsp_binary(lhs, rhs, jnp.add)
    return _dense_fallback(lhs, rhs, jnp.add)


def subtract(lhs, rhs):
    if isinstance(lhs, RowSparseNDArray) and isinstance(rhs, RowSparseNDArray):
        return _rsp_binary(lhs, rhs, jnp.subtract)
    return _dense_fallback(lhs, rhs, jnp.subtract)


def multiply(lhs, rhs):
    if isinstance(lhs, BaseSparseNDArray) and np.isscalar(rhs):
        out = lhs.copy()
        out.data._data = out.data._data * rhs
        return out
    if isinstance(rhs, BaseSparseNDArray) and np.isscalar(lhs):
        return multiply(rhs, lhs)
    if isinstance(lhs, RowSparseNDArray) and isinstance(rhs, RowSparseNDArray):
        return _rsp_binary(lhs, rhs, jnp.multiply)
    return _dense_fallback(lhs, rhs, jnp.multiply)


def _dense_fallback(lhs, rhs, fn):
    l = lhs.todense() if isinstance(lhs, BaseSparseNDArray) else lhs
    r = rhs.todense() if isinstance(rhs, BaseSparseNDArray) else rhs
    ld = l._data if isinstance(l, NDArray) else jnp.asarray(l)
    rd = r._data if isinstance(r, NDArray) else jnp.asarray(r)
    return NDArray._from_data(fn(ld, rd))


def add_n(*arrs):
    """Sum of sparse/dense arrays (ref: ElemwiseSum sparse path)."""
    acc = arrs[0]
    for a in arrs[1:]:
        acc = add(acc, a)
    return acc


# ---------------------------------------------------------------------------
# constructors / conversion
# ---------------------------------------------------------------------------


def row_sparse_array(arg, shape=None, ctx=None, dtype=None):
    if isinstance(arg, tuple) and len(arg) == 2:
        data, indices = arg
        return RowSparseNDArray(NDArray(np.asarray(data, dtype=np.float32 if dtype is None else dtype)),
                                NDArray(np.asarray(indices, dtype=np.int64)), shape)
    dense = np.asarray(arg.asnumpy() if isinstance(arg, NDArray) else arg)
    nz_rows = np.where(np.any(dense != 0, axis=tuple(range(1, dense.ndim))))[0]
    return RowSparseNDArray(NDArray(dense[nz_rows]), NDArray(nz_rows.astype(np.int64)), dense.shape)


def csr_matrix(arg, shape=None, ctx=None, dtype=None):
    if isinstance(arg, tuple) and len(arg) == 3:
        data, indices, indptr = arg
        return CSRNDArray(NDArray(np.asarray(data)), NDArray(np.asarray(indptr, dtype=np.int64)),
                          NDArray(np.asarray(indices, dtype=np.int64)), shape)
    dense = np.asarray(arg.asnumpy() if isinstance(arg, NDArray) else arg)
    if dense.ndim != 2:
        raise ValueError("csr_matrix needs a 2-D input")
    # dense -> CSR without scipy: row-major scan of the nonzero pattern
    rows, cols = np.nonzero(dense)
    data = dense[rows, cols]
    indptr = np.zeros(dense.shape[0] + 1, np.int64)
    np.add.at(indptr, rows + 1, 1)
    indptr = np.cumsum(indptr)
    return CSRNDArray(NDArray(data), NDArray(indptr),
                      NDArray(cols.astype(np.int64)), dense.shape)


def cast_storage(arr, stype):
    """(ref: src/operator/tensor/cast_storage-inl.h)."""
    if stype == "default":
        return arr.todense() if isinstance(arr, BaseSparseNDArray) else arr
    if stype == "row_sparse":
        return arr if isinstance(arr, RowSparseNDArray) else row_sparse_array(arr)
    if stype == "csr":
        return arr if isinstance(arr, CSRNDArray) else csr_matrix(arr)
    raise ValueError(stype)


def zeros(stype, shape, ctx=None, dtype="float32"):
    if stype == "row_sparse":
        return RowSparseNDArray(
            NDArray(np.zeros((0,) + tuple(shape[1:]), dtype=dtype)),
            NDArray(np.zeros((0,), dtype=np.int64)),
            shape,
        )
    if stype == "csr":
        return CSRNDArray(
            NDArray(np.zeros((0,), dtype=dtype)),
            NDArray(np.zeros((shape[0] + 1,), dtype=np.int64)),
            NDArray(np.zeros((0,), dtype=np.int64)),
            shape,
        )
    raise ValueError(stype)


def retain(rsp: RowSparseNDArray, indices) -> RowSparseNDArray:
    """Keep only the given rows of a row_sparse array
    (ref: src/operator/tensor/sparse_retain-inl.h)."""
    want = np.asarray(indices.asnumpy() if isinstance(indices, NDArray) else indices).astype(np.int64)
    have = rsp.indices.asnumpy()
    mask = np.isin(have, want)
    return RowSparseNDArray(
        NDArray(rsp.data.asnumpy()[mask]), NDArray(have[mask]), rsp.shape
    )
