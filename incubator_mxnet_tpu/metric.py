"""Evaluation metrics (ref: python/mxnet/metric.py — 18 metric classes)."""
from __future__ import annotations

import math

import numpy as _numpy

from .ndarray.ndarray import NDArray

__all__ = [
    "EvalMetric", "CompositeEvalMetric", "Accuracy", "TopKAccuracy", "F1", "MCC",
    "Perplexity", "MAE", "MSE", "RMSE", "CrossEntropy", "NegativeLogLikelihood",
    "PearsonCorrelation", "Loss", "Torch", "Caffe", "CustomMetric", "np", "create",
]

_REGISTRY = {}


def register(klass):
    _REGISTRY[klass.__name__.lower()] = klass
    return klass


def create(metric, *args, **kwargs):
    if callable(metric):
        return CustomMetric(metric, *args, **kwargs)
    if isinstance(metric, EvalMetric):
        return metric
    if isinstance(metric, list):
        composite = CompositeEvalMetric()
        for m in metric:
            composite.add(create(m, *args, **kwargs))
        return composite
    aliases = {"acc": "accuracy", "ce": "crossentropy", "nll_loss": "negativeloglikelihood",
               "top_k_acc": "topkaccuracy", "top_k_accuracy": "topkaccuracy",
               "pearson_correlation": "pearsoncorrelation", "cross-entropy": "crossentropy",
               "composite": "compositeevalmetric", "custom": "custommetric"}
    key = metric.lower().replace("-", "")
    key = aliases.get(metric.lower(), aliases.get(key, key))
    return _REGISTRY[key](*args, **kwargs)


def _asnp(x):
    return x.asnumpy() if isinstance(x, NDArray) else _numpy.asarray(x)


class EvalMetric:
    def __init__(self, name, output_names=None, label_names=None, **kwargs):
        self.name = str(name)
        self.output_names = output_names
        self.label_names = label_names
        self._kwargs = kwargs
        self.reset()

    @staticmethod
    def _select(mapping, names):
        """Ordered values, filtered to `names` when given."""
        if names is None:
            return list(mapping.values())
        return [mapping[n] for n in names]

    def update_dict(self, label, pred):
        self.update(self._select(label, self.label_names),
                    self._select(pred, self.output_names))

    def update(self, labels, preds):
        raise NotImplementedError

    def reset(self):
        self.num_inst = 0
        self.sum_metric = 0.0

    def get(self):
        mean = (self.sum_metric / self.num_inst if self.num_inst
                else float("nan"))
        return (self.name, mean)

    def get_name_value(self):
        name, value = self.get()
        as_list = lambda v: v if isinstance(v, list) else [v]  # noqa: E731
        return list(zip(as_list(name), as_list(value)))

    def __str__(self):
        return f"EvalMetric: {dict(self.get_name_value())}"


@register
class CompositeEvalMetric(EvalMetric):
    def __init__(self, metrics=None, name="composite", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)
        self.metrics = [create(m) for m in (metrics or [])]

    def add(self, metric):
        self.metrics.append(create(metric))

    def get_metric(self, index):
        return self.metrics[index]

    def update(self, labels, preds):
        for m in self.metrics:
            m.update(labels, preds)

    def reset(self):
        for m in getattr(self, "metrics", []):
            m.reset()

    def get(self):
        names, values = [], []
        for m in self.metrics:
            n, v = m.get()
            names.append(n)
            values.append(v)
        return names, values


def _check_same_len(labels, preds):
    if len(labels) != len(preds):
        raise ValueError(f"labels/preds count mismatch: {len(labels)} vs {len(preds)}")


@register
class Accuracy(EvalMetric):
    def __init__(self, axis=1, name="accuracy", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)
        self.axis = axis

    def update(self, labels, preds):
        _check_same_len(labels, preds)
        for label, pred in zip(labels, preds):
            label, pred = _asnp(label), _asnp(pred)
            if pred.ndim > label.ndim:
                pred = _numpy.argmax(pred, axis=self.axis)
            pred = pred.astype("int32").flatten()
            label = label.astype("int32").flatten()
            self.sum_metric += float((pred == label).sum())
            self.num_inst += len(label)


@register
class TopKAccuracy(EvalMetric):
    def __init__(self, top_k=1, name="top_k_accuracy", output_names=None, label_names=None):
        super().__init__(f"{name}_{top_k}", output_names, label_names)
        self.top_k = top_k

    def update(self, labels, preds):
        _check_same_len(labels, preds)
        for label, pred in zip(labels, preds):
            label, pred = _asnp(label).astype("int32"), _asnp(pred)
            topk = _numpy.argsort(pred, axis=-1)[:, -self.top_k:]
            self.sum_metric += float((topk == label.reshape(-1, 1)).any(axis=1).sum())
            self.num_inst += len(label)


class _ConfusionMetric(EvalMetric):
    """Shared streaming 2x2 confusion table for the binary metrics: one
    vectorized count per batch (predicted class x true class), from which
    F1 and MCC derive their closed forms."""

    def reset(self):
        self.counts = _numpy.zeros((2, 2))  # [pred][true]
        self.num_inst = 0
        self.sum_metric = 0.0

    def update(self, labels, preds):
        for label, pred in zip(labels, preds):
            y = _asnp(label).flatten()
            p = _asnp(pred)
            if p.ndim > 1:
                p = _numpy.argmax(p, axis=-1)
            p = p.flatten()
            # membership test on the RAW values (0.7 is neither class, not
            # class 0), then bincount the joint index 2*pred + true to fill
            # all four cells in one pass
            ok = ((y == 0) | (y == 1)) & ((p == 0) | (p == 1))
            joint = _numpy.bincount(
                2 * p[ok].astype(int) + y[ok].astype(int), minlength=4)
            self.counts += joint.reshape(2, 2)
            self.num_inst += 1

    @property
    def _cells(self):
        """(tp, fp, fn, tn) from the table."""
        return (self.counts[1, 1], self.counts[1, 0],
                self.counts[0, 1], self.counts[0, 0])


@register
class F1(_ConfusionMetric):
    def __init__(self, name="f1", output_names=None, label_names=None, average="macro"):
        super().__init__(name, output_names, label_names)
        self.average = average

    def get(self):
        tp, fp, fn, _ = self._cells
        # harmonic mean of precision and recall == 2tp / (2tp + fp + fn)
        f1 = 2 * tp / max(2 * tp + fp + fn, 1e-12)
        return (self.name, f1 if self.num_inst else float("nan"))


@register
class MCC(_ConfusionMetric):
    def __init__(self, name="mcc", output_names=None, label_names=None, average="macro"):
        super().__init__(name, output_names, label_names)

    def get(self):
        tp, fp, fn, tn = self._cells
        # correlation of the 2x2 table: cov / sqrt(prod of marginals)
        marginals = [tp + fp, tp + fn, tn + fp, tn + fn]
        denom = math.sqrt(math.prod(marginals))
        mcc = (tp * tn - fp * fn) / denom if denom else 0.0
        return (self.name, mcc if self.num_inst else float("nan"))


@register
class Perplexity(EvalMetric):
    def __init__(self, ignore_label=None, axis=-1, name="perplexity", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)
        self.ignore_label = ignore_label
        self.axis = axis

    def update(self, labels, preds):
        loss = 0.0
        num = 0
        for label, pred in zip(labels, preds):
            label, pred = _asnp(label), _asnp(pred)
            label = label.astype("int32").flatten()
            pred = pred.reshape(-1, pred.shape[-1])
            probs = pred[_numpy.arange(len(label)), label]
            if self.ignore_label is not None:
                ignore = (label == self.ignore_label)
                probs = _numpy.where(ignore, 1.0, probs)
                num -= int(ignore.sum())
            loss -= float(_numpy.sum(_numpy.log(_numpy.maximum(1e-10, probs))))
            num += len(label)
        self.sum_metric += loss
        self.num_inst += num

    def get(self):
        if self.num_inst == 0:
            return (self.name, float("nan"))
        return (self.name, math.exp(self.sum_metric / self.num_inst))


@register
class MAE(EvalMetric):
    def __init__(self, name="mae", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    def update(self, labels, preds):
        for label, pred in zip(labels, preds):
            label, pred = _asnp(label), _asnp(pred)
            self.sum_metric += float(_numpy.abs(label.reshape(pred.shape) - pred).mean())
            self.num_inst += 1


@register
class MSE(EvalMetric):
    def __init__(self, name="mse", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    def update(self, labels, preds):
        for label, pred in zip(labels, preds):
            label, pred = _asnp(label), _asnp(pred)
            self.sum_metric += float(((label.reshape(pred.shape) - pred) ** 2).mean())
            self.num_inst += 1


@register
class RMSE(MSE):
    def __init__(self, name="rmse", output_names=None, label_names=None):
        EvalMetric.__init__(self, name, output_names, label_names)

    def get(self):
        if self.num_inst == 0:
            return (self.name, float("nan"))
        return (self.name, math.sqrt(self.sum_metric / self.num_inst))


@register
class CrossEntropy(EvalMetric):
    def __init__(self, eps=1e-12, name="cross-entropy", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)
        self.eps = eps

    def update(self, labels, preds):
        for label, pred in zip(labels, preds):
            label, pred = _asnp(label).astype("int32").flatten(), _asnp(pred)
            pred = pred.reshape(-1, pred.shape[-1])
            prob = pred[_numpy.arange(len(label)), label]
            self.sum_metric += float((-_numpy.log(prob + self.eps)).sum())
            self.num_inst += len(label)


@register
class NegativeLogLikelihood(CrossEntropy):
    def __init__(self, eps=1e-12, name="nll-loss", output_names=None, label_names=None):
        super().__init__(eps=eps, name=name, output_names=output_names, label_names=label_names)


@register
class PearsonCorrelation(EvalMetric):
    def __init__(self, name="pearsonr", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    def update(self, labels, preds):
        for label, pred in zip(labels, preds):
            label, pred = _asnp(label).flatten(), _asnp(pred).flatten()
            cc = _numpy.corrcoef(pred, label)[0, 1]
            self.sum_metric += float(cc)
            self.num_inst += 1


@register
class Loss(EvalMetric):
    def __init__(self, name="loss", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    def update(self, _, preds):
        for pred in preds:
            loss = float(_asnp(pred).sum())
            self.sum_metric += loss
            self.num_inst += _asnp(pred).size


@register
class Torch(Loss):
    def __init__(self, name="torch", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)


@register
class Caffe(Loss):
    def __init__(self, name="caffe", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)


@register
class CustomMetric(EvalMetric):
    def __init__(self, feval, name=None, allow_extra_outputs=False,
                 output_names=None, label_names=None):
        name = name if name is not None else getattr(feval, "__name__", "custom")
        super().__init__(f"custom({name})", output_names, label_names)
        self._feval = feval
        self._allow_extra_outputs = allow_extra_outputs

    def update(self, labels, preds):
        if not self._allow_extra_outputs:
            _check_same_len(labels, preds)
        for label, pred in zip(labels, preds):
            reval = self._feval(_asnp(label), _asnp(pred))
            if isinstance(reval, tuple):
                num_inst, sum_metric = reval
                self.sum_metric += sum_metric
                self.num_inst += num_inst
            else:
                self.sum_metric += reval
                self.num_inst += 1


def np(numpy_feval, name=None, allow_extra_outputs=False):
    """Wrap a numpy feval into a metric (ref: metric.np)."""

    def feval(label, pred):
        return numpy_feval(label, pred)

    feval.__name__ = getattr(numpy_feval, "__name__", "feval")
    return CustomMetric(feval, name, allow_extra_outputs)
