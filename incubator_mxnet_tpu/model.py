"""Checkpointing + shared training helpers (ref: python/mxnet/model.py).

Checkpoint format: `prefix-symbol.json` (graph) + `prefix-%04d.params`
(NDArray container with arg:/aux: prefixed keys), exactly mirroring the
reference's save_checkpoint/load_checkpoint (model.py:394,424).

Crash consistency: every file goes through resilience.checkpoint's
tmp → fsync → atomic-rename protocol with a sha256 sidecar manifest, so a
crash mid-write leaves the previous epoch intact and a torn file is
DETECTED at load instead of silently loading garbage;
`latest_valid_checkpoint` walks back to the newest epoch that still
verifies (cf. CheckFreq, FAST'21).
"""
from __future__ import annotations

import collections
import logging
import os
import re

from . import ndarray as nd
from . import symbol as sym

logger = logging.getLogger(__name__)

__all__ = ["BatchEndParam", "save_checkpoint", "load_checkpoint",
           "latest_valid_checkpoint", "load_params", "wait_checkpoints",
           "bootstrap_params"]

BatchEndParam = collections.namedtuple(
    "BatchEndParams", ["epoch", "nbatch", "eval_metric", "locals"]
)


_ckpt_vars = {}  # prefix -> engine var ordering async writes per prefix


def save_checkpoint(prefix, epoch, symbol, arg_params, aux_params,
                    remove_amp_cast=True, run_async=False):
    """(ref: model.py:394). With run_async=True the file write is pushed
    onto the host dependency engine (write-var per prefix keeps epochs in
    order) so checkpointing overlaps the next training steps — the engine
    doing for host IO what it does for comm in the reference."""
    from . import resilience as _resilience
    from . import telemetry as _telemetry

    _telemetry.log_event("model_checkpoint", prefix=str(prefix),
                         epoch=int(epoch), run_async=bool(run_async))
    if symbol is not None:
        # own injection site: symbol rewrites must not consume the
        # ckpt.write fault stream the params files are scheduled on
        _resilience.atomic_save(
            f"{prefix}-symbol.json",
            lambda p: symbol.save(p), site="ckpt.symbol")
    save_dict = {f"arg:{k}": v for k, v in arg_params.items()}
    save_dict.update({f"aux:{k}": v for k, v in aux_params.items()})

    if not run_async:
        _resilience.atomic_save(
            f"{prefix}-{epoch:04d}.params",
            lambda p: nd.save(p, save_dict))
        return
    import atexit

    from . import engine as _engine

    eng = _engine.get_engine()
    if not _ckpt_vars:
        # never lose an in-flight checkpoint at interpreter exit
        atexit.register(wait_checkpoints)
    if prefix not in _ckpt_vars:
        _ckpt_vars[prefix] = eng.new_variable()
    # snapshot to host now (device buffers may be donated/overwritten by the
    # next step); the file write itself happens on an engine worker
    host_dict = {k: v.asnumpy() if hasattr(v, "asnumpy") else v
                 for k, v in save_dict.items()}
    path = f"{prefix}-{epoch:04d}.params"

    eng.push(lambda: _resilience.atomic_save(
                 path, lambda p: nd.save(p, host_dict)),
             write_vars=[_ckpt_vars[prefix]])


def wait_checkpoints(prefix=None):
    """Block until async checkpoints finished (ref: Engine::WaitForVar).

    With a prefix, waits only for that prefix's writes (no-op if it never
    checkpointed asynchronously); otherwise waits for all of them.
    """
    from . import engine as _engine

    eng = _engine.get_engine()
    if prefix is not None:
        if prefix in _ckpt_vars:
            eng.wait_for_var(_ckpt_vars[prefix])
        return
    first_exc = None
    for v in _ckpt_vars.values():
        try:  # one failed prefix must not strand the others' writes
            eng.wait_for_var(v)
        except BaseException as e:
            first_exc = first_exc or e
    if first_exc is not None:
        raise first_exc


def load_params(prefix, epoch):
    from . import resilience as _resilience
    from . import telemetry as _telemetry

    _telemetry.log_event("model_load", prefix=str(prefix), epoch=int(epoch))
    path = f"{prefix}-{epoch:04d}.params"
    # a missing file raises FileNotFoundError from nd.load as before;
    # verification guards the EXISTING-but-torn case
    if os.path.isfile(path) and not _resilience.verify(path):
        raise OSError(
            f"checkpoint {path} failed checksum verification (torn or "
            "corrupted write); latest_valid_checkpoint(prefix) finds the "
            "newest epoch that still verifies")
    save_dict = nd.load(path)
    arg_params, aux_params = {}, {}
    for k, v in save_dict.items():
        tp, name = k.split(":", 1)
        if tp == "arg":
            arg_params[name] = v
        elif tp == "aux":
            aux_params[name] = v
    return arg_params, aux_params


def bootstrap_params(client, keys=None):
    """Elastic-join state transfer (docs/FAULT_TOLERANCE.md — Elastic
    membership): fetch the parameter server's key directory over the
    wire, each tensor verified against the server's
    sharded_checkpoint-format state manifest, and return {key: NDArray}
    ready to load into a freshly-admitted worker's Block/Module.
    Optimizer state lives ON the server in this mode, so parameters are
    the whole transfer; `client` is a ps.PSClient that already join()ed."""
    from . import telemetry as _telemetry

    raw = client.bootstrap(keys)
    _telemetry.log_event("model_bootstrap", keys=len(raw),
                         epoch=client.epoch)
    return {k: nd.array(v) for k, v in raw.items()}


def load_checkpoint(prefix, epoch):
    """(ref: model.py:424) -> (symbol, arg_params, aux_params)"""
    symbol = sym.load(f"{prefix}-symbol.json")
    arg_params, aux_params = load_params(prefix, epoch)
    return symbol, arg_params, aux_params


def latest_valid_checkpoint(prefix):
    """Newest epoch under `prefix` whose params file passes manifest
    verification, or None — the recovery entry point: after a crash,
    resume from this epoch and every torn/corrupt newer file is skipped.

    The walk-back is BOUNDED by MXTPU_CKPT_WALKBACK (0 = unbounded):
    many consecutive corrupt epochs usually mean a sick filesystem, not
    a torn tail — better to stop and say so than to silently resume
    from days-old weights. Every skipped epoch lands in the flight
    recorder."""
    from . import config as _config
    from . import resilience as _resilience
    from . import telemetry as _telemetry

    d = os.path.dirname(prefix) or "."
    pat = re.compile(re.escape(os.path.basename(prefix))
                     + r"-(\d{4,})\.params$")
    try:
        names = os.listdir(d)
    except OSError:
        return None
    epochs = sorted({int(m.group(1)) for n in names
                     if (m := pat.match(n))}, reverse=True)
    bound = max(0, int(_config.get("MXTPU_CKPT_WALKBACK")))
    for i, epoch in enumerate(epochs):
        if bound and i >= bound:
            logger.warning(
                "latest_valid_checkpoint: gave up after %d corrupt "
                "epochs under %s (MXTPU_CKPT_WALKBACK=%d); refusing to "
                "walk back further — inspect the checkpoint directory",
                bound, prefix, bound)
            _telemetry.log_event("ckpt_walkback_exhausted",
                                 prefix=str(prefix), bound=bound,
                                 newest=epochs[0])
            return None
        if _resilience.verify(f"{prefix}-{epoch:04d}.params"):
            return epoch
        logger.warning("latest_valid_checkpoint: epoch %d under %s "
                       "failed verification; walking back", epoch, prefix)
        _telemetry.log_event("ckpt_skipped", prefix=str(prefix),
                             epoch=int(epoch))
    return None


class FeedForward:
    """Legacy training API (ref: python/mxnet/model.py FeedForward) — thin
    wrapper over Module kept for reference-script compatibility."""

    def __init__(self, symbol, ctx=None, num_epoch=None, epoch_size=None,
                 optimizer="sgd", initializer=None, numpy_batch_size=128,
                 arg_params=None, aux_params=None, allow_extra_params=False,
                 begin_epoch=0, learning_rate=0.01, **kwargs):
        from .module import Module

        self._symbol = symbol
        self._ctx = ctx
        self._num_epoch = num_epoch
        self._optimizer = optimizer
        self._initializer = initializer
        self._arg_params = arg_params
        self._aux_params = aux_params
        self._begin_epoch = begin_epoch
        self._lr = learning_rate
        self._opt_kwargs = kwargs
        self._module = None

    def fit(self, X, y=None, eval_data=None, eval_metric="acc",
            epoch_end_callback=None, batch_end_callback=None, kvstore="local",
            logger=None, work_load_list=None, monitor=None,
            eval_end_callback=None, eval_batch_end_callback=None):
        from .module import Module
        from . import io as io_mod
        import numpy as _np

        if not isinstance(X, io_mod.DataIter):
            X = io_mod.NDArrayIter(X, y, batch_size=128, shuffle=True)
        self._module = Module(self._symbol, context=self._ctx)
        opt_params = {"learning_rate": self._lr}
        opt_params.update({k: v for k, v in self._opt_kwargs.items()
                           if k in ("momentum", "wd", "clip_gradient", "rescale_grad")})
        self._module.fit(
            X, eval_data=eval_data, eval_metric=eval_metric,
            epoch_end_callback=epoch_end_callback,
            batch_end_callback=batch_end_callback, kvstore=kvstore,
            optimizer=self._optimizer, optimizer_params=opt_params,
            initializer=self._initializer, arg_params=self._arg_params,
            aux_params=self._aux_params, begin_epoch=self._begin_epoch,
            num_epoch=self._num_epoch, monitor=monitor,
        )
        return self

    def predict(self, X, num_batch=None, return_data=False, reset=True):
        from . import io as io_mod

        if not isinstance(X, io_mod.DataIter):
            X = io_mod.NDArrayIter(X, batch_size=128)
        return self._module.predict(X, num_batch=num_batch, reset=reset).asnumpy()

    def score(self, X, eval_metric="acc", num_batch=None, **kwargs):
        return self._module.score(X, eval_metric, num_batch=num_batch)[0][1]

    def save(self, prefix, epoch=None):
        arg, aux = self._module.get_params()
        save_checkpoint(prefix, epoch if epoch is not None else self._num_epoch,
                        self._symbol, arg, aux)

    @staticmethod
    def load(prefix, epoch, ctx=None, **kwargs):
        symbol, arg_params, aux_params = load_checkpoint(prefix, epoch)
        return FeedForward(symbol, ctx=ctx, arg_params=arg_params,
                           aux_params=aux_params, begin_epoch=epoch, **kwargs)

    @staticmethod
    def resume(prefix, ctx=None, **kwargs):
        """Resume from the newest VERIFIED checkpoint under `prefix`:
        torn or corrupt epochs (crash mid-write) are skipped via their
        checksum manifests. Raises FileNotFoundError when no epoch
        verifies — resuming from garbage is never the right default."""
        epoch = latest_valid_checkpoint(prefix)
        if epoch is None:
            raise FileNotFoundError(
                f"no valid checkpoint found under prefix {prefix!r}")
        return FeedForward.load(prefix, epoch, ctx=ctx, **kwargs)
