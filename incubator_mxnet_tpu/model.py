"""Checkpointing + shared training helpers (ref: python/mxnet/model.py).

Checkpoint format: `prefix-symbol.json` (graph) + `prefix-%04d.params`
(NDArray container with arg:/aux: prefixed keys), exactly mirroring the
reference's save_checkpoint/load_checkpoint (model.py:394,424).
"""
from __future__ import annotations

import collections

from . import ndarray as nd
from . import symbol as sym

__all__ = ["BatchEndParam", "save_checkpoint", "load_checkpoint", "load_params"]

BatchEndParam = collections.namedtuple(
    "BatchEndParams", ["epoch", "nbatch", "eval_metric", "locals"]
)


def save_checkpoint(prefix, epoch, symbol, arg_params, aux_params, remove_amp_cast=True):
    """(ref: model.py:394)"""
    if symbol is not None:
        symbol.save(f"{prefix}-symbol.json")
    save_dict = {f"arg:{k}": v for k, v in arg_params.items()}
    save_dict.update({f"aux:{k}": v for k, v in aux_params.items()})
    nd.save(f"{prefix}-{epoch:04d}.params", save_dict)


def load_params(prefix, epoch):
    save_dict = nd.load(f"{prefix}-{epoch:04d}.params")
    arg_params, aux_params = {}, {}
    for k, v in save_dict.items():
        tp, name = k.split(":", 1)
        if tp == "arg":
            arg_params[name] = v
        elif tp == "aux":
            aux_params[name] = v
    return arg_params, aux_params


def load_checkpoint(prefix, epoch):
    """(ref: model.py:424) -> (symbol, arg_params, aux_params)"""
    symbol = sym.load(f"{prefix}-symbol.json")
    arg_params, aux_params = load_params(prefix, epoch)
    return symbol, arg_params, aux_params
