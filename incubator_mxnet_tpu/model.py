"""Checkpointing + shared training helpers (ref: python/mxnet/model.py).

Checkpoint format: `prefix-symbol.json` (graph) + `prefix-%04d.params`
(NDArray container with arg:/aux: prefixed keys), exactly mirroring the
reference's save_checkpoint/load_checkpoint (model.py:394,424).
"""
from __future__ import annotations

import collections

from . import ndarray as nd
from . import symbol as sym

__all__ = ["BatchEndParam", "save_checkpoint", "load_checkpoint", "load_params"]

BatchEndParam = collections.namedtuple(
    "BatchEndParams", ["epoch", "nbatch", "eval_metric", "locals"]
)


def save_checkpoint(prefix, epoch, symbol, arg_params, aux_params, remove_amp_cast=True):
    """(ref: model.py:394)"""
    if symbol is not None:
        symbol.save(f"{prefix}-symbol.json")
    save_dict = {f"arg:{k}": v for k, v in arg_params.items()}
    save_dict.update({f"aux:{k}": v for k, v in aux_params.items()})
    nd.save(f"{prefix}-{epoch:04d}.params", save_dict)


def load_params(prefix, epoch):
    save_dict = nd.load(f"{prefix}-{epoch:04d}.params")
    arg_params, aux_params = {}, {}
    for k, v in save_dict.items():
        tp, name = k.split(":", 1)
        if tp == "arg":
            arg_params[name] = v
        elif tp == "aux":
            aux_params[name] = v
    return arg_params, aux_params


def load_checkpoint(prefix, epoch):
    """(ref: model.py:424) -> (symbol, arg_params, aux_params)"""
    symbol = sym.load(f"{prefix}-symbol.json")
    arg_params, aux_params = load_params(prefix, epoch)
    return symbol, arg_params, aux_params


class FeedForward:
    """Legacy training API (ref: python/mxnet/model.py FeedForward) — thin
    wrapper over Module kept for reference-script compatibility."""

    def __init__(self, symbol, ctx=None, num_epoch=None, epoch_size=None,
                 optimizer="sgd", initializer=None, numpy_batch_size=128,
                 arg_params=None, aux_params=None, allow_extra_params=False,
                 begin_epoch=0, learning_rate=0.01, **kwargs):
        from .module import Module

        self._symbol = symbol
        self._ctx = ctx
        self._num_epoch = num_epoch
        self._optimizer = optimizer
        self._initializer = initializer
        self._arg_params = arg_params
        self._aux_params = aux_params
        self._begin_epoch = begin_epoch
        self._lr = learning_rate
        self._opt_kwargs = kwargs
        self._module = None

    def fit(self, X, y=None, eval_data=None, eval_metric="acc",
            epoch_end_callback=None, batch_end_callback=None, kvstore="local",
            logger=None, work_load_list=None, monitor=None,
            eval_end_callback=None, eval_batch_end_callback=None):
        from .module import Module
        from . import io as io_mod
        import numpy as _np

        if not isinstance(X, io_mod.DataIter):
            X = io_mod.NDArrayIter(X, y, batch_size=128, shuffle=True)
        self._module = Module(self._symbol, context=self._ctx)
        opt_params = {"learning_rate": self._lr}
        opt_params.update({k: v for k, v in self._opt_kwargs.items()
                           if k in ("momentum", "wd", "clip_gradient", "rescale_grad")})
        self._module.fit(
            X, eval_data=eval_data, eval_metric=eval_metric,
            epoch_end_callback=epoch_end_callback,
            batch_end_callback=batch_end_callback, kvstore=kvstore,
            optimizer=self._optimizer, optimizer_params=opt_params,
            initializer=self._initializer, arg_params=self._arg_params,
            aux_params=self._aux_params, begin_epoch=self._begin_epoch,
            num_epoch=self._num_epoch, monitor=monitor,
        )
        return self

    def predict(self, X, num_batch=None, return_data=False, reset=True):
        from . import io as io_mod

        if not isinstance(X, io_mod.DataIter):
            X = io_mod.NDArrayIter(X, batch_size=128)
        return self._module.predict(X, num_batch=num_batch, reset=reset).asnumpy()

    def score(self, X, eval_metric="acc", num_batch=None, **kwargs):
        return self._module.score(X, eval_metric, num_batch=num_batch)[0][1]

    def save(self, prefix, epoch=None):
        arg, aux = self._module.get_params()
        save_checkpoint(prefix, epoch if epoch is not None else self._num_epoch,
                        self._symbol, arg, aux)

    @staticmethod
    def load(prefix, epoch, ctx=None, **kwargs):
        symbol, arg_params, aux_params = load_checkpoint(prefix, epoch)
        return FeedForward(symbol, ctx=ctx, arg_params=arg_params,
                           aux_params=aux_params, begin_epoch=epoch, **kwargs)
