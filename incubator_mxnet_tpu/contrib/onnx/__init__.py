"""ONNX exchange (ref: python/mxnet/contrib/onnx/ — mx2onnx export driver
`export_model` and onnx2mx `import_model`).

Gated on the `onnx` package (not bundled in this environment); the mapping
layer itself is real and covered by the serializer-independent graph walk.
For TPU-native deployment the first-class path is `incubator_mxnet_tpu.deploy`
(AOT StableHLO artifacts — XLA is the inference engine); ONNX here serves
interop with third-party runtimes, same as the reference.
"""
from .mx2onnx import export_model  # noqa: F401
from .onnx2mx import import_model  # noqa: F401
