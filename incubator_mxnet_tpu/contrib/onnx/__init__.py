"""ONNX exchange (ref: python/mxnet/contrib/onnx/ — mx2onnx export driver
`export_model` and onnx2mx `import_model`).

Self-contained: `proto.py` implements the ONNX protobuf wire format
directly, so import AND export work without the `onnx` package and the
emitted files are standard ONNX. For TPU-native deployment the first-class
path is `incubator_mxnet_tpu.deploy` (AOT StableHLO artifacts — XLA is the
inference engine); ONNX here serves interop with third-party runtimes,
same as the reference.
"""
from . import proto  # noqa: F401
from .mx2onnx import export_model  # noqa: F401
from .onnx2mx import import_model  # noqa: F401
