"""Self-contained ONNX protobuf wire-format codec
(ref: python/mxnet/contrib/onnx relies on the `onnx` package; this
environment has none, so the exchange format is read/written directly —
the ONNX schema below mirrors onnx/onnx.proto, which is stable public
wire format).

Only the message subset ONNX models actually use is modeled:
ModelProto / GraphProto / NodeProto / AttributeProto / TensorProto /
ValueInfoProto / TypeProto(.Tensor) / TensorShapeProto / OperatorSetId.
The decoder skips unknown fields (forward-compatible); repeated scalars
accept both packed and unpacked encodings, and the encoder emits packed
(proto3 default), so files interoperate with the official `onnx` package
byte-for-byte.
"""
from __future__ import annotations

import struct

# wire types
_VARINT, _I64, _LEN, _I32 = 0, 1, 2, 5

# field kinds
INT, FLOAT, DOUBLE, BYTES, STRING, MSG = range(6)

_F32 = struct.Struct("<f")
_F64 = struct.Struct("<d")


def _enc_varint(v):
    v &= (1 << 64) - 1  # two's-complement for negatives, per protobuf
    out = bytearray()
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _dec_varint(buf, pos):
    result = shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            break
        shift += 7
    if result >= 1 << 63:  # negative int64
        result -= 1 << 64
    return result, pos


def _tag(num, wt):
    return _enc_varint((num << 3) | wt)


def _skip(buf, pos, wt):
    if wt == _VARINT:
        return _dec_varint(buf, pos)[1]
    if wt == _I64:
        return pos + 8
    if wt == _LEN:
        n, pos = _dec_varint(buf, pos)
        return pos + n
    if wt == _I32:
        return pos + 4
    raise ValueError(f"unsupported wire type {wt}")


class Message:
    """Base: subclasses define FIELDS = {num: (name, kind, repeated[, cls])}."""

    FIELDS: dict = {}

    def __init__(self, **kwargs):
        for num, spec in self.FIELDS.items():
            name, kind, repeated = spec[0], spec[1], spec[2]
            default = [] if repeated else (
                0 if kind == INT else
                0.0 if kind in (FLOAT, DOUBLE) else
                b"" if kind == BYTES else
                "" if kind == STRING else None)
            setattr(self, name, kwargs.pop(name, default))
        if kwargs:
            raise TypeError(f"unknown fields {sorted(kwargs)}")

    # --- encode -----------------------------------------------------------
    def to_bytes(self):
        out = bytearray()
        for num, spec in sorted(self.FIELDS.items()):
            name, kind, repeated = spec[0], spec[1], spec[2]
            val = getattr(self, name)
            if repeated:
                if not val:
                    continue
                if kind == INT:  # packed
                    payload = b"".join(_enc_varint(int(v)) for v in val)
                    out += _tag(num, _LEN) + _enc_varint(len(payload)) + payload
                elif kind == FLOAT:
                    payload = b"".join(_F32.pack(float(v)) for v in val)
                    out += _tag(num, _LEN) + _enc_varint(len(payload)) + payload
                elif kind == DOUBLE:
                    payload = b"".join(_F64.pack(float(v)) for v in val)
                    out += _tag(num, _LEN) + _enc_varint(len(payload)) + payload
                elif kind in (BYTES, STRING):
                    for v in val:
                        b = v.encode() if isinstance(v, str) else bytes(v)
                        out += _tag(num, _LEN) + _enc_varint(len(b)) + b
                elif kind == MSG:
                    for v in val:
                        b = v.to_bytes()
                        out += _tag(num, _LEN) + _enc_varint(len(b)) + b
                continue
            if kind == INT:
                if val:
                    out += _tag(num, _VARINT) + _enc_varint(int(val))
            elif kind == FLOAT:
                if val:
                    out += _tag(num, _I32) + _F32.pack(float(val))
            elif kind == DOUBLE:
                if val:
                    out += _tag(num, _I64) + _F64.pack(float(val))
            elif kind in (BYTES, STRING):
                b = val.encode() if isinstance(val, str) else bytes(val)
                if b:
                    out += _tag(num, _LEN) + _enc_varint(len(b)) + b
            elif kind == MSG:
                if val is not None:
                    b = val.to_bytes()
                    out += _tag(num, _LEN) + _enc_varint(len(b)) + b
        return bytes(out)

    # --- decode -----------------------------------------------------------
    @classmethod
    def from_bytes(cls, buf):
        self = cls()
        pos, end = 0, len(buf)
        while pos < end:
            key, pos = _dec_varint(buf, pos)
            num, wt = key >> 3, key & 0x7
            spec = cls.FIELDS.get(num)
            if spec is None:
                pos = _skip(buf, pos, wt)
                continue
            name, kind, repeated = spec[0], spec[1], spec[2]
            if kind == MSG:
                n, pos = _dec_varint(buf, pos)
                sub = spec[3].from_bytes(bytes(buf[pos:pos + n]))
                pos += n
                if repeated:
                    getattr(self, name).append(sub)
                else:
                    setattr(self, name, sub)
            elif kind in (BYTES, STRING):
                n, pos = _dec_varint(buf, pos)
                raw = bytes(buf[pos:pos + n])
                pos += n
                v = raw.decode("utf-8", "surrogateescape") if kind == STRING else raw
                if repeated:
                    getattr(self, name).append(v)
                else:
                    setattr(self, name, v)
            elif kind == INT:
                if wt == _LEN:  # packed
                    n, pos = _dec_varint(buf, pos)
                    stop = pos + n
                    lst = getattr(self, name)
                    while pos < stop:
                        v, pos = _dec_varint(buf, pos)
                        lst.append(v)
                else:
                    v, pos = _dec_varint(buf, pos)
                    if repeated:
                        getattr(self, name).append(v)
                    else:
                        setattr(self, name, v)
            elif kind == FLOAT:
                if wt == _LEN:
                    n, pos = _dec_varint(buf, pos)
                    stop = pos + n
                    lst = getattr(self, name)
                    while pos < stop:
                        lst.append(_F32.unpack_from(buf, pos)[0])
                        pos += 4
                else:
                    v = _F32.unpack_from(buf, pos)[0]
                    pos += 4
                    if repeated:
                        getattr(self, name).append(v)
                    else:
                        setattr(self, name, v)
            elif kind == DOUBLE:
                if wt == _LEN:
                    n, pos = _dec_varint(buf, pos)
                    stop = pos + n
                    lst = getattr(self, name)
                    while pos < stop:
                        lst.append(_F64.unpack_from(buf, pos)[0])
                        pos += 8
                else:
                    v = _F64.unpack_from(buf, pos)[0]
                    pos += 8
                    if repeated:
                        getattr(self, name).append(v)
                    else:
                        setattr(self, name, v)
        return self

    def __repr__(self):
        parts = []
        for spec in self.FIELDS.values():
            v = getattr(self, spec[0])
            if v not in (None, [], "", b"", 0, 0.0):
                parts.append(f"{spec[0]}={v!r}")
        return f"{type(self).__name__}({', '.join(parts)})"


# --- ONNX data-type enum (TensorProto.DataType) ---------------------------
class DataType:
    FLOAT = 1
    UINT8 = 2
    INT8 = 3
    UINT16 = 4
    INT16 = 5
    INT32 = 6
    INT64 = 7
    STRING = 8
    BOOL = 9
    FLOAT16 = 10
    DOUBLE = 11
    UINT32 = 12
    UINT64 = 13
    BFLOAT16 = 16


class AttrType:
    FLOAT = 1
    INT = 2
    STRING = 3
    TENSOR = 4
    GRAPH = 5
    FLOATS = 6
    INTS = 7
    STRINGS = 8
    TENSORS = 9
    GRAPHS = 10


class TensorProto(Message):
    FIELDS = {
        1: ("dims", INT, True),
        2: ("data_type", INT, False),
        4: ("float_data", FLOAT, True),
        5: ("int32_data", INT, True),
        6: ("string_data", BYTES, True),
        7: ("int64_data", INT, True),
        8: ("name", STRING, False),
        9: ("raw_data", BYTES, False),
        10: ("double_data", DOUBLE, True),
        11: ("uint64_data", INT, True),
    }


class TensorShapeDim(Message):
    FIELDS = {
        1: ("dim_value", INT, False),
        2: ("dim_param", STRING, False),
    }


class TensorShapeProto(Message):
    FIELDS = {1: ("dim", MSG, True, TensorShapeDim)}


class TypeProtoTensor(Message):
    FIELDS = {
        1: ("elem_type", INT, False),
        2: ("shape", MSG, False, TensorShapeProto),
    }


class TypeProto(Message):
    FIELDS = {1: ("tensor_type", MSG, False, TypeProtoTensor)}


class ValueInfoProto(Message):
    FIELDS = {
        1: ("name", STRING, False),
        2: ("type", MSG, False, TypeProto),
        3: ("doc_string", STRING, False),
    }


class AttributeProto(Message):
    FIELDS = {
        1: ("name", STRING, False),
        2: ("f", FLOAT, False),
        3: ("i", INT, False),
        4: ("s", BYTES, False),
        5: ("t", MSG, False, TensorProto),
        7: ("floats", FLOAT, True),
        8: ("ints", INT, True),
        9: ("strings", BYTES, True),
        10: ("tensors", MSG, True, TensorProto),
        20: ("type", INT, False),
    }

    # mirror the tiny surface onnx2mx reads (a.INT etc.)
    INT = AttrType.INT
    FLOAT = AttrType.FLOAT
    STRING = AttrType.STRING
    INTS = AttrType.INTS
    FLOATS = AttrType.FLOATS
    TENSOR = AttrType.TENSOR


class NodeProto(Message):
    FIELDS = {
        1: ("input", STRING, True),
        2: ("output", STRING, True),
        3: ("name", STRING, False),
        4: ("op_type", STRING, False),
        5: ("attribute", MSG, True, AttributeProto),
        6: ("doc_string", STRING, False),
        7: ("domain", STRING, False),
    }


class GraphProto(Message):
    FIELDS = {
        1: ("node", MSG, True, NodeProto),
        2: ("name", STRING, False),
        5: ("initializer", MSG, True, TensorProto),
        10: ("doc_string", STRING, False),
        11: ("input", MSG, True, ValueInfoProto),
        12: ("output", MSG, True, ValueInfoProto),
        13: ("value_info", MSG, True, ValueInfoProto),
    }


class OperatorSetId(Message):
    FIELDS = {
        1: ("domain", STRING, False),
        2: ("version", INT, False),
    }


class ModelProto(Message):
    FIELDS = {
        1: ("ir_version", INT, False),
        2: ("producer_name", STRING, False),
        3: ("producer_version", STRING, False),
        4: ("domain", STRING, False),
        5: ("model_version", INT, False),
        6: ("doc_string", STRING, False),
        7: ("graph", MSG, False, GraphProto),
        8: ("opset_import", MSG, True, OperatorSetId),
    }


# --- numpy bridge (the numpy_helper role) ---------------------------------
_NP2ONNX = {
    "float32": DataType.FLOAT,
    "float64": DataType.DOUBLE,
    "float16": DataType.FLOAT16,
    "bfloat16": DataType.BFLOAT16,
    "int64": DataType.INT64,
    "int32": DataType.INT32,
    "int8": DataType.INT8,
    "uint8": DataType.UINT8,
    "bool": DataType.BOOL,
}
_ONNX2NP = {
    DataType.FLOAT: "float32",
    DataType.DOUBLE: "float64",
    DataType.FLOAT16: "float16",
    DataType.INT64: "int64",
    DataType.INT32: "int32",
    DataType.INT8: "int8",
    DataType.UINT8: "uint8",
    DataType.BOOL: "bool",
}


def from_array(arr, name=""):
    """np.ndarray -> TensorProto with raw_data (numpy_helper.from_array)."""
    import numpy as np

    arr = np.ascontiguousarray(arr)
    key = str(arr.dtype)
    if key not in _NP2ONNX:
        raise TypeError(f"unsupported dtype for ONNX export: {arr.dtype}")
    return TensorProto(dims=list(arr.shape), data_type=_NP2ONNX[key],
                       raw_data=arr.tobytes(), name=name)


def to_array(tensor):
    """TensorProto -> np.ndarray (numpy_helper.to_array)."""
    import numpy as np

    if tensor.data_type == DataType.BFLOAT16:
        import ml_dtypes

        dt = np.dtype(ml_dtypes.bfloat16)
    else:
        dt = np.dtype(_ONNX2NP[tensor.data_type])
    shape = tuple(tensor.dims)
    if tensor.raw_data:
        return np.frombuffer(tensor.raw_data, dtype=dt).reshape(shape).copy()
    if tensor.data_type == DataType.FLOAT and tensor.float_data:
        return np.asarray(tensor.float_data, np.float32).reshape(shape)
    if tensor.data_type == DataType.DOUBLE and tensor.double_data:
        return np.asarray(tensor.double_data, np.float64).reshape(shape)
    if tensor.data_type == DataType.INT64 and tensor.int64_data:
        return np.asarray(tensor.int64_data, np.int64).reshape(shape)
    if tensor.int32_data:
        if tensor.data_type in (DataType.FLOAT16, DataType.BFLOAT16):
            # spec: half-precision values travel as uint16 BIT PATTERNS
            bits = np.asarray(tensor.int32_data, np.int32).astype(np.uint16)
            return bits.view(dt).reshape(shape).copy()
        return np.asarray(tensor.int32_data, np.int32).astype(dt).reshape(shape)
    return np.zeros(shape, dt)


def load_model(path_or_bytes):
    """onnx.load analog."""
    if isinstance(path_or_bytes, (bytes, bytearray)):
        return ModelProto.from_bytes(bytes(path_or_bytes))
    with open(path_or_bytes, "rb") as f:
        return ModelProto.from_bytes(f.read())


def save_model(model, path):
    with open(path, "wb") as f:
        f.write(model.to_bytes())
