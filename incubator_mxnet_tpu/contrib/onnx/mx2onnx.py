"""Symbol -> ONNX export
(ref: python/mxnet/contrib/onnx/mx2onnx/export_model.py + _op_translations.py).

The graph walk + per-op translation tables are serializer-independent; only
the final protobuf assembly needs the `onnx` package.
"""
from __future__ import annotations

import numpy as np

__all__ = ["export_model", "ONNX_OP_MAP"]

# op-name -> (onnx_op_type, attr translator). Attr translators take the
# node's registry attrs and return ONNX attribute dicts (ref:
# mx2onnx/_op_translations.py one function per op).


def _tup(v, n, default):
    """Normalize a scalar-or-sequence attr to an n-list (MXNet accepts
    kernel=3 and kernel=(3,3) interchangeably)."""
    if v is None:
        return [default] * n
    if isinstance(v, (int, np.integer)):
        return [int(v)] * n
    return [int(x) for x in v]


def _conv_attrs(a):
    k = a.get("kernel")
    # scalar kernel means square 2-D (the layers always pass tuples; the
    # scalar spelling only appears in hand-built symbols) — same policy
    # as _pool_attrs so the two never disagree
    k = [int(k)] * 2 if isinstance(k, (int, np.integer)) else list(k)
    n = len(k)
    return {
        "kernel_shape": k,
        "strides": _tup(a.get("stride"), n, 1),
        "pads": _tup(a.get("pad"), n, 0) * 2,
        "dilations": _tup(a.get("dilate"), n, 1),
        "group": int(a.get("num_group", 1)),
    }


def _pool_attrs(a):
    k = a.get("kernel", (2, 2))
    k = [int(k)] * 2 if isinstance(k, (int, np.integer)) else list(k)
    n = len(k)
    return {
        "kernel_shape": k,
        "strides": _tup(a.get("stride"), n, 1),
        "pads": _tup(a.get("pad"), n, 0) * 2,
    }


def _slice_attrs(a):
    if any(int(s) != 1 for s in (a.get("step") or ()) if s is not None):
        raise NotImplementedError(
            "ONNX export: slice with step != 1 (Slice-9 has no steps)")
    return {
        "starts": [0 if v is None else int(v) for v in a["begin"]],
        "ends": [2**31 - 1 if v is None else int(v) for v in a["end"]],
    }


def _squeeze_attrs(a):
    ax = a.get("axis")
    if ax is None:
        return {}
    return {"axes": [int(ax)] if isinstance(ax, (int, np.integer))
            else [int(v) for v in ax]}


def _deconv_attrs_fwd(a):
    out = _conv_attrs(a)
    adj = a.get("adj")
    if adj:
        out["output_padding"] = _tup(adj, len(out["kernel_shape"]), 0)
    return out


def _reduce_attrs(a):
    ax = a.get("axis")
    out = {"keepdims": int(bool(a.get("keepdims", False)))}
    if ax is not None:
        out["axes"] = [int(ax)] if isinstance(ax, int) else [int(v) for v in ax]
    return out


ONNX_OP_MAP = {
    "Convolution": ("Conv", _conv_attrs),
    "FullyConnected": ("Gemm", lambda a: {"transB": 1}),
    "Activation": (None, None),  # dispatched by act_type below
    "BatchNorm": ("BatchNormalization",
                  lambda a: {"epsilon": float(a.get("eps", 1e-3)),
                             "momentum": float(a.get("momentum", 0.9))}),
    "Pooling": (None, None),  # max/avg dispatch below
    "Flatten": ("Flatten", lambda a: {"axis": 1}),
    "softmax": ("Softmax", lambda a: {"axis": int(a.get("axis", -1))}),
    "SoftmaxOutput": ("Softmax", lambda a: {"axis": -1}),
    "Concat": ("Concat", lambda a: {"axis": int(a.get("dim", 1))}),
    "Reshape": ("Reshape", lambda a: {}),  # shape initializer added in walk
    "transpose": ("Transpose", lambda a: {"perm": list(a["axes"])}
                  if a.get("axes") else {}),
    "Dropout": ("Dropout", lambda a: {"ratio": float(a.get("p", 0.5))}),
    "LeakyReLU": ("LeakyRelu", lambda a: {"alpha": float(a.get("slope", 0.25))}),
    "elemwise_add": ("Add", lambda a: {}),
    "broadcast_add": ("Add", lambda a: {}),
    "elemwise_mul": ("Mul", lambda a: {}),
    "broadcast_mul": ("Mul", lambda a: {}),
    "elemwise_sub": ("Sub", lambda a: {}),
    "dot": ("MatMul", lambda a: {}),
    "LayerNorm": ("LayerNormalization",
                  lambda a: {"epsilon": float(a.get("eps", 1e-5)),
                             "axis": int(a.get("axis", -1))}),
    "relu": ("Relu", lambda a: {}),
    "sigmoid": ("Sigmoid", lambda a: {}),
    "tanh": ("Tanh", lambda a: {}),
    "exp": ("Exp", lambda a: {}),
    "log": ("Log", lambda a: {}),
    "sqrt": ("Sqrt", lambda a: {}),
    "negative": ("Neg", lambda a: {}),
    # MXNet pad_width interleaves (b0,e0,b1,e1,...); ONNX pads groups all
    # begins then all ends
    "Pad": ("Pad", lambda a: {
        "mode": a.get("mode", "constant"),
        "value": float(a.get("constant_value") or 0.0),
        "pads": (list(a["pad_width"][0::2]) + list(a["pad_width"][1::2]))
        if a.get("pad_width") else []}),
    # Gather's ONNX input order is (table, indices); Embedding's is
    # (indices, weight) — reordered in graph_to_onnx_nodes
    "Embedding": ("Gather", lambda a: {}),
    # attribute forms valid at the emitted opset (8): Clip(min,max),
    # Slice(axes,starts,ends), Upsample(scales)
    "clip": ("Clip", lambda a: {"min": float(a["a_min"]),
                                "max": float(a["a_max"])}),
    "slice_axis": ("Slice", lambda a: {"axes": [int(a["axis"])],
                                       "starts": [int(a["begin"])],
                                       "ends": [int(a["end"]) if a.get("end")
                                                is not None else 2**31 - 1]}),
    "mean": ("ReduceMean", _reduce_attrs),
    "sum": ("ReduceSum", _reduce_attrs),
    "max": ("ReduceMax", _reduce_attrs),
    "min": ("ReduceMin", _reduce_attrs),
    "prod": ("ReduceProd", _reduce_attrs),
    # ---- round-3 tail: toward the reference's ~97 translations ----
    "abs": ("Abs", lambda a: {}),
    "ceil": ("Ceil", lambda a: {}),
    "floor": ("Floor", lambda a: {}),
    "sign": ("Sign", lambda a: {}),
    "erf": ("Erf", lambda a: {}),
    "reciprocal": ("Reciprocal", lambda a: {}),
    "identity": ("Identity", lambda a: {}),
    "_copy": ("Identity", lambda a: {}),
    "BlockGrad": ("Identity", lambda a: {}),
    "stop_gradient": ("Identity", lambda a: {}),
    "sin": ("Sin", lambda a: {}),
    "cos": ("Cos", lambda a: {}),
    "tan": ("Tan", lambda a: {}),
    "arcsin": ("Asin", lambda a: {}),
    "arccos": ("Acos", lambda a: {}),
    "arctan": ("Atan", lambda a: {}),
    "sinh": ("Sinh", lambda a: {}),
    "cosh": ("Cosh", lambda a: {}),
    "arcsinh": ("Asinh", lambda a: {}),
    "arccosh": ("Acosh", lambda a: {}),
    "arctanh": ("Atanh", lambda a: {}),
    "softsign": ("Softsign", lambda a: {}),
    "elemwise_div": ("Div", lambda a: {}),
    "broadcast_div": ("Div", lambda a: {}),
    "_div": ("Div", lambda a: {}),
    "_mul": ("Mul", lambda a: {}),
    "_plus": ("Add", lambda a: {}),
    "_add": ("Add", lambda a: {}),
    "_sub": ("Sub", lambda a: {}),
    "_minus": ("Sub", lambda a: {}),
    "broadcast_sub": ("Sub", lambda a: {}),
    "pow": ("Pow", lambda a: {}),
    "_power": ("Pow", lambda a: {}),
    "broadcast_power": ("Pow", lambda a: {}),
    "maximum": ("Max", lambda a: {}),
    "_maximum": ("Max", lambda a: {}),
    "broadcast_maximum": ("Max", lambda a: {}),
    "minimum": ("Min", lambda a: {}),
    "_minimum": ("Min", lambda a: {}),
    "broadcast_minimum": ("Min", lambda a: {}),
    "add_n": ("Sum", lambda a: {}),
    "ElementWiseSum": ("Sum", lambda a: {}),
    "batch_dot": ("MatMul", lambda a: {}),
    "expand_dims": ("Unsqueeze", lambda a: {"axes": [int(a["axis"])]}),
    "squeeze": ("Squeeze", _squeeze_attrs),
    "log_softmax": ("LogSoftmax", lambda a: {"axis": int(a.get("axis", -1))}),
    "argmax": ("ArgMax", lambda a: {"axis": int(a.get("axis", 0) or 0),
                                    "keepdims": int(bool(a.get("keepdims",
                                                               False)))}),
    "argmin": ("ArgMin", lambda a: {"axis": int(a.get("axis", 0) or 0),
                                    "keepdims": int(bool(a.get("keepdims",
                                                               False)))}),
    "hard_sigmoid": ("HardSigmoid",
                     lambda a: {"alpha": float(a.get("alpha", 0.2)),
                                "beta": float(a.get("beta", 0.5))}),
    "where": ("Where", lambda a: {}),
    "LRN": ("LRN", lambda a: {"alpha": float(a.get("alpha", 1e-4)),
                              "beta": float(a.get("beta", 0.75)),
                              "bias": float(a.get("knorm", 2.0)),
                              "size": int(a.get("nsize", 5))}),
    "InstanceNorm": ("InstanceNormalization",
                     lambda a: {"epsilon": float(a.get("eps", 1e-3))}),
    "Deconvolution": ("ConvTranspose", _deconv_attrs_fwd),
    "depth_to_space": ("DepthToSpace",
                       lambda a: {"blocksize": int(a["block_size"])}),
    "space_to_depth": ("SpaceToDepth",
                       lambda a: {"blocksize": int(a["block_size"])}),
    "SliceChannel": ("Split", None),     # special-cased (num_outputs)
    "split": ("Split", None),            # special-cased
    "tile": ("Tile", None),              # repeats is a tensor input
    "square": ("Mul", None),             # x*x: special-cased
    "zeros_like": (None, None),          # Shape+ConstantOfShape: special
    "Cast": ("Cast", None),              # dtype -> ONNX enum: special-cased
    "cast": ("Cast", None),
    "slice": ("Slice", _slice_attrs),
    "take": ("Gather", lambda a: {"axis": int(a.get("axis", 0))}),
    "flatten": ("Flatten", lambda a: {"axis": 1}),
    "reshape": ("Reshape", lambda a: {}),
    "concat": ("Concat", lambda a: {"axis": int(a.get("dim", 1))}),
}

# tensor-scalar ops: ONNX binary op + a scalar initializer input
# (True = scalar comes first, the _r* reversed variants)
_SCALAR_BINOPS = {
    "_plus_scalar": ("Add", False), "_minus_scalar": ("Sub", False),
    "_rminus_scalar": ("Sub", True), "_mul_scalar": ("Mul", False),
    "_div_scalar": ("Div", False), "_rdiv_scalar": ("Div", True),
    "_power_scalar": ("Pow", False), "_rpower_scalar": ("Pow", True),
    "_maximum_scalar": ("Max", False), "_minimum_scalar": ("Min", False),
}

# mx dtype string -> ONNX TensorProto.DataType enum (for Cast's `to`)
_ONNX_DTYPE_ENUM = {"float32": 1, "uint8": 2, "int8": 3, "uint16": 4,
                    "int16": 5, "int32": 6, "int64": 7, "bool": 9,
                    "float16": 10, "float64": 11, "uint32": 12,
                    "uint64": 13, "bfloat16": 16}

_OPSET = 9  # attribute forms above are all legal at 9 (Slice moves its
            # params to inputs at 10, Clip/Pad at 11, ReduceSum at 13);
            # 9 admits Sign/Erf/Where/Sinh/Asinh, and Upsample-9's
            # scales-as-input form is emitted accordingly

_ACT_MAP = {"relu": "Relu", "sigmoid": "Sigmoid", "tanh": "Tanh",
            "softrelu": "Softplus", "softsign": "Softsign"}
_POOL_MAP = {"max": "MaxPool", "avg": "AveragePool"}


def graph_to_onnx_nodes(symbol):
    """Walk the symbol graph into (op_type, inputs, outputs, attrs, name,
    const_inputs) tuples — the serializer-independent core of the exporter.
    const_inputs maps extra input names to numpy arrays that the serializer
    must materialize as initializers (e.g. Reshape's target shape)."""
    nodes = []
    for node in symbol._topo_nodes():
        if node.is_var:
            continue
        op = node.op.name
        attrs = dict(node.attrs)
        consts = {}
        if op == "Activation":
            ot, oattrs = _ACT_MAP[attrs.get("act_type", "relu")], {}
        elif op in ("SliceChannel", "split"):
            if attrs.get("squeeze_axis"):
                raise NotImplementedError(
                    "ONNX export: split with squeeze_axis=True")
            ot, oattrs = "Split", {"axis": int(attrs.get("axis", 1))}
        elif op in _SCALAR_BINOPS:
            ot, oattrs = _SCALAR_BINOPS[op][0], {}
            # the exporter declares float32 graphs (export_model
            # input_type default); a non-f32 graph would need the scalar
            # to inherit its tensor's dtype, which the symbol layer does
            # not carry statically
            consts[f"{node.name}_scalar"] = np.asarray(
                float(attrs.get("scalar", 0.0)), np.float32)
        elif op == "tile":
            ot, oattrs = "Tile", {}
            reps_name = f"{node.name}_repeats"
            consts[reps_name] = np.asarray(attrs["reps"], np.int64)
        elif op == "square":
            ot, oattrs = "Mul", {}  # x*x (input doubled below)
        elif op == "zeros_like":
            # Shape -> ConstantOfShape(0): exact even for inf/NaN inputs
            # (x-x or x*0 would yield NaN there); emitted as TWO nodes
            in0 = [src.name if src.is_var else f"{src.name}_out{idx}"
                   for src, idx in node.inputs][0]
            shp = f"{node.name}_shape_out0"
            nodes.append(("Shape", [in0], [shp], {},
                          f"{node.name}_shape", {}))
            nodes.append(("ConstantOfShape", [shp],
                          [f"{node.name}_out0"],
                          {"value": np.zeros(1, np.float32)},
                          node.name, {}))
            continue
        elif op in ("Cast", "cast", "amp_cast"):
            dt = str(attrs.get("dtype", "float32"))
            if dt not in _ONNX_DTYPE_ENUM:
                raise NotImplementedError(f"ONNX export: Cast to {dt}")
            ot, oattrs = "Cast", {"to": _ONNX_DTYPE_ENUM[dt]}
        elif op == "UpSampling":
            # Upsample-9: scales is a tensor input, not an attribute
            ot = "Upsample"
            oattrs = {"mode": "nearest"
                      if attrs.get("sample_type", "nearest") == "nearest"
                      else "linear"}
            sc = float(attrs["scale"])
            consts[f"{node.name}_scales"] = np.asarray(
                [1.0, 1.0, sc, sc], np.float32)
        elif op == "Pooling":
            if attrs.get("global_pool"):
                ot = ("GlobalMaxPool" if attrs.get("pool_type", "max") == "max"
                      else "GlobalAveragePool")
                oattrs = {}
            else:
                ot = _POOL_MAP[attrs.get("pool_type", "max")]
                oattrs = _pool_attrs(attrs)
        elif op in ONNX_OP_MAP and ONNX_OP_MAP[op][0] is not None:
            ot, tr = ONNX_OP_MAP[op]
            oattrs = tr(attrs)
        else:
            raise NotImplementedError(
                f"ONNX export: no translation for op '{op}' "
                f"(ref mapping table: mx2onnx/_op_translations.py)")
        in_names = [src.name if src.is_var else f"{src.name}_out{idx}"
                    for src, idx in node.inputs]
        if op in ("Convolution", "Deconvolution", "FullyConnected"):
            # a bias input the op ignores (no_bias) must not be exported —
            # ONNX Conv/ConvTranspose/Gemm would apply it
            nb = attrs.get("no_bias", op == "Deconvolution")
            if nb is True or str(nb).lower() in ("true", "1"):
                in_names = in_names[:2]
                if op == "FullyConnected":
                    # Gemm's C input is mandatory until opset 11: stand in
                    # a zero bias initializer
                    zb = f"{node.name}_zero_bias"
                    consts[zb] = np.zeros(int(attrs["num_hidden"]),
                                          np.float32)
                    in_names.append(zb)
        if op == "Embedding":  # ONNX Gather is (table, indices)
            in_names = [in_names[1], in_names[0]]
        elif op == "SoftmaxOutput":  # label input has no ONNX counterpart
            in_names = in_names[:1]
        elif op in ("Reshape", "reshape"):  # shape is an input at opset>=5
            shape_name = f"{node.name}_shape"
            consts[shape_name] = np.asarray(attrs["shape"], np.int64)
            in_names = in_names[:1] + [shape_name]
        elif op == "square":  # unary -> binary on itself
            in_names = [in_names[0], in_names[0]]
        elif op in _SCALAR_BINOPS:
            sc = f"{node.name}_scalar"
            in_names = ([sc] + in_names[:1] if _SCALAR_BINOPS[op][1]
                        else in_names[:1] + [sc])
        elif op == "tile":
            in_names = in_names[:1] + [f"{node.name}_repeats"]
        elif op == "UpSampling":
            in_names = in_names[:1] + [f"{node.name}_scales"]
        out_names = [f"{node.name}_out{i}" for i in range(node.num_outputs)]
        nodes.append((ot, in_names, out_names, oattrs, node.name, consts))
    return nodes


def _make_attr(name, value):
    """python value -> AttributeProto (the helper.make_attribute role)."""
    from . import proto

    A = proto.AttributeProto
    if isinstance(value, bool):
        return A(name=name, i=int(value), type=proto.AttrType.INT)
    if isinstance(value, (int, np.integer)):
        return A(name=name, i=int(value), type=proto.AttrType.INT)
    if isinstance(value, (float, np.floating)):
        return A(name=name, f=float(value), type=proto.AttrType.FLOAT)
    if isinstance(value, str):
        return A(name=name, s=value.encode(), type=proto.AttrType.STRING)
    if isinstance(value, np.ndarray):
        return A(name=name, t=proto.from_array(value),
                 type=proto.AttrType.TENSOR)
    if isinstance(value, (list, tuple)):
        if all(isinstance(v, (int, np.integer)) for v in value):
            return A(name=name, ints=[int(v) for v in value],
                     type=proto.AttrType.INTS)
        if all(isinstance(v, (int, float, np.floating, np.integer))
               for v in value):
            return A(name=name, floats=[float(v) for v in value],
                     type=proto.AttrType.FLOATS)
        if all(isinstance(v, str) for v in value):
            return A(name=name, strings=[v.encode() for v in value],
                     type=proto.AttrType.STRINGS)
    raise TypeError(f"cannot encode ONNX attribute {name}={value!r}")


def _value_info(name, shape=None, elem_type=None):
    from . import proto

    t = proto.TypeProtoTensor(
        elem_type=elem_type or proto.DataType.FLOAT)
    if shape is not None:
        t.shape = proto.TensorShapeProto(
            dim=[proto.TensorShapeDim(dim_value=int(s)) for s in shape])
    return proto.ValueInfoProto(name=name, type=proto.TypeProto(tensor_type=t))


def export_model(sym, params, input_shape, input_type=np.float32,
                 onnx_file_path="model.onnx", verbose=False):
    """Export symbol+params to an ONNX file (ref: export_model.py:83).

    Self-contained: the protobuf assembly uses the bundled wire-format
    codec (contrib/onnx/proto.py), so no `onnx` package is needed and the
    emitted bytes are standard ONNX readable by any runtime.
    """
    from . import proto

    nodes = graph_to_onnx_nodes(sym)
    args = sym.list_arguments()
    shapes = input_shape if isinstance(input_shape, list) else [input_shape]
    data_names = [n for n in args if n not in params][: len(shapes)]

    # BatchNorm with fix_gamma ignores its gamma; ONNX BatchNormalization
    # always applies scale, so export those gammas as ones
    ones_params = set()
    for node in sym._topo_nodes():
        if not node.is_var and node.op.name == "BatchNorm":
            fg = node.attrs.get("fix_gamma", True)
            if fg is True or str(fg).lower() in ("true", "1"):
                src, _idx = node.inputs[1]
                ones_params.add(src.name)

    inits, inputs = [], []
    for n, shp in zip(data_names, shapes):
        inputs.append(_value_info(n, shp))
    for name, arr in params.items():
        a = arr.asnumpy() if hasattr(arr, "asnumpy") else np.asarray(arr)
        if name in ones_params:
            a = np.ones_like(a)
        inits.append(proto.from_array(a, name=name))
        # graph.input also lists initializers at older opsets/IR; harmless
        # at newer ones and maximizes loader compatibility
        inputs.append(_value_info(name, a.shape))

    onnx_nodes = []
    for ot, ins, outs, attrs, name, consts in nodes:
        for cname, carr in consts.items():
            inits.append(proto.from_array(carr, name=cname))
            elem = (proto.DataType.INT64 if carr.dtype == np.int64
                    else proto.DataType.FLOAT)
            inputs.append(_value_info(cname, carr.shape, elem))
        onnx_nodes.append(proto.NodeProto(
            op_type=ot, input=list(ins), output=list(outs), name=name,
            attribute=[_make_attr(k, v) for k, v in sorted(attrs.items())]))
    last_outs = nodes[-1][2]
    outputs = [_value_info(o) for o in last_outs]
    graph = proto.GraphProto(node=onnx_nodes, name="incubator_mxnet_tpu",
                             initializer=inits, input=inputs, output=outputs)
    model = proto.ModelProto(
        ir_version=3, producer_name="incubator_mxnet_tpu",
        producer_version="2.0", graph=graph,
        opset_import=[proto.OperatorSetId(domain="", version=_OPSET)])
    proto.save_model(model, onnx_file_path)
    return onnx_file_path
