"""Symbol -> ONNX export
(ref: python/mxnet/contrib/onnx/mx2onnx/export_model.py + _op_translations.py).

The graph walk + per-op translation tables are serializer-independent; only
the final protobuf assembly needs the `onnx` package.
"""
from __future__ import annotations

import numpy as np

__all__ = ["export_model", "ONNX_OP_MAP"]

# op-name -> (onnx_op_type, attr translator). Attr translators take the
# node's registry attrs and return ONNX attribute dicts (ref:
# mx2onnx/_op_translations.py one function per op).


def _conv_attrs(a):
    k = a.get("kernel")
    return {
        "kernel_shape": list(k),
        "strides": list(a.get("stride") or (1,) * len(k)),
        "pads": list(a.get("pad") or (0,) * len(k)) * 2,
        "dilations": list(a.get("dilate") or (1,) * len(k)),
        "group": int(a.get("num_group", 1)),
    }


def _pool_attrs(a):
    k = a.get("kernel", (2, 2))
    return {
        "kernel_shape": list(k),
        "strides": list(a.get("stride") or (1,) * len(k)),
        "pads": list(a.get("pad") or (0,) * len(k)) * 2,
    }


ONNX_OP_MAP = {
    "Convolution": ("Conv", _conv_attrs),
    "FullyConnected": ("Gemm", lambda a: {"transB": 1}),
    "Activation": (None, None),  # dispatched by act_type below
    "BatchNorm": ("BatchNormalization",
                  lambda a: {"epsilon": float(a.get("eps", 1e-3)),
                             "momentum": float(a.get("momentum", 0.9))}),
    "Pooling": (None, None),  # max/avg dispatch below
    "Flatten": ("Flatten", lambda a: {"axis": 1}),
    "softmax": ("Softmax", lambda a: {"axis": int(a.get("axis", -1))}),
    "SoftmaxOutput": ("Softmax", lambda a: {"axis": -1}),
    "Concat": ("Concat", lambda a: {"axis": int(a.get("dim", 1))}),
    "Reshape": ("Reshape", lambda a: {}),  # shape initializer added in walk
    "transpose": ("Transpose", lambda a: {"perm": list(a["axes"])}
                  if a.get("axes") else {}),
    "Dropout": ("Dropout", lambda a: {"ratio": float(a.get("p", 0.5))}),
    "LeakyReLU": ("LeakyRelu", lambda a: {"alpha": float(a.get("slope", 0.25))}),
    "elemwise_add": ("Add", lambda a: {}),
    "broadcast_add": ("Add", lambda a: {}),
    "elemwise_mul": ("Mul", lambda a: {}),
    "broadcast_mul": ("Mul", lambda a: {}),
    "elemwise_sub": ("Sub", lambda a: {}),
    "dot": ("MatMul", lambda a: {}),
    "LayerNorm": ("LayerNormalization",
                  lambda a: {"epsilon": float(a.get("eps", 1e-5)),
                             "axis": int(a.get("axis", -1))}),
    "relu": ("Relu", lambda a: {}),
    "sigmoid": ("Sigmoid", lambda a: {}),
    "tanh": ("Tanh", lambda a: {}),
    "exp": ("Exp", lambda a: {}),
    "log": ("Log", lambda a: {}),
    "sqrt": ("Sqrt", lambda a: {}),
    "negative": ("Neg", lambda a: {}),
    "Pad": ("Pad", lambda a: {"mode": a.get("mode", "constant")}),
    # Gather's ONNX input order is (table, indices); Embedding's is
    # (indices, weight) — reordered in graph_to_onnx_nodes
    "Embedding": ("Gather", lambda a: {}),
    # attribute forms valid at the emitted opset (8): Clip(min,max),
    # Slice(axes,starts,ends), Upsample(scales)
    "clip": ("Clip", lambda a: {"min": float(a["a_min"]),
                                "max": float(a["a_max"])}),
    "slice_axis": ("Slice", lambda a: {"axes": [int(a["axis"])],
                                       "starts": [int(a["begin"])],
                                       "ends": [int(a["end"]) if a.get("end")
                                                is not None else 2**31 - 1]}),
    "UpSampling": ("Upsample", lambda a: {
        "mode": "nearest" if a.get("sample_type", "nearest") == "nearest"
        else "linear",
        "scales": [1.0, 1.0, float(a["scale"]), float(a["scale"])]}),
    "mean": ("ReduceMean", lambda a: {}),
    "sum": ("ReduceSum", lambda a: {}),
    "max": ("ReduceMax", lambda a: {}),
}

_OPSET = 8  # highest opset where the attribute forms above are all legal

_ACT_MAP = {"relu": "Relu", "sigmoid": "Sigmoid", "tanh": "Tanh",
            "softrelu": "Softplus", "softsign": "Softsign"}
_POOL_MAP = {"max": "MaxPool", "avg": "AveragePool"}


def graph_to_onnx_nodes(symbol):
    """Walk the symbol graph into (op_type, inputs, outputs, attrs, name,
    const_inputs) tuples — the serializer-independent core of the exporter.
    const_inputs maps extra input names to numpy arrays that the serializer
    must materialize as initializers (e.g. Reshape's target shape)."""
    nodes = []
    for node in symbol._topo_nodes():
        if node.is_var:
            continue
        op = node.op.name
        attrs = dict(node.attrs)
        consts = {}
        if op == "Activation":
            ot, oattrs = _ACT_MAP[attrs.get("act_type", "relu")], {}
        elif op == "Pooling":
            if attrs.get("global_pool"):
                ot = ("GlobalMaxPool" if attrs.get("pool_type", "max") == "max"
                      else "GlobalAveragePool")
                oattrs = {}
            else:
                ot = _POOL_MAP[attrs.get("pool_type", "max")]
                oattrs = _pool_attrs(attrs)
        elif op in ONNX_OP_MAP and ONNX_OP_MAP[op][0] is not None:
            ot, tr = ONNX_OP_MAP[op]
            oattrs = tr(attrs)
        else:
            raise NotImplementedError(
                f"ONNX export: no translation for op '{op}' "
                f"(ref mapping table: mx2onnx/_op_translations.py)")
        in_names = [src.name if src.is_var else f"{src.name}_out{idx}"
                    for src, idx in node.inputs]
        if op == "Embedding":  # ONNX Gather is (table, indices)
            in_names = [in_names[1], in_names[0]]
        elif op == "SoftmaxOutput":  # label input has no ONNX counterpart
            in_names = in_names[:1]
        elif op == "Reshape":  # target shape is a tensor input at opset>=5
            shape_name = f"{node.name}_shape"
            consts[shape_name] = np.asarray(attrs["shape"], np.int64)
            in_names = in_names[:1] + [shape_name]
        out_names = [f"{node.name}_out{i}" for i in range(node.num_outputs)]
        nodes.append((ot, in_names, out_names, oattrs, node.name, consts))
    return nodes


def export_model(sym, params, input_shape, input_type=np.float32,
                 onnx_file_path="model.onnx", verbose=False):
    """Export symbol+params to an ONNX file (ref: export_model.py:83).

    Requires the `onnx` package at call time.
    """
    try:
        import onnx
        from onnx import TensorProto, helper, numpy_helper
    except ImportError as e:  # environment gate, mirrors reference behavior
        raise ImportError(
            "onnx package is required for export_model; install onnx or use "
            "incubator_mxnet_tpu.deploy.export_predictor for the TPU-native "
            "StableHLO deployment path") from e

    nodes = graph_to_onnx_nodes(sym)
    args = sym.list_arguments()
    shapes = input_shape if isinstance(input_shape, list) else [input_shape]
    data_names = [n for n in args if n not in params][: len(shapes)]

    inits, inputs = [], []
    for n, shp in zip(data_names, shapes):
        inputs.append(helper.make_tensor_value_info(
            n, TensorProto.FLOAT, list(shp)))
    for name, arr in params.items():
        a = arr.asnumpy() if hasattr(arr, "asnumpy") else np.asarray(arr)
        inits.append(numpy_helper.from_array(a, name=name))

    onnx_nodes = []
    for ot, ins, outs, attrs, name, consts in nodes:
        for cname, carr in consts.items():
            inits.append(numpy_helper.from_array(carr, name=cname))
        onnx_nodes.append(helper.make_node(ot, ins, outs, name=name, **attrs))
    last_outs = nodes[-1][2]
    outputs = [helper.make_tensor_value_info(o, TensorProto.FLOAT, None)
               for o in last_outs]
    graph = helper.make_graph(onnx_nodes, "incubator_mxnet_tpu", inputs,
                              outputs, initializer=inits)
    model = helper.make_model(
        graph, opset_imports=[helper.make_opsetid("", _OPSET)])
    onnx.save(model, onnx_file_path)
    return onnx_file_path
