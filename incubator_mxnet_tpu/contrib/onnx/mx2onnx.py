"""Symbol -> ONNX export
(ref: python/mxnet/contrib/onnx/mx2onnx/export_model.py + _op_translations.py).

The graph walk + per-op translation tables are serializer-independent; only
the final protobuf assembly needs the `onnx` package.
"""
from __future__ import annotations

import numpy as np

__all__ = ["export_model", "ONNX_OP_MAP"]

# op-name -> (onnx_op_type, attr translator). Attr translators take the
# node's registry attrs and return ONNX attribute dicts (ref:
# mx2onnx/_op_translations.py one function per op).


def _conv_attrs(a):
    k = a.get("kernel")
    return {
        "kernel_shape": list(k),
        "strides": list(a.get("stride") or (1,) * len(k)),
        "pads": list(a.get("pad") or (0,) * len(k)) * 2,
        "dilations": list(a.get("dilate") or (1,) * len(k)),
        "group": int(a.get("num_group", 1)),
    }


def _pool_attrs(a):
    k = a.get("kernel", (2, 2))
    return {
        "kernel_shape": list(k),
        "strides": list(a.get("stride") or (1,) * len(k)),
        "pads": list(a.get("pad") or (0,) * len(k)) * 2,
    }


def _reduce_attrs(a):
    ax = a.get("axis")
    out = {"keepdims": int(bool(a.get("keepdims", False)))}
    if ax is not None:
        out["axes"] = [int(ax)] if isinstance(ax, int) else [int(v) for v in ax]
    return out


ONNX_OP_MAP = {
    "Convolution": ("Conv", _conv_attrs),
    "FullyConnected": ("Gemm", lambda a: {"transB": 1}),
    "Activation": (None, None),  # dispatched by act_type below
    "BatchNorm": ("BatchNormalization",
                  lambda a: {"epsilon": float(a.get("eps", 1e-3)),
                             "momentum": float(a.get("momentum", 0.9))}),
    "Pooling": (None, None),  # max/avg dispatch below
    "Flatten": ("Flatten", lambda a: {"axis": 1}),
    "softmax": ("Softmax", lambda a: {"axis": int(a.get("axis", -1))}),
    "SoftmaxOutput": ("Softmax", lambda a: {"axis": -1}),
    "Concat": ("Concat", lambda a: {"axis": int(a.get("dim", 1))}),
    "Reshape": ("Reshape", lambda a: {}),  # shape initializer added in walk
    "transpose": ("Transpose", lambda a: {"perm": list(a["axes"])}
                  if a.get("axes") else {}),
    "Dropout": ("Dropout", lambda a: {"ratio": float(a.get("p", 0.5))}),
    "LeakyReLU": ("LeakyRelu", lambda a: {"alpha": float(a.get("slope", 0.25))}),
    "elemwise_add": ("Add", lambda a: {}),
    "broadcast_add": ("Add", lambda a: {}),
    "elemwise_mul": ("Mul", lambda a: {}),
    "broadcast_mul": ("Mul", lambda a: {}),
    "elemwise_sub": ("Sub", lambda a: {}),
    "dot": ("MatMul", lambda a: {}),
    "LayerNorm": ("LayerNormalization",
                  lambda a: {"epsilon": float(a.get("eps", 1e-5)),
                             "axis": int(a.get("axis", -1))}),
    "relu": ("Relu", lambda a: {}),
    "sigmoid": ("Sigmoid", lambda a: {}),
    "tanh": ("Tanh", lambda a: {}),
    "exp": ("Exp", lambda a: {}),
    "log": ("Log", lambda a: {}),
    "sqrt": ("Sqrt", lambda a: {}),
    "negative": ("Neg", lambda a: {}),
    # MXNet pad_width interleaves (b0,e0,b1,e1,...); ONNX pads groups all
    # begins then all ends
    "Pad": ("Pad", lambda a: {
        "mode": a.get("mode", "constant"),
        "value": float(a.get("constant_value") or 0.0),
        "pads": (list(a["pad_width"][0::2]) + list(a["pad_width"][1::2]))
        if a.get("pad_width") else []}),
    # Gather's ONNX input order is (table, indices); Embedding's is
    # (indices, weight) — reordered in graph_to_onnx_nodes
    "Embedding": ("Gather", lambda a: {}),
    # attribute forms valid at the emitted opset (8): Clip(min,max),
    # Slice(axes,starts,ends), Upsample(scales)
    "clip": ("Clip", lambda a: {"min": float(a["a_min"]),
                                "max": float(a["a_max"])}),
    "slice_axis": ("Slice", lambda a: {"axes": [int(a["axis"])],
                                       "starts": [int(a["begin"])],
                                       "ends": [int(a["end"]) if a.get("end")
                                                is not None else 2**31 - 1]}),
    "UpSampling": ("Upsample", lambda a: {
        "mode": "nearest" if a.get("sample_type", "nearest") == "nearest"
        else "linear",
        "scales": [1.0, 1.0, float(a["scale"]), float(a["scale"])]}),
    "mean": ("ReduceMean", _reduce_attrs),
    "sum": ("ReduceSum", _reduce_attrs),
    "max": ("ReduceMax", _reduce_attrs),
}

_OPSET = 8  # highest opset where the attribute forms above are all legal

_ACT_MAP = {"relu": "Relu", "sigmoid": "Sigmoid", "tanh": "Tanh",
            "softrelu": "Softplus", "softsign": "Softsign"}
_POOL_MAP = {"max": "MaxPool", "avg": "AveragePool"}


def graph_to_onnx_nodes(symbol):
    """Walk the symbol graph into (op_type, inputs, outputs, attrs, name,
    const_inputs) tuples — the serializer-independent core of the exporter.
    const_inputs maps extra input names to numpy arrays that the serializer
    must materialize as initializers (e.g. Reshape's target shape)."""
    nodes = []
    for node in symbol._topo_nodes():
        if node.is_var:
            continue
        op = node.op.name
        attrs = dict(node.attrs)
        consts = {}
        if op == "Activation":
            ot, oattrs = _ACT_MAP[attrs.get("act_type", "relu")], {}
        elif op == "Pooling":
            if attrs.get("global_pool"):
                ot = ("GlobalMaxPool" if attrs.get("pool_type", "max") == "max"
                      else "GlobalAveragePool")
                oattrs = {}
            else:
                ot = _POOL_MAP[attrs.get("pool_type", "max")]
                oattrs = _pool_attrs(attrs)
        elif op in ONNX_OP_MAP and ONNX_OP_MAP[op][0] is not None:
            ot, tr = ONNX_OP_MAP[op]
            oattrs = tr(attrs)
        else:
            raise NotImplementedError(
                f"ONNX export: no translation for op '{op}' "
                f"(ref mapping table: mx2onnx/_op_translations.py)")
        in_names = [src.name if src.is_var else f"{src.name}_out{idx}"
                    for src, idx in node.inputs]
        if op == "Embedding":  # ONNX Gather is (table, indices)
            in_names = [in_names[1], in_names[0]]
        elif op == "SoftmaxOutput":  # label input has no ONNX counterpart
            in_names = in_names[:1]
        elif op == "Reshape":  # target shape is a tensor input at opset>=5
            shape_name = f"{node.name}_shape"
            consts[shape_name] = np.asarray(attrs["shape"], np.int64)
            in_names = in_names[:1] + [shape_name]
        out_names = [f"{node.name}_out{i}" for i in range(node.num_outputs)]
        nodes.append((ot, in_names, out_names, oattrs, node.name, consts))
    return nodes


def _make_attr(name, value):
    """python value -> AttributeProto (the helper.make_attribute role)."""
    from . import proto

    A = proto.AttributeProto
    if isinstance(value, bool):
        return A(name=name, i=int(value), type=proto.AttrType.INT)
    if isinstance(value, (int, np.integer)):
        return A(name=name, i=int(value), type=proto.AttrType.INT)
    if isinstance(value, (float, np.floating)):
        return A(name=name, f=float(value), type=proto.AttrType.FLOAT)
    if isinstance(value, str):
        return A(name=name, s=value.encode(), type=proto.AttrType.STRING)
    if isinstance(value, np.ndarray):
        return A(name=name, t=proto.from_array(value),
                 type=proto.AttrType.TENSOR)
    if isinstance(value, (list, tuple)):
        if all(isinstance(v, (int, np.integer)) for v in value):
            return A(name=name, ints=[int(v) for v in value],
                     type=proto.AttrType.INTS)
        if all(isinstance(v, (int, float, np.floating, np.integer))
               for v in value):
            return A(name=name, floats=[float(v) for v in value],
                     type=proto.AttrType.FLOATS)
        if all(isinstance(v, str) for v in value):
            return A(name=name, strings=[v.encode() for v in value],
                     type=proto.AttrType.STRINGS)
    raise TypeError(f"cannot encode ONNX attribute {name}={value!r}")


def _value_info(name, shape=None, elem_type=None):
    from . import proto

    t = proto.TypeProtoTensor(
        elem_type=elem_type or proto.DataType.FLOAT)
    if shape is not None:
        t.shape = proto.TensorShapeProto(
            dim=[proto.TensorShapeDim(dim_value=int(s)) for s in shape])
    return proto.ValueInfoProto(name=name, type=proto.TypeProto(tensor_type=t))


def export_model(sym, params, input_shape, input_type=np.float32,
                 onnx_file_path="model.onnx", verbose=False):
    """Export symbol+params to an ONNX file (ref: export_model.py:83).

    Self-contained: the protobuf assembly uses the bundled wire-format
    codec (contrib/onnx/proto.py), so no `onnx` package is needed and the
    emitted bytes are standard ONNX readable by any runtime.
    """
    from . import proto

    nodes = graph_to_onnx_nodes(sym)
    args = sym.list_arguments()
    shapes = input_shape if isinstance(input_shape, list) else [input_shape]
    data_names = [n for n in args if n not in params][: len(shapes)]

    # BatchNorm with fix_gamma ignores its gamma; ONNX BatchNormalization
    # always applies scale, so export those gammas as ones
    ones_params = set()
    for node in sym._topo_nodes():
        if not node.is_var and node.op.name == "BatchNorm":
            fg = node.attrs.get("fix_gamma", True)
            if fg is True or str(fg).lower() in ("true", "1"):
                src, _idx = node.inputs[1]
                ones_params.add(src.name)

    inits, inputs = [], []
    for n, shp in zip(data_names, shapes):
        inputs.append(_value_info(n, shp))
    for name, arr in params.items():
        a = arr.asnumpy() if hasattr(arr, "asnumpy") else np.asarray(arr)
        if name in ones_params:
            a = np.ones_like(a)
        inits.append(proto.from_array(a, name=name))
        # graph.input also lists initializers at older opsets/IR; harmless
        # at newer ones and maximizes loader compatibility
        inputs.append(_value_info(name, a.shape))

    onnx_nodes = []
    for ot, ins, outs, attrs, name, consts in nodes:
        for cname, carr in consts.items():
            inits.append(proto.from_array(carr, name=cname))
            inputs.append(_value_info(cname, carr.shape,
                                      proto.DataType.INT64))
        onnx_nodes.append(proto.NodeProto(
            op_type=ot, input=list(ins), output=list(outs), name=name,
            attribute=[_make_attr(k, v) for k, v in sorted(attrs.items())]))
    last_outs = nodes[-1][2]
    outputs = [_value_info(o) for o in last_outs]
    graph = proto.GraphProto(node=onnx_nodes, name="incubator_mxnet_tpu",
                             initializer=inits, input=inputs, output=outputs)
    model = proto.ModelProto(
        ir_version=3, producer_name="incubator_mxnet_tpu",
        producer_version="2.0", graph=graph,
        opset_import=[proto.OperatorSetId(domain="", version=_OPSET)])
    proto.save_model(model, onnx_file_path)
    return onnx_file_path
