"""ONNX -> Symbol import
(ref: python/mxnet/contrib/onnx/onnx2mx/import_model.py + import_onnx.py).

Returns (sym, arg_params, aux_params) like the reference's import_model.
"""
from __future__ import annotations

import numpy as np

__all__ = ["import_model"]

# ONNX TensorProto.DataType enum -> mx dtype string (Cast)
_CAST_DTYPES = {"float32": 1, "uint8": 2, "int8": 3, "uint16": 4,
                "int16": 5, "int32": 6, "int64": 7, "bool": 9,
                "float16": 10, "float64": 11, "uint32": 12,
                "uint64": 13, "bfloat16": 16}


def _attrs(node):
    out = {}
    for a in node.attribute:
        if a.type == a.INT:
            out[a.name] = int(a.i)
        elif a.type == a.FLOAT:
            out[a.name] = float(a.f)
        elif a.type == a.INTS:
            out[a.name] = tuple(a.ints)
        elif a.type == a.FLOATS:
            out[a.name] = tuple(a.floats)
        elif a.type == a.STRING:
            out[a.name] = a.s.decode()
        elif a.type == a.TENSOR:
            from . import proto

            out[a.name] = proto.to_array(a.t)
    return out


def import_model(model_file):
    """Load an ONNX model into (sym, arg_params, aux_params)
    (ref: import_model.py:31). Self-contained: parses the protobuf wire
    format directly (contrib/onnx/proto.py), no `onnx` package needed."""
    from ... import symbol as sym
    from ...ndarray import array as nd_array
    from . import proto

    model = proto.load_model(model_file)
    graph = model.graph
    params = {init.name: nd_array(proto.to_array(init))
              for init in graph.initializer}
    # default-domain opset governs where Clip/Pad/Reduce* parameters live
    # (attributes through opset 10, inputs from 11/13 on)
    opset = 0
    for imp in getattr(model, "opset_import", ()) or ():
        if not getattr(imp, "domain", ""):
            opset = max(opset, int(getattr(imp, "version", 0) or 0))
    if opset == 0:
        opset = 9  # unspecified: ONNX defines this as opset 1; legacy forms

    env = {}  # onnx value name -> Symbol
    shape_sources = {}  # Shape-node output name -> its input Symbol
    for name in list(params):
        env[name] = sym.Variable(name)
    for inp in graph.input:
        if inp.name not in env:
            env[inp.name] = sym.Variable(inp.name)

    def const_input(node, idx):
        """Constant-foldable input (opset>=11 moved several parameters from
        attributes to inputs; they must be initializers here)."""
        if len(node.input) <= idx or not node.input[idx]:
            return None
        name = node.input[idx]
        if name not in params:
            raise NotImplementedError(
                f"{node.op_type} input {name!r} must be an initializer "
                "(dynamic parameter tensors are not supported)")
        return params[name].asnumpy()

    def conv(node):
        a = _attrs(node)
        ins = [env[i] for i in node.input if i]
        t = node.op_type
        if t == "Conv":
            k = a.get("kernel_shape")
            pads = a.get("pads", (0,) * (2 * len(k)))
            return sym.Convolution(
                *ins, kernel=tuple(k), stride=tuple(a.get("strides", (1,) * len(k))),
                pad=tuple(pads[: len(k)]), dilate=tuple(a.get("dilations", (1,) * len(k))),
                num_group=int(a.get("group", 1)),
                num_filter=int(params[node.input[1]].shape[0]),
                no_bias=len(ins) < 3)
        if t == "Gemm":
            if a.get("alpha", 1.0) != 1.0 or a.get("beta", 1.0) != 1.0 \
                    or a.get("transA", 0):
                raise NotImplementedError(
                    "Gemm with alpha/beta != 1 or transA is not supported")
            w = params[node.input[1]]
            if not a.get("transB", 0):
                # FullyConnected computes x·W^T; un-transposed ONNX weight
                # (K, N) must be stored transposed
                w = nd_array(w.asnumpy().T)
                params[node.input[1]] = w
            return sym.FullyConnected(*ins, num_hidden=int(w.shape[0]),
                                      no_bias=len(ins) < 3)
        if t == "MatMul":
            return sym.dot(*ins)
        if t == "BatchNormalization":
            return sym.BatchNorm(*ins, eps=a.get("epsilon", 1e-5),
                                 momentum=a.get("momentum", 0.9),
                                 fix_gamma=False)
        if t in ("Relu", "Sigmoid", "Tanh"):
            return sym.Activation(ins[0], act_type=t.lower())
        if t == "LeakyRelu":
            return sym.LeakyReLU(ins[0], slope=a.get("alpha", 0.01))
        if t == "Softmax":
            return sym.softmax(ins[0], axis=a.get("axis", -1))
        if t == "MaxPool":
            return sym.Pooling(ins[0], kernel=tuple(a["kernel_shape"]),
                               pool_type="max",
                               stride=tuple(a.get("strides", (1, 1))),
                               pad=tuple(a.get("pads", (0, 0, 0, 0))[:2]))
        if t == "AveragePool":
            return sym.Pooling(ins[0], kernel=tuple(a["kernel_shape"]),
                               pool_type="avg",
                               stride=tuple(a.get("strides", (1, 1))),
                               pad=tuple(a.get("pads", (0, 0, 0, 0))[:2]))
        if t == "GlobalAveragePool":
            return sym.Pooling(ins[0], kernel=(1, 1), pool_type="avg",
                               global_pool=True)
        if t == "GlobalMaxPool":
            return sym.Pooling(ins[0], kernel=(1, 1), pool_type="max",
                               global_pool=True)
        if t == "Flatten":
            return sym.Flatten(ins[0])
        if t == "Add":
            return ins[0] + ins[1]
        if t == "Sub":
            return ins[0] - ins[1]
        if t == "Mul":
            return ins[0] * ins[1]
        if t == "Concat":
            return sym.Concat(*ins, dim=a.get("axis", 1))
        if t == "Reshape":
            shape = tuple(int(x) for x in
                          np.asarray(params[node.input[1]].asnumpy(), np.int64))
            return sym.Reshape(ins[0], shape=shape)
        if t == "Transpose":
            return sym.transpose(ins[0], axes=a.get("perm"))
        if t == "Dropout":
            return sym.Dropout(ins[0], p=a.get("ratio", 0.5))
        if t == "Gather":
            w = params[node.input[0]]
            return sym.Embedding(ins[1], ins[0], input_dim=int(w.shape[0]),
                                 output_dim=int(w.shape[1]))
        if t == "Div":
            return ins[0] / ins[1]
        if t == "Identity":
            return ins[0]
        if t == "Exp":
            return sym.exp(ins[0])
        if t == "Log":
            return sym.log(ins[0])
        if t == "Sqrt":
            return sym.sqrt(ins[0])
        if t == "Neg":
            return sym.negative(ins[0])
        if t == "Softplus":
            return sym.Activation(ins[0], act_type="softrelu")
        if t == "Softsign":
            return sym.Activation(ins[0], act_type="softsign")
        if t == "Clip":
            if opset >= 11 or len(node.input) > 1:
                lo, hi = const_input(node, 1), const_input(node, 2)
                return sym.clip(
                    ins[0],
                    a_min=float(lo) if lo is not None else -3.4e38,
                    a_max=float(hi) if hi is not None else 3.4e38)
            return sym.clip(ins[0], a_min=a.get("min", -3.4e38),
                            a_max=a.get("max", 3.4e38))
        if t == "Slice":
            if opset >= 10 or len(node.input) > 1:
                # starts/ends/axes/steps moved to inputs at opset 10
                starts = [int(v) for v in const_input(node, 1)]
                ends = [int(v) for v in const_input(node, 2)]
                ax = const_input(node, 3)
                axes = tuple(int(v) for v in ax) if ax is not None else None
                steps = const_input(node, 4)
                if steps is not None and any(int(s) != 1 for s in steps):
                    raise NotImplementedError(
                        "Slice with steps != 1 is not supported")
            else:
                axes = a.get("axes")
                starts, ends = a["starts"], a["ends"]
            out = ins[0]
            for ax, b, e in zip(axes or range(len(starts)), starts, ends):
                out = sym.slice_axis(out, axis=int(ax), begin=int(b),
                                     end=None if e >= 2**31 - 1 else int(e))
            return out
        if t == "ReduceMean":
            axes = a.get("axes")
            if opset >= 18 or len(node.input) > 1:  # axes moved to input 1
                ax = const_input(node, 1)
                if ax is None and a.get("noop_with_empty_axes", 0):
                    return ins[0]
                axes = tuple(int(x) for x in ax) if ax is not None else axes
            return sym.mean(ins[0], axis=axes,
                            keepdims=bool(a.get("keepdims", 1)))
        if t == "ReduceSum":
            axes = a.get("axes")
            if opset >= 13 or len(node.input) > 1:  # axes moved to input 1
                ax = const_input(node, 1)
                if ax is None and a.get("noop_with_empty_axes", 0):
                    return ins[0]  # empty axes + noop flag = identity
                axes = tuple(int(x) for x in ax) if ax is not None else None
            return sym.sum(ins[0], axis=axes,
                           keepdims=bool(a.get("keepdims", 1)))
        if t == "ReduceMax":
            axes = a.get("axes")
            if opset >= 18 or len(node.input) > 1:  # axes moved to input 1
                ax = const_input(node, 1)
                if ax is None and a.get("noop_with_empty_axes", 0):
                    return ins[0]
                axes = tuple(int(x) for x in ax) if ax is not None else axes
            return sym.max(ins[0], axis=axes,
                           keepdims=bool(a.get("keepdims", 1)))
        if t == "LayerNormalization":
            return sym.LayerNorm(*ins, eps=a.get("epsilon", 1e-5),
                                 axis=a.get("axis", -1))
        if t == "Upsample":
            scales = a.get("scales")
            if scales is None:  # opset >= 9: scales is input 1
                sc = const_input(node, 1)
                scales = [float(v) for v in sc] if sc is not None else None
            if scales is None:
                raise NotImplementedError("Upsample without static scales")
            return sym.UpSampling(ins[0], scale=int(scales[2]),
                                  sample_type="nearest")
        if t == "Pad":
            mode = a.get("mode", "constant")
            mode = mode.decode() if isinstance(mode, bytes) else mode
            if opset >= 11 or (len(node.input) > 1 and node.input[1]):
                # pads/value moved from attributes to inputs at opset 11
                pads_arr = const_input(node, 1)
                pads = [] if pads_arr is None else [int(v) for v in pads_arr]
                val = const_input(node, 2)
                a = dict(a, value=float(val) if val is not None else 0.0)
            else:
                pads = list(a.get("pads") or ())
            n = len(pads) // 2
            # ONNX groups all begins then all ends; pad_width interleaves
            pw = []
            for b, e in zip(pads[:n], pads[n:]):
                pw += [int(b), int(e)]
            return sym.Pad(ins[0], mode=mode, pad_width=tuple(pw),
                           constant_value=float(a.get("value", 0.0)))
        # ---- round-3 tail (mirrors the expanded export map) ----
        if t == "Abs":
            return sym.abs(ins[0])
        if t == "Ceil":
            return sym.ceil(ins[0])
        if t == "Floor":
            return sym.floor(ins[0])
        if t == "Round":
            return sym.round(ins[0])
        if t == "Sign":
            return sym.sign(ins[0])
        if t == "Erf":
            return sym.erf(ins[0])
        if t == "Reciprocal":
            return sym.reciprocal(ins[0])
        if t in ("Sin", "Cos", "Tan", "Sinh", "Cosh"):
            return getattr(sym, t.lower())(ins[0])
        if t == "Asin":
            return sym.arcsin(ins[0])
        if t == "Acos":
            return sym.arccos(ins[0])
        if t == "Atan":
            return sym.arctan(ins[0])
        if t == "Asinh":
            return sym.arcsinh(ins[0])
        if t == "Acosh":
            return sym.arccosh(ins[0])
        if t == "Atanh":
            return sym.arctanh(ins[0])
        if t == "Pow":
            return sym.broadcast_power(ins[0], ins[1])
        if t == "Max":
            out = ins[0]
            for s in ins[1:]:
                out = sym.broadcast_maximum(out, s)
            return out
        if t == "Min":
            out = ins[0]
            for s in ins[1:]:
                out = sym.broadcast_minimum(out, s)
            return out
        if t == "Sum":
            out = ins[0]
            for s in ins[1:]:
                out = out + s
            return out
        if t == "Unsqueeze":
            axes = a.get("axes")
            if axes is None:  # opset >= 13: axes moved to input 1
                ax = const_input(node, 1)
                axes = [int(v) for v in ax] if ax is not None else []
            out = ins[0]
            for ax in sorted(int(v) for v in axes):
                out = sym.expand_dims(out, axis=ax)
            return out
        if t == "Squeeze":
            axes = a.get("axes")
            if axes is None and len(node.input) > 1:
                ax = const_input(node, 1)
                axes = [int(v) for v in ax] if ax is not None else None
            return sym.squeeze(ins[0], axis=tuple(int(v) for v in axes)
                               if axes is not None else None)
        if t == "Split":
            n_out = len(node.output)
            sizes = a.get("split")
            if sizes is not None and len(set(int(v) for v in sizes)) > 1:
                raise NotImplementedError(
                    "Split with uneven part sizes is not supported")
            return sym.SliceChannel(ins[0], num_outputs=n_out,
                                    axis=int(a.get("axis", 0)))
        if t == "Shape":
            shape_sources[node.output[0]] = ins[0]
            return sym.shape_array(ins[0])
        if t == "ConstantOfShape":
            src = shape_sources.get(node.input[0])
            if src is None:
                raise NotImplementedError(
                    "ConstantOfShape with a dynamic shape input (only the "
                    "Shape(x) -> ConstantOfShape zeros_like pattern is "
                    "supported)")
            v = a.get("value")
            val = float(np.asarray(v).ravel()[0]) if v is not None else 0.0
            out = sym.zeros_like(src)
            return out if val == 0.0 else out + val
        if t == "Tile":
            reps = const_input(node, 1)
            if reps is None:
                raise NotImplementedError("Tile without static repeats")
            return sym.tile(ins[0], reps=tuple(int(v) for v in reps))
        if t == "ArgMax":
            return sym.argmax(ins[0], axis=int(a.get("axis", 0)),
                              keepdims=bool(a.get("keepdims", 1)))
        if t == "ArgMin":
            return sym.argmin(ins[0], axis=int(a.get("axis", 0)),
                              keepdims=bool(a.get("keepdims", 1)))
        if t == "ReduceMin":
            return sym.min(ins[0], axis=a.get("axes"),
                           keepdims=bool(a.get("keepdims", 1)))
        if t == "ReduceProd":
            return sym.prod(ins[0], axis=a.get("axes"),
                            keepdims=bool(a.get("keepdims", 1)))
        if t == "ReduceL2":
            return sym.norm(ins[0], ord=2, axis=a.get("axes"),
                            keepdims=bool(a.get("keepdims", 1)))
        if t == "LogSoftmax":
            return sym.log_softmax(ins[0], axis=a.get("axis", -1))
        if t == "HardSigmoid":
            return sym.hard_sigmoid(ins[0],
                                    alpha=float(a.get("alpha", 0.2)),
                                    beta=float(a.get("beta", 0.5)))
        if t == "Where":
            return sym.where(ins[0], ins[1], ins[2])
        if t == "LRN":
            return sym.LRN(ins[0], alpha=float(a.get("alpha", 1e-4)),
                           beta=float(a.get("beta", 0.75)),
                           knorm=float(a.get("bias", 2.0)),
                           nsize=int(a.get("size", 5)))
        if t == "InstanceNormalization":
            return sym.InstanceNorm(*ins,
                                    eps=float(a.get("epsilon", 1e-5)))
        if t == "ConvTranspose":
            k = a.get("kernel_shape")
            pads = a.get("pads", (0,) * (2 * len(k)))
            w = params[node.input[1]]
            return sym.Deconvolution(
                *ins, kernel=tuple(k),
                stride=tuple(a.get("strides", (1,) * len(k))),
                pad=tuple(pads[: len(k)]),
                adj=tuple(a.get("output_padding", (0,) * len(k))),
                num_filter=int(w.shape[1]) * int(a.get("group", 1)),
                num_group=int(a.get("group", 1)),
                no_bias=len(ins) < 3)
        if t == "DepthToSpace":
            return sym.depth_to_space(ins[0],
                                      block_size=int(a["blocksize"]))
        if t == "SpaceToDepth":
            return sym.space_to_depth(ins[0],
                                      block_size=int(a["blocksize"]))
        if t == "Cast":
            inv = {v: k for k, v in _CAST_DTYPES.items()}
            to = int(a["to"])
            if to not in inv:
                raise NotImplementedError(f"Cast to ONNX enum {to}")
            return sym.Cast(ins[0], dtype=inv[to])
        if t == "PRelu":
            return sym.LeakyReLU(ins[0], gamma=ins[1], act_type="prelu")
        if t == "Elu":
            return sym.LeakyReLU(ins[0], act_type="elu",
                                 slope=float(a.get("alpha", 1.0)))
        raise NotImplementedError(
            f"ONNX import: unsupported op {t} "
            f"(ref: onnx2mx/_op_translations.py)")

    for node in graph.node:
        out_sym = conv(node)
        outs = list(out_sym) if len(node.output) > 1 else [out_sym]
        for name, s in zip(node.output, outs):
            env[name] = s

    final = env[graph.output[0].name]
    arg_names = set(final.list_arguments())
    aux_names = set(final.list_auxiliary_states())
    arg_params = {k: v for k, v in params.items() if k in arg_names}
    aux_params = {k: v for k, v in params.items() if k in aux_names}
    return final, arg_params, aux_params
