"""INT8 quantization (ref: src/operator/quantization/ +
python/mxnet/contrib/quantization.py:422 quantize_model).

TPU-native: int8 matmuls hit the MXU natively; quantize/dequantize are pure
ops, calibration (minmax / entropy-lite) runs over a calibration iterator.
"""
from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from .. import autograd
from ..ndarray.ndarray import NDArray

__all__ = ["quantize", "dequantize", "requantize", "calib_minmax", "quantize_model"]


def quantize(data, min_range=None, max_range=None, out_type="int8"):
    """(ref: quantize op) symmetric int8 quantization -> (q, min, max)."""
    d = data._data if isinstance(data, NDArray) else jnp.asarray(data)
    if min_range is None:
        min_range = float(jnp.min(d))
    if max_range is None:
        max_range = float(jnp.max(d))
    amax = max(abs(min_range), abs(max_range), 1e-8)
    scale = 127.0 / amax
    q = jnp.clip(jnp.round(d * scale), -127, 127).astype(jnp.int8)
    return (NDArray._from_data(q), NDArray._from_data(jnp.asarray(-amax)),
            NDArray._from_data(jnp.asarray(amax)))


def dequantize(qdata, min_range, max_range, out_type="float32"):
    q = qdata._data if isinstance(qdata, NDArray) else jnp.asarray(qdata)
    amax = max_range._data if isinstance(max_range, NDArray) else jnp.asarray(max_range)
    return NDArray._from_data(q.astype(jnp.float32) * (amax / 127.0))


def requantize(qdata, min32, max32, min_calib=None, max_calib=None):
    """int32 accumulators -> int8 with calibrated range (ref: requantize op)."""
    q = qdata._data if isinstance(qdata, NDArray) else jnp.asarray(qdata)
    in_amax = float(max32.asscalar() if isinstance(max32, NDArray) else max32)
    out_amax = max_calib if max_calib is not None else in_amax
    scale = (in_amax / (2 ** 31 - 1)) * (127.0 / out_amax)
    out = jnp.clip(jnp.round(q.astype(jnp.float32) * scale), -127, 127).astype(jnp.int8)
    return (NDArray._from_data(out), NDArray._from_data(jnp.asarray(-out_amax)),
            NDArray._from_data(jnp.asarray(out_amax)))


def calib_minmax(net_or_fn, calib_iter, num_batches=10):
    """Collect per-output min/max over calibration batches
    (ref: quantization.py _collect_layer_statistics minmax mode)."""
    mins, maxs = [], []
    for i, batch in enumerate(calib_iter):
        if i >= num_batches:
            break
        data = batch.data[0] if hasattr(batch, "data") else batch[0]
        out = net_or_fn(data)
        o = out.asnumpy() if isinstance(out, NDArray) else np.asarray(out)
        mins.append(float(o.min()))
        maxs.append(float(o.max()))
    return min(mins), max(maxs)


def quantize_model(sym=None, arg_params=None, aux_params=None, net=None,
                   calib_data=None, num_calib_batches=10, quantized_dtype="int8",
                   **kwargs):
    """Quantize weights of a model to int8 with per-tensor scales
    (ref: contrib/quantization.py:422). Returns (quantized params dict,
    scales dict); activation quantization happens at op dispatch."""
    params = arg_params or {}
    qparams, scales = {}, {}
    for name, w in params.items():
        if name.endswith(("weight",)):
            q, mn, mx = quantize(w)
            qparams[name] = q
            scales[name] = (float(mn.asscalar()), float(mx.asscalar()))
        else:
            qparams[name] = w
    return qparams, scales
