"""INT8 quantization (ref: src/operator/quantization/ +
python/mxnet/contrib/quantization.py:422 quantize_model).

TPU-native: int8 matmuls hit the MXU natively; quantize/dequantize are pure
ops, calibration (minmax / entropy-lite) runs over a calibration iterator.
"""
from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from .. import autograd
from ..ndarray.ndarray import NDArray

__all__ = ["quantize", "dequantize", "requantize", "calib_minmax",
           "calib_entropy", "quantize_model", "quantize_net",
           "QuantizedNet", "as_chain"]


def quantize(data, min_range=None, max_range=None, out_type="int8"):
    """(ref: quantize op) symmetric int8 quantization -> (q, min, max)."""
    d = data._data if isinstance(data, NDArray) else jnp.asarray(data)
    if min_range is None:
        min_range = float(jnp.min(d))
    if max_range is None:
        max_range = float(jnp.max(d))
    amax = max(abs(min_range), abs(max_range), 1e-8)
    scale = 127.0 / amax
    q = jnp.clip(jnp.round(d * scale), -127, 127).astype(jnp.int8)
    return (NDArray._from_data(q), NDArray._from_data(jnp.asarray(-amax)),
            NDArray._from_data(jnp.asarray(amax)))


def dequantize(qdata, min_range, max_range, out_type="float32"):
    q = qdata._data if isinstance(qdata, NDArray) else jnp.asarray(qdata)
    amax = max_range._data if isinstance(max_range, NDArray) else jnp.asarray(max_range)
    return NDArray._from_data(q.astype(jnp.float32) * (amax / 127.0))


def requantize(qdata, min32, max32, min_calib=None, max_calib=None):
    """int32 accumulators -> int8 with calibrated range (ref: requantize op)."""
    q = qdata._data if isinstance(qdata, NDArray) else jnp.asarray(qdata)
    in_amax = float(max32.asscalar() if isinstance(max32, NDArray) else max32)
    out_amax = max_calib if max_calib is not None else in_amax
    scale = (in_amax / (2 ** 31 - 1)) * (127.0 / out_amax)
    out = jnp.clip(jnp.round(q.astype(jnp.float32) * scale), -127, 127).astype(jnp.int8)
    return (NDArray._from_data(out), NDArray._from_data(jnp.asarray(-out_amax)),
            NDArray._from_data(jnp.asarray(out_amax)))


def calib_minmax(net_or_fn, calib_iter, num_batches=10):
    """Collect per-output min/max over calibration batches
    (ref: quantization.py _collect_layer_statistics minmax mode)."""
    mins, maxs = [], []
    for i, batch in enumerate(calib_iter):
        if i >= num_batches:
            break
        data = batch.data[0] if hasattr(batch, "data") else batch[0]
        out = net_or_fn(data)
        o = out.asnumpy() if isinstance(out, NDArray) else np.asarray(out)
        mins.append(float(o.min()))
        maxs.append(float(o.max()))
    return min(mins), max(maxs)


def quantize_model(sym=None, arg_params=None, aux_params=None, net=None,
                   calib_data=None, num_calib_batches=10, quantized_dtype="int8",
                   **kwargs):
    """Quantize weights of a model to int8 with per-tensor scales
    (ref: contrib/quantization.py:422). Returns (quantized params dict,
    scales dict); activation quantization happens at op dispatch."""
    params = arg_params or {}
    qparams, scales = {}, {}
    for name, w in params.items():
        if name.endswith(("weight",)):
            q, mn, mx = quantize(w)
            qparams[name] = q
            scales[name] = (float(mn.asscalar()), float(mx.asscalar()))
        else:
            qparams[name] = w
    return qparams, scales


def _kl_divergence(p, q):
    p = p / max(p.sum(), 1e-12)
    q = q / max(q.sum(), 1e-12)
    mask = p > 0
    return float(np.sum(p[mask] * np.log(p[mask] / np.maximum(q[mask], 1e-12))))


def calib_entropy(net_or_fn, calib_iter, num_batches=10, num_bins=2048,
                  num_quantized_bins=255):
    """KL-divergence (entropy) calibration: pick the clipping threshold whose
    quantized distribution best matches the fp32 one
    (ref: python/mxnet/contrib/quantization.py _get_optimal_threshold /
    _LayerHistogramCollector — TensorRT-style entropy calibration).
    Returns (-threshold, threshold)."""
    if num_bins <= num_quantized_bins // 2:
        raise ValueError(
            f"num_bins ({num_bins}) must exceed num_quantized_bins//2 "
            f"({num_quantized_bins // 2}) for the threshold sweep")
    num_bins += num_bins % 2  # range-doubling rebin needs an even bin count
    # streaming histogram of |activations|: O(num_bins) memory, range doubles
    # (with 2:1 re-binning) when a batch exceeds it — single pass over the
    # iterator (ref: _LayerHistogramCollector keeps running histograms)
    hist = np.zeros(num_bins, np.float64)
    hi_range = None
    n_seen = 0
    for i, batch in enumerate(calib_iter):
        if i >= num_batches:
            break
        data = batch.data[0] if hasattr(batch, "data") else batch[0]
        out = net_or_fn(data)
        o = np.abs(out.asnumpy() if isinstance(out, NDArray)
                   else np.asarray(out)).reshape(-1)
        n_seen += o.size
        bmax = float(o.max()) if o.size else 0.0
        if hi_range is None:
            if bmax == 0.0:
                # don't seed the range from an all-zero batch: a later normal
                # batch would trigger ~40 range doublings and collapse all
                # histogram mass into bin 0 (zeros land in bin 0 regardless)
                continue
            hi_range = bmax
        while bmax > hi_range:
            # double the range: merge adjacent bin pairs into the lower half
            hist = hist.reshape(num_bins // 2, 2).sum(axis=1)
            hist = np.concatenate([hist, np.zeros(num_bins - num_bins // 2)])
            hi_range *= 2
        hist += np.histogram(o, bins=num_bins, range=(0, hi_range))[0]
    if n_seen == 0:
        raise ValueError("calib_entropy: no calibration data "
                         "(empty iterator or num_batches <= 0)")
    if hi_range is None:
        raise ValueError("calib_entropy: every calibration activation was "
                         "exactly zero — no threshold can be calibrated for "
                         "this layer (check the calibration data)")
    best_t = _kl_sweep(hist, hi_range, num_quantized_bins)
    return -best_t, best_t


def _smooth_distribution(d, eps=1e-4):
    """Move a little mass onto zero bins so KL is finite
    (ref: quantization.py _smooth_distribution)."""
    is_zero = d == 0
    n_zero = int(is_zero.sum())
    n_nonzero = d.size - n_zero
    if n_nonzero == 0:
        return None
    eps1 = eps * n_zero / n_nonzero
    out = d.astype(np.float64).copy()
    out[~is_zero] -= eps1
    out[is_zero] = eps
    if (out < 0).any():
        return None
    return out


def _kl_sweep(hist, amax, num_quantized_bins=255):
    """Pick the clipping threshold minimizing KL(p || quantized p) over a
    |activation| histogram covering [0, amax] (the sweep half of the
    reference's _get_optimal_threshold). The sweep starts at
    num_quantized_bins — any narrower slice quantizes losslessly (KL=0)
    and would always win with a degenerate tiny threshold."""
    num_bins = len(hist)
    edges = np.linspace(0, amax, num_bins + 1)
    best_kl, best_t = None, amax
    for i in range(num_quantized_bins, num_bins + 1,
                   max(1, num_bins // 128)):
        t = edges[i] if i < len(edges) else amax
        sliced = hist[:i].astype(np.float64)
        outliers = hist[i:].sum()
        if len(sliced) == 0 or sliced.sum() + outliers == 0:
            continue
        p = sliced.copy()
        p[-1] += outliers  # clipped mass lands in p's edge bin ...
        # ... but q quantizes the histogram WITHOUT the outlier mass — the
        # resulting p/q mismatch is exactly the cost of clipping at t
        # (ref: _get_optimal_threshold builds q from sliced_nd_hist)
        factor = len(sliced) / num_quantized_bins
        q = np.zeros_like(sliced)
        for j in range(num_quantized_bins):
            lo, hi = int(j * factor), max(int((j + 1) * factor), int(j * factor) + 1)
            chunk = sliced[lo:hi]
            nz = (chunk > 0).sum()
            if nz:
                q[lo:hi] = np.where(chunk > 0, chunk.sum() / nz, 0)
        q[p == 0] = 0
        ps = _smooth_distribution(p)
        qs = _smooth_distribution(q)
        if ps is None or qs is None:
            continue
        kl = _kl_divergence(ps, qs)
        if best_kl is None or kl < best_kl:
            best_kl, best_t = kl, float(t)
    return best_t


# ---------------------------------------------------------------------------
# Model-level INT8 quantization: fp32 Gluon net -> jittable int8 predictor
# (ref: quantize_graph_pass.cc + python quantize_model:422 — there the graph
# pass splices quantize/quantized_op/requantize nodes; here the same chain is
# built as a pure jnp program whose conv/FC run int8 x int8 -> int32 on the
# MXU via _contrib_quantized_conv / _contrib_quantized_fully_connected)
# ---------------------------------------------------------------------------


def _iter_chain(net):
    """Flatten (Hybrid)Sequential containers into a layer list. ONLY
    Sequential containers are flattened: a composite block with its own
    hybrid_forward (residual blocks, branches) is kept whole and will run
    as an fp32 island — flattening it would silently drop its skip/branch
    logic."""
    if type(net).__name__ in ("Sequential", "HybridSequential"):
        out = []
        for k in net._children.values():
            out.extend(_iter_chain(k))
        return out
    return [net]


def _fold_conv_bn(w, b, bn):
    """Fold one BatchNorm's affine into the preceding conv/dense (w, b)."""
    gamma = bn.gamma.data().asnumpy()
    beta = bn.beta.data().asnumpy()
    mean = bn.running_mean.data().asnumpy()
    var = bn.running_var.data().asnumpy()
    if not bn._scale:
        gamma = np.ones_like(gamma)
    f = gamma / np.sqrt(var + bn._epsilon)
    w = w * f.reshape((-1,) + (1,) * (w.ndim - 1))
    b = (b if b is not None else 0.0) * f + beta - mean * f
    return w.astype(np.float32), b.astype(np.float32)


def _bn_affine(bn):
    """Inference-mode BatchNorm as a per-channel affine y = a*x + b."""
    gamma = bn.gamma.data().asnumpy()
    beta = bn.beta.data().asnumpy()
    mean = bn.running_mean.data().asnumpy()
    var = bn.running_var.data().asnumpy()
    if not bn._scale:
        gamma = np.ones_like(gamma)
    a = gamma / np.sqrt(var + bn._epsilon)
    b = beta - mean * a
    return a.astype(np.float32), b.astype(np.float32)


def _conv_attrs(lyr):
    return dict(kernel=lyr._kernel, stride=lyr._strides,
                dilate=lyr._dilation, pad=lyr._padding,
                num_filter=lyr._channels, num_group=lyr._groups)


def _fold_resunit(u):
    """Fold a v1 ResidualUnit's conv+BN pairs (body) and its projection
    shortcut. Returns (body, proj): body = [{lyr, w, b, inner}] where
    `inner` convs are followed by relu + int8 requantization and the last
    conv's int32 accumulator flows into the skip-add; proj = {lyr, w, b}
    or None (identity shortcut)."""
    body = []
    n = len(u.convs)
    for i in range(n):
        conv = u.convs[i]
        w = conv.weight.data().asnumpy()
        b = conv.bias.data().asnumpy() if conv.bias is not None else None
        w, b = _fold_conv_bn(w, b, u.norms[i])
        body.append(dict(lyr=conv, w=w, b=b, inner=i < n - 1))
    proj = None
    if u.proj is not None:
        w = u.proj.weight.data().asnumpy()
        b = None
        if u.proj_norm is not None:
            w, b = _fold_conv_bn(w, b, u.proj_norm)
        proj = dict(lyr=u.proj, w=w.astype(np.float32), b=b)
    return body, proj


def _fire_convs(u):
    """The three convs of a squeezenet Fire module."""
    return (u.squeeze, u.left, u.right)


def _fold_tower(u):
    """Nested folded structure for an inception _Tower (parallel branches
    concatenated on channels). Each plain branch is a folded CHAIN (the
    same records the top-level walker uses); a ('split', ...) branch is a
    _Fanout: stem chain -> concat(b1 chain, b2 chain). Every chain record
    carries its own amax slot (filled during calibration).

    A densenet _DenseLayer is the two-branch special case
    concat(x, body(x)): an IDENTITY branch (empty chain) + the
    bn-relu-conv body chain (quantizable since standalone BN emits as an
    int8 per-channel affine)."""
    if type(u).__name__ == "_DenseLayer":
        return [{"recs": []},
                {"recs": _fold_batchnorm(_iter_chain(u.body))}]
    branches = []
    for child in u._children.values():
        if type(child).__name__ == "_Fanout":
            branches.append({
                "fanout": {
                    "stem": _fold_batchnorm(_iter_chain(child.stem)),
                    "b1": _fold_batchnorm(_iter_chain(child.b1)),
                    "b2": _fold_batchnorm(_iter_chain(child.b2)),
                }})
        else:
            branches.append({"recs": _fold_batchnorm(_iter_chain(child))})
    return branches


def _chain_quantizable(recs):
    """A branch chain is int8-eligible when every record is a plain
    conv (no fused non-relu act), relu, valid pool, flatten, or dropout."""
    from ..gluon import nn as gnn

    for kind, lyr, _w, _b in recs:
        if kind == "conv":
            if getattr(lyr, "_channels_last", False):
                return False
            continue
        if kind == "bn_alone":
            if getattr(lyr, "_axis", 1) != 1:
                return False
            continue
        if isinstance(lyr, (gnn.MaxPool2D, gnn.AvgPool2D)):
            kw = lyr._kwargs
            if kw.get("pooling_convention", "valid") != "valid" \
                    and kw["pool_type"] != "max":
                return False
            if (kw["pool_type"] == "avg"
                    and not kw.get("count_include_pad", True)
                    and any(_p for _p in np.atleast_1d(kw.get("pad", 0)))):
                return False
            continue
        if isinstance(lyr, gnn.Activation) and lyr._act_type == "relu":
            continue
        if isinstance(lyr, (gnn.Flatten, gnn.Dropout)):
            continue
        return False
    return True


def _tower_quantizable(branches):
    for br in branches:
        if "fanout" in br:
            f = br["fanout"]
            if not all(_chain_quantizable(f[k]) for k in ("stem", "b1", "b2")):
                return False
        elif not _chain_quantizable(br["recs"]):
            return False
    return True


def _fold_resunit_v2(u):
    """Record chains for a v2 (pre-activation) ResidualUnit. Returns
    (pre, mid, last, proj): pre = [bn0, relu] (the shared pre-activation
    that also feeds the projection shortcut), mid = [conv0, bn1, relu,
    conv1, ...] up to but excluding the last conv, last = the final conv
    record (its int32 accumulator flows into the skip add, no relu after
    the add in v2), proj = the 1x1 projection conv record or None."""
    from ..gluon import nn as gnn

    n = len(u.convs)
    relu = gnn.Activation("relu")

    def conv_rec(c):
        w = c.weight.data().asnumpy().astype(np.float32)
        b = (c.bias.data().asnumpy().astype(np.float32)
             if c.bias is not None else None)
        return ("conv", c, w, b)

    pre = [("bn_alone", u.norms[0], None, None),
           ("relu", relu, None, None)]
    mid = []
    for i in range(n):
        if i > 0:
            mid.append(("bn_alone", u.norms[i], None, None))
            mid.append(("relu", relu, None, None))
        if i < n - 1:
            mid.append(conv_rec(u.convs[i]))
    last = conv_rec(u.convs[n - 1])
    proj = conv_rec(u.proj) if u.proj is not None else None
    return pre, mid, last, proj


def _fold_batchnorm(layers):
    """Fold BatchNorm into the preceding conv/dense weights
    (ref: the quantize pass fuses conv+bn before quantizing).
    Returns list of (kind, layer, w, b) records in float32."""
    from ..gluon import nn as gnn

    records = []
    for layer in layers:
        if type(layer).__name__ in ("_Tower", "_DenseLayer"):
            # inception tower: parallel conv-chain branches concatenated
            # on channels (possibly with one nested _Fanout split); each
            # branch quantizes as a sub-chain and rescales to ONE tower
            # output scale so the concat is a pure int8 op. Demoted to an
            # fp32 island later if any branch is not chain-quantizable.
            records.append(("tower", layer, None, None))
            continue
        if (type(layer).__name__ == "Fire"
                and not any(getattr(c, "_channels_last", False)
                            for c in _fire_convs(layer))):
            # squeezenet branch-concat unit: squeeze conv -> two parallel
            # expand convs -> channel concat, all relu, no BN — both
            # branches requantize to ONE calibrated output scale so the
            # concat itself is a pure int8 op
            records.append(("fire", layer, None, None))
            continue
        if (type(layer).__name__ == "ResidualUnit"
                and getattr(layer, "_version", None) == 2
                and not any(getattr(c, "_channels_last", False)
                            for c in layer.convs)):
            # v2 pre-activation: bn->relu precede each conv; standalone
            # BNs emit as int8 affines, so the unit quantizes too —
            # skip-add on dequantized accumulators, NO relu after the add
            records.append(("resunit2", layer, None, None))
            continue
        if (type(layer).__name__ == "ResidualUnit"
                and getattr(layer, "_version", None) == 1
                and not any(getattr(c, "_channels_last", False)
                            for c in layer.convs)):
            # v1 residual units quantize as a unit: int8 conv body +
            # int8 shortcut, fp32 dequant-add-requant at the junction
            # (ref: quantized resnet in src/operator/quantization/ — the
            # reference's flagship int8 model IS ResNet). v2's
            # pre-activation ordering breaks the conv+BN fold, so v2
            # units stay fp32 islands.
            records.append(("resunit", layer, None, None))
            continue
        if isinstance(layer, gnn.BatchNorm):
            # fold only into a PLAIN conv/dense: a fused activation between
            # the linear op and the BN makes the fold invalid
            # (BN(act(conv)) != act(f*conv + shift))
            if (not records or records[-1][0] not in ("conv", "dense")
                    or records[-1][1]._act_type is not None):
                records.append(("bn_alone", layer, None, None))
                continue
            kind, lyr, w, b = records[-1]
            w, b = _fold_conv_bn(w, b, layer)
            records[-1] = (kind, lyr, w, b)
        elif hasattr(layer, "weight") and getattr(layer, "_transpose", False) is False \
                and type(layer).__name__.startswith("Conv") \
                and layer._act_type in (None, "relu"):
            w = layer.weight.data().asnumpy()
            b = layer.bias.data().asnumpy() if layer.bias is not None else None
            records.append(("conv", layer, w, b))
        elif type(layer).__name__ == "Dense" and layer._act_type in (None, "relu"):
            w = layer.weight.data().asnumpy()
            b = layer.bias.data().asnumpy() if layer.bias is not None else None
            records.append(("dense", layer, w, b))
        else:
            # composite blocks, transposed convs, and conv/dense with fused
            # non-relu activations run whole as fp32 islands
            records.append((type(layer).__name__, layer, None, None))
    return records


def as_chain(net, probe=None):
    """Flatten the standard zoo composition `output(features(x))` into a
    HybridSequential sharing the same Parameters, so chain-only passes
    (`quantize_net`) can see the full layer stack of AlexNet/VGG-class
    models instead of one opaque fp32 island.

    The flattening assumes the block's forward is exactly
    output∘features; pass a `probe` batch to VERIFY that numerically
    (raises on mismatch) — composite forwards (residual adds, branches)
    fail the probe instead of being silently mis-flattened."""
    from ..gluon import nn as gnn

    if not (hasattr(net, "features") and hasattr(net, "output")):
        raise ValueError(
            "as_chain: net has no features/output children (zoo chain "
            "pattern); pass a (Hybrid)Sequential directly instead")
    chain = gnn.HybridSequential(prefix="")
    chain.add(net.features)
    chain.add(net.output)
    if probe is not None:
        from .. import autograd as _ag

        prev = _ag.set_training(False)
        try:
            a = net(probe).asnumpy()
            b = chain(probe).asnumpy()
        finally:
            _ag.set_training(prev)
        if not np.allclose(a, b, rtol=1e-5, atol=1e-5):
            raise ValueError(
                "as_chain: output(features(x)) does not reproduce the "
                "net's forward — composite model, cannot flatten")
    return chain


class QuantizedNet:
    """Jittable int8 inference program produced by `quantize_net`.

    Dataflow per quantized layer (symmetric per-tensor scales s = 127/amax):
      q_in int8  --int8 conv/fc, int32 accum-->  acc
      acc + round(bias * s_in * s_w)  --*(s_out/(s_in*s_w)), round, clip-->
      q_out int8  (ReLU = max(q_out, 0) since zero-point is 0)
    The final layer dequantizes to float32 logits.
    """

    def __init__(self, steps, s_in):
        import jax

        self._steps = steps
        self._s_in = float(s_in)
        self._jit = jax.jit(self._run)

    def _run(self, x):
        from ..ops import quantized as qops
        from ..ops import nn as nnops

        s = self._s_in
        q = jnp.clip(jnp.round(x * s), -127, 127).astype(jnp.int8)
        for step in self._steps:
            kind = step["kind"]
            if kind in ("conv", "dense"):
                if kind == "conv":
                    acc = qops.quantized_conv(
                        q, step["qw"], step["qb"], no_bias=step["qb"] is None,
                        **step["attrs"])
                else:
                    acc = qops.quantized_fully_connected(
                        q, step["qw"], step["qb"], no_bias=step["qb"] is None,
                        **step["attrs"])
                if step["last"]:
                    if step["relu"]:
                        acc = jnp.maximum(acc, 0)  # zero-point 0: relu on acc
                    return acc.astype(jnp.float32) * step["deq_scale"]
                out = acc.astype(jnp.float32) * step["requant_scale"]
                if step["relu"]:
                    out = jnp.maximum(out, 0)
                q = jnp.clip(jnp.round(out), -127, 127).astype(jnp.int8)
                s = step["s_out"]
            elif kind == "resunit":
                # all convolutions int8 (MXU integer path); the skip-add
                # happens in fp32 on the dequantized int32 accumulators —
                # a fused elementwise epilogue, no extra matmul FLOPs
                q_in = q
                h = q
                body32 = None
                for sub in step["body"]:
                    acc = qops.quantized_conv(
                        h, sub["qw"], sub["qb"], no_bias=sub["qb"] is None,
                        **sub["attrs"])
                    if sub["inner"]:
                        out = jnp.maximum(
                            acc.astype(jnp.float32) * sub["requant_scale"], 0)
                        h = jnp.clip(jnp.round(out), -127,
                                     127).astype(jnp.int8)
                    else:
                        body32 = acc.astype(jnp.float32) * sub["deq_scale"]
                if step["proj"] is not None:
                    accp = qops.quantized_conv(
                        q_in, step["proj"]["qw"], step["proj"]["qb"],
                        no_bias=step["proj"]["qb"] is None,
                        **step["proj"]["attrs"])
                    skip32 = accp.astype(jnp.float32) * step["proj"]["deq_scale"]
                else:
                    skip32 = q_in.astype(jnp.float32) * step["skip_deq"]
                out32 = jnp.maximum(body32 + skip32, 0)
                q = jnp.clip(jnp.round(out32 * step["s_out"]), -127,
                             127).astype(jnp.int8)
                s = step["s_out"]
            elif kind == "resunit2":
                # v2: shared pre-activation feeds body AND projection;
                # skip-add on dequantized accumulators, NO relu after
                # the add (pre-activation ordering), then requantize
                q_in = q
                qp = self._exec_branch(step["pre"], q)
                if step["proj"] is not None:
                    accp = qops.quantized_conv(
                        qp, step["proj"]["qw"], step["proj"]["qb"],
                        no_bias=step["proj"]["qb"] is None,
                        **step["proj"]["attrs"])
                    skip32 = (accp.astype(jnp.float32)
                              * step["proj"]["deq_scale"])
                else:
                    skip32 = q_in.astype(jnp.float32) * step["skip_deq"]
                qm = self._exec_branch(step["mid"], qp)
                accl = qops.quantized_conv(
                    qm, step["last"]["qw"], step["last"]["qb"],
                    no_bias=step["last"]["qb"] is None,
                    **step["last"]["attrs"])
                body32 = accl.astype(jnp.float32) * step["last"]["deq_scale"]
                out32 = body32 + skip32
                q = jnp.clip(jnp.round(out32 * step["s_out"]), -127,
                             127).astype(jnp.int8)
                s = step["s_out"]
            elif kind == "tower":
                parts = []
                for br in step["branches"]:
                    if "fanout" in br:
                        f = br["fanout"]
                        qs2 = self._exec_branch(f["stem"], q)
                        for part in f["parts"]:
                            parts.append(self._rescaled(
                                part["steps"], part["rescale"], qs2))
                    else:
                        parts.append(self._rescaled(
                            br["steps"], br["rescale"], q))
                q = jnp.concatenate(parts, axis=1)
                s = step["s_out"]
            elif kind == "fire":
                def _branch(qx, sub, relu=True):
                    acc = qops.quantized_conv(
                        qx, sub["qw"], sub["qb"], no_bias=False,
                        **sub["attrs"])
                    out = acc.astype(jnp.float32) * sub["requant_scale"]
                    if relu:
                        out = jnp.maximum(out, 0)
                    return jnp.clip(jnp.round(out), -127,
                                    127).astype(jnp.int8)

                qs = _branch(q, step["squeeze"])
                # both branches share s_out, so the concat stays int8
                q = jnp.concatenate(
                    [_branch(qs, step["left"]), _branch(qs, step["right"])],
                    axis=1)
                s = step["s_out"]
            elif kind == "affine":
                out = (q.astype(jnp.float32) * step["mul"]) + step["add"]
                q = jnp.clip(jnp.round(out), -127, 127).astype(jnp.int8)
                s = step["s_out"]
            elif kind == "maxpool":
                q = qops.quantized_pooling(q, pool_type="max", **step["attrs"])
            elif kind == "avgpool":
                q = qops.quantized_pooling(q, pool_type="avg", **step["attrs"])
            elif kind == "relu":
                q = jnp.maximum(q, 0)
            elif kind == "flatten":
                q = q.reshape(q.shape[0], -1)
            elif kind == "fp32":
                # fallback: dequantize, run the fp32 layer, requantize
                x32 = q.astype(jnp.float32) / s
                x32 = step["fn"](x32)
                s = step["s_out"]
                q = jnp.clip(jnp.round(x32 * s), -127, 127).astype(jnp.int8)
            else:  # identity (Dropout at inference)
                pass
        return q.astype(jnp.float32) / s

    def _exec_branch(self, bsteps, qx):
        """Execute an int8 sub-chain (tower branch / unit segment)."""
        from ..ops import quantized as qo

        for st in bsteps:
            if st["kind"] == "conv":
                acc = qo.quantized_conv(
                    qx, st["qw"], st["qb"], no_bias=st["qb"] is None,
                    **st["attrs"])
                out = acc.astype(jnp.float32) * st["requant_scale"]
                if st["relu"]:
                    out = jnp.maximum(out, 0)
                qx = jnp.clip(jnp.round(out), -127, 127).astype(jnp.int8)
            elif st["kind"] in ("maxpool", "avgpool"):
                qx = qo.quantized_pooling(qx, pool_type=st["kind"][:3],
                                          **st["attrs"])
            elif st["kind"] == "affine":
                o = qx.astype(jnp.float32) * st["mul"] + st["add"]
                qx = jnp.clip(jnp.round(o), -127, 127).astype(jnp.int8)
            elif st["kind"] == "relu":
                qx = jnp.maximum(qx, 0)
            elif st["kind"] == "flatten":
                qx = qx.reshape(qx.shape[0], -1)
        return qx

    def _rescaled(self, bsteps, rescale, qx):
        qb = self._exec_branch(bsteps, qx)
        return jnp.clip(jnp.round(qb.astype(jnp.float32) * rescale),
                        -127, 127).astype(jnp.int8)

    def apply(self, x):
        """The traceable forward (jnp in -> jnp out): compose under an
        outer jit / vmap / lax.scan; `__call__` is its jitted form."""
        return self._run(x)

    @property
    def num_fp32_islands(self):
        """Layers that fell back to fp32 between dequant/quant pairs;
        0 means the whole program runs on the int8 path."""
        return sum(1 for s in self._steps if s["kind"] == "fp32")

    def __call__(self, x):
        xd = x._data if isinstance(x, NDArray) else jnp.asarray(x)
        return NDArray._from_data(self._jit(xd))


def quantize_net(net, calib_data, num_calib_batches=10, calib_mode="minmax",
                 quantized_dtype="int8"):
    """fp32 Gluon chain -> QuantizedNet with calibrated activation scales
    (ref: python quantize_model flow: collect stats -> set ranges -> emit
    quantized graph). Supports Conv2D/Dense (+folded BatchNorm, fused
    relu), Max/Avg/Global pooling (incl. ceil-mode int8 max), Flatten,
    Activation('relu'), Dropout, and three composite-unit families —
    v1 residual units (int8 body + shortcut, fp32 dequant-add-requant at
    the skip junction), squeezenet Fire modules, and inception towers
    (parallel int8 sub-chains rescaled to one concat scale) — covering
    the reference's documented int8 model set (resnet / inception /
    mobilenet, src/operator/quantization/); anything else runs as an
    fp32 island between dequantize/quantize pairs."""
    from ..gluon import nn as gnn

    if quantized_dtype != "int8":
        raise ValueError("only int8 is supported")
    layers = _iter_chain(net)
    records = _fold_batchnorm(layers)
    # folded v1 residual units + per-internal-conv calibration ranges
    folded_units = {i: _fold_resunit(lyr)
                    for i, (kind, lyr, _w, _b) in enumerate(records)
                    if kind == "resunit"}
    res_amax = {i: [1e-8] * (len(body) - 1)
                for i, (body, _proj) in folded_units.items()}
    # fire units: one internal range (the squeeze activation)
    fire_amax = {i: 1e-8 for i, (kind, _l, _w, _b) in enumerate(records)
                 if kind == "fire"}
    # v2 residual units: pre/mid chains + last-conv/proj records, with
    # per-record ranges for the requant points
    folded_v2 = {i: _fold_resunit_v2(lyr)
                 for i, (kind, lyr, _w, _b) in enumerate(records)
                 if kind == "resunit2"}
    v2_amax = {i: {"pre": [1e-8] * len(pre), "mid": [1e-8] * len(mid)}
               for i, (pre, mid, _l, _p) in folded_v2.items()}
    # towers: folded branch trees + per-branch-record ranges (demote to
    # an fp32 island when any branch is not chain-quantizable)
    folded_towers = {}
    tower_amax = {}
    for i, (kind, lyr, _w, _b) in enumerate(records):
        if kind != "tower":
            continue
        branches = _fold_tower(lyr)
        if not _tower_quantizable(branches):
            records[i] = (type(lyr).__name__, lyr, None, None)
            continue
        folded_towers[i] = branches
        am = []
        for br in branches:
            if "fanout" in br:
                f = br["fanout"]
                am.append({k: [1e-8] * len(f[k])
                           for k in ("stem", "b1", "b2")})
            else:
                am.append({"recs": [1e-8] * len(br["recs"])})
        tower_amax[i] = am

    def _sim_chain(recs, x, amaxes):
        """fp32 simulation of a folded branch chain, recording per-record
        activation ranges at the (post-relu-fused) conv outputs."""
        from ..ops import nn as nnops

        for j, (kind, lyr, w, b) in enumerate(recs):
            if kind == "conv":
                x = nnops.convolution(
                    x, jnp.asarray(w), None if b is None else jnp.asarray(b),
                    no_bias=b is None, **_conv_attrs(lyr))
                if lyr._act_type == "relu":
                    x = jnp.maximum(x, 0)
                amaxes[j] = max(amaxes[j], float(jnp.max(jnp.abs(x))))
            elif kind == "bn_alone":
                a, bb = _bn_affine(lyr)
                x = x * a.reshape(1, -1, 1, 1) + bb.reshape(1, -1, 1, 1)
                amaxes[j] = max(amaxes[j], float(jnp.max(jnp.abs(x))))
            elif isinstance(lyr, (gnn.MaxPool2D, gnn.AvgPool2D)):
                x = nnops.pooling(x, **lyr._kwargs)
            elif isinstance(lyr, gnn.Activation):
                x = jnp.maximum(x, 0)
            elif isinstance(lyr, gnn.Flatten):
                x = x.reshape(x.shape[0], -1)
            # Dropout: identity at inference
        return x

    def _pool_quantizable(lyr):
        """int8 pooling: valid-convention pools, plus ceil-mode ('full')
        MAX pools (the int8-min pad identity keeps the max exact, except
        when a ceil window falls entirely in padding — then fp32 island).
        Non-count-include-pad avg with padding stays fp32."""
        kw = lyr._kwargs
        conv = kw.get("pooling_convention", "valid")
        if conv == "full":
            if kw["pool_type"] != "max":
                return False
            # reject when any ceil window would be empty (all padding)
            # — mirrors ops.nn.pooling's has_empty_window rule; shapes
            # are unknown here, so use the calibration-time shapes
            return not getattr(lyr, "_q_has_empty_window", False)
        if conv != "valid":
            return False
        if (kw["pool_type"] == "avg" and not kw.get("count_include_pad", True)
                and any(_p for _p in np.atleast_1d(kw.get("pad", 0)))):
            return False
        return True

    # ---- pass 1: fp32 simulation to collect per-step activation ranges ----
    def sim_steps(x):
        """Run the folded-fp32 chain, yielding (record_index, output)."""
        for i, (kind, lyr, w, b) in enumerate(records):
            if kind == "conv":
                from ..ops import nn as nnops

                x = nnops.convolution(
                    x, jnp.asarray(w), None if b is None else jnp.asarray(b),
                    kernel=lyr._kernel, stride=lyr._strides,
                    dilate=lyr._dilation, pad=lyr._padding,
                    num_filter=lyr._channels, num_group=lyr._groups,
                    no_bias=b is None)
                if lyr._act_type == "relu":
                    x = jnp.maximum(x, 0)
            elif kind == "dense":
                from ..ops import nn as nnops

                x = nnops.fully_connected(
                    x, jnp.asarray(w), None if b is None else jnp.asarray(b),
                    num_hidden=lyr._units, no_bias=b is None,
                    flatten=lyr._flatten)
                if lyr._act_type == "relu":
                    x = jnp.maximum(x, 0)
            elif kind == "resunit":
                from ..ops import nn as nnops

                body, proj = folded_units[i]
                h = x
                for j, rec in enumerate(body):
                    h = nnops.convolution(
                        h, jnp.asarray(rec["w"]),
                        None if rec["b"] is None else jnp.asarray(rec["b"]),
                        no_bias=rec["b"] is None, **_conv_attrs(rec["lyr"]))
                    if rec["inner"]:
                        h = jnp.maximum(h, 0)
                        res_amax[i][j] = max(res_amax[i][j],
                                             float(jnp.max(jnp.abs(h))))
                if proj is None:
                    skip = x
                else:
                    skip = nnops.convolution(
                        x, jnp.asarray(proj["w"]),
                        None if proj["b"] is None else jnp.asarray(proj["b"]),
                        no_bias=proj["b"] is None,
                        **_conv_attrs(proj["lyr"]))
                x = jnp.maximum(skip + h, 0)
            elif kind == "resunit2":
                from ..ops import nn as nnops

                pre, mid, last, proj = folded_v2[i]
                h = _sim_chain(pre, x, v2_amax[i]["pre"])
                skip = x
                if proj is not None:
                    _pk, pl, pw, pb = proj
                    skip = nnops.convolution(
                        h, jnp.asarray(pw),
                        None if pb is None else jnp.asarray(pb),
                        no_bias=pb is None, **_conv_attrs(pl))
                h = _sim_chain(mid, h, v2_amax[i]["mid"])
                _lk, ll, lw, lb = last
                h = nnops.convolution(
                    h, jnp.asarray(lw),
                    None if lb is None else jnp.asarray(lb),
                    no_bias=lb is None, **_conv_attrs(ll))
                x = skip + h
            elif kind == "tower":
                parts = []
                for br, am in zip(folded_towers[i], tower_amax[i]):
                    if "fanout" in br:
                        f = br["fanout"]
                        h = _sim_chain(f["stem"], x, am["stem"])
                        parts.append(_sim_chain(f["b1"], h, am["b1"]))
                        parts.append(_sim_chain(f["b2"], h, am["b2"]))
                    else:
                        parts.append(_sim_chain(br["recs"], x, am["recs"]))
                x = jnp.concatenate(parts, axis=1)
            elif kind == "fire":
                from ..ops import nn as nnops

                sq, left, right = _fire_convs(lyr)
                s = jnp.maximum(nnops.convolution(
                    x, jnp.asarray(sq.weight.data()._data),
                    jnp.asarray(sq.bias.data()._data),
                    no_bias=False, **_conv_attrs(sq)), 0)
                fire_amax[i] = max(fire_amax[i], float(jnp.max(s)))
                outs = []
                for c in (left, right):
                    outs.append(jnp.maximum(nnops.convolution(
                        s, jnp.asarray(c.weight.data()._data),
                        jnp.asarray(c.bias.data()._data),
                        no_bias=False, **_conv_attrs(c)), 0))
                x = jnp.concatenate(outs, axis=1)
            elif isinstance(lyr, (gnn.MaxPool2D, gnn.AvgPool2D,
                                  gnn.GlobalMaxPool2D, gnn.GlobalAvgPool2D)):
                from ..ops import nn as nnops

                kw = lyr._kwargs
                if kw.get("pooling_convention") == "full":
                    # record whether any ceil window is all-padding at
                    # THESE shapes (gates int8 eligibility below)
                    kk = np.atleast_1d(kw["kernel"])
                    ss = np.atleast_1d(kw["stride"])
                    pp = np.atleast_1d(kw.get("pad", 0))
                    empty = False
                    for ax in range(len(kk)):
                        dim = x.shape[2 + ax]
                        in_sz = dim + 2 * int(pp[ax % len(pp)])
                        kx = int(kk[ax % len(kk)])
                        sx = int(ss[ax % len(ss)])
                        rem = (in_sz - kx) % sx
                        extra = (sx - rem) % sx if rem != 0 else 0
                        n_out = 1 + (in_sz - kx + extra) // sx
                        if (n_out - 1) * sx >= int(pp[ax % len(pp)]) + dim:
                            empty = True
                    lyr._q_has_empty_window = empty
                x = nnops.pooling(x, **lyr._kwargs)
            elif isinstance(lyr, gnn.Flatten):
                x = x.reshape(x.shape[0], -1)
            elif isinstance(lyr, gnn.Dropout):
                pass
            elif kind == "bn_alone":
                from ..ops import nn as nnops

                x = nnops.batch_norm(
                    x, jnp.asarray(lyr.gamma.data()._data),
                    jnp.asarray(lyr.beta.data()._data),
                    jnp.asarray(lyr.running_mean.data()._data),
                    jnp.asarray(lyr.running_var.data()._data),
                    eps=lyr._epsilon, fix_gamma=not lyr._scale,
                    use_global_stats=True)
            else:
                x = lyr(NDArray._from_data(x))._data
            yield i, x

    amax_in = 1e-8
    amax_out = [1e-8] * len(records)
    n_done = 0
    for batch in calib_data:
        if n_done >= num_calib_batches:
            break
        data = batch.data[0] if hasattr(batch, "data") else batch[0]
        x = data._data if isinstance(data, NDArray) else jnp.asarray(data)
        amax_in = max(amax_in, float(jnp.max(jnp.abs(x))))
        for i, out in sim_steps(x):
            amax_out[i] = max(amax_out[i], float(jnp.max(jnp.abs(out))))
        n_done += 1
    if n_done == 0:
        raise ValueError("quantize_net: empty calibration iterator")

    if calib_mode == "entropy":
        # second pass (requires a re-iterable calib_data): per-step
        # |activation| histograms inside the minmax range, then a KL sweep
        # picks each quantized step's clipping threshold
        # (ref: _get_optimal_threshold entropy mode)
        nbins = 1024
        hists = [np.zeros(nbins) for _ in records]
        n2 = 0
        for batch in calib_data:
            if n2 >= num_calib_batches:
                break
            data = batch.data[0] if hasattr(batch, "data") else batch[0]
            x = data._data if isinstance(data, NDArray) else jnp.asarray(data)
            for i, out in sim_steps(x):
                o = np.abs(np.asarray(out)).ravel()
                hists[i] += np.histogram(o, bins=nbins,
                                         range=(0, amax_out[i]))[0]
            n2 += 1
        if n2 == 0:
            raise ValueError("calib_mode='entropy' needs a re-iterable "
                             "calib_data (the first pass consumed it)")
        for i, rec in enumerate(records):
            if rec[0] in ("conv", "dense") and hists[i].sum() > 0:
                amax_out[i] = _kl_sweep(hists[i], amax_out[i])
    elif calib_mode != "minmax":
        raise ValueError(f"unsupported calib_mode {calib_mode!r} "
                         "(use 'minmax' or 'entropy')")

    # ---- pass 2: emit the int8 program ----
    def _qweight(w, acc_bcast_shape):
        """Per-output-channel symmetric int8 weight quantization (the
        reference's channel-wise MKLDNN option — tighter than per-tensor;
        output channel = axis 0 for conv (O,I,kh,kw) and dense (U,in)).
        Returns (qw int8, s_w (C,), s_w broadcast over the int32
        accumulator)."""
        amax_w = np.abs(w).reshape(w.shape[0], -1).max(axis=1)
        s_w = 127.0 / np.maximum(amax_w, 1e-8)
        qw = jnp.asarray(
            np.clip(np.round(w * s_w.reshape((-1,) + (1,) * (w.ndim - 1))),
                    -127, 127).astype(np.int8))
        return qw, s_w, s_w.reshape(acc_bcast_shape).astype(np.float32)

    s_in0 = 127.0 / amax_in

    def _emit_chain(recs, s_in_c, amaxes):
        """Emit executable int8 steps for a folded branch chain; every
        conv requantizes to its own calibrated scale. Returns
        (steps, final scale)."""
        out = []
        s_cur = s_in_c
        for j, (kind, lyr, w, b) in enumerate(recs):
            if kind == "conv":
                qw, s_w, s_w_b = _qweight(w, (1, -1, 1, 1))
                qb = (None if b is None else
                      jnp.asarray(np.round(b * s_cur * s_w)
                                  .astype(np.int32)))
                s_j = 127.0 / amaxes[j]
                out.append(dict(
                    kind="conv", qw=qw, qb=qb, attrs=_conv_attrs(lyr),
                    relu=lyr._act_type == "relu", last=False,
                    requant_scale=jnp.asarray(s_j / (s_cur * s_w_b)),
                    deq_scale=jnp.asarray(1.0 / (s_cur * s_w_b)),
                    s_out=s_j))
                s_cur = s_j
            elif kind == "bn_alone":
                a, bb = _bn_affine(lyr)
                s_j = 127.0 / amaxes[j]
                out.append(dict(
                    kind="affine",
                    mul=jnp.asarray((a * (s_j / s_cur))
                                    .reshape(1, -1, 1, 1)),
                    add=jnp.asarray((bb * s_j).reshape(1, -1, 1, 1)),
                    s_out=s_j))
                s_cur = s_j
            elif isinstance(lyr, (gnn.MaxPool2D, gnn.AvgPool2D)):
                kw = lyr._kwargs
                out.append(dict(
                    kind="maxpool" if kw["pool_type"] == "max"
                    else "avgpool",
                    attrs=dict(kernel=kw["kernel"], stride=kw["stride"],
                               pad=kw["pad"],
                               pooling_convention=kw.get(
                                   "pooling_convention", "valid"))))
            elif isinstance(lyr, gnn.Activation):
                out.append(dict(kind="relu"))
            elif isinstance(lyr, gnn.Flatten):
                out.append(dict(kind="flatten"))
            else:  # Dropout
                out.append(dict(kind="identity"))
        return out, s_cur

    steps = []
    s_prev = s_in0
    last_q = max((i for i, r in enumerate(records) if r[0] in ("conv", "dense")),
                 default=-1)
    if last_q != len(records) - 1:
        last_q = -1  # trailing non-compute layers: dequantize at the very end
    for i, (kind, lyr, w, b) in enumerate(records):
        s_out = 127.0 / amax_out[i]
        if kind in ("conv", "dense"):
            bshape = (1, -1, 1, 1) if kind == "conv" else (1, -1)
            qw, s_w, s_w_b = _qweight(w, bshape)
            qb = (None if b is None else
                  jnp.asarray(np.round(b * s_prev * s_w).astype(np.int32)))
            attrs = (dict(kernel=lyr._kernel, stride=lyr._strides,
                          dilate=lyr._dilation, pad=lyr._padding,
                          num_filter=lyr._channels, num_group=lyr._groups)
                     if kind == "conv" else
                     dict(num_hidden=lyr._units, flatten=lyr._flatten))
            steps.append(dict(
                kind=kind, qw=qw, qb=qb, attrs=attrs,
                relu=lyr._act_type == "relu",
                last=i == last_q,
                requant_scale=jnp.asarray(s_out / (s_prev * s_w_b)),
                deq_scale=jnp.asarray(1.0 / (s_prev * s_w_b)),
                s_out=s_out))
            s_prev = s_out
        elif kind == "resunit":
            # int8 residual unit: int8 conv body + int8 shortcut conv,
            # dequantized fp32 add at the junction (all FLOPs stay int8;
            # the add is a fused elementwise epilogue), relu, requantize
            # to the calibrated unit-output scale
            body, proj = folded_units[i]
            s_cur = s_prev
            subs = []
            for j, rec in enumerate(body):
                qw, s_w, s_w_b = _qweight(rec["w"], (1, -1, 1, 1))
                qb = (None if rec["b"] is None else
                      jnp.asarray(np.round(rec["b"] * s_cur * s_w)
                                  .astype(np.int32)))
                sub = dict(qw=qw, qb=qb, attrs=_conv_attrs(rec["lyr"]),
                           inner=rec["inner"])
                if rec["inner"]:
                    s_j = 127.0 / res_amax[i][j]
                    sub["requant_scale"] = jnp.asarray(s_j / (s_cur * s_w_b))
                    s_cur = s_j
                else:
                    sub["deq_scale"] = jnp.asarray(1.0 / (s_cur * s_w_b))
                subs.append(sub)
            pstep = None
            if proj is not None:
                qw, s_w, s_w_b = _qweight(proj["w"], (1, -1, 1, 1))
                pstep = dict(
                    qw=qw,
                    qb=(None if proj["b"] is None else
                        jnp.asarray(np.round(proj["b"] * s_prev * s_w)
                                    .astype(np.int32))),
                    attrs=_conv_attrs(proj["lyr"]),
                    deq_scale=jnp.asarray(1.0 / (s_prev * s_w_b)))
            steps.append(dict(kind="resunit", body=subs, proj=pstep,
                              skip_deq=1.0 / s_prev, s_out=s_out))
            s_prev = s_out
        elif kind == "resunit2":
            pre, mid, last, proj = folded_v2[i]
            pre_steps, s_pre = _emit_chain(pre, s_prev, v2_amax[i]["pre"])
            pstep = None
            if proj is not None:
                _pk, pl, pw, pb = proj
                qw, s_w, s_w_b = _qweight(pw, (1, -1, 1, 1))
                pstep = dict(
                    qw=qw,
                    qb=(None if pb is None else
                        jnp.asarray(np.round(pb * s_pre * s_w)
                                    .astype(np.int32))),
                    attrs=_conv_attrs(pl),
                    deq_scale=jnp.asarray(1.0 / (s_pre * s_w_b)))
            mid_steps, s_mid = _emit_chain(mid, s_pre, v2_amax[i]["mid"])
            _lk, ll, lw, lb = last
            qw, s_w, s_w_b = _qweight(lw, (1, -1, 1, 1))
            lstep = dict(
                qw=qw,
                qb=(None if lb is None else
                    jnp.asarray(np.round(lb * s_mid * s_w)
                                .astype(np.int32))),
                attrs=_conv_attrs(ll),
                deq_scale=jnp.asarray(1.0 / (s_mid * s_w_b)))
            steps.append(dict(kind="resunit2", pre=pre_steps,
                              mid=mid_steps, last=lstep, proj=pstep,
                              skip_deq=1.0 / s_prev, s_out=s_out))
            s_prev = s_out
        elif kind == "tower":
            # inception tower: each branch emits as an int8 sub-chain and
            # RESCALES its final int8 activations to the shared tower
            # scale, so the channel concat stays int8; a nested fanout's
            # two sub-branches rescale directly to the tower scale
            # (concat(concat(a,b),c) == concat(a,b,c))
            ebranches = []
            for br, am in zip(folded_towers[i], tower_amax[i]):
                if "fanout" in br:
                    f = br["fanout"]
                    stem_steps, s_stem = _emit_chain(f["stem"], s_prev,
                                                     am["stem"])
                    parts = []
                    for key in ("b1", "b2"):
                        bsteps, s_b = _emit_chain(f[key], s_stem, am[key])
                        parts.append(dict(steps=bsteps,
                                          rescale=s_out / s_b))
                    ebranches.append(dict(fanout=dict(stem=stem_steps,
                                                      parts=parts)))
                else:
                    bsteps, s_b = _emit_chain(br["recs"], s_prev,
                                              am["recs"])
                    ebranches.append(dict(steps=bsteps,
                                          rescale=s_out / s_b))
            steps.append(dict(kind="tower", branches=ebranches,
                              s_out=s_out))
            s_prev = s_out
        elif kind == "fire":
            # int8 branch-concat unit: both expand branches requantize to
            # the SAME calibrated output scale, so the channel concat is
            # a pure int8 op (no per-branch dequant)
            sq, left, right = _fire_convs(lyr)

            def _fire_conv(c, s_in_c):
                wv = c.weight.data().asnumpy()
                bv = c.bias.data().asnumpy()
                qw, s_wv, s_w_bv = _qweight(wv, (1, -1, 1, 1))
                qb = jnp.asarray(np.round(bv * s_in_c * s_wv)
                                 .astype(np.int32))
                return qw, qb, s_w_bv

            s_sq = 127.0 / fire_amax[i]
            qw_s, qb_s, s_wb_s = _fire_conv(sq, s_prev)
            qw_l, qb_l, s_wb_l = _fire_conv(left, s_sq)
            qw_r, qb_r, s_wb_r = _fire_conv(right, s_sq)
            steps.append(dict(
                kind="fire",
                squeeze=dict(qw=qw_s, qb=qb_s, attrs=_conv_attrs(sq),
                             requant_scale=jnp.asarray(
                                 s_sq / (s_prev * s_wb_s))),
                left=dict(qw=qw_l, qb=qb_l, attrs=_conv_attrs(left),
                          requant_scale=jnp.asarray(
                              s_out / (s_sq * s_wb_l))),
                right=dict(qw=qw_r, qb=qb_r, attrs=_conv_attrs(right),
                           requant_scale=jnp.asarray(
                               s_out / (s_sq * s_wb_r))),
                s_out=s_out))
            s_prev = s_out
        elif (isinstance(lyr, (gnn.MaxPool2D, gnn.AvgPool2D))
              and _pool_quantizable(lyr)):
            steps.append(dict(
                kind="maxpool" if lyr._kwargs["pool_type"] == "max" else "avgpool",
                attrs=dict(kernel=lyr._kwargs["kernel"],
                           stride=lyr._kwargs["stride"],
                           pad=lyr._kwargs["pad"],
                           pooling_convention=lyr._kwargs.get(
                               "pooling_convention", "valid"))))
            # pooling keeps the input scale (max exactly; avg to rounding)
        elif isinstance(lyr, (gnn.GlobalMaxPool2D, gnn.GlobalAvgPool2D)):
            steps.append(dict(
                kind="maxpool" if lyr._kwargs["pool_type"] == "max"
                else "avgpool",
                attrs=dict(kernel=lyr._kwargs["kernel"],
                           stride=lyr._kwargs["stride"],
                           pad=lyr._kwargs["pad"], global_pool=True)))
        elif kind == "bn_alone" and getattr(lyr, "_axis", 1) == 1:
            # standalone inference BN = per-channel affine, exact in the
            # int8 requant epilogue: q' = round(q * (a*s_out/s_in) +
            # b*s_out) — no dequantized fp32 island needed (unlocks the
            # pre-activation bn->relu->conv families: densenet, resnet v2)
            a, b = _bn_affine(lyr)
            bshape = (1, -1, 1, 1)
            steps.append(dict(
                kind="affine",
                mul=jnp.asarray((a * (s_out / s_prev)).reshape(bshape)),
                add=jnp.asarray((b * s_out).reshape(bshape)),
                s_out=s_out))
            s_prev = s_out
        elif isinstance(lyr, gnn.Activation) and lyr._act_type == "relu":
            steps.append(dict(kind="relu"))
        elif isinstance(lyr, gnn.Flatten):
            steps.append(dict(kind="flatten"))
        elif isinstance(lyr, gnn.Dropout):
            steps.append(dict(kind="identity"))
        else:
            def fp32_fn(x32, _l=lyr):
                return _l(NDArray._from_data(x32))._data

            steps.append(dict(kind="fp32", fn=fp32_fn, s_out=s_out))
            s_prev = s_out
    return QuantizedNet(steps, s_in0)
