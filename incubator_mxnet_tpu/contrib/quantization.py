"""INT8 quantization (ref: src/operator/quantization/ +
python/mxnet/contrib/quantization.py:422 quantize_model).

TPU-native: int8 matmuls hit the MXU natively; quantize/dequantize are pure
ops, calibration (minmax / entropy-lite) runs over a calibration iterator.
"""
from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from .. import autograd
from ..ndarray.ndarray import NDArray

__all__ = ["quantize", "dequantize", "requantize", "calib_minmax",
           "calib_entropy", "quantize_model"]


def quantize(data, min_range=None, max_range=None, out_type="int8"):
    """(ref: quantize op) symmetric int8 quantization -> (q, min, max)."""
    d = data._data if isinstance(data, NDArray) else jnp.asarray(data)
    if min_range is None:
        min_range = float(jnp.min(d))
    if max_range is None:
        max_range = float(jnp.max(d))
    amax = max(abs(min_range), abs(max_range), 1e-8)
    scale = 127.0 / amax
    q = jnp.clip(jnp.round(d * scale), -127, 127).astype(jnp.int8)
    return (NDArray._from_data(q), NDArray._from_data(jnp.asarray(-amax)),
            NDArray._from_data(jnp.asarray(amax)))


def dequantize(qdata, min_range, max_range, out_type="float32"):
    q = qdata._data if isinstance(qdata, NDArray) else jnp.asarray(qdata)
    amax = max_range._data if isinstance(max_range, NDArray) else jnp.asarray(max_range)
    return NDArray._from_data(q.astype(jnp.float32) * (amax / 127.0))


def requantize(qdata, min32, max32, min_calib=None, max_calib=None):
    """int32 accumulators -> int8 with calibrated range (ref: requantize op)."""
    q = qdata._data if isinstance(qdata, NDArray) else jnp.asarray(qdata)
    in_amax = float(max32.asscalar() if isinstance(max32, NDArray) else max32)
    out_amax = max_calib if max_calib is not None else in_amax
    scale = (in_amax / (2 ** 31 - 1)) * (127.0 / out_amax)
    out = jnp.clip(jnp.round(q.astype(jnp.float32) * scale), -127, 127).astype(jnp.int8)
    return (NDArray._from_data(out), NDArray._from_data(jnp.asarray(-out_amax)),
            NDArray._from_data(jnp.asarray(out_amax)))


def calib_minmax(net_or_fn, calib_iter, num_batches=10):
    """Collect per-output min/max over calibration batches
    (ref: quantization.py _collect_layer_statistics minmax mode)."""
    mins, maxs = [], []
    for i, batch in enumerate(calib_iter):
        if i >= num_batches:
            break
        data = batch.data[0] if hasattr(batch, "data") else batch[0]
        out = net_or_fn(data)
        o = out.asnumpy() if isinstance(out, NDArray) else np.asarray(out)
        mins.append(float(o.min()))
        maxs.append(float(o.max()))
    return min(mins), max(maxs)


def quantize_model(sym=None, arg_params=None, aux_params=None, net=None,
                   calib_data=None, num_calib_batches=10, quantized_dtype="int8",
                   **kwargs):
    """Quantize weights of a model to int8 with per-tensor scales
    (ref: contrib/quantization.py:422). Returns (quantized params dict,
    scales dict); activation quantization happens at op dispatch."""
    params = arg_params or {}
    qparams, scales = {}, {}
    for name, w in params.items():
        if name.endswith(("weight",)):
            q, mn, mx = quantize(w)
            qparams[name] = q
            scales[name] = (float(mn.asscalar()), float(mx.asscalar()))
        else:
            qparams[name] = w
    return qparams, scales


def _kl_divergence(p, q):
    p = p / max(p.sum(), 1e-12)
    q = q / max(q.sum(), 1e-12)
    mask = p > 0
    return float(np.sum(p[mask] * np.log(p[mask] / np.maximum(q[mask], 1e-12))))


def calib_entropy(net_or_fn, calib_iter, num_batches=10, num_bins=2048,
                  num_quantized_bins=255):
    """KL-divergence (entropy) calibration: pick the clipping threshold whose
    quantized distribution best matches the fp32 one
    (ref: python/mxnet/contrib/quantization.py _get_optimal_threshold /
    _LayerHistogramCollector — TensorRT-style entropy calibration).
    Returns (-threshold, threshold)."""
    if num_bins <= num_quantized_bins // 2:
        raise ValueError(
            f"num_bins ({num_bins}) must exceed num_quantized_bins//2 "
            f"({num_quantized_bins // 2}) for the threshold sweep")
    num_bins += num_bins % 2  # range-doubling rebin needs an even bin count
    # streaming histogram of |activations|: O(num_bins) memory, range doubles
    # (with 2:1 re-binning) when a batch exceeds it — single pass over the
    # iterator (ref: _LayerHistogramCollector keeps running histograms)
    hist = np.zeros(num_bins, np.float64)
    hi_range = None
    n_seen = 0
    for i, batch in enumerate(calib_iter):
        if i >= num_batches:
            break
        data = batch.data[0] if hasattr(batch, "data") else batch[0]
        out = net_or_fn(data)
        o = np.abs(out.asnumpy() if isinstance(out, NDArray)
                   else np.asarray(out)).reshape(-1)
        n_seen += o.size
        bmax = float(o.max()) if o.size else 0.0
        if hi_range is None:
            if bmax == 0.0:
                # don't seed the range from an all-zero batch: a later normal
                # batch would trigger ~40 range doublings and collapse all
                # histogram mass into bin 0 (zeros land in bin 0 regardless)
                continue
            hi_range = bmax
        while bmax > hi_range:
            # double the range: merge adjacent bin pairs into the lower half
            hist = hist.reshape(num_bins // 2, 2).sum(axis=1)
            hist = np.concatenate([hist, np.zeros(num_bins - num_bins // 2)])
            hi_range *= 2
        hist += np.histogram(o, bins=num_bins, range=(0, hi_range))[0]
    if n_seen == 0:
        raise ValueError("calib_entropy: no calibration data "
                         "(empty iterator or num_batches <= 0)")
    if hi_range is None:
        raise ValueError("calib_entropy: every calibration activation was "
                         "exactly zero — no threshold can be calibrated for "
                         "this layer (check the calibration data)")
    amax = hi_range
    edges = np.linspace(0, hi_range, num_bins + 1)

    best_kl, best_t = None, amax
    # sweep candidate thresholds (same loop structure as the reference)
    for i in range(num_quantized_bins // 2, num_bins + 1,
                   max(1, num_bins // 128)):
        t = edges[i] if i < len(edges) else amax
        p = hist[:i].astype(np.float64).copy()
        outliers = hist[i:].sum()
        if len(p) == 0 or p.sum() + outliers == 0:
            continue
        p[-1] += outliers  # clip outliers into the last bin
        # quantize p into num_quantized_bins then expand back
        factor = len(p) / num_quantized_bins
        q = np.zeros_like(p)
        for j in range(num_quantized_bins):
            lo, hi = int(j * factor), max(int((j + 1) * factor), int(j * factor) + 1)
            chunk = p[lo:hi]
            nz = (chunk > 0).sum()
            if nz:
                q[lo:hi] = np.where(chunk > 0, chunk.sum() / nz, 0)
        kl = _kl_divergence(p, q)
        if best_kl is None or kl < best_kl:
            best_kl, best_t = kl, float(t)
    return -best_t, best_t
