"""Contrib IO: gluon DataLoader → Module-style DataIter bridge
(ref: python/mxnet/contrib/io.py DataLoaderIter:28 — lets the imperative
data pipeline feed the symbolic Module/fit world)."""
from __future__ import annotations

import numpy as np

from ..io import DataBatch, DataDesc, DataIter
from ..ndarray.ndarray import NDArray
from .. import ndarray as nd

__all__ = ["DataLoaderIter"]


class DataLoaderIter(DataIter):
    """Wrap a `gluon.data.DataLoader` as a `DataIter` with
    provide_data/provide_label, so Module.fit (and anything else written
    against the iterator protocol) can consume it."""

    def __init__(self, loader, data_name="data", label_name="softmax_label",
                 dtype="float32"):
        super().__init__()
        self._loader = loader
        self._iter = iter(loader)
        self.dtype = dtype
        data, label = self._peek()
        self.batch_size = data.shape[0]
        self.provide_data = [DataDesc(data_name, data.shape, dtype)]
        self.provide_label = [DataDesc(label_name, label.shape, dtype)]

    def _peek(self):
        """First batch, kept to serve shapes; re-served on first next()."""
        self._head = next(self._iter)
        return self._head

    def _as_nd(self, x):
        x = x.asnumpy() if isinstance(x, NDArray) else np.asarray(x)
        return nd.array(x.astype(self.dtype, copy=False))

    def reset(self):
        self._iter = iter(self._loader)
        self._head = None

    def next(self):
        if self._head is not None:
            data, label = self._head
            self._head = None
        else:
            data, label = next(self._iter)
        if (isinstance(data, NDArray) and isinstance(label, NDArray)
                and data.shape[0] == self.batch_size
                and str(data.dtype) == self.dtype
                and str(label.dtype) == self.dtype):
            # common case: full device-side batch already in dtype
            return DataBatch(data=[data], label=[label], pad=0)
        data = np.asarray(data.asnumpy() if isinstance(data, NDArray)
                          else data)
        label = np.asarray(label.asnumpy() if isinstance(label, NDArray)
                           else label)
        pad = self.batch_size - data.shape[0]
        if pad > 0:
            # short final batch (DataLoader last_batch="keep"): pad by
            # repeating the last row and report it, like NDArrayIter —
            # score()/predict() strip padded rows via DataBatch.pad
            data = np.concatenate(
                [data, np.repeat(data[-1:], pad, axis=0)], axis=0)
            label = np.concatenate(
                [label, np.repeat(label[-1:], pad, axis=0)], axis=0)
        return DataBatch(data=[self._as_nd(data)],
                         label=[self._as_nd(label)],
                         pad=max(0, pad))
