"""Sharded distributed checkpointing over orbax
(beyond the reference: SURVEY §5.4 — the reference replicates params and
rank 0 writes the whole file; sharded/distributed checkpointing does NOT
exist there. On TPU pods, per-host sharded saves are the difference between
checkpointing in seconds and serializing the full model through one host).

Saves/restores a pytree of (possibly GSPMD-sharded) jax.Arrays or
NDArrays: every host writes only the shards it owns; restore reassembles
onto any mesh whose shardings are supplied. Works transparently for
single-host too.

Typical use with a Gluon net::

    from incubator_mxnet_tpu.contrib import sharded_checkpoint as sc
    tree = {n: p.data() for n, p in net.collect_params().items()}
    sc.save(path, tree)
    restored = sc.restore(path, like=tree)   # NDArrays back, shardings kept
"""
from __future__ import annotations

import hashlib
import json
import logging
import os

import jax

from ..ndarray.ndarray import NDArray

logger = logging.getLogger(__name__)

__all__ = ["save", "restore", "latest_step", "verify", "CheckpointManager",
           "manifest_entry", "verify_wire_entry"]

# An orbax checkpoint is a DIRECTORY; its sidecar manifest lists every
# file with its sha256 so `restore` detects torn/corrupted shards before
# orbax deserializes them. Single-process only: with multiple hosts each
# writes just its own shards, so no one host can hash the full tree.
_MANIFEST_SUFFIX = ".sha256"


def _dir_manifest_entries(path):
    entries = {}
    for root, _dirs, files in os.walk(path):
        for name in sorted(files):
            full = os.path.join(root, name)
            rel = os.path.relpath(full, path)
            h = hashlib.sha256()
            with open(full, "rb") as f:
                for chunk in iter(lambda: f.read(1 << 20), b""):
                    h.update(chunk)
            entries[rel] = {"sha256": h.hexdigest(),
                            "size": os.path.getsize(full)}
    return entries


def manifest_entry(data):
    """Manifest entry for an in-memory payload — the same
    {"sha256", "size"} shape _dir_manifest_entries records per file,
    reused as the parameter server's elastic-join wire/transfer format
    (ps.ParameterServer state_manifest / PSClient.bootstrap)."""
    return {"sha256": hashlib.sha256(data).hexdigest(), "size": len(data)}


def verify_wire_entry(entry, data):
    """True iff `data` matches a manifest_entry (extra keys ignored)."""
    return (len(data) == entry.get("size")
            and hashlib.sha256(data).hexdigest() == entry.get("sha256"))


def _gather_to_host(tree):
    """Multi-host save support: turn every leaf into something ONE
    process (rank 0) can serialize and hash — the sha256 dir-manifest
    needs the complete byte stream on a single host.

    Per leaf: a host-local value (numpy, python scalar, or a jax array
    whose shards are all addressable here) is assembled from its
    addressable shards; a fully-replicated array is materialized from
    any one shard (every device holds a whole copy, so every host can).
    An array that is genuinely sharded ACROSS hosts cannot be gathered
    without a collective — that leaf fails loudly, named, so the caller
    can reshard (device_put onto a replicated sharding) or save through
    orbax directly instead of discovering a partial checkpoint later."""
    import numpy as np

    def one(path, leaf):
        data = leaf._data if _is_nd(leaf) else leaf
        if not hasattr(data, "is_fully_addressable"):
            return leaf  # numpy / python scalar: already host-local
        sharding = getattr(data, "sharding", None)
        if sharding is not None and getattr(sharding, "is_fully_replicated",
                                            False):
            return np.asarray(data.addressable_data(0))
        if data.is_fully_addressable:
            out = np.empty(data.shape, dtype=data.dtype)
            for s in data.addressable_shards:
                out[s.index] = np.asarray(s.data)
            return out
        raise ValueError(
            f"sharded_checkpoint.save: tensor {jax.tree_util.keystr(path)} "
            f"(shape {tuple(data.shape)}, sharding {sharding}) is sharded "
            "across hosts — no single process holds all of it, and the "
            "sha256 dir-manifest requires the full byte stream on the "
            "writing host. Reshard it to a replicated or single-host "
            "sharding before save(), or checkpoint through orbax "
            "directly.")

    return jax.tree_util.tree_map_with_path(one, tree, is_leaf=_is_nd)


def _write_dir_manifest(path):
    manifest = path + _MANIFEST_SUFFIX
    tmp = manifest + f".tmp.{os.getpid()}"
    payload = json.dumps({"files": _dir_manifest_entries(path),
                          "version": 1}, sort_keys=True).encode("utf-8")
    with open(tmp, "wb") as f:
        f.write(payload)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, manifest)


def verify(path):
    """True iff the checkpoint directory matches its sidecar manifest.
    A checkpoint without a manifest (pre-resilience save) verifies as
    legacy-valid. On a multi-host job only rank 0 (the manifest writer)
    verifies; other ranks trust it and return True."""
    if jax.process_count() > 1 and jax.process_index() != 0:
        return True
    path = os.path.abspath(path)
    if not os.path.isdir(path):
        return False
    try:
        with open(path + _MANIFEST_SUFFIX, "rb") as f:
            manifest = json.loads(f.read().decode("utf-8"))
    except FileNotFoundError:
        return True  # legacy: no manifest was ever written
    except (OSError, ValueError):
        return False
    want = manifest.get("files", {})
    have = _dir_manifest_entries(path)
    if want != have:
        logger.warning("sharded checkpoint %s failed manifest "
                       "verification", path)
        from .. import telemetry as _telemetry

        _telemetry.inc("mxtpu_ckpt_verify_failures_total", 1,
                       help="Checkpoint files failing manifest "
                            "verification at load, by reason.",
                       reason="sharded")
        return False
    return True


def _is_nd(v):
    return isinstance(v, NDArray)


def _to_jax_tree(tree):
    return jax.tree_util.tree_map(
        lambda v: v._data if _is_nd(v) else v, tree, is_leaf=_is_nd)


def _restore_args(like_jax_tree):
    import orbax.checkpoint as ocp

    return jax.tree_util.tree_map(
        lambda a: ocp.ArrayRestoreArgs(sharding=getattr(a, "sharding", None)),
        like_jax_tree)


def _rewrap_like(restored, like):
    """Mirror `like`'s NDArray-ness onto the restored jax leaves."""
    return jax.tree_util.tree_map(
        lambda template, value: NDArray._from_data(value)
        if _is_nd(template) else value,
        like, restored, is_leaf=_is_nd)


def save(path, tree, force=False):
    """Write a (sharded) pytree checkpoint.

    Refuses to overwrite an existing checkpoint unless force=True (orbax's
    safe default — a failed re-save must not destroy the previous good
    checkpoint silently). On a multi-host job every leaf is first gathered
    to host memory (see _gather_to_host; a tensor sharded across hosts
    fails loudly, named) and only rank 0 writes + hashes the manifest —
    the sha256 dir-manifest needs the complete byte stream on one host."""
    import orbax.checkpoint as ocp

    path = os.path.abspath(path)
    jtree = _to_jax_tree(tree)
    if jax.process_count() > 1:
        jtree = _gather_to_host(jtree)
        if jax.process_index() != 0:
            return path
    with ocp.Checkpointer(ocp.PyTreeCheckpointHandler()) as ckptr:
        ckptr.save(path, jtree, force=force)
    _write_dir_manifest(path)
    return path


def restore(path, like=None, shardings=None):
    """Restore a pytree checkpoint.

    `like`: a pytree of arrays/NDArrays giving the target structure, the
    destination shardings, and which leaves come back as NDArrays; shards
    land directly on their devices without materializing the global array
    on one host. `shardings`: alternatively, a matching pytree of
    jax.sharding.Sharding (returns raw jax arrays).
    """
    import orbax.checkpoint as ocp

    path = os.path.abspath(path)
    if not verify(path):
        raise OSError(
            f"sharded checkpoint {path} failed manifest verification "
            "(torn or corrupted shard); restore from an older step")
    with ocp.Checkpointer(ocp.PyTreeCheckpointHandler()) as ckptr:
        if like is not None:
            out = ckptr.restore(path, args=ocp.args.PyTreeRestore(
                restore_args=_restore_args(_to_jax_tree(like))))
            return _rewrap_like(out, like)
        if shardings is not None:
            restore_args = jax.tree_util.tree_map(
                lambda s: ocp.ArrayRestoreArgs(sharding=s), shardings)
            return ckptr.restore(
                path, args=ocp.args.PyTreeRestore(restore_args=restore_args))
        return ckptr.restore(path)


def latest_step(directory):
    """Newest step saved by a CheckpointManager under `directory`; raises
    FileNotFoundError for a missing directory (a typo'd resume path must
    not silently restart training from scratch)."""
    import orbax.checkpoint as ocp

    directory = os.path.abspath(directory)
    if not os.path.isdir(directory):
        raise FileNotFoundError(f"no checkpoint directory {directory}")
    mgr = ocp.CheckpointManager(
        directory, options=ocp.CheckpointManagerOptions(create=False))
    try:
        return mgr.latest_step()
    finally:
        mgr.close()


class CheckpointManager:
    """Step-indexed manager with retention (keeps the reference's
    do_checkpoint(period) UX, adds max_to_keep garbage collection and
    sharded writes)."""

    def __init__(self, directory, max_to_keep=3):
        import orbax.checkpoint as ocp

        self._mgr = ocp.CheckpointManager(
            os.path.abspath(directory),
            options=ocp.CheckpointManagerOptions(max_to_keep=max_to_keep))

    def save(self, step, tree):
        import orbax.checkpoint as ocp

        self._mgr.save(step, args=ocp.args.PyTreeSave(_to_jax_tree(tree)))
        return step

    def restore(self, step=None, like=None):
        import orbax.checkpoint as ocp

        if step is None:
            step = self._mgr.latest_step()
        if like is None:
            return self._mgr.restore(step)
        out = self._mgr.restore(step, args=ocp.args.PyTreeRestore(
            restore_args=_restore_args(_to_jax_tree(like))))
        return _rewrap_like(out, like)

    def latest_step(self):
        return self._mgr.latest_step()

    def wait_until_finished(self):
        self._mgr.wait_until_finished()

    def close(self):
        self._mgr.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
