"""Sharded distributed checkpointing over orbax
(beyond the reference: SURVEY §5.4 — the reference replicates params and
rank 0 writes the whole file; sharded/distributed checkpointing does NOT
exist there. On TPU pods, per-host sharded saves are the difference between
checkpointing in seconds and serializing the full model through one host).

Saves/restores a pytree of (possibly GSPMD-sharded) jax.Arrays or
NDArrays: every host writes only the shards it owns; restore reassembles
onto any mesh whose shardings are supplied. Works transparently for
single-host too.

Typical use with a Gluon net::

    from incubator_mxnet_tpu.contrib import sharded_checkpoint as sc
    tree = {n: p.data() for n, p in net.collect_params().items()}
    sc.save(path, tree)
    restored = sc.restore(path, like=tree)   # NDArrays back, shardings kept
"""
from __future__ import annotations

import hashlib
import json
import logging
import os

import jax

from ..ndarray.ndarray import NDArray

logger = logging.getLogger(__name__)

__all__ = ["save", "restore", "latest_step", "verify", "CheckpointManager",
           "manifest_entry", "verify_wire_entry"]

# An orbax checkpoint is a DIRECTORY; its sidecar manifest lists every
# file with its sha256 so `restore` detects torn/corrupted shards before
# orbax deserializes them. Single-process only: with multiple hosts each
# writes just its own shards, so no one host can hash the full tree.
_MANIFEST_SUFFIX = ".sha256"


def _dir_manifest_entries(path):
    entries = {}
    for root, _dirs, files in os.walk(path):
        for name in sorted(files):
            full = os.path.join(root, name)
            rel = os.path.relpath(full, path)
            h = hashlib.sha256()
            with open(full, "rb") as f:
                for chunk in iter(lambda: f.read(1 << 20), b""):
                    h.update(chunk)
            entries[rel] = {"sha256": h.hexdigest(),
                            "size": os.path.getsize(full)}
    return entries


def manifest_entry(data):
    """Manifest entry for an in-memory payload — the same
    {"sha256", "size"} shape _dir_manifest_entries records per file,
    reused as the parameter server's elastic-join wire/transfer format
    (ps.ParameterServer state_manifest / PSClient.bootstrap)."""
    return {"sha256": hashlib.sha256(data).hexdigest(), "size": len(data)}


def verify_wire_entry(entry, data):
    """True iff `data` matches a manifest_entry (extra keys ignored)."""
    return (len(data) == entry.get("size")
            and hashlib.sha256(data).hexdigest() == entry.get("sha256"))


def _require_single_process(op):
    """The sha256 dir-manifest is single-process-only: on a multi-host
    save each host writes just its own shards, so no host can hash the
    full tree, and a partial manifest would surface much later as a
    baffling hash mismatch at restore. Fail the operation NOW with the
    limitation spelled out instead."""
    if jax.process_count() > 1:
        raise NotImplementedError(
            f"sharded_checkpoint.{op} on a multi-host job "
            f"(jax.process_count()={jax.process_count()}): the sha256 "
            "dir-manifest is single-process-only — each host writes only "
            "its own shards, so no host can hash the complete checkpoint "
            "tree, and a partial manifest would later fail restore with "
            "a misleading hash mismatch. Until a per-shard manifest "
            "exists, save/verify multi-host checkpoints through orbax "
            "directly, or gather to one host first.")


def _write_dir_manifest(path):
    manifest = path + _MANIFEST_SUFFIX
    tmp = manifest + f".tmp.{os.getpid()}"
    payload = json.dumps({"files": _dir_manifest_entries(path),
                          "version": 1}, sort_keys=True).encode("utf-8")
    with open(tmp, "wb") as f:
        f.write(payload)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, manifest)


def verify(path):
    """True iff the checkpoint directory matches its sidecar manifest.
    A checkpoint without a manifest (pre-resilience save) verifies as
    legacy-valid. Single-process only — a multi-host job fails loudly
    (see _require_single_process)."""
    _require_single_process("verify")
    path = os.path.abspath(path)
    if not os.path.isdir(path):
        return False
    try:
        with open(path + _MANIFEST_SUFFIX, "rb") as f:
            manifest = json.loads(f.read().decode("utf-8"))
    except FileNotFoundError:
        return True  # legacy: no manifest was ever written
    except (OSError, ValueError):
        return False
    want = manifest.get("files", {})
    have = _dir_manifest_entries(path)
    if want != have:
        logger.warning("sharded checkpoint %s failed manifest "
                       "verification", path)
        from .. import telemetry as _telemetry

        _telemetry.inc("mxtpu_ckpt_verify_failures_total", 1,
                       help="Checkpoint files failing manifest "
                            "verification at load, by reason.",
                       reason="sharded")
        return False
    return True


def _is_nd(v):
    return isinstance(v, NDArray)


def _to_jax_tree(tree):
    return jax.tree_util.tree_map(
        lambda v: v._data if _is_nd(v) else v, tree, is_leaf=_is_nd)


def _restore_args(like_jax_tree):
    import orbax.checkpoint as ocp

    return jax.tree_util.tree_map(
        lambda a: ocp.ArrayRestoreArgs(sharding=getattr(a, "sharding", None)),
        like_jax_tree)


def _rewrap_like(restored, like):
    """Mirror `like`'s NDArray-ness onto the restored jax leaves."""
    return jax.tree_util.tree_map(
        lambda template, value: NDArray._from_data(value)
        if _is_nd(template) else value,
        like, restored, is_leaf=_is_nd)


def save(path, tree, force=False):
    """Write a (sharded) pytree checkpoint; every host writes its shards.
    Refuses to overwrite an existing checkpoint unless force=True (orbax's
    safe default — a failed re-save must not destroy the previous good
    checkpoint silently). Single-process only while the sha256 manifest
    is — a multi-host job fails loudly up front rather than leaving a
    checkpoint that flunks verification later."""
    _require_single_process("save")
    import orbax.checkpoint as ocp

    path = os.path.abspath(path)
    with ocp.Checkpointer(ocp.PyTreeCheckpointHandler()) as ckptr:
        ckptr.save(path, _to_jax_tree(tree), force=force)
    _write_dir_manifest(path)
    return path


def restore(path, like=None, shardings=None):
    """Restore a pytree checkpoint.

    `like`: a pytree of arrays/NDArrays giving the target structure, the
    destination shardings, and which leaves come back as NDArrays; shards
    land directly on their devices without materializing the global array
    on one host. `shardings`: alternatively, a matching pytree of
    jax.sharding.Sharding (returns raw jax arrays).
    """
    import orbax.checkpoint as ocp

    path = os.path.abspath(path)
    if not verify(path):
        raise OSError(
            f"sharded checkpoint {path} failed manifest verification "
            "(torn or corrupted shard); restore from an older step")
    with ocp.Checkpointer(ocp.PyTreeCheckpointHandler()) as ckptr:
        if like is not None:
            out = ckptr.restore(path, args=ocp.args.PyTreeRestore(
                restore_args=_restore_args(_to_jax_tree(like))))
            return _rewrap_like(out, like)
        if shardings is not None:
            restore_args = jax.tree_util.tree_map(
                lambda s: ocp.ArrayRestoreArgs(sharding=s), shardings)
            return ckptr.restore(
                path, args=ocp.args.PyTreeRestore(restore_args=restore_args))
        return ckptr.restore(path)


def latest_step(directory):
    """Newest step saved by a CheckpointManager under `directory`; raises
    FileNotFoundError for a missing directory (a typo'd resume path must
    not silently restart training from scratch)."""
    import orbax.checkpoint as ocp

    directory = os.path.abspath(directory)
    if not os.path.isdir(directory):
        raise FileNotFoundError(f"no checkpoint directory {directory}")
    mgr = ocp.CheckpointManager(
        directory, options=ocp.CheckpointManagerOptions(create=False))
    try:
        return mgr.latest_step()
    finally:
        mgr.close()


class CheckpointManager:
    """Step-indexed manager with retention (keeps the reference's
    do_checkpoint(period) UX, adds max_to_keep garbage collection and
    sharded writes)."""

    def __init__(self, directory, max_to_keep=3):
        import orbax.checkpoint as ocp

        self._mgr = ocp.CheckpointManager(
            os.path.abspath(directory),
            options=ocp.CheckpointManagerOptions(max_to_keep=max_to_keep))

    def save(self, step, tree):
        import orbax.checkpoint as ocp

        self._mgr.save(step, args=ocp.args.PyTreeSave(_to_jax_tree(tree)))
        return step

    def restore(self, step=None, like=None):
        import orbax.checkpoint as ocp

        if step is None:
            step = self._mgr.latest_step()
        if like is None:
            return self._mgr.restore(step)
        out = self._mgr.restore(step, args=ocp.args.PyTreeRestore(
            restore_args=_restore_args(_to_jax_tree(like))))
        return _rewrap_like(out, like)

    def latest_step(self):
        return self._mgr.latest_step()

    def wait_until_finished(self):
        self._mgr.wait_until_finished()

    def close(self):
        self._mgr.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
