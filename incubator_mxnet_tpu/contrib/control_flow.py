"""Differentiable control flow (ref: src/operator/control_flow.cc —
_foreach:1255, _while_loop:1316, _cond:1378).

TPU-native: these lower directly to lax.scan / lax.while_loop / lax.cond —
compiled loops with O(1) program size in the trip count, which the reference
needed a subgraph-op mechanism to achieve.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .. import autograd
from ..ndarray.ndarray import NDArray

__all__ = ["foreach", "while_loop", "cond"]


def _wrap(x):
    return NDArray._from_data(x)


def _data(x):
    return x._data if isinstance(x, NDArray) else jnp.asarray(x)


def foreach(body, data, init_states):
    """Scan `body(x_t, states) -> (out_t, new_states)` over axis 0 of data
    (ref: contrib.foreach / _foreach op). Differentiable end-to-end."""
    single_data = isinstance(data, NDArray)
    datas = [data] if single_data else list(data)
    single_state = isinstance(init_states, NDArray)
    states = [init_states] if single_state else list(init_states)

    def fn(*leaf_datas):
        n = len(datas)
        xs = leaf_datas[:n]
        st = [d for d in leaf_datas[n:]]

        def scan_body(carry, x_slices):
            c_nd = [_wrap(c) for c in carry]
            x_nd = [_wrap(x) for x in x_slices]
            out, new_states = body(
                x_nd[0] if single_data else x_nd,
                c_nd[0] if single_state else c_nd,
            )
            outs = [out] if isinstance(out, NDArray) else list(out)
            ns = [new_states] if isinstance(new_states, NDArray) else list(new_states)
            return tuple(_data(s) for s in ns), tuple(_data(o) for o in outs)

        final, ys = lax.scan(scan_body, tuple(st), tuple(xs))
        return tuple(ys) + tuple(final)

    all_inputs = datas + states
    results = autograd.invoke_recorded(fn, all_inputs, name="foreach")
    x0 = [_wrap(_data(d)[0]) for d in datas]
    out_probe, st_probe = body(
        x0[0] if single_data else x0,
        states[0] if single_state else states,
    )
    n_out = 1 if isinstance(out_probe, NDArray) else len(out_probe)
    outs = results[:n_out]
    finals = results[n_out:]
    out_val = outs[0] if (n_out == 1 and isinstance(out_probe, NDArray)) else outs
    st_val = finals[0] if single_state else list(finals)
    return out_val, st_val


def while_loop(cond_fn, func, loop_vars, max_iterations=None):
    """(ref: _while_loop op). Runs func while cond holds; bounded by
    max_iterations with a scan so shapes stay static (XLA requirement —
    the reference pads outputs the same way)."""
    single = isinstance(loop_vars, NDArray)
    lvars = [loop_vars] if single else list(loop_vars)
    assert max_iterations is not None, "max_iterations required for static shapes"

    def fn(*leaf):
        def scan_body(carry, _):
            active, vals = carry
            v_nd = [_wrap(v) for v in vals]
            c = cond_fn(v_nd[0] if single else v_nd)
            c = _data(c).reshape(()).astype(bool) & active
            out, new_vals = func(v_nd[0] if single else v_nd)
            nv = [new_vals] if isinstance(new_vals, NDArray) else list(new_vals)
            stepped = tuple(
                jnp.where(c, _data(n), v) for n, v in zip(nv, vals)
            )
            outs = [out] if isinstance(out, NDArray) else list(out)
            o_vals = tuple(jnp.where(c, _data(o), jnp.zeros_like(_data(o))) for o in outs)
            return (c, stepped), o_vals

        (_, final_vals), ys = lax.scan(
            scan_body, (jnp.asarray(True), tuple(leaf)), None, length=max_iterations
        )
        return tuple(ys) + tuple(final_vals)

    probe_out, _ = func(lvars[0] if single else lvars)
    n_out = 1 if isinstance(probe_out, NDArray) else len(probe_out)
    results = autograd.invoke_recorded(fn, lvars, name="while_loop")
    outs = results[:n_out]
    finals = results[n_out:]
    return (outs[0] if n_out == 1 else outs), (finals[0] if single else list(finals))


def cond(pred, then_func, else_func, inputs=None):
    """(ref: _cond op) -> lax.cond."""
    inputs = inputs or []
    single = isinstance(inputs, NDArray)
    ins = [inputs] if single else list(inputs)

    def fn(p, *leaf):
        def then_branch(vals):
            v = [_wrap(x) for x in vals]
            out = then_func(*v) if v else then_func()
            outs = [out] if isinstance(out, NDArray) else list(out)
            return tuple(_data(o) for o in outs)

        def else_branch(vals):
            v = [_wrap(x) for x in vals]
            out = else_func(*v) if v else else_func()
            outs = [out] if isinstance(out, NDArray) else list(out)
            return tuple(_data(o) for o in outs)

        return lax.cond(p.reshape(()).astype(bool), then_branch, else_branch, leaf)

    results = autograd.invoke_recorded(fn, [pred] + ins, name="cond")
    return results if len(results) > 1 else results[0]
