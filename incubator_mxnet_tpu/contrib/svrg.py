"""SVRG optimization (ref: python/mxnet/contrib/svrg_optimization/ —
SVRGModule + _SVRGOptimizer).

Stochastic Variance-Reduced Gradient (Johnson & Zhang 2013): every
`update_freq` epochs, snapshot the weights w~ and compute the full-dataset
gradient mu = (1/N) sum_i grad_i(w~); each step then applies the
variance-reduced gradient

    g_i(w) - g_i(w~) + mu

TPU-native surface: a Gluon-style `SVRGTrainer` instead of the reference's
Module subclass — the snapshot pass and the paired two-gradient step are
plain eager autograd over the net's parameters, so it composes with any
Block. The reference's split (module keeps snapshots, a wrapped optimizer
consumes the stitched gradient) collapses into this one class.
"""
from __future__ import annotations

import numpy as np

from .. import autograd
from .. import optimizer as opt_mod
from ..ndarray.ndarray import NDArray

__all__ = ["SVRGTrainer"]


class SVRGTrainer:
    """Variance-reduced trainer (ref: svrg_module.py SVRGModule).

    Usage per epoch::

        if epoch % trainer.update_freq == 0:
            trainer.update_full_grads(batches)   # snapshot w~, compute mu
        for x, y in batches:
            loss = trainer.step(x, y)
    """

    def __init__(self, net, loss_fn, optimizer="sgd", optimizer_params=None,
                 update_freq=2):
        self.net = net
        self.loss_fn = loss_fn
        if isinstance(optimizer, str):
            optimizer = opt_mod.create(optimizer, **(optimizer_params or {}))
        self.optimizer = optimizer
        self.update_freq = int(update_freq)
        self._params = list(net.collect_params().items())
        self._states = {}
        self._snapshot = None  # name -> raw param values at w~
        self._mu = None        # name -> full-dataset gradient at w~

    def _batch_grads(self, x, y):
        """(loss, gradients of the batch loss) at the CURRENT params."""
        for _, p in self._params:
            if p.grad_req != "null":
                p.zero_grad()
        with autograd.record():
            loss = self.loss_fn(self.net, x, y)
        loss.backward()
        grads = {n: p.grad()._data for n, p in self._params
                 if p.grad_req != "null"}
        return loss, grads

    def _with_params(self, values):
        """Temporarily swap net params to `values` (name -> raw array)."""
        class _Swap:
            def __init__(s):
                s.saved = None

            def __enter__(s):
                s.saved = {n: p.data()._data for n, p in self._params}
                for n, p in self._params:
                    if n in values:
                        p.data()._data = values[n]

            def __exit__(s, *exc):
                for n, p in self._params:
                    p.data()._data = s.saved[n]

        return _Swap()

    def update_full_grads(self, batches):
        """Snapshot w~ := w and mu := mean over `batches` of grad(w~)
        (ref: SVRGModule.update_full_grads)."""
        self._snapshot = {n: p.data()._data for n, p in self._params}
        acc, count = {}, 0
        for batch in batches:
            x, y = batch if isinstance(batch, (tuple, list)) else \
                (batch.data[0], batch.label[0])
            _, g = self._batch_grads(x, y)
            for n, v in g.items():
                acc[n] = v if n not in acc else acc[n] + v
            count += 1
        if count == 0:
            raise ValueError("update_full_grads: empty batch iterable")
        self._mu = {n: v / count for n, v in acc.items()}

    def step(self, x, y):
        """One variance-reduced update; returns the batch loss."""
        if self._snapshot is None:
            raise RuntimeError("call update_full_grads() before step() "
                               "(the SVRG schedule needs a snapshot)")
        loss, g_cur = self._batch_grads(x, y)
        with self._with_params(self._snapshot):
            _, g_snap = self._batch_grads(x, y)
        for i, (n, p) in enumerate(self._params):
            if p.grad_req == "null" or n not in g_cur:
                continue
            vr = g_cur[n] - g_snap[n] + self._mu[n]
            if n not in self._states:
                self._states[n] = self.optimizer.create_state(i, p.data())
            self.optimizer.update(i, p.data(), NDArray._from_data(vr),
                                  self._states[n])
        return loss
