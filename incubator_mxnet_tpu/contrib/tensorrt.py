"""TensorRT-compat API (ref: python/mxnet/contrib/tensorrt.py —
set_use_tensorrt:30, get_optimized_symbol:50, tensorrt_bind:76).

Role mapping: TensorRT's job — an AOT-optimized inference engine with
optional half precision — is XLA's default job here. Every bind compiles
and fuses the whole graph, so there is no separate "TensorRT graph pass"
to toggle; what remains meaningful from this API is (a) script
compatibility and (b) the half-precision switch, which on TPU means
bfloat16 (`fp16_mode=True` casts the bound parameters so matmuls/convs hit
the MXU at its native dtype). For ahead-of-time serialized engines, see
`deploy.export_predictor` (the `.mxp` artifact)."""
from __future__ import annotations

import logging
import os

from .. import config as _config

__all__ = ["set_use_tensorrt", "get_use_tensorrt", "get_optimized_symbol",
           "tensorrt_bind"]

_ENV = "MXTPU_USE_TENSORRT"


def set_use_tensorrt(status):
    """Accepted for script compatibility; graph optimization is XLA's
    compile and is always on. The flag only records the preference."""
    os.environ[_ENV] = str(int(bool(status)))
    if status:
        logging.getLogger(__name__).info(
            "TensorRT-style graph optimization is XLA compilation here; "
            "already enabled for every bind")


def get_use_tensorrt():
    return _config.get(_ENV)


def get_optimized_symbol(executor):
    """The symbol whose whole graph the executor compiled. XLA fusion
    happens inside compilation, so the optimized program has the same
    symbol-level structure (there are no partitioned TRT subgraph nodes
    to surface)."""
    return executor._symbol


def tensorrt_bind(symbol, ctx=None, all_params=None, type_dict=None,
                  stype_dict=None, group2ctx=None, fp16_mode=False,
                  **kwargs):
    """simple_bind + parameter injection, the reference's one-call
    inference-engine entry. fp16_mode=True binds the net in bfloat16 (TPU
    half precision): parameters convert via contrib.amp (normalization
    statistics stay fp32) and the data slots bind bf16, so fp32 feeds cast
    down instead of promoting the matmuls back up."""
    all_params = dict(all_params or {})
    type_dict = dict(type_dict or {})
    arg_names = set(symbol.list_arguments())
    aux_names = set(symbol.list_auxiliary_states())
    arg_params = {k: v for k, v in all_params.items() if k in arg_names}
    aux_params = {k: v for k, v in all_params.items() if k in aux_names}
    dropped = set(all_params) - set(arg_params) - set(aux_params)
    if dropped:
        raise ValueError(f"params not in the symbol: {sorted(dropped)}")
    if fp16_mode:
        from .amp import convert_model

        _, arg_params, aux_params = convert_model(
            symbol, arg_params, aux_params, target_dtype="bfloat16")
        for name, arr in {**arg_params, **aux_params}.items():
            type_dict.setdefault(name, str(arr.dtype))
        for name in arg_names - set(arg_params):  # data/label inputs
            type_dict.setdefault(name, "bfloat16")
    ex = symbol.simple_bind(ctx=ctx, grad_req="null", type_dict=type_dict,
                            stype_dict=stype_dict, group2ctx=group2ctx,
                            **kwargs)
    ex.copy_params_from(arg_params, aux_params)
    return ex
