"""Graph sampling / DGL-support operators
(ref: src/operator/contrib/dgl_graph.cc — _contrib_dgl_csr_neighbor_uniform_sample:758,
_contrib_dgl_csr_neighbor_non_uniform_sample:852, _contrib_dgl_subgraph:1129,
_contrib_edge_id:1314, _contrib_dgl_adjacency:1390, _contrib_dgl_graph_compact:1565).

TPU-native stance: neighbor sampling is data-dependent index-set algebra —
exactly the work that cannot live inside an XLA program (dynamic shapes,
hash sets, rejection sampling). It therefore runs on host as a preprocessing
stage, like the reference's CPU-only FComputeEx kernels. What the host emits
is deliberately TPU-friendly: every output is padded to the static
`max_num_vertices` bound (the reference's own design), so a sampling loop
feeds fixed-shape minibatches into jitted GNN steps with no recompilation.

The BFS frontier expansion mirrors the reference's algorithm: seeds enter at
layer 0; each vertex below `num_hops` has at most `num_neighbor` of its
out-edges kept (uniform without replacement, or weighted by a per-vertex
probability); newly seen endpoints join the frontier until
`max_num_vertices` is reached. Sampled subgraphs keep ORIGINAL edge ids as
CSR values so edge features can be gathered from the parent graph.
"""
from __future__ import annotations

import warnings

import numpy as np

from ..ndarray.ndarray import NDArray
from ..ndarray.sparse import CSRNDArray

__all__ = [
    "csr_neighbor_uniform_sample",
    "csr_neighbor_non_uniform_sample",
    "dgl_subgraph",
    "edge_id",
    "dgl_adjacency",
    "dgl_graph_compact",
]


def _as_np(x, dtype=None):
    if isinstance(x, NDArray):
        x = x.asnumpy()
    arr = np.asarray(x)
    return arr.astype(dtype) if dtype is not None else arr


def _csr_parts(csr):
    if not isinstance(csr, CSRNDArray):
        raise TypeError(f"expected CSRNDArray, got {type(csr).__name__}")
    return (
        _as_np(csr.data, np.int64),
        _as_np(csr.indices, np.int64),
        _as_np(csr.indptr, np.int64),
    )


def _sample_row(rng, cols, eids, num_neighbor, prob):
    """Keep at most `num_neighbor` of a vertex's out-edges
    (ref: GetUniformSample / GetNonUniformSample, dgl_graph.cc:452,495)."""
    ver_len = cols.shape[0]
    if ver_len <= num_neighbor:
        return cols, eids
    if prob is None:
        pick = rng.choice(ver_len, size=num_neighbor, replace=False)
    else:
        w = prob[cols].astype(np.float64)
        positive = np.nonzero(w > 0)[0]
        if positive.shape[0] <= num_neighbor:
            # without replacement, only positive-weight neighbors can be
            # drawn — keep exactly those
            pick = positive
        else:
            pick = rng.choice(ver_len, size=num_neighbor, replace=False,
                              p=w / w.sum())
    pick.sort()
    return cols[pick], eids[pick]


def _sample_subgraph(data, indices, indptr, seeds, prob, num_hops,
                     num_neighbor, max_num_vertices, rng):
    """One seed array -> (sample_id[max+1], sub CSR, prob or None, layer)
    (ref: SampleSubgraph, dgl_graph.cc:540)."""
    n = indptr.shape[0] - 1
    seeds = np.asarray(seeds, dtype=np.int64).ravel()
    if seeds.shape[0] > max_num_vertices:
        raise ValueError("more seed vertices than max_num_vertices")

    seen = {}
    frontier = []  # (vertex, layer) in discovery order; doubles as the queue
    for s in seeds:
        v = int(s)
        if v not in seen:
            seen[v] = 0
            frontier.append((v, 0))
    neigh = {}  # vertex -> (cols, eids) of its sampled out-edges
    idx = 0
    while idx < len(frontier) and len(seen) < max_num_vertices:
        v, layer = frontier[idx]
        idx += 1
        if layer >= num_hops:
            continue
        lo, hi = int(indptr[v]), int(indptr[v + 1])
        cols, eids = _sample_row(rng, indices[lo:hi], data[lo:hi],
                                 num_neighbor, prob)
        neigh[v] = (cols, eids)
        for c in cols:
            if len(seen) >= max_num_vertices:
                break
            c = int(c)
            if c not in seen:
                seen[c] = layer + 1
                frontier.append((c, layer + 1))
    if any(layer < num_hops for _, layer in frontier[idx:]):
        warnings.warn(
            "sampling truncated at max_num_vertices; use fewer seeds or a "
            "smaller neighborhood")

    order = np.array(sorted(seen), dtype=np.int64)
    nv = order.shape[0]
    sample_id = np.zeros(max_num_vertices + 1, dtype=np.int64)
    sample_id[:nv] = order
    sample_id[max_num_vertices] = nv
    layer_out = np.zeros(max_num_vertices, dtype=np.int64)
    layer_out[:nv] = [seen[int(v)] for v in order]

    sub_indptr = np.zeros(max_num_vertices + 1, dtype=np.int64)
    col_chunks, eid_chunks = [], []
    for i, v in enumerate(order):
        cols, eids = neigh.get(int(v), (None, None))
        cnt = 0
        if cols is not None and cols.shape[0]:
            # when max_num_vertices truncated the frontier, some sampled
            # endpoints never entered the vertex set — drop those edges so
            # the subgraph is self-contained (the reference emits dangling
            # edges here, which its own graph_compact then rejects)
            keep = np.fromiter((int(c) in seen for c in cols), dtype=bool,
                               count=cols.shape[0])
            cols, eids = cols[keep], eids[keep]
            cnt = cols.shape[0]
            if cnt:
                col_chunks.append(cols)
                eid_chunks.append(eids)
        sub_indptr[i + 1] = sub_indptr[i] + cnt
    sub_indptr[nv + 1:] = sub_indptr[nv]
    sub_cols = (np.concatenate(col_chunks) if col_chunks
                else np.zeros(0, dtype=np.int64))
    sub_eids = (np.concatenate(eid_chunks) if eid_chunks
                else np.zeros(0, dtype=np.int64))
    sub_csr = CSRNDArray(NDArray(sub_eids), NDArray(sub_indptr),
                         NDArray(sub_cols), (max_num_vertices, n))

    prob_out = None
    if prob is not None:
        prob_out = np.zeros(max_num_vertices, dtype=np.float32)
        prob_out[:nv] = prob[order]
    return sample_id, sub_csr, prob_out, layer_out


def _check_square(indptr, csr):
    if csr.shape[0] != csr.shape[1]:
        raise ValueError(f"graph CSR must be square, got {csr.shape}")


def csr_neighbor_uniform_sample(csr, *seed_arrays, num_hops=1, num_neighbor=2,
                                max_num_vertices=100, rng=None):
    """Sample subgraphs by uniform neighbor sampling
    (ref: _contrib_dgl_csr_neighbor_uniform_sample, dgl_graph.cc:758).

    Returns a flat list in the reference's output order: all sampled-vertex
    arrays (length max_num_vertices+1, last element = actual vertex count),
    then all sampled CSR subgraphs (original edge ids as values), then all
    layer arrays.
    """
    data, indices, indptr = _csr_parts(csr)
    _check_square(indptr, csr)
    rng = np.random.default_rng() if rng is None else rng
    ids, csrs, layers = [], [], []
    for seed in seed_arrays:
        sid, sub, _, layer = _sample_subgraph(
            data, indices, indptr, _as_np(seed, np.int64), None,
            num_hops, num_neighbor, max_num_vertices, rng)
        ids.append(NDArray(sid))
        csrs.append(sub)
        layers.append(NDArray(layer))
    return ids + csrs + layers


def csr_neighbor_non_uniform_sample(csr, probability, *seed_arrays,
                                    num_hops=1, num_neighbor=2,
                                    max_num_vertices=100, rng=None):
    """Weighted neighbor sampling: edge (u -> v) is kept with probability
    proportional to probability[v]
    (ref: _contrib_dgl_csr_neighbor_non_uniform_sample, dgl_graph.cc:852).

    Output order: sampled-vertex arrays, CSR subgraphs, per-vertex
    probability arrays, layer arrays.
    """
    data, indices, indptr = _csr_parts(csr)
    _check_square(indptr, csr)
    prob = _as_np(probability, np.float32).ravel()
    if prob.shape[0] != csr.shape[0]:
        raise ValueError("probability must have one entry per vertex")
    rng = np.random.default_rng() if rng is None else rng
    ids, csrs, probs, layers = [], [], [], []
    for seed in seed_arrays:
        sid, sub, p, layer = _sample_subgraph(
            data, indices, indptr, _as_np(seed, np.int64), prob,
            num_hops, num_neighbor, max_num_vertices, rng)
        ids.append(NDArray(sid))
        csrs.append(sub)
        probs.append(NDArray(p))
        layers.append(NDArray(layer))
    return ids + csrs + probs + layers


def dgl_subgraph(graph, *vertex_arrays, return_mapping=False):
    """Induced subgraph over each sorted vertex set: edges whose endpoints
    both lie in the set are kept, vertices renumbered to 0..len(v)-1, values
    renumbered to new edge ids 0..nnz-1; with return_mapping a second CSR
    carries the ORIGINAL edge ids (ref: _contrib_dgl_subgraph +
    GetSubgraph, dgl_graph.cc:1129,1053)."""
    data, indices, indptr = _csr_parts(graph)
    n = graph.shape[0]
    subs, mappings = [], []
    for varr in vertex_arrays:
        vids = _as_np(varr, np.int64).ravel()
        if vids.size and np.any(np.diff(vids) <= 0):
            raise ValueError(
                "the input vertex list has to be sorted and duplicate-free")
        if vids.size and (vids[0] < 0 or vids[-1] >= n):
            raise ValueError("vertex id out of range")
        old2new = {int(v): i for i, v in enumerate(vids)}
        m = vids.shape[0]
        sub_indptr = np.zeros(m + 1, dtype=np.int64)
        new_cols, orig_eids = [], []
        for i, v in enumerate(vids):
            lo, hi = int(indptr[v]), int(indptr[v + 1])
            for c, e in zip(indices[lo:hi], data[lo:hi]):
                nc = old2new.get(int(c))
                if nc is not None:
                    new_cols.append(nc)
                    orig_eids.append(int(e))
            sub_indptr[i + 1] = len(new_cols)
        new_cols = np.asarray(new_cols, dtype=np.int64)
        orig_eids = np.asarray(orig_eids, dtype=np.int64)
        new_eids = np.arange(new_cols.shape[0], dtype=np.int64)
        subs.append(CSRNDArray(NDArray(new_eids), NDArray(sub_indptr),
                               NDArray(new_cols), (m, m)))
        if return_mapping:
            mappings.append(CSRNDArray(
                NDArray(orig_eids), NDArray(sub_indptr.copy()),
                NDArray(new_cols.copy()), (m, m)))
    out = subs + mappings
    return out if len(out) > 1 else out[0]


def edge_id(csr, u, v):
    """output[i] = csr[u[i], v[i]] (the edge id) or -1 when absent
    (ref: _contrib_edge_id, dgl_graph.cc:1314)."""
    data, indices, indptr = _csr_parts(csr)
    uu = _as_np(u, np.int64).ravel()
    vv = _as_np(v, np.int64).ravel()
    if uu.shape != vv.shape:
        raise ValueError("u and v must have the same length")
    out = np.full(uu.shape[0], -1, dtype=np.int64)
    for i, (a, b) in enumerate(zip(uu, vv)):
        lo, hi = int(indptr[a]), int(indptr[a + 1])
        hit = np.nonzero(indices[lo:hi] == b)[0]
        if hit.size:
            out[i] = data[lo + int(hit[0])]
    return NDArray(out)


def dgl_adjacency(csr):
    """Edge-id CSR -> adjacency CSR with float32 ones as values
    (ref: _contrib_dgl_adjacency, dgl_graph.cc:1390)."""
    _, indices, indptr = _csr_parts(csr)
    ones = np.ones(indices.shape[0], dtype=np.float32)
    return CSRNDArray(NDArray(ones), NDArray(indptr.copy()),
                      NDArray(indices.copy()), csr.shape)


def dgl_graph_compact(*args, graph_sizes, return_mapping=False):
    """Strip the max_num_vertices padding from sampled subgraphs: rows/cols
    are renumbered into the compact 0..graph_size-1 space via the sampled
    vertex array; values become new edge ids (original ids via the mapping
    output) (ref: _contrib_dgl_graph_compact + CompactSubgraph,
    dgl_graph.cc:1565,1444)."""
    if len(args) % 2:
        raise ValueError("expected (graph, ..., vertex_ids, ...) pairs")
    num_g = len(args) // 2
    graphs, vid_arrays = args[:num_g], args[num_g:]
    if np.isscalar(graph_sizes):
        graph_sizes = (int(graph_sizes),) * num_g
    if len(graph_sizes) != num_g:
        raise ValueError("graph_sizes must have one entry per graph")
    subs, mappings = [], []
    for csr, vids_in, size in zip(graphs, vid_arrays, graph_sizes):
        data, indices, indptr = _csr_parts(csr)
        vids = _as_np(vids_in, np.int64).ravel()
        # last element of the sampled-vertex array = actual vertex count
        if int(vids[-1]) != size:
            raise ValueError(
                f"graph_sizes entry {size} disagrees with sampled vertex "
                f"count {int(vids[-1])}")
        old2new = {int(v): i for i, v in enumerate(vids[:size])}
        nnz = int(indptr[size])
        out_indptr = indptr[:size + 1].copy()
        out_cols = np.fromiter(
            (old2new[int(c)] for c in indices[:nnz]), dtype=np.int64,
            count=nnz)
        new_eids = np.arange(nnz, dtype=np.int64)
        subs.append(CSRNDArray(NDArray(new_eids), NDArray(out_indptr),
                               NDArray(out_cols), (size, size)))
        if return_mapping:
            mappings.append(CSRNDArray(
                NDArray(data[:nnz].copy()), NDArray(out_indptr.copy()),
                NDArray(out_cols.copy()), (size, size)))
    out = subs + mappings
    return out if len(out) > 1 else out[0]
