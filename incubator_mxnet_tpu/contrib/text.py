"""Text utilities: vocabulary + pretrained token embeddings
(ref: python/mxnet/contrib/text/ — vocab.Vocabulary,
embedding.TokenEmbedding/CustomEmbedding, glossary composition).

Zero-egress environment: embeddings load from LOCAL text files in the
standard GloVe/fastText format (`token v1 v2 ... vD` per line) instead of
the reference's download-by-name; everything else keeps the reference's
semantics — frequency-ordered vocabularies with reserved tokens, unknown
handling, and `get_vecs_by_tokens` lookup into one dense table that an
`Embedding` op can consume on the MXU.
"""
from __future__ import annotations

import collections

import numpy as np

from ..ndarray.ndarray import NDArray

__all__ = ["Vocabulary", "TokenEmbedding", "count_tokens_from_str"]


def count_tokens_from_str(source, token_delim=" ", seq_delim="\n",
                          to_lower=False, counter_to_update=None):
    """Token frequency counter (ref: text/utils.py count_tokens_from_str)."""
    counter = counter_to_update if counter_to_update is not None \
        else collections.Counter()
    if to_lower:
        source = source.lower()
    for seq in source.split(seq_delim):
        counter.update(t for t in seq.split(token_delim) if t)
    return counter


class Vocabulary:
    """Frequency-ordered indexed vocabulary (ref: text/vocab.py Vocabulary).

    Index 0 is the unknown token; reserved tokens follow; the remaining
    tokens are ordered by descending frequency (ties broken
    lexicographically, like the reference).
    """

    def __init__(self, counter=None, most_freq_count=None, min_freq=1,
                 unknown_token="<unk>", reserved_tokens=None):
        if min_freq < 1:
            raise ValueError("min_freq must be >= 1")
        reserved_tokens = list(reserved_tokens or [])
        if unknown_token in reserved_tokens:
            raise ValueError("unknown_token must not be in reserved_tokens")
        if len(set(reserved_tokens)) != len(reserved_tokens):
            raise ValueError("reserved_tokens must be unique")
        self._unknown_token = unknown_token
        self._idx_to_token = [unknown_token] + reserved_tokens
        if counter is not None:
            pairs = sorted(counter.items(), key=lambda kv: (-kv[1], kv[0]))
            if most_freq_count is not None:
                pairs = pairs[:most_freq_count]
            for tok, freq in pairs:
                if freq < min_freq:
                    break
                if tok != unknown_token and tok not in reserved_tokens:
                    self._idx_to_token.append(tok)
        self._token_to_idx = {t: i for i, t in enumerate(self._idx_to_token)}

    def __len__(self):
        return len(self._idx_to_token)

    @property
    def unknown_token(self):
        return self._unknown_token

    @property
    def idx_to_token(self):
        return list(self._idx_to_token)

    @property
    def token_to_idx(self):
        return dict(self._token_to_idx)

    def to_indices(self, tokens):
        """(ref: vocab.py to_indices) — unknown tokens map to index 0."""
        single = isinstance(tokens, str)
        toks = [tokens] if single else tokens
        out = [self._token_to_idx.get(t, 0) for t in toks]
        return out[0] if single else out

    def to_tokens(self, indices):
        single = not isinstance(indices, (list, tuple))
        idxs = [indices] if single else indices
        for i in idxs:
            if not 0 <= i < len(self._idx_to_token):
                raise ValueError(f"index {i} out of vocabulary range")
        out = [self._idx_to_token[i] for i in idxs]
        return out[0] if single else out


class TokenEmbedding:
    """Pretrained embedding table over a vocabulary
    (ref: text/embedding.py TokenEmbedding/CustomEmbedding — file format
    `token v1 ... vD`; unknown/missing tokens get `init_unknown_vec`).
    """

    def __init__(self, file_path=None, vocabulary=None, vec_len=None,
                 init_unknown_vec=np.zeros, encoding="utf-8"):
        vectors = {}
        if file_path is not None:
            with open(file_path, encoding=encoding) as f:
                for lineno, line in enumerate(f):
                    parts = line.rstrip().split(" ")
                    if len(parts) < 2:
                        continue
                    tok, vals = parts[0], parts[1:]
                    if vec_len is None:
                        vec_len = len(vals)
                    elif len(vals) != vec_len:
                        # fastText-style header line or corrupt row: skip,
                        # as the reference does for header rows
                        if lineno == 0:
                            continue
                        raise ValueError(
                            f"{file_path}:{lineno + 1}: expected {vec_len} "
                            f"values, got {len(vals)}")
                    vectors[tok] = np.asarray(vals, np.float32)
        if vec_len is None:
            raise ValueError("vec_len is required without a file")
        self._vec_len = vec_len
        self._vectors = vectors
        self._init_unknown = init_unknown_vec
        if vocabulary is None:
            vocabulary = Vocabulary(
                collections.Counter({t: 1 for t in vectors}))
        self._vocab = vocabulary
        table = np.stack([
            vectors.get(tok, init_unknown_vec(vec_len).astype(np.float32))
            for tok in vocabulary.idx_to_token])
        self._table = table.astype(np.float32)

    @property
    def vec_len(self):
        return self._vec_len

    @property
    def vocabulary(self):
        return self._vocab

    @property
    def idx_to_vec(self):
        """(ref: TokenEmbedding.idx_to_vec) — dense (V, D) table, the input
        for `nd.Embedding` / `gluon.nn.Embedding.weight.set_data`."""
        return NDArray(self._table)

    def get_vecs_by_tokens(self, tokens, lower_case_backup=False):
        single = isinstance(tokens, str)
        toks = [tokens] if single else tokens
        rows = []
        for t in toks:
            idx = self._vocab.token_to_idx.get(t)
            if idx is None and lower_case_backup:
                idx = self._vocab.token_to_idx.get(t.lower())
            rows.append(self._table[idx if idx is not None else 0])
        out = NDArray(np.stack(rows))
        return out[0] if single else out

    def update_token_vectors(self, tokens, new_vectors):
        """(ref: TokenEmbedding.update_token_vectors)"""
        toks = [tokens] if isinstance(tokens, str) else tokens
        vecs = np.asarray(new_vectors.asnumpy()
                          if isinstance(new_vectors, NDArray)
                          else new_vectors, np.float32).reshape(len(toks), -1)
        for t, v in zip(toks, vecs):
            if t not in self._vocab.token_to_idx:
                raise ValueError(f"token {t!r} not in the vocabulary")
            self._table[self._vocab.token_to_idx[t]] = v
