"""Legacy experimental autograd API (ref: python/mxnet/contrib/autograd.py
— the pre-`mx.autograd` spelling: train_section/test_section scopes,
compute_gradient, and the functional grad/grad_and_loss wrappers). Thin
delegation onto the tape in `autograd.py`; new code should use
`mx.autograd` directly."""
from __future__ import annotations

import functools

from .. import autograd as ag
from ..ndarray.ndarray import NDArray

__all__ = ["set_is_training", "train_section", "test_section",
           "mark_variables", "backward", "compute_gradient",
           "grad_and_loss", "grad"]


def set_is_training(is_train):
    """Returns the previous state (legacy contract)."""
    prev = ag.is_training()
    ag.set_training(is_train)
    ag.set_recording(is_train)
    return prev


def train_section():
    """`with train_section():` — record + train mode."""
    return ag.record(train_mode=True)


def test_section():
    """`with test_section():` — no recording, predict mode."""
    return ag.pause(train_mode=False)


mark_variables = ag.mark_variables


def backward(outputs, out_grads=None, retain_graph=False):
    return ag.backward(outputs, head_grads=out_grads,
                       retain_graph=retain_graph)


def compute_gradient(outputs):
    """Legacy alias: backward on marked variables."""
    return backward(outputs)


def grad_and_loss(func, argnum=None):
    """Wrap func to return (gradients, outputs) for the selected args."""

    @functools.wraps(func)
    def wrapped(*args):
        picked = (list(range(len(args))) if argnum is None
                  else ([argnum] if isinstance(argnum, int) else list(argnum)))
        variables = [args[i] for i in picked]
        for x in variables:
            if not isinstance(x, NDArray):
                raise TypeError("grad requires NDArray arguments")
        with ag.record():
            for x in variables:
                x.attach_grad()
            outputs = func(*args)
        heads = outputs if isinstance(outputs, (list, tuple)) else [outputs]
        ag.backward(list(heads))
        return [x.grad for x in variables], outputs

    return wrapped


def grad(func, argnum=None):
    """Wrap func to return just the gradients."""
    wrapped = grad_and_loss(func, argnum)

    @functools.wraps(func)
    def only_grad(*args):
        return wrapped(*args)[0]

    return only_grad
