"""TensorBoard metric logging (ref: python/mxnet/contrib/tensorboard.py —
LogMetricsCallback:25, a Speedometer-shaped batch/eval callback that writes
scalar summaries instead of printing).

Backend: `torch.utils.tensorboard.SummaryWriter` when available (torch
ships in this stack); a clear ImportError otherwise — same gating posture
as the reference, which required the dmlc tensorboard package."""
from __future__ import annotations

from ..telemetry.tb import LogTelemetryCallback  # noqa: F401 — the registry-
# sourced sibling of LogMetricsCallback (same callback protocol, same
# SummaryWriter gating); lives in telemetry/tb.py, re-exported here so both
# tensorboard callbacks are importable from one place.

__all__ = ["LogMetricsCallback", "LogTelemetryCallback"]


class LogMetricsCallback:
    """Write each metric's current value as a TensorBoard scalar, keyed
    `prefix/metric_name`, at every callback invocation."""

    def __init__(self, logging_dir, prefix=None, flush_secs=5):
        self.prefix = prefix
        self.step = 0
        self._flush_secs = flush_secs
        self._last_flush = 0.0
        try:
            from torch.utils.tensorboard import SummaryWriter
        except ImportError as e:
            raise ImportError(
                "LogMetricsCallback needs a tensorboard writer; install "
                "`tensorboard` (torch.utils.tensorboard backend)") from e
        self.summary_writer = SummaryWriter(logging_dir)

    def __call__(self, param):
        """BatchEndParam/epoch-end callback protocol."""
        if param.eval_metric is None:
            return
        self.step += 1
        for name, value in param.eval_metric.get_name_value():
            if self.prefix is not None:
                name = f"{self.prefix}/{name}"
            self.summary_writer.add_scalar(name, value, self.step)
        import time

        now = time.monotonic()
        if now - self._last_flush >= self._flush_secs:
            # fit() never calls flush(); throttled flushing keeps events
            # visible for short runs without per-batch file IO
            self._last_flush = now
            self.summary_writer.flush()

    def flush(self):
        self.summary_writer.flush()

    def close(self):
        self.summary_writer.close()
