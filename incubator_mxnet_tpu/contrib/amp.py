"""Automatic mixed precision (ref: python/mxnet/contrib/amp precursor).

TPU-native stance: bfloat16 is the native MXU dtype — no loss scaling is
required (unlike fp16 on the reference's GPUs). `convert_model` /
`convert_block` cast parameters and compute to bf16 while keeping
normalization statistics and optimizer state in fp32.
"""
from __future__ import annotations

import numpy as np

__all__ = ["init", "convert_block", "convert_model", "scale_loss"]

_F32_KEEP_SUFFIXES = ("gamma", "beta", "running_mean", "running_var",
                      "moving_mean", "moving_var")


def init(target_dtype="bfloat16"):
    """Enable AMP defaults (kept for API parity; casting is explicit)."""
    return target_dtype


def convert_block(block, target_dtype="bfloat16"):
    """Cast a Gluon block to bf16 compute, fp32 norm statistics."""
    for name, p in block.collect_params().items():
        if name.endswith(_F32_KEEP_SUFFIXES):
            continue
        p.cast(target_dtype)
    return block


def convert_model(sym, arg_params, aux_params, target_dtype="bfloat16"):
    """Cast a symbolic checkpoint (ref: amp convert_model)."""
    new_args = {}
    for k, v in arg_params.items():
        if k.endswith(_F32_KEEP_SUFFIXES):
            new_args[k] = v
        else:
            new_args[k] = v.astype(target_dtype)
    return sym, new_args, dict(aux_params)


class scale_loss:
    """Loss-scaling context (ref: amp.scale_loss). On TPU bf16 has fp32-range
    exponent so scale defaults to 1; kept for fp16 compat."""

    def __init__(self, loss, optimizer_or_trainer, scale=1.0):
        self._loss = loss
        self._scale = scale

    def __enter__(self):
        return self._loss * self._scale if self._scale != 1.0 else self._loss

    def __exit__(self, *exc):
        return False
