"""Automatic mixed precision
(ref: python/mxnet/contrib/amp/amp.py — init:87, init_trainer:338,
scale_loss:311, loss_scaler.py DynamicLossScaler).

TPU-native stance: bfloat16 is the native MXU dtype and shares fp32's
exponent range, so TPU training normally needs NO loss scaling — cast with
`convert_block` and train. The dynamic loss scaler exists for float16
workflows (parity with the reference, and fp16 artifacts imported from
GPU-land): scale grows 2x every `scale_window` clean steps and halves on
any non-finite gradient, with the overflowed step skipped — the
reference's exact policy.
"""
from __future__ import annotations

import jax.numpy as jnp

__all__ = ["init", "init_trainer", "convert_block", "convert_model",
           "scale_loss", "DynamicLossScaler"]

_F32_KEEP_SUFFIXES = ("gamma", "beta", "running_mean", "running_var",
                      "moving_mean", "moving_var")


def init(target_dtype="bfloat16"):
    """Enable AMP defaults (kept for API parity; casting is explicit)."""
    return target_dtype


def convert_block(block, target_dtype="bfloat16"):
    """Cast a Gluon block to reduced-precision compute, fp32 norm stats."""
    for name, p in block.collect_params().items():
        if name.endswith(_F32_KEEP_SUFFIXES):
            continue
        p.cast(target_dtype)
    return block


def convert_model(sym, arg_params, aux_params, target_dtype="bfloat16"):
    """Cast a symbolic checkpoint (ref: amp convert_model)."""
    new_args = {}
    for k, v in arg_params.items():
        if k.endswith(_F32_KEEP_SUFFIXES):
            new_args[k] = v
        else:
            new_args[k] = v.astype(target_dtype)
    return sym, new_args, dict(aux_params)


class DynamicLossScaler:
    """Grow-on-success / halve-on-overflow loss scale
    (ref: contrib/amp/loss_scaler.py — init_scale 2**16, scale_factor 2,
    scale_window 2000)."""

    def __init__(self, init_scale=2.0 ** 16, scale_factor=2.0,
                 scale_window=2000, min_scale=1.0):
        self.loss_scale = float(init_scale)
        self.scale_factor = float(scale_factor)
        self.scale_window = int(scale_window)
        self.min_scale = float(min_scale)
        self._unskipped = 0

    def has_overflow(self, grads):
        """True if any gradient contains a non-finite value. All per-grad
        flags are OR-ed on device so only ONE host sync happens per step
        (the reference's multi_all_finite plays the same role)."""
        flag = None
        for g in grads:
            if hasattr(g, "data") and hasattr(g, "indices"):  # row_sparse
                data = g.data._data
            elif hasattr(g, "_data"):
                data = g._data
            else:
                data = jnp.asarray(g)
            bad = ~jnp.isfinite(data).all()
            flag = bad if flag is None else flag | bad
        return bool(flag) if flag is not None else False

    def update_scale(self, overflow):
        prev = self.loss_scale
        if overflow:
            self.loss_scale = max(self.min_scale,
                                  self.loss_scale / self.scale_factor)
            self._unskipped = 0
        else:
            self._unskipped += 1
            if self._unskipped >= self.scale_window:
                self.loss_scale *= self.scale_factor
                self._unskipped = 0
        from .. import telemetry as _telemetry

        _telemetry.set_gauge(
            "mxtpu_loss_scale", self.loss_scale,
            help="Current dynamic loss scale of the AMP scaler (moves on "
                 "overflow backoff and growth-window promotion).")
        if self.loss_scale != prev:
            # scale moves are rare and diagnostic gold: a shrinking scale
            # trail in a post-mortem dump is a numeric-instability flag
            _telemetry.log_event(
                "loss_scale_change", scale=self.loss_scale, prev=prev,
                cause="overflow" if overflow else "growth")


def init_trainer(trainer, scaler=None):
    """Attach dynamic loss scaling to a Gluon Trainer
    (ref: amp.init_trainer:338): scale_loss multiplies the loss by the
    live scale; the trainer unscales through rescale_grad and SKIPS any
    step whose gradients overflowed, halving the scale."""
    trainer._amp_scaler = scaler or DynamicLossScaler()
    return trainer._amp_scaler


class scale_loss:
    """Context manager yielding the scaled loss (ref: amp.scale_loss:311).

    with amp.scale_loss(loss, trainer) as scaled:
        scaled.backward()
    trainer.step(batch)   # unscales via rescale_grad; skips on overflow
    """

    def __init__(self, loss, optimizer_or_trainer, scale=None):
        self._trainer = optimizer_or_trainer
        scaler = getattr(optimizer_or_trainer, "_amp_scaler", None)
        self._scale = (scale if scale is not None
                       else (scaler.loss_scale if scaler else 1.0))
        self._loss = loss

    def __enter__(self):
        # record the scale actually applied so the trainer unscales by the
        # same factor even when the caller overrode it
        if hasattr(self._trainer, "_amp_scaler"):
            self._trainer._amp_applied_scale = self._scale
        if self._scale == 1.0:
            return self._loss
        from .. import autograd as _ag

        # the multiply must land on the tape even when the user scales
        # outside the record() block (the reference permits both placements)
        with _ag._AutogradScope(recording=True):
            if isinstance(self._loss, (list, tuple)):
                return type(self._loss)(l * self._scale for l in self._loss)
            return self._loss * self._scale

    def __exit__(self, *exc):
        return False
