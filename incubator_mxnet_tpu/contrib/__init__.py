"""Contrib: control flow, AMP, quantization (ref: python/mxnet/contrib/)."""
from . import control_flow  # noqa: F401
from .control_flow import foreach, while_loop, cond  # noqa: F401
from . import amp  # noqa: F401
from . import quantization  # noqa: F401
from . import onnx  # noqa: F401
from . import torch_bridge  # noqa: F401
from . import svrg  # noqa: F401
from . import text  # noqa: F401
from . import sharded_checkpoint  # noqa: F401
from . import graph  # noqa: F401
from . import io  # noqa: F401
from . import tensorboard  # noqa: F401
from . import tensorrt  # noqa: F401
from . import autograd  # noqa: F401
