"""Torch op bridge (ref: plugin/torch/ — TorchModule/TorchCriterion ops that
run Torch layers inside the graph).

TPU-native stance: torch (CPU) runs host-side behind `jax.pure_callback`,
exactly like Python CustomOps (ref: src/operator/custom/ runs user Python on
a dedicated thread pool so the engine never blocks). Gradients come from
torch.autograd inside the callback, spliced into the JAX VJP — so a bridged
layer is differentiable end-to-end inside `autograd.record()` and usable
under jit (the callback is a host excursion XLA schedules around).
"""
from __future__ import annotations


import numpy as np

__all__ = ["TorchModule", "torch_function"]


def _require_torch():
    try:
        import torch  # noqa: F401

        return torch
    except ImportError as e:  # pragma: no cover - torch is baked in here
        raise ImportError("contrib.torch_bridge requires torch") from e


class TorchModule:
    """Wrap a `torch.nn.Module` as a differentiable eager op
    (ref: plugin/torch/torch_module-inl.h TorchModuleOp).

    Torch parameters stay owned by torch; gradients w.r.t. the (JAX) inputs
    flow back onto the tape.

    Torch-side `.grad` accumulation is a side effect inside
    `jax.pure_callback`, which JAX may elide, cache, or re-execute under
    `jit`/`vmap`/higher-order `grad`. The "torch optimizer can drive the
    module's parameters via `.grad`" contract therefore holds ONLY in eager
    execution (the default dispatch of this bridge). Under `jit`, treat the
    torch module as frozen — or step it outside the jitted region.
    """

    def __init__(self, module):
        self._torch = _require_torch()
        self.module = module
        self._bridged_cache = {}  # input signature -> custom_vjp fn

    def _build_bridged(self, sig):
        import jax
        import jax.numpy as jnp

        torch = self._torch
        # probe the output spec ONCE per input signature (shapes, dtypes)
        with torch.no_grad():
            probe = self.module(*[torch.from_numpy(np.zeros(s, np.float32))
                                  for s, _ in sig])
        out_spec = jax.ShapeDtypeStruct(tuple(probe.shape), jnp.float32)

        def host_forward(*arrs):
            tins = [torch.from_numpy(np.array(a, np.float32))
                    for a in arrs]
            with torch.no_grad():
                return np.asarray(self.module(*tins).detach().numpy())

        def host_backward(g, *arrs):
            tins = [torch.from_numpy(np.array(a, np.float32))
                    .requires_grad_(True) for a in arrs]
            out = self.module(*tins)
            out.backward(torch.from_numpy(np.array(g, np.float32)))
            return tuple(
                np.asarray(t.grad.numpy()) if t.grad is not None
                else np.zeros(t.shape, np.float32)  # input unused by module
                for t in tins)

        @jax.custom_vjp
        def bridged(*arrs):
            return jax.pure_callback(host_forward, out_spec, *arrs)

        def fwd(*arrs):
            return bridged(*arrs), arrs

        def bwd(res, g):
            specs = tuple(jax.ShapeDtypeStruct(a.shape, jnp.float32)
                          for a in res)
            return jax.pure_callback(host_backward, specs, g, *res)

        bridged.defvjp(fwd, bwd)
        return bridged

    def __call__(self, *inputs):
        import jax.numpy as jnp

        from .. import autograd
        from ..ndarray.ndarray import NDArray

        datas = [x._data if isinstance(x, NDArray) else jnp.asarray(x)
                 for x in inputs]
        sig = tuple((tuple(d.shape), str(d.dtype)) for d in datas)
        bridged = self._bridged_cache.get(sig)
        if bridged is None:
            bridged = self._bridged_cache[sig] = self._build_bridged(sig)
        outs = autograd.invoke_recorded(lambda *a: bridged(*a), list(inputs))
        return outs[0]


def torch_function(fn):
    """Decorator form for stateless torch functions:
    `f = torch_function(torch.special.erf); y = f(x)`."""
    class _Fn:
        def __call__(self, *tins):
            return fn(*tins)

        def parameters(self):
            return []

    return TorchModule(_Fn())
