"""Activation blocks (capability parity with
python/mxnet/gluon/nn/activations.py).

The parameter-free activations are one generated class per LeakyReLU-op
act_type; PReLU (learned slope) and Swish (own formula) stay explicit.
"""
from __future__ import annotations

from ... import initializer as init_mod
from ..block import HybridBlock

__all__ = ["LeakyReLU", "PReLU", "ELU", "SELU", "GELU", "Swish"]


def _slope_activation(name, act_type, default_slope, doc):
    """Generate a HybridBlock wrapping F.LeakyReLU(act_type=...)."""

    if default_slope is None:
        def __init__(self, **kwargs):
            HybridBlock.__init__(self, **kwargs)

        def hybrid_forward(self, F, x):
            return F.LeakyReLU(x, act_type=act_type)
    else:
        def __init__(self, alpha=default_slope, **kwargs):
            HybridBlock.__init__(self, **kwargs)
            self._alpha = alpha

        def hybrid_forward(self, F, x):
            return F.LeakyReLU(x, act_type=act_type, slope=self._alpha)

    return type(name, (HybridBlock,), {
        "__init__": __init__,
        "hybrid_forward": hybrid_forward,
        "__doc__": doc,
    })


# LeakyReLU's reference signature has alpha REQUIRED; ELU defaults to 1.0
LeakyReLU = _slope_activation(
    "LeakyReLU", "leaky", default_slope=0.01,
    doc="max(x, alpha*x) (ref: activations.py LeakyReLU)")
ELU = _slope_activation(
    "ELU", "elu", default_slope=1.0,
    doc="x if x>0 else alpha*(exp(x)-1) (ref: activations.py ELU)")
SELU = _slope_activation(
    "SELU", "selu", default_slope=None,
    doc="scaled ELU, self-normalizing (ref: activations.py SELU)")
GELU = _slope_activation(
    "GELU", "gelu", default_slope=None,
    doc="Gaussian error linear unit (ref: activations.py GELU)")


class PReLU(HybridBlock):
    """Leaky relu whose per-channel slope is LEARNED
    (ref: activations.py PReLU)."""

    def __init__(self, alpha_initializer=None, in_channels=1, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.alpha = self.params.get(
                "alpha", shape=(in_channels,),
                init=alpha_initializer or init_mod.Constant(0.25),
            )

    def hybrid_forward(self, F, x, alpha):
        return F.LeakyReLU(x, alpha, act_type="prelu")


class Swish(HybridBlock):
    """x * sigmoid(beta x) (ref: activations.py Swish)."""

    def __init__(self, beta=1.0, **kwargs):
        super().__init__(**kwargs)
        self._beta = beta

    def hybrid_forward(self, F, x):
        return x * F.sigmoid(self._beta * x)
