"""Gluon conv/pool layers (ref: python/mxnet/gluon/nn/conv_layers.py)."""
from __future__ import annotations

from ... import initializer as init_mod
from ..block import HybridBlock

__all__ = [
    "Conv1D", "Conv2D", "Conv3D", "Conv1DTranspose", "Conv2DTranspose",
    "Conv3DTranspose", "MaxPool1D", "MaxPool2D", "MaxPool3D", "AvgPool1D",
    "AvgPool2D", "AvgPool3D", "GlobalMaxPool1D", "GlobalMaxPool2D",
    "GlobalMaxPool3D", "GlobalAvgPool1D", "GlobalAvgPool2D", "GlobalAvgPool3D",
]


def _tup(v, n):
    return (v,) * n if isinstance(v, int) else tuple(v)


class _Conv(HybridBlock):
    def __init__(self, channels, kernel_size, strides, padding, dilation,
                 groups, layout, in_channels=0, activation=None, use_bias=True,
                 weight_initializer=None, bias_initializer="zeros", ndim=2,
                 transpose=False, output_padding=0, **kwargs):
        super().__init__(**kwargs)
        self._channels = channels
        self._in_channels = in_channels
        self._kernel = _tup(kernel_size, ndim)
        self._strides = _tup(strides, ndim)
        self._padding = _tup(padding, ndim)
        self._dilation = _tup(dilation, ndim)
        self._groups = groups
        self._act_type = activation
        self._use_bias = use_bias
        self._ndim = ndim
        self._transpose = transpose
        self._output_padding = _tup(output_padding, ndim)
        self._layout = layout
        self._channels_last = bool(layout) and layout.endswith("C")
        if self._channels_last and transpose:
            raise ValueError(
                "channels-last layouts are not supported for transposed "
                "convolutions yet; use the default NC* layout")
        with self.name_scope():
            if transpose:
                wshape = (in_channels, channels // groups) + self._kernel
            else:
                wshape = (channels, in_channels // groups if in_channels else 0) + self._kernel
            self.weight = self.params.get(
                "weight", shape=wshape, init=weight_initializer, allow_deferred_init=True,
            )
            if use_bias:
                self.bias = self.params.get("bias", shape=(channels,), init=init_mod.Zero())
            else:
                self.bias = None

    def _pre_forward(self, x, *args):
        if not self.weight._shape_known():
            in_c = x.shape[-1] if self._channels_last else x.shape[1]
            if self._transpose:
                self.weight.shape = (in_c, self._channels // self._groups) + self._kernel
            else:
                self.weight.shape = (self._channels, in_c // self._groups) + self._kernel

    def hybrid_forward(self, F, x, weight, bias=None):
        if self._transpose:
            out = F.Deconvolution(
                x, weight, bias, kernel=self._kernel, stride=self._strides,
                pad=self._padding, adj=self._output_padding, num_filter=self._channels,
                num_group=self._groups, no_bias=bias is None,
            )
        else:
            out = F.Convolution(
                x, weight, bias, kernel=self._kernel, stride=self._strides,
                dilate=self._dilation, pad=self._padding, num_filter=self._channels,
                num_group=self._groups, no_bias=bias is None,
                layout=self._layout if self._channels_last else None,
            )
        if self._act_type:
            out = F.Activation(out, act_type=self._act_type)
        return out

    def __repr__(self):
        return f"{type(self).__name__}({self._channels}, kernel_size={self._kernel})"


class Conv1D(_Conv):
    def __init__(self, channels, kernel_size, strides=1, padding=0, dilation=1,
                 groups=1, layout="NCW", activation=None, use_bias=True,
                 weight_initializer=None, bias_initializer="zeros", in_channels=0, **kwargs):
        super().__init__(channels, kernel_size, strides, padding, dilation, groups,
                         layout, in_channels, activation, use_bias,
                         weight_initializer, bias_initializer, ndim=1, **kwargs)


class Conv2D(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1), padding=(0, 0),
                 dilation=(1, 1), groups=1, layout="NCHW", activation=None,
                 use_bias=True, weight_initializer=None, bias_initializer="zeros",
                 in_channels=0, **kwargs):
        super().__init__(channels, kernel_size, strides, padding, dilation, groups,
                         layout, in_channels, activation, use_bias,
                         weight_initializer, bias_initializer, ndim=2, **kwargs)


class Conv3D(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1, 1), padding=(0, 0, 0),
                 dilation=(1, 1, 1), groups=1, layout="NCDHW", activation=None,
                 use_bias=True, weight_initializer=None, bias_initializer="zeros",
                 in_channels=0, **kwargs):
        super().__init__(channels, kernel_size, strides, padding, dilation, groups,
                         layout, in_channels, activation, use_bias,
                         weight_initializer, bias_initializer, ndim=3, **kwargs)


class Conv1DTranspose(_Conv):
    def __init__(self, channels, kernel_size, strides=1, padding=0, output_padding=0,
                 dilation=1, groups=1, layout="NCW", activation=None, use_bias=True,
                 weight_initializer=None, bias_initializer="zeros", in_channels=0, **kwargs):
        super().__init__(channels, kernel_size, strides, padding, dilation, groups,
                         layout, in_channels, activation, use_bias, weight_initializer,
                         bias_initializer, ndim=1, transpose=True,
                         output_padding=output_padding, **kwargs)


class Conv2DTranspose(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1), padding=(0, 0),
                 output_padding=(0, 0), dilation=(1, 1), groups=1, layout="NCHW",
                 activation=None, use_bias=True, weight_initializer=None,
                 bias_initializer="zeros", in_channels=0, **kwargs):
        super().__init__(channels, kernel_size, strides, padding, dilation, groups,
                         layout, in_channels, activation, use_bias, weight_initializer,
                         bias_initializer, ndim=2, transpose=True,
                         output_padding=output_padding, **kwargs)


class Conv3DTranspose(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1, 1), padding=(0, 0, 0),
                 output_padding=(0, 0, 0), dilation=(1, 1, 1), groups=1, layout="NCDHW",
                 activation=None, use_bias=True, weight_initializer=None,
                 bias_initializer="zeros", in_channels=0, **kwargs):
        super().__init__(channels, kernel_size, strides, padding, dilation, groups,
                         layout, in_channels, activation, use_bias, weight_initializer,
                         bias_initializer, ndim=3, transpose=True,
                         output_padding=output_padding, **kwargs)


class _Pooling(HybridBlock):
    def __init__(self, pool_size, strides, padding, ceil_mode, global_pool,
                 pool_type, layout, count_include_pad=True, **kwargs):
        super().__init__(**kwargs)
        if strides is None:
            strides = pool_size
        self._kwargs = {
            "kernel": pool_size, "stride": strides, "pad": padding,
            "global_pool": global_pool, "pool_type": pool_type,
            "pooling_convention": "full" if ceil_mode else "valid",
            "count_include_pad": count_include_pad,
        }
        if layout and layout.endswith("C"):
            self._kwargs["layout"] = layout

    def hybrid_forward(self, F, x):
        return F.Pooling(x, **self._kwargs)

    def __repr__(self):
        return f"{type(self).__name__}(size={self._kwargs['kernel']})"


class MaxPool1D(_Pooling):
    def __init__(self, pool_size=2, strides=None, padding=0, layout="NCW",
                 ceil_mode=False, **kwargs):
        super().__init__(_tup(pool_size, 1), strides, _tup(padding, 1),
                         ceil_mode, False, "max", layout, **kwargs)


class MaxPool2D(_Pooling):
    def __init__(self, pool_size=(2, 2), strides=None, padding=0, layout="NCHW",
                 ceil_mode=False, **kwargs):
        super().__init__(_tup(pool_size, 2), strides, _tup(padding, 2),
                         ceil_mode, False, "max", layout, **kwargs)


class MaxPool3D(_Pooling):
    def __init__(self, pool_size=(2, 2, 2), strides=None, padding=0, layout="NCDHW",
                 ceil_mode=False, **kwargs):
        super().__init__(_tup(pool_size, 3), strides, _tup(padding, 3),
                         ceil_mode, False, "max", layout, **kwargs)


class AvgPool1D(_Pooling):
    def __init__(self, pool_size=2, strides=None, padding=0, layout="NCW",
                 ceil_mode=False, count_include_pad=True, **kwargs):
        super().__init__(_tup(pool_size, 1), strides, _tup(padding, 1),
                         ceil_mode, False, "avg", layout, count_include_pad, **kwargs)


class AvgPool2D(_Pooling):
    def __init__(self, pool_size=(2, 2), strides=None, padding=0, layout="NCHW",
                 ceil_mode=False, count_include_pad=True, **kwargs):
        super().__init__(_tup(pool_size, 2), strides, _tup(padding, 2),
                         ceil_mode, False, "avg", layout, count_include_pad, **kwargs)


class AvgPool3D(_Pooling):
    def __init__(self, pool_size=(2, 2, 2), strides=None, padding=0, layout="NCDHW",
                 ceil_mode=False, count_include_pad=True, **kwargs):
        super().__init__(_tup(pool_size, 3), strides, _tup(padding, 3),
                         ceil_mode, False, "avg", layout, count_include_pad, **kwargs)


class GlobalMaxPool1D(_Pooling):
    def __init__(self, layout="NCW", **kwargs):
        super().__init__((1,), None, (0,), False, True, "max", layout, **kwargs)


class GlobalMaxPool2D(_Pooling):
    def __init__(self, layout="NCHW", **kwargs):
        super().__init__((1, 1), None, (0, 0), False, True, "max", layout, **kwargs)


class GlobalMaxPool3D(_Pooling):
    def __init__(self, layout="NCDHW", **kwargs):
        super().__init__((1, 1, 1), None, (0, 0, 0), False, True, "max", layout, **kwargs)


class GlobalAvgPool1D(_Pooling):
    def __init__(self, layout="NCW", **kwargs):
        super().__init__((1,), None, (0,), False, True, "avg", layout, **kwargs)


class GlobalAvgPool2D(_Pooling):
    def __init__(self, layout="NCHW", **kwargs):
        super().__init__((1, 1), None, (0, 0), False, True, "avg", layout, **kwargs)


class GlobalAvgPool3D(_Pooling):
    def __init__(self, layout="NCDHW", **kwargs):
        super().__init__((1, 1, 1), None, (0, 0, 0), False, True, "avg", layout, **kwargs)
