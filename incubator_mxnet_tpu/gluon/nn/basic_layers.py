"""Gluon basic layers (ref: python/mxnet/gluon/nn/basic_layers.py)."""
from __future__ import annotations

import numpy as np

from ... import initializer as init_mod
from ...ndarray.ndarray import NDArray
from ..block import Block, HybridBlock, _F

__all__ = [
    "Sequential", "HybridSequential", "Dense", "Dropout", "BatchNorm",
    "InstanceNorm", "LayerNorm", "GroupNorm", "Embedding", "Flatten",
    "Lambda", "HybridLambda", "Activation", "ReflectionPad2D",
    "HybridBlock",
]


class Sequential(Block):
    """(ref: basic_layers.py Sequential)"""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def add(self, *blocks):
        for block in blocks:
            self.register_child(block)

    def forward(self, x):
        for block in self._children.values():
            x = block(x)
        return x

    def __len__(self):
        return len(self._children)

    def __getitem__(self, key):
        layers = list(self._children.values())[key]
        if isinstance(layers, list):
            net = type(self)()
            net.add(*layers)
            return net
        return layers

    def __iter__(self):
        return iter(self._children.values())

    def hybridize(self, active=True, **kwargs):
        super().hybridize(active, **kwargs)


class HybridSequential(HybridBlock):
    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def add(self, *blocks):
        for block in blocks:
            self.register_child(block)

    def hybrid_forward(self, F, x):
        for block in self._children.values():
            x = block(x)
        return x

    def _pre_forward(self, *args):
        return

    def __len__(self):
        return len(self._children)

    def __getitem__(self, key):
        layers = list(self._children.values())[key]
        if isinstance(layers, list):
            net = type(self)()
            net.add(*layers)
            return net
        return layers

    def __iter__(self):
        return iter(self._children.values())


class Dense(HybridBlock):
    """(ref: basic_layers.py Dense)"""

    def __init__(self, units, activation=None, use_bias=True, flatten=True,
                 dtype="float32", weight_initializer=None, bias_initializer="zeros",
                 in_units=0, **kwargs):
        super().__init__(**kwargs)
        self._units = units
        self._flatten = flatten
        self._use_bias = use_bias
        self._act_type = activation
        with self.name_scope():
            self.weight = self.params.get(
                "weight", shape=(units, in_units), dtype=dtype,
                init=weight_initializer, allow_deferred_init=True,
            )
            if use_bias:
                self.bias = self.params.get(
                    "bias", shape=(units,), dtype=dtype,
                    init=init_mod.Zero() if bias_initializer == "zeros" else bias_initializer,
                )
            else:
                self.bias = None

    def _pre_forward(self, x, *args):
        if not self.weight._shape_known():
            in_units = int(np.prod(x.shape[1:])) if self._flatten else x.shape[-1]
            self.weight.shape = (self._units, in_units)

    def hybrid_forward(self, F, x, weight, bias=None):
        out = F.FullyConnected(x, weight, bias, num_hidden=self._units,
                               no_bias=bias is None, flatten=self._flatten)
        if self._act_type:
            out = F.Activation(out, act_type=self._act_type)
        return out

    def __repr__(self):
        return f"Dense({self._units})"


class Dropout(HybridBlock):
    def __init__(self, rate, axes=(), **kwargs):
        super().__init__(**kwargs)
        self._rate = rate
        self._axes = axes

    def hybrid_forward(self, F, x):
        if self._rate > 0:
            return F.Dropout(x, p=self._rate, axes=self._axes)
        return x


class BatchNorm(HybridBlock):
    """(ref: basic_layers.py BatchNorm)"""

    def __init__(self, axis=1, momentum=0.9, epsilon=1e-5, center=True, scale=True,
                 use_global_stats=False, beta_initializer="zeros",
                 gamma_initializer="ones", running_mean_initializer="zeros",
                 running_variance_initializer="ones", in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self._axis = axis
        self._momentum = momentum
        self._epsilon = epsilon
        self._center = center
        self._scale = scale
        self._use_global_stats = use_global_stats
        with self.name_scope():
            self.gamma = self.params.get(
                "gamma", shape=(in_channels,), init=init_mod.One(),
                allow_deferred_init=True, grad_req="write" if scale else "null",
            )
            self.beta = self.params.get(
                "beta", shape=(in_channels,), init=init_mod.Zero(),
                allow_deferred_init=True, grad_req="write" if center else "null",
            )
            self.running_mean = self.params.get(
                "running_mean", shape=(in_channels,), init=init_mod.Zero(),
                allow_deferred_init=True, differentiable=False,
            )
            self.running_var = self.params.get(
                "running_var", shape=(in_channels,), init=init_mod.One(),
                allow_deferred_init=True, differentiable=False,
            )

    def _pre_forward(self, x, *args):
        if not self.gamma._shape_known():
            c = x.shape[self._axis]
            for p in (self.gamma, self.beta, self.running_mean, self.running_var):
                p.shape = (c,)

    def hybrid_forward(self, F, x, gamma, beta, running_mean, running_var):
        return F.BatchNorm(
            x, gamma, beta, running_mean, running_var,
            eps=self._epsilon, momentum=self._momentum, fix_gamma=not self._scale,
            use_global_stats=self._use_global_stats, axis=self._axis,
        )


class InstanceNorm(HybridBlock):
    def __init__(self, axis=1, epsilon=1e-5, center=True, scale=False,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self._epsilon = epsilon
        with self.name_scope():
            self.gamma = self.params.get("gamma", shape=(in_channels,),
                                         init=init_mod.One(), allow_deferred_init=True)
            self.beta = self.params.get("beta", shape=(in_channels,),
                                        init=init_mod.Zero(), allow_deferred_init=True)

    def _pre_forward(self, x, *args):
        if not self.gamma._shape_known():
            self.gamma.shape = (x.shape[1],)
            self.beta.shape = (x.shape[1],)

    def hybrid_forward(self, F, x, gamma, beta):
        return F.InstanceNorm(x, gamma, beta, eps=self._epsilon)


class LayerNorm(HybridBlock):
    def __init__(self, axis=-1, epsilon=1e-5, center=True, scale=True,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self._axis = axis
        self._epsilon = epsilon
        with self.name_scope():
            self.gamma = self.params.get("gamma", shape=(in_channels,),
                                         init=init_mod.One(), allow_deferred_init=True)
            self.beta = self.params.get("beta", shape=(in_channels,),
                                        init=init_mod.Zero(), allow_deferred_init=True)

    def _pre_forward(self, x, *args):
        if not self.gamma._shape_known():
            c = x.shape[self._axis]
            self.gamma.shape = (c,)
            self.beta.shape = (c,)

    def hybrid_forward(self, F, x, gamma, beta):
        return F.LayerNorm(x, gamma, beta, axis=self._axis, eps=self._epsilon)


class Embedding(HybridBlock):
    """Lookup table; `sparse_grad=True` records a row_sparse weight
    gradient covering only the rows a batch touches, engaging the lazy
    sparse optimizer paths (ref: gluon/nn/basic_layers.py Embedding +
    indexing_op.cc grad_stype=row_sparse)."""

    def __init__(self, input_dim, output_dim, dtype="float32",
                 weight_initializer=None, sparse_grad=False, **kwargs):
        super().__init__(**kwargs)
        self._input_dim = input_dim
        self._output_dim = output_dim
        with self.name_scope():
            self.weight = self.params.get(
                "weight", shape=(input_dim, output_dim), dtype=dtype,
                init=weight_initializer,
                grad_stype="row_sparse" if sparse_grad else "default",
            )

    def hybrid_forward(self, F, x, weight):
        # the Parameter's grad_stype drives the dispatch, as in the
        # reference (per-op grad stype support; Embedding honors it here)
        if self.weight.grad_stype == "row_sparse":
            import jax as _jax

            from ... import autograd as _ag
            from ...ndarray.ndarray import NDArray as _ND

            # eager tape only: under jit tracing the row set is dynamic, so
            # hybridized nets use the dense gather path instead
            if (isinstance(weight, _ND)
                    and not isinstance(weight._data, _jax.core.Tracer)):
                return _ag.sparse_embedding(x, weight, self._input_dim,
                                            self._output_dim)
        return F.Embedding(x, weight, input_dim=self._input_dim,
                           output_dim=self._output_dim)


class Flatten(HybridBlock):
    def __init__(self, **kwargs):
        super().__init__(**kwargs)

    def hybrid_forward(self, F, x):
        return F.Flatten(x)


class Activation(HybridBlock):
    def __init__(self, activation, **kwargs):
        self._act_type = activation  # before super(): _alias() uses it
        super().__init__(**kwargs)

    def _alias(self):
        return self._act_type

    def hybrid_forward(self, F, x):
        return F.Activation(x, act_type=self._act_type)


class Lambda(Block):
    def __init__(self, function, prefix=None):
        super().__init__(prefix=prefix)
        if isinstance(function, str):
            from ... import ndarray as nd

            self._func = getattr(nd, function)
        else:
            self._func = function

    def forward(self, *args):
        return self._func(*args)


class HybridLambda(HybridBlock):
    def __init__(self, function, prefix=None):
        super().__init__(prefix=prefix)
        if isinstance(function, str):
            self._func_name = function
            self._func = None
        else:
            self._func = function
            self._func_name = getattr(function, "__name__", "lambda")

    def hybrid_forward(self, F, *args):
        fn = self._func or getattr(F, self._func_name)
        return fn(*args)


class GroupNorm(HybridBlock):
    """Group normalization over channel groups
    (ref: gluon/nn/basic_layers.py GroupNorm, v1.6 / group_norm.cc)."""

    def __init__(self, num_groups=1, epsilon=1e-5, center=True, scale=True,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self._num_groups = int(num_groups)
        self._epsilon = epsilon
        with self.name_scope():
            self.gamma = self.params.get(
                "gamma", shape=(in_channels,), init=init_mod.One(),
                allow_deferred_init=True,
                grad_req="write" if scale else "null")
            self.beta = self.params.get(
                "beta", shape=(in_channels,), init=init_mod.Zero(),
                allow_deferred_init=True,
                grad_req="write" if center else "null")

    def _pre_forward(self, x, *args):
        if not self.gamma._shape_known():
            self.gamma.shape = (x.shape[1],)
            self.beta.shape = (x.shape[1],)

    def hybrid_forward(self, F, x, gamma, beta):
        return F.GroupNorm(x, gamma, beta, num_groups=self._num_groups,
                           eps=self._epsilon)


class ReflectionPad2D(HybridBlock):
    """Reflection padding on H/W of NCHW input
    (ref: gluon/nn/basic_layers.py ReflectionPad2D)."""

    def __init__(self, padding=0, **kwargs):
        super().__init__(**kwargs)
        if isinstance(padding, int):
            padding = (0, 0, 0, 0, padding, padding, padding, padding)
        if len(padding) != 8:
            raise ValueError(
                "padding must be an int or an 8-tuple (before/after for "
                "each NCHW axis); got %r" % (padding,))
        self._padding = tuple(padding)

    def hybrid_forward(self, F, x):
        return F.Pad(x, mode="reflect", pad_width=self._padding)
