"""Training losses, table-driven.

Capability parity with the reference's loss zoo (ref:
python/mxnet/gluon/loss.py), re-expressed in this framework's idiom: each
elementwise loss is a single declarative formula in `_LOSS_TABLE`; one
generic `Loss` engine owns the shared protocol (label/pred alignment,
sample weighting, per-sample batch mean). Structured losses whose reduction
isn't elementwise (softmax CE, CTC, triplet, cosine) are explicit classes
over the same engine.

All formulas run through `F`, so every loss works identically in eager and
hybridized/symbolic tracing.
"""
from __future__ import annotations

import numpy as np

from .block import HybridBlock

__all__ = [
    "Loss", "L2Loss", "L1Loss", "SigmoidBinaryCrossEntropyLoss", "SigmoidBCELoss",
    "SoftmaxCrossEntropyLoss", "SoftmaxCELoss", "KLDivLoss", "CTCLoss",
    "HuberLoss", "HingeLoss", "SquaredHingeLoss", "LogisticLoss",
    "TripletLoss", "CosineEmbeddingLoss", "PoissonNLLLoss",
]


class Loss(HybridBlock):
    """Shared loss protocol: optional sample_weight scaling, constant
    weight scaling, and mean over all non-batch axes."""

    def __init__(self, weight=1.0, batch_axis=0, **kwargs):
        super().__init__(**kwargs)
        self._weight = weight
        self._batch_axis = batch_axis

    def _finish(self, F, loss, sample_weight, mean=True):
        if sample_weight is not None:
            loss = F.broadcast_mul(loss, sample_weight)
        if self._weight is not None and self._weight != 1.0:
            loss = loss * self._weight
        if mean:
            loss = F.mean(loss, axis=self._batch_axis, exclude=True)
        return loss

    def __repr__(self):
        return f"{self.__class__.__name__}(batch_axis={self._batch_axis}, w={self._weight})"

    def hybrid_forward(self, F, x, *args, **kwargs):
        raise NotImplementedError


def _logit_bce(F, z, y):
    """Numerically stable BCE on logits: max(z,0) - z*y + log(1+e^-|z|)."""
    return F.relu(z) - z * y + F.Activation(-F.abs(z), act_type="softrelu")


# name -> (formula(F, pred, aligned_label) -> elementwise loss, extra ctor
# params with defaults, docstring)
_LOSS_TABLE = {
    "L2Loss": (
        lambda F, p, y, s: 0.5 * F.square(y - p),
        {},
        "mean of 0.5 (pred - label)^2",
    ),
    "L1Loss": (
        lambda F, p, y, s: F.abs(y - p),
        {},
        "mean of |pred - label|",
    ),
    "HingeLoss": (
        lambda F, p, y, s: F.relu(s["margin"] - p * y),
        {"margin": 1},
        "mean of max(0, margin - pred*label), labels in {-1, +1}",
    ),
    "SquaredHingeLoss": (
        lambda F, p, y, s: F.square(F.relu(s["margin"] - p * y)),
        {"margin": 1},
        "mean of max(0, margin - pred*label)^2, labels in {-1, +1}",
    ),
    "HuberLoss": (
        lambda F, p, y, s: F.where(
            F.abs(y - p) > s["rho"],
            F.abs(y - p) - 0.5 * s["rho"],
            (0.5 / s["rho"]) * F.square(y - p)),
        {"rho": 1},
        "smoothed L1: quadratic inside rho, linear outside",
    ),
    "LogisticLoss": (
        lambda F, p, y, s: _logit_bce(
            F, p, (y + 1.0) / 2.0 if s["label_format"] == "signed" else y),
        {"label_format": "signed"},
        "binary logistic loss on logits; labels signed {-1,1} or binary {0,1}",
    ),
}


def _make_elementwise_loss(name, formula, params, doc):
    # positional order matches the reference signatures: the loss's own
    # params first (e.g. HuberLoss(rho, ...)), then weight, batch_axis
    arg_names = list(params) + ["weight", "batch_axis"]
    defaults = {**params, "weight": 1.0, "batch_axis": 0}

    def __init__(self, *args, **kwargs):
        if len(args) > len(arg_names):
            raise TypeError(f"{name} takes at most {len(arg_names)} "
                            f"positional arguments")
        for n, v in zip(arg_names, args):
            if n in kwargs:
                raise TypeError(f"{name} got multiple values for {n!r}")
            kwargs[n] = v
        own = {k: kwargs.pop(k, defaults[k]) for k in params}
        Loss.__init__(self, kwargs.pop("weight", 1.0),
                      kwargs.pop("batch_axis", 0), **kwargs)
        self._p = own

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = F.reshape_like(label, pred)
        return self._finish(F, formula(F, pred, label, self._p), sample_weight)

    cls = type(name, (Loss,), {
        "__init__": __init__,
        "hybrid_forward": hybrid_forward,
        "__doc__": f"{doc} (ref: loss.py {name})",
    })
    return cls


for _name, (_formula, _params, _doc) in _LOSS_TABLE.items():
    globals()[_name] = _make_elementwise_loss(_name, _formula, _params, _doc)


class SigmoidBinaryCrossEntropyLoss(Loss):
    """BCE over sigmoid outputs or (default) raw logits via the stable
    log-sum-exp form (ref: loss.py SigmoidBinaryCrossEntropyLoss)."""

    def __init__(self, from_sigmoid=False, weight=1.0, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._from_sigmoid = from_sigmoid

    def hybrid_forward(self, F, pred, label, sample_weight=None, pos_weight=None):
        label = F.reshape_like(label, pred)
        if self._from_sigmoid:
            eps = 1e-12
            pos = F.log(pred + eps) * label
            if pos_weight is not None:
                pos = pos * pos_weight
            loss = -(pos + F.log(1.0 - pred + eps) * (1.0 - label))
        elif pos_weight is None:
            loss = _logit_bce(F, pred, label)
        else:
            # weighted stable form: (1-y)z + (1+(pw-1)y) * log(1+e^-z),
            # with log(1+e^-z) written as softrelu(-|z|) + relu(-z)
            log_weight = 1.0 + (pos_weight - 1.0) * label
            loss = (pred - pred * label
                    + log_weight * (F.Activation(-F.abs(pred),
                                                 act_type="softrelu")
                                    + F.relu(-pred)))
        return self._finish(F, loss, sample_weight)


class SoftmaxCrossEntropyLoss(Loss):
    """CE over an axis: sparse integer labels gather their log-prob; dense
    labels contract against log-probs (ref: loss.py SoftmaxCrossEntropyLoss)."""

    def __init__(self, axis=-1, sparse_label=True, from_logits=False,
                 weight=1.0, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._axis = axis
        self._sparse_label = sparse_label
        self._from_logits = from_logits

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        logp = pred if self._from_logits else F.log_softmax(pred, axis=self._axis)
        if self._sparse_label:
            nll = -F.pick(logp, label, axis=self._axis, keepdims=True)
        else:
            nll = -F.sum(logp * F.reshape_like(label, logp),
                         axis=self._axis, keepdims=True)
        return self._finish(F, nll, sample_weight)


class KLDivLoss(Loss):
    """KL(label || softmax(pred)); pred is log-probability when from_logits
    (ref: loss.py KLDivLoss)."""

    def __init__(self, from_logits=True, axis=-1, weight=1.0, batch_axis=0,
                 **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._from_logits = from_logits
        self._axis = axis

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        logp = pred if self._from_logits else F.log_softmax(pred, axis=self._axis)
        return self._finish(F, label * (F.log(label + 1e-12) - logp),
                            sample_weight)


class CTCLoss(Loss):
    """Connectionist temporal classification over the CTCLoss op
    (ref: loss.py CTCLoss); layouts select the time-major permutation."""

    def __init__(self, layout="NTC", label_layout="NT", weight=None, **kwargs):
        super().__init__(weight, 0, **kwargs)
        self._layout = layout
        self._label_layout = label_layout

    def hybrid_forward(self, F, pred, label, pred_lengths=None,
                       label_lengths=None, sample_weight=None):
        if self._layout == "NTC":
            pred = F.swapaxes(pred, dim1=0, dim2=1)
        if self._label_layout == "TN":
            label = F.swapaxes(label, dim1=0, dim2=1)
        loss = F.CTCLoss(pred, label, pred_lengths, label_lengths,
                         use_data_lengths=pred_lengths is not None,
                         use_label_lengths=label_lengths is not None)
        return self._finish(F, loss, sample_weight, mean=False)


class TripletLoss(Loss):
    """max(0, margin + d(pred, pos) - d(pred, neg)) with squared-L2
    distances summed per sample (ref: loss.py TripletLoss)."""

    def __init__(self, margin=1, weight=1.0, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def hybrid_forward(self, F, pred, positive, negative, sample_weight=None):
        d_pos = F.square(F.reshape_like(positive, pred) - pred)
        d_neg = F.square(F.reshape_like(negative, pred) - pred)
        gap = F.sum(d_pos - d_neg, axis=self._batch_axis, exclude=True)
        return self._finish(F, F.relu(gap + self._margin), sample_weight,
                            mean=False)


class CosineEmbeddingLoss(Loss):
    """1 - cos(a, b) for matching pairs, max(0, cos - margin) for
    non-matching (ref: loss.py CosineEmbeddingLoss)."""

    def __init__(self, weight=1.0, batch_axis=0, margin=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def hybrid_forward(self, F, input1, input2, label, sample_weight=None):
        a = input1.reshape((input1.shape[0], -1))
        b = input2.reshape((input2.shape[0], -1))
        cos = F.sum(a * b, axis=1) / F.sqrt(
            F.sum(F.square(a), axis=1) * F.sum(F.square(b), axis=1) + 1e-12)
        loss = F.where(label.reshape((-1,)) == 1,
                       1.0 - cos, F.relu(cos - self._margin))
        return self._finish(F, loss, sample_weight, mean=False)


SigmoidBCELoss = SigmoidBinaryCrossEntropyLoss
SoftmaxCELoss = SoftmaxCrossEntropyLoss



class PoissonNLLLoss(Loss):
    """Poisson negative log likelihood (ref: loss.py PoissonNLLLoss):
    exp(pred) - label*pred on logits, or pred - label*log(pred+eps) on
    rates; compute_full adds the Stirling approximation of log(label!).
    Reduces to the SCALAR mean over all axes, matching the reference's
    unique reduction for this loss."""

    def __init__(self, weight=None, from_logits=True, batch_axis=0,
                 compute_full=False, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._from_logits = from_logits
        self._compute_full = compute_full

    def hybrid_forward(self, F, pred, label, sample_weight=None,
                       epsilon=1e-08):
        label = F.reshape_like(label, pred)
        if self._from_logits:
            loss = F.exp(pred) - label * pred
        else:
            loss = pred - label * F.log(pred + epsilon)
        if self._compute_full:
            # Stirling term for label > 1: y log y - y + 0.5 log(2 pi y)
            stirling = (label * F.log(label + epsilon) - label
                        + 0.5 * F.log(2.0 * np.pi * (label + epsilon)))
            loss = loss + F.where(label > 1.0, stirling,
                                  F.zeros_like(stirling))
        loss = self._finish(F, loss, sample_weight, mean=False)
        return F.mean(loss)
