"""Gluon parameters (ref: python/mxnet/gluon/parameter.py — Parameter:43,
ParameterDict:632). Deferred shape init (0-dims resolved at first forward) is
kept; storage is a single (possibly mesh-sharded) NDArray instead of
per-device copies — replication across chips is a sharding annotation, not N
arrays.
"""
from __future__ import annotations

import contextlib
import threading
from collections import OrderedDict

import numpy as np
import jax.numpy as jnp

from ..base import dtype_np
from ..context import Context, cpu, current_context
from ..ndarray.ndarray import NDArray
from ..ndarray import zeros as nd_zeros
from ..telemetry import ledger as _ledger

_current_subst_fn = None


def _current_subst_cached():
    """block._current_subst, cached after first use (block imports this
    module; Parameter.data() is called per-param per-forward, so the
    per-call `from .block import` costs importlib-lock time)."""
    global _current_subst_fn
    if _current_subst_fn is None:
        from .block import _current_subst

        _current_subst_fn = _current_subst
    return _current_subst_fn()
from .. import initializer as init_mod

__all__ = ["DeferredInitializationError", "Parameter", "Constant", "ParameterDict"]


class DeferredInitializationError(Exception):
    """(ref: parameter.py DeferredInitializationError)"""


_ABSTRACT = threading.local()


@contextlib.contextmanager
def abstract_init_mode():
    """While active, deferred params resolve SHAPES but do not materialize
    arrays (used when shape inference runs inside a jax trace — creating
    values there would leak tracers into global state)."""
    prev = getattr(_ABSTRACT, "on", False)
    _ABSTRACT.on = True
    try:
        yield
    finally:
        _ABSTRACT.on = prev


def _abstract_mode():
    return getattr(_ABSTRACT, "on", False)


class Parameter:
    def __init__(self, name, grad_req="write", shape=None, dtype="float32",
                 lr_mult=1.0, wd_mult=1.0, init=None, allow_deferred_init=False,
                 differentiable=True, stype="default", grad_stype="default"):
        self.name = name
        self._grad_req = grad_req if differentiable else "null"
        self.stype = stype
        self.grad_stype = grad_stype
        self._shape = tuple(shape) if shape is not None else None
        self.dtype = dtype
        self.lr_mult = lr_mult
        self.wd_mult = wd_mult
        self.init = init
        self._allow_deferred_init = allow_deferred_init
        self._data = None          # NDArray
        self._grad = None
        self._deferred_init = None  # (initializer, ctx) captured at initialize()
        self._var = None

    def __repr__(self):
        return f"Parameter {self.name} (shape={self._shape}, dtype={self.dtype})"

    # -- shape handling ----------------------------------------------------
    @property
    def shape(self):
        return self._shape

    @shape.setter
    def shape(self, new_shape):
        if self._shape is not None and not _shape_compatible(self._shape, new_shape):
            raise AssertionError(
                f"{self.name}: incompatible shape {new_shape} vs {self._shape}"
            )
        self._shape = tuple(new_shape)
        if self._deferred_init is not None and self._shape_known():
            self._finish_deferred_init()

    def _shape_known(self):
        return self._shape is not None and all(s > 0 for s in self._shape)

    @property
    def grad_req(self):
        return self._grad_req

    @grad_req.setter
    def grad_req(self, req):
        self._grad_req = req
        if self._data is not None:
            if req == "null":
                self._grad = None
                self._data._grad = None
            else:
                self._attach_grad()

    # -- init --------------------------------------------------------------
    def initialize(self, init=None, ctx=None, default_init=None, force_reinit=False):
        """(ref: parameter.py Parameter.initialize)"""
        if self._data is not None and not force_reinit:
            return
        if default_init is None:
            default_init = init_mod.Uniform(0.07)
        initializer = init or self.init or default_init
        self._deferred_init = (initializer, ctx)
        if self._shape_known():
            self._finish_deferred_init()
        elif not self._allow_deferred_init:
            raise ValueError(
                f"cannot initialize {self.name}: shape {self._shape} unknown; "
                "set allow_deferred_init=True or give a full shape"
            )

    def _finish_deferred_init(self):
        if _abstract_mode():
            return  # shape now known; materialize later, outside the trace
        initializer, ctx = self._deferred_init
        if isinstance(ctx, (list, tuple)):
            # reference scripts pass ctx LISTS (per-device replicas); here a
            # parameter is ONE logical array — SPMD/mesh sharding handles
            # multi-device placement — so a list selects its first context
            ctx = ctx[0] if ctx else None
        arr = nd_zeros(self._shape, ctx=ctx, dtype=self.dtype)
        initializer(init_mod.InitDesc(self.name, {"__init__": None}), arr)
        self._data = arr
        _ledger.track(arr, "params")
        self._deferred_init = None
        if self._grad_req != "null":
            self._attach_grad()

    def _attach_grad(self):
        if self.grad_stype == "row_sparse":
            # a row_sparse-grad parameter (embedding table) must not pay a
            # vocab-sized dense zeros buffer before step one: start from an
            # EMPTY row_sparse grad. backward() deposits only the rows a
            # batch touched, the lazy optimizer paths consume them, and a
            # dense cotangent still lazily materializes a dense buffer in
            # autograd (no _dense_grad_buf backref to keep alive here).
            from ..ndarray.sparse import RowSparseNDArray

            width = tuple(self._shape[1:])
            self._grad = RowSparseNDArray(
                NDArray._from_data(jnp.zeros((0,) + width,
                                             dtype_np(self.dtype))),
                NDArray._from_data(jnp.zeros((0,), jnp.int64)),
                tuple(self._shape))
            _ledger.track(self._grad.data, "grads")
            self._data._grad = self._grad
            self._data._grad_req = self._grad_req
            return
        self._grad = NDArray._from_data(jnp.zeros(self._shape, dtype_np(self.dtype)))
        _ledger.track(self._grad, "grads")
        self._data._grad = self._grad
        self._data._grad_req = self._grad_req
        # backward() may swap _grad for a RowSparseNDArray; this backref
        # lets it restore THIS buffer when a dense cotangent returns, so
        # Parameter._grad identity survives the round trip
        self._data._dense_grad_buf = self._grad

    # -- access ------------------------------------------------------------
    def data(self, ctx=None):
        if self._data is None:
            if self._deferred_init is not None and _abstract_mode() and self._shape_known():
                # inside an abstract (eval_shape) pass: a trace-local dummy
                return NDArray._from_data(jnp.zeros(self._shape, dtype_np(self.dtype)))
            if self._deferred_init is not None:
                raise DeferredInitializationError(
                    f"parameter {self.name} deferred (shape {self._shape})"
                )
            raise RuntimeError(f"parameter {self.name} not initialized")
        subst = _current_subst_cached()
        if subst is not None and self.name in subst:
            return subst[self.name]
        return self._data

    def list_data(self):
        return [self.data()]

    def grad(self, ctx=None):
        # the data array's buffer is authoritative: backward() may have
        # replaced it with a RowSparseNDArray (sparse_grad embeddings)
        g = getattr(self._data, "_grad", None) if self._data is not None else None
        if g is not None:
            return g
        if self._grad is None:
            raise RuntimeError(f"parameter {self.name} has no gradient (grad_req=null?)")
        return self._grad

    def list_grad(self):
        return [self.grad()]

    def list_ctx(self):
        return [self._data.context] if self._data is not None else []

    @property
    def sharding(self):
        """The jax sharding of this parameter's live buffer (None until
        the data is committed to a device/mesh). Parameters carry their
        placement so the trainer and checkpoint layers can put optimizer
        state and restored values next to the weight (ZeRO policies,
        MXTPU_SHARD_POLICY) without reaching into ._data."""
        d = self._data._data if self._data is not None else None
        return getattr(d, "sharding", None)

    def place(self, sharding):
        """Commit the parameter's data (and dense grad buffer) onto
        `sharding` — a jax.sharding.Sharding or a device. The mesh
        entry point: place(NamedSharding(mesh, P())) replicates a
        weight over the dp axis; subsequent eager ops and fused steps
        then inherit the placement."""
        import jax as _jax

        if self._data is None:
            raise RuntimeError(
                f"cannot place uninitialized parameter {self.name}")
        self._data._data = _jax.device_put(self._data._data, sharding)
        if isinstance(self._grad, NDArray):
            # row_sparse grad buffers are O(batch rows) and rebuilt every
            # backward — placement would not survive the step, skip them
            self._grad._data = _jax.device_put(self._grad._data, sharding)
        return self

    def set_data(self, data):
        arr = data if isinstance(data, NDArray) else NDArray(data)
        if self._data is None:
            self._shape = arr.shape
            self._data = NDArray(jnp.asarray(arr._data, dtype_np(self.dtype)))
            _ledger.track(self._data, "params")
            self._deferred_init = None
            if self._grad_req != "null":
                self._attach_grad()
        else:
            self._data._data = jnp.asarray(arr._data, dtype=self._data._data.dtype).reshape(self._shape)

    def zero_grad(self):
        from ..ndarray.sparse import BaseSparseNDArray

        live = getattr(self._data, "_grad", None) if self._data is not None else None
        if isinstance(live, BaseSparseNDArray) or (
                live is not None and live is not self._grad):
            # backward() replaced the buffer (sparse grad, or a fresh dense
            # one displacing a sparse grad) — re-attach so self._grad and
            # _data._grad agree again
            self._attach_grad()
            return
        if self._grad is not None:
            self._grad._data = jnp.zeros_like(self._grad._data)

    def reset_ctx(self, ctx):
        pass

    def cast(self, dtype):
        self.dtype = dtype
        if self._data is not None:
            self._data._data = self._data._data.astype(dtype_np(dtype))
            if isinstance(self._grad, NDArray):
                self._grad._data = self._grad._data.astype(dtype_np(dtype))
            elif self._grad is not None:
                # sparse grad buffer: rebuild empty at the new dtype
                self._attach_grad()

    def var(self):
        if self._var is None:
            from .. import symbol as sym

            self._var = sym.Variable(self.name)
        return self._var


class Constant(Parameter):
    """Non-differentiable constant parameter (ref: parameter.py Constant)."""

    def __init__(self, name, value):
        value = np.asarray(value, dtype=np.float32)
        self.value = value

        class _CInit(init_mod.Initializer):
            def _init_weight(self_inner, _name, arr):
                arr._data = jnp.asarray(value)

        super().__init__(
            name, grad_req="null", shape=value.shape, init=_CInit(),
            differentiable=False,
        )


def _shape_compatible(old, new):
    if len(old) != len(new):
        return False
    return all(o == n or o in (0, -1) for o, n in zip(old, new))


class ParameterDict:
    """(ref: parameter.py:632 ParameterDict)"""

    def __init__(self, prefix="", shared=None):
        self._prefix = prefix
        self._params = OrderedDict()
        self._shared = shared

    @property
    def prefix(self):
        return self._prefix

    def __repr__(self):
        return f"ParameterDict({list(self._params)})"

    def __getitem__(self, key):
        return self._params[key]

    def __iter__(self):
        return iter(self._params)

    def items(self):
        return self._params.items()

    def keys(self):
        return self._params.keys()

    def values(self):
        return self._params.values()

    def __contains__(self, key):
        return key in self._params

    def get(self, name, **kwargs):
        """Create-or-retrieve (ref: ParameterDict.get)."""
        name = self._prefix + name
        if name in self._params:
            param = self._params[name]
            for k, v in kwargs.items():
                if k == "shape" and v is not None and param.shape is not None:
                    param.shape = tuple(v)
            return param
        if self._shared is not None and name in self._shared:
            self._params[name] = self._shared[name]
            return self._shared[name]
        param = Parameter(name, **kwargs)
        self._params[name] = param
        return param

    def get_constant(self, name, value=None):
        name = self._prefix + name
        if name in self._params:
            return self._params[name]
        c = Constant(name, value)
        self._params[name] = c
        return c

    def update(self, other):
        for k, v in other.items():
            if k in self._params and self._params[k] is not v:
                raise ValueError(f"duplicate parameter {k}")
            self._params[k] = v

    def initialize(self, init=None, ctx=None, verbose=False, force_reinit=False):
        for p in self.values():
            p.initialize(init=None, ctx=ctx, default_init=init or init_mod.Uniform(0.07),
                         force_reinit=force_reinit)

    def zero_grad(self):
        for p in self.values():
            p.zero_grad()

    def reset_ctx(self, ctx):
        pass

    def setattr(self, name, value):
        for p in self.values():
            setattr(p, name, value)

    def save(self, filename, strip_prefix=""):
        from ..ndarray import save as nd_save

        arg = {}
        for p in self.values():
            name = p.name
            if strip_prefix and name.startswith(strip_prefix):
                name = name[len(strip_prefix):]
            arg[name] = p._data
        nd_save(filename, arg)

    def load(self, filename, ctx=None, allow_missing=False, ignore_extra=False,
             restore_prefix=""):
        from ..ndarray import load as nd_load

        loaded = nd_load(filename)
        loaded = {restore_prefix + k: v for k, v in loaded.items()}
        for name, p in self.items():
            if name in loaded:
                p.set_data(loaded[name])
            elif not allow_missing:
                raise ValueError(f"parameter {name} missing in {filename}")
        if not ignore_extra:
            extra = set(loaded) - set(self.keys())
            if extra:
                raise ValueError(f"extra parameters in file: {sorted(extra)}")
