"""Gluon Blocks (ref: python/mxnet/gluon/block.py — Block:127,
HybridBlock:671, SymbolBlock:952).

TPU-native hybridization: instead of tracing into an nnvm CachedOp
(ref: block.py:748 _build_cache), `hybridize()` wraps the block's forward in
`jax.jit`. Parameters enter as function arguments (via a thread-local
substitution map, so `param.data()` yields tracers during tracing); RNG keys
and the training flag are threaded explicitly. Under autograd.record the
whole jitted call becomes ONE tape node via jax.vjp — the exact analog of
CachedOp recording one node for the whole subgraph (ref: cached_op.cc:889).
"""
from __future__ import annotations

import re
import threading
from collections import OrderedDict

import jax
import jax.numpy as jnp

from .. import autograd
from .. import random as _global_random
from ..ndarray.ndarray import NDArray
from .parameter import Parameter, ParameterDict, DeferredInitializationError

__all__ = ["Block", "HybridBlock", "SymbolBlock"]

_SUBST = threading.local()


def _current_subst():
    return getattr(_SUBST, "map", None)


_SYMBOLIC = threading.local()


def _symbolic_active():
    return getattr(_SYMBOLIC, "on", False)


class _SymbolicTrace:
    """While active, hybrid_forward's `F` namespace resolves to `sym.*`
    and parameters appear as named Variables — tracing a HybridBlock
    produces the declarative Symbol graph (the reference's
    CachedOp-to-Symbol bridge that powers HybridBlock.export,
    ref: gluon/block.py:1256 _build_cache/export)."""

    def __enter__(self):
        self._prev = getattr(_SYMBOLIC, "on", False)
        _SYMBOLIC.on = True
        return self

    def __exit__(self, *exc):
        _SYMBOLIC.on = self._prev


class _ParamSubst:
    """Substitute param.data() results during jit tracing."""

    def __init__(self, mapping):
        self.mapping = mapping

    def __enter__(self):
        self._prev = getattr(_SUBST, "map", None)
        _SUBST.map = self.mapping
        return self

    def __exit__(self, *exc):
        _SUBST.map = self._prev


class _BlockScope:
    _current = threading.local()

    def __init__(self, block):
        self._block = block
        self._counter = {}
        self._old_scope = None

    @staticmethod
    def create(prefix, params, hint):
        current = getattr(_BlockScope._current, "value", None)
        if current is None:
            if prefix is None:
                prefix = hint + str(_NameManager.next(hint)) + "_"
            if params is None:
                params = ParameterDict(prefix)
            else:
                params = ParameterDict(params.prefix, params)
            return prefix, params
        if prefix is None:
            count = current._counter.get(hint, 0)
            prefix = f"{hint}{count}_"
            current._counter[hint] = count + 1
        if params is None:
            parent = current._block.params
            params = ParameterDict(parent.prefix + prefix, parent._shared)
        else:
            params = ParameterDict(params.prefix, params)
        return current._block.prefix + prefix, params

    def __enter__(self):
        self._old_scope = getattr(_BlockScope._current, "value", None)
        _BlockScope._current.value = self
        return self

    def __exit__(self, *exc):
        _BlockScope._current.value = self._old_scope


class _NameManager:
    _counters = {}

    @classmethod
    def next(cls, hint):
        c = cls._counters.get(hint, 0)
        cls._counters[hint] = c + 1
        return c


class Block:
    """(ref: gluon/block.py:127)"""

    def __init__(self, prefix=None, params=None):
        self._empty_prefix = prefix == ""
        self._prefix, self._params = _BlockScope.create(prefix, params, self._alias())
        self._name = self._prefix[:-1] if self._prefix.endswith("_") else self._prefix
        self._scope = _BlockScope(self)
        self._children = OrderedDict()
        self._reg_params = {}
        self._forward_hooks = []
        self._forward_pre_hooks = []

    def _alias(self):
        return self.__class__.__name__.lower()

    @property
    def prefix(self):
        return self._prefix

    @property
    def name(self):
        return self._name

    def name_scope(self):
        return self._scope

    @property
    def params(self):
        return self._params

    def collect_params(self, select=None):
        """(ref: block.py collect_params)"""
        ret = ParameterDict(self._params.prefix)
        if select is None:
            ret.update(self.params)
        else:
            pattern = re.compile(select)
            ret.update({n: p for n, p in self.params.items() if pattern.match(n)})
        for child in self._children.values():
            ret.update(child.collect_params(select))
        return ret

    def __setattr__(self, name, value):
        if isinstance(value, Block):
            self.__dict__.setdefault("_children", OrderedDict())[name] = value
        elif isinstance(value, Parameter):
            self.__dict__.setdefault("_reg_params", {})[name] = value
            self._params._params[value.name] = value
        super().__setattr__(name, value)

    def register_child(self, block, name=None):
        self._children[name or str(len(self._children))] = block

    def register_forward_hook(self, hook):
        self._forward_hooks.append(hook)

    def register_forward_pre_hook(self, hook):
        self._forward_pre_hooks.append(hook)

    def apply(self, fn):
        for child in self._children.values():
            child.apply(fn)
        fn(self)
        return self

    def initialize(self, init=None, ctx=None, verbose=False, force_reinit=False):
        self.collect_params().initialize(init, ctx, verbose, force_reinit)

    def cast(self, dtype):
        for child in self._children.values():
            child.cast(dtype)
        for p in self.params.values():
            p.cast(dtype)

    def __call__(self, *args):
        for hook in self._forward_pre_hooks:
            hook(self, args)
        out = self.forward(*args)
        for hook in self._forward_hooks:
            hook(self, args, out)
        return out

    def forward(self, *args):
        raise NotImplementedError

    def summary(self, *inputs):
        out = self(*inputs)
        return out

    # -- serialization -----------------------------------------------------
    def _collect_params_with_prefix(self, prefix=""):
        """Structure-relative parameter names (ref: block.py
        _collect_params_with_prefix — keys like '0.weight' survive re-creating
        the model with fresh name counters)."""
        if prefix:
            prefix += "."
        ret = {prefix + name: p for name, p in self._reg_params.items()}
        for name, child in self._children.items():
            ret.update(child._collect_params_with_prefix(prefix + name))
        return ret

    def save_parameters(self, filename, deduplicate=False):
        """(ref: block.py:315)"""
        params = self._collect_params_with_prefix()
        from ..ndarray import save as nd_save

        arg = {n: p._data for n, p in params.items() if p._data is not None}
        nd_save(filename, arg)

    def load_parameters(self, filename, ctx=None, allow_missing=False,
                        ignore_extra=False, cast_dtype=False, dtype_source="current"):
        """(ref: block.py:356)"""
        from ..ndarray import load as nd_load

        loaded = nd_load(filename)
        params = self._collect_params_with_prefix()
        if loaded and params and not any(k in params for k in loaded):
            # fall back to full-prefix names (ParameterDict.save format)
            params = dict(self.collect_params().items())
        for name, p in params.items():
            if name in loaded:
                p.set_data(loaded[name])
            elif not allow_missing:
                raise ValueError(f"parameter {name} missing in {filename}")
        if not ignore_extra:
            extra = set(loaded) - set(params.keys())
            if extra:
                raise ValueError(f"extra parameters: {sorted(extra)}")

    save_params = save_parameters
    load_params = load_parameters

    def hybridize(self, active=True, **kwargs):
        for child in self._children.values():
            child.hybridize(active, **kwargs)


class HybridBlock(Block):
    """(ref: gluon/block.py:671)"""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._active = False
        self._cached_fn = None
        self._cached_param_names = None

    def hybridize(self, active=True, **kwargs):
        self._active = active
        self._cached_fn = None
        super().hybridize(active, **kwargs)

    def cast(self, dtype):
        self._cached_fn = None
        super().cast(dtype)

    def forward(self, x, *args):
        """(ref: HybridBlock.forward:901) — dispatch eager or cached-jit.

        When already inside a parent block's trace (param substitution
        active), inline into it instead of nesting another cached call —
        the analog of CachedOp flattening nested hybridized subgraphs.
        """
        if _symbolic_active():
            # symbolic trace: inputs are Symbols (no .shape for
            # _pre_forward; params must already be initialized)
            return self.hybrid_forward(_F, x, *args, **self._param_kwargs())
        self._pre_forward(x, *args)
        if not self._active or _current_subst() is not None:
            return self.hybrid_forward(_F, x, *args, **self._param_kwargs())
        return self._call_cached(x, *args)

    def _pre_forward(self, *args):
        """Hook: layers resolve deferred param shapes from the first input
        (the reference does this by catching DeferredInitializationError in
        forward, ref: block.py HybridBlock.forward)."""
        return

    def _param_kwargs(self):
        if _symbolic_active():
            from .. import symbol as sym_mod

            return {name: sym_mod.Variable(p.name)
                    for name, p in self._reg_params.items()}
        return {name: p.data() for name, p in self._reg_params.items()}

    def hybrid_forward(self, F, x, *args, **kwargs):
        raise NotImplementedError

    # -- cached (jitted) path ---------------------------------------------
    def _build_cache(self):
        params = self.collect_params()
        # only initialized params participate
        names = [n for n, p in params.items() if p._data is not None]
        param_objs = [params[n] for n in names]

        def fn(param_datas, input_datas, key, training):
            mapping = {
                n: NDArray._from_data(d) for n, d in zip(names, param_datas)
            }
            wrapped = [
                NDArray._from_data(d) if d is not None else None for d in input_datas
            ]
            prev_t = autograd.set_training(training)
            prev_r = autograd.set_recording(False)
            try:
                with _ParamSubst(mapping), _global_random.key_override(key):
                    out = self._eager_forward(wrapped)
            finally:
                autograd.set_training(prev_t)
                autograd.set_recording(prev_r)
            outs = out if isinstance(out, (tuple, list)) else [out]
            out_datas = tuple(o._data for o in outs)
            # aux writes (BN running stats): substituted arrays whose _data
            # changed during the call
            aux_updates = {
                n: arr._data for n, arr in mapping.items()
                if arr._data is not param_datas[names.index(n)]
            }
            return out_datas, aux_updates

        jitted = jax.jit(fn, static_argnums=(3,))
        self._cached_fn = jitted
        self._cached_param_names = names
        self._cached_param_objs = param_objs

    def _eager_forward(self, wrapped):
        return self.hybrid_forward(_F, *wrapped, **self._param_kwargs())

    def _call_cached(self, *inputs):
        if self._cached_fn is None:
            # one eager warmup resolves deferred param shapes before tracing
            with autograd.pause():
                self._eager_forward(list(inputs))
            self._build_cache()
        names = self._cached_param_names
        param_objs = self._cached_param_objs
        param_arrays = [p.data() for p in param_objs]
        key = _global_random.next_key()
        training = autograd.is_training()

        fn = self._cached_fn
        n_params = len(param_arrays)
        input_arrays = list(inputs)

        def call_fn(*datas):
            out_datas, aux_updates = fn(
                tuple(datas[:n_params]), tuple(datas[n_params:]), key, training
            )
            return tuple(out_datas) + tuple(aux_updates[k] for k in sorted(aux_updates))

        results = autograd.invoke_recorded(
            call_fn, param_arrays + input_arrays, name=self.name
        )
        # aux output names are deterministic per (shapes, training); derive by
        # abstract evaluation once and cache
        cache_key = (training, tuple(a.shape for a in input_arrays))
        aux_names = getattr(self, "_aux_names_cache", {}).get(cache_key)
        if aux_names is None:
            sd = lambda a: jax.ShapeDtypeStruct(tuple(a.shape), a._data.dtype)
            _, aux_updates = jax.eval_shape(
                lambda p, i: fn(p, i, key, training),
                tuple(sd(a) for a in param_arrays), tuple(sd(a) for a in input_arrays),
            )
            aux_names = sorted(aux_updates)
            if not hasattr(self, "_aux_names_cache"):
                self._aux_names_cache = {}
            self._aux_names_cache[cache_key] = aux_names
        n_out = len(results) - len(aux_names)
        primary = results[:n_out]
        for aux_name, new_val in zip(aux_names, results[n_out:]):
            param_objs[names.index(aux_name)]._data._data = new_val._data
        return primary if len(primary) > 1 else primary[0]

    def export(self, path, epoch=0, remove_amp_cast=True):
        """Export symbol+params for deployment (ref: block.py:868) — writes
        `path-symbol.json` + `path-%04d.params` in the reference's
        arg:/aux: container format. Works on any HybridBlock whose
        parameters are initialized (shapes must be known; run one forward
        or initialize with explicit in_units/in_channels first)."""
        sym_out, arg_params, aux_params = self._symbol_and_params()
        sym_out.save(f"{path}-symbol.json")
        from ..ndarray.legacy_io import save_mxnet_params

        payload = {}
        for name, arr in arg_params.items():
            payload["arg:" + name] = arr._data
        for name, arr in aux_params.items():
            payload["aux:" + name] = arr._data
        # reference byte format: the exported pair is loadable by the
        # reference runtime itself, not just by this framework
        save_mxnet_params(f"{path}-{epoch:04d}.params", payload)
        return f"{path}-symbol.json", f"{path}-{epoch:04d}.params"

    def _symbol_and_params(self, *input_names):
        """Trace to a Symbol and split initialized parameters into
        (symbol, arg_params, aux_params) — shared by export() and
        deploy.export_gluon_predictor. Uninitialized (deferred) params are
        skipped; downstream consumers report them as missing by name."""
        sym_out = self._to_symbol(*input_names)
        arg_names = set(sym_out.list_arguments())
        aux_names = set(sym_out.list_auxiliary_states())
        arg_params, aux_params = {}, {}
        for name, p in self.collect_params().items():
            if p._data is None:
                continue
            if name in aux_names:
                aux_params[name] = p.data()
            elif name in arg_names:
                arg_params[name] = p.data()
        return sym_out, arg_params, aux_params

    def _to_symbol(self, *input_names):
        """Trace this block into a declarative Symbol (the SymbolBlock
        bridge): `F` becomes `sym.*`, parameters become named Variables.
        Default input name: "data"."""
        from .. import symbol as sym_mod

        inputs = [sym_mod.Variable(n)
                  for n in (input_names or ("data",))]
        with _SymbolicTrace():
            out = self(*inputs)
        if isinstance(out, (list, tuple)):
            out = sym_mod.Group(list(out))
        return out


class _FModule:
    """The `F` namespace handed to hybrid_forward: eager nd ops (tracers flow
    through them transparently under jit), or `sym.*` during a symbolic
    trace (the reference's F=ndarray / F=symbol duality)."""

    def __getattr__(self, name):
        if _symbolic_active():
            from .. import symbol as sym_mod

            return getattr(sym_mod, name)
        from .. import ndarray as nd

        return getattr(nd, name)


_F = _FModule()


class SymbolBlock(HybridBlock):
    """Wrap a Symbol as a Block (ref: block.py:952)."""

    def __init__(self, outputs, inputs, params=None):
        super().__init__(prefix="", params=params)
        from ..symbol import Symbol, Group

        if isinstance(outputs, (list, tuple)):
            outputs = Group(list(outputs))
        self._symbol = outputs
        self._inputs = [i.name if isinstance(i, Symbol) else i for i in (
            inputs if isinstance(inputs, (list, tuple)) else [inputs]
        )]
        arg_names = outputs.list_arguments()
        aux_names = outputs.list_auxiliary_states()
        for name in arg_names + aux_names:
            if name not in self._inputs:
                self._params.get(
                    name.replace(self._params.prefix, "", 1) if self._params.prefix else name,
                    grad_req="write" if name in arg_names else "null",
                    allow_deferred_init=True,
                )
        self._eval_fn = outputs.make_eval_fn()

    def forward(self, *args):
        arg_dict = {}
        params = self.collect_params()
        datas = [a._data if isinstance(a, NDArray) else jnp.asarray(a) for a in args]
        for name, d in zip(self._inputs, datas):
            arg_dict[name] = d
        aux_names = set(self._symbol.list_auxiliary_states())
        aux_dict = {}
        for n, p in params.items():
            if p._data is None:
                continue
            if n in aux_names:
                aux_dict[n] = p.data()._data
            else:
                arg_dict[n] = p.data()._data
        outs, _ = self._eval_fn(arg_dict, aux_dict, _global_random.next_key(),
                                autograd.is_training())
        res = [NDArray._from_data(o) for o in outs]
        return res if len(res) > 1 else res[0]
