"""Gluon utilities (ref: python/mxnet/gluon/utils.py)."""
from __future__ import annotations

import hashlib

import numpy as np

from ..ndarray.ndarray import NDArray
from ..ndarray import array as nd_array

__all__ = ["split_data", "split_and_load", "clip_global_norm", "check_sha1", "download"]


def split_data(data, num_slice, batch_axis=0, even_split=True):
    """(ref: utils.py split_data)"""
    size = data.shape[batch_axis]
    if even_split and size % num_slice != 0:
        raise ValueError(
            f"data size {size} not divisible by {num_slice}"
        )
    step = size // num_slice
    slices = []
    for i in range(num_slice):
        lo = i * step
        hi = (i + 1) * step if i < num_slice - 1 else size
        idx = [slice(None)] * data.ndim
        idx[batch_axis] = slice(lo, hi)
        slices.append(data[tuple(idx)])
    return slices


def split_and_load(data, ctx_list, batch_axis=0, even_split=True):
    """(ref: utils.py split_and_load). On a mesh this is where the reference
    copies slices per GPU; we return per-context slices for API parity (the
    sharded-executor path doesn't need it)."""
    if not isinstance(data, NDArray):
        data = nd_array(data)
    if len(ctx_list) == 1:
        return [data.as_in_context(ctx_list[0])]
    slices = split_data(data, len(ctx_list), batch_axis, even_split)
    return [s.as_in_context(ctx) for s, ctx in zip(slices, ctx_list)]


def clip_global_norm(arrays, max_norm, check_isfinite=True):
    """(ref: utils.py clip_global_norm)"""
    import jax.numpy as jnp

    total = 0.0
    for a in arrays:
        total = total + jnp.sum(jnp.square(a._data))
    norm = float(jnp.sqrt(total))
    scale = max_norm / (norm + 1e-8)
    if scale < 1.0:
        for a in arrays:
            a._data = a._data * scale
    return norm


def check_sha1(filename, sha1_hash):
    sha1 = hashlib.sha1()
    with open(filename, "rb") as f:
        while True:
            data = f.read(1048576)
            if not data:
                break
            sha1.update(data)
    return sha1.hexdigest() == sha1_hash


def download(url, path=None, overwrite=False, sha1_hash=None, retries=5, verify_ssl=True):
    raise RuntimeError(
        "this environment has no network egress; place files locally instead"
    )
