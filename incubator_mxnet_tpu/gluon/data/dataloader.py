"""Gluon DataLoader (ref: python/mxnet/gluon/data/dataloader.py:98-190).

The reference forks worker processes sharing NDArrays through POSIX-shm
(CPUSharedStorageManager). TPU-native twist: batches are assembled in numpy
by a thread pool (JAX arrays are process-local; threads avoid the fork+IPC
machinery while XLA dispatch releases the GIL), then transferred async to
device. num_workers>0 selects the threaded prefetch path.
"""
from __future__ import annotations

import concurrent.futures as _futures
import time as _time

import numpy as np

from ... import telemetry as _telemetry
from ...ndarray.ndarray import NDArray
from ...ndarray import array as nd_array
from ...resilience import fault as _fault
from .sampler import BatchSampler, RandomSampler, SequentialSampler

__all__ = ["DataLoader", "default_batchify_fn"]


def default_batchify_fn(data):
    """(ref: dataloader.py default_batchify_fn)"""
    if isinstance(data[0], NDArray):
        import jax.numpy as jnp

        return nd_array(np.stack([d.asnumpy() for d in data]))
    if isinstance(data[0], tuple):
        data = zip(*data)
        return [default_batchify_fn(list(i)) for i in data]
    data = np.asarray(data)
    return nd_array(data)


class DataLoader:
    """(ref: dataloader.py DataLoader)"""

    def __init__(self, dataset, batch_size=None, shuffle=False, sampler=None,
                 last_batch=None, batch_sampler=None, batchify_fn=None,
                 num_workers=0, pin_memory=False, prefetch=None, thread_pool=True):
        self._dataset = dataset
        if batch_sampler is None:
            if batch_size is None:
                raise ValueError("batch_size required when batch_sampler is None")
            if sampler is None:
                sampler = RandomSampler(len(dataset)) if shuffle else SequentialSampler(len(dataset))
            elif shuffle:
                raise ValueError("shuffle must be False with custom sampler")
            batch_sampler = BatchSampler(sampler, batch_size, last_batch or "keep")
        self._batch_sampler = batch_sampler
        self._batchify_fn = batchify_fn or default_batchify_fn
        self._num_workers = num_workers
        self._prefetch = max(0, prefetch or 2 * max(num_workers, 1))
        # checkpointable position: epoch index + batches served within it
        # (docs/FAULT_TOLERANCE.md — Preemption and exact resume)
        self._epoch = 0
        self._batches = 0
        self._resume_skip = 0

    def state_dict(self):
        """Checkpointable pipeline position — callable mid-epoch: `batch`
        counts the batches the consumer has already received this epoch."""
        return {"version": 1, "epoch": self._epoch, "batch": self._batches,
                "batch_sampler": self._batch_sampler.state_dict()}

    def load_state_dict(self, state):
        """Restore a `state_dict()`: the next `__iter__` replays the
        interrupted epoch's index order (sampler RNG rewound to its epoch
        start) and fast-forwards past the first `batch` batches WITHOUT
        fetching their data, so a resumed job sees the exact batch
        sequence an uninterrupted run would have."""
        self._epoch = int(state["epoch"])
        self._batches = int(state["batch"])
        self._resume_skip = self._batches
        self._batch_sampler.load_state_dict(state["batch_sampler"],
                                            mid_epoch=self._batches > 0)

    def __iter__(self):
        self._batches = self._resume_skip
        it = self._iter_impl()
        if not _telemetry.enabled():
            # cursor BEFORE yield: the generator suspends at yield, so a
            # state_dict() taken after the consumer received batch k must
            # already read k served
            for batch in it:
                self._batches += 1
                yield batch
        else:
            # batch-fetch latency as the consumer sees it: time blocked in
            # next() — includes batchify for the serial path and result-wait
            # for the prefetched path (a well-fed pipeline reads near zero)
            while True:
                t0 = _time.perf_counter()
                try:
                    batch = next(it)
                except StopIteration:
                    break
                dt = _time.perf_counter() - t0
                _telemetry.observe(
                    "mxtpu_dataloader_fetch_seconds", dt,
                    help="Time the training loop blocked fetching a batch.")
                # the same measurement feeds the step breakdown: fetch
                # time belongs to the step that consumes the batch
                _telemetry.stepstats.record("data_fetch", dt)
                self._batches += 1
                yield batch
        # epoch bookkeeping only on normal exhaustion: an abandoned
        # generator leaves the mid-epoch cursor for state_dict() to report
        self._epoch += 1
        self._batches = 0

    def _fetch(self, batch):
        _fault.injector().raise_for("data.fetch")
        return self._batchify_fn([self._dataset[i] for i in batch])

    def _index_iter(self):
        """The epoch's batch-index stream, fast-forwarded past batches a
        restored cursor already served (index-only: skipping is free)."""
        it = iter(self._batch_sampler)
        skip, self._resume_skip = self._resume_skip, 0
        for _ in range(skip):
            try:
                next(it)
            except StopIteration:
                return iter(())
        return it

    def _iter_impl(self):
        if self._num_workers == 0:
            for batch in self._index_iter():
                yield self._fetch(batch)
            return
        # threaded prefetch pipeline (PrefetcherIter analog). Failure
        # path: the FIRST worker/batchify exception is re-raised promptly
        # with its batch index, and every still-pending future is
        # cancelled — without this, an early failure surfaced only after
        # the whole prefetch window drained, and non-executed futures
        # wedged pool shutdown behind work nobody will consume.
        pool = _futures.ThreadPoolExecutor(self._num_workers)
        try:
            pending = []  # (batch_index, future), consumed in order
            it = self._index_iter()
            n_submitted = 0

            def submit():
                nonlocal n_submitted
                try:
                    batch = next(it)
                except StopIteration:
                    return None
                idx = n_submitted
                n_submitted += 1
                return (idx, pool.submit(self._fetch, batch))

            for _ in range(self._prefetch):
                f = submit()
                if f is None:
                    break
                pending.append(f)
            while pending:
                idx, f = pending.pop(0)
                nxt = submit()
                if nxt is not None:
                    pending.append(nxt)
                _telemetry.set_gauge(
                    "mxtpu_dataloader_queue_depth", len(pending),
                    help="Prefetch batches in flight (0 = pipeline "
                         "starved, consumer about to block).")
                try:
                    yield f.result()
                except Exception as e:
                    for _i, p in pending:
                        p.cancel()
                    raise RuntimeError(
                        f"DataLoader worker failed on batch {idx}: "
                        f"{type(e).__name__}: {e}") from e
        finally:
            # cancel_futures: a generator abandoned mid-epoch (or the
            # failure path above) must not block on unconsumed batches
            pool.shutdown(wait=True, cancel_futures=True)

    def __len__(self):
        return len(self._batch_sampler)
