"""Vision transforms (ref: python/mxnet/gluon/data/vision/transforms.py)."""
from __future__ import annotations

import numpy as np

from ....ndarray.ndarray import NDArray
from ....ndarray import array as nd_array
from ...block import Block, HybridBlock
from ...nn import Sequential as Compose_base

__all__ = ["Compose", "Cast", "ToTensor", "Normalize", "Resize", "CenterCrop",
           "RandomResizedCrop", "RandomFlipLeftRight", "RandomFlipTopBottom",
           "RandomBrightness", "RandomContrast", "RandomSaturation",
           "RandomHue", "RandomColorJitter", "RandomLighting"]


class Compose(Compose_base):
    """(ref: transforms.py Compose)"""

    def __init__(self, transforms):
        super().__init__()
        self.add(*transforms)


class Cast(Block):
    def __init__(self, dtype="float32"):
        super().__init__()
        self._dtype = dtype

    def forward(self, x):
        return x.astype(self._dtype)


class ToTensor(Block):
    """HWC uint8 [0,255] -> CHW float32 [0,1] (ref: transforms.py ToTensor)."""

    def forward(self, x):
        a = x.asnumpy() if isinstance(x, NDArray) else np.asarray(x)
        a = a.astype(np.float32) / 255.0
        if a.ndim == 3:
            a = a.transpose(2, 0, 1)
        elif a.ndim == 4:
            a = a.transpose(0, 3, 1, 2)
        return nd_array(a)


class Normalize(Block):
    def __init__(self, mean=0.0, std=1.0):
        super().__init__()
        self._mean = np.asarray(mean, dtype=np.float32)
        self._std = np.asarray(std, dtype=np.float32)

    def forward(self, x):
        a = x.asnumpy() if isinstance(x, NDArray) else np.asarray(x)
        mean = self._mean.reshape(-1, 1, 1) if self._mean.ndim else self._mean
        std = self._std.reshape(-1, 1, 1) if self._std.ndim else self._std
        return nd_array((a - mean) / std)


class Resize(Block):
    def __init__(self, size, keep_ratio=False, interpolation=1):
        super().__init__()
        self._size = size if isinstance(size, (tuple, list)) else (size, size)
        self._interpolation = interpolation

    def forward(self, x):
        from .... import image

        return image.imresize(x, self._size[0], self._size[1], self._interpolation)


class CenterCrop(Block):
    def __init__(self, size, interpolation=1):
        super().__init__()
        self._size = size if isinstance(size, (tuple, list)) else (size, size)
        self._interpolation = interpolation

    def forward(self, x):
        from .... import image

        return image.center_crop(x, self._size, self._interpolation)[0]


class RandomResizedCrop(Block):
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3.0 / 4.0, 4.0 / 3.0),
                 interpolation=1):
        super().__init__()
        self._args = (size if isinstance(size, (tuple, list)) else (size, size),
                      scale, ratio, interpolation)

    def forward(self, x):
        from .... import image

        size, scale, ratio, interp = self._args
        return image.random_size_crop(x, size, scale, ratio, interp)[0]


class RandomFlipLeftRight(Block):
    def forward(self, x):
        if np.random.rand() < 0.5:
            a = x.asnumpy() if isinstance(x, NDArray) else np.asarray(x)
            return nd_array(np.ascontiguousarray(a[:, ::-1]))
        return x


class RandomFlipTopBottom(Block):
    def forward(self, x):
        if np.random.rand() < 0.5:
            a = x.asnumpy() if isinstance(x, NDArray) else np.asarray(x)
            return nd_array(np.ascontiguousarray(a[::-1]))
        return x


class RandomBrightness(Block):
    def __init__(self, brightness):
        super().__init__()
        self._b = brightness

    def forward(self, x):
        from .... import image

        return image.BrightnessJitterAug(self._b)(x)


class RandomContrast(Block):
    def __init__(self, contrast):
        super().__init__()
        self._c = contrast

    def forward(self, x):
        from .... import image

        return image.ContrastJitterAug(self._c)(x)


class RandomSaturation(Block):
    def __init__(self, saturation):
        super().__init__()
        self._s = saturation

    def forward(self, x):
        from .... import image

        return image.SaturationJitterAug(self._s)(x)


class RandomLighting(Block):
    def __init__(self, alpha):
        super().__init__()
        self._alpha = alpha

    def forward(self, x):
        from .... import image

        eigval = np.array([55.46, 4.794, 1.148])
        eigvec = np.array([[-0.5675, 0.7192, 0.4009],
                           [-0.5808, -0.0045, -0.814],
                           [-0.5836, -0.6948, 0.4203]])
        return image.LightingAug(self._alpha, eigval, eigvec)(x)


class RandomHue(Block):
    def __init__(self, hue):
        super().__init__()
        self._h = hue

    def forward(self, x):
        from .... import image

        return image.HueJitterAug(self._h)(x)


class RandomColorJitter(Block):
    """Brightness/contrast/saturation/hue jitter applied in random order
    (ref: transforms.py RandomColorJitter over image.ColorJitterAug)."""

    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0):
        super().__init__()
        self._args = (brightness, contrast, saturation)
        self._hue = hue

    def forward(self, x):
        from .... import image

        x = image.ColorJitterAug(*self._args)(x)
        if self._hue:
            x = image.HueJitterAug(self._hue)(x)
        return x
