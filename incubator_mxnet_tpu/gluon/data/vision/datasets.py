"""Vision datasets (ref: python/mxnet/gluon/data/vision/datasets.py).

Zero-egress environment: datasets read standard on-disk formats from `root`
(MNIST idx files, CIFAR binary batches) and raise a clear error if absent —
no downloads. SyntheticImageDataset provides a generated stand-in for tests
and benchmarks.
"""
from __future__ import annotations

import gzip
import os
import struct

import numpy as np

from ..dataset import Dataset, ArrayDataset
from ....ndarray import array as nd_array

__all__ = ["MNIST", "FashionMNIST", "CIFAR10", "CIFAR100", "ImageFolderDataset",
           "ImageRecordDataset", "SyntheticImageDataset"]


class _DownloadedDataset(Dataset):
    def __init__(self, root, transform):
        self._transform = transform
        self._root = os.path.expanduser(root)
        self._data = None
        self._label = None
        self._get_data()

    def __getitem__(self, idx):
        if self._transform is not None:
            return self._transform(nd_array(self._data[idx]), self._label[idx])
        return nd_array(self._data[idx]), self._label[idx]

    def __len__(self):
        return len(self._label)

    def _get_data(self):
        raise NotImplementedError


class MNIST(_DownloadedDataset):
    """MNIST from idx files in `root` (ref: datasets.py MNIST)."""

    _train_files = ("train-images-idx3-ubyte.gz", "train-labels-idx1-ubyte.gz")
    _test_files = ("t10k-images-idx3-ubyte.gz", "t10k-labels-idx1-ubyte.gz")

    def __init__(self, root="~/.mxnet/datasets/mnist", train=True, transform=None):
        self._train = train
        super().__init__(root, transform)

    def _read_idx(self, path):
        opener = gzip.open if path.endswith(".gz") else open
        with opener(path, "rb") as f:
            data = f.read()
        magic = struct.unpack(">i", data[:4])[0]
        ndim = magic % 256
        dims = struct.unpack(">" + "i" * ndim, data[4 : 4 + 4 * ndim])
        return np.frombuffer(data[4 + 4 * ndim:], dtype=np.uint8).reshape(dims)

    def _get_data(self):
        imgs, lbls = self._train_files if self._train else self._test_files
        img_path = os.path.join(self._root, imgs)
        lbl_path = os.path.join(self._root, lbls)
        for p in (img_path, lbl_path):
            if not os.path.exists(p) and not os.path.exists(p[:-3]):
                raise FileNotFoundError(
                    f"{p} not found. This environment has no network access: place the "
                    "standard MNIST idx files under the dataset root, or use "
                    "SyntheticImageDataset for smoke tests."
                )
        img_path = img_path if os.path.exists(img_path) else img_path[:-3]
        lbl_path = lbl_path if os.path.exists(lbl_path) else lbl_path[:-3]
        data = self._read_idx(img_path)
        label = self._read_idx(lbl_path)
        self._data = data.reshape(-1, 28, 28, 1)
        self._label = label.astype(np.int32)


class FashionMNIST(MNIST):
    def __init__(self, root="~/.mxnet/datasets/fashion-mnist", train=True, transform=None):
        super().__init__(root, train, transform)


class CIFAR10(_DownloadedDataset):
    """CIFAR-10 from the python/binary batches in `root`."""

    def __init__(self, root="~/.mxnet/datasets/cifar10", train=True, transform=None):
        self._train = train
        super().__init__(root, transform)

    def _get_data(self):
        files = ([f"data_batch_{i}.bin" for i in range(1, 6)] if self._train
                 else ["test_batch.bin"])
        data_list, label_list = [], []
        for fname in files:
            path = os.path.join(self._root, fname)
            if not os.path.exists(path):
                raise FileNotFoundError(
                    f"{path} not found (no network access; provide CIFAR binary batches "
                    "or use SyntheticImageDataset)"
                )
            raw = np.fromfile(path, dtype=np.uint8).reshape(-1, 3073)
            label_list.append(raw[:, 0])
            data_list.append(raw[:, 1:].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1))
        self._data = np.concatenate(data_list)
        self._label = np.concatenate(label_list).astype(np.int32)


class CIFAR100(CIFAR10):
    def __init__(self, root="~/.mxnet/datasets/cifar100", train=True,
                 fine_label=False, transform=None):
        self._fine = fine_label
        super().__init__(root, train, transform)

    def _get_data(self):
        fname = "train.bin" if self._train else "test.bin"
        path = os.path.join(self._root, fname)
        if not os.path.exists(path):
            raise FileNotFoundError(f"{path} not found")
        raw = np.fromfile(path, dtype=np.uint8).reshape(-1, 3074)
        self._label = raw[:, 1 if self._fine else 0].astype(np.int32)
        self._data = raw[:, 2:].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)


class ImageFolderDataset(Dataset):
    """(ref: datasets.py ImageFolderDataset) — label per subdirectory."""

    def __init__(self, root, flag=1, transform=None):
        self._root = os.path.expanduser(root)
        self._flag = flag
        self._transform = transform
        self._exts = {".jpg", ".jpeg", ".png"}
        self.synsets = []
        self.items = []
        for folder in sorted(os.listdir(self._root)):
            path = os.path.join(self._root, folder)
            if not os.path.isdir(path):
                continue
            label = len(self.synsets)
            self.synsets.append(folder)
            for filename in sorted(os.listdir(path)):
                if os.path.splitext(filename)[1].lower() in self._exts:
                    self.items.append((os.path.join(path, filename), label))

    def __getitem__(self, idx):
        from .... import image

        img = image.imread(self.items[idx][0], self._flag)
        label = self.items[idx][1]
        if self._transform is not None:
            return self._transform(img, label)
        return img, label

    def __len__(self):
        return len(self.items)


class ImageRecordDataset(Dataset):
    """(ref: datasets.py ImageRecordDataset over RecordIO shards)."""

    def __init__(self, filename, flag=1, transform=None):
        from ..dataset import RecordFileDataset

        self._record = RecordFileDataset(filename)
        self._flag = flag
        self._transform = transform

    def __getitem__(self, idx):
        from .... import image, recordio

        record = self._record[idx]
        header, img = recordio.unpack(record)
        img = image.imdecode(img, self._flag)
        label = header.label
        if self._transform is not None:
            return self._transform(img, label)
        return img, label

    def __len__(self):
        return len(self._record)


class SyntheticImageDataset(Dataset):
    """Deterministic generated image-classification data (for tests/bench in
    a zero-egress environment)."""

    def __init__(self, num_samples=1000, shape=(3, 224, 224), num_classes=1000,
                 transform=None, seed=0, channels_last=False):
        rng = np.random.RandomState(seed)
        self._labels = rng.randint(0, num_classes, size=num_samples).astype(np.int32)
        self._shape = tuple(shape)
        self._seed = seed
        self._transform = transform
        self._channels_last = channels_last

    def __getitem__(self, idx):
        rng = np.random.RandomState(self._seed + idx)
        img = rng.rand(*self._shape).astype(np.float32)
        label = self._labels[idx]
        if self._transform is not None:
            return self._transform(nd_array(img), label)
        return nd_array(img), label

    def __len__(self):
        return len(self._labels)
