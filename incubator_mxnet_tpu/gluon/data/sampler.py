"""Index samplers for DataLoader (capability parity with
python/mxnet/gluon/data/sampler.py: sequential/random index streams and the
batching wrapper with keep/discard/rollover tail policies).

Expressed generator-first: a sampler is just an index iterable with a
length; the batch wrapper chunks any such iterable, with the tail policy
isolated in `_flush_tail`.

Checkpointable: every sampler carries `state_dict()/load_state_dict()` so
a preempted job resumes MID-EPOCH with a bit-identical index order
(docs/FAULT_TOLERANCE.md — Preemption and exact resume). The contract:
`load_state_dict(state, mid_epoch=True)` restores the RNG to the state it
had when the interrupted epoch STARTED, so the next `__iter__` re-derives
the same order and the DataLoader fast-forwards past the batches already
served; `mid_epoch=False` (an epoch-boundary resume) restores the live
state so the next epoch draws fresh.
"""
from __future__ import annotations

import numpy as np

__all__ = ["Sampler", "SequentialSampler", "RandomSampler", "BatchSampler"]

_TAIL_POLICIES = ("keep", "discard", "rollover")


class Sampler:
    """An iterable of dataset indices with a known length."""

    def __iter__(self):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError

    def state_dict(self):
        """Checkpointable position; stateless samplers return {}."""
        return {}

    def load_state_dict(self, state, mid_epoch=False):
        """Restore `state_dict()` output. `mid_epoch=True` rewinds any
        per-epoch randomness to the interrupted epoch's start so the
        order replays exactly."""


class SequentialSampler(Sampler):
    """Indices start, start+1, ..., start+length-1."""

    def __init__(self, length, start=0):
        self._range = range(start, start + length)

    def __iter__(self):
        return iter(self._range)

    def __len__(self):
        return len(self._range)


class RandomSampler(Sampler):
    """A fresh uniform permutation of [0, length) per epoch.

    Owns its PRNG (seeded from the global numpy stream unless `seed` is
    given) so the shuffle order is checkpointable: `state_dict()` captures
    both the live RNG state and the state at the current epoch's start,
    and a mid-epoch restore replays the interrupted epoch's permutation
    bit-identically.
    """

    def __init__(self, length, seed=None):
        self._length = length
        if seed is None:
            # derived from the global stream: np.random.seed() upstream
            # keeps legacy runs deterministic end-to-end
            seed = int(np.random.randint(0, 2 ** 31 - 1))
        self._rng = np.random.RandomState(seed)
        self._epoch_start = self._rng.get_state()

    def __iter__(self):
        self._epoch_start = self._rng.get_state()
        yield from self._rng.permutation(self._length).tolist()

    def __len__(self):
        return self._length

    def state_dict(self):
        return {"rng": self._rng.get_state(),
                "epoch_start": self._epoch_start}

    def load_state_dict(self, state, mid_epoch=False):
        self._rng.set_state(state["epoch_start"] if mid_epoch
                            else state["rng"])
        self._epoch_start = self._rng.get_state()


class BatchSampler(Sampler):
    """Chunk an index sampler into batch-size lists.

    Tail policy for a short final chunk: 'keep' yields it, 'discard' drops
    it, 'rollover' prepends it to the NEXT epoch's first batch.
    """

    def __init__(self, sampler, batch_size, last_batch="keep"):
        if last_batch not in _TAIL_POLICIES:
            raise ValueError(
                f"last_batch must be one of {_TAIL_POLICIES}, got {last_batch!r}")
        self._sampler = sampler
        self._batch_size = batch_size
        self._last_batch = last_batch
        self._rolled = []
        self._start_rolled = []

    def __iter__(self):
        # remembered so a mid-epoch restore can re-seed the epoch with the
        # same rolled-over tail the interrupted iteration started with
        self._start_rolled = list(self._rolled)
        batch = self._rolled
        self._rolled = []
        for idx in self._sampler:
            batch.append(idx)
            if len(batch) == self._batch_size:
                yield batch
                batch = []
        yield from self._flush_tail(batch)

    def _flush_tail(self, batch):
        if not batch:
            return
        if self._last_batch == "keep":
            yield batch
        elif self._last_batch == "rollover":
            self._rolled = batch
        # 'discard': drop it

    def __len__(self):
        n, b = len(self._sampler), self._batch_size
        if self._last_batch == "keep":
            return -(-n // b)  # ceil
        if self._last_batch == "discard":
            return n // b
        return (n + len(self._rolled)) // b

    def state_dict(self):
        return {"sampler": self._sampler.state_dict(),
                "rolled": list(self._rolled),
                "start_rolled": list(self._start_rolled)}

    def load_state_dict(self, state, mid_epoch=False):
        self._sampler.load_state_dict(state["sampler"], mid_epoch)
        self._rolled = list(state["start_rolled"] if mid_epoch
                            else state["rolled"])
        self._start_rolled = list(self._rolled)
