"""Index samplers for DataLoader (capability parity with
python/mxnet/gluon/data/sampler.py: sequential/random index streams and the
batching wrapper with keep/discard/rollover tail policies).

Expressed generator-first: a sampler is just an index iterable with a
length; the batch wrapper chunks any such iterable, with the tail policy
isolated in `_flush_tail`.
"""
from __future__ import annotations

import numpy as np

__all__ = ["Sampler", "SequentialSampler", "RandomSampler", "BatchSampler"]

_TAIL_POLICIES = ("keep", "discard", "rollover")


class Sampler:
    """An iterable of dataset indices with a known length."""

    def __iter__(self):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class SequentialSampler(Sampler):
    """Indices start, start+1, ..., start+length-1."""

    def __init__(self, length, start=0):
        self._range = range(start, start + length)

    def __iter__(self):
        return iter(self._range)

    def __len__(self):
        return len(self._range)


class RandomSampler(Sampler):
    """A fresh uniform permutation of [0, length) per epoch."""

    def __init__(self, length):
        self._length = length

    def __iter__(self):
        yield from np.random.permutation(self._length).tolist()

    def __len__(self):
        return self._length


class BatchSampler(Sampler):
    """Chunk an index sampler into batch-size lists.

    Tail policy for a short final chunk: 'keep' yields it, 'discard' drops
    it, 'rollover' prepends it to the NEXT epoch's first batch.
    """

    def __init__(self, sampler, batch_size, last_batch="keep"):
        if last_batch not in _TAIL_POLICIES:
            raise ValueError(
                f"last_batch must be one of {_TAIL_POLICIES}, got {last_batch!r}")
        self._sampler = sampler
        self._batch_size = batch_size
        self._last_batch = last_batch
        self._rolled = []

    def __iter__(self):
        batch = self._rolled
        self._rolled = []
        for idx in self._sampler:
            batch.append(idx)
            if len(batch) == self._batch_size:
                yield batch
                batch = []
        yield from self._flush_tail(batch)

    def _flush_tail(self, batch):
        if not batch:
            return
        if self._last_batch == "keep":
            yield batch
        elif self._last_batch == "rollover":
            self._rolled = batch
        # 'discard': drop it

    def __len__(self):
        n, b = len(self._sampler), self._batch_size
        if self._last_batch == "keep":
            return -(-n // b)  # ceil
        if self._last_batch == "discard":
            return n // b
        return (n + len(self._rolled)) // b
