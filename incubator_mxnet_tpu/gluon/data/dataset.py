"""Datasets (capability parity with python/mxnet/gluon/data/dataset.py:
indexable sources plus filter/take/transform combinators and the RecordIO
file dataset).

One mapping wrapper (`_Mapped`) backs both transform flavors; eager
materialization is `list(...)` over the lazy form.
"""
from __future__ import annotations

import os

from ...ndarray.ndarray import NDArray

__all__ = ["Dataset", "SimpleDataset", "ArrayDataset", "RecordFileDataset"]


class Dataset:
    """Random-access sample source: __getitem__ + __len__."""

    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError

    def __iter__(self):
        return (self[i] for i in range(len(self)))

    # -- combinators -------------------------------------------------------
    def filter(self, fn):
        """Materialized subset of samples where fn(sample) is true."""
        return SimpleDataset([s for s in self if fn(s)])

    def take(self, count):
        """Materialized first min(count, len) samples."""
        return SimpleDataset(list(self)[:count])

    def transform(self, fn, lazy=True):
        """Apply fn to every sample (tuple samples are splatted)."""
        mapped = _Mapped(self, fn)
        return mapped if lazy else SimpleDataset(list(mapped))

    def transform_first(self, fn, lazy=True):
        """Apply fn to only the FIRST element of each tuple sample (the
        standard augment-data-not-label pattern)."""
        def first_only(x, *rest):
            return (fn(x),) + rest if rest else fn(x)

        return self.transform(first_only, lazy)


class SimpleDataset(Dataset):
    """Wrap any indexable container."""

    def __init__(self, data):
        self._data = data

    def __len__(self):
        return len(self._data)

    def __getitem__(self, idx):
        return self._data[idx]


class _Mapped(Dataset):
    """Lazy per-sample function application."""

    def __init__(self, source, fn):
        self._source = source
        self._fn = fn

    def __len__(self):
        return len(self._source)

    def __getitem__(self, idx):
        sample = self._source[idx]
        return self._fn(*sample) if isinstance(sample, tuple) \
            else self._fn(sample)


class ArrayDataset(Dataset):
    """Zip N equal-length arrays into tuple samples (single array: bare
    samples). NDArrays are snapshotted to numpy once up front so indexing
    is host-cheap."""

    def __init__(self, *arrays):
        if not arrays:
            raise ValueError("ArrayDataset needs at least one array")
        lengths = {len(a) for a in arrays}
        if len(lengths) != 1:
            raise ValueError(f"all arrays must share a length, got {lengths}")
        self._columns = [a.asnumpy() if isinstance(a, NDArray) else a
                         for a in arrays]

    def __len__(self):
        return len(self._columns[0])

    def __getitem__(self, idx):
        row = tuple(col[idx] for col in self._columns)
        return row[0] if len(row) == 1 else row


class RecordFileDataset(Dataset):
    """Raw records from a RecordIO shard addressed through its .idx
    (ref role: dataset.py RecordFileDataset over MXIndexedRecordIO)."""

    def __init__(self, filename):
        from ... import recordio

        self.filename = filename
        self.idx_file = os.path.splitext(filename)[0] + ".idx"
        self._record = recordio.MXIndexedRecordIO(self.idx_file, filename, "r")

    def __len__(self):
        return len(self._record.keys)

    def __getitem__(self, idx):
        return self._record.read_idx(self._record.keys[idx])
